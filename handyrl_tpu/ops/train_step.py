"""The compiled SGD update step.

One jit-compiled program per (architecture, config, batch shape): forward,
targets, losses, gradients, global-norm clip at 4.0, Adam with additive
weight decay 1e-5 (the reference optimizer, train.py:331,370), parameter
update. The learning rate is a runtime scalar (the host feeds the EMA
schedule value each step) so schedule changes never recompile.

On a multi-device mesh the batch arrives sharded along 'data' and params
replicated; XLA inserts the gradient all-reduce over ICI.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from .losses import LossConfig, compute_loss, split_batch_stats
from ..parallel.mesh import batch_sharding, replicated_sharding


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    steps: jnp.ndarray  # int32 scalar


def make_optimizer() -> optax.GradientTransformation:
    """clip(4.0) -> grad += wd * param -> Adam moments (lr applied outside)."""
    return optax.chain(
        optax.clip_by_global_norm(4.0),
        optax.add_decayed_weights(1e-5),
        optax.scale_by_adam(),
    )


def init_train_state(params) -> TrainState:
    """``params`` is the model's full flax variables dict. The optimizer
    covers only the trainable collections — a norm_kind='batch' model's
    ``batch_stats`` running averages advance by EMA in the forward
    (losses.py), never by Adam (zero-grad moments + weight decay would
    silently shrink them toward 0)."""
    opt = make_optimizer()
    trainable, _ = split_batch_stats(params)
    return TrainState(params=params, opt_state=opt.init(trainable),
                      steps=jnp.zeros((), jnp.int32))


def _update_core(module, cfg: LossConfig, optimizer, axis_name=None):
    """The un-jitted single SGD step shared by every compiled variant.

    With ``axis_name`` (the shard_map'd fused pipeline), each shard computes
    grads/metrics over its LOCAL batch slice and psums them: the loss is a
    sum over batch elements, so the psum'd gradient equals the single-device
    gradient of the full batch, and the (replicated) optimizer step — incl.
    the global-norm clip, which must see the GLOBAL gradient — is identical
    on every shard, keeping params replicated without a broadcast."""
    apply_fn = module.apply

    def init_hidden_for(batch):
        if not hasattr(module, 'init_hidden'):
            return None
        B = batch['value'].shape[0]
        P = batch['value'].shape[2]
        return module.init_hidden((B, P))

    def update(state: TrainState, batch: Dict[str, Any], lr: jnp.ndarray,
               target_params=None
               ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        init_hidden = init_hidden_for(batch)
        trainable, batch_stats = split_batch_stats(state.params)

        def loss_fn(params):
            return compute_loss(apply_fn, params, init_hidden, batch, cfg,
                                batch_stats=batch_stats,
                                target_params=target_params)

        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(trainable)
        new_bs = aux.pop('batch_stats', None)
        if axis_name is not None:
            grads = jax.lax.psum(grads, axis_name)
            aux = jax.lax.psum(aux, axis_name)
            if new_bs is not None:
                # shard_map path: each shard normalized by ITS batch
                # slice's statistics (torch DataParallel BatchNorm
                # semantics, what the reference trains with); averaging
                # the advanced running stats keeps the replicated train
                # state bit-identical across shards. NOTE the OTHER
                # multi-device path (build_update_step's jit+mesh, no
                # axis_name) lets GSPMD reduce the batch statistics over
                # the GLOBAL sharded batch — sync-BN semantics. Both are
                # faithful BatchNorm; they differ in stat granularity
                # (documented in PARITY.md).
                new_bs = jax.lax.pmean(new_bs, axis_name)
        # non-finite guard: one NaN/Inf gradient must not poison the
        # TrainState forever. All-finite check on the (global) loss, grad
        # norm and the runtime lr scalar, ON DEVICE — a bad step keeps the
        # previous params/optimizer buffers and reports metrics as zeros
        # plus nonfinite=1; the host reads that flag on its existing lazy
        # metric fetch (no extra sync) and escalates per guard policy
        # (guard.py: skip / rollback / abort).
        grad_norm = optax.global_norm(grads)
        ok = (jnp.isfinite(lr)
              & jnp.isfinite(aux['losses']['total'])
              & jnp.isfinite(grad_norm))
        updates, opt_state = optimizer.update(grads, state.opt_state, trainable)
        updates = jax.tree_util.tree_map(lambda u: -lr * u, updates)
        params = optax.apply_updates(trainable, updates)

        def keep(new, old):
            return jnp.where(ok, new, old)
        params = jax.tree_util.tree_map(keep, params, trainable)
        opt_state = jax.tree_util.tree_map(keep, opt_state, state.opt_state)
        if new_bs is not None:
            params = {**dict(params),
                      'batch_stats': jax.tree_util.tree_map(
                          keep, new_bs, batch_stats)}
        metrics = {**aux['losses'], 'data_count': aux['data_count']}
        # learning-dynamics diagnostics ride the same packed fetch under a
        # 'diag_' prefix: the host routes them to the per-epoch dynamics
        # summary instead of the reference-format loss line. grad_norm is
        # the post-psum GLOBAL gradient (per update, not per sample).
        for k, v in (aux.get('diag') or {}).items():
            metrics['diag_' + k] = v
        metrics['diag_grad_norm'] = grad_norm
        metrics = {k: jnp.where(ok, v, jnp.zeros_like(v))
                   for k, v in metrics.items()}
        metrics['nonfinite'] = 1.0 - ok.astype(jnp.float32)
        new_state = TrainState(params=params, opt_state=opt_state,
                               steps=state.steps + 1)
        return new_state, metrics

    return update


def build_update_step(module, cfg: LossConfig, mesh=None, donate: bool = True,
                      state_shardings=None, use_target: bool = False):
    """Returns update(state, batch, lr) -> (state, metrics), jit-compiled.

    ``metrics`` carries the per-term loss sums and the turn count of the
    batch (the reference's ``dcnt``) as device scalars.

    With ``use_target`` the compiled signature gains a 4th argument —
    update(state, batch, lr, target_params) — the frozen IMPACT target
    network's trainable params (losses.py target_clip). They are replicated
    like any other scalar input and NOT donated: the live params buffer is
    donated every step, so the target must keep its own device copy to
    survive between refreshes (train.py syncs it every
    streaming.target_sync_epochs epochs).

    On a mesh the program carries explicit NamedSharding types: the batch
    shards along 'data', and the TrainState layout comes from
    ``state_shardings`` — the per-leaf NamedSharding pytree the partition-
    rule engine builds (parallel/partition.py tree_shardings); None keeps
    the fully-replicated (pure data-parallel) layout. The same shardings
    type the outputs, so the donated state round-trips through every step
    without a reshard.
    """
    # Resolve the Pallas-vs-scan target path NOW, outside any trace: the
    # probe compiles and runs a real kernel on the backend, which cannot
    # happen once tracing of ``update`` has begun.
    from .pallas_targets import use_pallas_targets
    use_pallas_targets()

    update = _update_core(module, cfg, make_optimizer())
    # name the program so the retrace sentinel (telemetry.py) can report
    # WHICH compiled callable re-lowered after steady state
    update.__name__ = 'train_update_step'

    if mesh is None:
        return jax.jit(update, donate_argnums=(0,) if donate else ())

    repl = replicated_sharding(mesh)
    data = batch_sharding(mesh)
    state_sh = state_shardings if state_shardings is not None else repl
    # the target copy mirrors the live params' layout (it IS a copy of
    # them), so its sharding is the state tree's params component
    tgt_sh = getattr(state_sh, 'params', state_sh)
    in_sh = (state_sh, data, repl) + ((tgt_sh,) if use_target else ())
    return jax.jit(
        update,
        in_shardings=in_sh,
        out_shardings=(state_sh, repl),
        donate_argnums=(0,) if donate else (),
    )


def build_replay_update(module, cfg: LossConfig, capacity: int,
                        batch_size: int, num_steps: int,
                        default_lr: float = 3e-8, mesh=None,
                        spec_fn=None, state_shardings=None):
    """Fused replay-mode trainer: K SGD steps in ONE compiled program.

    The per-step host round trip (sample dispatch + update dispatch + PRNG
    split) is what bounds replay-mode throughput on a dispatch-latency-heavy
    backend (a tunneled TPU pays it ~3x per step). Here the whole inner loop
    moves on device: a ``lax.scan`` of ``num_steps`` iterations, each drawing
    a recency-biased batch straight from the HBM ring (same inverse-CDF as
    DeviceReplay.sample), computing the EMA learning-rate schedule from the
    on-device step counter (identical to Trainer._lr: steps is the count of
    completed updates), and applying the update. Metrics come back as sums
    over the K steps, matching what the host accumulator expects.

    Returns fused(state, buffers, key, size, cursor, data_cnt_ema) ->
    (state, key, summed_metrics). The key is carried through and returned so
    steady-state training needs zero host-side PRNG dispatches. On a mesh the
    ring is replicated and each sampled batch is sharding-constrained along
    'data', so XLA runs the same data-parallel step as build_update_step.
    """
    from .pallas_targets import use_pallas_targets
    use_pallas_targets()
    from .replay import recency_slots

    update = _update_core(module, cfg, make_optimizer())
    data = batch_sharding(mesh) if mesh is not None else None

    def gather(buffers, slots):
        """Ring rows are stored FLAT (capacity, prod(window shape)) to
        avoid TPU tile-padding blowup (ops/replay.py); ``spec_fn`` supplies
        the per-leaf window shapes at trace time. Two storage flavors:
        DeviceReplay's (leaf list + treedef) and DeviceWindower's ring
        (flat dict keyed like the batch)."""
        if spec_fn is None:
            return jax.tree_util.tree_map(lambda b: b[slots], buffers)
        spec, treedef = spec_fn()
        if isinstance(buffers, dict):
            from .device_windows import unflatten_window_keys
            return unflatten_window_keys(
                {k: buffers[k][slots].reshape(
                    (batch_size,) + spec[k][0]) for k in buffers})
        rows = [b[slots].reshape((batch_size,) + shape)
                for b, (shape, _) in zip(buffers, spec)]
        return jax.tree_util.tree_unflatten(treedef, rows)

    def fused(state: TrainState, buffers, key, size, cursor, data_cnt_ema):
        def body(carry, _):
            state, key = carry
            key, sub = jax.random.split(key)
            slots = recency_slots(sub, size, cursor, capacity, batch_size)
            batch = gather(buffers, slots)
            if data is not None:
                batch = jax.lax.with_sharding_constraint(
                    batch, jax.tree_util.tree_map(lambda _: data, batch))
            lr = (default_lr * data_cnt_ema
                  / (1 + state.steps.astype(jnp.float32) * 1e-5))
            state, metrics = update(state, batch, lr)
            return (state, key), metrics

        (state, key), stacked = jax.lax.scan(
            body, (state, key), None, length=num_steps)
        summed = jax.tree_util.tree_map(lambda m: jnp.sum(m, axis=0), stacked)
        return state, key, summed

    fused.__name__ = 'replay_fused_update'
    if mesh is None:
        return jax.jit(fused, donate_argnums=(0, 2))
    repl = replicated_sharding(mesh)
    # the ring stays replicated (each device gathers its batch from a local
    # replica); the TrainState layout comes from the partition-rule engine
    state_sh = state_shardings if state_shardings is not None else repl
    return jax.jit(
        fused,
        in_shardings=(state_sh, repl, repl, repl, repl, repl),
        out_shardings=(state_sh, repl, repl),
        donate_argnums=(0, 2),
    )
