"""The compiled SGD update step.

One jit-compiled program per (architecture, config, batch shape): forward,
targets, losses, gradients, global-norm clip at 4.0, Adam with additive
weight decay 1e-5 (the reference optimizer, train.py:331,370), parameter
update. The learning rate is a runtime scalar (the host feeds the EMA
schedule value each step) so schedule changes never recompile.

On a multi-device mesh the batch arrives sharded along 'data' and params
replicated; XLA inserts the gradient all-reduce over ICI.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from .losses import LossConfig, compute_loss
from ..parallel.mesh import batch_sharding, replicated_sharding


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    steps: jnp.ndarray  # int32 scalar


def make_optimizer() -> optax.GradientTransformation:
    """clip(4.0) -> grad += wd * param -> Adam moments (lr applied outside)."""
    return optax.chain(
        optax.clip_by_global_norm(4.0),
        optax.add_decayed_weights(1e-5),
        optax.scale_by_adam(),
    )


def init_train_state(params) -> TrainState:
    opt = make_optimizer()
    return TrainState(params=params, opt_state=opt.init(params),
                      steps=jnp.zeros((), jnp.int32))


def build_update_step(module, cfg: LossConfig, mesh=None, donate: bool = True):
    """Returns update(state, batch, lr) -> (state, metrics), jit-compiled.

    ``metrics`` carries the per-term loss sums and the turn count of the
    batch (the reference's ``dcnt``) as device scalars.
    """
    # Resolve the Pallas-vs-scan target path NOW, outside any trace: the
    # probe compiles and runs a real kernel on the backend, which cannot
    # happen once tracing of ``update`` has begun.
    from .pallas_targets import use_pallas_targets
    use_pallas_targets()

    optimizer = make_optimizer()
    apply_fn = module.apply

    def init_hidden_for(batch):
        if not hasattr(module, 'init_hidden'):
            return None
        B = batch['value'].shape[0]
        P = batch['value'].shape[2]
        return module.init_hidden((B, P))

    def update(state: TrainState, batch: Dict[str, Any], lr: jnp.ndarray
               ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        init_hidden = init_hidden_for(batch)

        def loss_fn(params):
            return compute_loss(apply_fn, params, init_hidden, batch, cfg)

        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        updates = jax.tree_util.tree_map(lambda u: -lr * u, updates)
        params = optax.apply_updates(state.params, updates)
        metrics = {**aux['losses'], 'data_count': aux['data_count']}
        new_state = TrainState(params=params, opt_state=opt_state,
                               steps=state.steps + 1)
        return new_state, metrics

    if mesh is None:
        return jax.jit(update, donate_argnums=(0,) if donate else ())

    repl = replicated_sharding(mesh)
    data = batch_sharding(mesh)
    return jax.jit(
        update,
        in_shardings=(repl, data, repl),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )
