"""The fully-fused device pipeline: ONE dispatch = rollout chunk + window
ingest + K SGD steps.

The split pipeline (DeviceGenerator dispatch -> chunk queue -> trainer-thread
ingest dispatch -> fused-update dispatch) keeps the whole loop on device, but
still pays one host round trip per program, and the generation thread's tiny
done/outcome fetch queues BEHIND the trainer thread's in-flight programs on
the single device stream — on a tunneled TPU that serialization, not
compute, bounds episodes/sec.

Here the entire steady-state loop body is one XLA program:

    rollout chunk (lax.scan over plies, make_gen_body)
      -> windower chunk ingest (episode windows scattered into the HBM ring)
      -> K SGD steps (recency-biased on-device sampling, EMA lr schedule)

The host dispatches it once per chunk and fetches only the previous chunk's
(done, outcome) arrays plus lazily-drained loss metrics. Actor params enter
as a replicated input refreshed once per epoch (self-play acts with the
epoch snapshot while the optimizer advances continuously, exactly like the
reference's worker/learner split, train.py:605-615); training params/opt
state are donated through every dispatch.

A second, SGD-free program covers the minimum_episodes warmup so the steps
counter and Adam state never see empty-ring batches.

Sample-reuse note: steps-per-chunk is a DIAL (sgd_steps_per_chunk), making
the replay ratio explicit: reuse ~= sgd_steps * batch_size / windows-per-
chunk. The threaded mode's reuse is implicit (however fast the trainer spins
vs generation); here it is pinned and logged.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..device_generation import _init_rollout_engine, make_gen_body
from .losses import LossConfig
from .replay import recency_slots
from .train_step import (TrainState, _update_core, init_train_state,
                         make_optimizer)


class FusedPipeline:
    """Owns the device-resident loop state (env vector, recurrent hidden,
    windower history, HBM ring) and the two compiled programs (warmup /
    steady). The caller owns the TrainState and actor params."""

    def __init__(self, env_mod, wrapper, cfg: LossConfig, windower,
                 args: Dict[str, Any], n_envs: int, chunk_steps: int,
                 sgd_steps: int, batch_size: int,
                 default_lr: float = 3e-8, seed: int = 0, mesh=None):
        self.chunk_steps = chunk_steps
        self.sgd_steps = sgd_steps
        self.mesh = mesh
        ndev = int(np.prod(list(mesh.shape.values()))) if mesh else 1
        self.ndev = ndev
        if mesh is not None:
            assert n_envs % ndev == 0 and batch_size % ndev == 0, \
                'generation_envs and batch_size must divide the mesh'
            assert windower.capacity >= 1, \
                'replay capacity must be >= 1 ring row per shard'
        n_loc = n_envs // ndev            # per-shard envs
        b_loc = batch_size // ndev        # per-shard SGD batch slice
        _init_rollout_engine(self, env_mod, wrapper, n_envs, seed)
        if self.hidden is not None:
            # models may alias hidden leaves (e.g. GeisterNet's
            # ``[zeros] * layers``); every dispatch donates the tree, and
            # XLA refuses to donate one buffer twice — copy into distinct
            # buffers once here
            self.hidden = jax.tree_util.tree_map(jnp.copy, self.hidden)
        rollout_chunk = make_gen_body(env_mod, wrapper.module.apply,
                                      self.recurrent, self.simultaneous)
        ingest = windower.ingest_fn()
        update = _update_core(wrapper.module, cfg, make_optimizer(),
                              axis_name='data' if mesh is not None else None)
        # windower.capacity is PER-SHARD on a mesh (Learner divides the ring
        # budget by the device count); the global ring has ndev * capacity rows
        capacity = windower.capacity
        self.capacity = capacity
        self.dispatches = 0

        # ring/windower state allocated from the record shapes (eval_shape:
        # nothing runs on device for this). On a mesh the GLOBAL shapes are
        # allocated (env axis = n_envs, ring axis = ndev * capacity) and
        # sharded over 'data'; each shard_map body sees the local slice.
        rec_spec = jax.eval_shape(
            lambda p, s, h, r: rollout_chunk(p, s, h, r, chunk_steps),
            wrapper.params, self.state, self.hidden, self.rng)[3]
        self.wstate = windower.init_state(rec_spec)
        ring_local = windower.init_ring(rec_spec)   # sets window_spec
        if mesh is None:
            self.ring = ring_local
            self.cursor = jnp.zeros((), jnp.int32)
            self.size = jnp.zeros((), jnp.int32)
        else:
            self.ring = {k: jnp.zeros((ndev * capacity,) + v.shape[1:],
                                      v.dtype)
                         for k, v in ring_local.items()}
            # per-shard ring cursors/sizes and PRNG streams, stored as
            # sharded (ndev,)-leading arrays
            self.cursor = jnp.zeros((ndev,), jnp.int32)
            self.size = jnp.zeros((ndev,), jnp.int32)
            self.rng = jax.random.split(jax.random.fold_in(
                jax.random.PRNGKey(seed), 7), ndev)
            self._shard_loop_state(mesh)

        self.num_players = int(env_mod.NUM_PLAYERS)
        # metric key order is part of the packed-fetch wire format; derive
        # it statically from the update's abstract aux (no device work, no
        # trace-order dependence)
        probe_update = _update_core(wrapper.module, cfg, make_optimizer())

        def _probe(params):
            from .device_windows import unflatten_window_keys
            batch = unflatten_window_keys(
                {k: jnp.zeros((batch_size,) + shape, dtype)
                 for k, (shape, dtype) in windower.window_spec.items()})
            ts = init_train_state(params)
            _, metrics = probe_update(ts, batch, jnp.float32(0.0))
            return metrics
        self._metric_keys: list = sorted(
            jax.eval_shape(_probe, wrapper.params))

        def gen_ingest(actor_params, env_state, hidden, wstate, ring,
                       cursor, size, rng):
            env_state, hidden, rng, records = rollout_chunk(
                actor_params, env_state, hidden, rng, chunk_steps)
            (wstate, ring, cursor, size, rng,
             n_done, n_win) = ingest(records, wstate, ring, cursor, size, rng)
            return (env_state, hidden, wstate, ring, cursor, size, rng,
                    records['done'], records['outcome'], n_win)

        def pack(done, outcome, size, size_min, n_win, metric_vals):
            # EVERYTHING the host reads per chunk rides ONE f32 array: a
            # distinct-array fetch costs a full tunnel round trip (~140 ms
            # measured), so one sync point per dispatch is the budget
            parts = [done.astype(jnp.float32).reshape(-1),
                     outcome.astype(jnp.float32).reshape(-1),
                     size.astype(jnp.float32).reshape(1),
                     size_min.astype(jnp.float32).reshape(1),
                     n_win.astype(jnp.float32).reshape(1)]
            parts += [v.astype(jnp.float32).reshape(1) for v in metric_vals]
            return jnp.concatenate(parts)

        def sgd_tail(train_state, ring, cursor, size, rng, data_cnt_ema,
                     batch_rows):
            """K recency-sampled SGD steps on this shard's ring slice."""
            def body(carry, _):
                ts, key = carry
                key, sub = jax.random.split(key)
                slots = recency_slots(sub, size, cursor, capacity,
                                      batch_rows)
                # ring rows are stored flat (device_windows.init_ring);
                # restore the (B, T, P, ...) window shape after the gather
                # and rebuild the batch pytree (dotted keys -> nested obs)
                from .device_windows import unflatten_window_keys
                batch = unflatten_window_keys(
                    {k: ring[k][slots].reshape(
                        (batch_rows,) + windower.window_spec[k][0])
                     for k in ring})
                lr = (default_lr * data_cnt_ema
                      / (1 + ts.steps.astype(jnp.float32) * 1e-5))
                ts, metrics = update(ts, batch, lr)
                return (ts, key), metrics

            (train_state, rng), stacked = jax.lax.scan(
                body, (train_state, rng), None, length=sgd_steps)
            metrics = jax.tree_util.tree_map(
                lambda m: jnp.sum(m, axis=0), stacked)
            return train_state, rng, [metrics[k]
                                      for k in self._metric_keys]

        if mesh is None:
            def warmup(actor_params, env_state, hidden, wstate, ring,
                       cursor, size, rng):
                (env_state, hidden, wstate, ring, cursor, size, rng,
                 done, outcome, n_win) = gen_ingest(
                    actor_params, env_state, hidden, wstate, ring, cursor,
                    size, rng)
                return (env_state, hidden, wstate, ring, cursor, size, rng,
                        pack(done, outcome, size, size, n_win, []))

            def fused(actor_params, train_state: TrainState, env_state,
                      hidden, wstate, ring, cursor, size, rng, data_cnt_ema):
                (env_state, hidden, wstate, ring, cursor, size, rng,
                 done, outcome, n_win) = gen_ingest(
                    actor_params, env_state, hidden, wstate, ring, cursor,
                    size, rng)
                train_state, rng, mvals = sgd_tail(
                    train_state, ring, cursor, size, rng, data_cnt_ema,
                    batch_size)
                return (train_state, env_state, hidden, wstate, ring, cursor,
                        size, rng,
                        pack(done, outcome, size, size, n_win, mvals))
        else:
            warmup, fused = self._build_sharded(
                mesh, gen_ingest, sgd_tail, pack, b_loc)

        # donate everything the pipeline owns plus the train state; actor
        # params and the EMA scalar are plain (re-used) inputs. On a mesh
        # the program boundary is TYPED with explicit NamedShardings (the
        # same vocabulary the partition-rule engine speaks): loop state
        # sharded along 'data', actor params / train state / the packed
        # host fetch replicated — placement is part of the program, not an
        # accident of where the caller left the inputs.
        # name the programs so the retrace sentinel (telemetry.py) can
        # report WHICH compiled callable re-lowered after steady state
        warmup.__name__ = 'fused_pipeline_warmup'
        fused.__name__ = 'fused_pipeline_train'
        if mesh is None:
            self._warmup = jax.jit(warmup,
                                   donate_argnums=(1, 2, 3, 4, 5, 6, 7))
            self._fused = jax.jit(fused,
                                  donate_argnums=tuple(range(1, 10)))
        else:
            from ..parallel.mesh import batch_sharding, replicated_sharding
            R, D = replicated_sharding(mesh), batch_sharding(mesh)
            self._warmup = jax.jit(
                warmup,
                in_shardings=(R, D, D, D, D, D, D, D),
                out_shardings=(D, D, D, D, D, D, D, R),
                donate_argnums=(1, 2, 3, 4, 5, 6, 7))
            self._fused = jax.jit(
                fused,
                in_shardings=(R, R, D, D, D, D, D, D, D, R),
                out_shardings=(R, D, D, D, D, D, D, D, R),
                donate_argnums=tuple(range(1, 10)))
        self._pending = None   # (pack_future, has_metrics), one deep
        self.ring_size_host = 0
        self.ring_min_host = 0          # min ring size across shards
        self.windows_ingested_host = 0  # cumulative windows ingested

    # -- multi-chip construction -------------------------------------------
    def _shard_loop_state(self, mesh):
        """Lay the loop state out over the mesh: env/hidden/windower state
        and per-shard cursors split along 'data', ring rows split along the
        capacity axis."""
        from ..parallel.mesh import shard_batch
        self.state = shard_batch(mesh, self.state)
        if self.hidden is not None:
            self.hidden = shard_batch(mesh, self.hidden)
        self.wstate = shard_batch(mesh, self.wstate)
        self.ring = shard_batch(mesh, self.ring)
        self.cursor = shard_batch(mesh, self.cursor)
        self.size = shard_batch(mesh, self.size)
        self.rng = shard_batch(mesh, self.rng)

    def _build_sharded(self, mesh, gen_ingest, sgd_tail, pack, b_loc):
        """shard_map'd variants: every shard runs rollout + ingest on its
        own envs and ring slice; the SGD tail samples the per-shard batch
        slice and psums grads/metrics inside the update (train_step.py),
        so train_state stays replicated with no broadcast. The only
        cross-chip traffic in steady state is the gradient/metric psum —
        the layout How-to-Scale calls pure data parallelism, riding ICI."""
        from functools import partial

        try:
            # jax >= 0.8: jax.shard_map, replication check named check_vma
            shard_map = partial(jax.shard_map, check_vma=False)
        except AttributeError:         # older jax
            from jax.experimental.shard_map import shard_map
            shard_map = partial(shard_map, check_rep=False)
        from jax.sharding import PartitionSpec as P

        D, R = P('data'), P()

        def shard_warm(actor_params, env_state, hidden, wstate, ring,
                       cursor, size, rng):
            (env_state, hidden, wstate, ring, c, s, k,
             done, outcome, n_win) = gen_ingest(
                actor_params, env_state, hidden, wstate, ring,
                cursor[0], size[0], rng[0])
            size_tot = jax.lax.psum(s, 'data')
            size_min = jax.lax.pmin(s, 'data')
            win_tot = jax.lax.psum(n_win, 'data')
            return (env_state, hidden, wstate, ring, c[None], s[None],
                    k[None], done, outcome, size_tot, size_min, win_tot)

        def shard_fused(actor_params, train_state, env_state, hidden,
                        wstate, ring, cursor, size, rng, data_cnt_ema):
            (env_state, hidden, wstate, ring, c, s, k,
             done, outcome, n_win) = gen_ingest(
                actor_params, env_state, hidden, wstate, ring,
                cursor[0], size[0], rng[0])
            train_state, k, mvals = sgd_tail(
                train_state, ring, c, s, k, data_cnt_ema, b_loc)
            size_tot = jax.lax.psum(s, 'data')
            size_min = jax.lax.pmin(s, 'data')
            win_tot = jax.lax.psum(n_win, 'data')
            return (train_state, env_state, hidden, wstate, ring, c[None],
                    s[None], k[None], done, outcome, size_tot, size_min,
                    win_tot, jnp.stack(mvals) if mvals else jnp.zeros((0,)))

        sm_warm = shard_map(
            shard_warm, mesh=mesh,
            in_specs=(R, D, D, D, D, D, D, D),
            out_specs=(D, D, D, D, D, D, D, P(None, 'data'),
                       P(None, 'data'), R, R, R))
        sm_fused = shard_map(
            shard_fused, mesh=mesh,
            in_specs=(R, R, D, D, D, D, D, D, D, R),
            out_specs=(R, D, D, D, D, D, D, D, P(None, 'data'),
                       P(None, 'data'), R, R, R, R))

        def warmup(actor_params, env_state, hidden, wstate, ring,
                   cursor, size, rng):
            (env_state, hidden, wstate, ring, cursor, size, rng,
             done, outcome, size_tot, size_min, win_tot) = sm_warm(
                actor_params, env_state, hidden, wstate, ring, cursor,
                size, rng)
            return (env_state, hidden, wstate, ring, cursor, size, rng,
                    pack(done, outcome, size_tot, size_min, win_tot, []))

        def fused(actor_params, train_state, env_state, hidden, wstate,
                  ring, cursor, size, rng, data_cnt_ema):
            (train_state, env_state, hidden, wstate, ring, cursor, size,
             rng, done, outcome, size_tot, size_min, win_tot,
             mvec) = sm_fused(
                actor_params, train_state, env_state, hidden, wstate,
                ring, cursor, size, rng, data_cnt_ema)
            mvals = [mvec[i] for i in range(len(self._metric_keys))]
            return (train_state, env_state, hidden, wstate, ring, cursor,
                    size, rng,
                    pack(done, outcome, size_tot, size_min, win_tot, mvals))

        return warmup, fused

    # -- dispatch helpers --------------------------------------------------
    def _parse(self, pending):
        flat, has_metrics = pending
        flat = np.asarray(flat)
        K, N, P = self.chunk_steps, self.n_envs, self.num_players
        done = flat[:K * N].reshape(K, N) > 0.5
        outcome = flat[K * N:K * N * (1 + P)].reshape(K, N, P)
        rest = flat[K * N * (1 + P):]
        self.ring_size_host = int(rest[0])
        self.ring_min_host = int(rest[1])
        # true cumulative ingest count (ring size saturates at capacity
        # once the ring wraps, so it cannot stand in for this)
        self.windows_ingested_host += int(rest[2])
        metrics = None
        if has_metrics:
            metrics = {k: float(v)
                       for k, v in zip(self._metric_keys, rest[3:])}
        return {'done': done, 'outcome': outcome, 'metrics': metrics}

    def _flip(self, pack_future, has_metrics):
        """Pipeline the single per-chunk fetch one dispatch deep."""
        prev, self._pending = self._pending, (pack_future, has_metrics)
        self.dispatches += 1
        if prev is None:
            return None
        return self._parse(prev)

    def warm_step(self, actor_params):
        """Generation+ingest only (pre-minimum_episodes). Returns the parsed
        accounting of the PREVIOUS chunk, or None on the first call."""
        (self.state, self.hidden, self.wstate, self.ring, self.cursor,
         self.size, self.rng, packed) = self._warmup(
            actor_params, self.state, self.hidden, self.wstate, self.ring,
            self.cursor, self.size, self.rng)
        return self._flip(packed, False)

    def train_step(self, actor_params, train_state: TrainState,
                   data_cnt_ema: float):
        """One fused chunk+ingest+K-SGD-steps dispatch. Returns
        (train_state, parsed_prev_chunk_or_None)."""
        (train_state, self.state, self.hidden, self.wstate, self.ring,
         self.cursor, self.size, self.rng, packed) = self._fused(
            actor_params, train_state, self.state, self.hidden, self.wstate,
            self.ring, self.cursor, self.size, self.rng,
            jnp.asarray(data_cnt_ema, jnp.float32))
        return train_state, self._flip(packed, True)

    def drain(self):
        """Fetch the last in-flight chunk's accounting (loop shutdown)."""
        if self._pending is None:
            return None
        prev, self._pending = self._pending, None
        return self._parse(prev)
