"""Episode storage and training-batch construction (host side).

Turns ragged self-play episodes into the fixed-shape ``(B, T, P, ...)``
arrays the compiled update step consumes. Semantics replicate the reference
batch builder exactly (train.py:33-124) — every downstream mask depends on
them:

  * missing per-player entries are backfilled: prob -> 1, action -> 0,
    action_mask -> +1e32 (all actions illegal), observation -> zeros;
  * windows shorter than ``burn_in_steps + forward_steps`` are padded:
    before-window with zeros (masks 0), after episode end with zeros except
    ``value``, which is padded with the final outcome (terminal bootstrap),
    and ``progress``, padded with 1;
  * ``turn_mask`` marks steps where the player actually acted,
    ``observation_mask`` steps where they observed, ``episode_mask`` real
    (non-padding) steps.

Episodes are stored as independently decompressible chunks of
``compress_steps`` moments (bz2), so window selection only decodes the
blocks it needs (generation.py:87-90, train.py:307-314).
"""

from __future__ import annotations

import bz2
import pickle
import random
from typing import Any, Dict, List, Sequence

import numpy as np

from ..utils.tree import map_structure, stack_structure

MOMENT_KEYS = ('observation', 'selected_prob', 'action_mask', 'action',
               'value', 'reward', 'return')


def compress_moments(moments: List[dict], compress_steps: int) -> List[bytes]:
    """Chunk + compress a finished episode's moments."""
    return [bz2.compress(pickle.dumps(moments[i:i + compress_steps]))
            for i in range(0, len(moments), compress_steps)]


def decompress_moments(blocks: Sequence[bytes]) -> List[dict]:
    out: List[dict] = []
    for block in blocks:
        out += pickle.loads(bz2.decompress(block))
    return out


def select_episode(episodes: Sequence[dict], args: Dict[str, Any]) -> dict:
    """Recency-biased episode + window sampling (train.py:291-315).

    Index i among N buffered episodes is accepted with probability
    (i+1)/N — newer episodes are proportionally more likely — then a uniform
    random ``forward_steps`` window (plus up to ``burn_in_steps`` of warmup
    context) is sliced out, keeping only the compressed blocks it covers.
    """
    while True:
        ep_count = min(len(episodes), args['maximum_episodes'])
        ep_idx = random.randrange(ep_count)
        accept_rate = 1 - (ep_count - 1 - ep_idx) / ep_count
        if random.random() >= accept_rate:
            continue
        try:
            ep = episodes[ep_idx]
            break
        except IndexError:
            continue

    turn_candidates = 1 + max(0, ep['steps'] - args['forward_steps'])
    train_st = random.randrange(turn_candidates)
    st = max(0, train_st - args['burn_in_steps'])
    ed = min(train_st + args['forward_steps'], ep['steps'])
    cs = args['compress_steps']
    st_block, ed_block = st // cs, (ed - 1) // cs + 1
    return {
        'args': ep['args'], 'outcome': ep['outcome'],
        'moment': ep['moment'][st_block:ed_block],
        'base': st_block * cs,
        'start': st, 'end': ed, 'train_start': train_st, 'total': ep['steps'],
    }


def _replace_none(value, fallback):
    return value if value is not None else fallback


def _build_one(ep: dict, args: Dict[str, Any]) -> Dict[str, Any]:
    moments = decompress_moments(ep['moment'])[ep['start'] - ep['base']:ep['end'] - ep['base']]
    return build_window(moments, ep, args)


def build_window(moments: List[dict], ep: dict, args: Dict[str, Any]
                 ) -> Dict[str, Any]:
    """Build one training window from already-decoded moments (``moments``
    is the [start:end) slice; ``ep`` supplies outcome/start/end/train_start/
    total). Lets callers that decode an episode once build many windows
    without re-decompressing."""
    players = list(moments[0]['observation'].keys())
    if not args['turn_based_training']:   # solo training: one random seat
        players = [random.choice(players)]

    first_turn = moments[0]['turn'][0]
    obs_zeros = map_structure(np.zeros_like, moments[0]['observation'][first_turn])
    amask_full = np.zeros_like(moments[0]['action_mask'][first_turn]) + 1e32

    if args['turn_based_training'] and not args['observation']:
        # store only the turn player's data each step (P axis of size 1)
        players_list = [[m['turn'][0]] for m in moments]
    else:
        players_list = [players for _ in moments]

    obs = [[_replace_none(m['observation'][p], obs_zeros) for p in ps]
           for m, ps in zip(moments, players_list)]
    obs = stack_structure([stack_structure(row) for row in obs])   # (T, P, ...)

    prob = np.array([[[_replace_none(m['selected_prob'][p], 1.0)] for p in ps]
                     for m, ps in zip(moments, players_list)], dtype=np.float32)
    act = np.array([[[_replace_none(m['action'][p], 0)] for p in ps]
                    for m, ps in zip(moments, players_list)], dtype=np.int32)
    amask = np.array([[_replace_none(m['action_mask'][p], amask_full) for p in ps]
                      for m, ps in zip(moments, players_list)], dtype=np.float32)

    T, P = len(moments), len(players)
    v = np.array([[_replace_none(m['value'][p], [0]) for p in players]
                  for m in moments], dtype=np.float32).reshape(T, P, -1)
    rew = np.array([[_replace_none(m['reward'][p], 0) for p in players]
                    for m in moments], dtype=np.float32).reshape(T, P, -1)
    ret = np.array([[_replace_none(m['return'][p], 0) for p in players]
                    for m in moments], dtype=np.float32).reshape(T, P, -1)
    oc = np.array([ep['outcome'][p] for p in players],
                  dtype=np.float32).reshape(1, P, -1)

    # NOTE: masks span ALL players even in turn-alternating mode (where
    # obs/prob/action/action_mask carry only the turn player, P=1): the
    # loss pipeline gathers the turn player's policy row via turn_mask and
    # gates per-player RNN state via observation_mask (train.py:86-87).
    emask = np.ones((T, 1, 1), dtype=np.float32)
    tmask = np.array([[[m['selected_prob'][p] is not None] for p in players]
                      for m in moments], dtype=np.float32)
    omask = np.array([[[m['observation'][p] is not None] for p in players]
                      for m in moments], dtype=np.float32)
    progress = (np.arange(ep['start'], ep['end'], dtype=np.float32)[:, None]
                / ep['total'])

    batch_steps = args['burn_in_steps'] + args['forward_steps']
    if T < batch_steps:
        pad_b = args['burn_in_steps'] - (ep['train_start'] - ep['start'])
        pad_a = batch_steps - T - pad_b

        def pad_t(a, before, after, value):
            width = [(before, after)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, width, 'constant', constant_values=value)

        obs = map_structure(lambda o: pad_t(o, pad_b, pad_a, 0), obs)
        prob = pad_t(prob, pad_b, pad_a, 1)
        # value: zeros before the window, final outcome beyond episode end
        v = np.concatenate([pad_t(v, pad_b, 0, 0), np.tile(oc, (pad_a, 1, 1))])
        act = pad_t(act, pad_b, pad_a, 0)
        rew = pad_t(rew, pad_b, pad_a, 0)
        ret = pad_t(ret, pad_b, pad_a, 0)
        emask = pad_t(emask, pad_b, pad_a, 0)
        tmask = pad_t(tmask, pad_b, pad_a, 0)
        omask = pad_t(omask, pad_b, pad_a, 0)
        amask = pad_t(amask, pad_b, pad_a, 1e32)
        progress = pad_t(progress, pad_b, pad_a, 1)

    return {
        'observation': obs, 'selected_prob': prob, 'value': v, 'action': act,
        'outcome': oc, 'reward': rew, 'return': ret, 'episode_mask': emask,
        'turn_mask': tmask, 'observation_mask': omask, 'action_mask': amask,
        'progress': progress,
    }


def stack_windows(rows: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Stack per-window dicts into one (B, T, P, ...) batch dict."""
    batch = {}
    for key in rows[0]:
        batch[key] = stack_structure([r[key] for r in rows])
    return batch


def make_batch(episodes: Sequence[dict], args: Dict[str, Any]) -> Dict[str, Any]:
    """Build a (B, T, P, ...) training batch from selected episode windows."""
    return stack_windows([_build_one(ep, args) for ep in episodes])
