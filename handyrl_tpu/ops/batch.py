"""Episode storage and training-batch construction (host side).

Turns ragged self-play episodes into the fixed-shape ``(B, T, P, ...)``
arrays the compiled update step consumes. Semantics replicate the reference
batch builder exactly (train.py:33-124) — every downstream mask depends on
them:

  * missing per-player entries are backfilled: prob -> 1, action -> 0,
    action_mask -> +1e32 (all actions illegal), observation -> zeros;
  * windows shorter than ``burn_in_steps + forward_steps`` are padded:
    before-window with zeros (masks 0), after episode end with zeros except
    ``value``, which is padded with the final outcome (terminal bootstrap),
    and ``progress``, padded with 1;
  * ``turn_mask`` marks steps where the player actually acted,
    ``observation_mask`` steps where they observed, ``episode_mask`` real
    (non-padding) steps.

Episodes are stored as independently decompressible chunks of
``compress_steps`` moments (bz2), so window selection only decodes the
blocks it needs (generation.py:87-90, train.py:307-314).

Two builders produce identical bits:

  * the ARENA builder (``make_batch`` / ``build_window``) — the production
    path: each episode is decoded once and written straight into
    preallocated ``(B, T, P, ...)`` numpy arenas (optionally caller-owned,
    e.g. shared-memory slots via ``out=``), with pad defaults pre-filled in
    bulk. No per-moment list comprehensions, no intermediate per-window
    arrays, no final re-stack;
  * the REFERENCE builder (``make_batch_reference``) — the original
    per-moment/per-player list-comprehension implementation, kept verbatim
    as the semantic pin. tests/test_batch_vectorized.py fuzzes ragged
    episodes through both and asserts bit-exact equality.
"""

from __future__ import annotations

import bz2
import pickle
import random
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.tree import map_structure, stack_structure

MOMENT_KEYS = ('observation', 'selected_prob', 'action_mask', 'action',
               'value', 'reward', 'return')


def compress_moments(moments: List[dict], compress_steps: int,
                     level: int = 9) -> List[bytes]:
    """Chunk + compress a finished episode's moments.

    ``level`` is bz2's compresslevel (1 fastest .. 9 smallest, the bz2
    default): on engine-mode workers compression dominates the remaining
    per-episode CPU, so hosts squeezed for actor cycles can trade upload
    bytes for throughput via the ``compress_level`` config knob."""
    return [bz2.compress(pickle.dumps(moments[i:i + compress_steps]),
                         compresslevel=int(level))
            for i in range(0, len(moments), compress_steps)]


def decompress_moments(blocks: Sequence[bytes]) -> List[dict]:
    out: List[dict] = []
    for block in blocks:
        out += pickle.loads(bz2.decompress(block))
    return out


def _chunk_recv_time(ep: dict, train_st: int):
    """Ingest timestamp of the window at ``train_st``: per-chunk for a
    streamed entry (streaming.py stamps ``chunk_recv`` per exposed window),
    the whole-episode stamp otherwise."""
    recv = ep.get('chunk_recv')
    if recv:
        T = max(1, int(ep.get('chunk_steps') or 1))
        return recv[min(train_st // T, len(recv) - 1)]
    return ep.get('recv_time')


def select_episode(episodes: Sequence[dict], args: Dict[str, Any],
                   now=None) -> dict:
    """Recency-biased episode + window sampling (train.py:291-315).

    Index i among N buffered episodes is accepted with probability
    (i+1)/N — newer episodes are proportionally more likely — then a uniform
    random ``forward_steps`` window (plus up to ``burn_in_steps`` of warmup
    context) is sliced out, keeping only the compressed blocks it covers.

    With ``streaming.staleness_half_life`` > 0 and streamed (chunk-stamped)
    entries in the buffer, a drawn window is additionally accepted with
    probability ``0.5 ** (chunk_age / half_life)`` over its PER-CHUNK
    ``sample_age`` — stale windows of long in-flight episodes decay instead
    of sampling uniformly — re-drawing episode + window up to
    ``streaming.max_reselect`` times before accepting regardless (bounded
    work, no starvation). The knob at 0 adds ZERO random draws: the off
    path is byte-identical to the pre-streaming sampler.
    """
    stm = args.get('streaming') or {}
    half_life = float(stm.get('staleness_half_life', 0.0) or 0.0)
    reselects = int(stm.get('max_reselect', 4)) if half_life > 0 else 0
    while True:
        while True:
            ep_count = min(len(episodes), args['maximum_episodes'])
            ep_idx = random.randrange(ep_count)
            accept_rate = 1 - (ep_count - 1 - ep_idx) / ep_count
            if random.random() >= accept_rate:
                continue
            try:
                ep = episodes[ep_idx]
                break
            except IndexError:
                continue

        turn_candidates = 1 + max(0, ep['steps'] - args['forward_steps'])
        train_st = random.randrange(turn_candidates)
        if reselects <= 0:
            break
        recv = _chunk_recv_time(ep, train_st)
        if recv is None:
            break
        if now is None:
            import time as _time
            now = _time.time()
        age = max(0.0, float(now) - float(recv))
        if random.random() < 0.5 ** (age / half_life):  # graftlint: allow[GL001] learner-side window SELECTION, not record production — same process-global stream the surrounding sampler (train.py:291-315 parity) already draws from, and active only when streaming.staleness_half_life opts in
            break
        reselects -= 1

    st = max(0, train_st - args['burn_in_steps'])
    ed = min(train_st + args['forward_steps'], ep['steps'])
    cs = args['compress_steps']
    st_block, ed_block = st // cs, (ed - 1) // cs + 1
    return {
        'args': ep['args'], 'outcome': ep['outcome'],
        'moment': ep['moment'][st_block:ed_block],
        'base': st_block * cs,
        'start': st, 'end': ed, 'train_start': train_st, 'total': ep['steps'],
        # learner ingest timestamp (stamped by feed_episodes, or per-chunk
        # by the streaming assembler): selection is the consumption point,
        # so the batcher can histogram sample age over the data actually
        # trained on (policy-lag accounting, docs/observability.md)
        'recv_time': _chunk_recv_time(ep, train_st),
    }


def _replace_none(value, fallback):
    return value if value is not None else fallback


# ---------------------------------------------------------------------------
# reference builder — the original implementation, kept VERBATIM as the
# semantic pin for the arena builder (and the denominator of the ingest
# benchmark, bench.py BENCH_MODE=ingest). Not used on the production path.


def build_window_reference(moments: List[dict], ep: dict, args: Dict[str, Any]
                           ) -> Dict[str, Any]:
    """One training window via per-moment/per-player list comprehensions
    (reference train.py:33-124 semantics, pre-vectorization)."""
    players = list(moments[0]['observation'].keys())
    if not args['turn_based_training']:   # solo training: one random seat
        players = [random.choice(players)]

    first_turn = moments[0]['turn'][0]
    obs_zeros = map_structure(np.zeros_like, moments[0]['observation'][first_turn])
    amask_full = np.zeros_like(moments[0]['action_mask'][first_turn]) + 1e32

    if args['turn_based_training'] and not args['observation']:
        # store only the turn player's data each step (P axis of size 1)
        players_list = [[m['turn'][0]] for m in moments]
    else:
        players_list = [players for _ in moments]

    obs = [[_replace_none(m['observation'][p], obs_zeros) for p in ps]
           for m, ps in zip(moments, players_list)]
    obs = stack_structure([stack_structure(row) for row in obs])   # (T, P, ...)

    prob = np.array([[[_replace_none(m['selected_prob'][p], 1.0)] for p in ps]
                     for m, ps in zip(moments, players_list)], dtype=np.float32)
    act = np.array([[[_replace_none(m['action'][p], 0)] for p in ps]
                    for m, ps in zip(moments, players_list)], dtype=np.int32)
    amask = np.array([[_replace_none(m['action_mask'][p], amask_full) for p in ps]
                      for m, ps in zip(moments, players_list)], dtype=np.float32)

    T, P = len(moments), len(players)
    v = np.array([[_replace_none(m['value'][p], [0]) for p in players]
                  for m in moments], dtype=np.float32).reshape(T, P, -1)
    rew = np.array([[_replace_none(m['reward'][p], 0) for p in players]
                    for m in moments], dtype=np.float32).reshape(T, P, -1)
    ret = np.array([[_replace_none(m['return'][p], 0) for p in players]
                    for m in moments], dtype=np.float32).reshape(T, P, -1)
    oc = np.array([ep['outcome'][p] for p in players],
                  dtype=np.float32).reshape(1, P, -1)

    # NOTE: masks span ALL players even in turn-alternating mode (where
    # obs/prob/action/action_mask carry only the turn player, P=1): the
    # loss pipeline gathers the turn player's policy row via turn_mask and
    # gates per-player RNN state via observation_mask (train.py:86-87).
    emask = np.ones((T, 1, 1), dtype=np.float32)
    tmask = np.array([[[m['selected_prob'][p] is not None] for p in players]
                      for m in moments], dtype=np.float32)
    omask = np.array([[[m['observation'][p] is not None] for p in players]
                      for m in moments], dtype=np.float32)
    progress = (np.arange(ep['start'], ep['end'], dtype=np.float32)[:, None]
                / ep['total'])

    batch_steps = args['burn_in_steps'] + args['forward_steps']
    if T < batch_steps:
        pad_b = args['burn_in_steps'] - (ep['train_start'] - ep['start'])
        pad_a = batch_steps - T - pad_b

        def pad_t(a, before, after, value):
            width = [(before, after)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, width, 'constant', constant_values=value)

        obs = map_structure(lambda o: pad_t(o, pad_b, pad_a, 0), obs)
        prob = pad_t(prob, pad_b, pad_a, 1)
        # value: zeros before the window, final outcome beyond episode end
        v = np.concatenate([pad_t(v, pad_b, 0, 0), np.tile(oc, (pad_a, 1, 1))])
        act = pad_t(act, pad_b, pad_a, 0)
        rew = pad_t(rew, pad_b, pad_a, 0)
        ret = pad_t(ret, pad_b, pad_a, 0)
        emask = pad_t(emask, pad_b, pad_a, 0)
        tmask = pad_t(tmask, pad_b, pad_a, 0)
        omask = pad_t(omask, pad_b, pad_a, 0)
        amask = pad_t(amask, pad_b, pad_a, 1e32)
        progress = pad_t(progress, pad_b, pad_a, 1)

    return {
        'observation': obs, 'selected_prob': prob, 'value': v, 'action': act,
        'outcome': oc, 'reward': rew, 'return': ret, 'episode_mask': emask,
        'turn_mask': tmask, 'observation_mask': omask, 'action_mask': amask,
        'progress': progress,
    }


def _decode_window(ep: dict, cache: Optional['BlockCache'] = None
                   ) -> List[dict]:
    if cache is None:
        moments = decompress_moments(ep['moment'])
    else:
        moments = []
        for block in ep['moment']:
            moments += cache.get(block)
    return moments[ep['start'] - ep['base']:ep['end'] - ep['base']]


class BlockCache:
    """Bounded LRU of decoded bz2 moment blocks, shared across batches.

    Window selection is recency-biased, so the same episodes — the same
    compressed blocks — are decoded over and over: within one batch (B
    windows drawn from far fewer buffered episodes) and across consecutive
    batches. Keying on the immutable block bytes themselves (CPython caches
    a bytes object's hash, and dict hits short-circuit on identity) makes
    each block's bz2+pickle cost one-time until evicted, which collapses
    the 'decode' stage of the ingest breakdown to near zero at steady
    state. Thread-safe: one instance serves every batcher thread.

    Cached moments are shared READ-ONLY: both builders only read moment
    dicts (arena assignment copies leaf arrays), so sharing is safe.
    """

    def __init__(self, max_blocks: int = 1024):
        from collections import OrderedDict
        import threading
        self.max_blocks = max_blocks
        self._od: 'OrderedDict[bytes, List[dict]]' = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, block: bytes) -> List[dict]:
        with self._lock:
            hit = self._od.get(block)
            if hit is not None:
                self._od.move_to_end(block)
                self.hits += 1
                return hit
            self.misses += 1
        decoded = pickle.loads(bz2.decompress(block))
        with self._lock:
            self._od[block] = decoded
            while len(self._od) > self.max_blocks:
                self._od.popitem(last=False)
        return decoded


def make_block_cache(args: Dict[str, Any]) -> Optional[BlockCache]:
    """BlockCache sized by args['decode_cache_blocks'] (default 1024);
    0 disables the cross-batch cache (per-batch de-dup remains)."""
    n = args.get('decode_cache_blocks')
    n = 1024 if n is None else int(n)
    return BlockCache(n) if n > 0 else None


def stack_windows(rows: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Stack per-window dicts into one (B, T, P, ...) batch dict."""
    batch = {}
    for key in rows[0]:
        batch[key] = stack_structure([r[key] for r in rows])
    return batch


def make_batch_reference(episodes: Sequence[dict], args: Dict[str, Any]
                         ) -> Dict[str, Any]:
    """(B, T, P, ...) batch via the reference per-window builder + stack."""
    return stack_windows([build_window_reference(_decode_window(ep), ep, args)
                          for ep in episodes])


# ---------------------------------------------------------------------------
# arena builder — the production path


def _leaf_paths(x, prefix: Tuple = ()) -> List[Tuple]:
    """Depth-first paths of every non-container leaf (dict keys in
    insertion order, list/tuple indices), mirroring utils.tree walks."""
    if isinstance(x, dict):
        out: List[Tuple] = []
        for k in x:
            out += _leaf_paths(x[k], prefix + (k,))
        return out
    if isinstance(x, (list, tuple)):
        out = []
        for i, v in enumerate(x):
            out += _leaf_paths(v, prefix + (i,))
        return out
    return [prefix]


def _get_path(x, path: Tuple):
    for k in path:
        x = x[k]
    return x


def _tail_dim(windows: Sequence[List[dict]], key: str) -> int:
    """Trailing feature dim the reference's ``reshape(T, P, -1)`` yields for
    ``key``: the element count of the first non-None entry (1 if all None,
    from the scalar/[0] fallback)."""
    for moments in windows:
        for m in moments:
            for v in m[key].values():
                if v is not None:
                    return max(1, int(np.asarray(v).size))
    return 1


def _alloc_arenas(B: int, S: int, moments0: List[dict], players: List,
                  args: Dict[str, Any], dims: Tuple[int, int, int]
                  ) -> Dict[str, Any]:
    """Preallocate the full (B, S, P, ...) batch with pad defaults baked in
    (obs/act/value/reward/return/masks 0, prob 1, action_mask 1e32,
    progress 1). Shapes/dtypes come from the first window's acting seat,
    exactly where the reference builder takes its zero templates."""
    first_turn = moments0[0]['turn'][0]
    obs_t = moments0[0]['observation'][first_turn]
    amask_t = np.asarray(moments0[0]['action_mask'][first_turn])
    P = len(players)
    Pd = 1 if (args['turn_based_training'] and not args['observation']) else P
    Vv, Vr, Vt = dims
    return {
        'observation': map_structure(
            lambda leaf: np.zeros((B, S, Pd) + np.asarray(leaf).shape,
                                  np.asarray(leaf).dtype), obs_t),
        'selected_prob': np.full((B, S, Pd, 1), 1.0, np.float32),
        'value': np.zeros((B, S, P, Vv), np.float32),
        'action': np.zeros((B, S, Pd, 1), np.int32),
        'outcome': np.zeros((B, 1, P, 1), np.float32),
        'reward': np.zeros((B, S, P, Vr), np.float32),
        'return': np.zeros((B, S, P, Vt), np.float32),
        'episode_mask': np.zeros((B, S, 1, 1), np.float32),
        'turn_mask': np.zeros((B, S, P, 1), np.float32),
        'observation_mask': np.zeros((B, S, P, 1), np.float32),
        'action_mask': np.full((B, S, Pd) + amask_t.shape, 1e32, np.float32),
        'progress': np.full((B, S, 1), 1.0, np.float32),
    }


def _reset_arenas(ar: Dict[str, Any]):
    """Restore pad defaults in a reused (e.g. shared-memory) arena set."""
    for key, arena in ar.items():
        if key == 'observation':
            map_structure(lambda a: a.fill(0), arena)
        elif key == 'selected_prob' or key == 'progress':
            arena.fill(1)
        elif key == 'action_mask':
            arena.fill(1e32)
        else:
            arena.fill(0)


def _fill_window(ar: Dict[str, Any], b: int, moments: List[dict], ep: dict,
                 args: Dict[str, Any], players: List,
                 obs_dsts: List[Tuple[Tuple, np.ndarray]]):
    """Write one window into batch row ``b`` of the preallocated arenas.
    Rows outside [pad_b, pad_b+T) keep their pre-filled pad defaults; the
    value tail additionally gets the terminal-bootstrap outcome."""
    S = args['burn_in_steps'] + args['forward_steps']
    T = len(moments)
    compact = args['turn_based_training'] and not args['observation']
    pad_b = (args['burn_in_steps'] - (ep['train_start'] - ep['start'])
             if T < S else 0)
    plain_obs = len(obs_dsts) == 1 and obs_dsts[0][0] == ()

    prob, act = ar['selected_prob'], ar['action']
    amask, val = ar['action_mask'], ar['value']
    rew, ret = ar['reward'], ar['return']
    tmask, omask = ar['turn_mask'], ar['observation_mask']

    for t, m in enumerate(moments):
        tt = pad_b + t
        ps = (m['turn'][0],) if compact else players
        m_obs, m_prob = m['observation'], m['selected_prob']
        m_amask, m_act = m['action_mask'], m['action']
        for j, p in enumerate(ps):
            x = m_prob[p]
            if x is not None:
                prob[b, tt, j, 0] = x
            x = m_act[p]
            if x is not None:
                act[b, tt, j, 0] = x
            x = m_amask[p]
            if x is not None:
                amask[b, tt, j] = x
            x = m_obs[p]
            if x is not None:
                if plain_obs:
                    obs_dsts[0][1][b, tt, j] = x
                else:
                    for path, dst in obs_dsts:
                        dst[b, tt, j] = _get_path(x, path)
        m_val, m_rew, m_ret = m['value'], m['reward'], m['return']
        for j, p in enumerate(players):
            x = m_val[p]
            if x is not None:
                val[b, tt, j] = np.asarray(x, np.float32).reshape(-1)
            x = m_rew[p]
            if x is not None:
                rew[b, tt, j] = np.asarray(x, np.float32).reshape(-1)
            x = m_ret[p]
            if x is not None:
                ret[b, tt, j] = np.asarray(x, np.float32).reshape(-1)
            if m_prob[p] is not None:
                tmask[b, tt, j, 0] = 1.0
            if m_obs[p] is not None:
                omask[b, tt, j, 0] = 1.0

    ar['episode_mask'][b, pad_b:pad_b + T, 0, 0] = 1.0
    ar['progress'][b, pad_b:pad_b + T, 0] = (
        np.arange(ep['start'], ep['end'], dtype=np.float32) / ep['total'])
    tail = pad_b + T
    for j, p in enumerate(players):
        oc = np.float32(ep['outcome'][p])
        ar['outcome'][b, 0, j, 0] = oc
        if tail < S:
            val[b, tail:, j] = oc


def _window_players(moments: List[dict], args: Dict[str, Any]) -> List:
    """The window's player axis — all seats, or one RANDOM seat in solo
    mode. The draw matches the reference builder's (one random.choice per
    window, same argument, same order), so a seeded RNG produces identical
    batches from either builder."""
    players = list(moments[0]['observation'].keys())
    if not args['turn_based_training']:
        players = [random.choice(players)]
    return players


def _obs_dsts(ar: Dict[str, Any]) -> List[Tuple[Tuple, np.ndarray]]:
    return [(path, _get_path(ar['observation'], path))
            for path in _leaf_paths(ar['observation'])]


def make_batch(episodes: Sequence[dict], args: Dict[str, Any],
               out: Optional[Dict[str, Any]] = None,
               timer=None, cache: Optional[BlockCache] = None
               ) -> Dict[str, Any]:
    """Build a (B, T, P, ...) training batch from selected episode windows.

    Each distinct bz2 block is decoded at most ONCE per batch — and, with a
    shared ``cache`` (BlockCache), at most once across batches until
    evicted — and windows are written directly into the batch arenas.
    ``out`` lets the caller own the arenas (shared-memory batcher slots
    write batches in place; pad defaults are restored on reuse). ``timer``
    (utils.timing.StageTimer) splits the wall time into the 'decode' and
    'assemble' stages of the ingest breakdown.
    """
    import time as _time
    t0 = _time.perf_counter()
    if cache is None:
        # within-batch de-dup at minimum: recency bias repeats episodes
        cache = BlockCache(max_blocks=max(256, 64 * len(episodes)))
    windows = [_decode_window(ep, cache) for ep in episodes]
    if timer is not None:
        t1 = _time.perf_counter()
        timer.add('decode', t1 - t0)
        t0 = t1
    players_per = [_window_players(m, args) for m in windows]
    dims = (_tail_dim(windows, 'value'), _tail_dim(windows, 'reward'),
            _tail_dim(windows, 'return'))
    S = args['burn_in_steps'] + args['forward_steps']
    if out is None:
        ar = _alloc_arenas(len(episodes), S, windows[0], players_per[0],
                           args, dims)
    else:
        ar = out
        _reset_arenas(ar)
    obs_dsts = _obs_dsts(ar)
    for b, (moments, players) in enumerate(zip(windows, players_per)):
        _fill_window(ar, b, moments, episodes[b], args, players, obs_dsts)
    if timer is not None:
        timer.add('assemble', _time.perf_counter() - t0)
    return ar


def build_window(moments: List[dict], ep: dict, args: Dict[str, Any]
                 ) -> Dict[str, Any]:
    """Build one training window from already-decoded moments (``moments``
    is the [start:end) slice; ``ep`` supplies outcome/start/end/train_start/
    total). Lets callers that decode an episode once build many windows
    without re-decompressing. Returns (T, P, ...) views over a one-row
    arena — same bits as ``build_window_reference``."""
    players = _window_players(moments, args)
    dims = (_tail_dim([moments], 'value'), _tail_dim([moments], 'reward'),
            _tail_dim([moments], 'return'))
    S = args['burn_in_steps'] + args['forward_steps']
    ar = _alloc_arenas(1, S, moments, players, args, dims)
    _fill_window(ar, 0, moments, ep, args, players, _obs_dsts(ar))
    return {k: (map_structure(lambda a: a[0], v) if k == 'observation'
                else v[0])
            for k, v in ar.items()}
