"""Forward pass + loss composition: one pure, jittable function.

Numerical parity targets: the reference training pipeline
(train.py:127-267) — same masks, same importance-sampling clipping, same
two-player value symmetrization and terminal bootstrap — rebuilt as a single
XLA program:

  * feed-forward nets: (B, T, P) folded into one batch dim — one big MXU
    matmul stream instead of T small ones;
  * recurrent nets: ``lax.scan`` over time with observation-mask-gated
    hidden carry; burn-in steps run in a separate scan whose carry passes
    through ``stop_gradient`` (the reference's no_grad replay,
    train.py:159-162);
  * turn-alternating batches (P_obs=1, P=2): the acting player's policy row
    is gathered by multiplying with turn_mask and summing the player axis
    (train.py:179-180); per-player hidden state is gated by
    observation_mask and merged back after each step (train.py:153-173).

Losses are sums (not means) so the EMA learning-rate schedule sees the true
data count, exactly like the reference.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .targets import compute_target

tmap = jax.tree_util.tree_map


class LossConfig(NamedTuple):
    """Hashable (static-arg) training configuration for the compiled step."""
    turn_based_training: bool = True
    observation: bool = False
    burn_in_steps: int = 0
    policy_target: str = 'TD'
    value_target: str = 'TD'
    lmb: float = 0.7
    gamma: float = 0.8
    entropy_regularization: float = 0.1
    entropy_regularization_decay: float = 0.1
    # IMPACT-style clipped target network (streaming.target_clip): > 0
    # replaces the V-Trace behavior ratio with the target-network ratio
    # pi_target/mu, clipped at this value. 0 = off (byte-identical step).
    target_clip: float = 0.0

    @classmethod
    def from_args(cls, args: Dict[str, Any]) -> 'LossConfig':
        return cls(
            turn_based_training=bool(args['turn_based_training']),
            observation=bool(args['observation']),
            burn_in_steps=int(args['burn_in_steps']),
            policy_target=str(args['policy_target']),
            value_target=str(args['value_target']),
            lmb=float(args['lambda']),
            gamma=float(args['gamma']),
            entropy_regularization=float(args['entropy_regularization']),
            entropy_regularization_decay=float(args['entropy_regularization_decay']),
            target_clip=float((args.get('streaming') or {})
                              .get('target_clip', 0.0) or 0.0),
        )


def _fold_bt(x):
    """(B, T, P, ...) -> (B*T*P, ...)"""
    return x.reshape((-1,) + x.shape[3:])


def split_batch_stats(variables):
    """Split a flax variables dict into (trainable collections, batch_stats
    or None). Models without a ``batch_stats`` collection (every norm_kind
    but 'batch') pass through unchanged."""
    from collections.abc import Mapping
    if isinstance(variables, Mapping) and 'batch_stats' in variables:
        rest = {k: v for k, v in variables.items() if k != 'batch_stats'}
        return rest, variables['batch_stats']
    return variables, None


def forward_prediction(apply_fn, params, hidden, batch: Dict[str, Any],
                       cfg: LossConfig, batch_stats=None):
    """Run the net over a training window; returns time-major-stacked outputs
    shaped (B, T, P, ...) with policy/value/return masking applied.

    ``batch_stats`` engages reference-BatchNorm training semantics
    (norm_kind='batch', reference model.py:54 train/eval split): the net is
    applied with ``train=True, mutable=['batch_stats']`` so normalization
    uses the CURRENT batch's statistics while the running averages advance
    — once per window for feed-forward nets (the fold makes the statistics
    span B*T*P, exactly like the reference's flattened forward) and once
    per scan step for recurrent nets (the reference's T per-timestep
    BatchNorm calls, burn-in included: torch updates running stats under
    no_grad too). The return becomes ``(outputs, new_batch_stats)``; the
    updated stats are stop_gradient'd (write-only during training — the
    forward reads only batch statistics in train mode)."""
    observations = batch['observation']
    B, T, P_obs = batch['action'].shape[:3]

    def net(bs, obs_in, h_in):
        """One apply in the right mode; returns (out_dict, new_bs)."""
        if bs is None:
            return dict(apply_fn(params, obs_in, h_in)), None
        out, mut = apply_fn({**dict(params), 'batch_stats': bs}, obs_in,
                            h_in, train=True, mutable=['batch_stats'])
        return dict(out), lax.stop_gradient(mut['batch_stats'])

    if hidden is None:
        obs = tmap(_fold_bt, observations)
        outputs, new_bs = net(batch_stats, obs, None)
        outputs = {k: v.reshape((B, T, P_obs) + v.shape[1:])
                   for k, v in outputs.items() if k != 'hidden' and v is not None}
    else:
        obs_tm = tmap(lambda o: jnp.moveaxis(o, 1, 0), observations)   # (T, B, P_obs, ...)
        omask_tm = jnp.moveaxis(batch['observation_mask'], 1, 0)       # (T, B, P, 1)

        def step(carry, x):
            h_carry, bs = carry
            obs_t, omask_t = x
            # gate each player's hidden by whether they observed this step
            def gate(h):
                m = omask_t.reshape(omask_t.shape[:2] + (1,) * (h.ndim - 2))
                return h * m
            gated = tmap(gate, h_carry)
            if cfg.turn_based_training and not cfg.observation:
                # only the turn player observed: summing the player axis
                # selects their state (others were zeroed)
                h_in = tmap(lambda h: h.sum(axis=1), gated)
                obs_in = tmap(lambda o: o.reshape((B,) + o.shape[2:]), obs_t)
            else:
                h_in = tmap(lambda h: h.reshape((-1,) + h.shape[2:]), gated)
                obs_in = tmap(lambda o: o.reshape((-1,) + o.shape[2:]), obs_t)
            out, bs = net(bs, obs_in, h_in)
            next_h = out.pop('hidden')
            out = {k: v.reshape((B, P_obs) + v.shape[1:])
                   for k, v in out.items() if v is not None}
            next_h = tmap(lambda h: h.reshape((B, -1) + h.shape[1:]), next_h)

            def merge(h, nh):
                m = omask_t.reshape(omask_t.shape[:2] + (1,) * (h.ndim - 2))
                return h * (1 - m) + nh * m
            h_carry = tmap(merge, h_carry, next_h)
            return (h_carry, bs), out

        bi = cfg.burn_in_steps
        if bi > 0:
            xs_burn = (tmap(lambda o: o[:bi], obs_tm), omask_tm[:bi])
            (hidden, batch_stats), _ = lax.scan(
                step, (hidden, batch_stats), xs_burn)
            hidden = lax.stop_gradient(hidden)
        xs_main = (tmap(lambda o: o[bi:], obs_tm), omask_tm[bi:])
        (_, new_bs), outputs_tm = lax.scan(step, (hidden, batch_stats),
                                           xs_main)
        outputs = {k: jnp.moveaxis(v, 0, 1) for k, v in outputs_tm.items()}

        # re-attach zero outputs for burn-in steps so downstream slicing is
        # uniform with the feed-forward path
        if bi > 0:
            outputs = {k: jnp.concatenate(
                [jnp.zeros(v.shape[:1] + (bi,) + v.shape[2:], v.dtype), v], axis=1)
                for k, v in outputs.items()}

    masked = {}
    for k, o in outputs.items():
        if k == 'policy':
            o = o * batch['turn_mask']
            if o.shape[2] > 1 and P_obs == 1:
                # turn-alternating batch: gather the acting player's row
                o = o.sum(axis=2, keepdims=True)
            masked[k] = o - batch['action_mask']
        else:
            masked[k] = o * batch['observation_mask']
    if batch_stats is None and new_bs is None:
        return masked          # historical API: norm-stateless models
    return masked, new_bs


def _entropy(logits: jnp.ndarray) -> jnp.ndarray:
    """Categorical entropy over the last axis; -1e32-masked logits contribute
    exactly zero (their probability underflows to 0 while the logit stays
    finite)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -(jnp.exp(logp) * logp).sum(axis=-1)


def compose_losses(outputs: Dict[str, jnp.ndarray],
                   log_selected_policies: jnp.ndarray,
                   total_advantages: jnp.ndarray,
                   targets: Dict[str, Optional[jnp.ndarray]],
                   batch: Dict[str, Any], cfg: LossConfig
                   ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    tmasks = batch['turn_mask']
    omasks = batch['observation_mask']

    losses: Dict[str, jnp.ndarray] = {}
    dcnt = tmasks.sum()

    losses['p'] = (-log_selected_policies * total_advantages * tmasks).sum()
    if 'value' in outputs:
        losses['v'] = (((outputs['value'] - targets['value']) ** 2) * omasks).sum() / 2
    if 'return' in outputs:
        huber = optax_huber(outputs['return'], targets['return'])
        losses['r'] = (huber * omasks).sum()

    entropy = _entropy(outputs['policy']) * tmasks.sum(axis=-1)
    losses['ent'] = entropy.sum()

    base = losses['p'] + losses.get('v', 0) + losses.get('r', 0)
    decay = 1 - batch['progress'] * (1 - cfg.entropy_regularization_decay)
    entropy_loss = (entropy * decay).sum() * -cfg.entropy_regularization
    losses['total'] = base + entropy_loss
    return losses, dcnt


def optax_huber(pred: jnp.ndarray, target: jnp.ndarray, delta: float = 1.0
                ) -> jnp.ndarray:
    """Smooth-L1 (huber, delta=1), elementwise."""
    err = pred - target
    abs_err = jnp.abs(err)
    quad = jnp.minimum(abs_err, delta)
    return 0.5 * quad ** 2 + delta * (abs_err - quad)


def compute_loss(apply_fn, params, init_hidden, batch: Dict[str, Any],
                 cfg: LossConfig, batch_stats=None, target_params=None
                 ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """Full pipeline: forward, targets, advantages, composed losses.

    Returns (total_loss, aux) where aux carries per-term sums and the data
    count for the EMA lr schedule. For norm_kind='batch' models the caller
    may pass the full variables dict as ``params`` (the batch_stats
    collection is split off here) or pass ``batch_stats`` explicitly; the
    advanced running averages come back as ``aux['batch_stats']``.

    ``target_params`` (with ``cfg.target_clip`` > 0) engages the
    IMPACT-style clipped target network: a second, stop-gradient forward
    under the slow-moving target params supplies the importance ratio
    pi_target/mu used for the V-Trace corrections — clipped at
    ``target_clip`` for rho, at 1 for c — in place of the current-policy
    ratio. Streamed (staler) data then drives value targets through a
    policy that moves once per ``target_sync_epochs`` instead of every
    SGD step, which is what keeps high-lag chunks trainable. The policy
    gradient itself still differentiates the CURRENT policy's log-prob.
    """
    if batch_stats is None:
        params, batch_stats = split_batch_stats(params)
    outputs = forward_prediction(apply_fn, params, init_hidden, batch, cfg,
                                 batch_stats)
    new_bs = None
    if batch_stats is not None:
        outputs, new_bs = outputs

    use_target = target_params is not None and cfg.target_clip > 0
    tgt_outputs = None
    if use_target:
        t_params, t_bs = split_batch_stats(target_params)
        tgt_outputs = forward_prediction(apply_fn, t_params, init_hidden,
                                         batch, cfg, t_bs)
        if t_bs is not None:
            tgt_outputs, _ = tgt_outputs   # target stats never advance
        tgt_outputs = {k: lax.stop_gradient(v)
                       for k, v in tgt_outputs.items()}

    bi = cfg.burn_in_steps
    if bi > 0:
        batch = _slice_burn_in(batch, bi)
        outputs = {k: v[:, bi:] for k, v in outputs.items()}
        if tgt_outputs is not None:
            tgt_outputs = {k: v[:, bi:] for k, v in tgt_outputs.items()}

    actions = batch['action']
    emasks = batch['episode_mask']
    omasks = batch['observation_mask']
    value_target_masks = omasks

    clip_rho, clip_c = 1.0, 1.0

    log_b = jnp.log(jnp.clip(batch['selected_prob'], 1e-16, 1)) * emasks
    logp = jax.nn.log_softmax(outputs['policy'], axis=-1)
    log_t = jnp.take_along_axis(logp, actions, axis=-1) * emasks

    log_rhos = lax.stop_gradient(log_t) - log_b
    rhos = jnp.exp(log_rhos)
    if use_target:
        logp_tgt = jax.nn.log_softmax(tgt_outputs['policy'], axis=-1)
        log_tgt = jnp.take_along_axis(logp_tgt, actions, axis=-1) * emasks
        rhos_tgt = jnp.exp(log_tgt - log_b)
        clipped_rhos = jnp.clip(rhos_tgt, 0, cfg.target_clip)
        cs = jnp.clip(rhos_tgt, 0, clip_c)
    else:
        clipped_rhos = jnp.clip(rhos, 0, clip_rho)
        cs = jnp.clip(rhos, 0, clip_c)
    outputs_nograd = {k: lax.stop_gradient(v) for k, v in outputs.items()}

    if 'value' in outputs_nograd:
        values_nograd = outputs_nograd['value']
        if cfg.turn_based_training and values_nograd.shape[2] == 2:
            # two-player zero-sum: each player's estimate is blended with the
            # negation of the opponent's (train.py:243-247)
            values_opp = -jnp.flip(values_nograd, axis=2)
            omasks_opp = jnp.flip(omasks, axis=2)
            values_nograd = ((values_nograd * omasks + values_opp * omasks_opp)
                             / (omasks + omasks_opp + 1e-8))
            value_target_masks = jnp.clip(omasks + omasks_opp, 0, 1)
        # bootstrap padded steps beyond episode end with the final outcome
        outputs_nograd['value'] = (values_nograd * emasks
                                   + batch['outcome'] * (1 - emasks))

    targets: Dict[str, Any] = {}
    advantages: Dict[str, Any] = {}

    value_args = (outputs_nograd.get('value', None), batch['outcome'], None,
                  cfg.lmb, 1.0, clipped_rhos, cs, value_target_masks)
    return_args = (outputs_nograd.get('return', None), batch['return'],
                   batch['reward'], cfg.lmb, cfg.gamma, clipped_rhos, cs, omasks)

    targets['value'], advantages['value'] = compute_target(cfg.value_target, *value_args)
    targets['return'], advantages['return'] = compute_target(cfg.value_target, *return_args)

    if cfg.policy_target != cfg.value_target:
        _, advantages['value'] = compute_target(cfg.policy_target, *value_args)
        _, advantages['return'] = compute_target(cfg.policy_target, *return_args)

    total_advantages = clipped_rhos * sum(advantages.values())

    losses, dcnt = compose_losses(outputs, log_t, total_advantages, targets,
                                  batch, cfg)
    # off-policy health diagnostics, summed over acting (step, player)
    # pairs like every loss term so the host normalizes by data_count:
    # V-Trace rho/c clip fractions and the importance-ratio first/second
    # moments (mean/std of the behavior->target ratio). They ride the
    # update step's existing lazy metric fetch — no extra device sync.
    tmask = batch['turn_mask']
    diag = {
        'rho_clip': ((rhos > clip_rho) * tmask).sum(),
        'c_clip': ((rhos > clip_c) * tmask).sum(),
        'rho_sum': (rhos * tmask).sum(),
        'rho_sq_sum': (jnp.square(rhos) * tmask).sum(),
    }
    if use_target:
        # target-network health: clip fraction and first moment of the
        # target/behavior ratio, plus the current-vs-target log-prob gap
        # on taken actions (a drift/KL proxy) — how far the fast policy
        # has moved since the last target sync
        diag['target_clip'] = ((rhos_tgt > cfg.target_clip) * tmask).sum()
        diag['target_ratio_sum'] = (rhos_tgt * tmask).sum()
        diag['target_gap_sum'] = ((lax.stop_gradient(log_t) - log_tgt)
                                  * tmask).sum()
    aux = {'losses': losses, 'data_count': dcnt, 'diag': diag}
    if new_bs is not None:
        aux['batch_stats'] = new_bs
    return losses['total'], aux


def _slice_burn_in(batch: Dict[str, Any], bi: int) -> Dict[str, Any]:
    """Drop burn-in steps from every time-indexed entry (time-size-1 entries
    like outcome pass through, mirroring train.py:221)."""
    def cut(v):
        return v if v.shape[1] <= 1 else v[:, bi:]
    return {k: tmap(cut, v) if isinstance(v, dict) else cut(v)
            for k, v in batch.items()}
