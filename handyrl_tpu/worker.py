"""Actor-side process tree: episode workers, relay proxies, cluster fronts.

Round-2 redesign of the actor plumbing. The wire protocol is unchanged —
the four RPCs (``args`` / ``episode`` / ``result`` / ``model``), the entry
handshake on port 9999 (base_worker_id assignment + merged config), and the
data connections on port 9998 all match the reference topology
(reference worker.py:26-254) — but the machinery is built differently:

* every multiplexing component composes a :class:`~.connection.Hub`
  (single selector event loop) instead of subclassing a thread-pair
  communicator;
* workers cache model *snapshots per model id* in a small LRU vault and
  materialize wrappers per id — two ids of the same architecture can never
  alias one set of live params (a league/past-epoch opponent setup works);
* with the ``inference`` config block enabled, workers become pure
  env-steppers: the host relay (Gather) spawns one
  :class:`~.inference.InferenceEngine` that alone materializes snapshots
  and serves coalesced batched forward passes for every worker on the
  host — the 'model' RPC then flows learner -> gather -> engine only, so
  model broadcast cost is O(hosts), not O(workers). The engine is owned
  through an :class:`~.inference.EngineSupervisor` (restart on crash or
  stall, error fan-out) and workers degrade to the per-worker inference
  path — losslessly, records stay byte-identical — when it is
  unreachable, re-promoting once a probe succeeds;
* the 'model' RPC ships an architecture-name + msgpack-params snapshot
  (model.ModelWrapper.snapshot), never pickled code, and socket frames are
  msgpack data — nothing on the public ports can execute on decode.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue
import random
import threading
import time
import traceback
from collections import OrderedDict, defaultdict, deque
from socket import gethostname
from typing import Any, Dict, Optional

from . import telemetry
from .connection import (HEARTBEAT_KIND, INFER_KIND, RESUME_KIND, Hub,
                         accept_socket_connections,
                         connect_socket_connection, force_cpu_backend,
                         send_recv, spawn_pipe_workers)
from .environment import make_env, prepare_env
from .evaluation import Evaluator
from .fault import Backoff, parse_chaos
from .generation import Generator
# ModelVault moved to inference.py (the engine shares it); re-exported here
# for compatibility with existing imports
from .inference import (EngineClient, EngineSupervisor, InferenceEngine,
                        ModelVault, RemoteModelCache)

_LOG = telemetry.get_logger('worker')

# Overridable so several learner/worker fleets (or parallel test runs) can
# share one host without colliding on the well-known ports.
ENTRY_PORT = int(os.environ.get('HANDYRL_TPU_ENTRY_PORT', 9999))
DATA_PORT = int(os.environ.get('HANDYRL_TPU_DATA_PORT', 9998))

# connection-death signatures on the blocking RPC paths (sockets AND pipes);
# socket.timeout / Broken/ResetError are OSError subclasses
_CONN_ERRORS = (OSError, EOFError, ConnectionError)


class Worker:
    """One actor process: loops task requests over the 4-RPC protocol and
    plays out generation ('g') or evaluation ('e') assignments."""

    def __init__(self, args: Dict[str, Any], conn, wid: int):
        _LOG.info('opened worker %d', wid)
        telemetry.adopt_config(args)
        telemetry.set_process_label('worker-%d' % wid)
        telemetry.install_crash_dump()
        self.worker_id = wid
        self.conn = conn
        self.env = make_env({**args['env'], 'id': wid})
        random.seed(args['seed'] + wid)
        # one-way liveness/telemetry beacon cadence toward the gather: the
        # snapshot rides the same HEARTBEAT frame the Hub already filters
        ft = args.get('fault_tolerance') or {}
        self._hb_interval = float(ft.get('heartbeat_interval', 10.0))
        self._hb_next = time.time() + self._hb_interval

        inf = args.get('inference') or {}
        self.client: Optional[EngineClient] = None
        if inf.get('enabled'):
            # engine mode: this process materializes no params up front —
            # models are wire proxies onto the host relay's InferenceEngine.
            # The shared EngineClient owns request deadlines and the
            # circuit-breaker failover to the per-worker path (at which
            # point snapshots ARE materialized locally, via the same
            # 'model' RPC — graceful degradation costs memory, not bytes).
            self.client = EngineClient(conn, args, namespace=wid)
            self.vault = RemoteModelCache(self.client)
        else:
            self.env.reset()
            example_obs = self.env.observation(self.env.players()[0])
            self.vault = ModelVault(
                lambda mid: send_recv(conn, ('model', mid)), example_obs,
                capacity=int(inf.get('vault_size', 3)))

        generator = Generator(self.env, args, namespace=wid)
        evaluator = Evaluator(self.env, args)
        # role -> (episode producer, upload RPC name)
        self.playbook = {'g': (generator.execute, 'episode'),
                         'e': (evaluator.execute, 'result')}
        if (args.get('streaming') or {}).get('enabled'):
            # streaming ingest: the generator flushes fixed-T chunks
            # through the same RPC pipe mid-episode ('chunk' uploads ride
            # the gather's stash/resend machinery like any other kind);
            # the whole-episode upload collapses into a streamed sentinel
            # the run loop skips — the learner's assembler completes the
            # task once every window lands
            self.playbook['g'] = (
                lambda models, task: generator.execute(
                    models, task, emit=lambda c: self._rpc(('chunk', c))),
                'episode')

    def __del__(self):
        _LOG.info('closed worker %d', self.worker_id)

    def _maybe_heartbeat(self):
        """Piggyback this worker's registry snapshot on a heartbeat frame
        toward the gather's hub (filtered there into peer_info, merged into
        the gather's own beacon toward the learner)."""
        now = time.time()
        if self._hb_interval <= 0 or now < self._hb_next:
            return
        self._hb_next = now + self._hb_interval
        # refresh the device-memory gauges so every heartbeat snapshot
        # carries this process's current footprint up the merge tree
        telemetry.sample_device_memory()
        self.conn.send((HEARTBEAT_KIND,
                        {'worker': self.worker_id,
                         'telemetry': telemetry.snapshot()}))
        # keep the shared trace file current even while this worker lives:
        # a gather-killed (chaos) worker must not strand its episode spans
        telemetry.trace_flush()

    def _rpc(self, msg):
        """One blocking call-response on the gather pipe. In engine mode
        the EngineClient filters out any late inference reply that would
        otherwise be mistaken for this RPC's answer."""
        if self.client is not None:
            return self.client.rpc(msg)
        return send_recv(self.conn, msg)

    def run(self):
        """Supervised task loop: a broken pipe to the gather ends the
        process (the gather's supervisor respawns the whole subtree), but a
        crashing episode only costs that one episode — the payload becomes
        None (skipped server-side; the task ledger re-issues it on
        deadline) and the loop keeps serving."""
        chaos = parse_chaos()
        doom = None
        if chaos.get('kill_worker'):
            rng = random.Random(int(chaos.get('seed', 0)) * 7919
                                + self.worker_id)
            doom = time.time() + rng.expovariate(1.0 / chaos['kill_worker'])
        while True:
            if doom is not None and time.time() >= doom:
                print('chaos: worker %d self-destructing' % self.worker_id,
                      flush=True)
                os._exit(17)
            try:
                self._maybe_heartbeat()
                task = self._rpc(('args', None))
            except _CONN_ERRORS:
                self._gather_lost()
                return
            if task is None:
                return
            if task.get('role') == 'idle':
                # elastic fleet control: the learner is withholding fresh
                # tasks from this host (quarantined/draining) — nap and
                # re-ask instead of exiting, so the host stays warm for
                # re-admission
                telemetry.counter('worker_idle_tasks_total').inc()
                time.sleep(min(5.0, float(task.get('wait', 1.0))))
                continue
            produce, upload_as = self.playbook[task['role']]
            t0 = time.perf_counter()
            try:
                models = self.vault.obtain(dict(task.get('model_id', {})))
                payload = produce(models, task)
            except _CONN_ERRORS:       # model fetch rode the dead pipe
                self._gather_lost()
                return
            except Exception:
                traceback.print_exc()
                payload = None
                telemetry.counter('worker_task_failures_total').inc()
            telemetry.counter('worker_tasks_total',
                              role=task['role']).inc()
            telemetry.REGISTRY.histogram(
                'worker_task_seconds', role=task['role']).observe(
                    time.perf_counter() - t0)
            if isinstance(payload, dict) and payload.get('streamed'):
                # every window (final chunk included) already rode the
                # pipe mid-episode; there is no whole-episode upload
                continue
            try:
                self._rpc((upload_as, payload))
            except _CONN_ERRORS:
                self._gather_lost()
                return

    def _gather_lost(self):
        """The pipe to the gather died under us: leave a blackbox dump
        behind (the postmortem's evidence of WHICH side died first) and
        let the process exit — the gather supervisor owns respawns."""
        _LOG.warning('worker %d: lost its gather; exiting', self.worker_id)
        telemetry.record_event('guard', 'gather connection lost',
                               worker=self.worker_id)
        telemetry.dump_blackbox('gather-lost', worker=self.worker_id)


def open_worker(args, conn, wid):
    force_cpu_backend()
    Worker(args, conn, wid).run()


def _shard(total: int, parts: int, index: int) -> int:
    """Size of shard ``index`` when ``total`` items split across ``parts``."""
    return total // parts + (1 if index < total % parts else 0)


class UploadTrace:
    """Per-episode ``upload`` spans for the gather relay: payload stash
    time -> server ack. Only deterministically-sampled trace ids are
    tracked (the same keep/drop every other hop computes), bounded so a
    long outage cannot grow the book past the resend buffer's order."""

    MAX_PER_KIND = 512

    def __init__(self, gather_id: int):
        self.gather_id = int(gather_id)
        self._box: Dict[str, list] = defaultdict(list)

    def stash(self, kind: str, payload):
        if not telemetry.trace_enabled():
            return
        tid = telemetry.episode_trace_id((payload or {}).get('args') or {})
        if tid and telemetry.trace_sampled(tid):
            box = self._box[kind]
            if len(box) < self.MAX_PER_KIND:
                box.append((tid, time.time()))

    def shipped(self, kind: str):
        """The server acked this kind's batch: emit one span per tracked
        payload covering its whole stash->ack residence in the relay."""
        entries = self._box.pop(kind, None)
        if not entries:
            return
        now = time.time()
        for tid, t0 in entries:
            telemetry.trace_event('upload', ts=t0, dur=now - t0,
                                  trace_id=tid, kind=kind,
                                  gather=self.gather_id)
        telemetry.trace_flush()


class Gather:
    """Fan-in relay between ~16 workers and the learner.

    Amortizes server round-trips three ways: task assignments are prefetched
    in blocks, model snapshots are served from a per-id cache, and episode /
    result uploads are batched before shipping. State lives in three small
    stores; routing is a dispatch over the RPC kind.

    Fault tolerance (remote mode, i.e. ``reconnect`` given): every server
    RPC is supervised — a socket failure closes the connection, redials the
    data port with exponential backoff + jitter, and retries the same RPC,
    so batched ``_upload_box`` contents survive a severed link instead of
    dying with it (an RPC whose ack was lost is resent; the server's task
    ledger drops the duplicate). A daemon thread additionally sends one-way
    heartbeat frames carrying this relay's fleet stats, so the server's Hub
    can detach silently-dead peers and the learner can aggregate
    reconnect/drop counts per epoch.
    """

    def __init__(self, args: Dict[str, Any], server_conn, gather_id: int,
                 reconnect=None):
        _LOG.info('started gather %d', gather_id)
        telemetry.adopt_config(args)
        telemetry.set_process_label('gather-%d' % gather_id)
        telemetry.install_crash_dump()
        self.gather_id = gather_id
        self._upload_trace = UploadTrace(gather_id)
        gid = str(gather_id)
        self._m_uploads = {
            'episode': telemetry.counter('gather_uploads_total',
                                         gather=gid, kind='episode'),
            'result': telemetry.counter('gather_uploads_total',
                                        gather=gid, kind='result'),
            'chunk': telemetry.counter('gather_uploads_total',
                                       gather=gid, kind='chunk')}
        self._m_retries = telemetry.counter('gather_rpc_retries_total',
                                            gather=gid)
        self._m_reconnects = telemetry.counter('gather_reconnects_total',
                                               gather=gid)
        self._m_dropped = telemetry.counter('gather_dropped_uploads_total',
                                            gather=gid)
        self._m_box_depth = telemetry.gauge('gather_upload_box_depth',
                                            gather=gid)
        self._m_eps_rate = telemetry.gauge('gather_episodes_per_sec',
                                           gather=gid)
        ft = args.get('fault_tolerance') or {}
        self._reconnect_fn = reconnect
        self._rpc_timeout = float(ft.get('rpc_timeout', 120.0))
        self._hb_interval = float(ft.get('heartbeat_interval', 10.0))
        self._backoff_initial = float(ft.get('reconnect_initial_delay', 1.0))
        self._backoff_max = float(ft.get('reconnect_max_delay', 30.0))
        self._max_tries = int(ft.get('reconnect_max_tries', 30))
        self._resend_max = int(ft.get('resend_buffer', 256))
        # resume token stamped by a durable learner (train.py publishes it in
        # the merged entry config): presented on every redial so a RESTARTED
        # learner recognizes this gather and it rides through without a
        # respawn — an unrecognized run_id forces the cold path instead
        self._resume_token = dict(args.get('resume_token') or {})
        self.stats = {'reconnects': 0, 'dropped_uploads': 0, 'reattaches': 0}
        self._m_resend_dropped = telemetry.counter(
            'gather_resend_dropped_total', gather=gid)
        self._m_reattach = telemetry.counter('gather_reattach_total',
                                             gather=gid)
        if server_conn is None and reconnect is not None:
            server_conn = self._dial()   # child-side dial (respawn-friendly)
        self.server = server_conn
        if getattr(server_conn, 'sock', None) is not None:
            # a silently-dead server must fail the blocking recv, not hang it
            server_conn.sock.settimeout(self._rpc_timeout)
            if self._hb_interval > 0:
                threading.Thread(target=self._heartbeat_loop,
                                 name='gather-%d-heartbeat' % gather_id,
                                 daemon=True).start()

        n_total = args['worker']['num_parallel']
        n_relays = args['worker']['num_gathers']
        n_here = _shard(n_total, n_relays, gather_id)
        first_wid = args['worker'].get('base_worker_id', 0)

        def worker_args(i, child_conn):
            wid = first_wid + i * n_relays + gather_id
            return (args, child_conn, wid)

        self.hub = Hub(spawn_pipe_workers(n_here, open_worker, worker_args))

        self.block = 1 + n_here // 4          # round-trip amortization factor
        self.SNAP_SLOTS = 4                   # snapshots cached per relay
        self._task_stock: deque = deque()
        # shared with the engine thread's snapshot fetches (graftlint GL004)
        self._snap_cache: OrderedDict = OrderedDict()   # guarded-by: _rpc_lock
        self._upload_box: Dict[str, list] = defaultdict(list)
        self._upload_count = 0
        # the engine thread fetches snapshots through the same server link
        # as the main task loop: RPCs must not interleave on the wire
        self._rpc_lock = threading.RLock()

        self.engine: Optional[EngineSupervisor] = None
        srv = args.get('serving') or {}
        # remote mode engages on an explicit endpoint list OR a fleet
        # resolver (the EngineClient fetches the replica table itself)
        remote_endpoint = srv.get('endpoint') \
            or (srv.get('fleet') or {}).get('resolver')
        if (args.get('inference') or {}).get('enabled') and remote_endpoint:
            # remote-service mode (docs/serving.md): workers dial the
            # standalone InferenceService (or fleet) directly (EngineClient
            # owns the links + replica failover), so this relay spawns no
            # engine of its own — the 'model' RPC path stays available for
            # degraded workers
            _LOG.info('gather %d: inference routed to remote service %s; '
                      'no local engine', gather_id, remote_endpoint)
        elif (args.get('inference') or {}).get('enabled'):
            # per-host batched inference service: this relay alone pulls
            # model snapshots; its workers submit (mid, obs, hidden, legal)
            # frames and receive sampled actions back over the same pipes.
            # The supervisor watchdogs the engine thread (restart on
            # crash/stall, error fan-out so no reply is silently dropped);
            # replies ride the pipe as (INFER_KIND, reply) frames so the
            # worker's client can tell them from task-RPC answers.
            self.engine = EngineSupervisor(
                args, fetch_snapshot=self._snapshot,
                reply_fn=lambda ep, msg: self.hub.send(ep, (INFER_KIND, msg)),
                clients=n_here)

    def __del__(self):
        _LOG.info('finished gather %d', self.gather_id)

    # -- supervised server link --

    def _dial(self):
        return self._reconnect_fn()

    def _heartbeat_loop(self):
        """One-way liveness beacons, sent even while the main loop blocks
        inside a long RPC (e.g. the server is busy at an epoch boundary).
        FramedConnection.send serializes with the RPC path internally.

        Each beacon piggybacks this relay's telemetry: its own registry
        merged with the latest snapshot every worker child sent up its
        pipe, plus a freshly computed episodes/sec gauge — the learner
        aggregates these per-peer payloads into the fleet view."""
        last_n, last_t = 0, time.time()
        while True:
            time.sleep(self._hb_interval)
            now = time.time()
            n = self._m_uploads['episode'].value
            self._m_eps_rate.set((n - last_n) / max(now - last_t, 1e-9))
            last_n, last_t = n, now
            # the beacon thread starts before the worker hub is built; the
            # first beats may carry only the gather's own registry
            hub = getattr(self, 'hub', None)
            worker_snaps = [info.get('telemetry')
                            for info in (hub.peer_info_snapshot().values()
                                         if hub is not None else ())
                            if isinstance(info, dict)]
            # gather processes sample their own memory footprint too: a
            # leaking relay shows up in the fleet merge, not just workers
            telemetry.sample_device_memory()
            snap = telemetry.merge_snapshots(
                [telemetry.snapshot()] + worker_snaps)
            conn = self.server
            try:
                conn.send((HEARTBEAT_KIND,
                           {'gather': self.gather_id, **self.stats,
                            'telemetry': snap}))
            except Exception:
                pass   # the RPC path owns failure handling and reconnect
            telemetry.trace_flush()   # keep the shared trace file current

    def _recover(self, exc: Exception):
        """Redial the data port with exponential backoff + jitter (the
        ``entry()`` retry pattern, hardened)."""
        _LOG.warning('gather %d: server link lost (%s: %s); reconnecting',
                     self.gather_id, type(exc).__name__, str(exc)[:120])
        try:
            self.server.close()
        except Exception:
            pass
        backoff = Backoff(self._backoff_initial, self._backoff_max)
        last_err: Optional[Exception] = exc
        for _ in range(self._max_tries):
            time.sleep(backoff.next_delay())
            try:
                conn = self._dial()
            except OSError as e:
                last_err = e
                continue
            conn.sock.settimeout(self._rpc_timeout)
            if self._resume_token:
                # resume-token handshake (durable learner): prove membership
                # before committing the link. A RESTARTED learner with the
                # same run_id answers ok + its new generation — this gather
                # reattaches in place and its resend buffer replays as
                # ordinary duplicate-screened uploads. A different run_id
                # (or a reply this build cannot read) means the fleet we
                # belonged to is gone: fail hard so the supervisor
                # cold-respawns against the new run.
                try:
                    reply = send_recv(conn, (RESUME_KIND, dict(
                        self._resume_token, gather=self.gather_id)))
                except _CONN_ERRORS as e:
                    last_err = e
                    try:
                        conn.close()
                    except Exception:
                        pass
                    continue
                if not (isinstance(reply, dict) and reply.get('ok')):
                    try:
                        conn.close()
                    except Exception:
                        pass
                    raise ConnectionError(
                        'gather %d: learner rejected the resume token '
                        '(run over or replaced); cold respawn required'
                        % self.gather_id)
                gen = int(reply.get('generation',
                                    self._resume_token.get('generation', 0)))
                if gen != int(self._resume_token.get('generation', 0)):
                    # the learner restarted while we were severed: this
                    # redial is a zero-respawn reattach, not a mere blip
                    self._resume_token['generation'] = gen
                    self.stats['reattaches'] += 1
                    self._m_reattach.inc()
                    _LOG.warning(
                        'gather %d: reattached across a learner restart '
                        '(generation %d)', self.gather_id, gen)
            self.server = conn
            self.stats['reconnects'] += 1
            self._m_reconnects.inc()
            _LOG.warning('gather %d: reconnected to the server',
                         self.gather_id)
            return
        raise ConnectionError(
            'gather %d: could not re-reach the server after %d tries (%s)'
            % (self.gather_id, self._max_tries, last_err))

    def _server_rpc(self, msg):
        """send_recv with supervised reconnect; the in-flight request is
        resent on the fresh link (the server dedupes by task_id, so a
        request whose ack was lost cannot double-count). Serialized: the
        engine thread's snapshot fetches share this link with the main
        task loop, and two interleaved call-response pairs would cross
        their replies."""
        with self._rpc_lock:
            while True:
                try:
                    return send_recv(self.server, msg)
                except _CONN_ERRORS as exc:
                    if self._reconnect_fn is None:  # pipe mode: unrecoverable
                        raise
                    self._m_retries.inc()
                    self._recover(exc)

    # -- per-RPC handling --

    def _next_task(self):
        if not self._task_stock:
            self._task_stock.extend(
                self._server_rpc(('args', [None] * self.block)))
        return self._task_stock.popleft()

    def _snapshot(self, mid):
        """Per-id snapshot LRU: one epoch's params per entry, bounded — the
        epoch counter increments for the life of the run, so an unbounded
        map would leak a params-sized blob per update. Thread-safe: serves
        both worker 'model' RPCs (per-worker mode) and the inference
        engine's fetches (engine mode)."""
        with self._rpc_lock:
            if mid not in self._snap_cache:
                while len(self._snap_cache) >= self.SNAP_SLOTS:
                    self._snap_cache.popitem(last=False)
                self._snap_cache[mid] = self._server_rpc(('model', mid))
            self._snap_cache.move_to_end(mid)
            return self._snap_cache[mid]

    def _stash_upload(self, kind: str, payload):
        self._upload_box[kind].append(payload)
        self._upload_trace.stash(kind, payload)
        self._upload_count += 1
        if kind in self._m_uploads:
            self._m_uploads[kind].inc()
        while self._upload_count > self._resend_max:
            # bounded resend buffer: under a long outage, keep the newest
            # uploads and count the sacrifice instead of growing forever
            biggest = max(self._upload_box, key=lambda k: len(self._upload_box[k]))
            self._upload_box[biggest].pop(0)
            self._upload_count -= 1
            self.stats['dropped_uploads'] += 1
            self._m_dropped.inc()
            self._m_resend_dropped.inc()
            if self.stats['dropped_uploads'] == 1 \
                    or self.stats['dropped_uploads'] % 50 == 0:
                # loud, throttled: evicted uploads are PERMANENT episode
                # loss — the alert catalog watches the counter, this line
                # lands in the FlightRecorder ring for the post-mortem
                _LOG.warning(
                    'gather %d: resend buffer full (%d); dropped a %r '
                    'upload (%d dropped so far) — raise '
                    'fault_tolerance.resend_buffer or shorten outages',
                    self.gather_id, self._resend_max, biggest,
                    self.stats['dropped_uploads'])
        if self._upload_count >= self.block:
            for kind in list(self._upload_box):
                self._server_rpc((kind, self._upload_box[kind]))
                # acked: this kind's batch is safely booked server-side
                del self._upload_box[kind]
                self._upload_trace.shipped(kind)
            self._upload_count = sum(len(v) for v in self._upload_box.values())
        self._m_box_depth.set(self._upload_count)

    def run(self):
        while self.hub.count() > 0:
            try:
                ep, (kind, body) = self.hub.recv(timeout=0.3)
            except queue.Empty:
                continue
            if kind == 'args':
                self.hub.send(ep, self._next_task())
            elif kind == 'model':
                self.hub.send(ep, self._snapshot(body))
            elif kind == INFER_KIND:
                if self.engine is None:
                    self.hub.send(ep, (INFER_KIND,
                                       {'rid': (body or {}).get('rid'),
                                        'engine_fault': True,
                                        'error': 'inference engine disabled '
                                                 'on this host'}))
                else:
                    self.engine.submit(ep, body)
            else:
                self.hub.send(ep, None)       # ack now, ship in bulk later
                self._stash_upload(kind, body)
        self._flush_and_beacon()

    def _flush_and_beacon(self):
        """End of the relay's life (training over): ship the final partial
        upload block — it would otherwise die in the box — and beacon a
        last telemetry snapshot so the learner's fleet view includes
        this relay's complete engine/upload counters. The loop covers
        every stashed kind, streamed ``'chunk'`` windows included, so a
        budgeted run's tail chunks land instead of stranding mid-episode
        assemblies server-side."""
        for kind in list(self._upload_box):
            if self._upload_box[kind]:
                self._server_rpc((kind, self._upload_box[kind]))
                self._upload_trace.shipped(kind)
            del self._upload_box[kind]
        if self.engine is not None:
            self.engine.stop()
        try:
            self.server.send((HEARTBEAT_KIND,
                              {'gather': self.gather_id, **self.stats,
                               'telemetry': telemetry.snapshot()}))
        except Exception:
            pass   # the run is over; a dead link changes nothing


def resolve_generation_backend(args: Dict[str, Any]) -> str:
    """Which actor engine a gather host runs: 'worker' (per-worker
    inference), 'engine' (per-host InferenceEngine), or 'device' (fused
    on-device rollouts, DeviceActorGather). A per-host override
    (``worker_args.backend``, riding the entry handshake) wins over the
    training config's ``generation.backend``; with neither set, the
    presence of the inference block picks engine vs worker — exactly the
    pre-backend-knob behavior."""
    backend = str((args.get('worker') or {}).get('backend') or ''
                  ) or str((args.get('generation') or {}).get('backend')
                           or '')
    if not backend:
        backend = ('engine' if (args.get('inference') or {}).get('enabled')
                   else 'worker')
    return backend


class DeviceActorGather(Gather):
    """A gather whose 'workers' are lanes of one fused device rollout.

    Reuses ALL of Gather's learner-side plumbing — the supervised server
    RPC with reconnect, the task-block prefetch, the snapshot LRU, the
    batched upload box with resend bounds, heartbeats — by initializing the
    parent with zero worker children and no inference engine. The run loop
    then pulls task blocks through ``_next_task`` and serves them with a
    :class:`~.device_generation.DeviceActorEngine`; tasks the compiled
    program cannot express fall back to a host Generator/Evaluator pair in
    this same process, so every assigned task is answered either way."""

    def __init__(self, args: Dict[str, Any], server_conn, gather_id: int,
                 reconnect=None):
        from .device_generation import DeviceActorEngine
        from .environment import make_jax_env
        doctored = dict(args)
        doctored['worker'] = dict(args['worker'], num_parallel=0)
        doctored['inference'] = dict(args.get('inference') or {},
                                     enabled=False)
        super().__init__(doctored, server_conn, gather_id,
                         reconnect=reconnect)
        gen = dict(args.get('generation') or {})
        n_envs = int(gen.get('device_actor_envs', 64))
        slots = int(gen.get('device_actor_slots', 2))
        self.block = max(1, n_envs // 4)      # task-prefetch granularity
        self.host_env = make_env(args['env'])
        self.host_env.reset()
        example_obs = self.host_env.observation(self.host_env.players()[0])
        self.vault = ModelVault(self._snapshot, example_obs,
                                capacity=slots + 2)
        self.device_engine = DeviceActorEngine(
            make_jax_env(args['env']), self.vault, self.host_env, args,
            n_envs=n_envs,
            chunk_steps=int(gen.get('device_actor_chunk_steps', 16)),
            slots=slots,
            record_mode=str(gen.get('device_actor_record', '') or ''),
            seed=int(args.get('seed', 0)) * 1009 + gather_id)
        if (args.get('streaming') or {}).get('enabled'):
            # streamed windows ride the same upload box as whole episodes
            # (resend buffer, reconnect replay and the clean-exit flush
            # all cover the 'chunk' kind)
            self.device_engine.emit = \
                lambda c: self._stash_upload('chunk', c)
        self._fallback_gen = Generator(self.host_env, args,
                                       namespace=gather_id)
        self._fallback_eval = Evaluator(self.host_env, args)
        self._m_deferred = telemetry.counter('device_actor_deferred_total')
        _LOG.info('gather %d: device actor backend (%d lanes, %d slots, '
                  '%s records)', gather_id, n_envs, slots,
                  self.device_engine.record_mode)

    def _collect_block(self):
        """Pull up to one lane-count of tasks; returns (tasks, stop)."""
        tasks = []
        while len(tasks) < self.device_engine.n_envs:
            task = self._next_task()
            if task is None:
                return tasks, True
            if task.get('role') == 'idle':
                if tasks:
                    return tasks, False   # serve the partial block now
                telemetry.counter('worker_idle_tasks_total').inc()
                time.sleep(min(5.0, float(task.get('wait', 1.0))))
                continue
            tasks.append(task)
        return tasks, False

    def _run_host(self, task):
        """Host fallback for a task the device program cannot express
        (unknown opponent, slot overflow, missing sample key). Same
        payload contract as a worker process; a crash costs one task."""
        self._m_deferred.inc()
        kind = 'result' if task.get('role') == 'e' else 'episode'
        try:
            models = self.vault.obtain(dict(task.get('model_id', {})))
            with telemetry.expected_compile('device-actor host fallback'):
                if task.get('role') == 'e':
                    payload = self._fallback_eval.execute(models, task)
                else:
                    payload = self._fallback_gen.execute(models, task)
        except Exception:
            traceback.print_exc()
            payload = None
            telemetry.counter('worker_task_failures_total').inc()
        self._stash_upload(kind, payload)

    def run(self):
        while True:
            tasks, stop = self._collect_block()
            if tasks:
                uploads, deferred = self.device_engine.run_block(tasks)
                for kind, payload in uploads:
                    self._stash_upload(kind, payload)
                for task in deferred:
                    self._run_host(task)
            if stop:
                break
        self._flush_and_beacon()


def gather_loop(args, conn, gather_id, server_address=None):
    from .environment import make_jax_env
    backend = resolve_generation_backend(args)
    inf = args.get('inference') or {}
    if (backend == 'device'
            or (inf.get('enabled')
                and str(inf.get('engine_backend', 'cpu')) == 'device')):
        # the rollout/inference engine is the ONE process on this host
        # allowed to claim a local accelerator (hosts without one fall back
        # to jax's default); workers stay CPU-pinned either way
        from . import setup_compile_cache
        setup_compile_cache()
    else:
        force_cpu_backend()
    reconnect = None
    if server_address:
        def reconnect():
            return connect_socket_connection(server_address,
                                             WorkerServer.WORKER_PORT)
    if backend == 'device':
        if make_jax_env(args['env']) is not None:
            DeviceActorGather(args, conn, gather_id,
                              reconnect=reconnect).run()
            return
        _LOG.warning(
            'gather %d: generation backend "device" requested but env %r '
            'has no pure-JAX twin; falling back to the host path',
            gather_id, (args.get('env') or {}).get('env'))
    if backend == 'worker' and inf.get('enabled'):
        # per-host override demoted this gather to plain workers: they
        # must materialize their own params instead of dialing an engine
        args = dict(args, inference=dict(inf, enabled=False))
    elif backend == 'engine' and not inf.get('enabled'):
        args = dict(args, inference=dict(inf, enabled=True))
    Gather(args, conn, gather_id, reconnect=reconnect).run()


def default_num_gathers(num_parallel: int) -> int:
    return 1 + max(0, num_parallel - 1) // 16


class WorkerCluster:
    """Local mode: gather processes over spawned pipes, one hub in the
    learner. ``recv``/``send``/``connection_count`` delegate to the hub —
    the learner's server loop is transport-agnostic."""

    def __init__(self, args: Dict[str, Any]):
        self.args = args
        self.hub = Hub()
        ft = args.get('fault_tolerance') or {}
        self.hub.LIVENESS_TIMEOUT = float(
            ft.get('liveness_timeout', Hub.LIVENESS_TIMEOUT))

    def connection_count(self) -> int:
        return self.hub.count()

    def recv(self, timeout: Optional[float] = None):
        return self.hub.recv(timeout=timeout)

    def send(self, conn, data):
        self.hub.send(conn, data)

    # fleet observability, consumed by the learner's ledger + epoch stats
    def hub_stats(self) -> Dict[str, int]:
        return self.hub.stats_snapshot()

    def peer_info(self) -> Dict[Any, Any]:
        return self.hub.peer_info_snapshot()

    def drain_detach_events(self):
        return self.hub.drain_detach_events()

    def run(self):
        wargs = self.args['worker']
        wargs.setdefault('num_gathers',
                         default_num_gathers(wargs['num_parallel']))
        for ep in spawn_pipe_workers(
                wargs['num_gathers'], gather_loop,
                lambda i, c: (self.args, c, i)):
            self.hub.attach(ep)


class WorkerServer(WorkerCluster):
    """Remote mode, learner side. Two listener threads: the entry port
    hands each arriving host its base_worker_id plus the merged config;
    the data port feeds accepted sockets straight into the hub. Hosts may
    join or leave at any time, mid-training."""

    ENTRY_PORT = ENTRY_PORT
    WORKER_PORT = DATA_PORT

    def __init__(self, args: Dict[str, Any]):
        super().__init__(args)
        self._next_base_wid = 0

    def _entry_loop(self):
        _LOG.info('started entry server %d', self.ENTRY_PORT)
        for conn in accept_socket_connections(port=self.ENTRY_PORT):
            host_args = conn.recv()
            _LOG.info('accepted connection from %s!', host_args['address'])
            host_args['base_worker_id'] = self._next_base_wid
            self._next_base_wid += host_args['num_parallel']
            merged = dict(self.args)
            merged['worker'] = host_args
            conn.send(merged)
            conn.close()

    def _data_loop(self):
        _LOG.info('started worker server %d', self.WORKER_PORT)
        for conn in accept_socket_connections(port=self.WORKER_PORT):
            self.hub.attach(conn)

    def run(self):
        for loop in (self._entry_loop, self._data_loop):
            threading.Thread(target=loop, name=loop.__name__.strip('_'),
                             daemon=True).start()


def entry(worker_args, retries: int = 30, delay: float = 2.0):
    """Entry handshake with retry: the learner may still be starting (jax
    import + bind) when a worker host comes up. Retries back off with
    jitter so a whole fleet booting at once does not hammer in lockstep."""
    last_err: Optional[Exception] = None
    port = WorkerServer.ENTRY_PORT
    backoff = Backoff(delay, maximum=4 * delay)
    for _ in range(retries):
        try:
            conn = connect_socket_connection(
                worker_args['server_address'], port)
            try:
                conn.send(worker_args)
                return conn.recv()
            finally:
                conn.close()
        except (OSError, ConnectionResetError) as e:
            last_err = e
            time.sleep(backoff.next_delay())
    raise ConnectionError('could not reach training server at %s:%d (%s)'
                          % (worker_args['server_address'], port, last_err))


class RemoteWorkerCluster:
    """Remote mode, worker-host side: entry handshake, then one data socket
    per gather, each driven by its own spawned process — plus a supervisor
    that respawns crashed gathers (with per-slot backoff) instead of
    sleeping forever next to a shrinking fleet. A gather that exits cleanly
    (exit code 0: the server handed out a None task, training is over) is
    retired, so the host process itself terminates when the run ends.

    ``HANDYRL_TPU_CHAOS=kill_gather=<mean s>[,max_kills=N][,seed=S]`` arms
    a fault injector that SIGKILLs random gather children on an exponential
    clock — the chaos tests (and soak runs) prove the supervisor + task
    ledger recover."""

    RESPAWN_RESET_AFTER = 60.0   # gather alive this long => backoff resets

    def __init__(self, args: Dict[str, Any]):
        args['address'] = gethostname()
        args.setdefault('num_gathers',
                        default_num_gathers(args['num_parallel']))
        self.args = args

    def run(self):
        merged = entry(self.args)
        telemetry.adopt_config(merged)
        telemetry.set_process_label('worker-host')
        telemetry.install_crash_dump()
        _LOG.info('joined run %s as %s (base_worker_id %s, %s gathers)',
                  merged.get('run_id', '?'), self.args['address'],
                  merged['worker'].get('base_worker_id'),
                  self.args['num_gathers'])
        _LOG.debug('merged config: %r', merged)
        prepare_env(merged['env'])

        ctx = mp.get_context('spawn')
        address = self.args['server_address']
        ft = merged.get('fault_tolerance') or {}
        max_fails = int(ft.get('reconnect_max_tries', 30))

        chaos = parse_chaos()
        rng = random.Random(int(chaos.get('seed', 0)))
        kills_left = int(chaos.get('max_kills', 1 << 30))
        next_kill = None
        if chaos.get('kill_gather'):
            next_kill = time.time() + rng.expovariate(
                1.0 / chaos['kill_gather'])

        def spawn(i):
            # the gather dials the data port itself: respawns need no
            # parent-held socket, and a half-dead link is its own problem
            proc = ctx.Process(target=gather_loop,
                               args=(merged, None, i, address))
            proc.start()
            return proc

        n = self.args['num_gathers']
        children = {i: spawn(i) for i in range(n)}
        started_at = {i: time.time() for i in children}
        backoffs = {i: Backoff(float(ft.get('reconnect_initial_delay', 1.0)),
                               float(ft.get('reconnect_max_delay', 30.0)))
                    for i in children}
        fails = {i: 0 for i in children}
        try:
            while children:
                time.sleep(0.5)
                now = time.time()
                if next_kill is not None and now >= next_kill:
                    if kills_left > 0:
                        live = [i for i, p in children.items() if p.is_alive()]
                        if live:
                            victim = rng.choice(live)
                            print('chaos: killing gather %d' % victim,
                                  flush=True)
                            children[victim].kill()
                            kills_left -= 1
                    next_kill = now + rng.expovariate(
                        1.0 / chaos['kill_gather'])
                for i, proc in list(children.items()):
                    if proc.is_alive():
                        if (fails[i] and
                                now - started_at[i] > self.RESPAWN_RESET_AFTER):
                            fails[i] = 0
                            backoffs[i].reset()
                        continue
                    if proc.exitcode == 0:
                        del children[i]   # clean exit: training ended
                        continue
                    fails[i] += 1
                    if fails[i] > max_fails:
                        # likely the server is gone for good — stop churning
                        _LOG.error('gather %d: giving up after %d failed '
                                   'respawns', i, fails[i] - 1)
                        del children[i]
                        continue
                    delay = backoffs[i].next_delay()
                    _LOG.warning('gather %d died (exit %s); respawning '
                                 'in %.1fs', i, proc.exitcode, delay)
                    # supervisor death declaration: the gather itself had
                    # no chance to dump (SIGKILL), so the host supervisor
                    # records the evidence for the postmortem
                    telemetry.record_event(
                        'supervisor', 'gather %d died' % i,
                        exitcode=proc.exitcode, respawn_in=round(delay, 2))
                    telemetry.dump_blackbox('gather-death', gather=i,
                                            exitcode=proc.exitcode)
                    time.sleep(delay)
                    children[i] = spawn(i)
                    started_at[i] = time.time()
        finally:
            for proc in children.values():
                if proc.is_alive():
                    proc.terminate()


def worker_main(args, argv):
    force_cpu_backend()   # worker hosts are CPU actors by design
    worker_args = args['worker_args']
    if len(argv) >= 1:
        worker_args['num_parallel'] = int(argv[0])
    RemoteWorkerCluster(args=worker_args).run()
