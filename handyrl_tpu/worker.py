"""Actor-side process tree: episode workers, relay proxies, cluster fronts.

Round-2 redesign of the actor plumbing. The wire protocol is unchanged —
the four RPCs (``args`` / ``episode`` / ``result`` / ``model``), the entry
handshake on port 9999 (base_worker_id assignment + merged config), and the
data connections on port 9998 all match the reference topology
(reference worker.py:26-254) — but the machinery is built differently:

* every multiplexing component composes a :class:`~.connection.Hub`
  (single selector event loop) instead of subclassing a thread-pair
  communicator;
* workers cache model *snapshots per model id* in a small LRU vault and
  materialize wrappers per id — two ids of the same architecture can never
  alias one set of live params (a league/past-epoch opponent setup works);
* the 'model' RPC ships an architecture-name + msgpack-params snapshot
  (model.ModelWrapper.snapshot), never pickled code, and socket frames are
  msgpack data — nothing on the public ports can execute on decode.
"""

from __future__ import annotations

import multiprocessing as mp
import queue
import random
import threading
import time
from collections import OrderedDict, defaultdict, deque
from socket import gethostname
from typing import Any, Dict, Optional

from .connection import (Hub, accept_socket_connections,
                         connect_socket_connection, force_cpu_backend,
                         send_recv, spawn_pipe_workers)
from .environment import make_env, prepare_env
from .evaluation import Evaluator
from .generation import Generator
from .model import ModelWrapper, RandomModel

ENTRY_PORT = 9999
DATA_PORT = 9998


class ModelVault:
    """Small LRU of materialized models keyed by model id.

    ``fetch(model_id)`` pulls a snapshot over the RPC connection on miss.
    Each cached id owns its wrapper (sharing only the per-architecture jit
    cache inside ModelWrapper), so distinct ids never share live params.
    Id 0 denotes the untrained epoch-0 net and is served as a RandomModel —
    a deliberate, documented divergence (see PARITY.md): its uniform play
    matches the sampler's selected_prob, keeping training math identical.
    """

    def __init__(self, fetch, example_obs, capacity: int = 3):
        self._fetch = fetch
        self._example_obs = example_obs
        self._capacity = capacity
        self._slots: OrderedDict = OrderedDict()
        self._templates: Dict[str, Any] = {}   # arch -> params pytree

    def obtain(self, wanted: Dict[Any, Optional[int]]) -> Dict[Any, Any]:
        """Return player -> model for every requested id (None/negative ->
        no model: the server assigns those seats to built-in opponents)."""
        out = {}
        for player, mid in wanted.items():
            if mid is None or mid < 0:
                out[player] = None
                continue
            if mid not in self._slots:
                self._admit(mid)
            self._slots.move_to_end(mid)
            out[player] = self._slots[mid]
        return out

    def _admit(self, mid: int):
        snap = self._fetch(mid)
        # template key includes the wire config: the same architecture with
        # a different param-tree-shaping knob (e.g. GeisterNet norm_kind)
        # must not reuse a structurally different template
        key = (snap['architecture'], tuple(sorted(snap.get('config', {}).items())))
        wrapper = ModelWrapper.from_snapshot(
            snap, self._example_obs,
            params_template=self._templates.get(key))
        self._templates.setdefault(key, wrapper.params)
        model = RandomModel(wrapper, self._example_obs) if mid == 0 else wrapper
        while len(self._slots) >= self._capacity:
            self._slots.popitem(last=False)
        self._slots[mid] = model


class Worker:
    """One actor process: loops task requests over the 4-RPC protocol and
    plays out generation ('g') or evaluation ('e') assignments."""

    def __init__(self, args: Dict[str, Any], conn, wid: int):
        print('opened worker %d' % wid)
        self.worker_id = wid
        self.conn = conn
        self.env = make_env({**args['env'], 'id': wid})
        random.seed(args['seed'] + wid)

        self.env.reset()
        example_obs = self.env.observation(self.env.players()[0])
        self.vault = ModelVault(
            lambda mid: send_recv(conn, ('model', mid)), example_obs)

        generator = Generator(self.env, args)
        evaluator = Evaluator(self.env, args)
        # role -> (episode producer, upload RPC name)
        self.playbook = {'g': (generator.execute, 'episode'),
                         'e': (evaluator.execute, 'result')}

    def __del__(self):
        print('closed worker %d' % self.worker_id)

    def run(self):
        while True:
            task = send_recv(self.conn, ('args', None))
            if task is None:
                break
            produce, upload_as = self.playbook[task['role']]
            models = self.vault.obtain(dict(task.get('model_id', {})))
            send_recv(self.conn, (upload_as, produce(models, task)))


def open_worker(args, conn, wid):
    force_cpu_backend()
    Worker(args, conn, wid).run()


def _shard(total: int, parts: int, index: int) -> int:
    """Size of shard ``index`` when ``total`` items split across ``parts``."""
    return total // parts + (1 if index < total % parts else 0)


class Gather:
    """Fan-in relay between ~16 workers and the learner.

    Amortizes server round-trips three ways: task assignments are prefetched
    in blocks, model snapshots are served from a per-id cache, and episode /
    result uploads are batched before shipping. State lives in three small
    stores; routing is a dispatch over the RPC kind.
    """

    def __init__(self, args: Dict[str, Any], server_conn, gather_id: int):
        print('started gather %d' % gather_id)
        self.gather_id = gather_id
        self.server = server_conn

        n_total = args['worker']['num_parallel']
        n_relays = args['worker']['num_gathers']
        n_here = _shard(n_total, n_relays, gather_id)
        first_wid = args['worker'].get('base_worker_id', 0)

        def worker_args(i, child_conn):
            wid = first_wid + i * n_relays + gather_id
            return (args, child_conn, wid)

        self.hub = Hub(spawn_pipe_workers(n_here, open_worker, worker_args))

        self.block = 1 + n_here // 4          # round-trip amortization factor
        self.SNAP_SLOTS = 4                   # snapshots cached per relay
        self._task_stock: deque = deque()
        self._snap_cache: OrderedDict = OrderedDict()
        self._upload_box: Dict[str, list] = defaultdict(list)
        self._upload_count = 0

    def __del__(self):
        print('finished gather %d' % self.gather_id)

    # -- per-RPC handling --

    def _next_task(self):
        if not self._task_stock:
            self._task_stock.extend(
                send_recv(self.server, ('args', [None] * self.block)))
        return self._task_stock.popleft()

    def _snapshot(self, mid):
        """Per-id snapshot LRU: one epoch's params per entry, bounded — the
        epoch counter increments for the life of the run, so an unbounded
        map would leak a params-sized blob per update."""
        if mid not in self._snap_cache:
            while len(self._snap_cache) >= self.SNAP_SLOTS:
                self._snap_cache.popitem(last=False)
            self._snap_cache[mid] = send_recv(self.server, ('model', mid))
        self._snap_cache.move_to_end(mid)
        return self._snap_cache[mid]

    def _stash_upload(self, kind: str, payload):
        self._upload_box[kind].append(payload)
        self._upload_count += 1
        if self._upload_count >= self.block:
            for kind, batch in self._upload_box.items():
                send_recv(self.server, (kind, batch))
            self._upload_box.clear()
            self._upload_count = 0

    def run(self):
        while self.hub.count() > 0:
            try:
                ep, (kind, body) = self.hub.recv(timeout=0.3)
            except queue.Empty:
                continue
            if kind == 'args':
                self.hub.send(ep, self._next_task())
            elif kind == 'model':
                self.hub.send(ep, self._snapshot(body))
            else:
                self.hub.send(ep, None)       # ack now, ship in bulk later
                self._stash_upload(kind, body)


def gather_loop(args, conn, gather_id):
    force_cpu_backend()
    Gather(args, conn, gather_id).run()


def default_num_gathers(num_parallel: int) -> int:
    return 1 + max(0, num_parallel - 1) // 16


class WorkerCluster:
    """Local mode: gather processes over spawned pipes, one hub in the
    learner. ``recv``/``send``/``connection_count`` delegate to the hub —
    the learner's server loop is transport-agnostic."""

    def __init__(self, args: Dict[str, Any]):
        self.args = args
        self.hub = Hub()

    def connection_count(self) -> int:
        return self.hub.count()

    def recv(self, timeout: Optional[float] = None):
        return self.hub.recv(timeout=timeout)

    def send(self, conn, data):
        self.hub.send(conn, data)

    def run(self):
        wargs = self.args['worker']
        wargs.setdefault('num_gathers',
                         default_num_gathers(wargs['num_parallel']))
        for ep in spawn_pipe_workers(
                wargs['num_gathers'], gather_loop,
                lambda i, c: (self.args, c, i)):
            self.hub.attach(ep)


class WorkerServer(WorkerCluster):
    """Remote mode, learner side. Two listener threads: the entry port
    hands each arriving host its base_worker_id plus the merged config;
    the data port feeds accepted sockets straight into the hub. Hosts may
    join or leave at any time, mid-training."""

    ENTRY_PORT = ENTRY_PORT
    WORKER_PORT = DATA_PORT

    def __init__(self, args: Dict[str, Any]):
        super().__init__(args)
        self._next_base_wid = 0

    def _entry_loop(self):
        print('started entry server %d' % self.ENTRY_PORT)
        for conn in accept_socket_connections(port=self.ENTRY_PORT):
            host_args = conn.recv()
            print('accepted connection from %s!' % host_args['address'])
            host_args['base_worker_id'] = self._next_base_wid
            self._next_base_wid += host_args['num_parallel']
            merged = dict(self.args)
            merged['worker'] = host_args
            conn.send(merged)
            conn.close()

    def _data_loop(self):
        print('started worker server %d' % self.WORKER_PORT)
        for conn in accept_socket_connections(port=self.WORKER_PORT):
            self.hub.attach(conn)

    def run(self):
        for loop in (self._entry_loop, self._data_loop):
            threading.Thread(target=loop, daemon=True).start()


def entry(worker_args, retries: int = 30, delay: float = 2.0):
    """Entry handshake with retry: the learner may still be starting (jax
    import + bind) when a worker host comes up."""
    last_err: Optional[Exception] = None
    port = WorkerServer.ENTRY_PORT
    for _ in range(retries):
        try:
            conn = connect_socket_connection(
                worker_args['server_address'], port)
            try:
                conn.send(worker_args)
                return conn.recv()
            finally:
                conn.close()
        except (OSError, ConnectionResetError) as e:
            last_err = e
            time.sleep(delay)
    raise ConnectionError('could not reach training server at %s:%d (%s)'
                          % (worker_args['server_address'], port, last_err))


class RemoteWorkerCluster:
    """Remote mode, worker-host side: entry handshake, then one data socket
    per gather, each driven by its own spawned process."""

    def __init__(self, args: Dict[str, Any]):
        args['address'] = gethostname()
        args.setdefault('num_gathers',
                        default_num_gathers(args['num_parallel']))
        self.args = args

    def run(self):
        merged = entry(self.args)
        print(merged)
        prepare_env(merged['env'])

        ctx = mp.get_context('spawn')
        children = []
        try:
            for i in range(self.args['num_gathers']):
                sock = connect_socket_connection(
                    self.args['server_address'], WorkerServer.WORKER_PORT)
                proc = ctx.Process(target=gather_loop,
                                   args=(merged, sock, i))
                proc.start()
                sock.close()
                children.append(proc)
            while True:
                time.sleep(100)
        finally:
            for proc in children:
                proc.terminate()


def worker_main(args, argv):
    force_cpu_backend()   # worker hosts are CPU actors by design
    worker_args = args['worker_args']
    if len(argv) >= 1:
        worker_args['num_parallel'] = int(argv[0])
    RemoteWorkerCluster(args=worker_args).run()
