"""Worker processes, gather fan-in proxies, and cluster front-ends.

Topology parity with the reference (worker.py): learner -> gathers (one per
~16 workers, amortizing RPCs via request prefetch, model caching, and result
batching) -> workers running Generator/Evaluator episodes. Local mode forks
processes over mp.Pipe; remote mode connects over TCP with an entry
handshake on port 9999 (base_worker_id assignment + merged config) and data
connections on port 9998.

Differences from the reference: the 'model' RPC answers with an
architecture-name + msgpack-params snapshot (model.ModelWrapper.snapshot)
instead of a pickled nn.Module (reference worker.py:46-47) — a worker can
reconstruct the model without trusting the wire to carry code.
"""

from __future__ import annotations

import copy
import functools
import multiprocessing as mp
import queue
import random
import threading
import time
from collections import deque
from socket import gethostname
from typing import Any, Dict

from .connection import (QueueCommunicator, accept_socket_connections,
                         connect_socket_connection,
                         open_multiprocessing_connections, send_recv)
from .environment import make_env, prepare_env
from .evaluation import Evaluator
from .generation import Generator
from .model import ModelWrapper, RandomModel


class Worker:
    """Episode/evaluation executor: request loop over the 4-RPC protocol."""

    def __init__(self, args: Dict[str, Any], conn, wid: int):
        print('opened worker %d' % wid)
        self.worker_id = wid
        self.args = args
        self.conn = conn
        self.model_pool: Dict[int, Any] = {}
        self._arch_wrappers: Dict[str, ModelWrapper] = {}

        self.env = make_env({**args['env'], 'id': wid})
        self.generator = Generator(self.env, self.args)
        self.evaluator = Evaluator(self.env, self.args)

        random.seed(args['seed'] + wid)

    def __del__(self):
        print('closed worker %d' % self.worker_id)

    def _example_obs(self):
        self.env.reset()
        return self.env.observation(self.env.players()[0])

    def _gather_models(self, model_ids):
        for model_id in model_ids:
            if model_id is None or model_id < 0 or model_id in self.model_pool:
                continue
            snap = send_recv(self.conn, ('model', model_id))
            # reuse one wrapper per architecture: loading new params into it
            # keeps the compiled apply and the param template across epochs
            arch = snap['architecture']
            wrapper = self._arch_wrappers.get(arch)
            if wrapper is None:
                wrapper = ModelWrapper.from_snapshot(snap, self._example_obs())
                self._arch_wrappers[arch] = wrapper
            else:
                wrapper.load_params_bytes(snap['params'], self._example_obs())
            model = wrapper
            if model_id == 0:
                # epoch 0 means an untrained net: play uniformly at random
                model = RandomModel(wrapper, self._example_obs())
            # single-slot cache: evict the oldest entry
            if len(self.model_pool) >= 1:
                self.model_pool.pop(next(iter(self.model_pool)))
            self.model_pool[model_id] = model

    def run(self):
        while True:
            role_args = send_recv(self.conn, ('args', None))
            if role_args is None:
                break
            role = role_args['role']

            models = {}
            if 'model_id' in role_args:
                self._gather_models(list(role_args['model_id'].values()))
                for p, model_id in role_args['model_id'].items():
                    models[p] = self.model_pool.get(model_id, None)

            if role == 'g':
                episode = self.generator.execute(models, role_args)
                send_recv(self.conn, ('episode', episode))
            elif role == 'e':
                result = self.evaluator.execute(models, role_args)
                send_recv(self.conn, ('result', result))


def _worker_args(args, n_gathers, gather_id, base_wid, wid, conn):
    return args, conn, base_wid + wid * n_gathers + gather_id


def open_worker(args, conn, wid):
    from .connection import force_cpu_backend
    force_cpu_backend()
    worker = Worker(args, conn, wid)
    worker.run()


class Gather(QueueCommunicator):
    """Fan-in proxy for ~16 workers: prefetches 'args' from the server in
    bulk, caches 'model' responses by id, and flushes episodes/results in
    batches (reference worker.py:92-161)."""

    def __init__(self, args: Dict[str, Any], conn, gather_id: int):
        print('started gather %d' % gather_id)
        super().__init__()
        self.gather_id = gather_id
        self.server_conn = conn
        self.args_queue: deque = deque()
        self.data_map: Dict[str, dict] = {'model': {}}
        self.result_send_map: Dict[str, list] = {}
        self.result_send_cnt = 0

        n_pro = args['worker']['num_parallel']
        n_ga = args['worker']['num_gathers']
        num_workers_here = (n_pro // n_ga) + int(gather_id < n_pro % n_ga)
        base_wid = args['worker'].get('base_worker_id', 0)

        worker_conns = open_multiprocessing_connections(
            num_workers_here, open_worker,
            functools.partial(_worker_args, args, n_ga, gather_id, base_wid))
        for wconn in worker_conns:
            self.add_connection(wconn)

        self.buffer_length = 1 + len(worker_conns) // 4

    def __del__(self):
        print('finished gather %d' % self.gather_id)

    def run(self):
        while self.connection_count() > 0:
            try:
                conn, (command, args) = self.recv(timeout=0.3)
            except queue.Empty:
                continue

            if command == 'args':
                if len(self.args_queue) == 0:
                    self.server_conn.send((command, [None] * self.buffer_length))
                    self.args_queue += self.server_conn.recv()
                self.send(conn, self.args_queue.popleft())

            elif command in self.data_map:
                data_id = args
                if data_id not in self.data_map[command]:
                    self.server_conn.send((command, args))
                    self.data_map[command][data_id] = self.server_conn.recv()
                self.send(conn, self.data_map[command][data_id])

            else:
                # ack immediately, ship to the server in bulk later
                self.send(conn, None)
                self.result_send_map.setdefault(command, []).append(args)
                self.result_send_cnt += 1
                if self.result_send_cnt >= self.buffer_length:
                    for cmd, args_list in self.result_send_map.items():
                        self.server_conn.send((cmd, args_list))
                        self.server_conn.recv()
                    self.result_send_map = {}
                    self.result_send_cnt = 0


def gather_loop(args, conn, gather_id):
    from .connection import force_cpu_backend
    force_cpu_backend()
    gather = Gather(args, conn, gather_id)
    gather.run()


def default_num_gathers(num_parallel: int) -> int:
    return 1 + max(0, num_parallel - 1) // 16


class WorkerCluster(QueueCommunicator):
    """Local mode: fork gather processes connected by mp.Pipe."""

    def __init__(self, args: Dict[str, Any]):
        super().__init__()
        self.args = args

    def run(self):
        if 'num_gathers' not in self.args['worker']:
            self.args['worker']['num_gathers'] = \
                default_num_gathers(self.args['worker']['num_parallel'])
        ctx = mp.get_context('spawn')   # never fork a TPU-holding learner
        for i in range(self.args['worker']['num_gathers']):
            conn0, conn1 = ctx.Pipe(duplex=True)
            ctx.Process(target=gather_loop, args=(self.args, conn1, i)).start()
            conn1.close()
            self.add_connection(conn0)


class WorkerServer(QueueCommunicator):
    """Remote mode, learner side: entry handshake on :9999 (assigns
    base_worker_id, returns merged config), worker data conns on :9998.
    Workers may join or leave at any time."""

    ENTRY_PORT = 9999
    WORKER_PORT = 9998

    def __init__(self, args: Dict[str, Any]):
        super().__init__()
        self.args = args
        self.total_worker_count = 0

    def run(self):
        def entry_server(port):
            print('started entry server %d' % port)
            for conn in accept_socket_connections(port=port):
                worker_args = conn.recv()
                print('accepted connection from %s!' % worker_args['address'])
                worker_args['base_worker_id'] = self.total_worker_count
                self.total_worker_count += worker_args['num_parallel']
                args = copy.deepcopy(self.args)
                args['worker'] = worker_args
                conn.send(args)
                conn.close()

        def worker_server(port):
            print('started worker server %d' % port)
            for conn in accept_socket_connections(port=port):
                self.add_connection(conn)

        threading.Thread(target=entry_server, args=(self.ENTRY_PORT,),
                         daemon=True).start()
        threading.Thread(target=worker_server, args=(self.WORKER_PORT,),
                         daemon=True).start()


def entry(worker_args, retries: int = 30, delay: float = 2.0):
    """Entry handshake with retry: the learner may still be starting (jax
    import + bind) when a worker host comes up."""
    last_err = None
    for _ in range(retries):
        try:
            conn = connect_socket_connection(worker_args['server_address'],
                                             WorkerServer.ENTRY_PORT)
            conn.send(worker_args)
            args = conn.recv()
            conn.close()
            return args
        except (OSError, ConnectionResetError) as e:
            last_err = e
            time.sleep(delay)
    raise ConnectionError('could not reach training server at %s:%d (%s)'
                          % (worker_args['server_address'],
                             WorkerServer.ENTRY_PORT, last_err))


class RemoteWorkerCluster:
    """Remote mode, worker-host side: entry handshake then one socket per
    gather."""

    def __init__(self, args: Dict[str, Any]):
        args['address'] = gethostname()
        if 'num_gathers' not in args:
            args['num_gathers'] = default_num_gathers(args['num_parallel'])
        self.args = args

    def run(self):
        args = entry(self.args)
        print(args)
        prepare_env(args['env'])

        processes = []
        ctx = mp.get_context('spawn')
        try:
            for i in range(self.args['num_gathers']):
                conn = connect_socket_connection(self.args['server_address'],
                                                 WorkerServer.WORKER_PORT)
                p = ctx.Process(target=gather_loop, args=(args, conn, i))
                p.start()
                conn.close()
                processes.append(p)
            while True:
                time.sleep(100)
        finally:
            for p in processes:
                p.terminate()


def worker_main(args, argv):
    from .connection import force_cpu_backend
    force_cpu_backend()   # worker hosts are CPU actors by design
    worker_args = args['worker_args']
    if len(argv) >= 1:
        worker_args['num_parallel'] = int(argv[0])
    RemoteWorkerCluster(args=worker_args).run()
