"""Per-host batched inference service for the distributed actor fleet.

The reference design runs B=1 CPU inference inside every episode worker
(reference model.py:50-60): each worker process holds a full model snapshot
and pays one jitted dispatch per ply. The Podracer/Sebulba architecture
(https://arxiv.org/pdf/2104.06272) restructures that: env-steppers submit
observations to one accelerator-adjacent inference server that coalesces
them into large batched forward passes. This module is that restructuring
for the 4-RPC worker fleet:

* :class:`InferenceEngine` — owned by the per-host relay (``worker.Gather``).
  It is the only process on the host that materializes model snapshots
  (model broadcast cost drops from O(workers) to O(hosts)); it coalesces
  outstanding ``(model_id, obs, hidden, legal_actions)`` requests across all
  workers on the host — per model id, under a ``batch_wait_ms`` deadline and
  a ``max_batch`` cap, padding ragged rows exactly like the learner-local
  batched generator — runs ONE ``batch_inference`` per tick, performs masked
  sampling engine-side (the same audited routine the B=1 path uses, so
  episode records stay bit-identical), and fans the
  ``(action, prob, value, hidden')`` replies back over the Hub.

* :class:`RemoteModel` / :class:`RemoteModelCache` — the worker-side proxies.
  A worker in engine mode never touches params: its "model" is a handle that
  turns ``act``/``inference`` calls into request frames on the existing
  worker<->gather pipe (multiplexed by the gather's Hub event loop alongside
  the task RPCs).

* :class:`ModelVault` — the snapshot-materialization LRU (moved here from
  ``worker.py``; the per-worker B=1 path still uses it directly). Capacity
  is the ``inference.vault_size`` knob. Two ids of the same architecture
  never alias one set of live params.

Recurrent state rides the requests: a request with ``hidden=None`` against a
recurrent model gets a fresh ``init_hidden()`` engine-side (episode start),
and every reply carries the advanced per-row hidden for the worker to send
back on its next ply — the engine itself holds no per-episode state, so
workers may crash/join at any time without poisoning the service.
"""

from __future__ import annotations

import threading
import time
import traceback
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import telemetry
from .connection import INFER_KIND, send_recv
from .generation import masked_sample_batch, pad_to_bucket
from .model import ModelWrapper, RandomModel
from .utils.tree import map_structure

_LOG = telemetry.get_logger('inference')

_UNSET = object()   # per-wrapper init_hidden cache sentinel


def _canon(x):
    """Rebind an unpickled ndarray's dtype to the interned descriptor.

    Arrays that crossed the engine pipe carry a fresh ``dtype`` instance;
    value-equal but not identical to numpy's interned singleton. Pickle
    memoizes dtype objects by IDENTITY, so a moment dict mixing local and
    wire arrays would serialize to different bytes than an all-local one —
    breaking the bit-identical episode record contract. Rebinding is O(1)
    (descriptor swap, no data copy)."""
    if isinstance(x, np.ndarray):
        x.dtype = np.dtype(x.dtype.str)
    return x


class ModelVault:
    """Small LRU of materialized models keyed by model id.

    ``fetch(model_id)`` pulls a snapshot over the RPC connection on miss.
    Each cached id owns its wrapper (sharing only the per-architecture jit
    cache inside ModelWrapper), so distinct ids never share live params.
    Id 0 denotes the untrained epoch-0 net and is served as a RandomModel —
    a deliberate, documented divergence (see PARITY.md): its uniform play
    matches the sampler's selected_prob, keeping training math identical.
    """

    def __init__(self, fetch, example_obs, capacity: int = 3):
        self._fetch = fetch
        self._example_obs = example_obs
        self._capacity = max(1, int(capacity))
        self._slots: OrderedDict = OrderedDict()
        self._templates: Dict[str, Any] = {}   # arch -> params pytree
        self.fetches = 0                       # snapshot pulls (cache misses)

    def obtain(self, wanted: Dict[Any, Optional[int]]) -> Dict[Any, Any]:
        """Return player -> model for every requested id (None/negative ->
        no model: the server assigns those seats to built-in opponents)."""
        out = {}
        for player, mid in wanted.items():
            if mid is None or mid < 0:
                out[player] = None
                continue
            out[player] = self.model(mid)
        return out

    def model(self, mid: int):
        """The materialized model for one id (admitting it on miss)."""
        if mid not in self._slots:
            self._admit(mid)
        self._slots.move_to_end(mid)
        return self._slots[mid]

    def _admit(self, mid: int):
        snap = self._fetch(mid)
        self.fetches += 1
        # template key includes the wire config: the same architecture with
        # a different param-tree-shaping knob (e.g. GeisterNet norm_kind)
        # must not reuse a structurally different template
        key = (snap['architecture'], tuple(sorted(snap.get('config', {}).items())))
        wrapper = ModelWrapper.from_snapshot(
            snap, self._example_obs,
            params_template=self._templates.get(key))
        self._templates.setdefault(key, wrapper.params)
        model = RandomModel(wrapper, self._example_obs) if mid == 0 else wrapper
        while len(self._slots) >= self._capacity:
            self._slots.popitem(last=False)
        self._slots[mid] = model


class RemoteModel:
    """Worker-side model handle: calls become engine request frames.

    Presents the model surface the generators/agents dispatch on
    (``inference`` / ``init_hidden`` plus the engine-native ``act``), but
    holds no params — every call is one strict call-response round trip on
    the worker's pipe, routed by the gather's Hub to the host engine.
    ``init_hidden`` returns None by design: the engine substitutes a fresh
    initial state for a None hidden, so the worker needs no knowledge of
    the recurrent state's structure.
    """

    def __init__(self, conn, model_id: int):
        self.conn = conn
        self.model_id = int(model_id)
        self._rid = 0

    def init_hidden(self, batch_shape=None):
        return None

    def _send(self, body: Dict[str, Any]) -> int:
        self._rid += 1
        body['rid'] = self._rid
        body['mid'] = self.model_id
        self.conn.send((INFER_KIND, body))
        return self._rid

    def _recv(self, rid: int) -> Dict[str, Any]:
        reply = self.conn.recv()
        if not isinstance(reply, dict):
            raise ConnectionError('inference engine reply was %r' % (reply,))
        if reply.get('error'):
            raise RuntimeError('inference engine: %s' % (reply['error'],))
        if reply.get('rid') != rid:
            raise ConnectionError('inference reply out of order (rid %r, '
                                  'expected %d)' % (reply.get('rid'), rid))
        return map_structure(_canon, reply)

    def _rpc(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self._recv(self._send(body))

    def inference(self, obs, hidden=None) -> Dict[str, Any]:
        """Full-output forward (observer plies, evaluation agents)."""
        return self._rpc({'obs': obs, 'hidden': hidden})['outputs']

    def act(self, obs, hidden, legal_actions, seed_seq) -> Dict[str, Any]:
        """Engine-side masked sampling: one round trip returns the sampled
        action, its probability, the action mask, value and hidden'."""
        return self._recv(self.act_send(obs, hidden, legal_actions, seed_seq))

    # split act: generators submit every simultaneous-turn request before
    # collecting any reply, so one worker's plies coalesce into the same
    # engine batch (replies come back FIFO on the worker's pipe — the Hub
    # serves per-endpoint outboxes and the engine answers groups in
    # arrival order, so send order IS receive order)
    def act_send(self, obs, hidden, legal_actions, seed_seq) -> int:
        return self._send({'obs': obs, 'hidden': hidden,
                           'legal': [int(a) for a in legal_actions],
                           'seed': [int(s) for s in seed_seq]})

    act_recv = _recv


class RemoteModelCache:
    """Engine-mode stand-in for the worker's ModelVault: same ``obtain``
    surface, but entries are weightless wire proxies instead of
    materialized snapshots."""

    def __init__(self, conn, capacity: int = 8):
        self.conn = conn
        self._capacity = max(1, int(capacity))
        self._slots: OrderedDict = OrderedDict()

    def obtain(self, wanted: Dict[Any, Optional[int]]) -> Dict[Any, Any]:
        out = {}
        for player, mid in wanted.items():
            if mid is None or mid < 0:
                out[player] = None
                continue
            if mid not in self._slots:
                while len(self._slots) >= self._capacity:
                    self._slots.popitem(last=False)
                self._slots[mid] = RemoteModel(self.conn, mid)
            self._slots.move_to_end(mid)
            out[player] = self._slots[mid]
        return out


class InferenceEngine:
    """Coalescing batched-inference server for one host's episode workers.

    ``submit(endpoint, request)`` may be called from any thread (the
    gather's Hub loop); a single engine thread drains the queue in ticks:
    it waits until ``max_batch`` requests are pending, ``batch_wait_ms``
    has passed since the oldest arrival, or the queue has gone quiescent
    with at least ``clients`` requests waiting (see ``_collect``); then it
    groups the tick's requests per model id, pads each group to a
    power-of-two row bucket, runs ONE ``batch_inference`` per group, samples
    actions engine-side for the rows that carry legal actions, and replies
    through ``reply_fn(endpoint, message)``.

    A failure while serving a group (snapshot fetch error, model crash)
    answers the affected requests with an ``error`` reply — the worker
    raises, loses that one episode, and the service keeps running.
    """

    def __init__(self, args: Dict[str, Any], fetch_snapshot: Callable,
                 reply_fn: Callable, clients: Optional[int] = None,
                 example_obs=None):
        inf = dict(args.get('inference') or {})
        self.batch_wait = max(0.0, float(inf.get('batch_wait_ms', 2.0))) / 1e3
        self.max_batch = max(1, int(inf.get('max_batch', 64)))
        self.vault_size = int(inf.get('vault_size', 3))
        self.clients = clients
        self._args = args
        self._fetch = fetch_snapshot
        self._reply = reply_fn
        self._example_obs = example_obs
        self.vault: Optional[ModelVault] = None   # built lazily (engine thread)
        self._queue: deque = deque()              # (endpoint, request, t_arrival)
        self._cv = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # local tallies mirror the registry so the fill ratio is computable
        # even with telemetry disabled (the bench/smoke contract reads it)
        self.requests_served = 0
        self.batches_run = 0
        self._m_requests = telemetry.counter('engine_requests_total')
        self._m_batches = telemetry.counter('engine_batches_total')
        self._m_rows = telemetry.REGISTRY.histogram(
            'engine_batch_rows', buckets=telemetry.BATCH_ROW_BUCKETS)
        self._m_wait = telemetry.REGISTRY.histogram('engine_coalesce_seconds')
        self._m_depth = telemetry.gauge('engine_queue_depth')
        self._m_fill = telemetry.gauge('engine_batch_fill_ratio')

    # -- lifecycle --------------------------------------------------------

    def start(self) -> 'InferenceEngine':
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def batch_fill_ratio(self) -> float:
        """Mean requests per dispatched forward batch (1.0 = no coalescing
        benefit over per-worker B=1)."""
        return self.requests_served / max(1, self.batches_run)

    # -- request intake (any thread) --------------------------------------

    def submit(self, endpoint, request: Dict[str, Any]):
        with self._cv:
            self._queue.append((endpoint, request, time.monotonic()))
            self._m_depth.set(len(self._queue))
            self._cv.notify()

    # -- engine thread ----------------------------------------------------

    def _ensure_vault(self):
        if self.vault is not None:
            return
        example_obs = self._example_obs
        if example_obs is None:
            from .environment import make_env
            env = make_env(dict(self._args['env']))
            env.reset()
            example_obs = env.observation(env.players()[0])
        self.vault = ModelVault(self._fetch, example_obs,
                                capacity=self.vault_size)

    def _collect(self) -> Optional[List[tuple]]:
        """Block until a tick's worth of requests is due; None on stop.

        A tick dispatches when ``max_batch`` requests are pending, when
        ``batch_wait_ms`` has elapsed since the oldest arrival (the hard
        latency cap), or when the queue has gone QUIESCENT — no new arrival
        for a fraction of the deadline while at least ``clients`` requests
        wait. Quiescence is the early-dispatch workhorse: submitters push
        their whole turn burst back-to-back, so a silent queue means
        everyone who was going to join this batch already has, and holding
        the deadline out would only add latency, not fill."""
        gap = max(2e-4, self.batch_wait / 8)
        floor = min(self.max_batch, max(1, self.clients or 1))
        with self._cv:
            while not self._queue:
                if self._stop:
                    return None
                self._cv.wait(1.0)
            deadline = self._queue[0][2] + self.batch_wait
            while len(self._queue) < self.max_batch and not self._stop:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                before = len(self._queue)
                self._cv.wait(min(remaining, gap))
                if len(self._queue) == before and before >= floor:
                    break
            n = min(len(self._queue), self.max_batch)
            items = [self._queue.popleft() for _ in range(n)]
            self._m_depth.set(len(self._queue))
        self._m_wait.observe(time.monotonic() - items[0][2])
        return items

    def _loop(self):
        while True:
            items = self._collect()
            if items is None:
                return
            groups: Dict[int, List[tuple]] = {}
            for item in items:
                groups.setdefault(int(item[1]['mid']), []).append(item)
            for mid, group in groups.items():
                try:
                    self._serve(mid, group)
                except Exception as exc:
                    _LOG.warning('engine: serving model %d failed (%s: %s)',
                                 mid, type(exc).__name__, str(exc)[:200])
                    _LOG.debug('%s', traceback.format_exc())
                    for ep, req, _t in group:
                        self._reply(ep, {'rid': req.get('rid'),
                                         'error': '%s: %s'
                                         % (type(exc).__name__,
                                            str(exc)[:200])})

    def _serve(self, mid: int, group: List[tuple]):
        self._ensure_vault()
        model = self.vault.model(mid)
        reqs = [req for _ep, req, _t in group]
        rows = len(reqs)
        self.requests_served += rows
        self.batches_run += 1
        self._m_requests.inc(rows)
        self._m_batches.inc()
        self._m_rows.observe(rows)
        self._m_fill.set(self.batch_fill_ratio())

        if isinstance(model, RandomModel):
            # id 0: zero outputs, no forward pass — masked sampling over a
            # zero policy is exactly the uniform play RandomModel encodes
            out = model.inference(None)
            policies = np.broadcast_to(out['policy'],
                                       (rows,) + out['policy'].shape)
            values = (np.broadcast_to(out['value'],
                                      (rows,) + out['value'].shape)
                      if 'value' in out else None)
            next_hidden = None
        else:
            obs_batch, _ = pad_to_bucket([r['obs'] for r in reqs])
            init = getattr(model, '_engine_h0', _UNSET)
            if init is _UNSET:
                init = model.init_hidden()
                model._engine_h0 = init
            hidden_batch = None
            if init is not None:
                hidden_batch, _ = pad_to_bucket(
                    [r.get('hidden') if r.get('hidden') is not None else init
                     for r in reqs])
            outputs = model.batch_inference(obs_batch, hidden_batch)
            policies = np.asarray(outputs['policy'])
            values = (np.asarray(outputs['value'])
                      if outputs.get('value') is not None else None)
            next_hidden = outputs.get('hidden')

        act_rows = [n for n, r in enumerate(reqs) if r.get('legal') is not None]
        if act_rows:
            actions, probs, masks = masked_sample_batch(
                policies[act_rows],
                [reqs[n]['legal'] for n in act_rows],
                [reqs[n].get('seed') or [0] for n in act_rows])
        act_index = {n: k for k, n in enumerate(act_rows)}

        for n, (ep, req, _t) in enumerate(group):
            hidden_row = None
            if next_hidden is not None:
                hidden_row = map_structure(
                    lambda a: np.asarray(a)[n], next_hidden)
            if n in act_index:
                k = act_index[n]
                reply = {'rid': req.get('rid'),
                         'action': int(actions[k]), 'prob': probs[k],
                         'action_mask': masks[k],
                         'value': values[n] if values is not None else None,
                         'hidden': hidden_row}
            else:
                row_out = {'policy': policies[n]}
                if values is not None:
                    row_out['value'] = values[n]
                if hidden_row is not None:
                    row_out['hidden'] = hidden_row
                reply = {'rid': req.get('rid'), 'outputs': row_out}
            self._reply(ep, reply)
