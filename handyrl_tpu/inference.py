"""Per-host batched inference service for the distributed actor fleet.

The reference design runs B=1 CPU inference inside every episode worker
(reference model.py:50-60): each worker process holds a full model snapshot
and pays one jitted dispatch per ply. The Podracer/Sebulba architecture
(https://arxiv.org/pdf/2104.06272) restructures that: env-steppers submit
observations to one accelerator-adjacent inference server that coalesces
them into large batched forward passes. This module is that restructuring
for the 4-RPC worker fleet — and, since PR 6, the *self-healing* version of
it: every worker on a host depends on one engine thread, so that thread is
supervised, requests carry deadlines, and the worker can degrade to the
per-worker inference path and come back, all without losing a single
episode byte.

* :class:`InferenceEngine` — the coalescing batched-forward server. It
  groups outstanding ``(model_id, obs, hidden, legal_actions)`` requests
  across all workers on the host — per model id, under a ``batch_wait_ms``
  deadline and a ``max_batch`` cap, padding ragged rows exactly like the
  learner-local batched generator — runs ONE ``batch_inference`` per tick,
  performs masked sampling engine-side (the same audited routine the B=1
  path uses, so episode records stay bit-identical), and fans the
  ``(action, prob, value, hidden')`` replies back over the Hub. Its intake
  queue is bounded (``inference.queue_max``): an overloaded engine sheds
  requests with an error reply instead of growing without bound, and a
  fatal engine error fans an error reply to every in-flight request — no
  reply is ever silently dropped by a crash.

* :class:`EngineSupervisor` — the watchdog the Gather actually owns. It
  health-checks the engine's tick progress, restarts a crashed or stalled
  engine with :class:`~.fault.Backoff`, drains + error-answers whatever the
  dead engine was holding, and suppresses replies from an abandoned
  (zombie) engine thread via a generation tag so a restart can never
  double-answer a request. It also hosts the ``enginekill=`` /
  ``enginestall=`` chaos injectors.

* :class:`EngineClient` / :class:`RemoteModel` / :class:`RemoteModelCache`
  — the worker side. A worker in engine mode never touches params by
  default: its "models" are handles that turn ``act``/``inference`` calls
  into request frames on the existing worker<->gather pipe. Every round
  trip carries a deadline (``inference.request_timeout``) with bounded
  resends (``request_retries``); when the engine stays unreachable the
  client opens a circuit breaker and **degrades to the per-worker
  inference path** — materializing snapshots locally through the same
  'model' RPC — and, because the PR 5 seeded sampler makes an episode a
  pure function of ``(seed, sample_key, params)`` on either path, the
  failover is lossless: records stay byte-identical. A half-open probe
  (``reprobe_initial_delay`` backoff) re-promotes the worker to the engine
  path once the engine answers again.

* :class:`ModelVault` — the snapshot-materialization LRU (the per-worker
  B=1 path and the degraded failover path use it directly; the engine uses
  it engine-side). Capacity is the ``inference.vault_size`` knob. Two ids
  of the same architecture never alias one set of live params.

Recurrent state rides the requests: a request with ``hidden=None`` against
a recurrent model gets a fresh ``init_hidden()`` engine-side (episode
start), and every reply carries the advanced per-row hidden for the worker
to send back on its next ply — the engine itself holds no per-episode
state, so workers may crash/join/degrade/re-promote at any ply without
poisoning the service or the episode.
"""

from __future__ import annotations

import random
import threading
import time
import traceback
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import telemetry
from .connection import INFER_KIND, is_infer
from .fault import Backoff, parse_chaos
from .generation import (bucketed_inference, masked_sample_batch, model_act,
                         pad_to_bucket)
from .model import ModelWrapper, RandomModel
from .utils.tree import map_structure

_LOG = telemetry.get_logger('inference')

_UNSET = object()   # per-wrapper init_hidden cache sentinel


def _canon(x):
    """Rebind an unpickled ndarray's dtype to the interned descriptor.

    Arrays that crossed the engine pipe carry a fresh ``dtype`` instance;
    value-equal but not identical to numpy's interned singleton. Pickle
    memoizes dtype objects by IDENTITY, so a moment dict mixing local and
    wire arrays would serialize to different bytes than an all-local one —
    breaking the bit-identical episode record contract. Rebinding is O(1)
    (descriptor swap, no data copy)."""
    if isinstance(x, np.ndarray):
        x.dtype = np.dtype(x.dtype.str)
    return x


class ModelVault:
    """Small LRU of materialized models keyed by model id.

    ``fetch(model_id)`` pulls a snapshot over the RPC connection on miss.
    Each cached id owns its wrapper (sharing only the per-architecture jit
    cache inside ModelWrapper), so distinct ids never share live params.
    Id 0 denotes the untrained epoch-0 net and is served as a RandomModel —
    a deliberate, documented divergence (see PARITY.md): its uniform play
    matches the sampler's selected_prob, keeping training math identical.
    """

    def __init__(self, fetch, example_obs, capacity: int = 3):
        self._fetch = fetch
        self._example_obs = example_obs
        self._capacity = max(1, int(capacity))
        self._slots: OrderedDict = OrderedDict()
        self._templates: Dict[str, Any] = {}   # arch -> params pytree
        self.fetches = 0                       # snapshot pulls (cache misses)

    def obtain(self, wanted: Dict[Any, Optional[int]]) -> Dict[Any, Any]:
        """Return player -> model for every requested id (None/negative ->
        no model: the server assigns those seats to built-in opponents)."""
        out = {}
        for player, mid in wanted.items():
            if mid is None or mid < 0:
                out[player] = None
                continue
            out[player] = self.model(mid)
        return out

    def model(self, mid: int):
        """The materialized model for one id (admitting it on miss)."""
        if mid not in self._slots:
            self._admit(mid)
        self._slots.move_to_end(mid)
        return self._slots[mid]

    def params(self, mid: int):
        """The raw params pytree for one id, or None for ids served as
        RandomModel (id 0) — the device actor backend stacks these as slot
        leaves; paramless seats run zero-policy modes instead."""
        return getattr(self.model(int(mid)), 'params', None)

    def _admit(self, mid: int):
        snap = self._fetch(mid)
        self.fetches += 1
        # template key includes the wire config: the same architecture with
        # a different param-tree-shaping knob (e.g. GeisterNet norm_kind)
        # must not reuse a structurally different template
        key = (snap['architecture'], tuple(sorted(snap.get('config', {}).items())))
        wrapper = ModelWrapper.from_snapshot(
            snap, self._example_obs,
            params_template=self._templates.get(key))
        self._templates.setdefault(key, wrapper.params)
        model = RandomModel(wrapper, self._example_obs) if mid == 0 else wrapper
        while len(self._slots) >= self._capacity:
            self._slots.popitem(last=False)
        self._slots[mid] = model


# ---------------------------------------------------------------------------
# worker side: deadline-bounded transport + circuit-breaker failover


class EngineClient:
    """Worker-side engine transport: deadlines, bounded retry, and a
    circuit breaker that degrades to the per-worker inference path.

    One client per worker process owns the worker's half of the engine
    protocol on the shared gather pipe: request ids, the pending-request
    book (kept so a timed-out request can be REPLAYED locally from its own
    inputs — lossless, since the two paths are bit-identical), early/stale
    reply routing, and the circuit-breaker state machine:

    * **closed** (``engine_ok``): requests go to the engine, each with a
      ``request_timeout`` deadline and up to ``request_retries`` resends.
    * **open**: after a request exhausts its deadline budget or gets an
      engine-fault error reply, the client logs the degradation, computes
      every in-flight and subsequent request locally (ModelVault over the
      same 'model' RPC), and schedules a half-open probe.
    * **half-open**: once the :class:`~.fault.Backoff` delay elapses, ONE
      request is routed to the engine as a probe; success re-promotes the
      worker to the engine path (circuit closes, backoff resets), failure
      re-opens with a longer delay.

    ``rpc`` is the client's filtered call-response for the worker's
    non-inference RPCs (args/episode/model): a late reply from an abandoned
    inference request may arrive at any time after a failover, and must be
    absorbed instead of being mistaken for the RPC's reply.
    """

    def __init__(self, conn, args: Dict[str, Any], namespace: int = 0):
        inf = dict(args.get('inference') or {})
        srv = dict(args.get('serving') or {})
        self.conn = conn
        self._args = args
        self.namespace = int(namespace)
        # remote-service mode (serving.endpoint, docs/serving.md): engine
        # frames dial a standalone InferenceService over TCP instead of
        # riding the gather pipe; requests name the model '<line>@<mid>'
        # against the service registry. Everything else — deadlines,
        # retries, the circuit breaker, the byte-identical local fallback —
        # is the same machinery, so a dead service degrades exactly like a
        # dead in-Gather engine. The endpoint may be a comma-separated
        # replica list (and/or a serving.fleet.resolver to fetch the live
        # table from): a dead replica rotates to the next one, and only an
        # all-replicas-down fleet degrades to per-worker inference.
        flt = dict(srv.get('fleet') or {})
        self.endpoint = str(srv.get('endpoint') or '')
        self._endpoints = [e.strip() for e in self.endpoint.split(',')
                           if e.strip()]
        self._resolver = str(flt.get('resolver') or '')
        self._resolver_refresh = max(0.5, float(flt.get('refresh_interval',
                                                        2.0)))
        self._resolver_next = 0.0      # next fleet-table fetch
        self._remote_mode = bool(self._endpoints or self._resolver)
        self._line = str(srv.get('line', 'default'))
        self._remote = None            # lazy FramedConnection to the service
        self._remote_ep = ''           # endpoint self._remote targets
        self._ep_idx = 0               # rotation cursor over _endpoints
        self._ep_down: Dict[str, float] = {}     # endpoint -> retry-at
        self._ep_backoff: Dict[str, Backoff] = {}
        self._m_dials = telemetry.counter('worker_engine_remote_dials_total')
        self._m_rotations = telemetry.counter(
            'worker_engine_endpoint_rotations_total')
        self.timeout = max(0.05, float(inf.get('request_timeout', 10.0)))
        self.retries = max(0, int(inf.get('request_retries', 1)))
        self.failover = bool(inf.get('failover', True))
        self.vault_size = int(inf.get('vault_size', 3))
        self._backoff = Backoff(
            float(inf.get('reprobe_initial_delay', 2.0)),
            float(inf.get('reprobe_max_delay', 30.0)))
        self.engine_ok = True          # circuit closed: engine path active
        self._probe_at = 0.0           # open circuit: next half-open probe
        self._probing_rid: Optional[int] = None
        self._rid = 0
        self._pending: Dict[int, Dict[str, Any]] = {}   # rid -> request
        self._box: Dict[int, Dict[str, Any]] = {}       # rid -> early reply
        self._local_box: Dict[int, Dict[str, Any]] = {}  # rid -> local reply
        self._vault: Optional[ModelVault] = None
        self._m_timeouts = telemetry.counter('worker_engine_timeouts_total')
        self._m_errors = telemetry.counter('worker_engine_errors_total')
        self._m_failovers = telemetry.counter('worker_engine_failovers_total')
        self._m_repromote = telemetry.counter(
            'worker_engine_repromotions_total')
        self._m_local = telemetry.counter('worker_local_inference_total')
        self._m_stale = telemetry.counter('worker_stale_replies_total')
        self._m_path = telemetry.gauge('worker_inference_path')
        self._m_path.set(1.0)

    # -- non-inference RPCs (args / episode / result / model) --------------

    def rpc(self, msg):
        """send_recv with inference-frame filtering: a stale engine reply
        (late answer to a request the client already failed over) must not
        be mistaken for this RPC's reply."""
        self.conn.send(msg)
        while True:
            reply = self.conn.recv()
            if is_infer(reply):
                self._absorb(reply[1] if isinstance(reply[1], dict) else {})
                continue
            return reply

    # -- request submission ------------------------------------------------

    def send(self, mid: int, body: Dict[str, Any]) -> int:
        """Submit one inference request; returns its request id. Routed to
        the engine when the circuit is closed (or as the half-open probe),
        computed locally otherwise."""
        self._rid += 1
        rid = self._rid
        rec = dict(body)
        rec['mid'] = int(mid)
        engine_path = self.engine_ok
        if (not engine_path and self.failover and self._probing_rid is None
                and time.monotonic() >= self._probe_at):
            engine_path = True          # half-open: one probe in flight
            self._probing_rid = rid
            _LOG.info('worker %d: probing inference engine (rid %d)',
                      self.namespace, rid)
        if engine_path:
            self._pending[rid] = rec
            if not self._send_engine(rid, rec):
                # every service replica is down: fail over NOW instead of
                # burning the request deadline on sockets that never opened
                self._local_box[rid] = self._fail(
                    rid, rec,
                    'service endpoint(s) %s unreachable'
                    % (self.endpoint or self._resolver))
        else:
            self._local_box[rid] = self._local_reply(rec)
        return rid

    def recv(self, rid: int) -> Dict[str, Any]:
        """Collect the reply for ``rid``: deadline-bounded with bounded
        resends on the engine path, instant on the degraded local path."""
        if rid in self._local_box:
            return self._local_box.pop(rid)
        rec = self._pending.get(rid)
        if rec is None:
            raise RuntimeError('unknown inference request id %r' % rid)
        err = 'no reply within %.1fs' % self.timeout
        # a probe gets ONE deadline (no resends): the point is to test the
        # engine cheaply, not to wait retries*timeout on a dead one
        attempts = 1 + (0 if self._probing_rid == rid else self.retries)
        for attempt in range(attempts):
            reply = self._box.pop(rid, None)
            if reply is None:
                reply = self._await(rid, self.timeout)
            if reply is None:                     # deadline expired
                self._m_timeouts.inc()
                if attempt + 1 < attempts:
                    # a silent service endpoint is down-marked before the
                    # resend so the redial rotates to another replica (the
                    # blackholed-replica case; no-op on the gather pipe)
                    self._drop_remote()
                    # resend under the same rid: if BOTH replies eventually
                    # arrive, the second is absorbed as stale
                    if not self._send_engine(rid, rec):
                        break                     # dead service: fail now
                continue
            if reply.get('error'):
                self._m_errors.inc()
                err = str(reply['error'])
                break
            self._settle_ok(rid)
            out = map_structure(_canon, reply)
            if isinstance(out.get('prob'), float):
                # the remote-service hop (msgpack) degrades np.float32
                # scalars to python floats; records must keep the dtype or
                # they pickle to different bytes than the local path's
                out['prob'] = np.float32(out['prob'])
            return out
        return self._fail(rid, rec, err)

    # -- internals ---------------------------------------------------------

    def _refresh_endpoints(self):
        """Fetch the routable replica table from the fleet resolver (when
        one is configured), replacing the endpoint rotation; a resolver
        failure keeps the stale list — the data plane outlives it."""
        now = time.monotonic()
        if not self._resolver or now < self._resolver_next:
            return
        self._resolver_next = now + self._resolver_refresh
        try:
            from .serving.client import (ServiceClient, ServiceUnavailable,
                                         parse_endpoint)
            host, port = parse_endpoint(self._resolver)
            probe = ServiceClient(host, port, timeout=2.0, dial_retries=0)
            try:
                table = probe.fleet(timeout=2.0).get('replicas') or []
            finally:
                probe.close()
        except (OSError, ConnectionError, EOFError, ValueError,
                TimeoutError, RuntimeError):
            return
        fresh = [str(r.get('endpoint')) for r in table
                 if r.get('state') in ('healthy', 'degraded')
                 and not r.get('draining') and r.get('endpoint')]
        if fresh and sorted(fresh) != sorted(self._endpoints):
            _LOG.info('worker %d: fleet resolver lists %d routable '
                      'replica(s): %s', self.namespace, len(fresh),
                      ', '.join(fresh))
            self._endpoints = fresh

    def _pick_endpoint(self) -> str:
        """Next admissible endpoint in rotation; an endpoint stays skipped
        until its down-mark expires. All down -> the soonest-retryable one
        (so a fleet-wide blip still probes instead of deadlocking)."""
        self._refresh_endpoints()
        if not self._endpoints:
            raise OSError('no service endpoints known (resolver %s has no '
                          'routable replicas)' % (self._resolver or '-'))
        now = time.monotonic()
        n = len(self._endpoints)
        for off in range(n):
            ep = self._endpoints[(self._ep_idx + off) % n]
            if self._ep_down.get(ep, 0.0) <= now:
                self._ep_idx = (self._ep_idx + off) % n
                return ep
        return min(self._endpoints, key=lambda e: self._ep_down.get(e, 0.0))

    def _infer_conn(self):
        """The connection engine frames ride: the gather pipe, or — with a
        ``serving.endpoint``/fleet resolver configured — a lazily-dialed
        TCP link to one of the InferenceService replicas."""
        if not self._remote_mode:
            return self.conn
        if self._remote is None:
            from .connection import connect_socket_connection
            ep = self._pick_endpoint()
            host, _, port = ep.rpartition(':')
            self._remote = connect_socket_connection(host or 'localhost',
                                                     int(port))
            self._remote_ep = ep
            self._m_dials.inc()
            _LOG.info('worker %d: dialed inference service %s',
                      self.namespace, ep)
        return self._remote

    def _drop_remote(self):
        if self._remote is not None:
            try:
                self._remote.close()
            except Exception:
                pass
            self._remote = None
        ep = self._remote_ep
        if ep:
            # down-mark the endpoint so the next dial rotates to another
            # replica; the mark expires on a per-endpoint backoff
            self._remote_ep = ''
            backoff = self._ep_backoff.setdefault(
                ep, Backoff(initial=0.5, maximum=15.0))
            self._ep_down[ep] = time.monotonic() + backoff.next_delay()
            if len(self._endpoints) > 1:
                self._m_rotations.inc()
                _LOG.warning('worker %d: service replica %s dropped; '
                             'rotating to the next endpoint',
                             self.namespace, ep)

    def _send_engine(self, rid: int, rec: Dict[str, Any]) -> bool:
        """Post one request on the engine path. False means the remote
        service could not be reached (dial or send failure) — the caller
        fails the request over; the gather-pipe path never fails here (a
        dead pipe is fatal to the worker, as before)."""
        body = {'rid': rid, **rec}
        if not self._remote_mode:
            self.conn.send((INFER_KIND, body))
            return True
        # the service resolves models by name against its registry; the
        # learner's publish hook registers epoch E as '<line>@<E>'
        body['model'] = '%s@%d' % (self._line, int(rec['mid']))
        # one attempt per known replica: a dead endpoint down-marks and
        # rotates; False only when the WHOLE fleet refused the frame
        attempts = max(1, len(self._endpoints))
        for _attempt in range(attempts):
            try:
                self._infer_conn().send((INFER_KIND, body))
                return True
            except (OSError, ConnectionError, EOFError, ValueError):
                self._drop_remote()
        return False

    def _poll(self, conn, timeout: float) -> bool:
        poll = getattr(conn, 'poll', None)
        return True if poll is None else poll(timeout)

    def _await(self, rid: int, timeout: float) -> Optional[Dict[str, Any]]:
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                conn = self._infer_conn()
                if not self._poll(conn, remaining):
                    return None
                msg = conn.recv()
            except (OSError, ConnectionError, EOFError):
                if not self._remote_mode:
                    raise          # a dead gather pipe is fatal (unchanged)
                self._drop_remote()
                return None        # treated as a timeout: retry/fail over
            if not is_infer(msg):
                raise ConnectionError(
                    'unexpected %s frame while awaiting an inference reply'
                    % type(msg).__name__)
            body = msg[1] if isinstance(msg[1], dict) else {}
            if body.get('rid') == rid:
                return body
            self._absorb(body)

    def _absorb(self, body: Dict[str, Any]):
        rid = body.get('rid')
        if rid in self._pending:
            self._box[rid] = body      # early reply for a later recv()
        else:
            self._m_stale.inc()        # late reply to an abandoned request

    def _settle_ok(self, rid: int):
        self._pending.pop(rid, None)
        if self._remote_ep:
            # the replica answered: clear its down-mark and backoff
            self._ep_down.pop(self._remote_ep, None)
            self._ep_backoff.pop(self._remote_ep, None)
        if self._probing_rid == rid:
            self._probing_rid = None
        if not self.engine_ok:
            self.engine_ok = True      # re-promotion: circuit closes
            self._backoff.reset()
            self._m_repromote.inc()
            self._m_path.set(1.0)
            _LOG.warning('worker %d: engine answered the probe; re-promoted '
                         'to engine inference', self.namespace)

    def _fail(self, rid: int, rec: Dict[str, Any], err: str
              ) -> Dict[str, Any]:
        self._pending.pop(rid, None)
        probing = self._probing_rid == rid
        if probing:
            self._probing_rid = None
        if not self.failover:
            raise RuntimeError('inference engine: %s' % err)
        now = time.monotonic()
        self._probe_at = now + self._backoff.next_delay()
        if self.engine_ok:
            self.engine_ok = False     # circuit opens
            self._m_failovers.inc()
            self._m_path.set(0.0)
            _LOG.warning('worker %d: engine unreachable (%s); degrading to '
                         'per-worker inference', self.namespace, err)
            # resolve the rest of the in-flight burst locally too — waiting
            # out each one's deadline serially would stall the episode for
            # pending * timeout seconds (their late replies are absorbed
            # as stale; the local results are bit-identical anyway)
            for orid in [r for r in self._pending if r not in self._box]:
                self._local_box[orid] = self._local_reply(
                    self._pending.pop(orid))
        elif probing:
            _LOG.info('worker %d: engine probe failed (%s); next probe in '
                      '%.1fs', self.namespace, err, self._probe_at - now)
        return self._local_reply(rec)

    # -- degraded path: per-worker inference, replayed from the request ----

    def _local_model(self, mid: int):
        if self._vault is None:
            from .environment import make_env
            env = make_env(dict(self._args['env']))
            env.reset()
            example_obs = env.observation(env.players()[0])
            self._vault = ModelVault(
                lambda m: self.rpc(('model', m)), example_obs,
                capacity=self.vault_size)
            _LOG.info('worker %d: materialized local model vault for the '
                      'degraded inference path', self.namespace)
        return self._vault.model(mid)

    def _local_reply(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        """Serve one request on the per-worker path, replaying exactly the
        inputs the engine would have seen — the reply is bit-identical to
        the engine's (PR 5 parity contract), so records do not fork."""
        self._m_local.inc()
        model = self._local_model(rec['mid'])
        hidden = rec.get('hidden')
        if hidden is None:
            hidden = model.init_hidden()   # same substitution as _serve
        if rec.get('legal') is None:
            return {'outputs': bucketed_inference(model, rec['obs'], hidden)}
        return model_act(model, rec['obs'], hidden, rec['legal'], rec['seed'])


class RemoteModel:
    """Worker-side model handle: calls become engine request frames.

    Presents the model surface the generators/agents dispatch on
    (``inference`` / ``init_hidden`` plus the engine-native ``act``), but
    holds no params — calls delegate to the shared :class:`EngineClient`,
    which owns deadlines, failover and the degraded local path.
    ``init_hidden`` returns None by design: both serving paths substitute a
    fresh initial state for a None hidden, so the worker needs no knowledge
    of the recurrent state's structure.
    """

    def __init__(self, client: EngineClient, model_id: int):
        self.client = client
        self.model_id = int(model_id)

    def init_hidden(self, batch_shape=None):
        return None

    def inference(self, obs, hidden=None) -> Dict[str, Any]:
        """Full-output forward (observer plies, evaluation agents)."""
        rid = self.client.send(self.model_id, {'obs': obs, 'hidden': hidden})
        return self.client.recv(rid)['outputs']

    def act(self, obs, hidden, legal_actions, seed_seq) -> Dict[str, Any]:
        """Masked sampling in one round trip: returns the sampled action,
        its probability, the action mask, value and hidden'."""
        return self.act_recv(self.act_send(obs, hidden, legal_actions,
                                           seed_seq))

    # split act: generators submit every simultaneous-turn request before
    # collecting any reply, so one worker's plies coalesce into the same
    # engine batch
    def act_send(self, obs, hidden, legal_actions, seed_seq) -> int:
        return self.client.send(self.model_id, {
            'obs': obs, 'hidden': hidden,
            'legal': [int(a) for a in legal_actions],
            'seed': [int(s) for s in seed_seq]})

    def act_recv(self, rid: int) -> Dict[str, Any]:
        return self.client.recv(rid)


class RemoteModelCache:
    """Engine-mode stand-in for the worker's ModelVault: same ``obtain``
    surface, but entries are weightless wire proxies (sharing one
    :class:`EngineClient`) instead of materialized snapshots."""

    def __init__(self, client, capacity: int = 8):
        self.client = client
        self._capacity = max(1, int(capacity))
        self._slots: OrderedDict = OrderedDict()

    def obtain(self, wanted: Dict[Any, Optional[int]]) -> Dict[Any, Any]:
        out = {}
        for player, mid in wanted.items():
            if mid is None or mid < 0:
                out[player] = None
                continue
            if mid not in self._slots:
                while len(self._slots) >= self._capacity:
                    self._slots.popitem(last=False)
                self._slots[mid] = RemoteModel(self.client, mid)
            self._slots.move_to_end(mid)
            out[player] = self._slots[mid]
        return out


# ---------------------------------------------------------------------------
# host side: the engine and its supervisor


class _ChaosEngineKill(RuntimeError):
    """Injected engine crash (HANDYRL_TPU_CHAOS enginekill=)."""


class InferenceEngine:
    """Coalescing batched-inference server for one host's episode workers.

    ``submit(endpoint, request)`` may be called from any thread (the
    gather's Hub loop); a single engine thread drains the queue in ticks:
    it waits until ``max_batch`` requests are pending, ``batch_wait_ms``
    has passed since the oldest arrival, or the queue has gone quiescent
    with at least ``clients`` requests waiting (see ``_collect``); then it
    groups the tick's requests per model id, pads each group to a
    power-of-two row bucket, runs ONE ``batch_inference`` per group, samples
    actions engine-side for the rows that carry legal actions, and replies
    through ``reply_fn(endpoint, message)``.

    Robustness contract (PR 6): the intake queue is bounded — a submit past
    ``queue_max`` is shed with an immediate error reply instead of growing
    the backlog without bound; a failure while serving a group (snapshot
    fetch error, model crash) answers the affected requests with an
    ``error`` reply; a FATAL engine error (anything escaping the tick loop)
    error-answers every in-flight and queued request before the thread
    exits, so no reply is ever silently dropped. Tick progress is exported
    (``progress_age`` / ``busy``) for the :class:`EngineSupervisor`
    watchdog, which restarts crashed/stalled engines.
    """

    def __init__(self, args: Dict[str, Any], fetch_snapshot: Callable,
                 reply_fn: Callable, clients: Optional[int] = None,
                 example_obs=None):
        inf = dict(args.get('inference') or {})
        self.batch_wait = max(0.0, float(inf.get('batch_wait_ms', 2.0))) / 1e3
        self.max_batch = max(1, int(inf.get('max_batch', 64)))
        self.vault_size = int(inf.get('vault_size', 3))
        self.queue_max = max(0, int(inf.get('queue_max', 1024)))
        self.clients = clients
        self._args = args
        self._fetch = fetch_snapshot
        self._reply = reply_fn
        self._example_obs = example_obs
        self.vault: Optional[ModelVault] = None   # built lazily (engine thread)
        self._cv = threading.Condition()
        # intake queue entries are (endpoint, request, t_arrival); shared by
        # submitters (hub loop), the engine thread, and the supervisor's
        # drain (lexical discipline checked by graftlint GL004)
        self._queue: deque = deque()              # guarded-by: _cv
        self._stop = False                        # guarded-by: _cv
        self._thread: Optional[threading.Thread] = None
        # watchdog surface: last tick progress + the tick's in-flight items
        self.started_at = time.monotonic()
        self.last_progress = time.monotonic()
        self._current: List[tuple] = []
        self.crashed: Optional[BaseException] = None
        self._fault: Optional[tuple] = None       # (kind, due_at, stall_s)
        # local tallies mirror the registry so the fill ratio (and the
        # serving tier's per-service shed accounting) is computable even
        # with telemetry disabled (the bench/smoke contract reads them)
        self.requests_served = 0
        self.batches_run = 0
        self.sheds = 0
        self._m_requests = telemetry.counter('engine_requests_total')
        self._m_batches = telemetry.counter('engine_batches_total')
        self._m_rows = telemetry.REGISTRY.histogram(
            'engine_batch_rows', buckets=telemetry.BATCH_ROW_BUCKETS)
        self._m_wait = telemetry.REGISTRY.histogram('engine_coalesce_seconds')
        self._m_depth = telemetry.gauge('engine_queue_depth')
        self._m_fill = telemetry.gauge('engine_batch_fill_ratio')
        self._m_shed = telemetry.counter('engine_shed_total')
        self._m_errors = telemetry.counter('engine_error_replies_total')
        self._m_leaked = telemetry.counter('engine_stop_leaked_total')

    # -- lifecycle --------------------------------------------------------

    def start(self) -> 'InferenceEngine':
        self.started_at = self.last_progress = time.monotonic()
        self._thread = threading.Thread(target=self._run,
                                        name='inference-engine', daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        with self._cv:
            self._stop = True
            queued = len(self._queue)
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # a wedged loop thread (stuck forward pass, hung snapshot
                # fetch) survives the join: make the leak VISIBLE instead
                # of silently returning over it
                self._m_leaked.inc()
                _LOG.warning(
                    'engine: loop thread still running %.0fs after stop() '
                    '(last progress %.1fs ago, %d queued) — leaking it',
                    timeout, self.progress_age(), queued)

    def abandon(self):
        """Mark the engine stopped without joining (supervisor restart of a
        wedged engine: the zombie thread exits at its next loop boundary —
        if any — and its replies are suppressed by the generation tag)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def thread_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- watchdog surface --------------------------------------------------

    def progress_age(self) -> float:
        """Seconds since the engine thread last demonstrated progress."""
        return time.monotonic() - self.last_progress

    def busy(self) -> bool:
        """True when the engine holds work a stalled thread would strand."""
        with self._cv:
            queued = bool(self._queue)
        return queued or bool(self._current)

    def batch_fill_ratio(self) -> float:
        """Mean requests per dispatched forward batch (1.0 = no coalescing
        benefit over per-worker B=1)."""
        return self.requests_served / max(1, self.batches_run)

    def drain_pending(self) -> List[tuple]:
        """Remove and return every queued + in-flight item (supervisor
        restart path: the caller owns answering them)."""
        with self._cv:
            items = list(self._queue)
            self._queue.clear()
            self._m_depth.set(0)
            self._cv.notify_all()
        current, self._current = list(self._current), []
        return current + items

    # -- chaos (HANDYRL_TPU_CHAOS enginekill= / enginestall=) --------------

    def arm_fault(self, kind: str, delay: float, stall_secs: float = 3600.0):
        """Schedule one injected fault: 'kill' raises out of the tick loop
        (a crashed engine), 'stall' sleeps inside it while holding the
        tick's requests (a wedged forward pass / hung snapshot fetch)."""
        self._fault = (kind, time.monotonic() + max(0.0, delay),
                       float(stall_secs))

    def _maybe_chaos(self):
        if self._fault is None or time.monotonic() < self._fault[1]:
            return
        kind, _due, stall_secs = self._fault
        self._fault = None
        if kind == 'kill':
            raise _ChaosEngineKill('chaos: engine kill injected')
        _LOG.warning('chaos: engine stall injected (%.0fs)', stall_secs)
        time.sleep(stall_secs)

    # -- request intake (any thread) --------------------------------------

    def submit(self, endpoint, request: Dict[str, Any]):
        shed = False
        with self._cv:
            if self.queue_max and len(self._queue) >= self.queue_max:
                shed = True    # backpressure: bounded queue, visible drop
                self.sheds += 1
            else:
                self._queue.append((endpoint, request, time.monotonic()))
                self._m_depth.set(len(self._queue))
                self._cv.notify()
        if shed:
            self._m_shed.inc()
            self._safe_reply(endpoint, {
                'rid': (request or {}).get('rid'), 'engine_fault': True,
                'error': 'engine overloaded: request shed '
                         '(queue >= %d)' % self.queue_max})

    # -- engine thread ----------------------------------------------------

    def _safe_reply(self, endpoint, msg):
        try:
            self._reply(endpoint, msg)
        except Exception:
            pass   # a dead endpoint's reply is a no-op, like a dead socket

    def fail_pending(self, reason: str) -> int:
        """Error-answer every queued + in-flight request (crash fan-out /
        supervisor drain): no submitter is left waiting on a reply the
        engine will never send."""
        items = self.drain_pending()
        for ep, req, _t in items:
            self._m_errors.inc()
            self._safe_reply(ep, {'rid': (req or {}).get('rid'),
                                  'error': reason, 'engine_fault': True})
        return len(items)

    def _ensure_vault(self):
        if self.vault is not None:
            return
        example_obs = self._example_obs
        if example_obs is None:
            from .environment import make_env
            env = make_env(dict(self._args['env']))
            env.reset()
            example_obs = env.observation(env.players()[0])
        self.vault = ModelVault(self._fetch, example_obs,
                                capacity=self.vault_size)

    def _collect(self) -> Optional[List[tuple]]:
        """Block until a tick's worth of requests is due; None on stop.

        A tick dispatches when ``max_batch`` requests are pending, when
        ``batch_wait_ms`` has elapsed since the oldest arrival (the hard
        latency cap), or when the queue has gone QUIESCENT — no new arrival
        for a fraction of the deadline while at least ``clients`` requests
        wait. Quiescence is the early-dispatch workhorse: submitters push
        their whole turn burst back-to-back, so a silent queue means
        everyone who was going to join this batch already has, and holding
        the deadline out would only add latency, not fill."""
        gap = max(2e-4, self.batch_wait / 8)
        floor = min(self.max_batch, max(1, self.clients or 1))
        with self._cv:
            while not self._queue:
                if self._stop:
                    return None
                self.last_progress = time.monotonic()   # idle, not stalled
                self._cv.wait(1.0)
            deadline = self._queue[0][2] + self.batch_wait
            while len(self._queue) < self.max_batch and not self._stop:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                before = len(self._queue)
                self._cv.wait(min(remaining, gap))
                if len(self._queue) == before and before >= floor:
                    break
            n = min(len(self._queue), self.max_batch)
            items = [self._queue.popleft() for _ in range(n)]
            self._m_depth.set(len(self._queue))
        self._m_wait.observe(time.monotonic() - items[0][2])
        self.last_progress = time.monotonic()
        return items

    def _run(self):
        """Thread body: the tick loop plus the fatal-error fan-out. A
        per-group failure is answered inline and the service keeps running;
        anything escaping the loop itself error-answers EVERYTHING still in
        flight, marks the engine crashed, and lets the supervisor restart."""
        try:
            self._loop()
        except BaseException as exc:   # noqa: BLE001 — crash containment
            self.crashed = exc
            _LOG.error('engine: fatal %s: %s', type(exc).__name__,
                       str(exc)[:200])
            if not isinstance(exc, _ChaosEngineKill):
                _LOG.debug('%s', traceback.format_exc())
            failed = self.fail_pending(
                'inference engine crashed (%s: %s)'
                % (type(exc).__name__, str(exc)[:200]))
            if failed:
                _LOG.warning('engine: error-answered %d in-flight '
                             'request(s) after the crash', failed)

    def _loop(self):
        while True:
            items = self._collect()
            if items is None:
                return
            self._current = items
            self._maybe_chaos()
            groups: Dict[int, List[tuple]] = {}
            for item in items:
                groups.setdefault(int(item[1]['mid']), []).append(item)
            for mid, group in groups.items():
                try:
                    self._serve(mid, group)
                except Exception as exc:
                    _LOG.warning('engine: serving model %d failed (%s: %s)',
                                 mid, type(exc).__name__, str(exc)[:200])
                    _LOG.debug('%s', traceback.format_exc())
                    for ep, req, _t in group:
                        self._m_errors.inc()
                        self._safe_reply(ep, {'rid': req.get('rid'),
                                              'error': '%s: %s'
                                              % (type(exc).__name__,
                                                 str(exc)[:200])})
                self.last_progress = time.monotonic()
            self._current = []

    def _serve(self, mid: int, group: List[tuple]):
        # rate-sampled batch-level span in the episode trace (plus the
        # stage_seconds{stage=engine_batch} histogram): one span per
        # coalesced forward batch, sized for the critical-path report
        extra: Dict[str, Any] = {}
        if telemetry.trace_enabled():
            # serving-path context: per-request queue_wait spans (intake ->
            # batch start) for every sampled trace id, and the batch span
            # carries args.trace_ids so --serve chains link through it
            # (same linkage shape as train_step's episode list)
            now_mono, now_wall = time.monotonic(), time.time()
            tids = []
            for _ep, req, t_arr in group:
                tid = req.get('trace')
                if not (tid and telemetry.trace_sampled(tid)):
                    continue
                tids.append(tid)
                wait = max(0.0, now_mono - t_arr)
                telemetry.trace_event('queue_wait', ts=now_wall - wait,
                                      dur=wait, trace_id=tid, mid=mid)
            if tids:
                extra = {'trace_ids': tids, 'always': True}
        with telemetry.trace_span('engine_batch', rows=len(group), mid=mid,
                                  **extra):
            self._serve_group(mid, group)

    def _serve_group(self, mid: int, group: List[tuple]):
        self._ensure_vault()
        model = self.vault.model(mid)
        reqs = [req for _ep, req, _t in group]
        rows = len(reqs)
        self.requests_served += rows
        self.batches_run += 1
        self._m_requests.inc(rows)
        self._m_batches.inc()
        self._m_rows.observe(rows)
        self._m_fill.set(self.batch_fill_ratio())

        if isinstance(model, RandomModel):
            # id 0: zero outputs, no forward pass — masked sampling over a
            # zero policy is exactly the uniform play RandomModel encodes
            out = model.inference(None)
            policies = np.broadcast_to(out['policy'],
                                       (rows,) + out['policy'].shape)
            values = (np.broadcast_to(out['value'],
                                      (rows,) + out['value'].shape)
                      if 'value' in out else None)
            next_hidden = None
        else:
            obs_batch, _ = pad_to_bucket([r['obs'] for r in reqs])
            init = getattr(model, '_engine_h0', _UNSET)
            if init is _UNSET:
                init = model.init_hidden()
                model._engine_h0 = init
            hidden_batch = None
            if init is not None:
                hidden_batch, _ = pad_to_bucket(
                    [r.get('hidden') if r.get('hidden') is not None else init
                     for r in reqs])
            outputs = model.batch_inference(obs_batch, hidden_batch)
            policies = np.asarray(outputs['policy'])
            values = (np.asarray(outputs['value'])
                      if outputs.get('value') is not None else None)
            next_hidden = outputs.get('hidden')

        act_rows = [n for n, r in enumerate(reqs) if r.get('legal') is not None]
        if act_rows:
            actions, probs, masks = masked_sample_batch(
                policies[act_rows],
                [reqs[n]['legal'] for n in act_rows],
                [reqs[n].get('seed') or [0] for n in act_rows])
        act_index = {n: k for k, n in enumerate(act_rows)}

        for n, (ep, req, _t) in enumerate(group):
            hidden_row = None
            if next_hidden is not None:
                hidden_row = map_structure(
                    lambda a: np.asarray(a)[n], next_hidden)
            if n in act_index:
                k = act_index[n]
                reply = {'rid': req.get('rid'),
                         'action': int(actions[k]), 'prob': probs[k],
                         'action_mask': masks[k],
                         'value': values[n] if values is not None else None,
                         'hidden': hidden_row}
            else:
                row_out = {'policy': policies[n]}
                if values is not None:
                    row_out['value'] = values[n]
                if hidden_row is not None:
                    row_out['hidden'] = hidden_row
                reply = {'rid': req.get('rid'), 'outputs': row_out}
            self._safe_reply(ep, reply)


class EngineSupervisor:
    """Watchdog + restart policy around :class:`InferenceEngine`.

    The Gather owns one of these instead of a bare engine. A monitor thread
    health-checks the engine's tick progress on a short cadence:

    * **crash** — the engine thread died (its own fan-out already answered
      what it could); the supervisor drains any later arrivals with error
      replies and restarts the engine after a :class:`~.fault.Backoff`
      delay (reset once an engine survives ``RESET_AFTER`` seconds).
    * **stall** — the engine is ``busy()`` but has made no tick progress
      for ``inference.stall_timeout`` seconds (wedged forward pass, hung
      snapshot fetch). The thread cannot be killed, so it is ABANDONED: the
      generation counter advances (suppressing any reply the zombie might
      eventually produce — a request must never be answered twice), every
      queued + in-flight request is error-answered, and a fresh engine
      starts. Requests the zombie physically holds get their error reply
      from this fan-out; workers that raced it are covered by their own
      request deadlines.

    While the engine is down (the backoff window), ``submit`` answers
    immediately with an error so workers fail fast into their degraded
    path instead of burning a full request deadline.

    Chaos: ``HANDYRL_TPU_CHAOS=enginekill=<mean s>`` / ``enginestall=<mean
    s>`` arm one injected fault per engine incarnation (alternating kinds
    when both are set) on an exponential clock, bounded by
    ``engine_max_faults=<n>``; ``enginestall_secs=<s>`` sets the injected
    stall's length (default 3600 — "forever" at test scale).
    """

    RESET_AFTER = 60.0   # engine alive this long => restart backoff resets

    def __init__(self, args: Dict[str, Any], fetch_snapshot: Callable,
                 reply_fn: Callable, clients: Optional[int] = None,
                 example_obs=None, chaos: Optional[Dict[str, float]] = None):
        inf = dict(args.get('inference') or {})
        self.stall_timeout = max(0.2, float(inf.get('stall_timeout', 30.0)))
        self._args = args
        self._fetch = fetch_snapshot
        self._reply_raw = reply_fn
        self._clients = clients
        self._example_obs = example_obs
        self._chaos = parse_chaos() if chaos is None else dict(chaos)
        self._faults_left = int(self._chaos.get('engine_max_faults', 1 << 30))
        self._fault_cycle = 0
        self._chaos_rng = random.Random(
            int(self._chaos.get('seed', 0)) * 104729 + 13)
        self._backoff = Backoff(0.5, float(inf.get('restart_max_delay', 10.0)))
        self._lock = threading.RLock()
        self._gen = 0
        self._stopping = False
        self._served_total = 0
        self._batches_total = 0
        self._sheds_total = 0
        self.restarts = 0
        self._m_restarts = {
            reason: telemetry.counter('engine_restarts_total', reason=reason)
            for reason in ('crash', 'stall')}
        self._m_stale = telemetry.counter('engine_stale_replies_total')
        self._spawned_at = time.monotonic()
        self.engine: Optional[InferenceEngine] = self._spawn()
        self._thread = threading.Thread(target=self._watch,
                                        name='engine-supervisor', daemon=True)
        self._thread.start()

    # -- bench/back-compat surface ----------------------------------------

    @property
    def requests_served(self) -> int:
        engine = self.engine
        return self._served_total + (engine.requests_served if engine else 0)

    @property
    def batches_run(self) -> int:
        engine = self.engine
        return self._batches_total + (engine.batches_run if engine else 0)

    @property
    def sheds(self) -> int:
        engine = self.engine
        return self._sheds_total + (engine.sheds if engine else 0)

    def batch_fill_ratio(self) -> float:
        return self.requests_served / max(1, self.batches_run)

    # -- lifecycle ---------------------------------------------------------

    def _tagged(self, gen: int) -> Callable:
        """Reply function bound to one engine incarnation: replies from an
        abandoned engine (older generation) are dropped — an answered
        request was already error-answered by the restart fan-out, and a
        second reply would desync the worker's reply stream."""
        def reply(ep, msg):
            if gen == self._gen:
                self._reply_raw(ep, msg)
            else:
                self._m_stale.inc()
        return reply

    def _spawn(self) -> InferenceEngine:
        self._gen += 1
        engine = InferenceEngine(
            self._args, fetch_snapshot=self._fetch,
            reply_fn=self._tagged(self._gen), clients=self._clients,
            example_obs=self._example_obs)
        self._arm_chaos(engine)
        self._spawned_at = time.monotonic()
        return engine.start()

    def _arm_chaos(self, engine: InferenceEngine):
        kinds = [k for k in ('enginekill', 'enginestall')
                 if self._chaos.get(k)]
        if not kinds or self._faults_left <= 0:
            return
        kind = kinds[self._fault_cycle % len(kinds)]
        self._fault_cycle += 1
        self._faults_left -= 1
        delay = self._chaos_rng.expovariate(1.0 / float(self._chaos[kind]))
        engine.arm_fault('kill' if kind == 'enginekill' else 'stall', delay,
                         stall_secs=float(self._chaos.get('enginestall_secs',
                                                          3600.0)))
        _LOG.info('chaos: armed engine %s in ~%.1fs (%d fault(s) left)',
                  kind, delay, self._faults_left)

    def submit(self, endpoint, request: Dict[str, Any]):
        with self._lock:
            engine = self.engine
        if engine is None:    # restart backoff window: fail fast
            self._reply_raw(endpoint, {
                'rid': (request or {}).get('rid'), 'engine_fault': True,
                'error': 'inference engine restarting'})
            return
        engine.submit(endpoint, request)

    def stop(self):
        self._stopping = True
        with self._lock:
            engine = self.engine
        if engine is not None:
            engine.stop()

    # -- the watchdog ------------------------------------------------------

    def _watch(self):
        interval = max(0.1, min(1.0, self.stall_timeout / 4))
        while not self._stopping:
            time.sleep(interval)
            with self._lock:
                engine = self.engine
            if engine is None or self._stopping:
                continue
            reason = None
            if engine.crashed is not None or not engine.thread_alive():
                reason = 'crash'
            elif (engine.busy()
                    and engine.progress_age() > self.stall_timeout):
                reason = 'stall'
            if reason is None:
                if time.monotonic() - self._spawned_at > self.RESET_AFTER:
                    self._backoff.reset()
                continue
            self._restart(engine, reason)

    def _restart(self, engine: InferenceEngine, reason: str):
        with self._lock:
            if self.engine is not engine:
                return
            self.engine = None
            self._gen += 1            # zombie replies suppressed from here
        engine.abandon()
        self._served_total += engine.requests_served
        self._batches_total += engine.batches_run
        self._sheds_total += engine.sheds
        # fan-out THROUGH THE RAW reply path: the engine's own (tagged)
        # reply function is already suppressed by the generation bump
        stranded = engine.drain_pending()
        for ep, req, _t in stranded:
            try:
                self._reply_raw(ep, {'rid': (req or {}).get('rid'),
                                     'engine_fault': True,
                                     'error': 'inference engine %s; '
                                              'restarting' % reason})
            except Exception:
                pass
        self.restarts += 1
        self._m_restarts[reason].inc()
        telemetry.record_event('supervisor', 'engine %s declared' % reason,
                               restarts=self.restarts,
                               stranded=len(stranded))
        telemetry.dump_blackbox('engine-' + reason, restarts=self.restarts,
                                stranded=len(stranded))
        delay = self._backoff.next_delay()
        _LOG.warning('engine %s detected (progress %.1fs ago, %d request(s) '
                     'error-answered); restarting in %.1fs',
                     reason, engine.progress_age(), len(stranded), delay)
        time.sleep(delay)
        with self._lock:
            if not self._stopping:
                self.engine = self._spawn()
