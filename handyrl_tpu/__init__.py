"""handyrl_tpu — a TPU-native distributed self-play RL framework.

Capability peer of DeNA/HandyRL (IMPALA-style learner/worker self-play with
TD(lambda) / Monte-Carlo / V-Trace / UPGO off-policy corrections), rebuilt
JAX-first: Flax models, a single jit/pjit-compiled update step over a device
mesh, batched actor inference, and host-side Python only for environments and
orchestration.
"""

__version__ = "0.1.0"
