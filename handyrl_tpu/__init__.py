"""handyrl_tpu — a TPU-native distributed self-play RL framework.

Capability peer of DeNA/HandyRL (IMPALA-style learner/worker self-play with
TD(lambda) / Monte-Carlo / V-Trace / UPGO off-policy corrections), rebuilt
JAX-first: Flax models, a single jit/pjit-compiled update step over a device
mesh, batched actor inference, and host-side Python only for environments and
orchestration.
"""

__version__ = "0.1.0"

# Runtime concurrency sanitizer (analysis/sanitizer.py): opt-in via
# HANDYRL_TPU_SANITIZE=1 — the chaos/e2e CI legs run under it. It must
# install BEFORE any framework lock or thread exists, which is exactly
# import time; unset (the default) this is a single env check and the
# package import stays side-effect free.
import os as _os
if _os.environ.get('HANDYRL_TPU_SANITIZE', '').strip().lower() \
        not in ('', '0', 'false', 'off'):
    from .analysis import sanitizer as _sanitizer
    _sanitizer.install_from_env()

_cache_ready = False


def setup_compile_cache():
    """Enable the persistent XLA compilation cache (explicit, idempotent).

    The recurrent update steps (DRC nets) take minutes of LLVM codegen on
    the CPU backend and tens of seconds on TPU; caching makes every compile
    a one-time cost across processes and runs. Called from the framework's
    own entry points (CLI, Learner, spawned workers, bench, tests) — NOT at
    package import, so embedding applications keep full control of jax
    config and ``import handyrl_tpu`` stays side-effect free. If the
    operator already configured a cache dir (JAX_COMPILATION_CACHE_DIR or
    jax.config), their setup is left untouched. Opt out entirely with
    HANDYRL_TPU_NO_COMPILE_CACHE=1.
    """
    global _cache_ready
    import os
    if _cache_ready or os.environ.get('HANDYRL_TPU_NO_COMPILE_CACHE'):
        return
    _cache_ready = True
    try:
        import jax

        if jax.config.jax_compilation_cache_dir:
            return   # operator already chose a cache; leave it alone
        # Scope the cache per MACHINE: XLA:CPU cache entries are AOT
        # executables specialized to the compiling host's CPU features, and
        # loading one compiled elsewhere can SIGILL/segfault ("machine type
        # used for compilation doesn't match the machine type for
        # execution"). A home dir shared across containers/hosts must not
        # share entries, so the path embeds a CPU-capability fingerprint.
        import hashlib
        import platform
        try:
            with open('/proc/cpuinfo') as f:
                # x86 calls the capability line 'flags', ARM 'Features';
                # mix in the machine arch so hosts without either line
                # still separate by ISA
                caps = [l for l in f
                        if l.startswith(('flags', 'Features'))][:1]
        except OSError:
            caps = []
        fp = hashlib.sha1(
            (platform.machine() + ''.join(caps)).encode()).hexdigest()[:12]
        cache_dir = os.path.join(os.path.expanduser('~'), '.cache',
                                 'handyrl_tpu_xla', fp)
        jax.config.update('jax_compilation_cache_dir', cache_dir)
        # cache across backends including CPU, and even quick compiles —
        # the test suite and bench re-trace the same programs constantly
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.5)
    except Exception:  # pragma: no cover - cache is best-effort
        pass


def honor_platform_env():
    """Re-assert an explicit JAX_PLATFORMS choice over the axon site hook.

    The axon TPU plugin's site registration overrides jax_platforms at
    import time, so the env var alone does not stick in this image; every
    entry point that wants to honor an operator's JAX_PLATFORMS=cpu (tests,
    benches, measurement scripts) calls this instead of hand-rolling the
    re-assert. 'axon' itself (or unset) is left to the site default.
    """
    import os
    plat = os.environ.get('JAX_PLATFORMS', '').strip()
    if plat and plat != 'axon':
        import jax
        jax.config.update('jax_platforms', plat)
