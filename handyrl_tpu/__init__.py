"""handyrl_tpu — a TPU-native distributed self-play RL framework.

Capability peer of DeNA/HandyRL (IMPALA-style learner/worker self-play with
TD(lambda) / Monte-Carlo / V-Trace / UPGO off-policy corrections), rebuilt
JAX-first: Flax models, a single jit/pjit-compiled update step over a device
mesh, batched actor inference, and host-side Python only for environments and
orchestration.
"""

__version__ = "0.1.0"

import os as _os

# Persistent XLA compilation cache: the recurrent update steps (DRC nets)
# take minutes of LLVM codegen on the CPU backend and tens of seconds on
# TPU; caching makes every compile a one-time cost across processes and
# runs. Opt out with HANDYRL_TPU_NO_COMPILE_CACHE=1.
if not _os.environ.get('HANDYRL_TPU_NO_COMPILE_CACHE'):
    _cache_dir = _os.environ.get(
        'JAX_COMPILATION_CACHE_DIR',
        _os.path.join(_os.path.expanduser('~'), '.cache', 'handyrl_tpu_xla'))
    _os.environ.setdefault('JAX_COMPILATION_CACHE_DIR', _cache_dir)
    try:
        import jax as _jax

        _jax.config.update('jax_compilation_cache_dir', _cache_dir)
        # cache across backends including CPU, and even quick compiles —
        # the test suite and bench re-trace the same programs constantly
        _jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.5)
    except Exception:  # pragma: no cover - cache is best-effort
        pass
