"""Hungry Geese net.

Capability peer of the reference GeeseNet (hungry_geese.py:38-57): 12
residual torus-conv blocks over the 17x7x11 board encoding; policy read out
at the acting goose's head cell, value from head + global average pooling.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from . import register
from .blocks import ConvLSTMCell, TorusConv, to_nhwc


@register('GeeseNetLSTM')
class GeeseNetLSTM(nn.Module):
    """Recurrent Hungry Geese net (the LSTM-era baseline configuration,
    BASELINE.md row 4): torus-conv stem, ConvLSTM core carrying state across
    plies, the same head readout as GeeseNet."""
    filters: int = 32
    stem_layers: int = 4
    norm_kind: str = 'group'
    torus_impl: str = 'pad'
    dtype: jnp.dtype = jnp.float32

    def init_hidden(self, batch_shape=()):
        # distinct arrays per leaf: donating consumers hand the tree to
        # XLA, which refuses to donate one buffer twice
        shape = tuple(batch_shape) + (7, 11, self.filters)
        return (jnp.zeros(shape, self.dtype), jnp.zeros(shape, self.dtype))

    @nn.compact
    def __call__(self, obs, hidden, train: bool = False):
        x = to_nhwc(obs)
        h = nn.relu(TorusConv(self.filters, norm_kind=self.norm_kind,
                              impl=self.torus_impl, dtype=self.dtype)(x, train))
        for _ in range(self.stem_layers):
            h = nn.relu(h + TorusConv(self.filters, norm_kind=self.norm_kind,
                                      impl=self.torus_impl,
                                      dtype=self.dtype)(h, train))
        if hidden is None:
            hidden = self.init_hidden(h.shape[:-3])
        h, next_hidden = ConvLSTMCell(self.filters, dtype=self.dtype)(h, hidden)

        head_mask = x[..., :1]
        h_head = (h * head_mask).sum(axis=(-3, -2))
        h_avg = h.mean(axis=(-3, -2))
        policy = nn.Dense(4, use_bias=False, dtype=self.dtype)(h_head)
        value = jnp.tanh(nn.Dense(1, use_bias=False, dtype=self.dtype)(
            jnp.concatenate([h_head, h_avg], axis=-1)))
        return {'policy': policy, 'value': value, 'hidden': next_hidden}


@register('GeeseNet')
class GeeseNet(nn.Module):
    filters: int = 32
    layers: int = 12
    # 'batch' = the reference TorusConv2d's nn.BatchNorm2d in the stem +
    # all 12 blocks (reference hungry_geese.py:23-35,43-44) with full
    # running-average semantics; default follows the measured A/B verdict
    # in BENCHMARKS.md (the round-4 Geister forensics flipped the burden
    # of proof onto GroupNorm for this net too).
    norm_kind: str = 'group'
    # 'halo' computes the identical torus conv without materializing the
    # wrap-padded activation (blocks.TorusConv docstring / round-5 per-op
    # HBM table); parity pinned by tests/test_torus_halo.py.
    # 'pallas' fuses the WHOLE trunk (stem + all blocks) into one kernel
    # that keeps activations in VMEM (ops/pallas_geese.py); same param
    # tree, parity pinned by tests/test_pallas_geese.py. GroupNorm only.
    torus_impl: str = 'pad'
    pallas_tile: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs, hidden=None, train: bool = False):
        x = to_nhwc(obs)                       # (..., 7, 11, 17)
        if self.torus_impl == 'pallas':
            h = self._pallas_trunk(x)
        else:
            h = nn.relu(TorusConv(self.filters, norm_kind=self.norm_kind,
                                  impl=self.torus_impl,
                                  dtype=self.dtype)(x, train))
            for _ in range(self.layers):
                h = nn.relu(h + TorusConv(self.filters,
                                          norm_kind=self.norm_kind,
                                          impl=self.torus_impl,
                                          dtype=self.dtype)(h, train))

        # pool features at the acting goose's head cell (channel 0 of obs)
        head_mask = x[..., :1]                 # (..., 7, 11, 1)
        h_head = (h * head_mask).sum(axis=(-3, -2))   # (..., F)
        h_avg = h.mean(axis=(-3, -2))                 # (..., F)

        policy = nn.Dense(4, use_bias=False, dtype=self.dtype)(h_head)
        value = jnp.tanh(nn.Dense(1, use_bias=False, dtype=self.dtype)(
            jnp.concatenate([h_head, h_avg], axis=-1)))
        return {'policy': policy, 'value': value}

    def _pallas_trunk(self, x):
        """Route the trunk through the fused VMEM kernel. The Flax
        TorusConv stack still OWNS the params (each submodule is touched
        once on a dummy sample — dead code XLA eliminates — so the param
        tree is identical to the other impls); the kernel reads them."""
        from ..ops.pallas_geese import trunk_apply, trunk_params_from_geesenet
        if self.norm_kind != 'group':
            raise ValueError("torus_impl='pallas' implements GroupNorm "
                             "only (norm_kind=%r)" % (self.norm_kind,))
        convs = [TorusConv(self.filters, norm_kind=self.norm_kind,
                           dtype=self.dtype)
                 for _ in range(self.layers + 1)]
        for i, conv in enumerate(convs):
            cin = x.shape[-1] if i == 0 else self.filters
            conv(jnp.zeros((1, 7, 11, cin), self.dtype))
        kp = trunk_params_from_geesenet(
            {'TorusConv_%d' % i: c.variables['params']
             for i, c in enumerate(convs)}, layers=self.layers)
        lead = x.shape[:-3]
        xf = x.reshape((-1,) + x.shape[-3:]).astype(self.dtype)
        n = xf.shape[0]
        tile = min(self.pallas_tile, n)
        pad = (-n) % tile
        if pad:
            xf = jnp.concatenate(
                [xf, jnp.zeros((pad,) + xf.shape[1:], xf.dtype)], axis=0)
        groups = min(8, self.filters)
        # Mosaic lowering needs the TPU; everywhere else (CPU tests,
        # virtual-device meshes) the kernel runs in interpret mode.
        # HANDYRL_PALLAS_INTERPRET=1 forces interpret anywhere (e.g.
        # CPU-placed execution on a TPU host, debugging a Mosaic crash).
        import os

        import jax
        interpret = (jax.default_backend() != 'tpu'
                     or os.environ.get('HANDYRL_PALLAS_INTERPRET') == '1')
        h = trunk_apply(xf, *kp, groups, tile, interpret)
        return h[:n].reshape(lead + h.shape[1:])
