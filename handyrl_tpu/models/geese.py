"""Hungry Geese net.

Capability peer of the reference GeeseNet (hungry_geese.py:38-57): 12
residual torus-conv blocks over the 17x7x11 board encoding; policy read out
at the acting goose's head cell, value from head + global average pooling.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from . import register
from .blocks import ConvLSTMCell, TorusConv, to_nhwc


@register('GeeseNetLSTM')
class GeeseNetLSTM(nn.Module):
    """Recurrent Hungry Geese net (the LSTM-era baseline configuration,
    BASELINE.md row 4): torus-conv stem, ConvLSTM core carrying state across
    plies, the same head readout as GeeseNet."""
    filters: int = 32
    stem_layers: int = 4
    norm_kind: str = 'group'
    torus_impl: str = 'pad'
    dtype: jnp.dtype = jnp.float32

    def init_hidden(self, batch_shape=()):
        # distinct arrays per leaf: donating consumers hand the tree to
        # XLA, which refuses to donate one buffer twice
        shape = tuple(batch_shape) + (7, 11, self.filters)
        return (jnp.zeros(shape, self.dtype), jnp.zeros(shape, self.dtype))

    @nn.compact
    def __call__(self, obs, hidden, train: bool = False):
        x = to_nhwc(obs)
        h = nn.relu(TorusConv(self.filters, norm_kind=self.norm_kind,
                              impl=self.torus_impl, dtype=self.dtype)(x, train))
        for _ in range(self.stem_layers):
            h = nn.relu(h + TorusConv(self.filters, norm_kind=self.norm_kind,
                                      impl=self.torus_impl,
                                      dtype=self.dtype)(h, train))
        if hidden is None:
            hidden = self.init_hidden(h.shape[:-3])
        h, next_hidden = ConvLSTMCell(self.filters, dtype=self.dtype)(h, hidden)

        head_mask = x[..., :1]
        h_head = (h * head_mask).sum(axis=(-3, -2))
        h_avg = h.mean(axis=(-3, -2))
        policy = nn.Dense(4, use_bias=False, dtype=self.dtype)(h_head)
        value = jnp.tanh(nn.Dense(1, use_bias=False, dtype=self.dtype)(
            jnp.concatenate([h_head, h_avg], axis=-1)))
        return {'policy': policy, 'value': value, 'hidden': next_hidden}


@register('GeeseNet')
class GeeseNet(nn.Module):
    filters: int = 32
    layers: int = 12
    # 'batch' = the reference TorusConv2d's nn.BatchNorm2d in the stem +
    # all 12 blocks (reference hungry_geese.py:23-35,43-44) with full
    # running-average semantics; default follows the measured A/B verdict
    # in BENCHMARKS.md (the round-4 Geister forensics flipped the burden
    # of proof onto GroupNorm for this net too).
    norm_kind: str = 'group'
    # 'halo' computes the identical torus conv without materializing the
    # wrap-padded activation (blocks.TorusConv docstring / round-5 per-op
    # HBM table); parity pinned by tests/test_torus_halo.py.
    torus_impl: str = 'pad'
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs, hidden=None, train: bool = False):
        x = to_nhwc(obs)                       # (..., 7, 11, 17)
        h = nn.relu(TorusConv(self.filters, norm_kind=self.norm_kind,
                              impl=self.torus_impl, dtype=self.dtype)(x, train))
        for _ in range(self.layers):
            h = nn.relu(h + TorusConv(self.filters, norm_kind=self.norm_kind,
                                      impl=self.torus_impl,
                                      dtype=self.dtype)(h, train))

        # pool features at the acting goose's head cell (channel 0 of obs)
        head_mask = x[..., :1]                 # (..., 7, 11, 1)
        h_head = (h * head_mask).sum(axis=(-3, -2))   # (..., F)
        h_avg = h.mean(axis=(-3, -2))                 # (..., F)

        policy = nn.Dense(4, use_bias=False, dtype=self.dtype)(h_head)
        value = jnp.tanh(nn.Dense(1, use_bias=False, dtype=self.dtype)(
            jnp.concatenate([h_head, h_avg], axis=-1)))
        return {'policy': policy, 'value': value}
