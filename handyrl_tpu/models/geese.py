"""Hungry Geese net.

Capability peer of the reference GeeseNet (hungry_geese.py:38-57): 12
residual torus-conv blocks over the 17x7x11 board encoding; policy read out
at the acting goose's head cell, value from head + global average pooling.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from . import register
from .blocks import TorusConv, to_nhwc


@register('GeeseNet')
class GeeseNet(nn.Module):
    filters: int = 32
    layers: int = 12
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs, hidden=None):
        x = to_nhwc(obs)                       # (..., 7, 11, 17)
        h = nn.relu(TorusConv(self.filters, dtype=self.dtype)(x))
        for _ in range(self.layers):
            h = nn.relu(h + TorusConv(self.filters, dtype=self.dtype)(h))

        # pool features at the acting goose's head cell (channel 0 of obs)
        head_mask = x[..., :1]                 # (..., 7, 11, 1)
        h_head = (h * head_mask).sum(axis=(-3, -2))   # (..., F)
        h_avg = h.mean(axis=(-3, -2))                 # (..., F)

        policy = nn.Dense(4, use_bias=False, dtype=self.dtype)(h_head)
        value = jnp.tanh(nn.Dense(1, use_bias=False, dtype=self.dtype)(
            jnp.concatenate([h_head, h_avg], axis=-1)))
        return {'policy': policy, 'value': value}
