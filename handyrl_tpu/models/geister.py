"""Geister net: recurrent DRC (Deep Repeated ConvLSTM) policy/value/return.

Capability peer of the reference GeisterNet (geister.py:131-167): scalar
features broadcast onto the 6x6 board, conv stem, 3-layer x 3-repeat DRC
body, move policy (4x36) + setup policy (70) heads, tanh value head and a
separate return head.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from . import register
from .blocks import (ConvBlock, DRC, PolicyHead, ScalarHead,
                     SpatialPolicyHead, to_nhwc)


@register('GeisterNet')
class GeisterNet(nn.Module):
    filters: int = 32
    drc_layers: int = 3
    drc_repeats: int = 3
    # 'batch' = the reference's BatchNorm2d placement (geister.py:107,122)
    # with FULL semantics: current-batch statistics in the training forward
    # (the learning-dynamics ingredient the round-4 forensics proved causal
    # — the reference drops 0.661 -> 0.486 when its BatchNorm is swapped
    # for GroupNorm) plus running averages served on every inference path
    # (reference model.py:54 — self.eval() before inference). The round-4
    # pure-statistics half-measure is kept as 'batchstats' for the record;
    # it measured tied with GroupNorm (0.452 vs 0.466 at ~1k episodes,
    # BENCHMARKS.md). Default follows the measured verdict in BENCHMARKS.md.
    norm_kind: str = 'group'
    # 'dense' = the measured r1-r4 baseline head (1x1 conv -> Dense over
    # the flattened map); 'spatial' = the reference Conv2dHead structure
    # (3x3 conv + norm + relu -> 1x1 conv, 4 logits PER CELL — reference
    # geister.py:100-113,144). The round-5 rescores measured BOTH norm
    # arms flat at ~0.45 vs the reference's 0.661 while its policy stays
    # near-uniform — the spatially-local head is the next suspect: per-
    # cell logits see their own 3x3 neighborhood instead of learning a
    # global 288->144 dense map. Default follows BENCHMARKS.md verdicts.
    policy_head: str = 'dense'
    # 'torch' reproduces the reference framework's default weight
    # distributions (kaiming-uniform kernels, uniform biases —
    # blocks.torch_default_inits); 'flax' is this repo's measured
    # baseline (lecun_normal, zero biases). Initialization is the
    # remaining dynamics suspect for the early-curve Geister gap after
    # norm + head were measured (BENCHMARKS.md).
    init_kind: str = 'flax'
    dtype: jnp.dtype = jnp.float32

    def init_hidden(self, batch_shape=()):
        """Zero DRC state: (hs, cs) lists of (..., 6, 6, F) arrays —
        DISTINCT arrays per leaf (donating consumers may pass the tree to
        XLA, which refuses to donate one buffer twice)."""
        shape = tuple(batch_shape) + (6, 6, self.filters)
        mk = lambda: jnp.zeros(shape, self.dtype)  # noqa: E731
        return ([mk() for _ in range(self.drc_layers)],
                [mk() for _ in range(self.drc_layers)])

    @nn.compact
    def __call__(self, obs, hidden, train: bool = False):
        board = to_nhwc(obs['board'])                    # (..., 6, 6, 7)
        scalar = obs['scalar']                           # (..., 18)
        s_map = jnp.broadcast_to(scalar[..., None, None, :],
                                 board.shape[:-1] + scalar.shape[-1:])
        x = jnp.concatenate([board, s_map], axis=-1)     # (..., 6, 6, 25)

        # 'group' maps the heads to their original 'group1' (num_groups=1)
        # so the default reproduces the measured baseline configuration
        # exactly; only 'batch' switches the heads' statistics
        head_norm = 'group1' if self.norm_kind == 'group' else self.norm_kind
        h = nn.relu(ConvBlock(self.filters, norm_kind=self.norm_kind,
                              init_kind=self.init_kind,
                              dtype=self.dtype)(x, train))
        body = DRC(self.drc_layers, self.filters,
                   num_repeats=self.drc_repeats, init_kind=self.init_kind,
                   dtype=self.dtype)
        if hidden is None:
            hidden = self.init_hidden(h.shape[:-3])
        h, next_hidden = body(h, hidden)

        if self.policy_head == 'spatial':
            p_move = SpatialPolicyHead(8, 4, norm_kind=head_norm,
                                       init_kind=self.init_kind,
                                       dtype=self.dtype)(h, train)
        else:
            p_move = PolicyHead(8, 4 * 36, init_kind=self.init_kind,
                                dtype=self.dtype)(h)
        # setup-phase logits conditioned only on the side-to-move bit
        turn_color = scalar[..., :1]
        from .blocks import dense_inits
        p_set = nn.Dense(70, dtype=self.dtype,
                         **dense_inits(self.init_kind, 1))(turn_color)
        policy = jnp.concatenate([p_move, p_set], axis=-1)

        value = jnp.tanh(ScalarHead(2, 1, norm_kind=head_norm,
                                    init_kind=self.init_kind,
                                    dtype=self.dtype)(h, train))
        ret = ScalarHead(2, 1, norm_kind=head_norm,
                         init_kind=self.init_kind,
                         dtype=self.dtype)(h, train)
        return {'policy': policy, 'value': value, 'return': ret,
                'hidden': next_hidden}
