"""Flax model zoo.

Registry maps architecture names to constructors so model snapshots can be
shipped over the wire as (name, flat params) instead of pickled code objects
(the reference pickles whole nn.Modules — train.py:615; we deliberately
don't).
"""

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register(name: str):
    def deco(ctor):
        _REGISTRY[name] = ctor
        return ctor
    return deco


def build(name: str, **kwargs):
    if name not in _REGISTRY:
        # lazily import the built-in model modules, which self-register
        from . import (tictactoe, geister, geese, transformer,  # noqa: F401
                       connect_four)
    return _REGISTRY[name](**kwargs)


def architecture_name(module) -> str:
    return type(module).__name__
