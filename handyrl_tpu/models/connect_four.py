"""ConnectX (Connect Four) policy/value net.

Same family as the TicTacToe SimpleConv2dModel — stem conv + normalized
conv blocks over the (3, 6, 7) plane codec, a 7-way column policy head,
tanh value head. The 6x7 board carries longer tactical lines than 3x3, so
the trunk is one block deeper by default.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from . import register
from .blocks import ConvBlock, PolicyHead, ScalarHead, to_nhwc


@register('ConnectFourNet')
class ConnectFourNet(nn.Module):
    filters: int = 32
    layers: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs, hidden=None):
        x = to_nhwc(obs)
        h = nn.relu(nn.Conv(self.filters, (3, 3), padding='SAME',
                            dtype=self.dtype)(x))
        for _ in range(self.layers):
            h = nn.relu(ConvBlock(self.filters, dtype=self.dtype)(h))
        policy = PolicyHead(2, 7, dtype=self.dtype)(h)
        value = jnp.tanh(ScalarHead(1, 1, dtype=self.dtype)(h))
        return {'policy': policy, 'value': value}
