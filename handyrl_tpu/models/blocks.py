"""Shared Flax building blocks.

Model convention (framework-wide):
  * ``module(obs, hidden)`` returns a dict with 'policy' (logits over the
    action space), optionally 'value' / 'return' (shape (..., 1)), and
    'hidden' (next recurrent state pytree) for RNNs.
  * observations arrive channel-first (C, H, W) exactly as environments emit
    them (parity with the reference protocol); blocks transpose to NHWC at
    the input edge because that is the layout XLA tiles best onto the MXU.
  * normalization defaults to GroupNorm (stateless — nothing mutable to
    thread through lax.scan or checkpoints, no cross-chip batch-stat sync);
    nets that measurably need the reference's BatchNorm learning dynamics
    (GeisterNet — the round-4 forensics) take ``norm_kind='batch'``, a full
    flax nn.BatchNorm whose ``batch_stats`` collection the trainer threads
    through the forward (ops/losses.py) and whose running averages every
    inference path reads via the plain ``module.apply`` default.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


def to_nhwc(x: jnp.ndarray) -> jnp.ndarray:
    """(..., C, H, W) -> (..., H, W, C)."""
    return jnp.moveaxis(x, -3, -1)


def torch_default_inits(fan_in: int):
    """(kernel_init, bias_init) mirroring torch's Conv2d/Linear defaults:
    kaiming_uniform(a=sqrt(5)) == uniform(+-1/sqrt(fan_in)) for the kernel
    (std 1.73x SMALLER than flax's lecun_normal default) and
    uniform(+-1/sqrt(fan_in)) for the bias (flax default: zeros). fan_in
    counts receptive field x channels for convs, in_features for dense.
    An init-dynamics knob for the Geister early-curve investigation —
    weight DISTRIBUTIONS differ between frameworks even when every
    architectural choice matches (torch nn/init kaiming_uniform +
    Conv2d/Linear reset_parameters semantics)."""
    kernel = nn.initializers.variance_scaling(1.0 / 3.0, 'fan_in', 'uniform')
    bound = 1.0 / (fan_in ** 0.5)

    def bias(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return kernel, bias


def conv_inits(init_kind: str, in_ch: int, kernel: int) -> dict:
    """kwargs for nn.Conv under the given init regime ('flax' = defaults)."""
    if init_kind == 'flax':
        return {}
    if init_kind == 'torch':
        k, b = torch_default_inits(in_ch * kernel * kernel)
        return {'kernel_init': k, 'bias_init': b}
    raise ValueError('unknown init_kind %r' % (init_kind,))


def dense_inits(init_kind: str, in_features: int) -> dict:
    """kwargs for nn.Dense under the given init regime."""
    if init_kind == 'flax':
        return {}
    if init_kind == 'torch':
        k, b = torch_default_inits(in_features)
        return {'kernel_init': k, 'bias_init': b}
    raise ValueError('unknown init_kind %r' % (init_kind,))


class BatchStatsNorm(nn.Module):
    """(norm_kind='batchstats' — the round-4 investigation variant, kept
    for the A/B record; 'batch' is now full nn.BatchNorm with running
    averages.) Train-mode BatchNorm semantics as a PURE function: per-channel
    normalization by the CURRENT batch's statistics over every non-channel
    axis, with learned scale/bias — no running averages, so nothing
    mutable threads through scan/jit/checkpoints.

    Why it exists: the round-4 Geister quality forensics measured the
    GroupNorm-for-BatchNorm substitution as THE cause of the quality gap
    vs the reference (its nn.BatchNorm2d stem/heads, reference
    geister.py:107,122 — swap them for GroupNorm and the reference drops
    from 0.661 to 0.486 at ~1k episodes, exactly this repo's level; see
    BENCHMARKS.md). Batch statistics in the training forward are the
    learning-dynamics ingredient; this block provides them without
    running-stats state.

    Inference caveats: the training/benchmark paths (device + batched
    evaluators and generators) run batched env vectors, so inference
    statistics match training's regime. The SEQUENTIAL host paths —
    worker-mode Evaluator/exec_match and NetworkAgent (evaluation.py) —
    infer at B=1, where this block degrades to per-sample (instance)
    statistics: a different network function than trained (the torch
    reference uses running averages there instead). Window-tail pad rows
    also enter the statistics during training, exactly as they entered
    the reference's train-mode BatchNorm.
    """
    dtype: jnp.dtype = jnp.float32
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        axes = tuple(range(x.ndim - 1))
        # statistics in float32 regardless of activation dtype (bf16
        # mean/var over ~1k elements loses the variance to cancellation;
        # flax's own norm layers upcast the same way)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        y = ((xf - mean) / jnp.sqrt(var + self.eps)).astype(self.dtype)
        scale = self.param('scale', nn.initializers.ones, (c,), self.dtype)
        bias = self.param('bias', nn.initializers.zeros, (c,), self.dtype)
        return y * scale + bias


def make_norm(kind: str, filters: int, dtype, train: bool = False) -> nn.Module:
    """'group' (stateless default) | 'batch' (FULL reference-parity
    BatchNorm: current-batch statistics in the training forward, running
    averages served at inference — matches the reference's nn.BatchNorm2d
    train/eval split, reference geister.py:107,122 + model.py:54) |
    'batchstats' (the round-4 pure investigation variant above, batch
    statistics with NO running averages) | 'layer'.

    'batch' carries a mutable ``batch_stats`` collection: the training
    forward must apply with ``mutable=['batch_stats']`` and ``train=True``
    (ops/losses.py threads it, incl. through the recurrent scan); every
    other apply reads the running averages, so the sequential B=1 host
    paths (worker-mode Evaluator, NetworkAgent) see the SAME network
    function as the batched ones — the trap BatchStatsNorm had.

    torch-parity notes: momentum 0.9 here == torch's 0.1 (flax weights the
    old average, torch the new term); eps 1e-5 matches; flax updates the
    running variance with the biased estimator where torch uses unbiased —
    an O(1/batch-elements) difference, negligible at conv feature-map
    sizes."""
    if kind == 'batch':
        return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                            epsilon=1e-5, dtype=dtype)
    if kind == 'batchstats':
        return BatchStatsNorm(dtype=dtype)
    if kind == 'layer':
        return nn.LayerNorm(dtype=dtype)
    if kind == 'group':
        return nn.GroupNorm(num_groups=min(8, filters), dtype=dtype)
    if kind == 'group1':   # the heads' single-group flavor
        return nn.GroupNorm(num_groups=1, dtype=dtype)
    # never fall back silently: a typo'd kind reinstating GroupNorm would
    # quietly reintroduce the exact regression 'batch' exists to fix
    raise ValueError('unknown norm kind %r' % (kind,))


class ConvBlock(nn.Module):
    """3x3 conv + optional normalization, operating on NHWC."""
    filters: int
    kernel: int = 3
    norm: bool = True
    norm_kind: str = 'group'
    init_kind: str = 'flax'
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.filters, (self.kernel, self.kernel), padding='SAME',
                    use_bias=not self.norm, dtype=self.dtype,
                    **conv_inits(self.init_kind, x.shape[-1], self.kernel))(x)
        if self.norm:
            x = make_norm(self.norm_kind, self.filters, self.dtype, train)(x)
        return x


class TorusConv(nn.Module):
    """Conv with wrap-around (toroidal) padding, NHWC.

    TPU-native counterpart of the reference's TorusConv2d
    (hungry_geese.py:23-35). Two mathematically identical implementations
    (pinned against each other by tests/test_torus_halo.py):

    * ``impl='pad'``: jnp.pad(mode='wrap') then a VALID conv. Simple, but
      the wrap-pad materializes a padded copy of the full activation in
      HBM for every block — the round-5 per-op table showed these
      copies/slices as the largest single HBM consumers of the GeeseNet
      update step (BENCHMARKS.md round-5 chip window).
    * ``impl='halo'``: the conv runs with XLA window padding (zero-pad
      folded into the conv HLO — no materialized pad), and the missing
      wrapped contributions are added back exactly: kernel-row strips for
      the top/bottom output rows, kernel-column strips for the left/right
      output columns, and the four diagonal corner taps. All correction
      operands are 1-row/1-col strips, so the full-tensor pad copy never
      exists.

    Both impls share the same param tree ('Conv_0' kernel/bias), so
    checkpoints transfer and an A/B is config-only."""
    filters: int
    kernel: int = 3
    norm: bool = True
    norm_kind: str = 'group'
    impl: str = 'pad'
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        kh, kw = self.kernel // 2, self.kernel // 2
        conv_padding = ('VALID' if self.impl == 'pad'
                        else ((kh, kh), (kw, kw)))
        conv = nn.Conv(self.filters, (self.kernel, self.kernel),
                       padding=conv_padding, use_bias=not self.norm,
                       dtype=self.dtype)
        if self.impl == 'pad':
            pad = [(0, 0)] * (x.ndim - 3) + [(kh, kh), (kw, kw), (0, 0)]
            x = conv(jnp.pad(x, pad, mode='wrap'))
        elif self.impl == 'halo':
            if self.kernel != 3:
                raise ValueError('halo impl is written for 3x3 kernels '
                                 '(got %d)' % (self.kernel,))
            x = _halo_correct(conv(x), x, conv, self.dtype)
        else:
            raise ValueError('unknown TorusConv impl %r' % (self.impl,))
        if self.norm:
            x = make_norm(self.norm_kind, self.filters, self.dtype, train)(x)
        return x


def _halo_correct(y, x, conv: nn.Conv, dtype) -> jnp.ndarray:
    """Add the wrapped-edge contributions a zero-padded 3x3 conv omitted.

    y: conv(x) with window padding (1,1),(1,1); x: (..., H, W, C) NHWC.
    Every omitted term has a source index out of range in rows, columns,
    or both; the three classes are reinstated separately:

      rows    output row 0 misses kernel-row-0 terms sourced from row H-1
              (and symmetrically row H-1 / kernel row 2 / source row 0),
              with IN-RANGE columns -> a 1-row conv, columns zero-padded;
      cols    symmetric with kernel columns;
      corners output (0,0) misses only the (di,dj)=(-1,-1) tap sourced at
              (H-1, W-1) -> one C x F contraction per corner.
    """
    w = conv.variables['params']['kernel'].astype(dtype)   # (3, 3, C, F)
    x = x.astype(dtype)
    lead, (H, W, C) = x.shape[:-3], x.shape[-3:]
    F = w.shape[-1]
    x4 = x.reshape((-1, H, W, C))
    dn = jax.lax.conv_dimension_numbers(
        x4.shape, w.shape, ('NHWC', 'HWIO', 'NHWC'))

    def strip_conv(src, kern, padding):
        out = jax.lax.conv_general_dilated(
            src, kern, (1, 1), padding, dimension_numbers=dn)
        return out.reshape(lead + out.shape[1:])

    # row wraps: single source row, single kernel row, columns zero-padded
    top = strip_conv(x4[:, H - 1:H], w[0:1], ((0, 0), (1, 1)))  # (..,1,W,F)
    bot = strip_conv(x4[:, 0:1], w[2:3], ((0, 0), (1, 1)))
    # column wraps: single source column, single kernel column
    left = strip_conv(x4[:, :, W - 1:], w[:, 0:1], ((1, 1), (0, 0)))
    right = strip_conv(x4[:, :, 0:1], w[:, 2:3], ((1, 1), (0, 0)))

    corner = lambda i, j, ki, kj: jnp.tensordot(
        x[..., i, j, :], w[ki, kj], axes=1)               # (..., F)

    y = y.at[..., 0, :, :].add(top[..., 0, :, :])
    y = y.at[..., H - 1, :, :].add(bot[..., 0, :, :])
    y = y.at[..., :, 0, :].add(left[..., :, 0, :])
    y = y.at[..., :, W - 1, :].add(right[..., :, 0, :])
    y = y.at[..., 0, 0, :].add(corner(H - 1, W - 1, 0, 0))
    y = y.at[..., 0, W - 1, :].add(corner(H - 1, 0, 0, 2))
    y = y.at[..., H - 1, 0, :].add(corner(0, W - 1, 2, 0))
    y = y.at[..., H - 1, W - 1, :].add(corner(0, 0, 2, 2))
    return y


class SpatialPolicyHead(nn.Module):
    """Per-cell policy logits with the reference Conv2dHead's structure
    (reference geister.py:100-113): 3x3 conv (no bias) + norm + relu, then
    a 1x1 conv emitting ``out_filters`` logits PER CELL, flattened
    channel-major so logit index = f*H*W + x*W + y — the '4 x 36' move
    encoding. The spatial parameterization is the head's point: each
    cell's logits come from its own 3x3 neighborhood (a strong inductive
    bias for per-piece directional moves) instead of a global dense map.
    """
    filters: int
    out_filters: int
    norm_kind: str = 'group1'
    init_kind: str = 'flax'
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Conv(self.filters, (3, 3), padding='SAME', use_bias=False,
                    dtype=self.dtype,
                    **conv_inits(self.init_kind, x.shape[-1], 3))(x)
        h = make_norm(self.norm_kind, self.filters, self.dtype, train)(h)
        h = nn.relu(h)
        h = nn.Conv(self.out_filters, (1, 1), dtype=self.dtype,
                    **conv_inits(self.init_kind, self.filters, 1))(h)
        h = jnp.moveaxis(h, -1, -3)            # (..., F, H, W)
        return h.reshape(*h.shape[:-3], -1)


class PolicyHead(nn.Module):
    """1x1 conv squeeze -> leaky-relu -> dense logits (no bias)."""
    out_filters: int
    outputs: int
    init_kind: str = 'flax'
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.out_filters, (1, 1), dtype=self.dtype,
                    **conv_inits(self.init_kind, x.shape[-1], 1))(x)
        h = nn.leaky_relu(h, negative_slope=0.1)
        h = h.reshape(*h.shape[:-3], -1)
        return nn.Dense(self.outputs, use_bias=False, dtype=self.dtype,
                        **dense_inits(self.init_kind, h.shape[-1]))(h)


class ScalarHead(nn.Module):
    """1x1 conv + norm + relu -> dense scalar(s) (no bias)."""
    filters: int
    outputs: int = 1
    norm_kind: str = 'group1'
    init_kind: str = 'flax'
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                    **conv_inits(self.init_kind, x.shape[-1], 1))(x)
        h = make_norm(self.norm_kind, self.filters, self.dtype, train)(h)
        h = nn.relu(h)
        h = h.reshape(*h.shape[:-3], -1)
        return nn.Dense(self.outputs, use_bias=False, dtype=self.dtype,
                        **dense_inits(self.init_kind, h.shape[-1]))(h)


class ConvLSTMCell(nn.Module):
    """Convolutional LSTM cell on NHWC feature maps.

    State is an (h, c) tuple with shape (..., H, W, F). Gates come from one
    fused convolution over [x, h] — a single large MXU matmul per step.
    """
    features: int
    kernel: int = 3
    init_kind: str = 'flax'
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, state):
        h_prev, c_prev = state
        xin = jnp.concatenate([x, h_prev], axis=-1)
        gates = nn.Conv(4 * self.features, (self.kernel, self.kernel),
                        padding='SAME', dtype=self.dtype,
                        **conv_inits(self.init_kind, xin.shape[-1],
                                     self.kernel))(xin)
        i, f, o, g = jnp.split(gates, 4, axis=-1)
        c = nn.sigmoid(f) * c_prev + nn.sigmoid(i) * jnp.tanh(g)
        h = nn.sigmoid(o) * jnp.tanh(c)
        return h, (h, c)


class DRC(nn.Module):
    """Deep Repeated ConvLSTM (Guez et al. 2019, arXiv:1901.03559).

    ``num_layers`` stacked ConvLSTM cells applied ``num_repeats`` times per
    observation; layer i>0 consumes layer i-1's fresh hidden state. Hidden
    state: tuple(list_h, list_c) with NHWC leaves.
    """
    num_layers: int = 3
    features: int = 32
    kernel: int = 3
    num_repeats: int = 3
    init_kind: str = 'flax'
    dtype: jnp.dtype = jnp.float32

    def initial_state(self, spatial: Sequence[int], batch_shape=()):
        shape = tuple(batch_shape) + tuple(spatial) + (self.features,)
        zeros = jnp.zeros(shape, self.dtype)
        hs = [zeros for _ in range(self.num_layers)]
        cs = [zeros for _ in range(self.num_layers)]
        return (hs, cs)

    @nn.compact
    def __call__(self, x, state):
        if state is None:
            state = self.initial_state(x.shape[-3:-1], x.shape[:-3])
        cells = [ConvLSTMCell(self.features, self.kernel,
                              init_kind=self.init_kind, dtype=self.dtype)
                 for _ in range(self.num_layers)]
        hs, cs = list(state[0]), list(state[1])
        for _ in range(self.num_repeats):
            for i, cell in enumerate(cells):
                inp = x if i == 0 else hs[i - 1]
                _, (hs[i], cs[i]) = cell(inp, (hs[i], cs[i]))
        return hs[-1], (hs, cs)
