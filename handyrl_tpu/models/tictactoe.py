"""TicTacToe policy/value net.

Capability peer of the reference SimpleConv2dModel (tictactoe.py:52-69):
stem conv + 3 normalized conv blocks, 9-way policy head, tanh value head.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from . import register
from .blocks import ConvBlock, PolicyHead, ScalarHead, to_nhwc


@register('SimpleConv2dModel')
class SimpleConv2dModel(nn.Module):
    filters: int = 32
    layers: int = 3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs, hidden=None):
        x = to_nhwc(obs)
        h = nn.relu(nn.Conv(self.filters, (3, 3), padding='SAME', dtype=self.dtype)(x))
        for _ in range(self.layers):
            h = nn.relu(ConvBlock(self.filters, dtype=self.dtype)(h))
        policy = PolicyHead(2, 9, dtype=self.dtype)(h)
        value = jnp.tanh(ScalarHead(1, 1, dtype=self.dtype)(h))
        return {'policy': policy, 'value': value}
