"""Transformer policy net over board cells (attention-based model family).

The reference has no attention models (its nets are conv/ConvLSTM); this
family exists so attention-based policies are first-class, including
long-context execution: set ``mesh``/``ring_axis`` and every attention layer
runs as sequence-parallel ring attention (parallel/ring_attention.py) with
the token axis sharded across devices; unset, it runs ordinary fused
attention on one device.

``GeeseFormer`` instantiates it for Hungry Geese: the 77 board cells become
tokens (channel vector + learned position embedding), K pre-norm transformer
blocks, policy read at the acting goose's head cell, value from head + mean
pooling — the attention analog of GeeseNet's conv trunk.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import linen as nn

from . import register
from ..parallel.ring_attention import full_attention, ring_attention


class SelfAttention(nn.Module):
    heads: int = 4
    dim: int = 64
    mesh: Optional[object] = None
    ring_axis: str = 'model'
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):                     # x: (B, T, F)
        B, T, F = x.shape
        head_dim = self.dim // self.heads
        qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype)(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, self.heads, head_dim)
        k = k.reshape(B, T, self.heads, head_dim)
        v = v.reshape(B, T, self.heads, head_dim)
        if self.mesh is not None:
            out = ring_attention(q, k, v, self.mesh, self.ring_axis)
        else:
            out = full_attention(q, k, v)
        out = out.reshape(B, T, self.dim)
        return nn.Dense(F, use_bias=False, dtype=self.dtype)(out)


class Block(nn.Module):
    heads: int
    dim: int
    mesh: Optional[object] = None
    ring_axis: str = 'model'
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = x + SelfAttention(self.heads, self.dim, self.mesh, self.ring_axis,
                              dtype=self.dtype)(nn.LayerNorm(dtype=self.dtype)(x))
        mlp = nn.Sequential([
            nn.Dense(2 * self.dim, dtype=self.dtype), nn.gelu,
            nn.Dense(x.shape[-1], dtype=self.dtype),
        ])
        return h + mlp(nn.LayerNorm(dtype=self.dtype)(h))


@register('GeeseFormer')
class GeeseFormer(nn.Module):
    """Attention policy/value net for Hungry Geese (obs (..., 17, 7, 11))."""
    dim: int = 64
    layers: int = 4
    heads: int = 4
    pad_to: int = 80          # 77 cells padded so ring shards divide evenly
    mesh: Optional[object] = None
    ring_axis: str = 'model'
    remat: bool = False       # rematerialize blocks: trade FLOPs for HBM
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs, hidden=None):
        single = obs.ndim == 3
        if single:
            obs = obs[None]
        B = obs.shape[0]
        C = obs.shape[1]
        cells = obs.reshape(B, C, -1).transpose(0, 2, 1)       # (B, 77, 17)
        T = cells.shape[1]
        pad = self.pad_to - T
        if pad > 0:
            cells = jnp.pad(cells, ((0, 0), (0, pad), (0, 0)))

        tokens = nn.Dense(self.dim, dtype=self.dtype)(cells)
        pos = self.param('pos_embed', nn.initializers.normal(0.02),
                         (self.pad_to, self.dim))
        tokens = tokens + pos.astype(self.dtype)

        block_cls = nn.remat(Block) if self.remat else Block
        for _ in range(self.layers):
            tokens = block_cls(self.heads, self.dim, self.mesh, self.ring_axis,
                               dtype=self.dtype)(tokens)
        tokens = nn.LayerNorm(dtype=self.dtype)(tokens)

        head_mask = cells[..., :1]               # own-head channel is first
        h_head = (tokens * head_mask).sum(axis=1)
        h_avg = tokens.mean(axis=1)

        policy = nn.Dense(4, use_bias=False, dtype=self.dtype)(h_head)
        value = jnp.tanh(nn.Dense(1, use_bias=False, dtype=self.dtype)(
            jnp.concatenate([h_head, h_avg], axis=-1)))
        if single:
            policy, value = policy[0], value[0]
        return {'policy': policy, 'value': value}
