"""GL005 — vocabulary drift between code and docs, both directions.

PRs 3/7 established that every metric name, span/stage name, and config
knob belongs to ONE documented vocabulary (docs/observability.md, the
stage glossary, docs/parameters.md, config.py defaults + validation). This
checker turns doc rot into a lint failure:

* a metric/stage literal used at a call site but absent from
  docs/observability.md — an undocumented signal nobody will find on a
  dashboard;
* a metric/stage documented in the catalog tables but used nowhere — the
  doc describes a signal that no longer exists;
* a config knob in ``config.py`` defaults missing its docs/parameters.md
  row, or a documented knob with no default — an operator reading the doc
  would set a key nothing reads;
* a key referenced by ``config.validate()`` that is not a known knob — a
  validation rule silently checking nothing.

Everything is static: ``config.py`` is AST-parsed (no package import), the
docs are parsed for backticked tokens, sources for string literals at the
registry call sites. Dynamically constructed names (``key + '_mean'``) are
matched by the documented-name -> source-substring direction with a
``_mean`` suffix fallback.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, SourceFile

_BACKTICK_RE = re.compile(r'`([^`]+)`')


def _doc_tokens(doc: SourceFile) -> Set[str]:
    """Backticked tokens, matched per line (tokens never span lines) with
    triple-backtick fence lines skipped — a ``` delimiter would otherwise
    desync every later pairing in the file."""
    out: Set[str] = set()
    for line in doc.lines:
        if '```' in line:
            continue
        out.update(_BACKTICK_RE.findall(line))
    return out

# registry entry points whose first positional string literal is a metric
_METRIC_CALLS = {'counter', 'gauge', 'histogram'}
# entry points whose first positional string literal is a stage name
_STAGE_CALLS = {'observe_stage', 'trace_span', 'span'}

# package files whose literals are NOT part of the runtime vocabulary
_EXCLUDED_PREFIXES = ('handyrl_tpu/analysis/',)


def _first_str_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _call_name(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def collect_code_vocabulary(sources: Dict[str, SourceFile]
                            ) -> Tuple[Dict[str, Tuple[str, int]],
                                       Dict[str, Tuple[str, int]]]:
    """(metrics, stages): literal name -> first (path, line) using it."""
    metrics: Dict[str, Tuple[str, int]] = {}
    stages: Dict[str, Tuple[str, int]] = {}
    for path, src in sorted(sources.items()):
        if not path.startswith('handyrl_tpu/') \
                or path.startswith(_EXCLUDED_PREFIXES):
            continue
        try:
            tree = ast.parse(src.text)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            lit = _first_str_arg(node)
            if name in _METRIC_CALLS and lit:
                metrics.setdefault(lit, (path, node.lineno))
                for kw in node.keywords:
                    if kw.arg == 'stage' and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        stages.setdefault(kw.value.value, (path, node.lineno))
            elif name in _STAGE_CALLS and lit:
                stages.setdefault(lit, (path, node.lineno))
        # the canonical ingest vocabulary constant (telemetry.INGEST_STAGES)
        if path.endswith('telemetry.py'):
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign) \
                        and any(isinstance(t, ast.Name)
                                and t.id == 'INGEST_STAGES'
                                for t in node.targets):
                    for elt in getattr(node.value, 'elts', []):
                        if isinstance(elt, ast.Constant):
                            stages.setdefault(str(elt.value),
                                              (path, node.lineno))
    return metrics, stages


def collect_alert_names(sources: Dict[str, SourceFile]
                        ) -> Dict[str, Tuple[str, int]]:
    """Alert-rule names: the builtin catalog (telemetry.BUILTIN_ALERTS
    rule dicts' ``name`` values) plus ``name=`` literals handed to
    AlertRule/alert-rule dict constructions at call sites. Each one is an
    operator-facing identifier (``alerts_active{alert=}`` label values,
    metrics_jsonl ``alerts.active`` entries) and must be documented."""
    names: Dict[str, Tuple[str, int]] = {}
    for path, src in sorted(sources.items()):
        if not path.endswith('telemetry.py') \
                or not path.startswith('handyrl_tpu/') \
                or path.startswith(_EXCLUDED_PREFIXES):
            continue
        try:
            tree = ast.parse(src.text)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) \
                    or not any(isinstance(t, ast.Name)
                               and t.id == 'BUILTIN_ALERTS'
                               for t in node.targets):
                continue
            for elt in getattr(node.value, 'elts', []):
                if not isinstance(elt, ast.Dict):
                    continue
                for k, v in zip(elt.keys, elt.values):
                    if isinstance(k, ast.Constant) and k.value == 'name' \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        names.setdefault(v.value, (path, k.lineno))
    return names


# ---------------------------------------------------------------------------
# docs parsing


def _doc_line_of(doc: SourceFile, token: str) -> int:
    needle = '`%s`' % token
    for i, line in enumerate(doc.lines, start=1):
        if needle in line:
            return i
    return 1


def _table_first_cells(doc: SourceFile, section_match=None) -> List[str]:
    """Backticked tokens from the first cell of markdown table rows,
    optionally restricted to sections whose heading matches."""
    tokens: List[str] = []
    in_section = section_match is None
    for line in doc.lines:
        if line.startswith('#'):
            if section_match is not None:
                in_section = bool(section_match(line))
            continue
        if not in_section or not line.startswith('|'):
            continue
        cells = line.split('|')
        if len(cells) < 2:
            continue
        first = cells[1]
        if set(first.strip()) <= set('-: '):
            continue
        tokens.extend(_BACKTICK_RE.findall(first))
    return tokens


# ---------------------------------------------------------------------------
# config.py defaults + validate() knob extraction (pure AST, no import)


def _literal_keys(node: ast.Dict, prefix: str = ''
                  ) -> List[Tuple[str, bool]]:
    """[(dotted key, is_container)]: a container key (dict-valued block
    like ``inference``) is a namespace — its children need doc rows, the
    block name itself does not."""
    keys: List[Tuple[str, bool]] = []
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            continue
        name = prefix + k.value
        is_container = isinstance(v, ast.Dict) and bool(v.keys)
        keys.append((name, is_container))
        if isinstance(v, ast.Dict):
            keys.extend(_literal_keys(v, name + '.'))
    return keys


def _aux_block_keys(sources: Dict[str, 'SourceFile']
                    ) -> List[Tuple[str, bool]]:
    """The ``telemetry`` block's canonical defaults live in
    telemetry.TELEMETRY_DEFAULTS (config.py keeps the legacy bool); fold
    them in as ``telemetry.<key>`` knobs."""
    src = sources.get('handyrl_tpu/telemetry.py')
    if src is None:
        return []
    try:
        tree = ast.parse(src.text)
    except SyntaxError:
        return []
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            if any(isinstance(t, ast.Name) and t.id == 'TELEMETRY_DEFAULTS'
                   for t in targets) and isinstance(value, ast.Dict):
                return _literal_keys(value, 'telemetry.')
    return []


def collect_config_keys(config_src: SourceFile
                        ) -> Tuple[List[str], List[Tuple[str, int]]]:
    """([(dotted default key, is_container)], [(validated key literal,
    line), ...])."""
    try:
        tree = ast.parse(config_src.text)
    except SyntaxError:
        return [], []
    keys: List[Tuple[str, bool]] = []
    validated: List[Tuple[str, int]] = []
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name) \
                        and tgt.id in ('TRAIN_DEFAULTS', 'WORKER_DEFAULTS') \
                        and isinstance(node.value, ast.Dict):
                    keys.extend(_literal_keys(node.value))
        if isinstance(node, ast.FunctionDef) and node.name == 'validate':
            # knob references through the block aliases validate() uses
            _BLOCKS = {'ta', 'ft', 'inf', 'g', 'tel', 'par', 'srv', 'flt',
                       'lg', 'gen'}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == 'get' \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id in _BLOCKS:
                    lit = _first_str_arg(sub)
                    if lit:
                        validated.append((lit, sub.lineno))
                elif isinstance(sub, ast.Subscript) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id in _BLOCKS \
                        and isinstance(sub.slice, ast.Constant) \
                        and isinstance(sub.slice.value, str):
                    validated.append((sub.slice.value, sub.lineno))
    return keys, validated


# ---------------------------------------------------------------------------
# the check


OBSERVABILITY_DOC = 'docs/observability.md'
PARAMETERS_DOC = 'docs/parameters.md'
CONFIG_PATH = 'handyrl_tpu/config.py'


def check_gl005(sources: Dict[str, SourceFile]) -> List[Finding]:
    obs = sources.get(OBSERVABILITY_DOC)
    params = sources.get(PARAMETERS_DOC)
    config = sources.get(CONFIG_PATH)
    out: List[Finding] = []
    if obs is None or params is None or config is None:
        return out     # partial fixture trees check what they provide

    source_blob = '\n'.join(
        s.text for p, s in sources.items()
        if p.startswith('handyrl_tpu/') and not p.startswith(_EXCLUDED_PREFIXES))
    metrics, stages = collect_code_vocabulary(sources)
    doc_tokens: Set[str] = _doc_tokens(obs)

    # code -> doc: every metric/stage literal must be documented
    for name, (path, line) in sorted(metrics.items()):
        if name not in doc_tokens:
            src = sources[path]
            out.append(src.finding(
                'GL005', line,
                'metric %r is emitted here but has no row in '
                'docs/observability.md — document it or drop it' % name))
    for name, (path, line) in sorted(stages.items()):
        if name not in doc_tokens:
            src = sources[path]
            out.append(src.finding(
                'GL005', line,
                'stage %r is recorded here but missing from the '
                'docs/observability.md stage glossary' % name))

    # alert rules -> doc: every builtin alert name is an operator-facing
    # identifier (alerts_active{alert=} label, metrics_jsonl alerts.active
    # entry) and must appear in docs/observability.md
    alerts = collect_alert_names(sources)
    for name, (path, line) in sorted(alerts.items()):
        if name not in doc_tokens:
            src = sources[path]
            out.append(src.finding(
                'GL005', line,
                'alert rule %r is defined here but has no row in the '
                'docs/observability.md alert catalog' % name))

    # doc -> alert rules: alert-catalog rows must name a real rule
    alert_rows = _table_first_cells(
        obs, lambda h: 'alert catalog' in h.lower())
    for name in sorted(set(alert_rows)):
        if name not in alerts and name not in source_blob:
            out.append(obs.finding(
                'GL005', _doc_line_of(obs, name),
                'documented alert %r matches no rule in '
                'telemetry.BUILTIN_ALERTS — stale doc row' % name))

    # doc -> code: catalog rows must correspond to something emitted
    def _in_code(name: str) -> bool:
        if name in source_blob:
            return True
        # names assembled at runtime: gauge(key + '_mean')
        return name.endswith('_mean') and name[:-5] in source_blob

    catalog = _table_first_cells(
        obs, lambda h: 'Metric catalog' in h or 'stage glossary' in h.lower()
        or 'Span stage glossary' in h)
    for name in sorted(set(catalog)):
        if not _in_code(name):
            out.append(obs.finding(
                'GL005', _doc_line_of(obs, name),
                'documented metric/stage %r is emitted nowhere in '
                'handyrl_tpu/ — stale doc row' % name))

    # config defaults -> parameters doc
    keys, validated = collect_config_keys(config)
    keys = keys + _aux_block_keys(sources)
    param_tokens: Set[str] = _doc_tokens(params)
    flat_names = {k.split('.')[-1] for k, _c in keys} \
        | {k for k, _c in keys}
    def _config_line_of(bare: str) -> int:
        needle = "'%s':" % bare
        for i, line in enumerate(config.lines, start=1):
            if needle in line:
                return i
        return 1

    for key in sorted(k for k, container in keys if not container):
        bare = key.split('.')[-1]
        if key not in param_tokens and bare not in param_tokens:
            out.append(config.finding(
                'GL005', _config_line_of(bare),
                'config knob %r has a default but no docs/parameters.md '
                'row — operators cannot discover it' % key))

    # parameters doc -> config defaults (train_args / worker_args tables)
    def _param_section(heading: str) -> bool:
        return 'train_args' in heading or 'worker_args' in heading \
            or 'extensions' in heading.lower()

    for name in sorted(set(_table_first_cells(params, _param_section))):
        if name not in flat_names:
            out.append(params.finding(
                'GL005', _doc_line_of(params, name),
                'documented knob %r has no default in config.py — stale '
                'doc row or missing default' % name))

    # validate() must only reference known knobs
    for lit, line in validated:
        if lit not in flat_names:
            out.append(config.finding(
                'GL005', line,
                'validate() references %r which is not a known config '
                'knob — typo or a rule checking nothing' % lit))
    return out
