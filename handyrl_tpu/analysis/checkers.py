"""graftlint AST checkers GL001-GL004.

Each checker is a small visitor over ``ast`` producing
:class:`~.core.Finding` objects with a stable rule id. Scoping is by
repo-relative path suffix (``SCOPE_*`` below), so test fixtures placed
under a temporary tree with the same shape exercise the same rules.

GL001 — determinism. Episode records are pure functions of
``(seed, sample_key, params)`` (the PR 5 byte-identity contract); a raw
``random.*`` or global ``np.random.*`` draw, or a wall-clock read, inside
a record-producing path silently breaks replayability. Explicitly seeded
constructions (``random.Random(s)``, ``np.random.default_rng(seq)``,
``np.random.RandomState(s)``) and ``random.seed`` are allowed — they
*establish* determinism rather than consuming hidden global state.

GL002 — host-sync. The train step performs no extra host syncs (the PR 4
on-device guard rides the existing lazy metric fetch); a stray ``.item()``
/ ``float()`` / ``np.asarray`` inside a jit/shard_map-compiled function
forces a device round trip per step — ~140 ms per dispatch on a tunneled
TPU. Traced functions are found by: ``@jax.jit``-style decorators, names
passed to ``jax.jit``/``shard_map``/``pjit`` (including names returned by a
locally-defined builder whose call is jitted), lexical nesting inside a
traced function, and transitive closure over same-module-set calls.

GL003 — atomic-write. Durable files (checkpoints, metrics, traces) must go
through ``utils/fs.py`` (temp+fsync+rename, CRC sidecars, O_APPEND JSONL):
a raw write-mode ``open()`` anywhere in the package is a torn-file bug
waiting for a preemption (PRs 2/4). ``utils/fs.py`` itself is the one
sanctioned implementation site.

GL004 — lock discipline. Fields annotated ``# guarded-by: <lock>`` must
only be touched inside a matching ``with <recv>.<lock>`` block, in
``__init__``, or in a function whose name ends with ``_locked`` (the
caller-holds-the-lock convention). Threads started in the concurrency
modules must carry ``name=`` (the runtime sanitizer attributes leaks by
name) and be daemon or joined.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, SourceFile

# ---------------------------------------------------------------------------
# rule scopes (repo-relative posix path suffixes)

SCOPE_GL001 = (
    'handyrl_tpu/generation.py',
    'handyrl_tpu/evaluation.py',
    'handyrl_tpu/device_generation.py',
    'handyrl_tpu/agent.py',
    'handyrl_tpu/ops/batch.py',
    # the serving tier serves the SAME act/sample contract the record
    # paths replay: a hidden global draw or wall-clock read in a service
    # reply would fork records between the remote and local paths
    'handyrl_tpu/serving/registry.py',
    'handyrl_tpu/serving/service.py',
    'handyrl_tpu/serving/client.py',
)

SCOPE_GL002 = (
    'handyrl_tpu/ops/train_step.py',
    'handyrl_tpu/ops/fused_pipeline.py',
    'handyrl_tpu/ops/losses.py',
    'handyrl_tpu/ops/targets.py',
    'handyrl_tpu/ops/replay.py',
    'handyrl_tpu/device_generation.py',
    # the NamedSharding/pjit entry points: the partition-rule-built train
    # step and the mesh staging helpers share the no-host-sync contract
    'handyrl_tpu/parallel/partition.py',
    'handyrl_tpu/parallel/mesh.py',
    # the serving tier dispatches compiled forwards through the engines it
    # hosts; any jitted code it grows inherits the no-host-sync contract
    'handyrl_tpu/serving/registry.py',
    'handyrl_tpu/serving/service.py',
    'handyrl_tpu/serving/client.py',
)

SCOPE_GL003_EXEMPT = (
    'handyrl_tpu/utils/fs.py',
)

SCOPE_GL004 = (
    'handyrl_tpu/connection.py',
    'handyrl_tpu/worker.py',
    'handyrl_tpu/inference.py',
    'handyrl_tpu/fault.py',
    'handyrl_tpu/telemetry.py',
    # the service's pending-request book and handle maps are shared by the
    # dispatch thread and every engine thread; the registry's manifest
    # cache by arbitrary resolver threads
    'handyrl_tpu/serving/registry.py',
    'handyrl_tpu/serving/service.py',
    'handyrl_tpu/serving/client.py',
)


def in_scope(path: str, suffixes: Iterable[str]) -> bool:
    return any(path.endswith(s) for s in suffixes)


def _parse(src: SourceFile) -> Optional[ast.Module]:
    try:
        return ast.parse(src.text)
    except SyntaxError:
        return None


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ''


# ---------------------------------------------------------------------------
# GL001 — determinism


_RANDOM_ALLOWED = {'Random', 'SystemRandom', 'seed', 'getstate', 'setstate'}
_NP_RANDOM_ALLOWED = {'default_rng', 'RandomState', 'Generator',
                      'SeedSequence', 'PCG64', 'Philox'}
_WALL_CLOCK = {'time', 'time_ns'}


def check_gl001(src: SourceFile) -> List[Finding]:
    tree = _parse(src)
    if tree is None:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        # random.<draw>(...)
        if isinstance(fn.value, ast.Name) and fn.value.id == 'random':
            if fn.attr not in _RANDOM_ALLOWED:
                out.append(src.finding(
                    'GL001', node.lineno,
                    'process-global random.%s() in a record-producing path; '
                    'derive the draw from the task sample_key via '
                    'generation.sample_seed/masked_sample' % fn.attr))
            continue
        # np.random.<draw>(...) / numpy.random.<draw>(...)
        if (isinstance(fn.value, ast.Attribute) and fn.value.attr == 'random'
                and isinstance(fn.value.value, ast.Name)
                and fn.value.value.id in ('np', 'numpy')):
            if fn.attr not in _NP_RANDOM_ALLOWED:
                out.append(src.finding(
                    'GL001', node.lineno,
                    'global np.random.%s() in a record-producing path; use '
                    'an explicitly seeded np.random.default_rng' % fn.attr))
            continue
        # time.time() / time.time_ns() — wall clock in record data
        if (isinstance(fn.value, ast.Name) and fn.value.id == 'time'
                and fn.attr in _WALL_CLOCK):
            out.append(src.finding(
                'GL001', node.lineno,
                'wall-clock time.%s() in a record-producing path; records '
                'must replay bit-identically (use time.perf_counter for '
                'pure timing)' % fn.attr))
    return out


# ---------------------------------------------------------------------------
# GL002 — host syncs inside compiled code


_JIT_CALLEES = {'jit', 'pjit', 'shard_map', 'pmap'}


def _is_jit_callable(fn: ast.AST) -> bool:
    """jax.jit / jit / jax.experimental.pjit.pjit / shard_map / partial(jit)"""
    if isinstance(fn, ast.Name):
        return fn.id in _JIT_CALLEES
    if isinstance(fn, ast.Attribute):
        return fn.attr in _JIT_CALLEES
    if isinstance(fn, ast.Call):   # partial(jax.jit, ...) / partial(shard_map)
        fname = fn.func
        is_partial = (isinstance(fname, ast.Name) and fname.id == 'partial') \
            or (isinstance(fname, ast.Attribute) and fname.attr == 'partial')
        if is_partial and fn.args:
            return _is_jit_callable(fn.args[0])
    return False


class _FnInfo:
    __slots__ = ('node', 'name', 'parent', 'calls', 'returned_names')

    def __init__(self, node, name, parent):
        self.node = node
        self.name = name
        self.parent = parent               # enclosing _FnInfo or None
        self.calls: Set[str] = set()       # simple names called in the body
        self.returned_names: Set[str] = set()


def _collect_functions(tree: ast.Module) -> List[_FnInfo]:
    """Every def/lambda with its enclosing function, called names, and the
    simple names it returns (builder pattern: ``return update``)."""
    infos: List[_FnInfo] = []

    def visit(node, parent):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            info = _FnInfo(node, getattr(node, 'name', '<lambda>'), parent)
            infos.append(info)
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                _scan_body(stmt, info)
            for child in ast.iter_child_nodes(node):
                visit(child, info)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, parent)

    def _scan_body(node, info):
        """Record calls/returns in this function, not in nested defs."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            info.calls.add(node.func.id)
        if isinstance(node, ast.Return):
            if isinstance(node.value, ast.Name):
                info.returned_names.add(node.value.id)
            elif isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Name):
                        info.returned_names.add(elt.id)
        for child in ast.iter_child_nodes(node):
            _scan_body(child, info)

    visit(tree, None)
    return infos


def _jit_root_names(tree: ast.Module, infos: List[_FnInfo]
                    ) -> Tuple[Set[str], Set[ast.AST]]:
    """(names passed to jit-like calls, decorated/lambda nodes)."""
    names: Set[str] = set()
    nodes: Set[ast.AST] = set()
    by_name: Dict[str, List[_FnInfo]] = {}
    for info in infos:
        by_name.setdefault(info.name, []).append(info)

    for info in infos:
        for dec in getattr(info.node, 'decorator_list', []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _is_jit_callable(target) or _is_jit_callable(dec):
                nodes.add(info.node)

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_jit_callable(node.func)):
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            elif isinstance(arg, ast.Lambda):
                nodes.add(arg)
            elif (isinstance(arg, ast.Call)
                  and isinstance(arg.func, ast.Name)):
                # jax.jit(build(...)): whatever the local builder returns
                for binfo in by_name.get(arg.func.id, []):
                    names.update(binfo.returned_names)
    return names, nodes


def _traced_functions(trees: Dict[str, ast.Module]
                      ) -> Dict[str, Set[ast.AST]]:
    """Per-path set of function nodes considered traced, closed over the
    whole GL002 module set (jitted code in train_step calls into losses)."""
    all_infos: Dict[str, List[_FnInfo]] = {}
    root_names: Set[str] = set()
    root_nodes: Set[ast.AST] = set()
    for path, tree in trees.items():
        infos = _collect_functions(tree)
        all_infos[path] = infos
        names, nodes = _jit_root_names(tree, infos)
        root_names |= names
        root_nodes |= nodes

    by_name: Dict[str, List[Tuple[str, _FnInfo]]] = {}
    for path, infos in all_infos.items():
        for info in infos:
            by_name.setdefault(info.name, []).append((path, info))

    traced: Set[int] = set()           # id(info)
    worklist: List[Tuple[str, _FnInfo]] = []
    for path, infos in all_infos.items():
        for info in infos:
            if info.name in root_names or info.node in root_nodes:
                worklist.append((path, info))
    while worklist:
        path, info = worklist.pop()
        if id(info) in traced:
            continue
        traced.add(id(info))
        # lexically nested defs trace with their parent
        for cpath, cinfo in ((path, i) for i in all_infos[path]
                             if i.parent is info):
            worklist.append((cpath, cinfo))
        # names the body calls resolve across the module set
        for called in info.calls:
            for tpath, tinfo in by_name.get(called, []):
                worklist.append((tpath, tinfo))

    out: Dict[str, Set[ast.AST]] = {}
    for path, infos in all_infos.items():
        out[path] = {i.node for i in infos if id(i) in traced}
    return out


_SYNC_COERCIONS = {'float', 'int', 'bool'}
_NP_SYNC = {'asarray', 'array'}


def _jnp_rooted(node: ast.AST) -> bool:
    """True for an expression rooted at jnp/jax.numpy/jax.lax."""
    while isinstance(node, (ast.Attribute, ast.Call, ast.Subscript)):
        node = getattr(node, 'func', None) or getattr(node, 'value', None)
        if node is None:
            return False
    return isinstance(node, ast.Name) and node.id == 'jnp'


def _check_traced_body(src: SourceFile, fn_node: ast.AST,
                       out: List[Finding], seen: Set[int]):
    body = fn_node.body if isinstance(fn_node.body, list) else [fn_node.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if id(node) in seen:
                continue
            seen.add(id(node))
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute):
                    if fn.attr == 'item' and not node.args:
                        out.append(src.finding(
                            'GL002', node.lineno,
                            '.item() inside a compiled function forces a '
                            'device->host sync per step'))
                    elif (fn.attr in _NP_SYNC
                          and isinstance(fn.value, ast.Name)
                          and fn.value.id in ('np', 'numpy')):
                        out.append(src.finding(
                            'GL002', node.lineno,
                            'np.%s() inside a compiled function '
                            'materializes the traced value on host; use '
                            'jnp ops' % fn.attr))
                    elif (fn.attr == 'device_get'
                          and isinstance(fn.value, ast.Name)
                          and fn.value.id == 'jax'):
                        out.append(src.finding(
                            'GL002', node.lineno,
                            'jax.device_get() inside a compiled function '
                            'is a host sync'))
                elif (isinstance(fn, ast.Name)
                      and fn.id in _SYNC_COERCIONS and node.args
                      and not isinstance(node.args[0], ast.Constant)):
                    out.append(src.finding(
                        'GL002', node.lineno,
                        '%s() coercion of a traced value inside a compiled '
                        'function syncs to host; keep it a device scalar '
                        '(jnp.float32/astype) or hoist to build time'
                        % fn.id))
            elif isinstance(node, (ast.If, ast.While)):
                if _jnp_rooted(node.test):
                    out.append(src.finding(
                        'GL002', node.lineno,
                        'python branching on a traced value (implicit '
                        'bool()) inside a compiled function; use jnp.where '
                        'or lax.cond'))


def check_gl002(sources: Dict[str, SourceFile]) -> List[Finding]:
    """Cross-module check over every GL002-scoped source in ``sources``."""
    scoped = {p: s for p, s in sources.items() if in_scope(p, SCOPE_GL002)}
    trees = {p: t for p, s in scoped.items()
             if (t := _parse(s)) is not None}
    traced = _traced_functions(trees)
    out: List[Finding] = []
    for path, nodes in traced.items():
        seen: Set[int] = set()
        # check outermost traced functions first so nested nodes dedupe
        for node in sorted(nodes, key=lambda n: n.lineno):
            _check_traced_body(scoped[path], node, out, seen)
    return out


# ---------------------------------------------------------------------------
# GL003 — raw write-mode open()


def _mode_of(call: ast.Call) -> Optional[str]:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == 'mode' and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def check_gl003(src: SourceFile) -> List[Finding]:
    if in_scope(src.path, SCOPE_GL003_EXEMPT):
        return []
    tree = _parse(src)
    if tree is None:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == 'open'):
            continue
        mode = _mode_of(node)
        if mode and any(c in mode for c in 'wax+'):
            out.append(src.finding(
                'GL003', node.lineno,
                "open(..., %r): durable writes must route through "
                "utils/fs.py (atomic_write_bytes / checksummed_write_bytes "
                "/ append_jsonl) — a raw write dies torn under preemption"
                % mode))
    return out


# ---------------------------------------------------------------------------
# GL004 — guarded-by lock discipline + thread accounting


_GUARDED_BY_RE = re.compile(r'#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)')


def _guarded_fields(src: SourceFile, tree: ast.Module) -> Dict[str, str]:
    """field name -> lock attribute, from ``self.<field> = ...`` assignments
    whose line (or the line above) carries ``# guarded-by: <lock>``."""
    fields: Dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for tgt in targets:
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)):
                continue
            for cand in (node.lineno, node.lineno - 1):
                line = src.lines[cand - 1] if 1 <= cand <= len(src.lines) \
                    else ''
                if cand != node.lineno and not line.strip().startswith('#'):
                    continue   # the line above counts only as a pure comment
                m = _GUARDED_BY_RE.search(line)
                if m:
                    lock = m.group(1)
                    fields[tgt.attr] = lock[5:] if lock.startswith('self.') \
                        else lock
                    break
    return fields


def _enclosing_with_locks(stack: List[ast.AST]) -> Set[str]:
    """Unparsed context-manager expressions of every enclosing ``with``."""
    locks: Set[str] = set()
    for node in stack:
        if isinstance(node, ast.With):
            for item in node.items:
                locks.add(_unparse(item.context_expr))
    return locks


def check_gl004(src: SourceFile) -> List[Finding]:
    tree = _parse(src)
    if tree is None:
        return []
    fields = _guarded_fields(src, tree)
    out: List[Finding] = []

    # -- guarded field accesses --
    def walk(node, stack, fn_stack):
        for child in ast.iter_child_nodes(node):
            new_fn_stack = fn_stack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                new_fn_stack = fn_stack + [child.name]
            if (fields and isinstance(child, ast.Attribute)
                    and child.attr in fields):
                recv = _unparse(child.value)
                lock = fields[child.attr]
                exempt = any(fn == '__init__' or fn.endswith('_locked')
                             for fn in new_fn_stack)
                held = _enclosing_with_locks(stack + [node])
                want = '%s.%s' % (recv, lock)
                if not exempt and want not in held:
                    out.append(src.finding(
                        'GL004', child.lineno,
                        '%s.%s is guarded-by %s but accessed outside '
                        '"with %s" (allowed: __init__, *_locked helpers, '
                        'or an allow pragma with a reason)'
                        % (recv, child.attr, lock, want)))
            walk(child, stack + [child], new_fn_stack)

    walk(tree, [], [])

    # -- thread accounting --
    has_join = '.join(' in src.text
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        is_thread = (isinstance(fn, ast.Attribute) and fn.attr == 'Thread'
                     and isinstance(fn.value, ast.Name)
                     and fn.value.id == 'threading') \
            or (isinstance(fn, ast.Name) and fn.id == 'Thread')
        if not is_thread:
            continue
        kwargs = {kw.arg for kw in node.keywords if kw.arg}
        if 'name' not in kwargs:
            out.append(src.finding(
                'GL004', node.lineno,
                'threading.Thread(...) without name=: the sanitizer and '
                'crash logs cannot attribute an anonymous thread'))
        daemon = any(kw.arg == 'daemon' and isinstance(kw.value, ast.Constant)
                     and kw.value.value is True for kw in node.keywords)
        if not daemon and not has_join:
            out.append(src.finding(
                'GL004', node.lineno,
                'non-daemon thread started but nothing in this module '
                'joins it: join it, mark it daemon, or pragma why'))
    return out


# unique line-dedup for findings produced by overlapping walks
def dedupe(findings: List[Finding]) -> List[Finding]:
    seen: Set[Tuple[str, str, int, str]] = set()
    out = []
    for f in findings:
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
