"""graftlint — invariant-enforcing static analysis for handyrl_tpu.

``python -m handyrl_tpu.analysis --strict`` is the CI gate. Rules:

* **GL001** determinism — unseeded RNG / wall clock in record-producing
  paths (the PR 5 ``(seed, sample_key, params)`` byte-identity contract).
* **GL002** host-sync — device fetches / traced-value coercions inside
  jit/shard_map-compiled functions (the PR 4 no-extra-syncs contract).
* **GL003** atomic-write — raw write-mode ``open()`` anywhere in the
  package; durable files go through ``utils/fs.py`` (PRs 2/4).
* **GL004** lock discipline — ``# guarded-by:`` fields touched outside
  their lock, anonymous/unaccounted threads (Hub/Gather/engine tier).
* **GL005** vocabulary — metrics/stages/config knobs drifting out of sync
  with docs/observability.md, docs/parameters.md and config.validate.

Suppression: ``# graftlint: allow[GLnnn] <reason>`` pragmas inline, or
``.graftlint-baseline.json`` entries (reason mandatory) for grandfathered
findings. ``analysis.sanitizer`` is the runtime half: a lock-order
-inversion detector + thread accountant the chaos legs enable with
``HANDYRL_TPU_SANITIZE=1``. See docs/static_analysis.md.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional

from .checkers import (check_gl001, check_gl002, check_gl003, check_gl004,
                       dedupe, in_scope, SCOPE_GL001, SCOPE_GL004)
from .core import (BASELINE_NAME, Finding, LintResult, RULES, SourceFile,
                   apply_suppressions, load_baseline, load_source,
                   write_baseline)
from .vocabulary import check_gl005

__all__ = ['RULES', 'Finding', 'LintResult', 'SourceFile', 'run_lint',
           'collect_sources', 'BASELINE_NAME']

DEFAULT_RULES = tuple(sorted(RULES))

# files GL005 needs beyond the package sources
_EXTRA_PATHS = ('docs/observability.md', 'docs/parameters.md')


def repo_root() -> str:
    """The tree to lint: parent of the installed package directory."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def collect_sources(root: str,
                    paths: Optional[Iterable[str]] = None
                    ) -> Dict[str, SourceFile]:
    """Load the lint surface: every .py under handyrl_tpu/ plus the docs
    GL005 reads (or an explicit path list, repo-relative)."""
    rels: List[str] = []
    if paths:
        rels = [p.replace(os.sep, '/') for p in paths]
    else:
        pkg_dir = os.path.join(root, 'handyrl_tpu')
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = [d for d in dirnames if d != '__pycache__']
            for fn in sorted(filenames):
                if fn.endswith('.py'):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    rels.append(rel.replace(os.sep, '/'))
        rels.extend(_EXTRA_PATHS)
    sources: Dict[str, SourceFile] = {}
    for rel in rels:
        src = load_source(root, rel)
        if src is not None:
            sources[rel] = src
    return sources


def run_checks(sources: Dict[str, SourceFile],
               rules: Iterable[str] = DEFAULT_RULES) -> List[Finding]:
    rules = set(rules)
    findings: List[Finding] = []
    for path, src in sorted(sources.items()):
        if not path.endswith('.py'):
            continue
        if 'GL001' in rules and in_scope(path, SCOPE_GL001):
            findings.extend(check_gl001(src))
        if 'GL003' in rules and path.startswith('handyrl_tpu/'):
            findings.extend(check_gl003(src))
        if 'GL004' in rules and in_scope(path, SCOPE_GL004):
            findings.extend(check_gl004(src))
    if 'GL002' in rules:
        findings.extend(check_gl002(sources))
    if 'GL005' in rules:
        findings.extend(check_gl005(sources))
    findings = dedupe(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def run_lint(root: Optional[str] = None,
             rules: Iterable[str] = DEFAULT_RULES,
             baseline_path: Optional[str] = None,
             paths: Optional[Iterable[str]] = None) -> LintResult:
    """The full pipeline: collect -> check -> pragma/baseline filter."""
    root = root or repo_root()
    sources = collect_sources(root, paths)
    findings = run_checks(sources, rules)
    bl_path = baseline_path or os.path.join(root, BASELINE_NAME)
    baseline, errors = load_baseline(bl_path)
    baseline = [e for e in baseline if e.rule in set(rules)]
    result = apply_suppressions(findings, sources, baseline)
    result.config_errors.extend(errors)
    return result
