"""graftlint CLI: ``python -m handyrl_tpu.analysis [--strict] [paths...]``.

Exit codes: 0 clean (everything pragma'd/baselined with reasons), 1 live
findings (plus, under ``--strict``, reasonless pragmas, stale baseline
entries, and baseline config errors), 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import os
import sys

from . import (BASELINE_NAME, DEFAULT_RULES, RULES, repo_root, run_lint)
from .core import write_baseline


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='python -m handyrl_tpu.analysis',
        description='graftlint: invariant-enforcing static analysis '
                    '(rules GL001-GL005; see docs/static_analysis.md)')
    ap.add_argument('paths', nargs='*',
                    help='repo-relative files to lint (default: the whole '
                         'package + docs)')
    ap.add_argument('--root', default=None,
                    help='repo root (default: autodetected from the package)')
    ap.add_argument('--rules', default=','.join(DEFAULT_RULES),
                    help='comma-separated rule ids to run')
    ap.add_argument('--baseline', default=None,
                    help='baseline file (default: <root>/%s)' % BASELINE_NAME)
    ap.add_argument('--strict', action='store_true',
                    help='also fail on stale baseline entries, reasonless '
                         'pragmas and baseline config errors (the CI gate)')
    ap.add_argument('--write-baseline', action='store_true',
                    help='write current live findings to the baseline file '
                         '(reasons must then be filled in by hand)')
    ap.add_argument('--list-rules', action='store_true')
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print('%s  %s' % (rid, RULES[rid]))
        return 0

    rules = [r.strip().upper() for r in args.rules.split(',') if r.strip()]
    unknown = [r for r in rules if r not in RULES]
    if unknown:
        print('graftlint: unknown rule(s): %s' % ', '.join(unknown),
              file=sys.stderr)
        return 2

    root = os.path.abspath(args.root) if args.root else repo_root()
    result = run_lint(root=root, rules=rules, baseline_path=args.baseline,
                      paths=args.paths or None)

    for f in result.findings:
        print(f.render())
    for f in result.pragma_errors:
        print(f.render())

    strict_failures = 0
    if args.strict:
        for entry in result.stale_baseline:
            strict_failures += 1
            print('%s: %s STALE baseline entry (context %r matches '
                  'nothing) — delete it' % (entry.path, entry.rule,
                                            entry.context[:60]))
        for err in result.config_errors:
            strict_failures += 1
            print('graftlint: %s' % err)
        for msg in result.placeholder_reasons:
            strict_failures += 1
            print('graftlint: %s' % msg)
    else:
        for err in result.config_errors:
            print('graftlint: warning: %s' % err, file=sys.stderr)
        for msg in result.placeholder_reasons:
            print('graftlint: warning: %s' % msg, file=sys.stderr)

    if args.write_baseline:
        path = args.baseline or os.path.join(root, BASELINE_NAME)
        write_baseline(path, result.findings)
        print('graftlint: wrote %d baseline entr%s to %s — fill in the '
              'reasons' % (len(result.findings),
                           'y' if len(result.findings) == 1 else 'ies',
                           path))

    print('graftlint: %d finding(s), %d baselined, %d pragma-suppressed'
          % (len(result.findings) + len(result.pragma_errors),
             len(result.baselined), len(result.suppressed))
          + (', %d strict failure(s)' % strict_failures
             if args.strict and strict_failures else ''))
    if result.findings or result.pragma_errors or strict_failures:
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
