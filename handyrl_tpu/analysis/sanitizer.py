"""Runtime concurrency sanitizer: lock-order-inversion detection + thread
accounting, for the chaos/e2e legs (``HANDYRL_TPU_SANITIZE=1``).

The Hub/Gather/engine threads share mutable state under the lock
conventions GL004 checks statically; this module checks the part statics
cannot see — the ORDER locks are actually taken in at runtime. It installs
thin wrappers over ``threading.Lock`` / ``threading.RLock`` (so every lock
the package — or anything else in the process — creates afterwards is
instrumented) and over ``threading.Thread.start``:

* **Lock-order graph.** Each wrapper remembers its allocation site
  (file:line of construction — the stable identity that generalizes across
  instances, e.g. every ``Hub._lock`` is one node). Per-thread held-lock
  stacks feed a global edge set ``A -> B`` ("B acquired while A held");
  the first time the REVERSE edge is observed the pair is recorded as an
  inversion with both stacks — the classic ABBA deadlock, detected without
  ever deadlocking. Same-site pairs (two locks from one construction line,
  e.g. a list comprehension of per-endpoint locks) carry no order
  information and are skipped.

* **Thread accounting.** Every ``Thread.start`` records (name, daemon,
  site). ``thread_report`` flags anonymous threads (default ``Thread-N``
  names — GL004's static twin) and live non-daemon threads (leak
  candidates: they outlive the component that started them).

``Condition`` objects built after install wrap an instrumented RLock; the
wrapper implements ``_release_save`` / ``_acquire_restore`` / ``_is_owned``
so a ``cv.wait()`` correctly pops and re-pushes the held stack — waiting
must not leave phantom held locks that would fabricate edges.

Enabled via env: ``HANDYRL_TPU_SANITIZE=1`` installs at ``handyrl_tpu``
import and prints a one-line report (plus inversion details) at process
exit. The API (``install`` / ``uninstall`` / ``lock_report`` /
``thread_report`` / ``assert_clean``) serves the unit tests directly.
Overhead is a dict update per acquire — fine for chaos tests, not for
production throughput runs.
"""

from __future__ import annotations

import atexit
import os
import re
import sys
import threading
import traceback
import weakref
from typing import Any, Dict, List, Optional, Tuple

_raw_allocate = threading._allocate_lock          # the real C lock factory

_ENV_VAR = 'HANDYRL_TPU_SANITIZE'
_ANON_THREAD_RE = re.compile(r'^Thread-\d+')


def enabled_by_env() -> bool:
    return os.environ.get(_ENV_VAR, '').strip().lower() \
        not in ('', '0', 'false', 'off')


class _State:
    def __init__(self):
        self.meta = _raw_allocate()               # guards edges/inversions
        self.edges: Dict[Tuple[str, str], List[str]] = {}
        self.inversions: List[Dict[str, Any]] = []
        self.tls = threading.local()              # .held: list[(site, wrapper)]
        self.threads: List[Dict[str, Any]] = []
        self.installed = False
        self.orig_lock = None
        self.orig_rlock = None
        self.orig_start = None


_S = _State()


def _alloc_site() -> str:
    """file:line of the frame that constructed the lock (first frame
    outside this module and the threading module)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(('sanitizer.py', 'threading.py')):
            return '%s:%d' % (fn, f.f_lineno)
        f = f.f_back
    return '<unknown>'


def _held_stack() -> list:
    held = getattr(_S.tls, 'held', None)
    if held is None:
        held = _S.tls.held = []
    return held


def _note_acquired(wrapper):
    held = _held_stack()
    site = wrapper._site
    for prev_site, _prev in held:
        if prev_site == site:
            continue                      # same allocation site: unordered
        edge = (prev_site, site)
        back = (site, prev_site)
        with _S.meta:
            if edge not in _S.edges:
                _S.edges[edge] = traceback.format_stack()[:-2]
            if back in _S.edges:
                key = tuple(sorted((prev_site, site)))
                if not any(i['pair'] == key for i in _S.inversions):
                    _S.inversions.append({
                        'pair': key,
                        'first_order': back,
                        'second_order': edge,
                        'stack_then': _S.edges[back],
                        'stack_now': traceback.format_stack()[:-2],
                    })
    held.append((site, wrapper))


def _note_released(wrapper):
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i][1] is wrapper:
            del held[i]
            return


class _SanitizedLock:
    """Wrapper over a raw lock; order-checks on acquire."""

    _reentrant = False

    def __init__(self):
        self._lock = self._make()
        self._site = _alloc_site()
        self._count = 0                   # reentrancy depth (RLock)

    @staticmethod
    def _make():
        return _raw_allocate()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            if not (self._reentrant and self._count > 0
                    and self._owned_by_me()):
                _note_acquired(self)
            self._count += 1
        return got

    def release(self):
        self._count = max(0, self._count - 1)
        if self._count == 0:
            _note_released(self)
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        # dispatches through the subclass override (a bare
        # ``__enter__ = acquire`` would freeze the base implementation)
        return self.acquire()

    def __exit__(self, *exc):
        self.release()

    def _owned_by_me(self):
        return True                       # refined by the RLock subclass

    def __repr__(self):
        return '<sanitized %s site=%s>' % (type(self).__name__, self._site)


class _SanitizedRLock(_SanitizedLock):
    _reentrant = True

    def __init__(self):
        super().__init__()
        self._owner: Optional[int] = None

    @staticmethod
    def _make():
        return threading._CRLock() if threading._CRLock is not None \
            else threading._PyRLock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            me = threading.get_ident()
            if self._owner != me:
                self._owner = me
                _note_acquired(self)
                self._count = 1
            else:
                self._count += 1
        return got

    def release(self):
        if self._count <= 1:
            self._count = 0
            self._owner = None
            _note_released(self)
        else:
            self._count -= 1
        self._lock.release()

    def _owned_by_me(self):
        return self._owner == threading.get_ident()

    # Condition integration: wait() must fully release (popping the held
    # stack) and reacquire (pushing it back) through the bookkeeping.
    def _release_save(self):
        count, owner = self._count, self._owner
        self._count, self._owner = 0, None
        _note_released(self)
        if hasattr(self._lock, '_release_save'):
            state = self._lock._release_save()
        else:
            state = count
            for _ in range(count):
                self._lock.release()
        return (state, count, owner)

    def _acquire_restore(self, saved):
        state, count, owner = saved
        if hasattr(self._lock, '_acquire_restore'):
            self._lock._acquire_restore(state)
        else:
            for _ in range(count):
                self._lock.acquire()
        self._count, self._owner = count, threading.get_ident()
        _note_acquired(self)

    def _is_owned(self):
        return self._owner == threading.get_ident()


def _sanitized_lock_factory():
    return _SanitizedLock()


def _sanitized_rlock_factory():
    return _SanitizedRLock()


# ---------------------------------------------------------------------------
# thread accounting


def _recording_start(self, *a, **kw):
    _S.threads.append({
        'ref': weakref.ref(self),
        'name': self.name,
        'named': not _ANON_THREAD_RE.match(self.name or ''),
        'daemon': self.daemon,
        'site': _alloc_site(),
    })
    return _S.orig_start(self, *a, **kw)


# ---------------------------------------------------------------------------
# lifecycle + reports


def install():
    """Idempotent. Locks created BEFORE install stay uninstrumented (import
    order matters: the env-gated install in handyrl_tpu/__init__ runs before
    any framework lock exists)."""
    if _S.installed:
        return
    _S.orig_lock = threading.Lock
    _S.orig_rlock = threading.RLock
    _S.orig_start = threading.Thread.start
    threading.Lock = _sanitized_lock_factory
    threading.RLock = _sanitized_rlock_factory
    threading.Thread.start = _recording_start
    _S.installed = True


def uninstall():
    if not _S.installed:
        return
    threading.Lock = _S.orig_lock
    threading.RLock = _S.orig_rlock
    threading.Thread.start = _S.orig_start
    _S.installed = False


def reset():
    """Clear collected state (tests)."""
    with _S.meta:
        _S.edges.clear()
        _S.inversions.clear()
    _S.threads = []


def lock_report() -> Dict[str, Any]:
    with _S.meta:
        return {'edges': len(_S.edges),
                'inversions': [dict(i) for i in _S.inversions]}


def thread_report() -> Dict[str, Any]:
    unnamed, leaked = [], []
    for rec in _S.threads:
        t = rec['ref']()
        alive = t is not None and t.is_alive()
        if not rec['named']:
            unnamed.append({'name': rec['name'], 'site': rec['site'],
                            'alive': alive})
        if alive and not rec['daemon'] \
                and t is not threading.current_thread():
            leaked.append({'name': rec['name'], 'site': rec['site']})
    return {'started': len(_S.threads), 'unnamed': unnamed, 'leaked': leaked}


def assert_clean():
    locks = lock_report()
    threads = thread_report()
    problems = []
    for inv in locks['inversions']:
        problems.append('lock-order inversion between %s and %s'
                        % inv['pair'])
    for t in threads['leaked']:
        problems.append('leaked non-daemon thread %r (started at %s)'
                        % (t['name'], t['site']))
    if problems:
        raise AssertionError('sanitizer: ' + '; '.join(problems))


def _exit_report():
    locks = lock_report()
    threads = thread_report()
    line = ('graftlint-sanitizer: %d lock-order inversion(s), '
            '%d unnamed thread(s), %d leaked non-daemon thread(s) '
            '[%d lock edges, %d threads started]'
            % (len(locks['inversions']), len(threads['unnamed']),
               len(threads['leaked']), locks['edges'], threads['started']))
    print(line, file=sys.stderr, flush=True)
    for inv in locks['inversions']:
        print('graftlint-sanitizer: INVERSION %s <-> %s\n'
              '  first seen order %s -> %s:\n%s\n  reversed here:\n%s'
              % (inv['pair'][0], inv['pair'][1], *inv['first_order'],
                 ''.join(inv['stack_then'][-6:]),
                 ''.join(inv['stack_now'][-6:])),
              file=sys.stderr, flush=True)
    for t in threads['unnamed']:
        print('graftlint-sanitizer: UNNAMED thread %r started at %s'
              % (t['name'], t['site']), file=sys.stderr, flush=True)


def install_from_env() -> bool:
    """Called from handyrl_tpu/__init__: install + register the atexit
    report when HANDYRL_TPU_SANITIZE is set. Returns whether installed."""
    if not enabled_by_env():
        return False
    install()
    atexit.register(_exit_report)
    return True
