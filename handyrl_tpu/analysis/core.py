"""graftlint core: findings, pragmas, and the grandfather baseline.

The framework invariants PRs 1-7 earned (seeded sampling, no host syncs in
compiled code, atomic durable writes, lock discipline, one documented
metric/knob vocabulary) hold only as long as every later edit preserves
them. This package turns each invariant into a checker with a stable rule
id; this module holds the pieces every checker shares:

* :class:`Finding` — one (rule, path, line, message) violation. Findings
  carry the stripped source line as ``context``: baseline matching keys on
  (rule, path, context) instead of line numbers, so unrelated edits above a
  grandfathered site do not churn the baseline.

* **Pragmas** — ``# graftlint: allow[GL001] <reason>`` on the flagged line
  (or the line above) suppresses that rule there. The reason is mandatory:
  a bare pragma does not suppress, it is reported as its own violation —
  an undocumented exemption is exactly the rot the suite exists to stop.

* **Baseline** — ``.graftlint-baseline.json`` at the repo root lists
  grandfathered findings, each with a written reason. An entry suppresses
  every finding matching its (rule, path, context); an entry matching
  nothing is STALE (the code it excused is gone) and fails ``--strict`` so
  the baseline only ever shrinks deliberately.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

RULES: Dict[str, str] = {
    'GL001': 'determinism: unseeded RNG / wall clock in record-producing paths',
    'GL002': 'host-sync: device fetch or traced-value coercion in compiled code',
    'GL003': 'atomic-write: raw write-mode open() must route through utils/fs.py',
    'GL004': 'lock discipline: guarded-by fields and thread accounting',
    'GL005': 'vocabulary drift: metrics/stages/knobs out of sync with docs',
}

_PRAGMA_RE = re.compile(
    r'#\s*graftlint:\s*allow\[(GL\d{3})\]\s*(.*)$')


@dataclass
class Finding:
    rule: str
    path: str          # repo-root-relative, posix separators
    line: int
    message: str
    context: str = ''  # stripped source line (the baseline key)

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.context)

    def render(self) -> str:
        return '%s:%d: %s %s' % (self.path, self.line, self.rule,
                                 self.message)


@dataclass
class SourceFile:
    """One parsed source file plus its pragma table."""

    path: str                      # repo-relative posix path
    text: str
    lines: List[str] = field(default_factory=list)
    # line number -> {rule: reason-or-None}; reasonless pragmas keep None
    pragmas: Dict[int, Dict[str, Optional[str]]] = field(default_factory=dict)

    def __post_init__(self):
        self.lines = self.text.splitlines()
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                reason = m.group(2).strip() or None
                self.pragmas.setdefault(i, {})[m.group(1)] = reason

    def context(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ''

    def finding(self, rule: str, line: int, message: str) -> Finding:
        return Finding(rule, self.path, line, message, self.context(line))

    def pragma_for(self, rule: str, line: int) -> Optional[Tuple[int, Optional[str]]]:
        """(pragma line, reason) covering ``rule`` at ``line`` — the flagged
        line itself or the line directly above."""
        for cand in (line, line - 1):
            rules = self.pragmas.get(cand)
            if rules and rule in rules:
                return cand, rules[rule]
        return None


def load_source(root: str, relpath: str) -> Optional[SourceFile]:
    try:
        with open(os.path.join(root, relpath), encoding='utf-8') as f:
            return SourceFile(relpath, f.read())
    except (OSError, UnicodeDecodeError):
        return None


# ---------------------------------------------------------------------------
# baseline


BASELINE_NAME = '.graftlint-baseline.json'


@dataclass
class BaselineEntry:
    rule: str
    path: str
    context: str
    reason: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.context)


def load_baseline(path: str) -> Tuple[List[BaselineEntry], List[str]]:
    """(entries, errors). Entries without a reason are config errors, not
    silent suppressions."""
    if not os.path.exists(path):
        return [], []
    try:
        with open(path, encoding='utf-8') as f:
            raw = json.load(f)
    except (OSError, ValueError) as exc:
        return [], ['baseline %s unreadable: %s' % (path, exc)]
    entries, errors = [], []
    for i, item in enumerate(raw if isinstance(raw, list) else []):
        rule = str(item.get('rule', ''))
        reason = str(item.get('reason', '') or '').strip()
        if rule not in RULES:
            errors.append('baseline entry %d: unknown rule %r' % (i, rule))
            continue
        if not reason:
            errors.append('baseline entry %d (%s %s): missing reason — '
                          'every grandfathered finding must say why'
                          % (i, rule, item.get('path')))
            continue
        entries.append(BaselineEntry(rule, str(item.get('path', '')),
                                     str(item.get('context', '')).strip(),
                                     reason))
    if not isinstance(raw, list):
        errors.append('baseline %s: expected a JSON list' % path)
    return entries, errors


# the reason --write-baseline scaffolds entries with. A scaffolded reason
# is not a written reason: --strict fails any baseline entry or pragma
# still carrying it (non-strict runs keep suppressing, with a warning, so
# the baseline stays usable while the reasons are being written).
PLACEHOLDER_REASON = 'TODO: justify this exemption'


def write_baseline(path: str, findings: List[Finding]):
    entries = [{'rule': f.rule, 'path': f.path, 'context': f.context,
                'reason': PLACEHOLDER_REASON}
               for f in findings]
    # one entry per key: several findings on identical lines (e.g. the
    # reference builder's draw repeated in the arena twin) share one excuse
    seen, out = set(), []
    for e in entries:
        k = (e['rule'], e['path'], e['context'])
        if k not in seen:
            seen.add(k)
            out.append(e)
    # graftlint: allow[GL003] the baseline is dev-tool output rewritten on demand, not a durable run artifact
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(out, f, indent=2)
        f.write('\n')


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)      # live
    baselined: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)    # by pragma
    pragma_errors: List[Finding] = field(default_factory=list)
    stale_baseline: List[BaselineEntry] = field(default_factory=list)
    config_errors: List[str] = field(default_factory=list)
    # suppressions whose reason is still the --write-baseline scaffold
    # placeholder: they suppress (non-strict) but fail --strict
    placeholder_reasons: List[str] = field(default_factory=list)


def apply_suppressions(findings: List[Finding], sources: Dict[str, SourceFile],
                       baseline: List[BaselineEntry]) -> LintResult:
    """Split raw findings into live / baselined / pragma-suppressed, flag
    reasonless pragmas, and detect stale baseline entries. Suppressions
    whose reason is still :data:`PLACEHOLDER_REASON` are collected into
    ``placeholder_reasons`` — the mandatory-reason contract is not
    satisfied by the scaffold text ``--write-baseline`` emitted."""
    result = LintResult()
    used_keys = set()
    baseline_keys = {e.key() for e in baseline}
    flagged_placeholders = set()
    for f in findings:
        src = sources.get(f.path)
        pragma = src.pragma_for(f.rule, f.line) if src else None
        if pragma is not None:
            pline, reason = pragma
            if reason:
                result.suppressed.append(f)
                if (reason.strip() == PLACEHOLDER_REASON
                        and (f.path, pline) not in flagged_placeholders):
                    flagged_placeholders.add((f.path, pline))
                    result.placeholder_reasons.append(
                        '%s:%d: %s pragma reason is still the scaffold '
                        'placeholder %r — justify the exemption'
                        % (f.path, pline, f.rule, PLACEHOLDER_REASON))
            else:
                result.pragma_errors.append(Finding(
                    f.rule, f.path, pline,
                    'pragma without a reason does not suppress: '
                    'write "# graftlint: allow[%s] <why>"' % f.rule,
                    src.context(pline)))
                result.findings.append(f)
            continue
        if f.key() in baseline_keys:
            used_keys.add(f.key())
            result.baselined.append(f)
            continue
        result.findings.append(f)
    result.stale_baseline = [e for e in baseline if e.key() not in used_keys]
    for e in baseline:
        if e.reason.strip() == PLACEHOLDER_REASON and e.key() in used_keys:
            result.placeholder_reasons.append(
                '%s: %s baseline reason is still the scaffold placeholder '
                '%r — justify the exemption (context %r)'
                % (e.path, e.rule, PLACEHOLDER_REASON, e.context[:60]))
    return result
