"""Environment protocol and registry.

Host-side plug-in API for games. The protocol mirrors the reference
(`/root/reference/handyrl/environment.py:41-145`): the same 17 methods with the
same semantics, so any HandyRL environment can be carried over with only its
neural net rewritten as a Flax module (exposed via ``net()``).

Environments are plain Python — they never see JAX. The framework's device
code consumes only the numpy arrays they produce (``observation``) and the
integer action spaces they define (``legal_actions``).
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional


# Registry: short name -> module path. Environments can also be referenced by
# a fully-qualified dotted module path (mirrors environment.py:9-36).
ENVS = {
    'TicTacToe': 'handyrl_tpu.envs.tictactoe',
    'ParallelTicTacToe': 'handyrl_tpu.envs.parallel_tictactoe',
    'Geister': 'handyrl_tpu.envs.geister',
    'HungryGeese': 'handyrl_tpu.envs.kaggle.hungry_geese',
    'ConnectX': 'handyrl_tpu.envs.kaggle.connectx',
}

# Pure-JAX twins: envs re-implemented as jittable array functions for
# fully device-resident rollouts (device_generation.py).
JAX_ENVS = {
    'TicTacToe': 'handyrl_tpu.envs.jax_tictactoe',
    'HungryGeese': 'handyrl_tpu.envs.jax_hungry_geese',
    'Geister': 'handyrl_tpu.envs.jax_geister',
    'ConnectX': 'handyrl_tpu.envs.jax_connectx',
}


def make_jax_env(env_args: Dict[str, Any]):
    """Return the pure-JAX twin module for an env, or None."""
    name = env_args['env']
    if name not in JAX_ENVS:
        return None
    return importlib.import_module(JAX_ENVS[name])


def _resolve_module(env_args: Dict[str, Any]):
    name = env_args['env']
    return importlib.import_module(ENVS.get(name, name))


def prepare_env(env_args: Dict[str, Any]) -> None:
    """Run a module-level ``prepare()`` hook if the env defines one."""
    module = _resolve_module(env_args)
    if hasattr(module, 'prepare'):
        module.prepare()


def make_env(env_args: Dict[str, Any]) -> 'BaseEnvironment':
    module = _resolve_module(env_args)
    return module.Environment(env_args)


class BaseEnvironment:
    """Base class every game implements.

    Required in all games: ``reset``, ``terminal``, ``outcome``,
    ``legal_actions``, ``observation`` and either ``play`` (turn-based) or a
    custom ``step`` (simultaneous). The network-battle interface
    (``diff_info``/``update``/``action2str``/``str2action``) lets a mirror
    environment be reconstructed from per-step deltas — kept identical to the
    reference so the consistency oracle in tests applies unchanged.
    """

    def __init__(self, args: Optional[Dict[str, Any]] = None):
        pass

    def __str__(self) -> str:
        return ''

    # -- core transitions -------------------------------------------------
    def reset(self, args: Optional[Dict[str, Any]] = None):
        raise NotImplementedError()

    def play(self, action: int, player: Optional[int] = None):
        """Apply one player's action (turn-based games)."""
        raise NotImplementedError()

    def step(self, actions: Dict[int, Optional[int]]):
        """Apply a dict of simultaneous actions; default defers to play()."""
        for player, action in actions.items():
            if action is not None:
                self.play(action, player)

    # -- whose move -------------------------------------------------------
    def turn(self) -> int:
        return 0

    def turns(self) -> List[int]:
        return [self.turn()]

    def observers(self) -> List[int]:
        """Players that should observe (for RNN state) without acting."""
        return []

    # -- termination and scoring -----------------------------------------
    def terminal(self) -> bool:
        raise NotImplementedError()

    def reward(self) -> Dict[int, float]:
        """Immediate per-step rewards (optional)."""
        return {}

    def outcome(self) -> Dict[int, float]:
        raise NotImplementedError()

    # -- action/observation spaces ---------------------------------------
    def legal_actions(self, player: Optional[int] = None) -> List[int]:
        raise NotImplementedError()

    def players(self) -> List[int]:
        return [0]

    def observation(self, player: Optional[int] = None):
        raise NotImplementedError()

    # -- string codec (network battle mode) ------------------------------
    def action2str(self, a: int, player: Optional[int] = None) -> str:
        return str(a)

    def str2action(self, s: str, player: Optional[int] = None) -> int:
        return int(s)

    def diff_info(self, player: Optional[int] = None):
        return ''

    def update(self, info, reset: bool):
        raise NotImplementedError()

    # -- model hook -------------------------------------------------------
    def net(self):
        """Return the Flax module for this game (optional)."""
        raise NotImplementedError()
