"""TorchScript mirrors of the feed-forward model zoo.

Portable-export backend for scripts/export_model.py --torch: rebuilds an
architecture as a plain PyTorch module, transplants the trained flax params
into it, numerically validates the transplant against the flax forward, and
emits a self-contained TorchScript artifact. The resulting ``.pt`` runs
anywhere torch does — ``torch.jit.load`` needs no handyrl_tpu (or even
flax/jax) code — which restores the portability contract of the reference's
ONNX export (reference scripts/make_onnx_model.py:28-58) in an image where
no ONNX writer exists (no onnx/onnxscript/tensorflow).

Layout notes (the subtle parts of the transplant):
  * flax runs NHWC, the mirrors run native-torch NCHW. Conv kernels map
    (kh, kw, cin, cout) -> (cout, cin, kh, kw).
  * the flax heads flatten NHWC feature maps before their Dense layers, so
    those Dense kernels are row-permuted from (H,W,C) order into the
    mirror's (C,H,W) flatten order.
  * flax GroupNorm uses eps=1e-6 (torch defaults to 1e-5): set explicitly.

Supported: SimpleConv2dModel, GeeseNet (the feed-forward nets — the kaggle
submission path). Recurrent architectures (DRC, ConvLSTM, GeeseFormer)
export via the .jaxexp path instead.
"""

from __future__ import annotations

import numpy as np
import torch
import torch.nn as nn


def _t(arr) -> torch.Tensor:
    return torch.from_numpy(np.array(arr, dtype=np.float32))  # owning copy


def _conv_kernel(kernel) -> torch.Tensor:
    """(kh, kw, cin, cout) -> (cout, cin, kh, kw)."""
    return _t(np.transpose(np.asarray(kernel), (3, 2, 0, 1)))


def _dense_kernel(kernel) -> torch.Tensor:
    """(cin, cout) -> (cout, cin)."""
    return _t(np.asarray(kernel).T)


def _dense_kernel_from_nhwc_flatten(kernel, h, w, c) -> torch.Tensor:
    """Dense weight whose input was an NHWC flatten, re-ordered for an
    NCHW flatten: rows (h,w,c) -> (c,h,w)."""
    k = np.asarray(kernel).reshape(h, w, c, -1)
    k = np.transpose(k, (2, 0, 1, 3)).reshape(h * w * c, -1)
    return _t(k.T)


class TorusConv2dMirror(nn.Module):
    """Circular-padded 3x3 conv + GroupNorm (mirror of blocks.TorusConv)."""

    def __init__(self, cin: int, cout: int):
        super().__init__()
        self.conv = nn.Conv2d(cin, cout, 3, padding=1,
                              padding_mode='circular', bias=False)
        self.norm = nn.GroupNorm(min(8, cout), cout, eps=1e-6)

    def forward(self, x):
        return self.norm(self.conv(x))

    def load_flax(self, p):
        self.conv.weight.data = _conv_kernel(p['Conv_0']['kernel'])
        self.norm.weight.data = _t(p['GroupNorm_0']['scale'])
        self.norm.bias.data = _t(p['GroupNorm_0']['bias'])


class GeeseNetMirror(nn.Module):
    """NCHW twin of models.geese.GeeseNet; obs (B, 17, 7, 11) -> (policy(4),
    value(1))."""

    def __init__(self, filters: int = 32, layers: int = 12):
        super().__init__()
        self.stem = TorusConv2dMirror(17, filters)
        self.blocks = nn.ModuleList(
            [TorusConv2dMirror(filters, filters) for _ in range(layers)])
        self.policy = nn.Linear(filters, 4, bias=False)
        self.value = nn.Linear(2 * filters, 1, bias=False)

    def forward(self, obs):
        h = torch.relu(self.stem(obs))
        for block in self.blocks:
            h = torch.relu(h + block(h))
        head_mask = obs[:, :1]                      # own head plane
        h_head = (h * head_mask).sum(dim=(2, 3))
        h_avg = h.mean(dim=(2, 3))
        policy = self.policy(h_head)
        value = torch.tanh(self.value(torch.cat([h_head, h_avg], dim=1)))
        return policy, value

    def load_flax(self, params):
        p = params['params']
        self.stem.load_flax(p['TorusConv_0'])
        for i, block in enumerate(self.blocks):
            block.load_flax(p['TorusConv_%d' % (i + 1)])
        self.policy.weight.data = _dense_kernel(p['Dense_0']['kernel'])
        self.value.weight.data = _dense_kernel(p['Dense_1']['kernel'])


class SimpleConv2dMirror(nn.Module):
    """NCHW twin of models.tictactoe.SimpleConv2dModel; obs (B, 3, 3, 3) ->
    (policy(9), value(1))."""

    def __init__(self, filters: int = 32, layers: int = 3):
        super().__init__()
        self.stem = nn.Conv2d(3, filters, 3, padding=1)
        self.blocks = nn.ModuleList()
        for _ in range(layers):
            self.blocks.append(nn.ModuleDict({
                'conv': nn.Conv2d(filters, filters, 3, padding=1, bias=False),
                'norm': nn.GroupNorm(min(8, filters), filters, eps=1e-6),
            }))
        # PolicyHead(2, 9): 1x1 squeeze -> leaky-relu(0.1) -> dense
        self.p_squeeze = nn.Conv2d(filters, 2, 1)
        self.p_out = nn.Linear(2 * 9, 9, bias=False)
        # ScalarHead(1, 1): 1x1 (no bias) -> GroupNorm(1) -> relu -> dense
        self.v_squeeze = nn.Conv2d(filters, 1, 1, bias=False)
        self.v_norm = nn.GroupNorm(1, 1, eps=1e-6)
        self.v_out = nn.Linear(9, 1, bias=False)

    def forward(self, obs):
        h = torch.relu(self.stem(obs))
        for block in self.blocks:
            h = torch.relu(block['norm'](block['conv'](h)))
        hp = torch.nn.functional.leaky_relu(self.p_squeeze(h), 0.1)
        policy = self.p_out(hp.flatten(1))
        hv = torch.relu(self.v_norm(self.v_squeeze(h)))
        value = torch.tanh(self.v_out(hv.flatten(1)))
        return policy, value

    def load_flax(self, params):
        p = params['params']
        self.stem.weight.data = _conv_kernel(p['Conv_0']['kernel'])
        self.stem.bias.data = _t(p['Conv_0']['bias'])
        for i, block in enumerate(self.blocks):
            bp = p['ConvBlock_%d' % i]
            block['conv'].weight.data = _conv_kernel(bp['Conv_0']['kernel'])
            block['norm'].weight.data = _t(bp['GroupNorm_0']['scale'])
            block['norm'].bias.data = _t(bp['GroupNorm_0']['bias'])
        ph = p['PolicyHead_0']
        self.p_squeeze.weight.data = _conv_kernel(ph['Conv_0']['kernel'])
        self.p_squeeze.bias.data = _t(ph['Conv_0']['bias'])
        self.p_out.weight.data = _dense_kernel_from_nhwc_flatten(
            ph['Dense_0']['kernel'], 3, 3, 2)
        sh = p['ScalarHead_0']
        self.v_squeeze.weight.data = _conv_kernel(sh['Conv_0']['kernel'])
        self.v_norm.weight.data = _t(sh['GroupNorm_0']['scale'])
        self.v_norm.bias.data = _t(sh['GroupNorm_0']['bias'])
        self.v_out.weight.data = _dense_kernel_from_nhwc_flatten(
            sh['Dense_0']['kernel'], 3, 3, 1)


MIRRORS = {
    'GeeseNet': GeeseNetMirror,
    'SimpleConv2dModel': SimpleConv2dMirror,
}


def export_torchscript(arch: str, params, example_obs, out_path: str,
                       atol: float = 1e-4):
    """Transplant ``params`` into the torch mirror of ``arch``, validate the
    forward numerically, and save a traced TorchScript artifact."""
    if arch not in MIRRORS:
        raise SystemExit(
            'no torch mirror for %r (supported: %s); recurrent nets export '
            'via the .jaxexp path' % (arch, sorted(MIRRORS)))
    mirror = MIRRORS[arch]()
    mirror.load_flax(params)
    mirror.eval()

    example = torch.from_numpy(
        np.asarray(example_obs, np.float32)[None])
    with torch.no_grad():
        traced = torch.jit.trace(mirror, example)
    torch.jit.save(traced, out_path)
    return mirror


def validate_against_flax(mirror, wrapper, example_obs, atol=1e-4):
    """Max abs deviation between the flax forward and the torch mirror."""
    flax_out = wrapper.inference(example_obs, None)
    with torch.no_grad():
        policy, value = mirror(
            torch.from_numpy(np.asarray(example_obs, np.float32)[None]))
    dev = max(
        float(np.abs(policy.numpy()[0] - np.asarray(flax_out['policy'])).max()),
        float(np.abs(value.numpy()[0] - np.asarray(flax_out['value'])).max()))
    if dev > atol:
        raise SystemExit('torch mirror deviates from flax by %g (atol %g)'
                         % (dev, atol))
    return dev
