"""Plot per-term loss curves from a training stdout log.

Parses ``loss = p:... v:... ent:... total:...`` lines (one per epoch).

Usage: python scripts/loss_plot.py LOG_FILE [OUT.png]
"""

import re
import sys

LOSS_RE = re.compile(r'^loss = (.+)$')
TERM_RE = re.compile(r'(\w+):(-?[\d.]+(?:e-?\d+)?)')


def parse(path):
    series = {}
    with open(path) as f:
        for line in f:
            m = LOSS_RE.match(line)
            if not m:
                continue
            for term, value in TERM_RE.findall(m.group(1)):
                series.setdefault(term, []).append(float(value))
    return series


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else 'train.log'
    out = sys.argv[2] if len(sys.argv) > 2 else None
    series = parse(path)
    if not series:
        print('no loss lines found in', path)
        return
    for term, values in series.items():
        print('%s: %d points, last = %.4f' % (term, len(values), values[-1]))
    try:
        import matplotlib
        matplotlib.use('Agg')
        import matplotlib.pyplot as plt
    except ImportError:
        print('matplotlib not available; printed summary only')
        return
    fig, ax = plt.subplots(figsize=(8, 5))
    for term, values in sorted(series.items()):
        ax.plot(values, label=term)
    ax.set_xlabel('epoch')
    ax.set_ylabel('loss (per-sample)')
    ax.legend()
    out = out or path + '.loss.png'
    fig.savefig(out, dpi=120, bbox_inches='tight')
    print('wrote', out)


if __name__ == '__main__':
    main()
