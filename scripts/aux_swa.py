"""Stochastic Weight Averaging over a range of epoch checkpoints.

Counterpart of the reference's SWA script (scripts/aux_swa.py): equal-weight
running average of ``models/<epoch>.ckpt`` params (a plain pytree mean — no
torch AveragedModel machinery needed), written to ``models/swa.ckpt`` and
verified by strict reload + inference.

Usage: python scripts/aux_swa.py ENV START END [MODEL_DIR]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))


def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp

    from handyrl_tpu.environment import make_env
    from handyrl_tpu.model import ModelWrapper

    env_name = sys.argv[1] if len(sys.argv) > 1 else 'TicTacToe'
    start = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    end = int(sys.argv[3]) if len(sys.argv) > 3 else start
    model_dir = sys.argv[4] if len(sys.argv) > 4 else 'models'

    env = make_env({'env': env_name})
    env.reset()
    example_obs = env.observation(env.players()[0])

    avg = None
    count = 0
    wrapper = ModelWrapper(env.net())
    for epoch in range(start, end + 1):
        path = os.path.join(model_dir, '%d.ckpt' % epoch)
        if not os.path.exists(path):
            print('skip missing', path)
            continue
        with open(path, 'rb') as f:
            wrapper.load_params_bytes(f.read(), example_obs)
        count += 1
        if avg is None:
            avg = jax.tree_util.tree_map(jnp.asarray, wrapper.params)
        else:
            # running equal-weight mean: avg += (x - avg) / n
            avg = jax.tree_util.tree_map(
                lambda a, x: a + (x - a) / count, avg, wrapper.params)
    assert avg is not None, 'no checkpoints found in range'
    print('averaged %d checkpoints' % count)

    wrapper.params = avg
    out_path = os.path.join(model_dir, 'swa.ckpt')
    with open(out_path, 'wb') as f:
        f.write(wrapper.params_bytes())
    print('wrote', out_path)

    # strict reload + probe inference as a self-test
    check = ModelWrapper(env.net())
    with open(out_path, 'rb') as f:
        check.load_params_bytes(f.read(), example_obs)
    out = check.inference(example_obs, check.init_hidden())
    assert 'policy' in out
    print('reload check ok; policy shape', out['policy'].shape)


if __name__ == '__main__':
    main()
