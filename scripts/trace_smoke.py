"""CI trace smoke: a short CPU learner+worker fleet with tracing on.

Proves the episode-lifecycle tracing loop end to end:

  1. launches a tiny TCP fleet (server-mode learner + one worker host)
     with ``HANDYRL_TPU_TRACE`` set;
  2. after the run, validates the collated Chrome-trace JSON parses, spans
     from >= 3 distinct processes share trace ids, and per-chain stage
     ordering holds (spans nest causally);
  3. runs ``scripts/trace_report.py`` on the trace dir and asserts it
     reports a non-empty generation->gradient critical path (exit 0).

Exits 0 on success, 1 with a reason on any failure. Stdlib + repo only.
"""

import json
import glob
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ENTRY_PORT = int(os.environ.get('TRACE_SMOKE_ENTRY_PORT', '23110'))
DATA_PORT = int(os.environ.get('TRACE_SMOKE_DATA_PORT', '23111'))

LEARNER = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 2,
                          'forward_steps': 8, 'num_batchers': 1,
                          'model_dir': %(model_dir)r,
                          'metrics_jsonl': %(metrics)r,
                          'fault_tolerance': {'heartbeat_interval': 1.0,
                                              'liveness_timeout': 15.0}}}
    learner = Learner(args=apply_defaults(raw), remote=True)
    learner.run()
    print('TRACE SMOKE LEARNER DONE', learner.model_epoch, flush=True)

if __name__ == '__main__':
    main()
'''

WORKER = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


def fail(msg):
    print('TRACE SMOKE FAILED: %s' % msg, flush=True)
    sys.exit(1)


def main():
    import tempfile
    workdir = tempfile.mkdtemp(prefix='trace_smoke.')
    trace_dir = os.path.join(workdir, 'traces')
    learner_py = os.path.join(workdir, 'learner.py')
    worker_py = os.path.join(workdir, 'worker.py')
    with open(learner_py, 'w') as f:
        f.write(LEARNER % {'model_dir': os.path.join(workdir, 'models'),
                           'metrics': os.path.join(workdir, 'metrics.jsonl')})
    with open(worker_py, 'w') as f:
        f.write(WORKER)

    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'HANDYRL_TPU_TRACE': trace_dir,
           'HANDYRL_TPU_TRACE_RATE': '1.0',
           'HANDYRL_TPU_ENTRY_PORT': str(ENTRY_PORT),
           'HANDYRL_TPU_DATA_PORT': str(DATA_PORT),
           'PYTHONPATH': REPO + os.pathsep + os.environ.get('PYTHONPATH', '')}
    learner = subprocess.Popen([sys.executable, learner_py], env=env)
    worker = None
    try:
        time.sleep(3)
        worker = subprocess.Popen([sys.executable, worker_py], env=env)
        rc = learner.wait(timeout=420)
        worker.wait(timeout=120)
    except subprocess.TimeoutExpired:
        fail('fleet did not finish in time')
    finally:
        for proc in (worker, learner):
            if proc is not None and proc.poll() is None:
                proc.kill()
    if rc != 0:
        fail('learner exited rc=%d' % rc)

    # -- the collated Chrome trace parses and links >= 3 processes --------
    finalized = glob.glob(os.path.join(trace_dir, 'trace-*.json'))
    if not finalized:
        fail('no finalized trace-<run_id>.json in %s' % trace_dir)
    events = json.load(open(finalized[0])).get('traceEvents')
    if not events:
        fail('finalized trace has no events')

    sys.path.insert(0, os.path.join(REPO, 'scripts'))
    import trace_report
    chains = trace_report.build_chains(events)
    linked_pids = set()
    full = 0
    for tid, stages in chains.items():
        if trace_report.chain_errors(stages):
            fail('chain %s violates stage ordering: %s'
                 % (tid, trace_report.chain_errors(stages)))
        for stage, (_ts, _dur, pid) in stages.items():
            linked_pids.add(pid)
        if {'task_assign', 'generate', 'upload', 'ingest'} <= set(stages):
            full += 1
    if len(linked_pids) < 3:
        fail('trace-linked spans from only %d process(es); want >= 3 '
             '(learner, gather, worker)' % len(linked_pids))
    if full < 1:
        fail('no chain covers task_assign+generate+upload+ingest')
    print('trace OK: %d events, %d chains (%d full), %d linked processes'
          % (len(events), len(chains), full, len(linked_pids)))

    # -- trace_report emits a non-empty critical path ---------------------
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts', 'trace_report.py'),
         trace_dir], capture_output=True, text=True, timeout=120)
    if proc.returncode != 0:
        fail('trace_report exited rc=%d: %s'
             % (proc.returncode, proc.stderr[-400:]))
    if 'generation->gradient' not in proc.stdout:
        fail('trace_report emitted no generation->gradient line')
    print(proc.stdout)
    print('TRACE SMOKE PASSED')


if __name__ == '__main__':
    main()
