#!/bin/bash
# Supervise the round-5 second chip window across tunnel outages: probe
# until the backend answers, run the (re-entrant) queue, and if the
# queue exits with items unfinished — a mid-queue wedge — go back to
# probing. Stops when every queue item has its done marker or MAX_TRIES
# windows have been spent. Chip discipline unchanged: SIGINT-only
# budgets inside chip_window2.sh, never kill -9.
set -u
cd "$(dirname "$0")/.."
LOG_DIR=${LOG_DIR:-/tmp/chip_window2/r5}
PROBE_LOG=${PROBE_LOG:-/tmp/tpu_probe_r5.log}
MAX_TRIES=${MAX_TRIES:-40}
ITEMS="north_star hbm_experiments geister_arms geister_rescore_base geister_rescore_spbn geister_rescore_spbnti ns_rescore_random ns_rescore_rulebase bench"
mkdir -p "$LOG_DIR"

all_done() {
  for it in $ITEMS; do
    [ -e "$LOG_DIR/done.$it" ] || return 1
  done
  return 0
}

# Cutoffs are ABSOLUTE unix epochs computed ONCE at supervisor start
# (START + duration). The previous `date -d 'HH:MM'` wall-clock anchors
# re-resolved on every loop iteration, so a run crossing midnight saw
# "past 17:40" immediately and stood the supervisor down hours early.
START_TS=$(date +%s)
STOP_AFTER_S=${STOP_AFTER_S:-21600}        # stand down N seconds after start
STOP_AT_TS=${STOP_AT_TS:-$(( START_TS + STOP_AFTER_S ))}
NS_TAIL_S=${NS_TAIL_S:-6000}               # reserve for the non-NS queue tail
NS_CUTOFF_TS=$(( STOP_AT_TS - NS_TAIL_S ))
echo "$(date +%H:%M:%S) supervisor: start $START_TS stop_at $STOP_AT_TS ns_cutoff $NS_CUTOFF_TS" >> "$LOG_DIR/queue.log"
for try in $(seq 1 "$MAX_TRIES"); do
  if all_done; then
    echo "$(date +%H:%M:%S) supervisor: all items done" >> "$LOG_DIR/queue.log"
    exit 0
  fi
  # never contend with the driver's round-end bench for the exclusive
  # tunnel grant: stop opening windows near the round boundary
  if [ "$(date +%s)" -gt "$STOP_AT_TS" ]; then
    echo "$(date +%H:%M:%S) supervisor: past stop epoch $STOP_AT_TS, standing down" >> "$LOG_DIR/queue.log"
    exit 0
  fi
  bash scripts/tpu_probe_loop.sh "$PROBE_LOG" 300 || exit 1
  # North-star budget: whatever gets closest to the 1M-episode endpoint
  # (~16800 s at the measured 57.4 eps/s on top of the 60k in the bank)
  # without pushing the rest of the queue past the round's tail — cap
  # at the precomputed cutoff epoch, floor at 30 min so a late window
  # still extends the curve meaningfully.
  now=$(date +%s)
  ns=$(( NS_CUTOFF_TS - now )); [ "$ns" -gt 16800 ] && ns=16800
  [ "$ns" -lt 1800 ] && ns=1800
  echo "$(date +%H:%M:%S) supervisor: window $try (NS_BUDGET_S=$ns)" >> "$LOG_DIR/queue.log"
  LOG_DIR="$LOG_DIR" NS_BUDGET_S="$ns" bash scripts/chip_window2.sh
done
echo "$(date +%H:%M:%S) supervisor: gave up after $MAX_TRIES windows" >> "$LOG_DIR/queue.log"
