#!/bin/bash
# Poll the tunneled TPU backend until it comes back. Each probe runs
# jax.devices() in a subprocess with an INTERNAL deadline (the process
# exits cleanly on its own; we never SIGKILL a client that might hold
# the exclusive grant). Logs one line per attempt.
LOG=${1:-/tmp/tpu_probe.log}
INTERVAL=${2:-180}
while true; do
  TS=$(date +%H:%M:%S)
  OUT=$(python - <<'PY' 2>&1
import threading, os, sys
def bail():
    os._exit(42)   # clean-ish exit before the driver would signal us
t = threading.Timer(110, bail); t.daemon = True; t.start()
import jax
ds = jax.devices()
print("OK", ds[0].platform, len(ds))
os._exit(0)
PY
)
  RC=$?
  echo "$TS rc=$RC $(echo "$OUT" | tail -c 220 | tr '\n' ' ')" >> "$LOG"
  if [ $RC -eq 0 ] && echo "$OUT" | grep -q "^OK"; then
    echo "$TS BACKEND UP" >> "$LOG"
    exit 0
  fi
  sleep "$INTERVAL"
done
