"""CI chaos smoke for the match gateway (docs/serving.md, "Match
gateway").

Runs a REAL fleet resolver + 2 managed replica subprocesses and a REAL
gateway subprocess (``python -m handyrl_tpu.serving --gateway``), opens
concurrent HungryGeese sessions against a published **recurrent**
GeeseNetLSTM (so the server-side hidden cache and the journal's hidden
digest are live, not trivially empty), SIGKILLs one replica while every
session is held mid-match, and asserts the session tier's zero-loss
contract:

  * ZERO dropped sessions and zero client-visible errors — every match
    plays to a terminal outcome through the kill;
  * >= 1 session is reconstructed from its journal through a survivor,
    with ZERO mismatches — the gateway replays every journaled opponent
    ply with its original audited seed and verifies both the replayed
    actions and the rebuilt hidden digest byte-identically before
    adopting the rebuilt state;
  * every outcome is booked into the RatingBook: one provisional
    ``gateway:<client>`` entry per client (never promotion-eligible)
    plus the rated model entry, round-tripped through the on-disk
    rating journal;
  * gateway and fleet SIGTERM drains both exit 75 (EX_TEMPFAIL — the
    PreemptionGuard supervisor contract);
  * the collated trace holds >= 1 complete client->router->engine->reply
    chain and >= 1 journal-reconstruction chain linked to its session's
    ORIGINAL open-time trace_id, and ``trace_report.py --serve --json``
    exits 0 on it.

Runs under ``HANDYRL_TPU_SANITIZE=1`` in CI like the other chaos legs.
Exits 0 on success, 1 with a reason on any failure. Stdlib + repo only.
"""

import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_SESSIONS = 8
ENV = 'HungryGeese'


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    # serving-path tracing at rate 1.0, inherited by the fleet, every
    # replica, and the gateway (telemetry reads the env at import)
    trace_dir = tempfile.mkdtemp(prefix='gateway_smoke_trace.')
    os.environ['HANDYRL_TPU_TRACE'] = trace_dir
    os.environ['HANDYRL_TPU_TRACE_RATE'] = '1'
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.league import journal_path, make_rating_book
    from handyrl_tpu.model import ModelWrapper
    from handyrl_tpu.serving.fleet import RoutedClient
    from handyrl_tpu.serving.gateway import GatewayClient
    from handyrl_tpu.serving.registry import ModelRegistry

    env = make_env({'env': ENV, 'net_kind': 'lstm'})
    env.reset()
    obs = env.observation(env.players()[0])
    wrapper = ModelWrapper(env.net(), seed=7)
    wrapper.ensure_params(obs)

    root = tempfile.mkdtemp(prefix='gateway_smoke_registry.')
    fleet = gw = rc = None
    try:
        ModelRegistry(root).publish('default', snapshot=wrapper.snapshot(),
                                    version=1, promote=True)
        fleet = subprocess.Popen(
            [sys.executable, '-m', 'handyrl_tpu.serving', '--fleet',
             '--replicas', '2', '--env', ENV, '--registry', root,
             '--port', '0', '--line', 'default',
             '--heartbeat', '0.2', '--heartbeat-timeout', '2.0'],
            cwd=REPO, stdout=subprocess.PIPE, text=True)
        fleet_port = int(json.loads(
            fleet.stdout.readline())['fleet_ready']['port'])
        gw = subprocess.Popen(
            [sys.executable, '-m', 'handyrl_tpu.serving', '--gateway',
             '--resolver', 'localhost:%d' % fleet_port,
             '--registry', root, '--env', ENV,
             '--gateway-workers', '8', '--max-sessions', '16',
             '--seed', '17'],
            cwd=REPO, stdout=subprocess.PIPE, text=True)
        gport = int(json.loads(
            gw.stdout.readline())['gateway_ready']['port'])

        # every session plays 2 plies, then holds mid-match until the
        # SIGKILL (and the journal reconstructions) have happened — so
        # the kill is guaranteed to land on live, stateful sessions
        hold = threading.Event()
        ready = threading.Semaphore(0)
        results = [None] * N_SESSIONS

        def session(ci):
            rng = random.Random(100 + ci)
            marked = False
            cl = GatewayClient('localhost', gport, timeout=120.0,
                               name='smoke%d' % ci)
            try:
                r = cl.open(ENV, seat=0)
                sid = r['sid']
                plies = 0
                while not r.get('done'):
                    if plies >= 2 and not marked:
                        marked = True
                        ready.release()
                        hold.wait(timeout=300)
                    action = (rng.choice(r['legal'])
                              if r.get('to_move') and r.get('legal')
                              else None)
                    r = cl.play(sid, action)
                    plies += 1
                results[ci] = r.get('outcome')
            except Exception as exc:  # noqa: BLE001 — asserted below
                results[ci] = 'ERROR: %s' % exc
            finally:
                if not marked:
                    ready.release()
                cl.close()

        threads = [threading.Thread(target=session, args=(ci,),
                                    name='smoke-session-%d' % ci)
                   for ci in range(N_SESSIONS)]
        for t in threads:
            t.start()
        for _ in range(N_SESSIONS):
            assert ready.acquire(timeout=300), 'sessions never got rolling'

        status_cl = GatewayClient('localhost', gport, timeout=60.0,
                                  name='smoke-status')
        by_replica = {}
        for s in status_cl.sessions():
            if not s.get('done'):
                by_replica.setdefault(s.get('replica'), []).append(s['sid'])
        by_replica.pop(None, None)
        assert by_replica, 'no session is pinned to any replica'
        victim = max(by_replica, key=lambda n: len(by_replica[n]))
        rc = RoutedClient('localhost', fleet_port, timeout=30.0,
                          refresh_interval=0.2)
        table = {r['replica']: r for r in rc.replicas()}
        os.kill(int(table[victim]['pid']), signal.SIGKILL)

        # the monitor must notice the corpse and reconstruct its
        # sessions from their journals before we let play resume
        deadline = time.monotonic() + 60
        status = {}
        while time.monotonic() < deadline:
            status = status_cl.status()
            if status.get('reconstructs', 0) >= len(by_replica[victim]):
                break
            time.sleep(0.25)
        hold.set()
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads), \
            'session thread(s) wedged'

        errors = [r for r in results if not isinstance(r, dict)]
        assert not errors, 'client-visible failure(s): %s' % errors[:3]
        status = status_cl.status()
        assert status['dropped'] == 0, \
            '%d session(s) dropped' % status['dropped']
        assert status['mismatches'] == 0, \
            '%d reconstruction(s) diverged from the journal' \
            % status['mismatches']
        assert status['reconstructs'] >= len(by_replica[victim]), \
            'only %d of %d stranded session(s) reconstructed' \
            % (status['reconstructs'], len(by_replica[victim]))
        assert status['replayed_plies'] >= 2 * status['reconstructs'], \
            'reconstructions replayed suspiciously few plies: %s' % status
        assert status['outcomes'] >= N_SESSIONS, \
            'only %d of %d outcomes booked' % (status['outcomes'],
                                               N_SESSIONS)
        assert status['shed'] == 0, '%d open(s) shed' % status['shed']
        for ci in range(N_SESSIONS):
            assert 'gateway:smoke%d' % ci in status['ratings'], \
                'client smoke%d missing from the RatingBook' % ci
        status_cl.close()

        # outcomes round-trip through the on-disk rating journal: the
        # external players are provisional (never promotion-eligible),
        # the served model is a rated entry
        book = make_rating_book({})
        assert book.load(journal_path(root)), 'rating journal missing'
        for ci in range(N_SESSIONS):
            name = 'gateway:smoke%d' % ci
            assert book.is_provisional(name), \
                '%s is not a provisional member' % name
        rated = [n for n in book.names() if n.startswith('default@')]
        assert rated, 'served model missing from the rating journal'

        # graceful drains: gateway first, then the whole fleet — both 75
        gw.send_signal(signal.SIGTERM)
        code = gw.wait(timeout=60)
        assert code == 75, 'gateway exited %s, not 75' % code
        fleet.send_signal(signal.SIGTERM)
        code = fleet.wait(timeout=120)
        assert code == 75, 'fleet exited %s, not 75' % code

        # the collated trace reads as one causal chain per session:
        # >= 1 complete client->router->engine->reply chain, and >= 1
        # journal reconstruction linked to its session's ORIGINAL
        # open-time trace_id
        from handyrl_tpu import telemetry
        telemetry.trace_flush()
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, 'scripts', 'trace_report.py'),
             trace_dir, '--serve', '--json'],
            capture_output=True, text=True)
        assert out.returncode == 0, \
            'trace_report --serve exited %d: %s' % (out.returncode,
                                                    out.stderr[:500])
        serve = json.loads(out.stdout)['serve']
        assert serve['complete_chains'] >= 1, serve
        assert serve['reconstruct_chains'] >= 1, serve

        print('gateway smoke OK: %d/%d matches finished through a replica '
              'SIGKILL (%s), %d session(s) journal-reconstructed '
              '(%d plies replayed, 0 mismatches), 0 drops, %d outcomes '
              'in the RatingBook, both drains exited 75; trace holds %d '
              'complete serve chain(s) and %d reconstruct chain(s)'
              % (len(results), N_SESSIONS, victim,
                 status['reconstructs'], status['replayed_plies'],
                 status['outcomes'], serve['complete_chains'],
                 serve['reconstruct_chains']))
        return 0
    finally:
        if rc is not None:
            rc.close()
        for proc in (gw, fleet):
            if proc is not None and proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(trace_dir, ignore_errors=True)


if __name__ == '__main__':
    raise SystemExit(main())
