#!/usr/bin/env python3
"""Perf-regression gate over benchmarks.jsonl (docs/observability.md
"Compiled-performance plane").

Every bench run appends one JSON row to benchmarks.jsonl; this gate turns
that trajectory into a CI check. The newest row per (row, backend,
geometry) key is compared against the MEDIAN of the prior same-key rows —
the median, not the mean, because a single wedged-tunnel outlier must not
move the bar — and fails the build when the fresh value falls more than
the per-row noise tolerance below it.

Row handling:
  * rows without a numeric 'value' (pre-schema-v2 history) are skipped;
  * some row kinds stamp hard-bounded side fields (BOUNDED_FIELDS) that
    gate against a fixed ceiling rather than the history median — e.g.
    ``tracing_overhead_pct`` on the serving rows must stay <= 2% (the
    tracing-on/off A/B pair, docs/observability.md "Serving-path
    tracing"); a row that predates the field skips the bound;
  * rows marked ``degraded: true`` (a TPU request that fell back to CPU —
    bench.py stamps backend_requested/backend_actual) never gate and never
    enter the baseline: comparing a fallback row against silicon history
    is exactly the silent-fallback blind spot this plane closes;
  * a key with fewer than --min-history prior rows is "insufficient
    history" (exit 2, or 0 under --allow-insufficient — fresh CI
    geometries have no trajectory yet).

Optional pinned baseline: --baseline FILE consults {key: value} medians
written by a previous --update-baseline run instead of recomputing from
history (the file wins when both exist).

Exit contract: 0 = pass, 1 = regression, 2 = insufficient history /
unusable input.

Usage:
  python scripts/perf_gate.py                         # gate repo history
  python scripts/perf_gate.py --fresh /tmp/row.json   # gate one fresh row
  python scripts/perf_gate.py --tolerance bench-ingest=30 --min-history 2
  python scripts/perf_gate.py --update-baseline --baseline perf_base.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from statistics import median
from typing import Any, Dict, List, Optional, Tuple

# per-row noise tolerance (percent below the median that still passes):
# host-path benches on shared CI runners are noisy; device benches less so
DEFAULT_TOLERANCE_PCT = 25.0
ROW_TOLERANCE_PCT = {
    'bench-ingest': 30.0,      # host threads vs CI scheduler noise
    'bench-actor': 30.0,
    'bench-actor-device': 30.0,   # fused on-device rollout fleet row
    'bench-serve': 30.0,
    'bench-serve-device': 30.0,   # device-backed serving engines row
    'bench-gateway': 30.0,        # session tier: subprocess + chaos noise
    'bench-headline': 15.0,    # compiled step timing is steadier
    'bench-mesh': 20.0,
}

# hard-bounded side fields: {row kind: {field: max allowed}}. Unlike the
# median gate these are absolute ceilings — the serving tracing A/B pair
# must cost <= 2% regardless of what history says. Rows that predate a
# field simply skip its bound.
BOUNDED_FIELDS: Dict[str, Dict[str, float]] = {
    'bench-serve': {'tracing_overhead_pct': 2.0},
    'bench-serve-device': {'tracing_overhead_pct': 2.0},
    'bench-gateway': {'tracing_overhead_pct': 2.0},
    # durable plane: the episode-WAL A/B pair on the host ingest path;
    # streaming plane: the chunked-ingest A/B pair (reassembly cost)
    'bench-ingest': {'spool_overhead_pct': 2.0,
                     'chunk_overhead_pct': 2.0},
}

Key = Tuple[str, str, str]


def row_key(row: Dict[str, Any]) -> Key:
    return (str(row.get('row') or row.get('metric') or '?'),
            str(row.get('backend') or '?'),
            str(row.get('geometry') or '?'))


def usable(row: Dict[str, Any]) -> bool:
    """Gate-eligible: numeric value (post-v2 schema) and not a degraded
    (backend-fallback) measurement."""
    if row.get('degraded'):
        return False
    try:
        float(row['value'])
    except (KeyError, TypeError, ValueError):
        return False
    return True


def load_history(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue   # a torn/hand-edited line is not a gate failure
            if isinstance(row, dict):
                rows.append(row)
    return rows


def tolerance_for(key: Key, overrides: Dict[str, float]) -> float:
    if key[0] in overrides:
        return overrides[key[0]]
    return ROW_TOLERANCE_PCT.get(key[0], DEFAULT_TOLERANCE_PCT)


def gate_key(key: Key, prior: List[float], fresh: float, tol_pct: float,
             baseline: Optional[float], min_history: int):
    """One key's verdict: ('pass'|'regress'|'insufficient', detail)."""
    base = baseline
    if base is None:
        if len(prior) < min_history:
            return 'insufficient', ('%d prior row(s), need %d'
                                    % (len(prior), min_history))
        base = median(prior)
    if base <= 0:
        return 'insufficient', 'non-positive baseline %r' % (base,)
    floor = base * (1.0 - tol_pct / 100.0)
    pct = 100.0 * (fresh - base) / base
    detail = ('fresh %.2f vs baseline %.2f (%+.1f%%, floor %.2f at '
              '-%.0f%%)' % (fresh, base, pct, floor, tol_pct))
    return ('regress' if fresh < floor else 'pass'), detail


def gate_bounds(key: Key, row: Dict[str, Any]):
    """Hard-bounded side fields for one fresh row: list of
    ('pass'|'regress', field, detail) — empty when the row kind has no
    bounds or the row predates the field."""
    out = []
    for field, bound in sorted(BOUNDED_FIELDS.get(key[0], {}).items()):
        if field not in row:
            continue
        try:
            val = float(row[field])
        except (TypeError, ValueError):
            out.append(('regress', field,
                        '%s=%r is not numeric' % (field, row[field])))
            continue
        out.append(('pass' if val <= bound else 'regress', field,
                    '%s %.2f vs ceiling %.2f' % (field, val, bound)))
    return out


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--history',
                    default=os.path.join(repo, 'benchmarks.jsonl'),
                    help='benchmarks JSONL trajectory (default: repo copy)')
    ap.add_argument('--fresh', default='',
                    help='file holding ONE fresh bench JSON row to gate '
                         'against the history (e.g. a CI bench stdout); '
                         'without it the newest history row per key gates '
                         'against its own priors')
    ap.add_argument('--baseline', default='',
                    help='pinned {key: value} baseline JSON (written by '
                         '--update-baseline); wins over the history median')
    ap.add_argument('--update-baseline', action='store_true',
                    help='write the current per-key medians (including the '
                         'fresh row) to --baseline and exit 0')
    ap.add_argument('--tolerance', action='append', default=[],
                    metavar='ROW=PCT',
                    help='override noise tolerance for one row kind '
                         '(repeatable), e.g. bench-ingest=30')
    ap.add_argument('--min-history', type=int, default=2,
                    help='prior same-key rows required to gate (default 2)')
    ap.add_argument('--allow-insufficient', action='store_true',
                    help='exit 0 instead of 2 when a key has no usable '
                         'history yet (fresh CI geometries)')
    ap.add_argument('--key', default='',
                    help='gate only keys whose row kind matches (e.g. '
                         'bench-ingest)')
    args = ap.parse_args(argv)

    overrides: Dict[str, float] = {}
    for spec in args.tolerance:
        name, _, pct = spec.partition('=')
        try:
            overrides[name.strip()] = float(pct)
        except ValueError:
            print('perf_gate: bad --tolerance %r' % spec, file=sys.stderr)
            return 2

    try:
        history = load_history(args.history)
    except OSError as exc:
        print('perf_gate: cannot read history %s: %s'
              % (args.history, exc), file=sys.stderr)
        return 2

    # group usable history per key, newest last (file order == append order)
    per_key: Dict[Key, List[Dict[str, Any]]] = {}
    for row in history:
        if usable(row):
            per_key.setdefault(row_key(row), []).append(row)

    # the rows under test: one external fresh row, or the newest per key
    # (the full dict rides along for the bounded side fields)
    fresh_rows: List[Tuple[Key, float, Dict[str, Any]]] = []
    if args.fresh:
        try:
            with open(args.fresh) as fh:
                text = fh.read().strip()
            fresh = json.loads(text.splitlines()[-1]) if text else {}
        except (OSError, ValueError) as exc:
            print('perf_gate: cannot parse fresh row %s: %s'
                  % (args.fresh, exc), file=sys.stderr)
            return 2
        if not isinstance(fresh, dict) or not usable(fresh):
            why = ('degraded (backend fallback)' if isinstance(fresh, dict)
                   and fresh.get('degraded') else 'no numeric value')
            print('perf_gate: fresh row not gate-eligible (%s) — skipping'
                  % why, file=sys.stderr)
            return 0 if args.allow_insufficient else 2
        fresh_rows.append((row_key(fresh), float(fresh['value']), fresh))
    else:
        for key, rows in per_key.items():
            fresh_rows.append((key, float(rows[-1]['value']), rows[-1]))
            per_key[key] = rows[:-1]   # priors exclude the row under test

    if args.key:
        fresh_rows = [(k, v, r) for k, v, r in fresh_rows
                      if k[0] == args.key]

    baseline_map: Dict[str, float] = {}
    if args.baseline and os.path.exists(args.baseline) \
            and not args.update_baseline:
        try:
            with open(args.baseline) as fh:
                baseline_map = {k: float(v)
                                for k, v in json.load(fh).items()}
        except (OSError, ValueError) as exc:
            print('perf_gate: bad baseline %s: %s' % (args.baseline, exc),
                  file=sys.stderr)
            return 2

    if args.update_baseline:
        if not args.baseline:
            print('perf_gate: --update-baseline needs --baseline FILE',
                  file=sys.stderr)
            return 2
        out = {}
        for key, fresh_val, _row in fresh_rows:
            vals = [float(r['value']) for r in per_key.get(key, [])]
            vals.append(fresh_val)
            out['|'.join(key)] = round(median(vals), 4)
        with open(args.baseline, 'w') as fh:
            json.dump(out, fh, indent=2, sort_keys=True)
        print('perf_gate: wrote %d baseline value(s) to %s'
              % (len(out), args.baseline))
        return 0

    if not fresh_rows:
        print('perf_gate: no gate-eligible rows found', file=sys.stderr)
        return 0 if args.allow_insufficient else 2

    worst = 0
    for key, fresh_val, row in sorted(fresh_rows, key=lambda t: t[:2]):
        prior = [float(r['value']) for r in per_key.get(key, [])]
        verdict, detail = gate_key(
            key, prior, fresh_val, tolerance_for(key, overrides),
            baseline_map.get('|'.join(key)), args.min_history)
        print('perf_gate: %-10s %s: %s' % (verdict.upper(),
                                           '/'.join(key), detail))
        if verdict == 'regress':
            worst = max(worst, 1)
        elif verdict == 'insufficient' and not args.allow_insufficient:
            worst = max(worst, 2) if worst != 1 else worst
        for bverdict, _field, bdetail in gate_bounds(key, row):
            print('perf_gate: %-10s %s: %s' % (bverdict.upper(),
                                               '/'.join(key), bdetail))
            if bverdict == 'regress':
                worst = max(worst, 1)
    return worst


if __name__ == '__main__':
    sys.exit(main())
