#!/bin/bash
# Round-5 SECOND chip window: the first window (scripts/chip_window.sh)
# drained at 07:02; the tunnel then wedged (grant held by a hard-killed
# probe client — the round-1 failure mode, reconfirmed). This queue
# fires when the tunnel heals. Same discipline: SIGINT-only timeouts,
# never kill -9 a chip client.
#
# Priority: (1) resume the north-star run toward 1M episodes — the first
# window's run hit the default 600-epoch cap after 60k episodes; the cap
# fix makes --budget-s govern. (2) measure the halo/pallas torus-conv
# variants (with the in-run parity probe). (3) longer geister
# spatial-head arms — the first window measured sp-bn 0.533 vs baseline
# 0.434 at 3.2k episodes (2.3 sigma; needs power). (4) re-score the
# extended north-star checkpoints at 1k games/point. (5) headline bench.
set -u
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=
LOG_DIR=${LOG_DIR:-/tmp/chip_window2/$(date +%m%d_%H%M%S)}
NS_BUDGET_S=${NS_BUDGET_S:-14400}
mkdir -p "$LOG_DIR"

note() { echo "$(date +%H:%M:%S) $*" >> "$LOG_DIR/queue.log"; }

# Per-item done markers make the queue RE-ENTRANT: a mid-queue tunnel
# wedge (or a supervisor restart) re-runs only unfinished items. The
# stateful items are idempotent anyway (north_star resumes from its
# checkpoints, rescores use --skip-scored).
run_item() {  # run_item NAME BUDGET_S CMD...
  local name=$1 budget=$2; shift 2
  if [ -e "$LOG_DIR/done.$name" ]; then
    note "SKIP  $name (done marker)"
    return 0
  fi
  note "START $name (budget ${budget}s): $*"
  timeout --signal=INT "$budget" "$@" > "$LOG_DIR/$name.log" 2>&1
  local rc=$?
  note "END   $name rc=$rc"
  [ "$rc" -eq 0 ] && touch "$LOG_DIR/done.$name"
}

note "=== chip window 2 opened ==="

# hbm first: ~4 min, and a halo/pallas win lets the torus default flip
# before the final bench item (and the driver's round-end bench) runs
run_item hbm_experiments 2400 python scripts/hbm_experiments.py

run_item north_star $((NS_BUDGET_S + 600)) \
  python scripts/run_north_star.py --budget-s "$NS_BUDGET_S" \
    --metrics-out north_star_device_r5.jsonl

run_item geister_arms 5400 \
  python scripts/run_benchmark_matrix.py geister-fused geister-fused-sp-bn \
    geister-fused-sp-bn-ti --epochs=120

# 1k-game rescores of the arm checkpoints (SE +-1.6% vs the ~255-game
# online rates): the decisive power for ranking the arms. --env-args
# must rebuild each arm's exact net so the checkpoint param tree loads.
run_item geister_rescore_base 1800 \
  python scripts/eval_checkpoints.py models_bench_geister-fused Geister \
    geister_arm_base_r5.jsonl --every 20 --games 1000 --skip-scored
run_item geister_rescore_spbn 1800 \
  python scripts/eval_checkpoints.py models_bench_geister-fused-sp-bn \
    Geister geister_arm_spbn_r5.jsonl --every 20 --games 1000 \
    --skip-scored --env-args '{"policy_head": "spatial", "norm_kind": "batch"}'
run_item geister_rescore_spbnti 1800 \
  python scripts/eval_checkpoints.py models_bench_geister-fused-sp-bn-ti \
    Geister geister_arm_spbnti_r5.jsonl --every 20 --games 1000 \
    --skip-scored --env-args '{"policy_head": "spatial", "norm_kind": "batch", "init_kind": "torch"}'

# LSTM-era flagship configuration (BASELINE.md matrix row 4): recurrent
# GeeseNetLSTM through the fused device pipeline, measured at the same
# protocol as the norm A/B (bonus row — not in the supervisor gate)
run_item geese_lstm 1800 \
  python scripts/run_benchmark_matrix.py geese-lstm-device --epochs=10

run_item ns_rescore_random 3600 \
  python scripts/eval_checkpoints.py models_north_star_device HungryGeese \
    north_star_device_curve_r5.jsonl --every 25 --games 1000 --skip-scored
run_item ns_rescore_rulebase 5400 \
  python scripts/eval_checkpoints.py models_north_star_device HungryGeese \
    north_star_device_curve_rulebase_r5.jsonl --every 25 --games 1000 \
    --opponent rulebase --skip-scored

BENCH_DEADLINE_SEC=900 run_item bench 960 python bench.py

note "=== queue 2 drained ==="
