"""Measure the reference-style PyTorch learner step on this host (CPU).

The reference (DeNA/HandyRL) publishes no benchmark numbers (BASELINE.md), so
the baseline is measured: a faithful PyTorch GeeseNet (12 torus-conv residual
blocks, reference hungry_geese.py:38-57) doing the reference's training step
— forward over a (B,T,P) window batch, TD(lambda) targets, policy-gradient +
value losses, backward, clipped Adam step — at the reference's default batch
geometry. Writes trajectories/sec to bench_baseline.json, which bench.py
uses as the vs_baseline denominator.

Run: python scripts/baseline_torch_learner.py [batch_size] [steps]
"""

import json
import os
import sys
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F


class TorusConv(nn.Module):
    def __init__(self, cin, cout, ksize=3, bn=True):
        super().__init__()
        self.pad = ksize // 2
        self.conv = nn.Conv2d(cin, cout, ksize)
        self.bn = nn.BatchNorm2d(cout) if bn else None

    def forward(self, x):
        h = torch.cat([x[..., -self.pad:], x, x[..., :self.pad]], dim=3)
        h = torch.cat([h[..., -self.pad:, :], h, h[..., :self.pad, :]], dim=2)
        h = self.conv(h)
        return self.bn(h) if self.bn is not None else h


class GeeseNetTorch(nn.Module):
    def __init__(self, layers=12, filters=32):
        super().__init__()
        self.conv0 = TorusConv(17, filters)
        self.blocks = nn.ModuleList([TorusConv(filters, filters) for _ in range(layers)])
        self.head_p = nn.Linear(filters, 4, bias=False)
        self.head_v = nn.Linear(filters * 2, 1, bias=False)

    def forward(self, x):
        h = F.relu(self.conv0(x))
        for b in self.blocks:
            h = F.relu(h + b(h))
        head = (h * x[:, :1]).flatten(2).sum(-1)
        avg = h.flatten(2).mean(-1)
        p = self.head_p(head)
        v = torch.tanh(self.head_v(torch.cat([head, avg], 1)))
        return p, v


def td_lambda_torch(values, returns_last, rewards, lmb, gamma):
    T = values.shape[1]
    tv = [None] * T
    tv[T - 1] = returns_last
    for t in range(T - 2, -1, -1):
        tv[t] = rewards[:, t] + gamma * ((1 - lmb) * values[:, t + 1] + lmb * tv[t + 1])
    return torch.stack(tv, dim=1)


def measure(B, T, steps, bf16=False):
    torch.manual_seed(0)
    rng = np.random.RandomState(0)

    model = GeeseNetTorch()
    model.train()
    opt = torch.optim.Adam(model.parameters(), lr=1e-5, weight_decay=1e-5)

    obs = torch.from_numpy(rng.rand(B, T, 17, 7, 11).astype(np.float32))
    actions = torch.from_numpy(rng.randint(0, 4, (B, T, 1)).astype(np.int64))
    b_prob = torch.full((B, T, 1), 0.25)
    outcome = torch.from_numpy(np.sign(rng.randn(B, 1, 1)).astype(np.float32))
    rewards = torch.zeros(B, T, 1)

    def loss_fn():
        p, v = model(obs.flatten(0, 1))
        p = p.unflatten(0, (B, T))
        v = v.unflatten(0, (B, T)).float()
        logp = F.log_softmax(p.float(), -1).gather(-1, actions)
        with torch.no_grad():
            rho = torch.clamp((logp.detach() - b_prob.log()).exp(), 0, 1)
            targets = td_lambda_torch(v.detach(), outcome[:, 0], rewards, 0.7, 1.0)
            adv = rho * (targets - v.detach())
        return (-logp * adv).sum() + ((v - targets) ** 2).sum() / 2

    def one_step():
        # bf16: autocast the net (convs/matmuls in bfloat16 — the same
        # activations-only reduction the jax learner's compute_dtype
        # applies; params/optimizer stay fp32 in both)
        if bf16:
            with torch.autocast('cpu', dtype=torch.bfloat16):
                loss = loss_fn()
        else:
            loss = loss_fn()
        opt.zero_grad()
        loss.backward()
        nn.utils.clip_grad_norm_(model.parameters(), 4.0)
        opt.step()

    for _ in range(3):
        one_step()
    t0 = time.time()
    for _ in range(steps):
        one_step()
    dt = time.time() - t0
    return B * steps / dt


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    T = 16

    out = {
        'torch_cpu_trajectories_per_sec': measure(B, T, steps, bf16=False),
        'torch_cpu_bf16_trajectories_per_sec': measure(B, T, steps, bf16=True),
        'batch_size': B, 'forward_steps': T,
        'model': 'GeeseNet(12x32 torus-conv)',
        'device': 'cpu', 'torch_version': torch.__version__,
        'note': 'reference-style learner step measured on this host, fp32 '
                'and bf16-autocast; see scripts/baseline_torch_learner.py',
    }
    path = os.path.join(os.path.dirname(__file__), '..', 'bench_baseline.json')
    with open(os.path.abspath(path), 'w') as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == '__main__':
    main()
