"""Plot generation-outcome statistics from a training stdout log.

Parses ``generation stats = MEAN +- STD`` lines (one per epoch).

Usage: python scripts/stats_plot.py LOG_FILE [OUT.png]
"""

import re
import sys

STATS_RE = re.compile(r'^generation stats = (-?[\d.]+) \+- ([\d.]+)')


def parse(path):
    rows = []
    with open(path) as f:
        for line in f:
            m = STATS_RE.match(line)
            if m:
                rows.append((float(m.group(1)), float(m.group(2))))
    return rows


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else 'train.log'
    out = sys.argv[2] if len(sys.argv) > 2 else None
    rows = parse(path)
    if not rows:
        print('no generation-stats lines found in', path)
        return
    print('%d points, last mean=%.3f std=%.3f' % (len(rows), *rows[-1]))
    try:
        import matplotlib
        matplotlib.use('Agg')
        import matplotlib.pyplot as plt
    except ImportError:
        print('matplotlib not available; printed summary only')
        return
    means = [r[0] for r in rows]
    stds = [r[1] for r in rows]
    fig, ax = plt.subplots(figsize=(8, 5))
    ax.plot(means, label='mean outcome')
    ax.fill_between(range(len(rows)),
                    [m - s for m, s in rows], [m + s for m, s in rows],
                    alpha=0.2)
    ax.set_xlabel('epoch')
    ax.set_ylabel('self-play outcome')
    ax.legend()
    out = out or path + '.stats.png'
    fig.savefig(out, dpi=120, bbox_inches='tight')
    print('wrote', out)


if __name__ == '__main__':
    main()
