"""A/B: GroupNorm vs full BatchNorm in GeeseNet (VERDICT r4 #2).

The round-4 Geister forensics proved the GroupNorm-for-BatchNorm
substitution causes that env's quality gap (reference drops 0.661 → 0.486
when its BatchNorm2d is shimmed to GroupNorm). The reference GeeseNet
carries BatchNorm in the stem + all 12 torus blocks
(reference hungry_geese.py:23-35,43-44), so the same substitution sits
under the flagship net — this measures whether it matters there.

Arms are config-only: identical budget/seeds/geometry through the fused
device pipeline (the geese-device row's config), differing only in
env_args norm_kind ('group' = repo baseline, 'batch' = full reference
BatchNorm parity with running-average inference). Win rates are scored
per opponent — 'rulebase' (the GreedyAgent behavioral port) keeps
discriminating after vs-random saturates.

Run: JAX_PLATFORMS=cpu python scripts/geese_norm_ab.py
     [--epochs N] [--arms group,batch]
Appends one JSON row per arm to benchmarks.jsonl.
"""

import json
import os
import sys
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))


def run_arm(norm_kind: str, epochs: int):
    import jax
    if os.environ.get('JAX_PLATFORMS', '').strip() == 'cpu':
        jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner

    raw = {
        'env_args': {'env': 'HungryGeese', 'norm_kind': norm_kind},
        'train_args': {
            'turn_based_training': False, 'observation': True,
            'gamma': 0.99, 'forward_steps': 16, 'compress_steps': 4,
            'batch_size': 64, 'update_episodes': 100,
            'minimum_episodes': 200, 'epochs': epochs,
            'generation_envs': 64, 'num_batchers': 1, 'eval_envs': 32,
            'policy_target': 'VTRACE', 'value_target': 'VTRACE',
            'device_generation': True, 'device_replay': True,
            'device_chunk_steps': 32, 'sgd_steps_per_chunk': 64,
            'eval': {'opponent': ['random', 'rulebase']},
            'model_dir': 'models_ab_norm_%s' % norm_kind,
        },
    }
    args = apply_defaults(raw)
    t0 = time.time()
    learner = Learner(args=args)
    learner.run()
    wall = time.time() - t0

    last = learner.model_epoch - 1
    per_opp = {}
    for epoch in range(max(1, last - 4), last + 1):
        for opp, (en, er, _) in \
                learner.results_per_opponent.get(epoch, {}).items():
            n0, r0 = per_opp.get(opp, (0, 0.0))
            per_opp[opp] = (n0 + en, r0 + er)
    rates = {opp: round((r0 / (n0 + 1e-6) + 1) / 2, 3)
             for opp, (n0, r0) in per_opp.items()}
    games = {opp: n0 for opp, (n0, _) in per_opp.items()}
    return {
        'row': 'geese-norm-ab',
        'norm_kind': norm_kind,
        'backend': jax.default_backend(),
        'epochs': learner.model_epoch,
        'episodes': learner.num_returned_episodes,
        'win_rate_last5': rates, 'eval_games': games,
        'episodes_per_sec': round(learner.num_returned_episodes / wall, 2),
        'wall_s': round(wall, 1),
        'time': time.strftime('%Y-%m-%d %H:%M:%S'),
    }


def main():
    epochs, arms = 10, ['group', 'batch']
    argv = iter(sys.argv[1:])
    for a in argv:
        key, _, val = a.partition('=')
        if key in ('--epochs', '--arms') and not val:
            try:
                val = next(argv)
            except StopIteration:
                raise SystemExit('%s needs a value' % key)
        if key == '--epochs':
            epochs = int(val)
        elif key == '--arms':
            arms = val.split(',')
        else:
            raise SystemExit('unknown argument %r' % a)
    out = os.path.join(os.path.dirname(__file__), '..', 'benchmarks.jsonl')
    for nk in arms:
        row = run_arm(nk, epochs)
        print(json.dumps(row), flush=True)
        with open(os.path.abspath(out), 'a') as f:
            f.write(json.dumps(row) + '\n')


if __name__ == '__main__':
    main()
