"""CI chaos smoke for the durable training plane
(docs/large_scale_training.md, "Zero-loss training plane").

Runs a REAL learner + worker-host fleet over TCP, SIGKILLs the learner
mid-epoch (after its first model update, with in-flight tasks booked and
admitted episodes sitting past the last ledger snapshot), restarts it with
``restart_epoch: -1``, and proves the headline contract:

  * the restarted learner adopts the run token and restores the persisted
    ledger book (``durable plane: restored ledger book``);
  * >= 1 admitted episode is replayed from the spool — episodes the dead
    process had counted but never checkpointed
    (``durable plane: recovered N spooled episode(s)``);
  * the ORIGINAL worker-host gathers ride through: resume-token handshake
    (``reattached across a learner restart``), ZERO gather respawns;
  * the exact epoch budget completes with converged accounting — nothing
    double-counts, nothing is lost;
  * restart MTTR (SIGKILL -> first post-restart train step) is measured
    and printed in the OK line.

Runs under ``HANDYRL_TPU_SANITIZE=1`` in CI like the other chaos legs.
Exits 0 on success, 1 with a reason on any failure. Stdlib + repo only.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ENTRY_PORT = int(os.environ.get('HANDYRL_TPU_ENTRY_PORT', 21940))
DATA_PORT = int(os.environ.get('HANDYRL_TPU_DATA_PORT', 21941))

LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax, json
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 3,
                          'forward_steps': 8, 'num_batchers': 1,
                          'restart_epoch': -1,
                          'model_dir': %(model_dir)r,
                          'fault_tolerance': {
                              'heartbeat_interval': 1.0,
                              'liveness_timeout': 8.0,
                              'rpc_timeout': 30.0,
                              'task_deadline': 30.0,
                              'reconnect_initial_delay': 0.25,
                              'reconnect_max_delay': 1.0,
                              'reconnect_max_tries': 240}}}
    args = apply_defaults(raw)
    learner = Learner(args=args, remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, learner.num_episodes,
          learner.num_returned_episodes, flush=True)
    print('LEDGER', json.dumps(learner.ledger.stats), flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


def _wait_for(predicate, deadline, poll=0.25):
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    work = tempfile.mkdtemp(prefix='learner_chaos_smoke.')
    model_dir = os.path.join(work, 'models')
    learner_py = os.path.join(work, 'learner.py')
    worker_py = os.path.join(work, 'worker.py')
    with open(learner_py, 'w') as f:
        f.write(LEARNER_SCRIPT % {'model_dir': model_dir})
    with open(worker_py, 'w') as f:
        f.write(WORKER_SCRIPT)

    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'HANDYRL_TPU_ENTRY_PORT': str(ENTRY_PORT),
           'HANDYRL_TPU_DATA_PORT': str(DATA_PORT),
           'PYTHONPATH': REPO + os.pathsep + os.environ.get('PYTHONPATH', '')}
    log1_path = os.path.join(work, 'learner1.log')
    log2_path = os.path.join(work, 'learner2.log')
    worker_path = os.path.join(work, 'worker.log')

    def read(path):
        try:
            with open(path) as f:
                return f.read()
        except OSError:
            return ''

    learner2 = worker = None
    log1 = open(log1_path, 'w')
    log2 = open(log2_path, 'w')
    worker_log = open(worker_path, 'w')
    learner1 = subprocess.Popen([sys.executable, learner_py], env=env,
                                stdout=log1, stderr=subprocess.STDOUT)
    try:
        time.sleep(3)
        worker = subprocess.Popen([sys.executable, worker_py], env=env,
                                  stdout=worker_log,
                                  stderr=subprocess.STDOUT)

        # wait for one full epoch (checkpoint + ledger snapshot exist),
        # then a little mid-epoch churn so admitted episodes sit past the
        # snapshot horizon and in-flight tasks are booked — then murder it
        assert _wait_for(lambda: 'updated model' in read(log1_path)
                         or learner1.poll() is not None, time.time() + 300), \
            'fleet never completed its first model update'
        assert learner1.poll() is None, 'learner died before the kill'
        time.sleep(2.0)
        kill_at = time.monotonic()
        learner1.send_signal(signal.SIGKILL)
        learner1.wait(timeout=30)

        learner2 = subprocess.Popen([sys.executable, learner_py], env=env,
                                    stdout=log2, stderr=subprocess.STDOUT)
        # restart MTTR: SIGKILL -> the restarted learner's first train step
        assert _wait_for(lambda: 'updated model' in read(log2_path)
                         or learner2.poll() is not None, time.time() + 300), \
            'restarted learner never reached a train step'
        mttr = time.monotonic() - kill_at

        assert _wait_for(lambda: 'LEARNER DONE' in read(log2_path)
                         or learner2.poll() is not None, time.time() + 300), \
            'restarted learner hung before finishing its budget'
        learner2.wait(timeout=120)
        worker.wait(timeout=120)

        out2 = read(log2_path)
        worker_out = read(worker_path)
        assert 'durable plane: restored ledger book' in out2, \
            'restart never restored the persisted ledger book'
        assert 'durable plane: recovered' in out2, \
            'restart recovered zero spooled episodes'
        recovered = int(out2.split('durable plane: recovered', 1)[1]
                        .strip().split()[0])
        assert recovered >= 1, 'spool recovery replayed no episodes'
        assert 'reattached across a learner restart' in worker_out, \
            'no gather went through the resume-token reattach'
        assert 'respawning' not in worker_out, \
            'a gather respawned — the fleet did not ride through'
        done_line = [l for l in out2.splitlines()
                     if l.startswith('LEARNER DONE')][0]
        _, _, epoch, _n_eps, num_returned = done_line.split()
        assert int(epoch) == 3, 'budget incomplete: epoch %s' % epoch
        assert int(num_returned) >= 36, \
            'accounting did not converge: %s returned' % num_returned
        ledger = json.loads(
            read(log2_path).split('LEDGER', 1)[1].strip().splitlines()[0])

        print('learner chaos smoke OK: SIGKILL mid-epoch -> restart '
              'recovered %d spooled episode(s), restored book re-issued %d, '
              'gathers reattached with 0 respawns, budget completed at '
              'epoch %s (%s episodes); restart MTTR %.1fs'
              % (recovered, ledger.get('reissued', 0), epoch,
                 num_returned, mttr), flush=True)
        return 0
    finally:
        for proc in (worker, learner2, learner1):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
        log1.close()
        log2.close()
        worker_log.close()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == '__main__':
    raise SystemExit(main())
