"""Distributed worker-fleet scaling: episodes/sec vs worker count.

The reference's headline deployment is many worker hosts feeding one
learner over TCP (reference worker.py:169-254, docs/large_scale_training
.md). This measures the same axis here: a --train-server Learner and one
worker host process with ``num_parallel`` = N (the reference's per-host
fleet knob), N swept over 1/2/4/8/16, steady-state episodes/sec sampled
at the learner AFTER a warmup interval so compile + handshake don't
pollute the number.

One-host caveat (recorded with every row): learner SGD, the Hub, and all
N worker processes share this box's single CPU core, so the curve shows
where the shared-core ceiling lands, not the DCN protocol's limit; on a
real deployment the workers' generation compute is elsewhere and only
the (measured-cheap) framed-msgpack ingest path remains at the learner.

Run: JAX_PLATFORMS=cpu python scripts/worker_scaling_bench.py
     [--workers 1,2,4,8,16] [--window 55] [--warmup 20]
Appends one JSON row per N to benchmarks.jsonl.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LEARNER_SCRIPT = r'''
import json, os, sys, threading, time
os.environ['JAX_PLATFORMS'] = 'cpu'


def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner

    warmup = float(sys.argv[1])
    window = float(sys.argv[2])
    out_path = sys.argv[3]

    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 64, 'update_episodes': 10**9,
                          'minimum_episodes': 10**9,  # never train:
                          'epochs': 1,                # isolate ingest
                          'forward_steps': 8, 'num_batchers': 1,
                          'model_dir': os.path.join(
                              os.path.dirname(out_path), 'models')}}
    args = apply_defaults(raw)
    learner = Learner(args=args, remote=True)

    def monitor():
        # readiness gate: the fleet is "up" once episodes actually flow
        # (spawned workers re-import jax serially on a shared core, so a
        # fixed sleep can open the window mid-ramp); ``warmup`` is then a
        # settling pad after first arrival, capped by ready_deadline
        t0 = time.time()
        ready_deadline = t0 + 600
        while (learner.num_returned_episodes == 0
               and time.time() < ready_deadline):
            time.sleep(0.5)
        time.sleep(warmup)
        n0, s0 = learner.num_returned_episodes, time.time()
        time.sleep(window)
        n1, s1 = learner.num_returned_episodes, time.time()
        with open(out_path, 'w') as f:
            json.dump({'episodes': n1 - n0, 'seconds': s1 - s0,
                       'eps_per_sec': (n1 - n0) / (s1 - s0)}, f)
        # unblock the server accept loop promptly
        os._exit(0)

    threading.Thread(target=monitor, daemon=True).start()
    learner.run()


if __name__ == '__main__':   # spawn-context safe (WorkerCluster)
    main()
'''

WORKER_SCRIPT = r'''
import os, sys
os.environ['JAX_PLATFORMS'] = 'cpu'


def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost',
                            'num_parallel': int(sys.argv[1])}}
    worker_main(args, [])


if __name__ == '__main__':   # spawn-context safe (WorkerCluster)
    main()
'''


def measure(n_workers: int, warmup: float, window: float,
            hosts_mode: bool = False):
    """hosts_mode=False: ONE worker host, num_parallel=N (for N<=16 its
    default_num_gathers gives a single learner-side data connection).
    hosts_mode=True: N worker host processes, num_parallel=1 each — N
    entry handshakes, N Gather connections, N Hub endpoints at the
    learner, i.e. the actual multi-host fan-in path."""
    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'PYTHONPATH': REPO + os.pathsep + os.environ.get('PYTHONPATH', '')}
    with tempfile.TemporaryDirectory() as td:
        learner_py = os.path.join(td, 'learner.py')
        worker_py = os.path.join(td, 'worker.py')
        out_path = os.path.join(td, 'result.json')
        with open(learner_py, 'w') as f:
            f.write(LEARNER_SCRIPT)
        with open(worker_py, 'w') as f:
            f.write(WORKER_SCRIPT)
        learner = subprocess.Popen(
            [sys.executable, learner_py, str(warmup), str(window), out_path],
            env=env, cwd=td, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        time.sleep(3.0)          # entry server up before workers knock
        fleet = [(1, 1)] * n_workers if hosts_mode else [(n_workers, 1)]
        workers = [subprocess.Popen(
            [sys.executable, worker_py, str(np_)],
            env=env, cwd=td, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL) for np_, _ in fleet]
        try:
            learner.wait(timeout=warmup + window + 660)
        finally:
            for proc in workers + [learner]:
                if proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)   # CPU-only: no grant
                    try:
                        proc.wait(timeout=15)
                    except subprocess.TimeoutExpired:
                        proc.kill()
        if not os.path.exists(out_path):
            return None
        with open(out_path) as f:
            return json.load(f)


def main():
    workers = [1, 2, 4, 8, 16]
    warmup, window = 20.0, 55.0
    hosts_mode = False
    argv = iter(sys.argv[1:])
    for a in argv:
        key, _, val = a.partition('=')
        if key in ('--workers', '--window', '--warmup') and not val:
            try:
                val = next(argv)
            except StopIteration:
                raise SystemExit('%s needs a value' % key)
        if key == '--workers':
            workers = [int(x) for x in val.split(',')]
        elif key == '--window':
            window = float(val)
        elif key == '--warmup':
            warmup = float(val)
        elif key == '--hosts':
            hosts_mode = True
        else:
            raise SystemExit('unknown argument %r' % a)
    out = os.path.join(REPO, 'benchmarks.jsonl')
    for n in workers:
        res = measure(n, warmup, window, hosts_mode)
        row = {'row': ('worker-scaling-hosts' if hosts_mode
                       else 'worker-scaling'),
               'workers': n,
               'episodes_per_sec': (round(res['eps_per_sec'], 2)
                                    if res else None),
               'window_s': window,
               'note': ('N worker-host procs, 1 worker each: N Gather '
                        'connections into the learner Hub'
                        if hosts_mode else
                        'one worker host, num_parallel=N: single Gather '
                        'connection') +
                       '; one shared CPU core; learner SGD disabled',
               'time': time.strftime('%Y-%m-%d %H:%M:%S')}
        print(json.dumps(row), flush=True)
        with open(out, 'a') as f:
            f.write(json.dumps(row) + '\n')


if __name__ == '__main__':
    main()
