"""Account for shard_map overhead on the virtual CPU mesh (VERDICT r3 #8).

Times ONE fused-pipeline dispatch (rollout chunk + window ingest + K SGD
steps, ops/fused_pipeline.py) at mesh sizes 1/2/4/8 with the GLOBAL
problem size held fixed (64 envs, batch 64, 16 SGD steps, 16-ply chunks —
the ttt-device benchmark geometry). On the virtual mesh every "device" is
a thread on the same physical core, so ideal scaling is FLAT wall time
(same total compute, more fixed overhead); any growth over the 1-device
row is the per-shard overhead a real ICI mesh would also pay per chip —
separated here into program count (dispatch), collective cost (psum
bytes), and small-kernel serialization.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python scripts/meshscale_bench.py [--steps N]
Appends one JSON row per mesh size to benchmarks.jsonl.
"""

import json
import os
import sys
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.setdefault(
    'XLA_FLAGS', '--xla_force_host_platform_device_count=8')
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

import jax  # noqa: E402
jax.config.update('jax_platforms', 'cpu')
import numpy as np  # noqa: E402

from handyrl_tpu.config import apply_defaults  # noqa: E402
from handyrl_tpu.environment import make_env, make_jax_env  # noqa: E402
from handyrl_tpu.model import ModelWrapper  # noqa: E402
from handyrl_tpu.ops.device_windows import DeviceWindower  # noqa: E402
from handyrl_tpu.ops.fused_pipeline import FusedPipeline  # noqa: E402
from handyrl_tpu.ops.losses import LossConfig  # noqa: E402
from handyrl_tpu.ops.train_step import init_train_state  # noqa: E402
from handyrl_tpu.parallel.mesh import make_mesh, replicated_sharding  # noqa: E402

ENVS, BATCH, SGD, CHUNK, FS = 64, 64, 16, 16, 8


def measure(ndev: int, steps: int):
    env_args = {'env': 'TicTacToe'}
    env = make_env(env_args)
    env.reset()
    wrapper = ModelWrapper(env.net())
    wrapper.ensure_params(env.observation(0))
    env_mod = make_jax_env(env_args)
    args = apply_defaults({'env_args': env_args, 'train_args': {
        'batch_size': BATCH, 'forward_steps': FS}})['train_args']
    mesh = make_mesh(jax.devices()[:ndev]) if ndev > 1 else None
    wd = DeviceWindower(mode='turn', fs=FS, bi=0, max_steps=9,
                        windows_cap=1, capacity=512 // max(1, ndev),
                        num_players=2, gamma=args['gamma'],
                        has_reward=False)
    fp = FusedPipeline(env_mod, wrapper, LossConfig.from_args(args), wd,
                       args, n_envs=ENVS, chunk_steps=CHUNK, sgd_steps=SGD,
                       batch_size=BATCH, mesh=mesh)
    # actor params must not alias the (donated) train-state params
    params = jax.tree_util.tree_map(jax.numpy.copy, wrapper.params)
    state = init_train_state(wrapper.params)
    if mesh is not None:
        repl = replicated_sharding(mesh)
        params = jax.device_put(params, repl)
        state = jax.device_put(state, repl)

    # warm the ring + compile both programs
    for _ in range(3):
        fp.warm_step(params)
    state, _ = fp.train_step(params, state, 1.0)   # compile fused
    fp.drain()

    t0 = time.time()
    for _ in range(steps):
        state, _ = fp.train_step(params, state, 1.0)
    fp.drain()                                     # hard sync
    dt = (time.time() - t0) / steps

    # program-level accounting from XLA's own cost model
    cost = {}
    try:
        lowered = fp._fused.lower(
            params, state, fp.state, fp.hidden, fp.wstate, fp.ring,
            fp.cursor, fp.size, fp.rng,
            jax.numpy.asarray(1.0, jax.numpy.float32))
        c = lowered.compile().cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0] if c else {}
        # XLA reports PER-PARTITION cost; label it so, since every other
        # field in the row (envs, batch, dispatch_ms) is global
        cost = {'flops_per_shard': float(c.get('flops', 0.0)),
                'bytes_per_shard': float(c.get('bytes accessed', 0.0))}
    except Exception as exc:  # noqa: BLE001 — accounting is best-effort
        cost = {'error': str(exc)[:80]}
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(wrapper.params))
    return {'row': 'meshscale-fused', 'ndev': ndev,
            'dispatch_ms': round(dt * 1e3, 1),
            'sgd_steps': SGD, 'envs': ENVS, 'batch': BATCH,
            'param_count': n_params,
            'psum_bytes_per_dispatch': 4 * n_params * SGD * (ndev > 1),
            **cost}


def main():
    steps = 20
    argv = iter(sys.argv[1:])
    for a in argv:
        if a.startswith('--steps='):
            steps = int(a.split('=', 1)[1])
        elif a == '--steps':
            steps = int(next(argv))
    out_path = os.path.join(os.path.dirname(__file__), '..',
                            'benchmarks.jsonl')
    for ndev in (1, 2, 4, 8):
        if ndev > len(jax.devices()):
            break
        row = measure(ndev, steps)
        row['time'] = time.strftime('%Y-%m-%d %H:%M:%S')
        print(json.dumps(row), flush=True)
        with open(os.path.abspath(out_path), 'a') as f:
            f.write(json.dumps(row) + '\n')


if __name__ == '__main__':
    main()
