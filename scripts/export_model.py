"""Export a trained model to a portable artifact.

Counterpart of the reference's ONNX export (scripts/make_onnx_model.py).
Two formats:

* default: ``jax.export`` StableHLO with params baked in — loadable by any
  JAX install with no handyrl_tpu code (see
  handyrl_tpu.evaluation.ExportedModel, the OnnxModel counterpart).
  Hidden-state inputs/outputs are preserved for recurrent nets.
* ``--torch``: a TorchScript ``.pt`` (see scripts/torch_export.py) that
  ``torch.jit.load`` runs anywhere torch does, with zero handyrl_tpu /
  jax / flax dependency — the portability contract of the reference's
  .onnx files (this image has no ONNX writer: no onnx/onnxscript/tf).
  Feed-forward architectures only; the transplant is numerically validated
  against the flax forward before the file is written.

Usage: python scripts/export_model.py [--torch] ENV CKPT_PATH OUT_PATH
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))


def main_torch(argv):
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.evaluation import load_model
    from torch_export import export_torchscript, validate_against_flax

    env_name = argv[0] if len(argv) > 0 else 'TicTacToe'
    ckpt = argv[1] if len(argv) > 1 else 'models/latest.ckpt'
    out_path = argv[2] if len(argv) > 2 else 'models/latest.pt'

    env = make_env({'env': env_name})
    env.reset()
    example_obs = env.observation(env.players()[0])
    wrapper = load_model(ckpt, env)
    arch = type(wrapper.module).__name__

    mirror = export_torchscript(arch, wrapper.params, example_obs, out_path)
    dev = validate_against_flax(mirror, wrapper, example_obs)
    print('wrote', out_path, os.path.getsize(out_path),
          'bytes (max deviation vs flax: %.2e)' % dev)

    # self-test: a fresh torch.jit.load needs none of our code
    import numpy as np
    import torch
    reloaded = torch.jit.load(out_path)
    policy, value = reloaded(torch.from_numpy(
        np.asarray(example_obs, np.float32)[None]))
    print('reload check ok; policy', tuple(policy.shape),
          'value', tuple(value.shape))


def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    from jax import export as jexport

    from handyrl_tpu.environment import make_env
    from handyrl_tpu.evaluation import load_model
    from handyrl_tpu.utils.tree import map_structure

    env_name = sys.argv[1] if len(sys.argv) > 1 else 'TicTacToe'
    ckpt = sys.argv[2] if len(sys.argv) > 2 else 'models/latest.ckpt'
    out_path = sys.argv[3] if len(sys.argv) > 3 else 'models/latest.jaxexp'

    env = make_env({'env': env_name})
    env.reset()
    example_obs = env.observation(env.players()[0])
    wrapper = load_model(ckpt, env)
    params = wrapper.params
    hidden = wrapper.init_hidden((1,))

    def infer(obs, hidden):
        return wrapper.module.apply(params, obs, hidden)

    obs_spec = map_structure(
        lambda v: jax.ShapeDtypeStruct((1,) + v.shape, jnp.float32), example_obs)
    if hidden is None:
        exported = jexport.export(jax.jit(lambda obs: infer(obs, None)))(obs_spec)
    else:
        hidden_spec = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), hidden)
        exported = jexport.export(jax.jit(infer))(obs_spec, hidden_spec)

    with open(out_path, 'wb') as f:
        f.write(exported.serialize())
    print('wrote', out_path, os.path.getsize(out_path), 'bytes')

    # self-test: reload and run
    from handyrl_tpu.evaluation import ExportedModel
    m = ExportedModel(out_path)
    out = m.inference(example_obs, m.init_hidden())
    print('reload check ok; outputs', sorted(out.keys()))


if __name__ == '__main__':
    if '--torch' in sys.argv:
        argv = [a for a in sys.argv[1:] if a != '--torch']
        main_torch(argv)
    else:
        main()
