"""Export a trained model to a portable serialized-StableHLO artifact.

Counterpart of the reference's ONNX export (scripts/make_onnx_model.py):
onnxruntime is not part of this stack, so the export format is
``jax.export`` StableHLO with params baked in — loadable by any JAX install
with no handyrl_tpu code (see handyrl_tpu.evaluation.ExportedModel, the
OnnxModel counterpart). Hidden-state inputs/outputs are preserved for
recurrent nets.

Usage: python scripts/export_model.py ENV CKPT_PATH OUT_PATH [BATCH]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))


def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    from jax import export as jexport

    from handyrl_tpu.environment import make_env
    from handyrl_tpu.evaluation import load_model
    from handyrl_tpu.utils.tree import map_structure

    env_name = sys.argv[1] if len(sys.argv) > 1 else 'TicTacToe'
    ckpt = sys.argv[2] if len(sys.argv) > 2 else 'models/latest.ckpt'
    out_path = sys.argv[3] if len(sys.argv) > 3 else 'models/latest.jaxexp'

    env = make_env({'env': env_name})
    env.reset()
    example_obs = env.observation(env.players()[0])
    wrapper = load_model(ckpt, env)
    params = wrapper.params
    hidden = wrapper.init_hidden((1,))

    def infer(obs, hidden):
        return wrapper.module.apply(params, obs, hidden)

    obs_spec = map_structure(
        lambda v: jax.ShapeDtypeStruct((1,) + v.shape, jnp.float32), example_obs)
    if hidden is None:
        exported = jexport.export(jax.jit(lambda obs: infer(obs, None)))(obs_spec)
    else:
        hidden_spec = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), hidden)
        exported = jexport.export(jax.jit(infer))(obs_spec, hidden_spec)

    with open(out_path, 'wb') as f:
        f.write(exported.serialize())
    print('wrote', out_path, os.path.getsize(out_path), 'bytes')

    # self-test: reload and run
    from handyrl_tpu.evaluation import ExportedModel
    m = ExportedModel(out_path)
    out = m.inference(example_obs, m.init_hidden())
    print('reload check ok; outputs', sorted(out.keys()))


if __name__ == '__main__':
    main()
