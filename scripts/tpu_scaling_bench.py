"""Batch-size scaling of the compiled update step on the real TPU chip.

Measures the full update step (forward + targets + losses + grads + Adam)
on GeeseNet at T=16 across a sweep of batch sizes, reporting step time,
trajectories/sec, and MFU per row. Companion to bench.py (which pins the
reference geometry B=128); this sweep shows where the chip saturates.

Usage: python scripts/tpu_scaling_bench.py [B ...] [--bf16]
(default sweep below; --bf16 clones the net with bfloat16 activations —
params stay float32, the learner's ``compute_dtype: bfloat16`` mode)
Appends rows tagged ``row: tpu-scaling`` to benchmarks.jsonl.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    import jax.numpy as jnp

    from handyrl_tpu.ops.train_step import build_update_step
    from bench import headline_setup, peak_flops, time_compiled_step

    bf16 = '--bf16' in sys.argv
    sizes = [int(a) for a in sys.argv[1:] if a.isdigit()] or \
        [64, 128, 256, 512, 1024, 2048]
    T, steps = 16, 20

    dev = jax.devices()[0]
    peak = peak_flops(dev.device_kind)
    lr = jnp.asarray(1e-5, jnp.float32)
    step_fn = None

    out_path = os.path.join(REPO, 'benchmarks.jsonl')
    for B in sizes:
        module, cfg, batch, state = headline_setup(
            B, T, dtype=jnp.bfloat16 if bf16 else None)
        if step_fn is None:
            step_fn = build_update_step(module, cfg, mesh=None, donate=False)
        dt, flops, _bytes = time_compiled_step(step_fn, state, batch, lr,
                                               steps)
        row = {'row': 'tpu-scaling', 'device': dev.device_kind, 'B': B,
               'T': T, 'dtype': 'bfloat16' if bf16 else 'float32',
               'step_ms': round(dt * 1e3, 2),
               'traj_per_sec': round(B / dt, 1),
               'flops_per_step': flops,
               'mfu': round(flops / dt / peak, 4) if peak else 0.0,
               'time': time.strftime('%Y-%m-%dT%H:%M')}
        print(json.dumps(row), flush=True)
        # append per row: a crash/OOM at a larger B keeps earlier results
        with open(out_path, 'a') as f:
            f.write(json.dumps(row) + '\n')


if __name__ == '__main__':
    main()
