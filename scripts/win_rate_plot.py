"""Plot win-rate curves from a training stdout log.

The learner's line-oriented log is the metrics interface (SURVEY.md §5.5):
this parses ``epoch N`` / ``win rate (opp) = X (w / n)`` lines and plots
win rate per opponent against episode count.

Usage: python scripts/win_rate_plot.py LOG_FILE [OUT.png]
"""

import re
import sys


EPOCH_RE = re.compile(r'^epoch (\d+)')
WIN_RE = re.compile(r'^win rate(?: \((.+)\))? = ([\d.]+) \(([\d.-]+) / (\d+)\)')
UPDATED_RE = re.compile(r'updated model\((\d+)\)')


def parse(path):
    epochs, series = [], {}
    current_epoch = None
    with open(path) as f:
        for line in f:
            m = EPOCH_RE.match(line)
            if m:
                current_epoch = int(m.group(1))
                epochs.append(current_epoch)
                continue
            m = WIN_RE.match(line)
            if m and current_epoch is not None:
                opponent = m.group(1) or 'total'
                series.setdefault(opponent, []).append(
                    (current_epoch, float(m.group(2)), int(m.group(4))))
    return epochs, series


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else 'train.log'
    out = sys.argv[2] if len(sys.argv) > 2 else None
    _, series = parse(path)
    if not series:
        print('no win-rate lines found in', path)
        return
    for opponent, rows in series.items():
        tail = rows[-1]
        print('%s: %d points, last = %.3f (epoch %d, n=%d)'
              % (opponent, len(rows), tail[1], tail[0], tail[2]))
    try:
        import matplotlib
        matplotlib.use('Agg')
        import matplotlib.pyplot as plt
    except ImportError:
        print('matplotlib not available; printed summary only')
        return
    fig, ax = plt.subplots(figsize=(8, 5))
    for opponent, rows in sorted(series.items()):
        xs = [r[0] for r in rows]
        ys = [r[1] for r in rows]
        ax.plot(xs, ys, label=opponent)
    ax.set_xlabel('epoch')
    ax.set_ylabel('win rate')
    ax.set_ylim(0, 1)
    ax.axhline(0.5, color='gray', lw=0.5)
    ax.legend()
    out = out or path + '.win_rate.png'
    fig.savefig(out, dpi=120, bbox_inches='tight')
    print('wrote', out)


if __name__ == '__main__':
    main()
