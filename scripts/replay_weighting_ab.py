"""A/B: per-window vs per-episode replay weighting (VERDICT r3 #7).

The device windower ingests up to ``replay_windows_per_episode`` (W)
uniformly-placed windows per finished episode; ring rows are then drawn
with the same recency bias regardless of origin. W>1 therefore weights
SAMPLING MASS per episode by min(len//fs, W) — long episodes get more —
while the reference draws an EPISODE first and one window inside it
(reference train.py:291-306), i.e. equal mass per episode. Because window
starts are already uniform within the episode, **W=1 is exactly the
reference's weighting**: one uniformly-placed window per episode, ring
row = episode. So the A/B is config-only: identical budget, seeds, and
geometry, W=1 (per-episode) vs the default W (per-window).

Env: HungryGeese — the long-episode env (1..200 plies, hunger-truncated),
where the two weightings actually differ.

Run: JAX_PLATFORMS=cpu python scripts/replay_weighting_ab.py
     [--epochs N] [--arms 1,4] [--init CKPT]
Appends one JSON row per arm to benchmarks.jsonl.

--init (VERDICT r4 #5 — the divergent regime): warm-start both arms from
a late-stage checkpoint (e.g. models_north_star_device/latest.ckpt) whose
policy plays LONG episodes, so min(len//fs, W) actually spreads and the
two weightings differ. Requires the full GeeseNet architecture (the
checkpoint's); the windows/episode ratio in each row is the regime gate —
rows where both arms sit near 1.0 are outside the divergent regime and
say nothing about weighting.
"""

import json
import os
import sys
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))


def run_arm(windows_cap: int, epochs: int, init_ckpt: str = ''):
    import jax
    if os.environ.get('JAX_PLATFORMS', '').strip() == 'cpu':
        jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.models import build
    from handyrl_tpu.train import Learner

    raw = {
        'env_args': {'env': 'HungryGeese'},
        'train_args': {
            'turn_based_training': False, 'observation': True,
            'gamma': 0.99, 'forward_steps': 16, 'compress_steps': 4,
            'batch_size': 32, 'update_episodes': 100,
            'minimum_episodes': 100, 'epochs': epochs,
            'generation_envs': 32, 'num_batchers': 1, 'eval_envs': 32,
            'policy_target': 'VTRACE', 'value_target': 'VTRACE',
            'device_generation': True, 'device_replay': True,
            'sgd_steps_per_chunk': 8,
            'replay_windows_per_episode': windows_cap,
            # rulebase discriminates long after vs-random saturates
            'eval': {'opponent': ['random', 'rulebase']},
            'model_dir': 'models_ab_w%d' % windows_cap,
            'init_params': init_ckpt,
        },
    }
    args = apply_defaults(raw)
    t0 = time.time()
    # --init checkpoints are full-GeeseNet snapshots; the from-scratch A/B
    # keeps the small net for CPU-budget reasons
    net = build('GeeseNet') if init_ckpt else build('GeeseNet', layers=4,
                                                    filters=16)
    learner = Learner(args=args, net=net)
    learner.run()
    wall = time.time() - t0

    last = learner.model_epoch - 1
    per_opp = {}
    for epoch in range(max(1, last - 4), last + 1):
        for opp, (en, er, _) in \
                learner.results_per_opponent.get(epoch, {}).items():
            n0, r0 = per_opp.get(opp, (0, 0.0))
            per_opp[opp] = (n0 + en, r0 + er)
    rates = {opp: round((r0 / (n0 + 1e-6) + 1) / 2, 3)
             for opp, (n0, r0) in per_opp.items()}
    games = {opp: n0 for opp, (n0, _) in per_opp.items()}
    stats = learner.trainer.replay_stats
    eps = max(1, learner.num_returned_episodes)
    return {
        'row': 'replay-weighting-ab',
        'init_ckpt': init_ckpt or None,
        'windows_per_episode_ratio': round(
            (stats.get('windows_ingested') or 0) / eps, 2),
        'windows_per_episode': windows_cap,
        'weighting': 'per-episode (reference)' if windows_cap == 1
                     else 'per-window (x%d cap)' % windows_cap,
        'backend': jax.default_backend(),
        'epochs': learner.model_epoch,
        'episodes': learner.num_returned_episodes,
        'win_rate_last5': rates, 'eval_games': games,
        'windows_ingested': stats.get('windows_ingested'),
        'samples_drawn': stats.get('samples_drawn'),
        'wall_s': round(wall, 1),
        'time': time.strftime('%Y-%m-%d %H:%M:%S'),
    }


def main():
    epochs, arms, init_ckpt = 12, [1, 4], ''
    argv = iter(sys.argv[1:])
    for a in argv:
        key, _, val = a.partition('=')
        if key in ('--epochs', '--arms', '--init') and not val:
            try:
                val = next(argv)
            except StopIteration:
                raise SystemExit('%s needs a value' % key)
        if key == '--epochs':
            epochs = int(val)
        elif key == '--arms':
            arms = [int(x) for x in val.split(',')]
        elif key == '--init':
            init_ckpt = val
        else:
            raise SystemExit('unknown argument %r' % a)
    out = os.path.join(os.path.dirname(__file__), '..', 'benchmarks.jsonl')
    for w in arms:
        row = run_arm(w, epochs, init_ckpt)
        print(json.dumps(row), flush=True)
        with open(os.path.abspath(out), 'a') as f:
            f.write(json.dumps(row) + '\n')


if __name__ == '__main__':
    main()
