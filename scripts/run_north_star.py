"""North-star quality run: long Hungry Geese self-play on the device pipeline.

BASELINE.json's quality metric is Hungry Geese win-rate-vs-random at scale
(the throughput half is covered by bench.py / run_benchmark_matrix.py).
This driver runs the geese-device config for as many episodes as the
wall-clock allows, writing one metrics-JSONL row per epoch (win_rate,
episodes, sgd steps) so scripts/north_star_curve.py can plot the
win-rate-vs-episodes curve.

The reference itself CANNOT run this env here (its HungryGeese wraps
kaggle_environments, not installed in this image — reference
envs/kaggle/hungry_geese.py:67); the same-budget dynamics control is our
host-path engine (per-episode buffer sampling faithful to reference
train.py:291-315), run with --host.

Auto-resume: if the model dir already holds checkpoints, training restarts
from the newest one (params + optimizer state), so the curve continues
across interrupted windows.

Usage:
  python scripts/run_north_star.py [--epochs N] [--host] [--budget-s S] \
      [--metrics-out PATH]

--metrics-out redirects the per-epoch metrics JSONL (default
north_star_<tag>.jsonl). Use it when the model dir starts EMPTY but the
default file already holds a previous run's epochs (the round-5 case:
checkpoints were lost to a re-provision, so a fresh run restarts at
epoch 0 — appending to the old file would interleave two incomparable
runs under the same epoch keys).
"""

import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

BASE = {
    'env_args': {'env': 'HungryGeese'},
    'train_args': {'batch_size': 64, 'forward_steps': 16,
                   'update_episodes': 100, 'minimum_episodes': 200,
                   'generation_envs': 64,
                   'turn_based_training': False, 'observation': True,
                   'gamma': 0.99,
                   'policy_target': 'VTRACE', 'value_target': 'VTRACE',
                   'device_generation': True, 'device_replay': True,
                   'device_chunk_steps': 32, 'eval_envs': 32,
                   'sgd_steps_per_chunk': 64,
                   # host snapshot + ckpt files every 10 epochs: the
                   # per-epoch state fetch+serialize was 42% of wall time
                   'checkpoint_interval': 10},
}


def latest_epoch(model_dir: str) -> int:
    if not os.path.isdir(model_dir):
        return 0
    best = 0
    for name in os.listdir(model_dir):
        m = re.match(r'^(\d+)\.ckpt$', name)
        if m:
            best = max(best, int(m.group(1)))
    return best


def main():
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner

    epochs = None   # None = not explicitly given (default 600, see below)
    host = False
    budget_s = None
    metrics_out = None
    argv = sys.argv[1:]
    while argv:
        a = argv.pop(0)
        if a == '--epochs':
            epochs = int(argv.pop(0))
        elif a == '--host':
            host = True
        elif a == '--budget-s':
            budget_s = float(argv.pop(0))
        elif a == '--metrics-out':
            metrics_out = argv.pop(0)
        else:
            raise SystemExit('unknown arg: %s' % a)

    tag = 'host' if host else 'device'
    raw = {'env_args': dict(BASE['env_args']),
           'train_args': dict(BASE['train_args'])}
    if host:
        # reference-dynamics control: same net/targets/cadence, host
        # generation + per-episode buffer sampling (reference
        # train.py:291-315 semantics), torch-free
        for k in ('device_generation', 'device_replay',
                  'device_chunk_steps', 'eval_envs', 'sgd_steps_per_chunk'):
            raw['train_args'].pop(k, None)
        raw['train_args']['generation_envs'] = 16
    model_dir = 'models_north_star_%s' % tag
    raw['train_args']['model_dir'] = model_dir
    raw['train_args']['metrics_jsonl'] = (metrics_out or
                                          'north_star_%s.jsonl' % tag)
    if epochs is None:
        # budget governs when given: the round-5 chip run stopped at the
        # DEFAULT 600-epoch cap after 17 min of a 150-min budget. Only an
        # epoch cap the operator actually TYPED limits a budgeted run —
        # `--epochs 600 --budget-s ...` really stops at 600 now.
        epochs = 10 ** 6 if budget_s is not None else 600
    raw['train_args']['epochs'] = epochs
    start = latest_epoch(model_dir)
    raw['train_args']['restart_epoch'] = start
    if budget_s is not None:
        # leave shutdown margin so the final checkpoint lands inside budget
        os.environ.setdefault('HANDYRL_TPU_DEADLINE',
                              str(time.time() + budget_s))

    args = apply_defaults(raw)
    print('north-star %s run: epochs %d->%d, model_dir=%s' %
          (tag, start, epochs, model_dir), flush=True)
    t0 = time.time()
    learner = Learner(args=args)
    learner.run()
    print(json.dumps({
        'row': 'north-star-%s' % tag,
        'epochs': learner.model_epoch,
        'episodes': learner.num_returned_episodes,
        'wall_s': round(time.time() - t0, 1),
    }), flush=True)


if __name__ == '__main__':
    main()
