"""CI chaos smoke for the replicated serving fleet (docs/serving.md,
"Serving fleet").

Runs a REAL resolver + 2 managed replica subprocesses (``python -m
handyrl_tpu.serving --fleet``) and proves the fleet's headline contract
under chaos, asserting invariants rather than throughput (CI machines are
too noisy — and often too small — for scaling thresholds):

  * routed requests answer byte-identically to a pre-kill reference
    (inference is a pure function of model version + request, so replicas
    are interchangeable);
  * a replica SIGKILLed with a burst in flight costs ZERO client-visible
    failures — stranded requests are replayed on the survivor and the
    replies stay byte-identical;
  * the resolver strands the corpse, respawns it under its old name, and
    the re-registration walks the quarantine round trip back to healthy
    (the controller's ``readmitted`` counter moves);
  * the respawned replica serves byte-identical replies again;
  * SIGTERM drains the whole fleet to exit 75 (EX_TEMPFAIL — the
    PreemptionGuard supervisor contract);
  * the collated trace holds >= 1 complete client->router->engine->reply
    chain — including >= 1 chain that crosses the SIGKILL replay under
    its ORIGINAL trace_id — and ``trace_report.py --serve --json``
    exits 0 on it.

Runs under ``HANDYRL_TPU_SANITIZE=1`` in CI like the other chaos legs:
the lock-order-inversion detector and thread accountant instrument the
resolver and every replica, and the leg must stay green.

Exits 0 on success, 1 with a reason on any failure. Stdlib + repo only.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    # serving-path tracing at rate 1.0, inherited by the resolver and
    # every replica subprocess (telemetry reads the env at import)
    trace_dir = tempfile.mkdtemp(prefix='fleet_smoke_trace.')
    os.environ['HANDYRL_TPU_TRACE'] = trace_dir
    os.environ['HANDYRL_TPU_TRACE_RATE'] = '1'
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.generation import sample_seed
    from handyrl_tpu.model import ModelWrapper
    from handyrl_tpu.serving.fleet import RoutedClient
    from handyrl_tpu.serving.registry import ModelRegistry

    env = make_env({'env': 'TicTacToe'})
    env.reset()
    obs = env.observation(env.players()[0])
    legal = env.legal_actions(env.players()[0])
    wrapper = ModelWrapper(env.net(), seed=7)
    wrapper.ensure_params(obs)

    root = tempfile.mkdtemp(prefix='fleet_smoke_registry.')
    proc = rc = None
    try:
        ModelRegistry(root).publish('default', snapshot=wrapper.snapshot(),
                                    version=1, promote=True)
        proc = subprocess.Popen(
            [sys.executable, '-m', 'handyrl_tpu.serving', '--fleet',
             '--replicas', '2', '--env', 'TicTacToe', '--registry', root,
             '--port', '0', '--line', 'default',
             '--heartbeat', '0.2', '--heartbeat-timeout', '2.0'],
            cwd=REPO, stdout=subprocess.PIPE, text=True)
        ready = json.loads(proc.stdout.readline())['fleet_ready']
        assert ready['replicas'] == 2, ready
        rc = RoutedClient('127.0.0.1', int(ready['port']), timeout=20.0,
                          refresh_interval=0.2)
        table = {r['replica']: r for r in rc.replicas()}
        assert len(table) == 2, table

        seeds = [sample_seed(11, (0, k), 0) for k in range(8)]
        refs = [rc.request('default@champion', obs, legal=legal, seed=s)
                for s in seeds]

        # SIGKILL one replica with a burst in flight (the whole burst is
        # steered onto the victim so the replay path is exercised for
        # certain, not left to round-robin timing)
        victim = sorted(table)[0]
        rids = [rc.submit('default@champion', obs, legal=legal, seed=s,
                          replica=victim)
                for s in seeds]
        os.kill(table[victim]['pid'], signal.SIGKILL)
        failures = 0
        for rid, ref in zip(rids, refs):
            rep = rc.collect(rid)
            if rep['action'] != ref['action'] or rep['prob'] != ref['prob']:
                failures += 1
        assert failures == 0, \
            '%d client-visible failure(s) through the SIGKILL' % failures

        # corpse -> quarantine -> respawn -> re-admission round trip
        round_trip = False
        states = {}
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            states = {r['replica']: r['state'] for r in rc.replicas()}
            readmitted = rc.status()['controller'].get('readmitted', 0)
            if readmitted >= 1 and states.get(victim) == 'healthy':
                round_trip = True
                break
            time.sleep(0.25)
        assert round_trip, \
            'kill never walked the quarantine round trip: %s' % states

        # the respawned replica serves byte-identical replies again
        for s, ref in zip(seeds, refs):
            rep = rc.request('default@champion', obs, legal=legal, seed=s)
            assert rep['prob'] == ref['prob'], 'post-respawn reply diverged'

        # fleet-wide graceful drain: exit 75 (EX_TEMPFAIL, restart me)
        proc.send_signal(signal.SIGTERM)
        code = proc.wait(timeout=120)
        assert code == 75, 'fleet exited %s, not 75' % code

        # the collated trace carries the whole causal story: >= 1
        # complete client->router->engine->reply chain, and >= 1 chain
        # crossing the SIGKILL replay under its ORIGINAL trace_id
        from handyrl_tpu import telemetry
        telemetry.trace_flush()
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, 'scripts', 'trace_report.py'),
             trace_dir, '--serve', '--json'],
            capture_output=True, text=True)
        assert out.returncode == 0, \
            'trace_report --serve exited %d: %s' % (out.returncode,
                                                    out.stderr[:500])
        serve = json.loads(out.stdout)['serve']
        assert serve['complete_chains'] >= 1, serve
        assert serve['routed_chains'] >= 1, serve
        assert serve['replay_chains'] >= 1, serve
        assert serve['complete_replay_chains'] >= 1, serve

        print('fleet smoke OK: %d/%d burst replies byte-identical through '
              'a replica SIGKILL, %s respawned and re-admitted, fleet '
              'drained to exit 75; trace holds %d complete serve chain(s) '
              'incl. %d crossing the replay'
              % (len(rids), len(rids), victim, serve['complete_chains'],
                 serve['complete_replay_chains']))
        return 0
    finally:
        if rc is not None:
            rc.close()
        if proc is not None and proc.poll() is None:
            proc.kill()
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(trace_dir, ignore_errors=True)


if __name__ == '__main__':
    raise SystemExit(main())
