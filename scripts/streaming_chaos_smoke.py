"""CI chaos smoke for streaming partial-episode ingest
(docs/large_scale_training.md, "Streaming ingest").

Runs a REAL learner + worker-host fleet over TCP with the ``streaming:``
block enabled (chunked uploads, staleness-aware selection), SIGKILLs the
host's only gather mid-run via the chaos harness, and proves the chunked
pipeline survives exactly like the whole-episode one:

  * workers flush fixed-T window chunks through the upload path — the
    learner ingests a meaningful number of them
    (``chunks_ingested_total``) and reassembles whole episodes
    (``streaming_reassembled_episodes_total``);
  * the killed gather strands in-flight chunk streams; the supervisor
    respawns it, the stranded tasks re-issue with their ORIGINAL
    sample_keys, and the regenerated chunks MERGE into the stranded
    assemblies instead of double-counting (accounting converges to the
    exact budget);
  * the run completes its epoch budget — partially-delivered episodes
    never wedge the learner.

Runs under ``HANDYRL_TPU_SANITIZE=1`` in CI like the other chaos legs.
Exits 0 on success, 1 with a reason on any failure. Stdlib + repo only.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ENTRY_PORT = int(os.environ.get('HANDYRL_TPU_ENTRY_PORT', 21950))
DATA_PORT = int(os.environ.get('HANDYRL_TPU_DATA_PORT', 21951))

LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax, json
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu import telemetry
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 3,
                          'forward_steps': 8, 'num_batchers': 1,
                          'compress_steps': 2,
                          'model_dir': %(model_dir)r,
                          # chunk_steps 2 on TicTacToe's 5-9 ply games
                          # makes EVERY episode multi-chunk, so the kill
                          # is guaranteed to strand partial streams
                          'streaming': {'enabled': True, 'chunk_steps': 2,
                                        'staleness_half_life': 30.0},
                          'fault_tolerance': {
                              'heartbeat_interval': 1.0,
                              'liveness_timeout': 8.0,
                              'rpc_timeout': 30.0,
                              'task_deadline': 30.0,
                              'reconnect_initial_delay': 0.25,
                              'reconnect_max_delay': 1.0,
                              'reconnect_max_tries': 240}}}
    args = apply_defaults(raw)
    learner = Learner(args=args, remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, learner.num_episodes,
          learner.num_returned_episodes, flush=True)
    print('LEDGER', json.dumps(learner.ledger.stats), flush=True)
    print('CHUNKS',
          telemetry.counter('chunks_ingested_total').value,
          telemetry.counter('streaming_reassembled_episodes_total').value,
          telemetry.counter('chunk_duplicates_total').value, flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


def _wait_for(predicate, deadline, poll=0.25):
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    work = tempfile.mkdtemp(prefix='streaming_chaos_smoke.')
    model_dir = os.path.join(work, 'models')
    learner_py = os.path.join(work, 'learner.py')
    worker_py = os.path.join(work, 'worker.py')
    with open(learner_py, 'w') as f:
        f.write(LEARNER_SCRIPT % {'model_dir': model_dir})
    with open(worker_py, 'w') as f:
        f.write(WORKER_SCRIPT)

    base_env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
                'HANDYRL_TPU_ENTRY_PORT': str(ENTRY_PORT),
                'HANDYRL_TPU_DATA_PORT': str(DATA_PORT),
                'PYTHONPATH': REPO + os.pathsep
                + os.environ.get('PYTHONPATH', '')}
    # chaos: SIGKILL the host's single gather once, early in the run —
    # after generation is underway, so in-flight chunk streams strand
    worker_env = {**base_env,
                  'HANDYRL_TPU_CHAOS': 'kill_gather=8,max_kills=1,seed=5'}
    learner_path = os.path.join(work, 'learner.log')
    worker_path = os.path.join(work, 'worker.log')

    def read(path):
        try:
            with open(path) as f:
                return f.read()
        except OSError:
            return ''

    worker = None
    learner_log = open(learner_path, 'w')
    worker_log = open(worker_path, 'w')
    learner = subprocess.Popen([sys.executable, learner_py], env=base_env,
                               stdout=learner_log,
                               stderr=subprocess.STDOUT)
    try:
        time.sleep(3)   # let the entry/data servers bind
        worker = subprocess.Popen([sys.executable, worker_py],
                                  env=worker_env, stdout=worker_log,
                                  stderr=subprocess.STDOUT)

        assert _wait_for(lambda: 'LEARNER DONE' in read(learner_path)
                         or learner.poll() is not None, time.time() + 420), \
            'fleet hung before finishing its epoch budget'
        learner.wait(timeout=120)
        worker.wait(timeout=120)

        learner_out = read(learner_path)
        worker_out = read(worker_path)

        # the chaos kill actually happened and the supervisor recovered it
        assert 'chaos: killing gather' in worker_out, \
            'chaos harness never killed the gather'
        assert 'respawning' in worker_out, \
            'the killed gather was never respawned'

        # the budget completed with converged accounting despite the
        # stranded chunk streams
        done_line = [l for l in learner_out.splitlines()
                     if l.startswith('LEARNER DONE')][0]
        _, _, epoch, _n_eps, num_returned = done_line.split()
        assert int(epoch) == 3, 'budget incomplete: epoch %s' % epoch
        assert int(num_returned) >= 36, \
            'accounting did not converge: %s returned' % num_returned

        # streaming was genuinely exercised: multi-chunk episodes flowed
        # and reassembled (chunk_steps 2 means >= 2 chunks per episode)
        chunks_line = [l for l in learner_out.splitlines()
                       if l.startswith('CHUNKS')][0]
        _, ingested, reassembled, dupes = chunks_line.split()
        assert int(ingested) >= 2 * int(num_returned) // 2, \
            'too few chunks ingested (%s) for %s episodes' % (
                ingested, num_returned)
        assert int(reassembled) >= 36, \
            'assembler reassembled only %s episodes' % reassembled

        ledger = json.loads(
            learner_out.split('LEDGER', 1)[1].strip().splitlines()[0])
        assert ledger['completed'] <= ledger['assigned']

        print('streaming chaos smoke OK: gather SIGKILL mid-stream -> '
              'respawned, budget completed at epoch %s; %s chunks '
              'ingested, %s episodes reassembled, %s duplicate chunk(s) '
              'screened, %d task(s) re-issued'
              % (epoch, ingested, reassembled, dupes,
                 ledger.get('reissued', 0)), flush=True)
        return 0
    finally:
        for proc in (worker, learner):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
        learner_log.close()
        worker_log.close()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == '__main__':
    raise SystemExit(main())
