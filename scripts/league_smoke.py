"""CI smoke for league training (docs/league.md).

Runs a REAL learner + worker-host fleet over TCP with ``league.enabled``
(tiny CPU geometry, a few epochs) and proves the headline contract
without throughput thresholds:

  * PFSP sampling draws >= 2 DISTINCT registry opponent versions into
    'g' episodes (the pool is a population, not just the newest ckpt);
  * the RatingBook journal lands on disk, is non-empty, and round-trips
    through the book bit-identically (the restart-survival contract);
  * every metrics_jsonl record carries the league block, and
    ``scripts/league_report.py`` renders the stream (exit 0).

Runs under ``HANDYRL_TPU_SANITIZE=1`` in CI like the other fleet legs:
the lock-order-inversion detector and thread accountant instrument the
learner and the worker host, and the leg must stay green.

Exits 0 on success, 1 with a reason on any failure. Stdlib + repo only.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 10,
                          'minimum_episodes': 10, 'epochs': 5,
                          'forward_steps': 8, 'num_batchers': 1,
                          'eval_rate': 0.3, 'seed': 11,
                          'keep_checkpoints': 3,
                          'metrics_jsonl': %(metrics)r,
                          'model_dir': %(model_dir)r,
                          'serving': {'publish': True, 'line': 'default'},
                          'league': {'enabled': True, 'self_play_rate': 0.0,
                                     'rating_match_rate': 1.0,
                                     'curve': 'uniform', 'min_games': 1,
                                     'promote_margin': 0.0}}}
    learner = Learner(args=apply_defaults(raw), remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    work = tempfile.mkdtemp(prefix='league_smoke.')
    model_dir = os.path.join(work, 'models')
    metrics = os.path.join(work, 'metrics.jsonl')
    journal = os.path.join(model_dir, 'league_ratings.json')
    learner_py = os.path.join(work, 'learner.py')
    worker_py = os.path.join(work, 'worker.py')
    with open(learner_py, 'w') as f:
        f.write(LEARNER_SCRIPT % {'model_dir': model_dir, 'metrics': metrics})
    with open(worker_py, 'w') as f:
        f.write(WORKER_SCRIPT)
    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'PYTHONPATH': REPO + os.pathsep + os.environ.get('PYTHONPATH', '')}

    learner = worker = None
    learner_log = open(os.path.join(work, 'learner.log'), 'w')
    worker_log = open(os.path.join(work, 'worker.log'), 'w')
    try:
        learner = subprocess.Popen([sys.executable, learner_py], env=env,
                                   stdout=learner_log,
                                   stderr=subprocess.STDOUT)
        time.sleep(3)   # let the entry/worker servers bind
        worker = subprocess.Popen([sys.executable, worker_py], env=env,
                                  stdout=worker_log,
                                  stderr=subprocess.STDOUT)
        deadline = time.time() + 240
        while time.time() < deadline and learner.poll() is None:
            time.sleep(2)
        assert learner.poll() is not None, 'learner never finished its epochs'
        assert learner.returncode == 0, \
            'learner exited %s' % learner.returncode

        # ratings journal: on disk, non-empty, bit-identical round trip
        assert os.path.exists(journal), 'no ratings journal at %s' % journal
        raw = open(journal, 'rb').read()
        state = json.loads(raw)
        assert state['entries'], 'ratings journal booked no games'
        from handyrl_tpu.league import RatingBook
        book = RatingBook()
        assert book.load(journal), 'journal did not reload'
        again = os.path.join(work, 'roundtrip.json')
        book.save(again)
        assert open(again, 'rb').read() == raw, \
            'journal round trip is not bit-identical'

        # metrics: league block on every record, >= 2 distinct versions
        sampled = set()
        league_recs = total_recs = 0
        with open(metrics) as f:
            for line in f:
                rec = json.loads(line)
                total_recs += 1
                lg = rec.get('league')
                if lg:
                    league_recs += 1
                    sampled.update(lg.get('opponents_sampled') or {})
        assert league_recs == total_recs > 0, \
            'league block on %d/%d records' % (league_recs, total_recs)
        versions = {m for m in sampled if '@' in m}
        assert len(versions) >= 2, \
            'PFSP sampled %r: wanted >= 2 registry versions' % (sampled,)

        # the report renders the stream
        rep = subprocess.run(
            [sys.executable, os.path.join(REPO, 'scripts/league_report.py'),
             metrics, '--journal', journal],
            capture_output=True, text=True, timeout=60)
        assert rep.returncode == 0, 'league_report failed: %s' % rep.stderr
        assert 'champion' in rep.stdout and 'learner' in rep.stdout

        print('league smoke OK: %d league records, versions sampled %s, '
              'journal %d entries round-tripped bit-identically'
              % (league_recs, sorted(versions), len(state['entries'])))
        return 0
    finally:
        for proc in (worker, learner):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
        learner_log.close()
        worker_log.close()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == '__main__':
    raise SystemExit(main())
