"""CI smoke for the standalone model-serving tier (docs/serving.md).

Runs ``bench.py`` in BENCH_MODE=serve on a tiny CPU geometry (TicTacToe,
4 clients) — a REAL InferenceService subprocess with a registry-resolved
model behind the framed INFER protocol — and asserts the service contract
rather than a throughput number (CI machines are too noisy for thresholds):

  * the run completes and honors the one-JSON-line stdout contract;
  * the engines actually batch across clients (batch-fill > 1) and nothing
    is shed at this load (shed_total == 0) with zero client errors;
  * the graceful-drain contract holds: every request in flight through the
    SIGTERM is answered (drain_unanswered == 0) and the service exits 75
    (EX_TEMPFAIL — the PreemptionGuard supervisor contract).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ)
    env.update({
        'JAX_PLATFORMS': 'cpu',
        'BENCH_MODE': 'serve',
        'BENCH_SERVE_ENV': 'TicTacToe',
        'BENCH_SERVE_CLIENTS': '4',
        'BENCH_SERVE_REQUESTS': '10',
        'BENCH_SERVE_WARMUP': '2',
        'BENCH_SERVE_DRAIN': '2',
        # generous coalescing window: the smoke asserts batching works, not
        # that it is fast, and CI boxes schedule client threads erratically
        'BENCH_SERVE_WAIT_MS': '20',
        # single-service contract only: the fleet phase has its own leg
        # (scripts/fleet_smoke.py) with chaos assertions
        'BENCH_SERVE_REPLICAS': '0',
        'BENCH_DEADLINE_SEC': env.get('BENCH_DEADLINE_SEC', '540'),
    })
    proc = subprocess.run([sys.executable, os.path.join(REPO, 'bench.py')],
                          env=env, stdout=subprocess.PIPE, text=True,
                          timeout=600)
    out = proc.stdout.strip().splitlines()
    assert len(out) == 1, 'one-JSON-line contract violated: %r' % (out,)
    row = json.loads(out[0])
    print(json.dumps(row, indent=2))
    assert 'error' not in row, row.get('error')
    assert row['value'] > 0, 'service produced no measured requests'
    assert row['client_errors'] == 0, row
    assert row['shed_total'] == 0, \
        'requests shed at the smoke load (shed_total %d)' % row['shed_total']
    assert row['batch_fill'] > 1.0, \
        'service never batched past 1 request/forward (fill %.2f)' \
        % row['batch_fill']
    assert row['drain_unanswered'] == 0, \
        '%d request(s) dropped un-answered through the SIGTERM drain' \
        % row['drain_unanswered']
    assert row['drain_exit_code'] == 75, \
        'service exited %s, not the supervisor-contract 75' \
        % row['drain_exit_code']
    print('serve smoke OK: %.1f req/s at %d clients (fill %.2f), '
          'drain %d/%d answered, exit 75'
          % (row['value'], row['clients'], row['batch_fill'],
             row['drain_answered'], row['drain_requests']))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
