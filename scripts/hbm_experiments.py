"""Close the gap to the HBM roofline floor (VERDICT r3 #4).

The round-3 roofline: the headline update step (GeeseNet B=128 T=16,
bf16 activations) moves 4.26 GB HBM/step, a 5.2 ms floor at the v5e's
819 GB/s, but measures 15.24 ms — MBU 34%. This script produces the two
artifacts the verdict asked for, ON the accelerator:

1. a per-op HBM-traffic table: the compiled executable's optimized HLO,
   each top-level instruction scored by the buffer bytes it touches
   (operands + outputs), sorted — names which convs/fusions carry the
   4.26 GB and whether XLA materializes something avoidable;
2. step-time variants: fp32 / bf16-activations / bf16-activations +
   bf16 params+Adam-moments (halves parameter+optimizer traffic; the
   quality impact is NOT evaluated here — this is a bandwidth
   experiment, not a training recommendation).

Run (needs the TPU): python scripts/hbm_experiments.py [--steps 30]
Appends rows to benchmarks.jsonl and prints the table.

--B/--T shrink the geometry for an off-chip plumbing dry-run
(JAX_PLATFORMS=cpu ... --B 8 --T 4 --steps 2); rows from non-default
geometry are tagged 'dryrun' and are NOT roofline evidence.
"""

import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

_DTYPE_BYTES = {'f32': 4, 'bf16': 2, 'f16': 2, 's32': 4, 'u32': 4,
                's8': 1, 'u8': 1, 'pred': 1, 's64': 8, 'u64': 8, 'f64': 8,
                's16': 2, 'u16': 2}


def _shape_bytes(shape_str: str) -> int:
    """'bf16[128,32,7,11]{3,2,1,0}' -> element bytes x product(dims).
    Tuples are handled by summing their parts."""
    total = 0
    for m in re.finditer(r'(\w+)\[([\d,]*)\]', shape_str):
        dt, dims = m.group(1), m.group(2)
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        if dims:
            for d in dims.split(','):
                n *= int(d)
        total += _DTYPE_BYTES[dt] * n
    return total


def per_op_table(compiled, top=25):
    """Score each top-level HLO instruction in the ENTRY computation by
    the bytes of its output + operand shapes (the traffic it would cost
    if every buffer hit HBM once). Fusions count their result + inputs —
    exactly the memory XLA cannot elide; their internals are free."""
    txt = compiled.as_text()
    entry = []
    in_entry = False
    for line in txt.splitlines():
        if line.startswith('ENTRY'):
            in_entry = True
            continue
        if in_entry:
            if line.startswith('}'):
                break
            entry.append(line.strip())
    rows = []
    for line in entry:
        m = re.match(r'(%?[\w.\-]+)\s*=\s*([^ ]+)\s+(\w+)', line)
        if not m:
            continue
        name, shape, op = m.groups()
        out_b = _shape_bytes(shape)
        # operand shapes appear inline in the args list
        args = line[line.find('('):]
        arg_b = _shape_bytes(args)
        rows.append({'op': op, 'name': name.lstrip('%'),
                     'bytes': out_b + arg_b, 'out_bytes': out_b})
    rows.sort(key=lambda r: -r['bytes'])
    return rows[:top], sum(r['bytes'] for r in rows)


# the roofline geometry (bench.py headline); rows at any other geometry
# are plumbing dry-runs, tagged so they can never read as roofline evidence
HEADLINE_B, HEADLINE_T = 128, 16


def variant(name, dtype=None, cast_state=False, torus_impl=None,
            B=HEADLINE_B, T=HEADLINE_T, steps=30):
    import jax
    import jax.numpy as jnp
    from bench import headline_setup, time_compiled_step
    from handyrl_tpu.ops.train_step import build_update_step

    tagged = (name if (B, T) == (HEADLINE_B, HEADLINE_T)
              else '%s-dryrun-B%d-T%d' % (name, B, T))
    module, cfg, batch, state = headline_setup(
        B, T, dtype=jnp.bfloat16 if dtype == 'bf16' else None,
        torus_impl=torus_impl)
    parity = None
    if torus_impl is not None:
        # numerics probe of the REAL lowering (interpret mode and Mosaic
        # are different executors): forward the same params/obs through
        # the wrap-pad twin and this impl before timing anything. The
        # criterion is RELATIVE to the reference logit scale — a fixed
        # 0.05 absolute band on bf16 logits silently loosens as the scale
        # grows and a bad lowering could pass it while being wrong.
        obs = batch['observation'][:64, 0, 0]
        ref = module.clone(torus_impl='pad').apply(state.params, obs, None)
        got = module.apply(state.params, obs, None)
        err = scale = 0.0
        for k in ('policy', 'value'):
            rk = jnp.asarray(ref[k], jnp.float32)
            gk = jnp.asarray(got[k], jnp.float32)
            err = max(err, float(jnp.abs(rk - gk).max()))
            scale = max(scale, float(jnp.abs(rk).max()))
        rel = err / max(scale, 1e-6)
        parity = {'max_abs_err_vs_pad': err, 'ref_scale': scale,
                  'rel_err': rel, 'ok': bool(rel < 0.05)}
        print('parity[%s]: %s' % (tagged, parity), flush=True)
        if not parity['ok']:
            # a lowering that fails parity must never produce a
            # fast-but-wrong headline candidate: skip the timed run and
            # emit an explicitly invalid row instead
            return {'row': 'hbm-experiment', 'variant': tagged,
                    'invalid': True, 'parity': parity,
                    'time': time.strftime('%Y-%m-%d %H:%M:%S')}
    if cast_state:
        # params AND Adam moments in bf16: halves the read+write traffic
        # of every weight and optimizer buffer
        state = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if hasattr(x, 'dtype') and x.dtype == jnp.float32 else x, state)
    step = build_update_step(module, cfg, donate=False)
    lr = jnp.asarray(1e-5, jnp.float32)
    sec, flops, hbm = time_compiled_step(step, state, batch, lr, steps)
    row = {'row': 'hbm-experiment', 'variant': tagged,
           'step_ms': round(sec * 1e3, 2),
           'traj_per_sec': round(B / sec, 1),
           'flops_per_step': flops, 'hbm_bytes_per_step': hbm,
           'time': time.strftime('%Y-%m-%d %H:%M:%S')}
    if parity is not None:
        row['parity'] = parity
    # per-op table for the bf16-activation variant (the headline config)
    try:
        compiled = step.lower(state, batch, lr).compile()
        table, total = per_op_table(compiled)
        row['top_ops'] = [{k: r[k] for k in ('op', 'bytes')}
                          for r in table[:8]]
        row['sum_table_bytes'] = total
        if name in ('bf16-act', 'bf16-act+halo', 'bf16-act+pallas'):   # base name: the print path runs in dry-runs too
            print('--- per-op traffic, %s (top 25) ---' % tagged)
            for r in table:
                print('%12d  %-18s %s' % (r['bytes'], r['op'], r['name']))
    except Exception as exc:  # noqa: BLE001
        row['table_error'] = str(exc)[:120]
    return row


def main():
    steps, B, T = 30, 128, 16
    argv = iter(sys.argv[1:])
    for a in argv:
        key, _, val = a.partition('=')
        if key == '--steps':
            steps = int(val or next(argv))
        elif key == '--B':
            B = int(val or next(argv))
        elif key == '--T':
            T = int(val or next(argv))
        else:
            raise SystemExit('unknown argument %r' % a)
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    out = os.path.join(os.path.dirname(__file__), '..', 'benchmarks.jsonl')
    for name, kw in (('fp32', {}),
                     ('bf16-act', {'dtype': 'bf16'}),
                     ('bf16-act+state', {'dtype': 'bf16',
                                         'cast_state': True}),
                     # halo torus conv: same function as bf16-act without
                     # the wrap-pad HBM copies (models/blocks.py) — the
                     # round-5 per-op table's named target
                     ('bf16-act+halo', {'dtype': 'bf16',
                                        'torus_impl': 'halo'}),
                     # whole trunk fused into one VMEM-resident Pallas
                     # kernel (ops/pallas_geese.py) — activations never
                     # round-trip HBM between the 13 conv layers
                     ('bf16-act+pallas', {'dtype': 'bf16',
                                          'torus_impl': 'pallas'})):
        row = variant(name, steps=steps, B=B, T=T, **kw)
        print(json.dumps(row), flush=True)
        with open(os.path.abspath(out), 'a') as f:
            f.write(json.dumps(row) + '\n')


if __name__ == '__main__':
    main()
