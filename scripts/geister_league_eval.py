"""Geister league-eval throughput on the current backend.

Reruns the round-4 `geister-league-eval-device` measurement (BENCHMARKS.md
"Geister league eval on device"): the full GeisterNet evaluated against a
full-GeisterNet CHECKPOINT opponent, whole matches (up to the env's 200-ply
cap) played inside compiled chunks with the opponent's DRC hidden carried
through the rollout scan (`handyrl_tpu/device_generation.py`). The
dispatch count is the TPU-relevant number: each `DeviceEvaluator.step()`
is ONE device program dispatch (= one tunnel round trip on the axon
backend), vs 100+ dispatches per match on a per-ply host evaluator —
reference counterpart: the eval child processes of
/root/reference/handyrl/evaluation.py run one net call per ply.

Run: python scripts/geister_league_eval.py [--budget-s 120] [--envs 16]
Appends one JSON row to benchmarks.jsonl.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))


def main():
    budget_s, n_envs, chunk_steps = 120.0, 16, 32
    argv = iter(sys.argv[1:])
    for a in argv:
        key, _, val = a.partition('=')
        if key in ('--budget-s', '--envs', '--chunk') and not val:
            try:
                val = next(argv)
            except StopIteration:
                raise SystemExit('%s needs a value' % key)
        if key == '--budget-s':
            budget_s = float(val)
            if budget_s <= 0:
                raise SystemExit('--budget-s must be > 0')
        elif key == '--envs':
            n_envs = int(val)
        elif key == '--chunk':
            chunk_steps = int(val)
        else:
            raise SystemExit('unknown argument %r' % a)

    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    handyrl_tpu.setup_compile_cache()
    import jax

    from handyrl_tpu.device_generation import DeviceEvaluator
    from handyrl_tpu.envs import jax_geister
    from handyrl_tpu.model import ModelWrapper
    from handyrl_tpu.models.geister import GeisterNet

    obs = jax_geister.observe(jax_geister.init_state(1))
    w = ModelWrapper(GeisterNet())
    w.params = w.module.init(jax.random.PRNGKey(0), obs, None)
    opp = ModelWrapper(GeisterNet())
    opp.params = opp.module.init(jax.random.PRNGKey(1), obs, None)

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, 'league_opp.ckpt')
        with open(path, 'wb') as f:
            f.write(opp.params_bytes())
        ev = DeviceEvaluator(jax_geister, w, {}, n_envs=n_envs,
                             chunk_steps=chunk_steps, opponents=[path])
        assert ev.recurrent, 'GeisterNet league opponent must be recurrent'
        t0 = time.time()
        ev.step()                       # compile + first chunk(s)
        compile_s = time.time() - t0

        games = 0
        d0 = ev.dispatches              # the evaluator's own authoritative
        t0 = time.time()                # count (step() is pipelined)
        # run to the budget, but never record a zero-game row: matches last
        # up to 200 plies, so a too-small budget could elapse before the
        # first game finishes (hard cap 4x budget bounds that extension)
        while (time.time() - t0 < budget_s or games == 0) \
                and time.time() - t0 < 4 * budget_s:
            games += len(ev.step())
        dispatches = ev.dispatches - d0
        wall = max(time.time() - t0, 1e-9)
        if games == 0:
            raise SystemExit('no games finished within %.0fs (4x budget) — '
                             'raise --budget-s' % (4 * budget_s))

    row = {
        'row': 'geister-league-eval-device',
        'backend': jax.default_backend(),
        'opponent': 'recurrent DRC checkpoint (full GeisterNet)',
        'games': games,
        'games_per_sec': round(games / wall, 2),
        'dispatches': dispatches,
        'n_envs': n_envs, 'chunk_steps': chunk_steps,
        'compile_s': round(compile_s, 1),
        'note': 'whole 200-ply-max matches on device, one dispatch per '
                '%d-ply chunk; opponent hidden carried in the compiled '
                'rollout (no host fallback)' % chunk_steps,
        'time': time.strftime('%Y-%m-%d %H:%M:%S'),
    }
    print(json.dumps(row), flush=True)
    out = os.path.join(os.path.dirname(__file__), '..', 'benchmarks.jsonl')
    with open(os.path.abspath(out), 'a') as f:
        f.write(json.dumps(row) + '\n')


if __name__ == '__main__':
    main()
