#!/bin/bash
# Round-5 chip-window queue: run the TPU-gated measurements in priority
# order against a live tunnel (BENCHMARKS.md "Round-5 continuity note").
# Designed to be chained off the probe loop:
#   bash scripts/tpu_probe_loop.sh /tmp/tpu_probe.log 300 && \
#   bash scripts/chip_window.sh
#
# Discipline (round-1 lesson): never SIGKILL a chip client — an axon
# client killed -9 leaves the exclusive tunnel grant unreleased and
# wedges the backend for everyone after. `timeout` here sends SIGINT
# only (no --kill-after): Python maps SIGINT to KeyboardInterrupt, so
# every queue script unwinds through its finally blocks and the axon
# client releases the grant (bench.py additionally installs its own
# INT/TERM handlers and emits its JSON line first). A process stuck
# inside a single wedged device dispatch won't see the signal until the
# call returns — if an item overstays its budget by a lot, inspect
# $LOG_DIR/queue.log before doing anything by hand, and never kill -9.
#
# Env knobs: LOG_DIR (default /tmp/chip_window), NS_BUDGET_S (north-star
# training budget, default 10800 = 3h).
set -u
cd "$(dirname "$0")/.."
# An inherited JAX_PLATFORMS=cpu (the documented de-risk setting) would
# silently run the whole chip-gated queue on CPU: export it EMPTY so the
# site default (axon TPU) wins everywhere — empty-but-set also defeats
# the cpu setdefault in geese_norm_ab.py / replay_weighting_ab.py.
export JAX_PLATFORMS=
# per-window log dir: re-runs (one per tunnel window) must not truncate
# the previous window's diagnostics
LOG_DIR=${LOG_DIR:-/tmp/chip_window/$(date +%m%d_%H%M%S)}
NS_BUDGET_S=${NS_BUDGET_S:-10800}
mkdir -p "$LOG_DIR"

note() { echo "$(date +%H:%M:%S) $*" >> "$LOG_DIR/queue.log"; }

run_item() {  # run_item NAME BUDGET_S CMD...
  local name=$1 budget=$2; shift 2
  note "START $name (budget ${budget}s): $*"
  timeout --signal=INT "$budget" "$@" > "$LOG_DIR/$name.log" 2>&1
  note "END   $name rc=$?"
}

note "=== chip window opened ==="

# 1. headline number (its own SIGALRM deadline is the real bound)
BENCH_DEADLINE_SEC=900 run_item bench 960 python bench.py

# 2. GeeseNet norm A/B (VERDICT r4 #2 — the highest-leverage unknown).
#    JAX_PLATFORMS= (empty) so the script's cpu setdefault does not fire
#    and the site default (axon TPU) wins.
JAX_PLATFORMS= run_item geese_norm_ab 5400 \
  python scripts/geese_norm_ab.py --epochs 10

# 3. roofline per-op table + bf16-state variants (VERDICT r3 #4 / r4 weak #3)
run_item hbm_experiments 1800 python scripts/hbm_experiments.py

# 4. league-eval dispatch economics on the tunnel (VERDICT r4 #7)
run_item geister_league_eval 900 \
  python scripts/geister_league_eval.py --budget-s 120

# 5. north-star fresh run (checkpoints lost to the re-provision; starts
#    at epoch 0 and re-earns the curve at chip speed). All outputs go to
#    _r5 files: the committed north_star_device*.jsonl hold the LOST
#    run's epochs, and a fresh epoch-0 run appended there would
#    interleave two incomparable runs under the same epoch keys.
run_item north_star $((NS_BUDGET_S + 600)) \
  python scripts/run_north_star.py --budget-s "$NS_BUDGET_S" \
    --metrics-out north_star_device_r5.jsonl

# 6. 1k-game rescore of the fresh north-star checkpoints, vs random AND
#    rulebase (VERDICT r4 #4: >=1k games/point)
if [ -d models_north_star_device ]; then
  run_item ns_rescore_random 3600 \
    python scripts/eval_checkpoints.py models_north_star_device HungryGeese \
      north_star_device_curve_r5.jsonl --every 5 --games 1000 --skip-scored
  run_item ns_rescore_rulebase 3600 \
    python scripts/eval_checkpoints.py models_north_star_device HungryGeese \
      north_star_device_curve_rulebase_r5.jsonl --every 5 --games 1000 \
      --opponent rulebase --skip-scored
fi

# 7. geister arms at chip speed, 30 epochs (the spatial-head/norm matrix)
run_item geister_arms 7200 \
  python scripts/run_benchmark_matrix.py geister-fused geister-fused-sp-bn \
    --epochs=30

# 8. divergent-regime replay A/B, warm-started from the freshest
#    north-star checkpoint (VERDICT r4 #5). latest.ckpt is rewritten on
#    every checkpoint interval, so it is by definition the newest params
#    file (numbered globs would also match trainer_state.ckpt).
if [ -f models_north_star_device/latest.ckpt ]; then
  JAX_PLATFORMS= run_item replay_ab 3600 \
    python scripts/replay_weighting_ab.py --epochs 12 \
      --init models_north_star_device/latest.ckpt
fi

note "=== queue drained ==="
