"""Run the BASELINE.md measurement matrix configs and record results.

Each row trains a config for a fixed number of epochs and records
throughput (episodes/sec, SGD steps/sec) and the aggregate win rate vs
random over the last 5 epochs, appending JSON rows to benchmarks.jsonl.

Usage: python scripts/run_benchmark_matrix.py [ROW ...] [--epochs N]
Rows: ttt-td ttt-device ttt-device-mesh8 ttt-vtrace geister
      geister-device geister-fused geese geese-device
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

ROWS = {
    'ttt-td': {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {'batch_size': 64, 'forward_steps': 8,
                       'update_episodes': 200, 'minimum_episodes': 400,
                       'generation_envs': 64},
    },
    'ttt-device': {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {'batch_size': 64, 'forward_steps': 8,
                       'update_episodes': 200, 'minimum_episodes': 400,
                       'generation_envs': 64,
                       'device_generation': True, 'device_replay': True,
                       # ~89 training samples per episode, the measured
                       # ratio of the round-2 threaded run (192*64 samples
                       # per ~136-episode chunk)
                       'sgd_steps_per_chunk': 192},
    },
    # the sharded fused pipeline on a virtual 8-device CPU mesh (multichip
    # evidence without multichip hardware): run with
    #   JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
    'ttt-device-mesh8': {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {'batch_size': 64, 'forward_steps': 8,
                       'update_episodes': 200, 'minimum_episodes': 400,
                       'generation_envs': 64, 'eval_envs': 32,
                       'device_generation': True, 'device_replay': True,
                       'sgd_steps_per_chunk': 192},
    },
    'ttt-vtrace': {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {'batch_size': 64, 'forward_steps': 8,
                       'update_episodes': 200, 'minimum_episodes': 400,
                       'generation_envs': 64,
                       'policy_target': 'UPGO', 'value_target': 'VTRACE'},
    },
    'geister': {
        'env_args': {'env': 'Geister'},
        'train_args': {'batch_size': 32, 'forward_steps': 16,
                       'burn_in_steps': 4, 'update_episodes': 100,
                       'minimum_episodes': 200, 'generation_envs': 32,
                       'observation': True},
    },
    # Geister through the device pipeline (DRC recurrent device rollouts).
    # The plain 'geister' row is unusable on the XLA-CPU backend: LLVM
    # codegen of the full DRC update step takes tens of minutes there
    # (first run only, with the persistent compile cache) — the TPU backend
    # is the real target for this net.
    'geister-device': {
        'env_args': {'env': 'Geister'},
        'train_args': {'batch_size': 32, 'forward_steps': 16,
                       'burn_in_steps': 4, 'update_episodes': 100,
                       'minimum_episodes': 200, 'generation_envs': 32,
                       'observation': True,
                       'device_generation': True, 'device_replay': True,
                       'device_chunk_steps': 32, 'eval_envs': 32},
    },
    # Geister through the FUSED pipeline (round 4): observation=True rides
    # the compact turn layout, so sample reuse is PINNED by
    # sgd_steps_per_chunk instead of free-spinning with the threaded
    # trainer (the round-3 quality-gap suspect). Reuse here ~= 4 * 32
    # samples per ~40-window chunk ~= 3x, near the reference's ~1x.
    'geister-fused': {
        'env_args': {'env': 'Geister'},
        'train_args': {'batch_size': 32, 'forward_steps': 16,
                       'burn_in_steps': 4, 'update_episodes': 100,
                       'minimum_episodes': 200, 'generation_envs': 32,
                       'observation': True,
                       'device_generation': True, 'device_replay': True,
                       'device_chunk_steps': 32, 'eval_envs': 32,
                       'sgd_steps_per_chunk': 4},
    },
    'geese': {
        'env_args': {'env': 'HungryGeese'},
        'train_args': {'batch_size': 64, 'forward_steps': 16,
                       'update_episodes': 100, 'minimum_episodes': 200,
                       'generation_envs': 32,
                       'turn_based_training': False, 'observation': True,
                       'gamma': 0.99,
                       'policy_target': 'VTRACE', 'value_target': 'VTRACE'},
    },
    # VERDICT r1 #5: the fully device-resident Hungry Geese pipeline —
    # rollouts, replay ring, and SGD all on the accelerator
    'geese-device': {
        'env_args': {'env': 'HungryGeese'},
        'train_args': {'batch_size': 64, 'forward_steps': 16,
                       'update_episodes': 100, 'minimum_episodes': 200,
                       'generation_envs': 64,
                       'turn_based_training': False, 'observation': True,
                       'gamma': 0.99,
                       'policy_target': 'VTRACE', 'value_target': 'VTRACE',
                       'device_generation': True, 'device_replay': True,
                       'device_chunk_steps': 32, 'eval_envs': 32,
                       # ~265 training samples per episode, the measured
                       # ratio of the round-2 threaded run (64*64 samples
                       # per ~17-episode chunk)
                       'sgd_steps_per_chunk': 64},
    },
}

# Round-5 norm A/B arms: DERIVED from their baseline rows so the pair can
# only ever differ in the one knob under test (norm_kind='batch' = full
# reference BatchNorm parity — batch statistics in the training forward,
# running averages served at inference; reference geister.py:107,122,
# hungry_geese.py:23-44, model.py:54). Baselines: 'geister-fused'
# (GroupNorm, 0.466 at 1,243 episodes r4; torch reference bar 0.661 at
# ~1k) and 'geese-device' (GroupNorm).
for _base, _twin in (('geister-fused', 'geister-fused-bn'),
                     ('geese-device', 'geese-device-bn')):
    _row = json.loads(json.dumps(ROWS[_base]))
    _row['env_args']['norm_kind'] = 'batch'
    ROWS[_twin] = _row

# the LSTM-era flagship configuration (BASELINE.md measurement-matrix
# row 4: "Hungry Geese, 4-player self-play, LSTM model"): recurrent
# GeeseNetLSTM through the same fused device pipeline — hidden state
# carried across plies like GeisterNet's DRC, burn-in windows included
ROWS['geese-lstm-device'] = json.loads(json.dumps(ROWS['geese-device']))
ROWS['geese-lstm-device']['env_args']['net_kind'] = 'lstm'
ROWS['geese-lstm-device']['train_args']['burn_in_steps'] = 4

# geister arms for the round-5 spatial-policy-head hypothesis: 'sp' =
# reference head structure alone, 'sp-bn' = head + full BatchNorm (the
# most reference-faithful GeisterNet this repo can express).
for _twin, _extra in (('geister-fused-sp', {'policy_head': 'spatial'}),
                      ('geister-fused-sp-bn', {'policy_head': 'spatial',
                                               'norm_kind': 'batch'}),
                      # + torch-default weight distributions
                      # (blocks.torch_default_inits) — the remaining
                      # dynamics suspect after head+norm measured +0.10
                      ('geister-fused-sp-bn-ti', {'policy_head': 'spatial',
                                                  'norm_kind': 'batch',
                                                  'init_kind': 'torch'})):
    _row = json.loads(json.dumps(ROWS['geister-fused']))
    _row['env_args'].update(_extra)
    ROWS[_twin] = _row


def run_row(name, epochs):
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner

    raw = json.loads(json.dumps(ROWS[name]))   # deep copy
    raw['train_args']['epochs'] = epochs
    raw['train_args']['model_dir'] = 'models_bench_%s' % name
    args = apply_defaults(raw)

    t0 = time.time()
    learner = Learner(args=args)
    init_s = time.time() - t0
    learner.run()
    wall = time.time() - t0

    last = learner.model_epoch - 1
    n = r = 0
    for epoch in range(max(1, last - 4), last + 1):
        if epoch in learner.results:
            en, er, _ = learner.results[epoch]
            n, r = n + en, r + er
    win_rate = (r / (n + 1e-6) + 1) / 2 if n else None

    import jax
    row = {
        'row': name, 'backend': jax.default_backend(),
        'epochs': learner.model_epoch,
        'episodes': learner.num_returned_episodes,
        'episodes_per_sec': round(learner.num_returned_episodes / wall, 2),
        'sgd_steps_per_sec': round(learner.trainer.last_steps_per_sec, 2),
        'win_rate_vs_random_last5': round(win_rate, 3) if win_rate else None,
        'eval_games': n, 'wall_s': round(wall, 1),
        'init_s': round(init_s, 1),
        'time': time.strftime('%Y-%m-%d %H:%M:%S'),
    }
    with open('benchmarks.jsonl', 'a') as f:
        f.write(json.dumps(row) + '\n')
    print(json.dumps(row))


def main():
    if os.environ.get('JAX_PLATFORMS', '').strip() == 'cpu':
        import jax
        jax.config.update('jax_platforms', 'cpu')
    epochs = 10
    rows = []
    argv = iter(sys.argv[1:])
    for a in argv:
        if a.startswith('--epochs='):
            epochs = int(a.split('=', 1)[1])
        elif a == '--epochs':
            epochs = int(next(argv))
        elif a in ROWS:
            rows.append(a)
        else:
            raise SystemExit('unknown row %r (choose from %s, or --epochs=N)'
                             % (a, ', '.join(ROWS)))
    rows = rows or ['ttt-td']
    for name in rows:
        run_row(name, epochs)


if __name__ == '__main__':
    main()
