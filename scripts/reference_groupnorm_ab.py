"""Isolate the GroupNorm-vs-BatchNorm suspect in the Geister quality gap.

Runs the UNMODIFIED reference (PYTHONPATH=/root/reference, its own torch
trainer) except that ``torch.nn.BatchNorm2d`` is replaced — via a
sitecustomize shim, the reference tree itself is never touched — with a
GroupNorm of the same group rule this repo's models use
(min(8, channels)). If the reference's geister quality at ~1k episodes
drops from its measured 0.661 toward the 0.45–0.48 this repo reaches,
the normalization substitution explains the gap (and the fix here is a
train-mode BatchNorm with batch_stats threaded through TrainState);
if it stays ≈ 0.66, normalization is exonerated.

Run: python scripts/reference_groupnorm_ab.py [--epochs N] [--deadline S]
Appends one row (implementation: 'reference+groupnorm') to
benchmarks.jsonl.
"""

import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = '/root/reference'

SHIM = r'''
# sitecustomize: swap BatchNorm2d for GroupNorm(min(8, C)) process-wide.
# Imported automatically by Python at startup (site module).
import torch.nn as _nn

class _GN2d(_nn.GroupNorm):
    def __init__(self, num_features, *a, **k):
        super().__init__(min(8, num_features), num_features)

_nn.BatchNorm2d = _GN2d
'''

CONFIG = '''env_args:
    env: 'Geister'

train_args:
    turn_based_training: True
    observation: True
    gamma: 0.8
    forward_steps: 16
    burn_in_steps: 4
    compress_steps: 4
    entropy_regularization: 0.1
    entropy_regularization_decay: 0.1
    update_episodes: 100
    batch_size: 32
    minimum_episodes: 200
    maximum_episodes: 100000
    epochs: %(epochs)d
    num_batchers: 2
    eval_rate: 0.1
    worker:
        num_parallel: 6
    lambda: 0.7
    policy_target: 'TD'
    value_target: 'TD'
    eval:
        opponent: ['random']
    seed: 0
    restart_epoch: 0

worker_args:
    server_address: ''
    num_parallel: 8
'''

_WIN_RE = re.compile(r'win rate(?: \(\w+\))? = ([\d.]+) \(([\d.]+) / (\d+)\)')
_EPOCH_RE = re.compile(r'^epoch (\d+)$')


def main():
    epochs, deadline = 10, 3300
    argv = iter(sys.argv[1:])
    for a in argv:
        key, _, val = a.partition('=')
        if key in ('--epochs', '--deadline') and not val:
            val = next(argv)
        if key == '--epochs':
            epochs = int(val)
        elif key == '--deadline':
            deadline = int(val)
        else:
            raise SystemExit('unknown argument %r' % a)

    scratch = tempfile.mkdtemp(prefix='ref_gn_geister_')
    with open(os.path.join(scratch, 'config.yaml'), 'w') as f:
        f.write(CONFIG % {'epochs': epochs})
    shim_dir = os.path.join(scratch, 'shim')
    os.makedirs(shim_dir)
    with open(os.path.join(shim_dir, 'sitecustomize.py'), 'w') as f:
        f.write(SHIM)
    log_path = os.path.join(scratch, 'train.log')

    env = dict(os.environ,
               PYTHONPATH=shim_dir + os.pathsep + REFERENCE,
               OMP_NUM_THREADS='1')
    t0 = time.time()
    with open(log_path, 'w') as log:
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REFERENCE, 'main.py'), '--train'],
            cwd=scratch, env=env, stdout=log, stderr=subprocess.STDOUT)
        try:
            proc.wait(timeout=deadline)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
    wall = time.time() - t0

    text = open(log_path, errors='replace').read()
    assert 'GroupNorm' in open(
        os.path.join(shim_dir, 'sitecustomize.py')).read()
    rates = [(float(m.group(1)), int(m.group(3)))
             for m in _WIN_RE.finditer(text)]
    epochs_seen = [int(m.group(1)) for line in text.splitlines()
                   for m in [_EPOCH_RE.match(line)] if m] or [0]
    last5 = rates[-5:]
    games = sum(n for _, n in last5)
    win_rate = (sum(r * n for r, n in last5) / games) if games else None

    row = {
        'implementation': 'reference+groupnorm', 'row': 'geister',
        'epochs': epochs, 'epochs_seen': max(epochs_seen),
        'wall_s': round(wall, 1),
        'win_rate_vs_random_last5': (round(win_rate, 3)
                                     if win_rate is not None else None),
        'eval_games': games, 'log': log_path,
        'time': time.strftime('%Y-%m-%d %H:%M:%S'),
    }
    print(json.dumps(row), flush=True)
    with open(os.path.join(REPO, 'benchmarks.jsonl'), 'a') as f:
        f.write(json.dumps(row) + '\n')


if __name__ == '__main__':
    main()
