"""CI telemetry smoke: a short CPU training with the exporter enabled.

Proves the whole observability loop end to end in one subprocess run:

  1. launches a tiny in-process (batched-generation) learner with
     ``telemetry_port`` set and a ``metrics_jsonl`` sink;
  2. scrapes ``/metrics`` once while the run is live and validates the
     Prometheus text exposition format line by line;
  3. after the run exits, validates that every metrics_jsonl line parses
     and carries the telemetry schema (run_id + summarized registry).

Exits 0 on success, 1 with a reason on any failure. Stdlib + repo only.
"""

import os
import re
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PORT = int(os.environ.get('TELEMETRY_SMOKE_PORT', '18917'))

LEARNER = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner
raw = {'env_args': {'env': 'TicTacToe'},
       'train_args': {'batch_size': 8, 'update_episodes': 20,
                      'minimum_episodes': 20, 'epochs': 2,
                      'forward_steps': 8, 'num_batchers': 1,
                      'generation_envs': 8, 'eval_envs': 4,
                      'model_dir': %(model_dir)r,
                      'metrics_jsonl': %(metrics)r,
                      'telemetry_port': %(port)d}}
learner = Learner(args=apply_defaults(raw))
learner.run()
if learner.trainer.failed:
    raise SystemExit('SMOKE LEARNER TRAIN FAILED: '
                     + (learner.trainer.failed_reason or 'see traceback'))
print('SMOKE LEARNER DONE', learner.model_epoch, flush=True)
'''

_PROM_LINE = re.compile(
    r'^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)'
    r'|[a-zA-Z_:][a-zA-Z0-9_:]*(_bucket|_sum|_count)?'
    r'(\{[^{}]*\})? [0-9eE.+-]+)$')


def fail(msg):
    print('TELEMETRY SMOKE FAILED: %s' % msg, flush=True)
    sys.exit(1)


def main():
    import tempfile
    workdir = tempfile.mkdtemp(prefix='telemetry_smoke.')
    metrics = os.path.join(workdir, 'metrics.jsonl')
    script = os.path.join(workdir, 'learner.py')
    with open(script, 'w') as f:
        f.write(LEARNER % {'model_dir': os.path.join(workdir, 'models'),
                           'metrics': metrics, 'port': PORT})

    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'PYTHONPATH': REPO + os.pathsep + os.environ.get('PYTHONPATH', '')}
    proc = subprocess.Popen([sys.executable, script], env=env)
    exposition = ''
    try:
        deadline = time.time() + 300
        url = 'http://127.0.0.1:%d/metrics' % PORT
        while time.time() < deadline and proc.poll() is None:
            try:
                exposition = urllib.request.urlopen(
                    url, timeout=5).read().decode()
                # wait for BOTH needles: episodes appear during generation,
                # stage histograms only once batching starts — scraping in
                # between is a race, not a failure
                if ('episodes_generated_total' in exposition
                        and 'stage_seconds_bucket' in exposition):
                    break
            except OSError:
                pass
            time.sleep(1)
        rc = proc.wait(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
    if rc != 0:
        fail('learner exited rc=%d' % rc)

    # -- Prometheus text exposition -------------------------------------
    if not exposition.strip():
        fail('never scraped a non-empty /metrics response')
    for line in exposition.splitlines():
        if line.strip() and not _PROM_LINE.match(line):
            fail('invalid exposition line: %r' % line)
    for needle in ('episodes_generated_total', 'learner_epoch',
                   'stage_seconds_bucket'):
        if needle not in exposition:
            fail('expected metric %r missing from /metrics' % needle)
    print('exposition OK (%d lines)' % len(exposition.splitlines()))

    # -- metrics_jsonl schema -------------------------------------------
    from handyrl_tpu.telemetry import validate_metrics_line
    lines = [l for l in open(metrics).read().splitlines() if l.strip()]
    if not lines:
        fail('no metrics_jsonl records written')
    for line in lines:
        try:
            validate_metrics_line(line)
        except ValueError as exc:
            fail(str(exc))
    print('metrics_jsonl OK (%d epoch records)' % len(lines))
    print('TELEMETRY SMOKE PASSED')


if __name__ == '__main__':
    main()
