"""CI smoke for the device actor backend (docs/large_scale_training.md).

Runs a REAL learner + one worker host over TCP where the host selects
``backend: device`` (worker_args.backend riding the entry handshake): the
gather serves its whole task block through the fused on-device rollout
engine (DeviceActorEngine) instead of worker processes. League training is
on, so PFSP pairings are served by the SAME compiled program via stacked
opponent params. Proves, without throughput thresholds:

  * episodes and eval results generated on device land through the task
    ledger and finish the learner's epochs (exit 0);
  * the retrace sentinel stays clean on the device host under
    ``HANDYRL_TPU_RETRACE=abort`` (one warmup compile, then steady state —
    a league pairing change must NOT retrace);
  * ``device_actor_*`` counters ride the gather heartbeat into the
    learner's merged fleet telemetry (metrics_jsonl);
  * PFSP sampled >= 2 distinct registry opponent versions while the only
    generation host in the fleet was the device gather.

``--chaos`` (the slow leg) arms ``HANDYRL_TPU_CHAOS=kill_gather`` on the
worker host: the device gather is SIGKILLed mid-run, the supervisor
respawns it (as a device gather — same merged args), the ledger re-issues
its in-flight tasks, and the run still completes.

Exits 0 on success, 1 with a reason on any failure. Stdlib + repo only.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 10,
                          'minimum_episodes': 10, 'epochs': 5,
                          'forward_steps': 8, 'num_batchers': 1,
                          'eval_rate': 0.3, 'seed': 11,
                          'keep_checkpoints': 3,
                          'metrics_jsonl': %(metrics)r,
                          'model_dir': %(model_dir)r,
                          'generation': {'device_actor_envs': 8,
                                         'device_actor_chunk_steps': 8,
                                         'device_actor_slots': 2},
                          # the tiny run is over in seconds; beat fast so
                          # device_actor_* counters ride the fleet merge
                          # before the last epoch record is written
                          'fault_tolerance': {'heartbeat_interval': 1.0},
                          'serving': {'publish': True, 'line': 'default'},
                          'league': {'enabled': True, 'self_play_rate': 0.0,
                                     'rating_match_rate': 0.3,
                                     'curve': 'uniform', 'min_games': 1,
                                     'promote_margin': 0.0}}}
    learner = Learner(args=apply_defaults(raw), remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, flush=True)

if __name__ == '__main__':
    main()
'''

# the host asks for the device backend itself: worker_args.backend rides
# the entry handshake and WINS over the training config's generation block
WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost',
                            'num_parallel': 2, 'backend': 'device'}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


def main() -> int:
    chaos = '--chaos' in sys.argv[1:]
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    work = tempfile.mkdtemp(prefix='device_actor_smoke.')
    model_dir = os.path.join(work, 'models')
    metrics = os.path.join(work, 'metrics.jsonl')
    learner_py = os.path.join(work, 'learner.py')
    worker_py = os.path.join(work, 'worker.py')
    with open(learner_py, 'w') as f:
        f.write(LEARNER_SCRIPT % {'model_dir': model_dir, 'metrics': metrics})
    with open(worker_py, 'w') as f:
        f.write(WORKER_SCRIPT)
    base_env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
                'PYTHONPATH': REPO + os.pathsep
                + os.environ.get('PYTHONPATH', '')}
    worker_env = dict(base_env, HANDYRL_TPU_RETRACE='abort')
    if chaos:
        # mean 8s between SIGKILLs: at least one hit lands mid-run on the
        # tiny geometry, the supervisor respawn + ledger re-issue recover
        worker_env['HANDYRL_TPU_CHAOS'] = 'kill_gather=8,max_kills=2,seed=3'
        worker_env.pop('HANDYRL_TPU_RETRACE')  # respawns recompile by design

    learner = worker = None
    learner_log = open(os.path.join(work, 'learner.log'), 'w')
    worker_log = open(os.path.join(work, 'worker.log'), 'w')
    try:
        learner = subprocess.Popen([sys.executable, learner_py],
                                   env=base_env, stdout=learner_log,
                                   stderr=subprocess.STDOUT)
        time.sleep(3)   # let the entry/worker servers bind
        worker = subprocess.Popen([sys.executable, worker_py],
                                  env=worker_env, stdout=worker_log,
                                  stderr=subprocess.STDOUT)
        deadline = time.time() + 240
        while time.time() < deadline and learner.poll() is None:
            time.sleep(2)
        assert learner.poll() is not None, 'learner never finished its epochs'
        assert learner.returncode == 0, \
            'learner exited %s' % learner.returncode

        # the worker log proves the backend actually engaged (and, in the
        # chaos leg, that the respawned gather came back as a device gather)
        wlog = open(os.path.join(work, 'worker.log')).read()
        engaged = wlog.count('device actor backend')
        assert engaged >= 1, 'device backend never engaged:\n%s' % wlog[-2000:]
        if chaos:
            assert engaged >= 2, \
                'chaos leg: expected a respawned device gather ' \
                '(saw %d backend banner(s))' % engaged
        assert 'retrace' not in wlog.lower() or chaos, \
            'retrace sentinel tripped on the device host:\n%s' % wlog[-2000:]

        # metrics: device_actor_* counters rode the heartbeat merge, and
        # PFSP drew >= 2 distinct registry versions through the device host
        sampled = set()
        dev_eps = dev_results = 0
        recs = 0
        with open(metrics) as f:
            for line in f:
                rec = json.loads(line)
                recs += 1
                lg = rec.get('league')
                if lg:
                    sampled.update(lg.get('opponents_sampled') or {})
                fleet = ((rec.get('fleet_telemetry') or {})
                         .get('counters') or {})
                dev_eps = max(dev_eps,
                              fleet.get('device_actor_episodes_total', 0))
                dev_results = max(
                    dev_results, fleet.get('device_actor_results_total', 0))
        assert recs > 0, 'no metrics records written'
        assert dev_eps > 0, \
            'no device_actor_episodes_total in fleet telemetry ' \
            '(device engine produced nothing?)'
        versions = {m for m in sampled if '@' in m}
        assert len(versions) >= 2, \
            'PFSP sampled %r: wanted >= 2 registry versions served by the ' \
            'device host' % (sampled,)

        print('device actor smoke OK%s: %d device episodes, %d device '
              'results, league versions sampled %s'
              % (' (chaos)' if chaos else '', dev_eps, dev_results,
                 sorted(versions)))
        return 0
    finally:
        for proc in (worker, learner):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
        learner_log.close()
        worker_log.close()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == '__main__':
    raise SystemExit(main())
