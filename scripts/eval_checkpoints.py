"""Offline win-rate curve from a model_dir of checkpoints.

The online eval share samples too few games per epoch to draw a smooth
quality curve for fast runs (an epoch lasts ~2s in the north-star config);
this scores saved checkpoints directly with the DeviceEvaluator — whole
matches on the accelerator, a few hundred games per point in seconds.

Usage:
  python scripts/eval_checkpoints.py MODEL_DIR ENV OUT.jsonl \
      [--every N] [--games G] [--envs E] [--opponent random|rulebase|CKPT] \
      [--env-args JSON] [--skip-scored]

--skip-scored makes reruns incremental: epochs already present in
OUT.jsonl (for the same opponent) are not re-scored, so a recurring
caller (scripts/chip_window.sh per tunnel window) only pays for
checkpoints that appeared since the last pass instead of re-evaluating
the whole curve and appending duplicate rows.

--env-args merges extra env_args (e.g. '{"norm_kind": "batch"}') so the
rebuilt net matches the checkpoints' param tree — REQUIRED when scoring a
run trained with a non-default model config.

Writes one JSON line per checkpoint: {"epoch": N, "opponent": O,
"games": G, "win_rate": W, "mean": M} where win_rate = (mean outcome+1)/2
(the reference's normalization, train.py win-rate lines).
"""

import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))


def main():
    model_dir, env_name, out_path = sys.argv[1:4]
    opts = sys.argv[4:]

    def opt(name, default):
        return int(opts[opts.index(name) + 1]) if name in opts else default

    every = opt('--every', 5)
    games = opt('--games', 192)
    n_envs = opt('--envs', 64)
    opponent = (opts[opts.index('--opponent') + 1]
                if '--opponent' in opts else 'random')
    extra_env_args = (json.loads(opts[opts.index('--env-args') + 1])
                      if '--env-args' in opts else {})

    import numpy as np

    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    handyrl_tpu.setup_compile_cache()
    from handyrl_tpu.device_generation import DeviceEvaluator
    from handyrl_tpu.environment import make_env, make_jax_env
    from handyrl_tpu.model import ModelWrapper

    env_args = {'env': env_name, **extra_env_args}
    env = make_env(env_args)
    env.reset()
    env_mod = make_jax_env(env_args)
    assert env_mod is not None, 'offline device eval needs a jax twin'
    example = env.observation(env.players()[0])

    ckpts = sorted(
        int(m.group(1)) for f in os.listdir(model_dir)
        if (m := re.match(r'^(\d+)\.ckpt$', f)))
    picks = [e for i, e in enumerate(ckpts) if i % every == 0]
    if ckpts and ckpts[-1] not in picks:
        picks.append(ckpts[-1])
    if '--skip-scored' in opts and os.path.exists(out_path):
        scored = set()
        with open(out_path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if row.get('opponent') == opponent and 'epoch' in row:
                    scored.add(row['epoch'])
        picks = [e for e in picks if e not in scored]
        print('skip-scored: %d epochs already in %s'
              % (len(scored), out_path), flush=True)
    print('evaluating %d checkpoints of %d (every %d) from %s'
          % (len(picks), len(ckpts), every, model_dir), flush=True)

    wrapper = ModelWrapper(env.net())
    args = {'eval': {'opponent': [opponent]}}
    # ONE evaluator reused across checkpoints: a fresh instance would
    # re-trace its rollout program per checkpoint. After each params swap,
    # a few chunks are discarded so games started under the previous
    # checkpoint don't contaminate the point.
    ev = None
    with open(out_path, 'a') as out:
        for epoch in picks:
            with open(os.path.join(model_dir, '%d.ckpt' % epoch), 'rb') as f:
                wrapper.load_params_bytes(f.read(), example)
            from handyrl_tpu.utils.fetch import put_tree
            wrapper.params = put_tree(wrapper.params)
            if ev is None:
                ev = DeviceEvaluator(env_mod, wrapper, args, n_envs=n_envs,
                                     chunk_steps=32, seed=1009,
                                     opponents=[opponent])
            else:
                # flush cross-checkpoint games: a full max-length episode
                # plus the one pipelined chunk must drain before counting
                max_steps = int(getattr(env_mod, 'MAX_STEPS', 256))
                for _ in range(max_steps // 32 + 2):
                    ev.step()
            results = []
            while len(results) < games:
                results.extend(ev.step())
            vals = [r['result'][r['args']['player'][0]] for r in results]
            mean = float(np.mean(vals))
            row = {'epoch': epoch, 'opponent': opponent,
                   'games': len(vals),
                   'win_rate': round((mean + 1) / 2, 4),
                   'mean': round(mean, 4)}
            out.write(json.dumps(row) + '\n')
            out.flush()
            print(row, flush=True)


if __name__ == '__main__':
    main()
