"""Render a league run's rating table and promotion history from its
metrics_jsonl stream (docs/league.md).

Every learner metrics record written with ``league.enabled`` carries a
``league`` block (champion, per-name ratings and games, promotion
counters, opponent-sampling tallies). This report replays those blocks
and prints:

  * the final rating table, sorted by rating, with games and the
    learner/champion/anchor markers;
  * the promotion history — every record where the promotion counter
    moved, with the champion it installed;
  * cumulative PFSP opponent-sampling tallies (per run_id the in-memory
    tally resets on restart, so tallies are summed per run).

``--journal`` additionally reads the ``league_ratings.json`` book for
the sigma column (the JSONL rounds ratings; the journal is exact).

Usage: python scripts/league_report.py metrics.jsonl [--journal PATH]
Exits 1 when the stream has no league blocks. Stdlib only.
"""

import argparse
import json
import sys


def read_league_records(path):
    """[(record, league block)] for every record carrying one."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue    # torn tail line: the writer died mid-record
            lg = rec.get('league')
            if lg:
                out.append((rec, lg))
    return out


def promotion_history(records):
    """[(epoch, champion, promotions)] at every promotion-counter move."""
    history = []
    last = None
    for rec, lg in records:
        p = int(lg.get('promotions') or 0)
        if last is not None and p > last:
            history.append((rec.get('epoch'), lg.get('champion'), p))
        last = p
    return history


def sampling_totals(records):
    """Cumulative opponent draws: per-run tallies reset on restart, so
    take each run's high-water mark and sum across runs."""
    per_run = {}
    for rec, lg in records:
        run = per_run.setdefault(rec.get('run_id', ''), {})
        for name, n in (lg.get('opponents_sampled') or {}).items():
            run[name] = max(run.get(name, 0), int(n))
    totals = {}
    for run in per_run.values():
        for name, n in run.items():
            totals[name] = totals.get(name, 0) + n
    return totals


def render(records, journal=None, out=sys.stdout):
    rec, lg = records[-1]
    sigmas = {}
    if journal:
        entries = (journal.get('entries') or {})
        sigmas = {k: v.get('sigma') for k, v in entries.items()}
    champion = lg.get('champion')
    ratings = lg.get('ratings') or {}
    games = lg.get('games') or {}
    members = set(lg.get('members') or [])

    print('league report: epoch %s, %d league records'
          % (rec.get('epoch'), len(records)), file=out)
    print('champion: %s  promotions: %s  games_since_promote: %s'
          % (champion, lg.get('promotions'), lg.get('games_since_promote')),
          file=out)
    print(file=out)
    header = '%-24s %10s %8s %7s  %s' % ('name', 'rating', 'sigma',
                                         'games', 'role')
    print(header, file=out)
    print('-' * len(header), file=out)
    for name in sorted(ratings, key=lambda n: -float(ratings[n])):
        if name == 'learner':
            role = 'learner'
        elif name == champion:
            role = 'champion'
        elif name in members:
            role = 'member'
        else:
            role = 'anchor'
        sigma = sigmas.get(name)
        print('%-24s %10.1f %8s %7d  %s'
              % (name, float(ratings[name]),
                 '%.1f' % sigma if sigma is not None else '-',
                 int(games.get(name, 0)), role), file=out)

    history = promotion_history(records)
    print(file=out)
    if history:
        print('promotions:', file=out)
        for epoch, champ, count in history:
            print('  epoch %-5s -> %s (total %d)' % (epoch, champ, count),
                  file=out)
    else:
        print('promotions: none recorded in this stream', file=out)

    totals = sampling_totals(records)
    if totals:
        print(file=out)
        print('opponents sampled (PFSP draws):', file=out)
        for name in sorted(totals, key=lambda n: -totals[n]):
            print('  %-24s %6d' % (name, totals[name]), file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('metrics', help='metrics_jsonl path from a league run')
    ap.add_argument('--journal', default='',
                    help='league_ratings.json for the exact sigma column')
    args = ap.parse_args(argv)

    records = read_league_records(args.metrics)
    if not records:
        print('league_report: no league blocks in %s (league.enabled run?)'
              % args.metrics, file=sys.stderr)
        return 1
    journal = None
    if args.journal:
        with open(args.journal) as f:
            journal = json.load(f)
    render(records, journal)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
