"""Markdown comparison of the round-5 geister arms vs the measured
torch-reference bar.

Joins the 1k-game checkpoint rescores (geister_arm_*_r5.jsonl, written
by the chip queue / eval_checkpoints.py) with the reference rows in
benchmarks.jsonl (implementation='reference', row='geister' — the
actual torch reference run on this host, round 4). Episode counts per
epoch come from each arm's matrix row (benchmarks.jsonl rows
geister-fused*). Standard errors are printed for every point: the
reference bar itself is a 252-game measurement (SE +-3.1%), which
bounds how small a 'gap' can still be called real.

Usage: python scripts/geister_arm_report.py [--dir .]
"""

import json
import math
import os
import sys

ARMS = (('baseline (GroupNorm, dense head)', 'geister-fused',
         'geister_arm_base_r5.jsonl'),
        ('spatial head + BatchNorm', 'geister-fused-sp-bn',
         'geister_arm_spbn_r5.jsonl'),
        ('spatial + BatchNorm + torch init', 'geister-fused-sp-bn-ti',
         'geister_arm_spbnti_r5.jsonl'))


def _rows(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def se(p, n):
    return math.sqrt(max(p * (1 - p), 1e-9) / n) if n else float('nan')


def main():
    base = '.'
    if '--dir' in sys.argv:
        base = sys.argv[sys.argv.index('--dir') + 1]
    bench = _rows(os.path.join(base, 'benchmarks.jsonl'))

    ref = [r for r in bench if r.get('implementation') == 'reference'
           and r.get('row') == 'geister' and r.get('win_rate_vs_random_last5')]
    if ref:
        r = ref[-1]
        p, n = r['win_rate_vs_random_last5'], r.get('eval_games', 0)
        print('reference bar (torch, this host): %.3f +- %.3f '
              '(%d games, %d epochs)\n' % (p, se(p, n), n, r['epochs']))

    for label, row_name, curve_file in ARMS:
        run = [r for r in bench if r.get('row') == row_name
               and r.get('episodes')]
        eps_per_epoch = (run[-1]['episodes'] / run[-1]['epochs']
                         if run else float('nan'))
        curve = [r for r in _rows(os.path.join(base, curve_file))
                 if r.get('opponent', 'random') == 'random']
        print('### %s  (%s, ~%.0f episodes/epoch)' %
              (label, row_name, eps_per_epoch))
        if not curve:
            print('  (no rescore rows yet)\n')
            continue
        print('| epoch | ~episodes | win rate vs random | SE | games |')
        print('|---|---|---|---|---|')
        for r in curve:
            n = r.get('games', 0)
            print('| %d | %.0f | %.3f | +-%.3f | %d |' %
                  (r['epoch'], r['epoch'] * eps_per_epoch,
                   r['win_rate'], se(r['win_rate'], n), n))
        print()


if __name__ == '__main__':
    main()
