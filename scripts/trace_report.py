"""Collate episode-lifecycle trace files into a critical-path summary.

Input: a trace directory (``HANDYRL_TPU_TRACE``) holding the per-run
``trace-<run_id>.jsonl`` event stream (and/or the finalized
``trace-<run_id>.json`` Chrome-trace file), or a single file of either
flavor. Every event is a Chrome-trace "complete" event; episode-linked
events carry ``args.trace_id`` (derived from the server-stamped task) and
the learner's ``train_step`` events carry ``args.trace_ids`` — the sampled
episodes whose windows that update consumed.

Output: a per-stage latency table, the per-episode critical path
(task_assign -> generate -> upload -> ingest -> train_step) with
generation->gradient p50/p95, and the batch-level stage summaries
(select/decode/assemble/ipc/h2d/compute/engine_batch). ``--chrome OUT``
additionally writes one merged Chrome-trace JSON across every run found.

``--serve`` additionally reduces the serving-path spans (PR 18,
docs/observability.md "Serving-path tracing"): per-hop latency
percentiles (client_request -> route_dispatch -> serve_request ->
queue_wait -> engine_batch), the queue-wait vs batch-compute split per
replica, failover replay / journal-reconstruction chain extraction (link
spans carrying the ORIGINAL trace_id), and per-session gateway ply
timelines.

Exit code: 0 when at least one complete chain of the required kind was
found, 2 otherwise (the CI smokes assert 0). ``--require
training|serve|any`` picks the kind; the default is ``training`` unless
``--serve`` is given (so serve-only runs, with no learner, don't report
failure). Stdlib only.

Usage:
    python scripts/trace_report.py <dir-or-file> [--chrome OUT] [--json]
                                   [--serve] [--require KIND]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

# the episode-lifecycle stage chain, in causal order (one vocabulary with
# docs/observability.md "Tracing"); 'evaluate' chains are reported too but
# only generation chains feed the generation->gradient headline
CHAIN_STAGES = ('task_assign', 'generate', 'upload', 'ingest', 'train_step')

# batch-level stages worth a duration summary when present
BATCH_STAGES = ('select', 'decode', 'assemble', 'ipc', 'h2d', 'dispatch',
                'host_block', 'engine_batch', 'generate', 'upload',
                'evaluate')

# the serving-path request chain, in causal order (client submit ->
# router dispatch -> replica service -> engine queue -> forward batch)
SERVE_CHAIN_STAGES = ('client_request', 'route_dispatch', 'serve_request',
                      'queue_wait', 'engine_batch')

# link spans: re-dispatches that carry the ORIGINAL trace_id so a
# failover reads as one causal chain (args.link names the kind)
SERVE_LINK_STAGES = ('router_replay', 'gateway_handoff',
                     'gateway_reconstruct')

# gateway session-tier spans (per-session ply timelines)
GATEWAY_STAGES = ('gateway_open', 'gateway_ply', 'gateway_seat')


def discover_files(path: str) -> List[str]:
    """Trace files under ``path``: per run, prefer the append-forever JSONL
    (a superset of the finalized snapshot) and fall back to the .json."""
    if os.path.isfile(path):
        return [path]
    jsonls = sorted(glob.glob(os.path.join(path, 'trace-*.jsonl')))
    have = {os.path.splitext(os.path.basename(p))[0] for p in jsonls}
    jsons = [p for p in sorted(glob.glob(os.path.join(path, 'trace-*.json')))
             if os.path.splitext(os.path.basename(p))[0] not in have]
    return jsonls + jsons


def load_events(files: List[str]) -> List[Dict[str, Any]]:
    events: List[Dict[str, Any]] = []
    for path in files:
        try:
            with open(path) as f:
                if path.endswith('.json'):
                    events.extend(json.load(f).get('traceEvents', []))
                    continue
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        continue   # torn tail line from a killed process
        except (OSError, ValueError) as exc:
            print('warning: skipping unreadable %s (%s)' % (path, exc),
                  file=sys.stderr)
    return events


def build_chains(events: List[Dict[str, Any]]
                 ) -> Dict[str, Dict[str, Tuple[int, int, int]]]:
    """trace_id -> {stage: (ts, dur, pid)}; the earliest event wins per
    stage (re-issues/resends may repeat a stage — the first occurrence is
    the critical-path one, later ones are retries)."""
    chains: Dict[str, Dict[str, Tuple[int, int, int]]] = defaultdict(dict)

    def note(tid, stage, ev):
        cur = chains[tid].get(stage)
        ent = (int(ev.get('ts', 0)), int(ev.get('dur', 0)),
               int(ev.get('pid', 0)))
        if cur is None or ent[0] < cur[0]:
            chains[tid][stage] = ent

    for ev in events:
        if ev.get('ph') != 'X':
            continue
        args = ev.get('args') or {}
        name = ev.get('name')
        tid = args.get('trace_id')
        if tid and name in CHAIN_STAGES:
            note(tid, name, ev)
        for linked in (args.get('trace_ids') or ()):
            if name == 'train_step':
                note(linked, 'train_step', ev)
    return chains


def chain_errors(stages: Dict[str, Tuple[int, int, int]]) -> List[str]:
    """Causal-order violations within one chain: each present stage must
    START no earlier than the previous present stage's start (spans may
    overlap across hosts by clock skew; a start-order inversion beyond
    that indicates broken propagation)."""
    errors = []
    prev: Optional[Tuple[str, int]] = None
    for stage in CHAIN_STAGES:
        ent = stages.get(stage)
        if ent is None:
            continue
        if prev is not None and ent[0] < prev[1]:
            errors.append('%s starts before %s' % (stage, prev[0]))
        prev = (stage, ent[0])
    return errors


def build_serve_chains(events: List[Dict[str, Any]]
                       ) -> Dict[str, Dict[str, Any]]:
    """trace_id -> serving-path chain record: the earliest event per hop
    stage (``engine_batch`` links through ``args.trace_ids``, like
    train_step), plus EVERY link span (replays/handoffs/reconstructs
    repeat legitimately — each one is part of the causal story, not a
    retry to collapse)."""
    chains: Dict[str, Dict[str, Any]] = defaultdict(
        lambda: {'stages': {}, 'links': []})

    def note(tid, stage, ev):
        stages = chains[tid]['stages']
        cur = stages.get(stage)
        ent = (int(ev.get('ts', 0)), int(ev.get('dur', 0)),
               int(ev.get('pid', 0)))
        if cur is None or ent[0] < cur[0]:
            stages[stage] = ent

    for ev in events:
        if ev.get('ph') != 'X':
            continue
        args = ev.get('args') or {}
        name = ev.get('name')
        tid = args.get('trace_id')
        if tid:
            if name in SERVE_CHAIN_STAGES or name in GATEWAY_STAGES:
                note(tid, name, ev)
            if name in SERVE_LINK_STAGES:
                chains[tid]['links'].append(dict(args, stage=name,
                                                 ts=int(ev.get('ts', 0))))
        if name == 'engine_batch':
            for linked in (args.get('trace_ids') or ()):
                note(linked, 'engine_batch', ev)
    return dict(chains)


def serve_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The ``--serve`` report block: chain counts (complete / replay /
    reconstruct), per-hop latency percentiles, the per-replica
    queue-wait vs batch-compute split, and per-session ply timelines."""
    chains = build_serve_chains(events)

    # pid -> replica name, learned from serve_request spans (the engine
    # runs in the service process, so its queue_wait/engine_batch events
    # share the pid)
    pid_replica: Dict[int, str] = {}
    for ev in events:
        if ev.get('ph') == 'X' and ev.get('name') == 'serve_request':
            replica = (ev.get('args') or {}).get('replica')
            if replica:
                pid_replica.setdefault(int(ev.get('pid', 0)), str(replica))

    hop_durs: Dict[str, List[float]] = defaultdict(list)
    split: Dict[str, Dict[str, List[float]]] = defaultdict(
        lambda: {'queue_wait': [], 'engine_batch': []})
    sessions: Dict[str, List[Tuple[int, int]]] = defaultdict(list)
    for ev in events:
        if ev.get('ph') != 'X':
            continue
        name = ev.get('name')
        dur_s = int(ev.get('dur', 0)) / 1e6
        if name in SERVE_CHAIN_STAGES or name in GATEWAY_STAGES:
            hop_durs[name].append(dur_s)
        if name in ('queue_wait', 'engine_batch'):
            replica = pid_replica.get(int(ev.get('pid', 0)))
            if replica is not None:
                split[replica][name].append(dur_s)
        if name == 'gateway_ply':
            sid = (ev.get('args') or {}).get('sid')
            if sid is not None:
                sessions[str(sid)].append((int(ev.get('ts', 0)),
                                           int(ev.get('dur', 0))))

    complete = routed = replays = complete_replays = reconstructs = 0
    for rec in chains.values():
        stages, links = rec['stages'], rec['links']
        is_complete = all(s in stages for s in
                          ('client_request', 'serve_request', 'engine_batch'))
        has_replay = any(l['stage'] == 'router_replay' for l in links)
        complete += is_complete
        routed += is_complete and 'route_dispatch' in stages
        replays += has_replay
        complete_replays += is_complete and has_replay
        reconstructs += ('gateway_open' in stages
                         and any(l['stage'] == 'gateway_reconstruct'
                                 for l in links))

    def pcts(d: List[float]) -> Dict[str, Any]:
        return {'n': len(d), 'p50': round(percentile(d, 0.50), 6),
                'p95': round(percentile(d, 0.95), 6),
                'p99': round(percentile(d, 0.99), 6)}

    return {
        'chains': len(chains),
        'complete_chains': complete,
        'routed_chains': routed,
        'replay_chains': replays,
        'complete_replay_chains': complete_replays,
        'reconstruct_chains': reconstructs,
        'hop_seconds': {name: pcts(d)
                        for name, d in sorted(hop_durs.items())},
        'replica_split': {
            replica: {'queue_wait': pcts(d['queue_wait']),
                      'engine_batch': pcts(d['engine_batch'])}
            for replica, d in sorted(split.items())},
        'sessions': {
            sid: {'plies': len(rows),
                  'ply_seconds': pcts([dur / 1e6 for _ts, dur in rows]),
                  'span_seconds': round(
                      (max(ts + dur for ts, dur in rows)
                       - min(ts for ts, _d in rows)) / 1e6, 6)}
            for sid, rows in sorted(sessions.items())},
    }


def percentile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(len(vals) - 1, int(q * (len(vals) - 1) + 0.5))
    return vals[idx]


def summarize(events: List[Dict[str, Any]], as_json: bool = False,
              serve: bool = False, require: str = 'training') -> int:
    chains = build_chains(events)
    pids = {ev.get('pid') for ev in events if ev.get('ph') == 'X'}

    # batch-level stage durations
    stage_durs: Dict[str, List[float]] = defaultdict(list)
    for ev in events:
        if ev.get('ph') == 'X' and ev.get('name') in BATCH_STAGES:
            stage_durs[ev['name']].append(int(ev.get('dur', 0)) / 1e6)

    # per-chain segments + generation->gradient totals
    seg_durs: Dict[str, List[float]] = defaultdict(list)
    totals: List[float] = []
    complete = 0
    bad_chains = 0
    for tid, stages in chains.items():
        if chain_errors(stages):
            bad_chains += 1
        present = [(s,) + stages[s] for s in CHAIN_STAGES if s in stages]
        for (s_a, ts_a, dur_a, _p), (s_b, ts_b, _d, _q) in zip(
                present, present[1:]):
            seg_durs['%s->%s' % (s_a, s_b)].append(
                max(0.0, (ts_b - ts_a) / 1e6))
        if 'generate' in stages and 'train_step' in stages:
            complete += 1
            t_end = stages['train_step'][0] + stages['train_step'][1]
            totals.append(max(0.0, (t_end - stages['generate'][0]) / 1e6))

    report = {
        'events': len(events),
        'processes': len(pids),
        'chains': len(chains),
        'complete_chains': complete,
        'order_violations': bad_chains,
        'stage_seconds': {
            name: {'n': len(d), 'p50': round(percentile(d, 0.50), 6),
                   'p95': round(percentile(d, 0.95), 6)}
            for name, d in sorted(stage_durs.items())},
        'segment_seconds': {
            name: {'n': len(d), 'p50': round(percentile(d, 0.50), 6),
                   'p95': round(percentile(d, 0.95), 6)}
            for name, d in sorted(seg_durs.items())},
        'generation_to_gradient_seconds': {
            'n': len(totals), 'p50': round(percentile(totals, 0.50), 6),
            'p95': round(percentile(totals, 0.95), 6)},
    }
    sv = serve_summary(events)
    if serve:
        report['serve'] = sv
    if as_json:
        print(json.dumps(report))
    else:
        print('trace report: %d events from %d processes, %d episode '
              'chains (%d complete, %d order violations)'
              % (report['events'], report['processes'], report['chains'],
                 complete, bad_chains))
        print('stage durations (s):')
        for name, row in report['stage_seconds'].items():
            print('  %-14s p50=%-10g p95=%-10g n=%d'
                  % (name, row['p50'], row['p95'], row['n']))
        print('critical path (%s):' % ' -> '.join(CHAIN_STAGES))
        for name, row in report['segment_seconds'].items():
            print('  %-26s p50=%-10g p95=%-10g n=%d'
                  % (name, row['p50'], row['p95'], row['n']))
        g2g = report['generation_to_gradient_seconds']
        print('generation->gradient: p50=%g p95=%g n=%d'
              % (g2g['p50'], g2g['p95'], g2g['n']))
        if serve:
            print('serving path: %d request chains (%d complete, %d '
                  'routed), %d replay chain(s) (%d complete), %d '
                  'reconstruct chain(s)'
                  % (sv['chains'], sv['complete_chains'],
                     sv['routed_chains'], sv['replay_chains'],
                     sv['complete_replay_chains'],
                     sv['reconstruct_chains']))
            print('per-hop latency (s):')
            for name, row in sv['hop_seconds'].items():
                print('  %-14s p50=%-10g p95=%-10g p99=%-10g n=%d'
                      % (name, row['p50'], row['p95'], row['p99'],
                         row['n']))
            for replica, row in sv['replica_split'].items():
                print('replica %s: queue_wait p50=%g p99=%g (n=%d) | '
                      'engine_batch p50=%g p99=%g (n=%d)'
                      % (replica,
                         row['queue_wait']['p50'], row['queue_wait']['p99'],
                         row['queue_wait']['n'],
                         row['engine_batch']['p50'],
                         row['engine_batch']['p99'],
                         row['engine_batch']['n']))
            for sid, row in sv['sessions'].items():
                print('session %s: %d plies over %gs, ply p50=%g p99=%g'
                      % (sid, row['plies'], row['span_seconds'],
                         row['ply_seconds']['p50'],
                         row['ply_seconds']['p99']))
    ok_training = complete > 0
    ok_serve = sv['complete_chains'] > 0
    if require == 'serve':
        return 0 if ok_serve else 2
    if require == 'any':
        return 0 if (ok_training or ok_serve) else 2
    return 0 if ok_training else 2


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument('path', help='trace dir (HANDYRL_TPU_TRACE) or one '
                                     'trace-*.jsonl / trace-*.json file')
    parser.add_argument('--chrome', metavar='OUT',
                        help='also write one merged Chrome-trace JSON')
    parser.add_argument('--json', action='store_true',
                        help='machine-readable summary (one JSON object)')
    parser.add_argument('--serve', action='store_true',
                        help='also reduce the serving-path spans (per-hop '
                             'percentiles, replica split, replay chains, '
                             'session timelines)')
    parser.add_argument('--require', choices=('training', 'serve', 'any'),
                        default=None,
                        help='which chain kind must be complete for exit 0 '
                             '(default: serve when --serve, else training)')
    opts = parser.parse_args(argv)
    require = opts.require or ('serve' if opts.serve else 'training')

    files = discover_files(opts.path)
    if not files:
        print('no trace files under %r' % opts.path, file=sys.stderr)
        return 2
    events = load_events(files)
    if opts.chrome:
        with open(opts.chrome, 'w') as f:
            json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
        print('merged Chrome trace -> %s (%d events)'
              % (opts.chrome, len(events)), file=sys.stderr)
    return summarize(events, as_json=opts.json, serve=opts.serve,
                     require=require)


if __name__ == '__main__':
    sys.exit(main())
