"""Collate episode-lifecycle trace files into a critical-path summary.

Input: a trace directory (``HANDYRL_TPU_TRACE``) holding the per-run
``trace-<run_id>.jsonl`` event stream (and/or the finalized
``trace-<run_id>.json`` Chrome-trace file), or a single file of either
flavor. Every event is a Chrome-trace "complete" event; episode-linked
events carry ``args.trace_id`` (derived from the server-stamped task) and
the learner's ``train_step`` events carry ``args.trace_ids`` — the sampled
episodes whose windows that update consumed.

Output: a per-stage latency table, the per-episode critical path
(task_assign -> generate -> upload -> ingest -> train_step) with
generation->gradient p50/p95, and the batch-level stage summaries
(select/decode/assemble/ipc/h2d/compute/engine_batch). ``--chrome OUT``
additionally writes one merged Chrome-trace JSON across every run found.

Exit code: 0 when at least one complete generation->gradient chain was
found, 2 otherwise (the CI smoke asserts 0). Stdlib only.

Usage:
    python scripts/trace_report.py <dir-or-file> [--chrome OUT] [--json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

# the episode-lifecycle stage chain, in causal order (one vocabulary with
# docs/observability.md "Tracing"); 'evaluate' chains are reported too but
# only generation chains feed the generation->gradient headline
CHAIN_STAGES = ('task_assign', 'generate', 'upload', 'ingest', 'train_step')

# batch-level stages worth a duration summary when present
BATCH_STAGES = ('select', 'decode', 'assemble', 'ipc', 'h2d', 'dispatch',
                'host_block', 'engine_batch', 'generate', 'upload',
                'evaluate')


def discover_files(path: str) -> List[str]:
    """Trace files under ``path``: per run, prefer the append-forever JSONL
    (a superset of the finalized snapshot) and fall back to the .json."""
    if os.path.isfile(path):
        return [path]
    jsonls = sorted(glob.glob(os.path.join(path, 'trace-*.jsonl')))
    have = {os.path.splitext(os.path.basename(p))[0] for p in jsonls}
    jsons = [p for p in sorted(glob.glob(os.path.join(path, 'trace-*.json')))
             if os.path.splitext(os.path.basename(p))[0] not in have]
    return jsonls + jsons


def load_events(files: List[str]) -> List[Dict[str, Any]]:
    events: List[Dict[str, Any]] = []
    for path in files:
        try:
            with open(path) as f:
                if path.endswith('.json'):
                    events.extend(json.load(f).get('traceEvents', []))
                    continue
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        continue   # torn tail line from a killed process
        except (OSError, ValueError) as exc:
            print('warning: skipping unreadable %s (%s)' % (path, exc),
                  file=sys.stderr)
    return events


def build_chains(events: List[Dict[str, Any]]
                 ) -> Dict[str, Dict[str, Tuple[int, int, int]]]:
    """trace_id -> {stage: (ts, dur, pid)}; the earliest event wins per
    stage (re-issues/resends may repeat a stage — the first occurrence is
    the critical-path one, later ones are retries)."""
    chains: Dict[str, Dict[str, Tuple[int, int, int]]] = defaultdict(dict)

    def note(tid, stage, ev):
        cur = chains[tid].get(stage)
        ent = (int(ev.get('ts', 0)), int(ev.get('dur', 0)),
               int(ev.get('pid', 0)))
        if cur is None or ent[0] < cur[0]:
            chains[tid][stage] = ent

    for ev in events:
        if ev.get('ph') != 'X':
            continue
        args = ev.get('args') or {}
        name = ev.get('name')
        tid = args.get('trace_id')
        if tid and name in CHAIN_STAGES:
            note(tid, name, ev)
        for linked in (args.get('trace_ids') or ()):
            if name == 'train_step':
                note(linked, 'train_step', ev)
    return chains


def chain_errors(stages: Dict[str, Tuple[int, int, int]]) -> List[str]:
    """Causal-order violations within one chain: each present stage must
    START no earlier than the previous present stage's start (spans may
    overlap across hosts by clock skew; a start-order inversion beyond
    that indicates broken propagation)."""
    errors = []
    prev: Optional[Tuple[str, int]] = None
    for stage in CHAIN_STAGES:
        ent = stages.get(stage)
        if ent is None:
            continue
        if prev is not None and ent[0] < prev[1]:
            errors.append('%s starts before %s' % (stage, prev[0]))
        prev = (stage, ent[0])
    return errors


def percentile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(len(vals) - 1, int(q * (len(vals) - 1) + 0.5))
    return vals[idx]


def summarize(events: List[Dict[str, Any]], as_json: bool = False) -> int:
    chains = build_chains(events)
    pids = {ev.get('pid') for ev in events if ev.get('ph') == 'X'}

    # batch-level stage durations
    stage_durs: Dict[str, List[float]] = defaultdict(list)
    for ev in events:
        if ev.get('ph') == 'X' and ev.get('name') in BATCH_STAGES:
            stage_durs[ev['name']].append(int(ev.get('dur', 0)) / 1e6)

    # per-chain segments + generation->gradient totals
    seg_durs: Dict[str, List[float]] = defaultdict(list)
    totals: List[float] = []
    complete = 0
    bad_chains = 0
    for tid, stages in chains.items():
        if chain_errors(stages):
            bad_chains += 1
        present = [(s,) + stages[s] for s in CHAIN_STAGES if s in stages]
        for (s_a, ts_a, dur_a, _p), (s_b, ts_b, _d, _q) in zip(
                present, present[1:]):
            seg_durs['%s->%s' % (s_a, s_b)].append(
                max(0.0, (ts_b - ts_a) / 1e6))
        if 'generate' in stages and 'train_step' in stages:
            complete += 1
            t_end = stages['train_step'][0] + stages['train_step'][1]
            totals.append(max(0.0, (t_end - stages['generate'][0]) / 1e6))

    report = {
        'events': len(events),
        'processes': len(pids),
        'chains': len(chains),
        'complete_chains': complete,
        'order_violations': bad_chains,
        'stage_seconds': {
            name: {'n': len(d), 'p50': round(percentile(d, 0.50), 6),
                   'p95': round(percentile(d, 0.95), 6)}
            for name, d in sorted(stage_durs.items())},
        'segment_seconds': {
            name: {'n': len(d), 'p50': round(percentile(d, 0.50), 6),
                   'p95': round(percentile(d, 0.95), 6)}
            for name, d in sorted(seg_durs.items())},
        'generation_to_gradient_seconds': {
            'n': len(totals), 'p50': round(percentile(totals, 0.50), 6),
            'p95': round(percentile(totals, 0.95), 6)},
    }
    if as_json:
        print(json.dumps(report))
    else:
        print('trace report: %d events from %d processes, %d episode '
              'chains (%d complete, %d order violations)'
              % (report['events'], report['processes'], report['chains'],
                 complete, bad_chains))
        print('stage durations (s):')
        for name, row in report['stage_seconds'].items():
            print('  %-14s p50=%-10g p95=%-10g n=%d'
                  % (name, row['p50'], row['p95'], row['n']))
        print('critical path (%s):' % ' -> '.join(CHAIN_STAGES))
        for name, row in report['segment_seconds'].items():
            print('  %-26s p50=%-10g p95=%-10g n=%d'
                  % (name, row['p50'], row['p95'], row['n']))
        g2g = report['generation_to_gradient_seconds']
        print('generation->gradient: p50=%g p95=%g n=%d'
              % (g2g['p50'], g2g['p95'], g2g['n']))
    return 0 if complete > 0 else 2


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument('path', help='trace dir (HANDYRL_TPU_TRACE) or one '
                                     'trace-*.jsonl / trace-*.json file')
    parser.add_argument('--chrome', metavar='OUT',
                        help='also write one merged Chrome-trace JSON')
    parser.add_argument('--json', action='store_true',
                        help='machine-readable summary (one JSON object)')
    opts = parser.parse_args(argv)

    files = discover_files(opts.path)
    if not files:
        print('no trace files under %r' % opts.path, file=sys.stderr)
        return 2
    events = load_events(files)
    if opts.chrome:
        with open(opts.chrome, 'w') as f:
            json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
        print('merged Chrome trace -> %s (%d events)'
              % (opts.chrome, len(events)), file=sys.stderr)
    return summarize(events, as_json=opts.json)


if __name__ == '__main__':
    sys.exit(main())
