"""CI smoke for the actor inference service (docs/large_scale_training.md).

Runs ``bench.py`` in BENCH_MODE=actor on a tiny CPU geometry (TicTacToe,
2 workers) — a real gather + worker-process fleet over the 4-RPC protocol,
once with the per-host InferenceEngine and once on the per-worker B=1
path — and asserts the service contract rather than a throughput number
(CI machines are too noisy for thresholds):

  * the run completes and honors the one-JSON-line stdout contract;
  * the engine actually coalesces: batch-fill ratio > 1 worker-equivalent;
  * episode records are byte-compatible with the per-worker path under the
    fixed seed (the bit-identical record contract).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ)
    env.update({
        'JAX_PLATFORMS': 'cpu',
        'BENCH_MODE': 'actor',
        'BENCH_ACTOR_ENV': 'TicTacToe',
        'BENCH_ACTOR_WORKERS': '2',
        'BENCH_ACTOR_EPISODES': '12',
        'BENCH_ACTOR_WARMUP': '2',
        # generous coalescing window: the smoke asserts batching works, not
        # that it is fast, and CI boxes schedule workers erratically
        'BENCH_ACTOR_WAIT_MS': '20',
        'BENCH_DEADLINE_SEC': env.get('BENCH_DEADLINE_SEC', '540'),
    })
    proc = subprocess.run([sys.executable, os.path.join(REPO, 'bench.py')],
                          env=env, stdout=subprocess.PIPE, text=True,
                          timeout=600)
    out = proc.stdout.strip().splitlines()
    assert len(out) == 1, 'one-JSON-line contract violated: %r' % (out,)
    row = json.loads(out[0])
    print(json.dumps(row, indent=2))
    assert 'error' not in row, row.get('error')
    assert row['value'] > 0, 'engine fleet produced no measured episodes'
    assert row['per_worker_episodes_per_sec'] > 0, \
        'per-worker fleet produced no measured episodes'
    assert row['failed_episodes'] == 0, row
    assert row['batch_fill'] > 1.0, \
        'engine never coalesced past 1 request/batch (fill %.2f)' \
        % row['batch_fill']
    assert row['records_identical'] is True, \
        'engine-path episode records are not byte-compatible with the ' \
        'per-worker path'
    print('actor smoke OK: fill %.2f, %.1f eps/s engine vs %.1f per-worker'
          % (row['batch_fill'], row['value'],
             row['per_worker_episodes_per_sec']))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
