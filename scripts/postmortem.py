"""Collate blackbox dumps into a cross-process postmortem.

Input: the ``blackbox/`` directory of flight-recorder dumps every fleet
process writes on abnormal death (telemetry.dump_blackbox — fatal error,
preemption signal, nonfinite abort, supervisor crash declaration), plus
optionally the run's ``trace-<run_id>.jsonl`` stream and the learner's
``metrics_jsonl`` file. Output: one causal timeline across processes —
which process failed FIRST, the last-N flight-recorder events before each
death, and the alert transitions the learner's SLO engine recorded around
the failure window.

The first failure is attributed by the earliest *triggering event* among
the dumps: a dump's own recorder ring usually contains the supervisor /
guard event that declared the death, so dumps are ordered by the time of
their final recorded event (falling back to the dump timestamp) — a
supervisor that dumped late about an early death still sorts first.

Exit code (the CI contract): 0 when at least one blackbox dump was found
and a causal timeline could be built, 2 otherwise. Stdlib only.

Usage:
    python scripts/postmortem.py [BLACKBOX_DIR]
        [--trace DIR-or-file] [--metrics PATH] [--run RUN_ID]
        [--last N] [--json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

BLACKBOX_SCHEMA = 'handyrl_tpu.blackbox/1'


def discover_dumps(path: str, run_id: Optional[str] = None
                   ) -> List[Dict[str, Any]]:
    """Load every parseable blackbox dump under ``path`` (a directory of
    ``<role>-<pid>-<run_id>.json`` files, or one such file), optionally
    filtered to one run id. Unreadable files are skipped with a warning —
    a postmortem must degrade, not crash."""
    if os.path.isfile(path):
        files = [path]
    else:
        files = sorted(glob.glob(os.path.join(path, '*.json')))
    dumps = []
    for fp in files:
        try:
            with open(fp) as f:
                payload = json.load(f)
        except (OSError, ValueError) as exc:
            print('warning: skipping unreadable %s (%s)' % (fp, exc),
                  file=sys.stderr)
            continue
        if not isinstance(payload, dict) \
                or payload.get('schema') != BLACKBOX_SCHEMA:
            continue
        if run_id and str(payload.get('run_id')) != str(run_id):
            continue
        payload['_path'] = fp
        dumps.append(payload)
    return dumps


def failure_time(dump: Dict[str, Any]) -> float:
    """The moment this dump's process (or the process it declared dead)
    actually failed: the last recorded event's timestamp when present —
    the ring ends at the death — else the dump write time."""
    events = dump.get('events') or []
    if events:
        try:
            return float(events[-1].get('t', 0.0))
        except (TypeError, ValueError):
            pass
    return float(dump.get('time', 0.0))


def load_metrics_alerts(path: str, run_id: Optional[str] = None
                        ) -> List[Dict[str, Any]]:
    """Alert transitions reconstructed from the metrics_jsonl stream:
    one entry per rule appearance/disappearance in successive records'
    ``alerts.active`` lists (plus the final cumulative fired counts).
    Reads ``<path>.1`` first when a rotation generation exists."""
    records: List[Dict[str, Any]] = []
    for fp in (path + '.1', path):
        if not os.path.isfile(fp):
            continue
        try:
            with open(fp) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue   # torn tail line from a killed learner
                    if run_id and str(rec.get('run_id')) != str(run_id):
                        continue
                    if isinstance(rec.get('alerts'), dict):
                        records.append(rec)
        except OSError as exc:
            print('warning: skipping unreadable %s (%s)' % (fp, exc),
                  file=sys.stderr)
    transitions: List[Dict[str, Any]] = []
    prev_active: set = set()
    prev_fired: Dict[str, int] = {}
    for rec in records:
        blk = rec['alerts']
        active = set(blk.get('active') or [])
        t = float(blk.get('time') or rec.get('time') or 0.0)
        for name in sorted(active - prev_active):
            transitions.append({'t': t, 'alert': name, 'state': 'firing'})
        for name in sorted(prev_active - active):
            transitions.append({'t': t, 'alert': name, 'state': 'cleared'})
        # records land per epoch but alerts evaluate every few seconds: a
        # rule that fired AND cleared entirely between two records never
        # shows in any active set — only its cumulative fired count moves
        fired_now = {k: int(v) for k, v in (blk.get('fired') or {}).items()}
        for name, n in sorted(fired_now.items()):
            if n > prev_fired.get(name, 0) and name not in active \
                    and name not in prev_active:
                transitions.append({'t': t, 'alert': name,
                                    'state': 'fired+cleared'})
        if fired_now:
            prev_fired = fired_now
        prev_active = active
    fired = dict((records[-1]['alerts'].get('fired') or {})) \
        if records else {}
    return [{'transitions': transitions, 'fired': fired,
             'records': len(records),
             'still_active': sorted(prev_active)}]


def load_trace_activity(path: str) -> Dict[str, Any]:
    """Per-pid last-activity marks from the trace stream: when a process
    stops emitting spans, that silence brackets its death from the other
    side of the blackbox evidence."""
    files: List[str] = []
    if os.path.isfile(path):
        files = [path]
    elif os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, 'trace-*.jsonl')))
    last_by_pid: Dict[int, float] = {}
    events = 0
    for fp in files:
        try:
            with open(fp) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get('ph') != 'X':
                        continue
                    events += 1
                    pid = int(ev.get('pid', 0))
                    t = (int(ev.get('ts', 0))
                         + int(ev.get('dur', 0))) / 1e6
                    if t > last_by_pid.get(pid, 0.0):
                        last_by_pid[pid] = t
        except OSError as exc:
            print('warning: skipping unreadable %s (%s)' % (fp, exc),
                  file=sys.stderr)
    return {'events': events,
            'last_activity': {str(pid): round(t, 6)
                              for pid, t in sorted(last_by_pid.items())}}


def build_report(dumps: List[Dict[str, Any]], last_n: int,
                 alerts: Optional[Dict[str, Any]] = None,
                 trace: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    deaths = []
    for dump in sorted(dumps, key=failure_time):
        events = dump.get('events') or []
        deaths.append({
            'role': dump.get('role'), 'pid': dump.get('pid'),
            'run_id': dump.get('run_id'), 'reason': dump.get('reason'),
            'time': failure_time(dump),
            'dumped_at': dump.get('time'),
            'path': dump.get('_path'),
            'context': dump.get('context') or {},
            'last_events': events[-last_n:],
        })
    timeline: List[Dict[str, Any]] = []
    for death in deaths:
        who = '%s[%s]' % (death['role'], death['pid'])
        for ev in death['last_events']:
            timeline.append({'t': float(ev.get('t', 0.0)), 'source': who,
                             'kind': ev.get('kind'), 'msg': ev.get('msg')})
        timeline.append({'t': death['time'], 'source': who,
                         'kind': 'death',
                         'msg': 'declared dead (%s)' % death['reason']})
    if alerts:
        for tr in alerts.get('transitions') or []:
            timeline.append({'t': float(tr['t']), 'source': 'alerts',
                             'kind': 'alert',
                             'msg': '%s %s' % (tr['alert'], tr['state'])})
    timeline.sort(key=lambda e: e['t'])
    report: Dict[str, Any] = {
        'schema': 'handyrl_tpu.postmortem/1',
        'dumps': len(deaths),
        'runs': sorted({str(d['run_id']) for d in deaths}),
        'first_failure': deaths[0] if deaths else None,
        'deaths': deaths,
        'timeline': timeline,
    }
    if alerts is not None:
        report['alerts'] = alerts
    if trace is not None:
        report['trace'] = trace
    return report


def render(report: Dict[str, Any]):
    first = report.get('first_failure')
    print('postmortem: %d blackbox dump(s) across run(s) %s'
          % (report['dumps'], ', '.join(report['runs']) or '-'))
    if first:
        print('first failure: %s (pid %s) — %s at %.3f'
              % (first['role'], first['pid'], first['reason'],
                 first['time']))
    for death in report['deaths']:
        print('\n%s (pid %s): %s — last %d event(s):'
              % (death['role'], death['pid'], death['reason'],
                 len(death['last_events'])))
        for ev in death['last_events']:
            print('  %.3f %-10s %s'
                  % (float(ev.get('t', 0.0)), ev.get('kind', '?'),
                     ev.get('msg', '')))
    alerts = report.get('alerts')
    if alerts:
        fired = alerts.get('fired') or {}
        if fired:
            print('\nalerts fired: '
                  + ', '.join('%s x%d' % kv for kv in sorted(fired.items())))
        if alerts.get('still_active'):
            print('alerts still active: '
                  + ', '.join(alerts['still_active']))
        for tr in (alerts.get('transitions') or [])[-10:]:
            print('  %.3f alert %s %s'
                  % (tr['t'], tr['alert'], tr['state']))
    print('\ncausal timeline (%d entries):' % len(report['timeline']))
    for ev in report['timeline'][-40:]:
        print('  %.3f %-20s %-10s %s'
              % (ev['t'], ev['source'], ev['kind'], ev['msg']))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument('blackbox', nargs='?', default='blackbox',
                        help='blackbox dump directory (or one dump file)')
    parser.add_argument('--trace', metavar='PATH',
                        help='trace dir or trace-<run_id>.jsonl file')
    parser.add_argument('--metrics', metavar='PATH',
                        help='the learner metrics_jsonl file')
    parser.add_argument('--run', metavar='RUN_ID',
                        help='only consider dumps/records from this run')
    parser.add_argument('--last', type=int, default=20, metavar='N',
                        help='events to keep before each death (default 20)')
    parser.add_argument('--json', action='store_true',
                        help='machine-readable report (one JSON object)')
    opts = parser.parse_args(argv)

    dumps = discover_dumps(opts.blackbox, run_id=opts.run)
    alerts = None
    if opts.metrics:
        alerts = load_metrics_alerts(opts.metrics, run_id=opts.run)[0]
    trace = load_trace_activity(opts.trace) if opts.trace else None
    report = build_report(dumps, max(1, opts.last), alerts=alerts,
                          trace=trace)
    if opts.json:
        print(json.dumps(report))
    else:
        render(report)
    # exit contract: evidence found and a timeline built => 0, else 2
    return 0 if report['dumps'] > 0 and report['timeline'] else 2


if __name__ == '__main__':
    sys.exit(main())
