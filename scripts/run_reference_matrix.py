"""Run the actual DeNA/HandyRL reference on the BASELINE.md matrix configs.

Head-to-head counterpart of scripts/run_benchmark_matrix.py: launches
``/root/reference/main.py --train`` (unmodified, torch CPU) in a scratch
directory with a config.yaml matching the given row's hyperparameters, at
the same episode budget our rows use, parses the stdout win-rate lines (the
reference's log format IS its metrics interface), and appends a row tagged
``implementation: reference`` to benchmarks.jsonl.

Rows: ttt-td ttt-vtrace geister   (HungryGeese is excluded: the reference
env wraps kaggle_environments, which is not installed in this image — the
reference cannot run that row here at all.)

Usage: python scripts/run_reference_matrix.py [ROW ...] [--epochs N]
"""

import json
import os
import re
import subprocess
import sys
import tempfile
import time

REFERENCE = '/root/reference'
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# hyperparameters mirror scripts/run_benchmark_matrix.py ROWS; schema is the
# reference's config.yaml (reference config.yaml:1-38)
ROWS = {
    'ttt-td': {
        'env': 'TicTacToe',
        'train': {'turn_based_training': True, 'observation': False,
                  'gamma': 0.8, 'forward_steps': 8, 'batch_size': 64,
                  'policy_target': 'TD', 'value_target': 'TD',
                  'update_episodes': 200, 'minimum_episodes': 400},
    },
    'ttt-vtrace': {
        'env': 'TicTacToe',
        'train': {'turn_based_training': True, 'observation': False,
                  'gamma': 0.8, 'forward_steps': 8, 'batch_size': 64,
                  'policy_target': 'UPGO', 'value_target': 'VTRACE',
                  'update_episodes': 200, 'minimum_episodes': 400},
    },
    'geister': {
        'env': 'Geister',
        'train': {'turn_based_training': True, 'observation': True,
                  'gamma': 0.8, 'forward_steps': 16, 'burn_in_steps': 4,
                  'batch_size': 32, 'policy_target': 'TD',
                  'value_target': 'TD',
                  'update_episodes': 100, 'minimum_episodes': 200},
    },
}

_WIN_RE = re.compile(
    r'win rate(?: \(\w+\))? = ([\d.]+) \(([\d.]+) / (\d+)\)')
_EPOCH_RE = re.compile(r'^epoch (\d+)$')


def _config_yaml(row, epochs):
    train = {
        'turn_based_training': True, 'observation': False, 'gamma': 0.8,
        'forward_steps': 8, 'burn_in_steps': 0, 'compress_steps': 4,
        'entropy_regularization': 0.1, 'entropy_regularization_decay': 0.1,
        'update_episodes': 200, 'batch_size': 64, 'minimum_episodes': 400,
        'maximum_episodes': 100000, 'epochs': epochs, 'num_batchers': 2,
        'eval_rate': 0.1, 'worker': {'num_parallel': 6}, 'lambda': 0.7,
        'policy_target': 'TD', 'value_target': 'TD',
        'eval': {'opponent': ['random']}, 'seed': 0, 'restart_epoch': 0,
    }
    train.update(row['train'])
    lines = ['env_args:', "    env: '%s'" % row['env'], '', 'train_args:']
    for key, val in train.items():
        if isinstance(val, dict):
            lines.append('    %s:' % key)
            for k2, v2 in val.items():
                lines.append('        %s: %s' % (k2, json.dumps(v2)))
        else:
            lines.append('    %s: %s' % (key, json.dumps(val)))
    lines += ['', 'worker_args:', "    server_address: ''",
              '    num_parallel: 8', '']
    return '\n'.join(lines)


def run_row(name, epochs, deadline=3600):
    scratch = tempfile.mkdtemp(prefix='ref_%s_' % name)
    with open(os.path.join(scratch, 'config.yaml'), 'w') as f:
        f.write(_config_yaml(ROWS[name], epochs))
    log_path = os.path.join(scratch, 'train.log')
    print('[%s] reference run in %s (epochs=%d)' % (name, scratch, epochs))

    env = dict(os.environ, PYTHONPATH=REFERENCE, OMP_NUM_THREADS='1')
    t0 = time.time()
    with open(log_path, 'w') as log:
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REFERENCE, 'main.py'), '--train'],
            cwd=scratch, env=env, stdout=log, stderr=subprocess.STDOUT)
        try:
            proc.wait(timeout=deadline)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
    wall = time.time() - t0

    text = open(log_path, errors='replace').read()
    rates = [(float(m.group(1)), int(m.group(3)))
             for m in _WIN_RE.finditer(text)]
    epochs_seen = [int(m.group(1)) for line in text.splitlines()
                   for m in [_EPOCH_RE.match(line)] if m] or [0]

    last5 = rates[-5:]
    games = sum(n for _, n in last5)
    win_rate = (sum(r * n for r, n in last5) / games) if games else None

    # field names match the rows run_benchmark_matrix.py writes, so one
    # read of benchmarks.jsonl compares both implementations directly
    row = {
        'implementation': 'reference', 'row': name, 'epochs': epochs,
        'epochs_seen': max(epochs_seen), 'wall_s': round(wall, 1),
        'win_rate_vs_random_last5': (round(win_rate, 3)
                                     if win_rate is not None else None),
        'eval_games': games, 'log': log_path,
        'time': time.strftime('%Y-%m-%d %H:%M:%S'),
    }
    with open(os.path.join(REPO, 'benchmarks.jsonl'), 'a') as f:
        f.write(json.dumps(row) + '\n')
    print('[%s] reference: win_rate_vs_random_last5=%s games=%s wall=%.0fs'
          % (name, row['win_rate_vs_random_last5'], games, wall))
    return row


def main():
    argv = iter(sys.argv[1:])
    epochs = 30
    rows = []
    for a in argv:
        if a.startswith('--epochs='):
            epochs = int(a.split('=', 1)[1])
        elif a == '--epochs':
            epochs = int(next(argv))
        elif a in ROWS:
            rows.append(a)
        else:
            raise SystemExit('unknown row %r (choose from %s, or --epochs N)'
                             % (a, sorted(ROWS)))
    for name in rows or ['ttt-vtrace']:
        run_row(name, epochs)


if __name__ == '__main__':
    main()
