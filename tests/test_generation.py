"""Generation data-contract tests: sampled probabilities, action masks,
discounted returns, and episode accounting."""

import random

import numpy as np

from handyrl_tpu.environment import make_env
from handyrl_tpu.generation import BatchedGenerator, Generator
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.ops.batch import decompress_moments
from handyrl_tpu.utils.tree import softmax

ARGS = {
    'observation': False, 'gamma': 0.8, 'compress_steps': 4,
}


def _wrapper():
    env = make_env({'env': 'TicTacToe'})
    env.reset()
    w = ModelWrapper(env.net())
    w.ensure_params(env.observation(0))
    return w


def test_episode_moment_contract():
    random.seed(3)
    env = make_env({'env': 'TicTacToe'})
    w = _wrapper()
    gen = Generator(env, ARGS)
    ep = gen.generate({0: w, 1: w}, {'player': [0, 1], 'model_id': {0: 1, 1: 1}})
    assert ep is not None
    moments = decompress_moments(ep['moment'])
    assert len(moments) == ep['steps']

    for t, m in enumerate(moments):
        acting = m['turn'][0]
        other = 1 - acting
        assert acting == t % 2
        # acting player recorded everything; the other observed nothing
        assert m['action'][acting] is not None
        assert m['observation'][other] is None
        assert m['selected_prob'][other] is None
        # action must have been legal under the recorded mask
        assert m['action_mask'][acting][m['action'][acting]] == 0
        # recorded prob equals the masked softmax prob of the taken action
        # (recompute from the model deterministically)
        obs = m['observation'][acting]
        policy = w.inference(obs)['policy']
        p = softmax(policy - m['action_mask'][acting])
        np.testing.assert_allclose(m['selected_prob'][acting],
                                   p[m['action'][acting]], rtol=1e-4)

    # returns: discounted backward sum of rewards (TicTacToe has none -> 0)
    for m in moments:
        for pl in (0, 1):
            assert m['return'][pl] == 0.0
    assert set(ep['outcome'].values()) <= {1.0, -1.0, 0.0}


def test_batched_generator_outcome_distribution():
    random.seed(4)
    w = _wrapper()
    gen = BatchedGenerator(lambda i: make_env({'env': 'TicTacToe'}), w, ARGS,
                           n_envs=16)
    episodes = []
    for _ in range(200):
        episodes += gen.step()
        if len(episodes) >= 40:
            break
    assert len(episodes) >= 40
    # zero-sum: outcomes mirror
    for ep in episodes:
        assert abs(ep['outcome'][0] + ep['outcome'][1]) < 1e-9
    lens = [ep['steps'] for ep in episodes]
    assert 5 <= min(lens) and max(lens) <= 9
