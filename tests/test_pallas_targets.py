"""Pallas target kernels vs the lax.scan reference.

Default suite run (CPU conftest pin): kernels execute in interpret mode.
With ``HANDYRL_TPU_TESTS=1`` and a live TPU backend, every parity test ALSO
runs the genuinely compiled kernels on silicon (interpret=False) — this is
the VERDICT-mandated proof that the Pallas path works as Pallas, not only
as its interpreter.
"""

import jax
import numpy as np
import pytest

from handyrl_tpu.ops import targets as ref
from handyrl_tpu.ops import pallas_targets as pt

B, T, P = 4, 16, 2
SHAPE = (B, T, P, 1)

_ON_TPU = jax.default_backend() in ('tpu', 'axon')

# interpret=True runs anywhere; interpret=False only compiles on real TPU
INTERPRET_MODES = [True] + ([False] if _ON_TPU else [])


@pytest.fixture(params=INTERPRET_MODES,
                ids=['interpret', 'compiled'][:len(INTERPRET_MODES)])
def interpret(request):
    return request.param


def _rand(seed):
    rng = np.random.RandomState(seed)
    values = rng.randn(*SHAPE).astype(np.float32)
    returns = rng.randn(*SHAPE).astype(np.float32)
    rewards = rng.randn(*SHAPE).astype(np.float32)
    rhos = rng.uniform(0.1, 1.0, SHAPE).astype(np.float32)
    cs = rng.uniform(0.1, 1.0, SHAPE).astype(np.float32)
    masks = (rng.rand(*SHAPE) > 0.3).astype(np.float32)
    lambda_ = 0.7 + (1 - 0.7) * (1 - masks)
    return values, returns, rewards, rhos, cs, lambda_


@pytest.mark.parametrize('gamma', [1.0, 0.8])
@pytest.mark.parametrize('use_rewards', [True, False])
def test_td_pallas_matches_scan(gamma, use_rewards, interpret):
    values, returns, rewards, _, _, lambda_ = _rand(0)
    rew = rewards if use_rewards else None
    want_t, want_a = ref.td_lambda(values, returns, rew, lambda_, gamma)
    got_t, got_a = pt.td_lambda_pallas(values, returns, rew, lambda_, gamma,
                                       interpret=interpret)
    np.testing.assert_allclose(np.asarray(got_t), np.asarray(want_t),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_a), np.asarray(want_a),
                               rtol=1e-5, atol=1e-5)


def test_upgo_pallas_matches_scan(interpret):
    values, returns, rewards, _, _, lambda_ = _rand(1)
    want_t, _ = ref.upgo(values, returns, rewards, lambda_, 0.9)
    got_t, _ = pt.upgo_pallas(values, returns, rewards, lambda_, 0.9,
                              interpret=interpret)
    np.testing.assert_allclose(np.asarray(got_t), np.asarray(want_t),
                               rtol=1e-5, atol=1e-5)


def test_vtrace_pallas_matches_scan(interpret):
    values, returns, rewards, rhos, cs, lambda_ = _rand(2)
    want_v, want_a = ref.vtrace(values, returns, rewards, lambda_, 0.9, rhos, cs)
    got_v, got_a = pt.vtrace_pallas(values, returns, rewards, lambda_, 0.9,
                                    rhos, cs, interpret=interpret)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(want_v),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_a), np.asarray(want_a),
                               rtol=1e-5, atol=1e-5)


def test_nonmultiple_of_128_lanes(interpret):
    """B*P = 6 forces lane padding."""
    rng = np.random.RandomState(3)
    shape = (3, 5, 2, 1)
    values = rng.randn(*shape).astype(np.float32)
    returns = rng.randn(*shape).astype(np.float32)
    lambda_ = np.full(shape, 0.7, np.float32)
    want_t, _ = ref.td_lambda(values, returns, None, lambda_, 0.9)
    got_t, _ = pt.td_lambda_pallas(values, returns, None, lambda_, 0.9,
                                   interpret=interpret)
    np.testing.assert_allclose(np.asarray(got_t), np.asarray(want_t),
                               rtol=1e-5, atol=1e-5)


def test_gate_closed_without_opt_in(monkeypatch):
    """Scan is the default everywhere (measured faster on TPU; module
    docstring) — the gate only opens with HANDYRL_PALLAS_TARGETS=1."""
    monkeypatch.delenv('HANDYRL_PALLAS_TARGETS', raising=False)
    assert pt.use_pallas_targets() is False


@pytest.mark.skipif(_ON_TPU, reason='backend guard legitimately passes on TPU')
def test_gate_rejects_non_tpu_backend_even_when_opted_in(monkeypatch):
    """With the env opt-in set, a non-TPU backend must still be refused
    BEFORE the probe runs (the real-kernel probe cannot work there)."""
    monkeypatch.setenv('HANDYRL_PALLAS_TARGETS', '1')
    monkeypatch.setattr(pt, '_PROBE_RESULT', None)
    assert pt.use_pallas_targets() is False
    # the probe must not have been attempted (it would have cached a result)
    assert pt._PROBE_RESULT is None


@pytest.mark.skipif(_ON_TPU, reason='probe legitimately passes on TPU')
def test_probe_never_raises_and_declines_off_tpu():
    """The startup probe compiles a real (non-interpret) kernel; on a
    backend where that cannot work it must decline gracefully, never
    raise — the trainer falls back to the lax.scan path."""
    assert pt._probe_on_device() is False


@pytest.mark.skipif(not _ON_TPU, reason='needs a live TPU backend')
def test_probe_passes_and_gate_opens_on_tpu(monkeypatch):
    """On real silicon the startup probe must compile, run, and agree
    with the scan reference — and the gate opens once opted in."""
    monkeypatch.setenv('HANDYRL_PALLAS_TARGETS', '1')
    assert pt._probe_on_device() is True
    assert pt.use_pallas_targets() is True
