"""Serving-tier tests: the versioned ModelRegistry (atomic promote/rollback
under races, restart recovery, CRC-verified loads, GC pinning), the
InferenceService end to end (continuous batching, name@version resolution,
promote mid-traffic, admission/drain), the EngineClient remote-service
path with its byte-identical failover, and the serve:///registry:// model
specs the evaluation stack resolves."""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.environment import make_env
from handyrl_tpu.generation import model_act, sample_seed
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.serving.client import (RemoteServiceModel, ServiceClient,
                                        ServiceError, model_from_spec)
from handyrl_tpu.serving.registry import (ModelRegistry, RegistryError,
                                          parse_spec,
                                          pinned_checkpoint_paths)
from handyrl_tpu.serving.service import InferenceService
from handyrl_tpu.utils.fs import checksummed_write_bytes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ttt_wrapper(seed=7):
    env = make_env({'env': 'TicTacToe'})
    env.reset()
    w = ModelWrapper(env.net(), seed=seed)
    w.ensure_params(env.observation(0))
    return env, w


def _service_args(root, **srv):
    args = apply_defaults({
        'env_args': {'env': 'TicTacToe'},
        'train_args': {'serving': {'port': 0, 'registry_dir': root, **srv}},
    })['train_args']
    args['env'] = {'env': 'TicTacToe'}
    return args


# ---------------------------------------------------------------------------
# registry


def test_split_model_specs_keeps_url_specs_whole():
    from handyrl_tpu.evaluation import split_model_specs
    assert split_model_specs('models/latest.ckpt') == ['models/latest.ckpt']
    assert split_model_specs('a.ckpt:random') == ['a.ckpt', 'random']
    assert split_model_specs('serve://h:9997/l@champion') == \
        ['serve://h:9997/l@champion']
    assert split_model_specs('serve://h:9997/l@champion:random') == \
        ['serve://h:9997/l@champion', 'random']
    assert split_model_specs('registry://models/l@3:rulebase') == \
        ['registry://models/l@3', 'rulebase']
    assert split_model_specs('a.ckpt:serve://h:1/l@latest') == \
        ['a.ckpt', 'serve://h:1/l@latest']


def test_parse_spec():
    assert parse_spec('line@champion') == ('line', 'champion')
    assert parse_spec('line@7') == ('line', '7')
    assert parse_spec('line') == ('line', 'champion')
    assert parse_spec('line@') == ('line', 'champion')
    with pytest.raises(RegistryError):
        parse_spec('@champion')


def test_registry_publish_resolve_load(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish('default', snapshot={'architecture': 'X',
                                          'params': b'AAAA'}, steps=10)
    v2 = reg.publish('default', snapshot={'architecture': 'X',
                                          'params': b'BBBB'}, steps=20)
    # first publish auto-champions; later ones are candidates
    assert reg.resolve('default', 'champion')[0] == v1
    assert reg.resolve('default', 'latest')[0] == v2
    assert reg.resolve('default', v2)[1]['steps'] == 20
    snap = reg.load_snapshot('default', v2)
    assert snap['params'] == b'BBBB'
    assert snap['architecture'] == 'X' and snap['version'] == v2
    with pytest.raises(RegistryError):
        reg.resolve('default', '99')
    with pytest.raises(RegistryError):
        reg.resolve('nosuchline')
    # restart recovery: a fresh instance reads the exact serving set
    again = ModelRegistry(str(tmp_path))
    assert again.resolve('default', 'champion')[0] == v1
    assert sorted(again.describe()['default']['versions']) == sorted([v1, v2])


def test_registry_promote_rollback_bit_identical(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    reg.publish('line', snapshot={'architecture': 'X', 'params': b'OLD1'},
                version=1, promote=True)
    reg.publish('line', snapshot={'architecture': 'X', 'params': b'NEW2'},
                version=2)
    before = reg.load_snapshot('line', 'champion')['params']
    reg.promote('line', 2)
    assert reg.load_snapshot('line', 'champion')['params'] == b'NEW2'
    restored = reg.rollback('line')
    assert restored == '1'
    # the prior champion returns bit-identically (its bytes never moved)
    assert reg.load_snapshot('line', 'champion')['params'] == before == b'OLD1'
    # rollback is itself revertible (champion/previous swap)
    assert reg.rollback('line') == '2'


def test_registry_publish_by_path_and_retire(tmp_path):
    ckpt = str(tmp_path / 'ext' / '5.ckpt')
    os.makedirs(os.path.dirname(ckpt))
    checksummed_write_bytes(ckpt, b'EXTERNAL')
    reg = ModelRegistry(str(tmp_path / 'reg'))
    with pytest.raises(RegistryError):
        reg.publish('l', path=ckpt)          # architecture required
    reg.publish('l', path=ckpt, architecture='X', version=5, promote=True)
    assert reg.load_snapshot('l')['params'] == b'EXTERNAL'
    assert ckpt in {os.path.abspath(p) for p in reg.pinned_paths()}
    reg.publish('l', snapshot={'architecture': 'X', 'params': b'C'},
                version=6)
    with pytest.raises(RegistryError):
        reg.retire('l', 5)                   # champion cannot be retired
    reg.retire('l', 6)                       # candidate can
    assert '6' not in reg.describe()['l']['versions']
    with pytest.raises(RegistryError):
        reg.publish('l', snapshot={'architecture': 'X', 'params': b'D'},
                    version=5)               # duplicate version id


def test_registry_corrupt_version_is_unloadable(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    reg.publish('l', snapshot={'architecture': 'X', 'params': b'GOODBYTES'},
                version=1, promote=True)
    path = reg.resolve('l', '1')[1]['path']
    raw = bytearray(open(path, 'rb').read())
    raw[0] ^= 0xFF
    with open(path, 'wb') as f:              # deliberate torn write
        f.write(bytes(raw))
    # resolution still answers (the manifest is intact)...
    assert reg.resolve('l', 'champion')[0] == '1'
    # ...but the load refuses the unverifiable bytes
    with pytest.raises(RegistryError, match='unverifiable'):
        reg.load_snapshot('l', 'champion')


def test_registry_corrupt_manifest_suspends_pinning(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    reg.publish('l', snapshot={'architecture': 'X', 'params': b'A'},
                version=1, promote=True)
    assert pinned_checkpoint_paths(str(tmp_path))
    with open(os.path.join(str(tmp_path), 'registry.json'), 'w') as f:
        f.write('{torn json')
    # present-but-unreadable manifest => pin set UNKNOWN, not empty
    assert pinned_checkpoint_paths(str(tmp_path)) is None
    # and no manifest at all => genuinely nothing pinned
    assert pinned_checkpoint_paths(str(tmp_path / 'nowhere')) == set()


@pytest.mark.timeout(120)
def test_registry_racing_promotes_never_torn(tmp_path):
    """Two promote racers (one in-process thread, one separate PROCESS) +
    a reader: every mid-race read observes a complete, CRC-valid serving
    set — champion always one of the two versions, bytes always loadable."""
    root = str(tmp_path)
    reg = ModelRegistry(root)
    reg.publish('l', snapshot={'architecture': 'X', 'params': b'AAAA'},
                version=1, promote=True)
    reg.publish('l', snapshot={'architecture': 'X', 'params': b'BBBB'},
                version=2)

    errs = []

    def thread_racer():
        try:
            r = ModelRegistry(root)
            for k in range(60):
                r.promote('l', 1 + (k % 2))
        except Exception as exc:   # noqa: BLE001 — surfaced via errs
            errs.append(exc)

    child = subprocess.Popen(
        [sys.executable, '-c',
         'import sys; sys.path.insert(0, %r)\n'
         'from handyrl_tpu.serving.registry import ModelRegistry\n'
         'r = ModelRegistry(%r)\n'
         'for k in range(60): r.promote("l", 2 - (k %% 2))\n'
         % (REPO, root)],
        stderr=subprocess.DEVNULL,
        env=dict(os.environ, JAX_PLATFORMS='cpu'))
    racer = threading.Thread(target=thread_racer, name='promote-racer')
    racer.start()
    reads = 0
    while racer.is_alive() or child.poll() is None:
        snap = ModelRegistry(root).load_snapshot('l', 'champion')
        assert snap['params'] in (b'AAAA', b'BBBB')
        assert snap['version'] in ('1', '2')
        reads += 1
    racer.join()
    assert child.wait() == 0
    assert not errs, errs
    assert reads > 0
    # the final state is one of the two promotes, fully consistent
    final = ModelRegistry(root)
    champ = final.resolve('l', 'champion')[0]
    assert champ in ('1', '2')
    assert final.load_snapshot('l')['params'] == \
        {'1': b'AAAA', '2': b'BBBB'}[champ]


# ---------------------------------------------------------------------------
# keep_checkpoints GC × registry pins (the PR 4 exclusion, extended)


class _GcLearnerStub:
    """The REAL Learner retention-GC code over a synthetic model_dir (the
    method needs only args + model_path)."""

    def __init__(self, args):
        from handyrl_tpu.train import Learner
        self.args = args
        self.model_path = Learner.model_path.__get__(self)
        self._gc_checkpoints = Learner._gc_checkpoints.__get__(self)
        self._registry_root = Learner._registry_root.__get__(self)


def _fake_ckpts(model_dir, epochs):
    os.makedirs(model_dir, exist_ok=True)
    for e in epochs:
        checksummed_write_bytes(os.path.join(model_dir, '%d.ckpt' % e),
                                b'ckpt-%d' % e)


def test_gc_never_collects_registry_pinned(tmp_path):
    model_dir = str(tmp_path / 'models')
    _fake_ckpts(model_dir, [1, 2, 3, 4, 5])
    # the registry pins epoch 2 (a champion) and epoch 3 (a candidate)
    reg = ModelRegistry(model_dir)
    reg.publish('default', path=os.path.join(model_dir, '2.ckpt'),
                architecture='X', version=2, promote=True)
    reg.publish('default', path=os.path.join(model_dir, '3.ckpt'),
                architecture='X', version=3)
    stub = _GcLearnerStub({'keep_checkpoints': 2, 'model_dir': model_dir,
                           'eval': {}, 'serving': {}})
    stub._gc_checkpoints()
    left = sorted(int(n.split('.')[0]) for n in os.listdir(model_dir)
                  if n.endswith('.ckpt') and n.split('.')[0].isdigit())
    # epochs 4,5 kept by the window; 2,3 kept by the PIN; only 1 collected
    assert left == [2, 3, 4, 5]
    # retiring the candidate unpins it: the next pass collects epoch 3
    reg.retire('default', 3)
    stub._gc_checkpoints()
    left = sorted(int(n.split('.')[0]) for n in os.listdir(model_dir)
                  if n.endswith('.ckpt') and n.split('.')[0].isdigit())
    assert left == [2, 4, 5]


def test_learner_publish_hook_pins_and_promotes(tmp_path):
    """The REAL Learner publish hook (serving.publish): each numbered
    checkpoint lands in the registry as <line>@<epoch>, auto_promote flips
    the champion, and the pin immediately protects it from the same
    update's retention GC."""
    from handyrl_tpu.train import Learner
    env, w = _ttt_wrapper(seed=7)
    model_dir = str(tmp_path / 'models')
    stub = _GcLearnerStub({'keep_checkpoints': 1, 'model_dir': model_dir,
                           'eval': {},
                           'serving': {'publish': True, 'line': 'ttt',
                                       'auto_promote': True}})
    stub.wrapper = w
    stub._registry = None
    stub._publish_checkpoint = Learner._publish_checkpoint.__get__(stub)
    os.makedirs(model_dir)
    for epoch in (1, 2, 3):
        checksummed_write_bytes(os.path.join(model_dir, '%d.ckpt' % epoch),
                                w.params_bytes())
        stub.model_epoch = epoch
        stub._publish_checkpoint(steps=epoch * 10)
        stub._gc_checkpoints()
    reg = ModelRegistry(model_dir)
    assert reg.resolve('ttt', 'champion')[0] == '3'
    assert reg.resolve('ttt', '3')[1]['steps'] == 30
    # every published epoch is pinned: GC (keep=1) collected NOTHING
    left = sorted(int(n.split('.')[0]) for n in os.listdir(model_dir)
                  if n.endswith('.ckpt') and n.split('.')[0].isdigit())
    assert left == [1, 2, 3]
    # the published bytes load back CRC-verified and bit-identical
    assert reg.load_snapshot('ttt')['params'] == w.params_bytes()
    # a remote worker's '<line>@<mid>' convention resolves epochs directly
    assert reg.resolve('ttt', '2')[0] == '2'


def test_gc_suspended_when_manifest_unreadable(tmp_path):
    model_dir = str(tmp_path / 'models')
    _fake_ckpts(model_dir, [1, 2, 3, 4])
    with open(os.path.join(model_dir, 'registry.json'), 'w') as f:
        f.write('{torn')
    stub = _GcLearnerStub({'keep_checkpoints': 1, 'model_dir': model_dir,
                           'eval': {}, 'serving': {}})
    stub._gc_checkpoints()
    left = [n for n in os.listdir(model_dir) if n.endswith('.ckpt')]
    # pin set unknown => conservatively collect NOTHING
    assert len(left) == 4


# ---------------------------------------------------------------------------
# the service end to end (in-process)


@pytest.mark.timeout(300)
def test_service_end_to_end_promote_and_drain(tmp_path):
    env, w1 = _ttt_wrapper(seed=7)
    _, w2 = _ttt_wrapper(seed=8)
    obs = env.observation(0)
    legal = env.legal_actions(0)
    reg = ModelRegistry(str(tmp_path))
    reg.publish('default', snapshot=w1.snapshot(), version=1, steps=10,
                promote=True)

    svc = InferenceService(_service_args(str(tmp_path))).start()
    try:
        client = ServiceClient('localhost', svc.port, name='t0')
        seed = sample_seed(11, (0, 3), 0)

        # act parity: the service reply equals the local path bit for bit
        rep = client.request('default@champion', obs, legal=legal, seed=seed)
        ref = model_act(w1, obs, None, legal, seed)
        assert rep['action'] == ref['action']
        assert rep['prob'] == ref['prob']
        assert isinstance(rep['prob'], np.float32)
        np.testing.assert_array_equal(rep['action_mask'], ref['action_mask'])
        np.testing.assert_array_equal(rep['value'], ref['value'])

        # outputs path (observer plies / Agent.inference): the engine runs
        # the padded-bucket batched program, so the bit-exact reference is
        # bucketed_inference, not the (last-float-bit-different) B=1 one
        from handyrl_tpu.generation import bucketed_inference
        out = RemoteServiceModel(client, 'default@1').inference(obs)
        np.testing.assert_array_equal(
            out['policy'], np.asarray(bucketed_inference(w1, obs)['policy']))

        # bare integer ids resolve as versions of the default line
        rep_mid = client.collect(client.submit('default@1', obs, legal=legal,
                                               seed=seed))
        assert rep_mid['action'] == ref['action']

        # unknown specs are error-ANSWERED, not dropped
        with pytest.raises(ServiceError):
            client.request('default@99', obs, legal=legal, seed=seed)
        with pytest.raises(ServiceError):
            client.request('nosuchline@champion', obs, legal=legal,
                           seed=seed)

        # promote mid-traffic: champion flips atomically, zero failed
        # requests on either side of the flip
        reg.publish('default', snapshot=w2.snapshot(), version=2, steps=20,
                    promote=True)
        rep2 = client.request('default@champion', obs, legal=legal,
                              seed=seed)
        ref2 = model_act(w2, obs, None, legal, seed)
        assert rep2['prob'] == ref2['prob']
        assert client.resolve('default@champion')['version'] == '2'
        # rollback restores the prior champion bit-identically
        reg.rollback('default')
        rep3 = client.request('default@champion', obs, legal=legal,
                              seed=seed)
        assert rep3['prob'] == rep['prob'] and rep3['action'] == rep['action']

        status = client.status()
        assert status['answered'] == status['received'] > 0
        assert status['inflight'] == 0 and status['shed'] == 0
        assert status['lines']['default']['champion'] == '1'

        # drain: new arrivals are error-answered, never silently dropped
        svc.request_drain()
        with pytest.raises(ServiceError, match='draining'):
            client.request('default@champion', obs, legal=legal, seed=seed)
        assert svc.drained()
        client.close()
    finally:
        svc.stop(drain=False)


@pytest.mark.timeout(300)
def test_eval_specs_resolve_against_registry_and_service(tmp_path):
    """The evaluation stack's model specs: ``registry://`` loads pinned
    bytes locally; ``serve://`` proxies matches through the service — and
    an exec_match completes against both, before AND after a promote."""
    from handyrl_tpu.agent import Agent, RandomAgent
    from handyrl_tpu.evaluation import exec_match, load_model

    env, w1 = _ttt_wrapper(seed=7)
    _, w2 = _ttt_wrapper(seed=8)
    reg = ModelRegistry(str(tmp_path))
    reg.publish('default', snapshot=w1.snapshot(), version=1, promote=True)

    local = load_model('registry://%s/default@champion' % tmp_path, env)
    from flax import serialization
    assert serialization.to_bytes(local.params) == w1.snapshot()['params']

    svc = InferenceService(_service_args(str(tmp_path))).start()
    try:
        spec = 'serve://localhost:%d/default@champion' % svc.port
        remote = load_model(spec, env)
        assert isinstance(remote, RemoteServiceModel)
        result = exec_match(make_env({'env': 'TicTacToe'}),
                            {0: Agent(remote), 1: RandomAgent()})
        assert result is not None and 0 in result['result']
        # promote mid-league: the SAME proxy follows the champion flip
        reg.publish('default', snapshot=w2.snapshot(), version=2,
                    promote=True)
        result2 = exec_match(make_env({'env': 'TicTacToe'}),
                             {0: Agent(remote), 1: RandomAgent()})
        assert result2 is not None
        remote.close()
    finally:
        svc.stop(drain=False)


# ---------------------------------------------------------------------------
# EngineClient remote-service mode (the serving.endpoint satellite)


class _GatherPipeStub:
    """The worker's gather pipe in remote mode: engine frames must NEVER
    ride it; the degraded path's 'model' RPC answers with the snapshot."""

    def __init__(self, snapshot):
        self._snapshot = snapshot
        self._last = None

    def send(self, msg):
        assert msg[0] != '__infer__', \
            'engine frame on the gather pipe in remote-service mode'
        self._last = msg

    def recv(self):
        assert self._last[0] == 'model'
        return self._snapshot

    def poll(self, timeout=0.0):
        return False


def _free_port() -> int:
    s = socket.socket()
    s.bind(('', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _remote_client(endpoint, snapshot, **inf):
    from handyrl_tpu.inference import EngineClient
    args = {'inference': {'enabled': True, 'request_timeout': 10.0,
                          'request_retries': 0, 'failover': True,
                          'reprobe_initial_delay': 0.1,
                          'reprobe_max_delay': 0.5, **inf},
            'serving': {'endpoint': endpoint, 'line': 'default'},
            'env': {'env': 'TicTacToe'}, 'seed': 11}
    return EngineClient(_GatherPipeStub(snapshot), args, namespace=9)


@pytest.mark.timeout(300)
def test_engine_client_remote_service_bitwise(tmp_path):
    from handyrl_tpu.inference import RemoteModel
    env, w = _ttt_wrapper(seed=7)
    obs = env.observation(0)
    legal = env.legal_actions(0)
    reg = ModelRegistry(str(tmp_path))
    reg.publish('default', snapshot=w.snapshot(), version=5, promote=True)
    svc = InferenceService(_service_args(str(tmp_path))).start()
    try:
        remote = RemoteModel(
            _remote_client('localhost:%d' % svc.port, w.snapshot()), 5)
        for draw in range(4):
            seed = sample_seed(11, (0, 4), draw)
            rep = remote.act(obs, None, legal, seed)
            ref = model_act(w, obs, None, legal, seed)
            assert rep['action'] == ref['action']
            assert rep['prob'] == ref['prob']
            assert isinstance(rep['prob'], np.float32)
            np.testing.assert_array_equal(rep['value'], ref['value'])
        assert remote.client.engine_ok
    finally:
        svc.stop(drain=False)


@pytest.mark.timeout(300)
def test_engine_client_dead_service_fails_over_and_repromotes(tmp_path):
    """A dead service endpoint degrades to the per-worker path (records
    byte-identical, circuit open); once a service appears on the endpoint
    a half-open probe re-promotes the client to the remote path."""
    from handyrl_tpu.inference import RemoteModel
    env, w = _ttt_wrapper(seed=7)
    obs = env.observation(0)
    legal = env.legal_actions(0)
    port = _free_port()
    remote = RemoteModel(_remote_client('localhost:%d' % port,
                                        w.snapshot()), 5)
    seed = sample_seed(11, (0, 6), 0)
    ref = model_act(w, obs, None, legal, seed)

    rep = remote.act(obs, None, legal, seed)          # dead endpoint
    assert rep['action'] == ref['action'] and rep['prob'] == ref['prob']
    np.testing.assert_array_equal(rep['action_mask'], ref['action_mask'])
    assert remote.client.engine_ok is False

    reg = ModelRegistry(str(tmp_path))
    reg.publish('default', snapshot=w.snapshot(), version=5, promote=True)
    svc = InferenceService(
        _service_args(str(tmp_path), port=port)).start()
    try:
        deadline = time.monotonic() + 30
        while not remote.client.engine_ok and time.monotonic() < deadline:
            time.sleep(0.15)   # let the reprobe backoff elapse
            rep = remote.act(obs, None, legal, seed)
            assert rep['prob'] == ref['prob']         # identical either path
        assert remote.client.engine_ok, 'probe never re-promoted the client'
    finally:
        svc.stop(drain=False)


# ---------------------------------------------------------------------------
# admission + drain e2e (subprocess, SIGTERM)


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_service_sigterm_drains_and_exits_75(tmp_path):
    env, w = _ttt_wrapper(seed=7)
    obs = env.observation(0)
    legal = env.legal_actions(0)
    ModelRegistry(str(tmp_path)).publish('default', snapshot=w.snapshot(),
                                         version=1, promote=True)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'handyrl_tpu.serving', '--env', 'TicTacToe',
         '--registry', str(tmp_path), '--port', '0', '--line', 'default'],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=dict(os.environ, JAX_PLATFORMS='cpu'))
    try:
        ready = json.loads(proc.stdout.readline())['serving_ready']
        client = ServiceClient('localhost', int(ready['port']), name='drain')
        # one served request proves the service is live
        client.request('default@champion', obs, legal=legal,
                       seed=sample_seed(1, (0, 0), 0))
        # a burst left in flight through the SIGTERM: every rid must be
        # ANSWERED (ok or an explicit drain error) before the exit
        rids = [client.submit('default@champion', obs, legal=legal,
                              seed=sample_seed(1, (0, k), 0))
                for k in range(8)]
        proc.send_signal(signal.SIGTERM)
        unanswered = 0
        for rid in rids:
            try:
                client.collect(rid, timeout=30)
            except ServiceError:
                pass               # drain error reply: answered
            except TimeoutError:
                unanswered += 1
        assert unanswered == 0, '%d request(s) dropped un-answered' \
            % unanswered
        assert proc.wait(timeout=60) == 75   # EX_TEMPFAIL: restart me
        client.close()
    finally:
        if proc.poll() is None:
            proc.kill()

# ---------------------------------------------------------------------------
# registry lock timeout (serving.lock_timeout satellite)


def test_registry_lock_timeout_raises_loudly(tmp_path):
    """A peer wedged while holding the manifest flock must surface as
    RegistryLockTimeout after serving.lock_timeout, not a silent hang."""
    import fcntl
    from handyrl_tpu.serving.registry import RegistryLockTimeout
    reg = ModelRegistry(str(tmp_path), lock_timeout=0.4)
    reg.publish('l', snapshot={'architecture': 'X', 'params': b'AAAA'},
                version=1, promote=True)
    fd = os.open(os.path.join(str(tmp_path), '.registry.lock'),
                 os.O_CREAT | os.O_RDWR)
    fcntl.flock(fd, fcntl.LOCK_EX)        # the wedged peer
    try:
        t0 = time.monotonic()
        with pytest.raises(RegistryLockTimeout):
            reg.promote('l', 1)
        assert time.monotonic() - t0 < 5.0
    finally:
        os.close(fd)
    # lock released: the same mutation goes through
    reg.promote('l', 1)


# ---------------------------------------------------------------------------
# ServiceClient transport-failure semantics (dial retry satellite)


def test_service_client_dead_endpoint_raises_unavailable():
    from handyrl_tpu.serving.client import ServiceUnavailable
    port = _free_port()
    t0 = time.monotonic()
    with pytest.raises(ServiceUnavailable):
        ServiceClient('localhost', port, dial_retries=1, dial_backoff=0.05)
    assert time.monotonic() - t0 < 5.0


def test_service_client_severed_socket_raises_unavailable(tmp_path):
    """A socket that dies mid-wait surfaces as ServiceUnavailable (the
    retryable transport error), never a raw OSError and never a
    ServiceError (which means the service ANSWERED with an error)."""
    from handyrl_tpu.serving.client import ServiceUnavailable
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    legal = env.legal_actions(0)
    ModelRegistry(str(tmp_path)).publish('default', snapshot=w.snapshot(),
                                         version=1, promote=True)
    svc = InferenceService(_service_args(str(tmp_path))).start()
    from tests.proxy import ChaosProxy
    proxy = ChaosProxy(target_port=svc.port)
    try:
        client = ServiceClient('127.0.0.1', proxy.port, timeout=10.0,
                               dial_retries=0)
        client.request('default@champion', obs, legal=legal,
                       seed=sample_seed(1, (0, 0), 0))
        rid = client.submit('default@champion', obs, legal=legal,
                            seed=sample_seed(1, (0, 1), 0))
        proxy.accepting = False    # a racing accept closes the socket
        proxy.blackhole = True     # …and anything accepted goes mute
        proxy.close()              # live sockets severed mid-wait
        with pytest.raises(ServiceUnavailable):
            client.collect(rid, timeout=10)
        # the next submit redials into the half-dead proxy (its pinned
        # listener backlog still completes handshakes — a blackhole): the
        # deadline surfaces as the OTHER retryable shape, never raw OSError
        rid2 = client.submit('default@champion', obs, legal=legal,
                             seed=sample_seed(1, (0, 2), 0))
        with pytest.raises((ServiceUnavailable, TimeoutError)):
            client.collect(rid2, timeout=1.0)
        client.close()
        # the failure was transport-scoped: the live service still answers
        direct = ServiceClient('127.0.0.1', svc.port)
        direct.request('default@champion', obs, legal=legal,
                       seed=sample_seed(1, (0, 3), 0))
        direct.close()
    finally:
        proxy.close()
        svc.stop(drain=False)


# ---------------------------------------------------------------------------
# fleet: breaker + autoscaler units (pure, fake clocks)


def test_replica_breaker_open_halfopen_close():
    from handyrl_tpu.serving.fleet import ReplicaBreaker
    now = [100.0]
    b = ReplicaBreaker(initial=1.0, maximum=8.0, clock=lambda: now[0],
                       rng=__import__('random').Random(0))
    assert b.admits() and b.state == 'closed'
    assert b.record_failure() is True          # newly opened
    assert b.state == 'open' and not b.admits()
    now[0] += 2.5                              # past the jittered delay
    assert b.admits()                          # half-open probe due
    b.begin_probe()
    assert not b.admits()                      # ONE probe in flight
    assert b.record_failure() is False         # re-open, not newly opened
    now[0] += 20.0
    assert b.admits()
    b.begin_probe()
    b.record_success()
    assert b.state == 'closed' and b.admits()


def test_autoscaler_policy_admit_and_drain():
    from handyrl_tpu.serving.fleet import AutoscalerPolicy
    now = [0.0]
    pol = AutoscalerPolicy(slo_p99_ms=50.0, breach_window=10.0,
                           idle_window=30.0, min_replicas=1, max_replicas=3,
                           clock=lambda: now[0])

    def table(p99, inflight, n=2, shed=0):
        return [{'replica': 'r%d' % i, 'state': 'healthy', 'p99_ms': p99,
                 'inflight': inflight, 'shed': shed} for i in range(n)]

    # sustained p99 breach -> admit (only after breach_window)
    assert pol.decide(table(80.0, 4)) is None
    now[0] = 5.0
    assert pol.decide(table(80.0, 4)) is None
    now[0] = 11.0
    assert pol.decide(table(80.0, 4)) == 'admit'
    # at max_replicas no admit fires even under breach
    now[0] = 30.0
    pol.decide(table(80.0, 4, n=3))
    now[0] = 45.0
    assert pol.decide(table(80.0, 4, n=3)) is None
    # recovery resets the breach timer; sustained idleness -> drain
    now[0] = 50.0
    assert pol.decide(table(10.0, 0)) is None
    now[0] = 79.0
    assert pol.decide(table(10.0, 0)) is None
    now[0] = 81.0
    assert pol.decide(table(10.0, 0)) == 'drain'
    # at min_replicas idleness never drains
    now[0] = 120.0
    pol.decide(table(10.0, 0, n=1))
    now[0] = 160.0
    assert pol.decide(table(10.0, 0, n=1)) is None
    # a growing shed counter is a breach even under the p99 target
    now[0] = 200.0
    pol.decide(table(10.0, 1, shed=5))
    now[0] = 201.0
    pol.decide(table(10.0, 1, shed=9))
    now[0] = 212.0
    assert pol.decide(table(10.0, 1, shed=12)) == 'admit'


# ---------------------------------------------------------------------------
# fleet: resolver + routed client (in-process)


def _fleet_args(root, resolver_port=None, **flt):
    fleet = dict(flt)
    if resolver_port is not None:
        fleet['resolver'] = '127.0.0.1:%d' % resolver_port
    return _service_args(str(root), fleet=fleet)


@pytest.mark.timeout(300)
def test_resolver_registration_heartbeat_and_quarantine_roundtrip(tmp_path):
    """Replicas register + heartbeat; silence past heartbeat_timeout walks
    the replica healthy -> draining -> quarantined; a re-registration under
    the same name re-admits it to healthy."""
    from handyrl_tpu.serving.fleet import ServiceResolver
    resolver = ServiceResolver(_fleet_args(
        tmp_path, heartbeat_interval=0.1, heartbeat_timeout=0.6,
        quarantine_period=60.0)).start()
    admin = ServiceClient('127.0.0.1', resolver.port, name='ops')
    try:
        rep = admin._call_admin({'op': 'register',
                                 'endpoint': '127.0.0.1:12345', 'pid': 1})
        name = rep['replica']
        assert rep['ok'] and name == 'r0'
        # heartbeats keep it healthy
        for _ in range(3):
            beat = admin._call_admin({'op': 'heartbeat', 'replica': name,
                                      'slo': {'p99_ms': 1.0, 'inflight': 0,
                                              'shed': 0}})
            assert beat['ok'] and beat['drain'] is False
            time.sleep(0.1)
        table = admin._call_admin({'op': 'fleet'})
        assert table['fleet'] is True
        assert table['replicas'][0]['state'] == 'healthy'
        # an unknown replica heartbeat is refused (register first)
        bad = admin._call_admin({'op': 'heartbeat', 'replica': 'ghost'})
        assert 'error' in bad
        # silence: the resolver strands it within a few ticks
        deadline = time.monotonic() + 20
        state = 'healthy'
        while time.monotonic() < deadline:
            rows = admin._call_admin({'op': 'fleet'})['replicas']
            state = rows[0]['state']
            if state == 'quarantined':
                break
            time.sleep(0.1)
        assert state == 'quarantined'
        # re-registration under the same name (a respawn) re-admits it
        rep2 = admin._call_admin({'op': 'register', 'replica': name,
                                  'endpoint': '127.0.0.1:12346', 'pid': 2})
        assert rep2['ok'] and rep2['replica'] == name
        rows = admin._call_admin({'op': 'fleet'})['replicas']
        assert rows[0]['state'] == 'healthy'
        assert rows[0]['endpoint'] == '127.0.0.1:12346'
        status = admin._call_admin({'op': 'status'})
        assert status['resolver'] is True
        assert status['controller']['readmitted'] >= 1
    finally:
        admin.close()
        resolver.stop(drain=False)


@pytest.mark.timeout(300)
def test_routed_client_chaos_failover_byte_identical(tmp_path):
    """The zero-loss chaos contract: one replica dies mid-burst (severed
    sockets + refused redials); every in-flight request is transparently
    replayed on the surviving replica and every reply stays byte-identical
    to the local reference — callers never see the failure."""
    from handyrl_tpu.serving.fleet import RoutedClient, ServiceResolver
    from tests.proxy import ChaosProxy
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    legal = env.legal_actions(0)
    ModelRegistry(str(tmp_path)).publish('default', snapshot=w.snapshot(),
                                         version=1, promote=True)
    resolver = ServiceResolver(_fleet_args(
        tmp_path, heartbeat_timeout=60.0)).start()
    svc_a = InferenceService(_service_args(str(tmp_path))).start()
    svc_b = InferenceService(_service_args(str(tmp_path))).start()
    proxy = ChaosProxy(target_port=svc_a.port)     # a dies through this
    admin = ServiceClient('127.0.0.1', resolver.port, name='ops')
    admin._call_admin({'op': 'register', 'replica': 'a',
                       'endpoint': '127.0.0.1:%d' % proxy.port, 'pid': 0})
    admin._call_admin({'op': 'register', 'replica': 'b',
                       'endpoint': '127.0.0.1:%d' % svc_b.port, 'pid': 0})
    rc = RoutedClient('127.0.0.1', resolver.port, timeout=15.0,
                      refresh_interval=0.2)
    try:
        refs, reps = [], []
        for k in range(4):
            seed = sample_seed(11, (0, k), 0)
            refs.append((seed, model_act(w, obs, None, legal, seed)))
            reps.append(rc.request('default@champion', obs, legal=legal,
                                   seed=seed))
        assert proxy.accepted > 0, 'round-robin never dialed replica a'
        for (_, ref), rep in zip(refs, reps):
            assert rep['action'] == ref['action']
            assert rep['prob'] == ref['prob']
        # leave a burst in flight, then kill replica a hard
        rids = [rc.submit('default@champion', obs, legal=legal, seed=s)
                for s, _ in refs]
        proxy.accepting = False
        proxy.sever()
        failures = 0
        for rid, (_, ref) in zip(rids, refs):
            rep = rc.collect(rid)          # replays ride replica b
            if rep['action'] != ref['action'] or rep['prob'] != ref['prob']:
                failures += 1
            assert isinstance(rep['prob'], np.float32)
        assert failures == 0, '%d non-identical replies' % failures
        # and fresh requests keep flowing (breaker shields replica a)
        for s, ref in refs:
            rep = rc.request('default@champion', obs, legal=legal, seed=s)
            assert rep['action'] == ref['action']
            assert rep['prob'] == ref['prob']
    finally:
        rc.close()
        admin.close()
        proxy.close()
        svc_a.stop(drain=False)
        svc_b.stop(drain=False)
        resolver.stop(drain=False)


@pytest.mark.timeout(300)
def test_failover_replay_links_original_trace_id(tmp_path):
    """Serving-path tracing across a failover: a request stranded by a
    dead replica is replayed on the survivor under its ORIGINAL trace_id,
    with a ``router_replay`` link span — the SIGKILL reads as one causal
    chain (client_request + serve_request + engine_batch all share the
    id) instead of two broken halves."""
    import glob

    from handyrl_tpu import telemetry
    from handyrl_tpu.serving.fleet import RoutedClient, ServiceResolver
    from tests.proxy import ChaosProxy
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    legal = env.legal_actions(0)
    ModelRegistry(str(tmp_path)).publish('default', snapshot=w.snapshot(),
                                         version=1, promote=True)
    trace_d = str(tmp_path / 'traces')
    telemetry.configure_tracing(trace_d, 1.0, force=True)
    resolver = ServiceResolver(_fleet_args(
        tmp_path, heartbeat_timeout=60.0)).start()
    svc_a = InferenceService(_service_args(str(tmp_path))).start()
    svc_b = InferenceService(_service_args(str(tmp_path))).start()
    proxy = ChaosProxy(target_port=svc_a.port)     # a dies through this
    admin = ServiceClient('127.0.0.1', resolver.port, name='ops')
    admin._call_admin({'op': 'register', 'replica': 'a',
                       'endpoint': '127.0.0.1:%d' % proxy.port, 'pid': 0})
    admin._call_admin({'op': 'register', 'replica': 'b',
                       'endpoint': '127.0.0.1:%d' % svc_b.port, 'pid': 0})
    rc = RoutedClient('127.0.0.1', resolver.port, timeout=15.0,
                      refresh_interval=0.2)
    try:
        seeds = [sample_seed(11, (0, k), 0) for k in range(4)]
        for s in seeds:                       # warm both replicas/engines
            rc.request('default@champion', obs, legal=legal, seed=s)
        # a burst with caller-supplied trace context, steered onto the
        # victim (so replay is exercised for certain), then kill it
        tids = ['pr18test%d' % k for k in range(4)]
        rids = [rc.submit('default@champion', obs, legal=legal, seed=s,
                          replica='a', trace=t)
                for s, t in zip(seeds, tids)]
        proxy.accepting = False
        proxy.sever()
        for rid in rids:
            rc.collect(rid)                   # replays ride replica b

        telemetry.trace_flush()
        events = []
        for path in glob.glob(os.path.join(trace_d, 'trace-*.jsonl')):
            events.extend(json.loads(l) for l in open(path) if l.strip())
        replays = [e for e in events if e['name'] == 'router_replay']
        assert replays, 'the severed burst produced no replay link spans'
        for e in replays:
            assert e['args']['trace_id'] in tids
            assert e['args']['link'] == 'replay'
            assert e['args']['to_replica'] == 'b'
        # every replayed request still reads as ONE complete chain
        for tid in {e['args']['trace_id'] for e in replays}:
            names = set()
            for e in events:
                a = e.get('args') or {}
                if a.get('trace_id') == tid or \
                        tid in (a.get('trace_ids') or ()):
                    names.add(e['name'])
            for stage in ('client_request', 'route_dispatch',
                          'serve_request', 'queue_wait', 'engine_batch'):
                assert stage in names, 'chain %s missing %s: %s' \
                    % (tid, stage, sorted(names))
    finally:
        telemetry.trace_flush()
        telemetry.configure_tracing('', 1.0, force=True)
        os.environ.pop('HANDYRL_TPU_TRACE', None)
        os.environ.pop('HANDYRL_TPU_TRACE_RATE', None)
        rc.close()
        admin.close()
        proxy.close()
        svc_a.stop(drain=False)
        svc_b.stop(drain=False)
        resolver.stop(drain=False)


@pytest.mark.timeout(300)
def test_fleet_rolling_promote_warms_before_flip(tmp_path):
    """A rolling promote warms every routable replica (the warm admin op
    materializes + compiles the candidate) BEFORE the champion flips, and
    requests against @champion follow the flip."""
    from handyrl_tpu.serving.fleet import RoutedClient, ServiceResolver
    env, w1 = _ttt_wrapper(seed=7)
    _, w2 = _ttt_wrapper(seed=19)
    obs = env.observation(0)
    legal = env.legal_actions(0)
    reg = ModelRegistry(str(tmp_path))
    reg.publish('default', snapshot=w1.snapshot(), version=1, promote=True)
    resolver = ServiceResolver(_fleet_args(
        tmp_path, heartbeat_timeout=60.0)).start()

    def replica():
        return InferenceService(_fleet_args(
            tmp_path, resolver_port=resolver.port,
            heartbeat_interval=0.1)).start()

    svc_a, svc_b = replica(), replica()
    assert resolver.wait_routable(2, timeout=30)
    rc = RoutedClient('127.0.0.1', resolver.port, timeout=15.0,
                      refresh_interval=0.2)
    try:
        seed = sample_seed(11, (0, 2), 0)
        ref1 = model_act(w1, obs, None, legal, seed)
        rep = rc.request('default@champion', obs, legal=legal, seed=seed)
        assert rep['prob'] == ref1['prob']
        reg.publish('default', snapshot=w2.snapshot(), version=2)
        out = rc.promote('default@2', timeout=120)
        assert out.get('ok'), out
        assert sorted(out['warmed']) == ['r0', 'r1']
        assert ModelRegistry(str(tmp_path)).resolve('default',
                                                    'champion')[0] == '2'
        ref2 = model_act(w2, obs, None, legal, seed)
        for _ in range(4):   # both replicas now serve v2 as champion
            rep = rc.request('default@champion', obs, legal=legal,
                             seed=seed)
            assert rep['prob'] == ref2['prob']
    finally:
        rc.close()
        svc_a.stop(drain=False)
        svc_b.stop(drain=False)
        resolver.stop(drain=False)


@pytest.mark.timeout(300)
def test_routed_client_pin_replays_byte_identical_across_promote(tmp_path):
    """Version pinning across a champion flip: requests dispatched against
    the floating ``@champion`` selector pin to the concrete version they
    resolved to at submit time, so a burst stranded by a replica death
    AFTER the champion flips still replays against the OLD version on the
    survivor — byte-identical to the old champion's replies, never the new
    one's — while fresh floating requests follow the flip (and a rolling
    ``promote`` clears the pin cache)."""
    from handyrl_tpu.serving.fleet import RoutedClient, ServiceResolver
    from tests.proxy import ChaosProxy
    env, w1 = _ttt_wrapper(seed=7)
    _, w2 = _ttt_wrapper(seed=19)
    obs = env.observation(0)
    legal = env.legal_actions(0)
    reg = ModelRegistry(str(tmp_path))
    reg.publish('default', snapshot=w1.snapshot(), version=1, promote=True)
    resolver = ServiceResolver(_fleet_args(
        tmp_path, heartbeat_timeout=60.0)).start()
    svc_a = InferenceService(_service_args(str(tmp_path))).start()
    svc_b = InferenceService(_service_args(str(tmp_path))).start()
    proxy = ChaosProxy(target_port=svc_a.port)     # a dies through this
    admin = ServiceClient('127.0.0.1', resolver.port, name='ops')
    admin._call_admin({'op': 'register', 'replica': 'a',
                       'endpoint': '127.0.0.1:%d' % proxy.port, 'pid': 0})
    admin._call_admin({'op': 'register', 'replica': 'b',
                       'endpoint': '127.0.0.1:%d' % svc_b.port, 'pid': 0})
    rc = RoutedClient('127.0.0.1', resolver.port, timeout=15.0,
                      refresh_interval=0.2)
    try:
        seeds = [sample_seed(11, (0, k), 0) for k in range(4)]
        refs1 = [model_act(w1, obs, None, legal, s) for s in seeds]
        refs2 = [model_act(w2, obs, None, legal, s) for s in seeds]
        # the two champions must be distinguishable or the test is void
        assert any(r1['prob'] != r2['prob']
                   for r1, r2 in zip(refs1, refs2))
        rep = rc.request('default@champion', obs, legal=legal,
                         seed=seeds[0])
        assert rep['prob'] == refs1[0]['prob']       # pinned default@1
        # strand a whole burst on replica a: the stall swallows replies
        # (requests ARRIVE, answers never come), so every rid must replay
        proxy.stall = True
        rids = [rc.submit('default@champion', obs, legal=legal, seed=s,
                          replica='a') for s in seeds]
        accepted = proxy.accepted
        assert accepted > 0, 'burst never dialed replica a'
        # the champion flips UNDER the stranded burst
        reg.publish('default', snapshot=w2.snapshot(), version=2,
                    promote=True)
        assert reg.resolve('default', 'champion')[0] == '2'
        time.sleep(0.3)          # outlive the pin cache TTL: the replay
        proxy.accepting = False  # must use the per-request pin, not a
        proxy.sever()            # conveniently-cached resolution
        for rid, ref in zip(rids, refs1):
            rep = rc.collect(rid)           # replays ride replica b
            assert rep['action'] == ref['action']
            assert rep['prob'] == ref['prob'], \
                'stranded request followed the champion flip'
        # fresh floating requests re-pin to the NEW champion
        for s, ref in zip(seeds, refs2):
            rep = rc.request('default@champion', obs, legal=legal, seed=s)
            assert rep['prob'] == ref['prob']
        # and the real rolling promote still walks a restored fleet
        # (warms both replicas, clears the pin cache)
        proxy.stall = False
        proxy.accepting = True
        out = rc.promote('default@2', timeout=120)
        assert out.get('ok'), out
        assert sorted(out['warmed']) == ['a', 'b']
        rep = rc.request('default@champion', obs, legal=legal,
                         seed=seeds[0])
        assert rep['prob'] == refs2[0]['prob']
    finally:
        rc.close()
        admin.close()
        proxy.close()
        svc_a.stop(drain=False)
        svc_b.stop(drain=False)
        resolver.stop(drain=False)


@pytest.mark.timeout(300)
def test_engine_client_rotates_across_replica_endpoints(tmp_path):
    """The worker EngineClient with a comma-separated endpoint list stays
    on the ENGINE path when one replica dies: the dead endpoint down-marks
    and the next dial rotates to the survivor (no local degradation)."""
    from handyrl_tpu.inference import RemoteModel
    env, w = _ttt_wrapper(seed=7)
    obs = env.observation(0)
    legal = env.legal_actions(0)
    ModelRegistry(str(tmp_path)).publish('default', snapshot=w.snapshot(),
                                         version=5, promote=True)
    svc_a = InferenceService(_service_args(str(tmp_path))).start()
    svc_b = InferenceService(_service_args(str(tmp_path))).start()
    try:
        # retries=1: a timed-out endpoint down-marks and the resend
        # rotates — an in-process stop() leaves sockets half-open (a
        # blackhole), unlike a real crash's RST
        remote = RemoteModel(_remote_client(
            'localhost:%d,localhost:%d' % (svc_a.port, svc_b.port),
            w.snapshot(), request_timeout=3.0, request_retries=1), 5)
        seed = sample_seed(11, (0, 4), 0)
        ref = model_act(w, obs, None, legal, seed)
        for _ in range(3):
            rep = remote.act(obs, None, legal, seed)
            assert rep['prob'] == ref['prob']
        assert remote.client.engine_ok
        svc_a.stop(drain=False)     # first replica gone
        for _ in range(6):
            rep = remote.act(obs, None, legal, seed)
            assert rep['prob'] == ref['prob']
        # the survivor kept the circuit closed: no local failover happened
        assert remote.client.engine_ok, \
            'client degraded locally despite a live replica'
    finally:
        svc_a.stop(drain=False)
        svc_b.stop(drain=False)


# ---------------------------------------------------------------------------
# fleet: SIGKILL zero-loss e2e (subprocess resolver + managed replicas)


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_fleet_sigkill_zero_loss_and_respawn(tmp_path):
    """The acceptance chaos run: a 2-replica managed fleet under client
    load; one replica is SIGKILLed mid-burst. Zero client-visible
    failures, byte-identical replayed replies, the resolver logs the
    healthy -> quarantined -> healthy round trip (respawn re-registers
    under the old name), and SIGTERM drains the fleet to exit 75."""
    from handyrl_tpu.serving.fleet import RoutedClient
    env, w = _ttt_wrapper(seed=7)
    obs = env.observation(0)
    legal = env.legal_actions(0)
    ModelRegistry(str(tmp_path)).publish('default', snapshot=w.snapshot(),
                                         version=1, promote=True)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'handyrl_tpu.serving', '--fleet',
         '--replicas', '2', '--env', 'TicTacToe', '--registry',
         str(tmp_path), '--port', '0', '--line', 'default',
         '--heartbeat', '0.2', '--heartbeat-timeout', '2.0'],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=dict(os.environ, JAX_PLATFORMS='cpu'))
    rc = None
    try:
        ready = json.loads(proc.stdout.readline())['fleet_ready']
        assert ready['replicas'] == 2
        rc = RoutedClient('127.0.0.1', int(ready['port']), timeout=20.0,
                          refresh_interval=0.2)
        table = {r['replica']: r for r in rc.replicas()}
        assert len(table) == 2
        seeds = [sample_seed(11, (0, k), 0) for k in range(6)]
        refs = [model_act(w, obs, None, legal, s) for s in seeds]
        for s, ref in zip(seeds, refs):
            rep = rc.request('default@champion', obs, legal=legal, seed=s)
            assert rep['prob'] == ref['prob']
        # SIGKILL one replica with a burst in flight
        rids = [rc.submit('default@champion', obs, legal=legal, seed=s)
                for s in seeds]
        victim = sorted(table)[0]
        os.kill(table[victim]['pid'], signal.SIGKILL)
        failures = 0
        for rid, ref in zip(rids, refs):
            rep = rc.collect(rid)
            if rep['action'] != ref['action'] or rep['prob'] != ref['prob']:
                failures += 1
        assert failures == 0, '%d client-visible failures' % failures
        # the resolver strands the corpse (healthy -> draining ->
        # quarantined), respawns it under its old name, and the
        # re-registration re-admits it to healthy: the 'readmitted'
        # controller counter only moves on that non-healthy -> healthy
        # round trip, so it can't be missed between table polls
        round_trip = False
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            states = {r['replica']: r['state'] for r in rc.replicas()}
            readmitted = rc.status()['controller'].get('readmitted', 0)
            if readmitted >= 1 and states.get(victim) == 'healthy':
                round_trip = True
                break
            time.sleep(0.25)
        assert round_trip, \
            'kill never walked the quarantine round trip: %s' % states
        # the respawned replica serves byte-identical replies again
        for s, ref in zip(seeds, refs):
            rep = rc.request('default@champion', obs, legal=legal, seed=s)
            assert rep['prob'] == ref['prob']
        # fleet-wide graceful drain: exit 75 (EX_TEMPFAIL, restart me)
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 75
    finally:
        if rc is not None:
            rc.close()
        if proc.poll() is None:
            proc.kill()
