"""Self-healing inference tier: engine supervision (crash/stall restart,
error fan-out, bounded queue shedding), worker-side request deadlines with
circuit-breaker failover to the per-worker path (byte-identical records),
the learner's elastic fleet controller, and the chaos end-to-end proving a
real TCP fleet survives injected engine kills and stalls.

The coalescing/parity behavior of a HEALTHY engine is pinned in
tests/test_inference_engine.py; this module is about what happens when the
engine is anything but.
"""

import json
import os
import pickle
import queue
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from collections import deque

import numpy as np
import pytest

from handyrl_tpu import telemetry
from handyrl_tpu.connection import (FramedConnection, INFER_KIND,
                                    connect_socket_connection, is_infer)
from handyrl_tpu.environment import make_env
from handyrl_tpu.fault import (FleetController, TaskLedger, parse_chaos,
                               HOST_DEGRADED, HOST_DRAINING, HOST_HEALTHY,
                               HOST_QUARANTINED)
from handyrl_tpu.generation import Generator, model_act, sample_seed
from handyrl_tpu.inference import (EngineClient, EngineSupervisor,
                                   InferenceEngine, RemoteModel,
                                   RemoteModelCache)
from handyrl_tpu.model import ModelWrapper

GEN_ARGS = {'observation': False, 'gamma': 0.8, 'compress_steps': 4,
            'seed': 11}


def _ttt_wrapper(seed=7):
    env = make_env({'env': 'TicTacToe'})
    env.reset()
    w = ModelWrapper(env.net(), seed=seed)
    w.ensure_params(env.observation(0))
    return env, w


def _counter_value(name, **labels):
    return telemetry.REGISTRY.counter(name, **labels).value


def _wait_for(predicate, timeout, poll=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


# ---------------------------------------------------------------------------
# ChaosProxy stall mode (satellite): accept frames, never reply


def test_chaos_proxy_stall_mode_is_one_way():
    from tests.proxy import ChaosProxy
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(('127.0.0.1', 0))
    lsock.listen(4)
    received, replies_sent = [], []

    def echo_server():
        while True:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            try:
                data = conn.recv(1 << 12)
                received.append(data)
                conn.sendall(b'reply:' + data)
                replies_sent.append(data)
            except OSError:
                pass

    threading.Thread(target=echo_server, daemon=True).start()
    proxy = ChaosProxy(target_port=lsock.getsockname()[1])
    try:
        proxy.stall = True
        client = socket.create_connection(('127.0.0.1', proxy.port),
                                          timeout=5)
        client.sendall(b'ping')
        # the REQUEST gets through (unlike blackhole) ...
        assert _wait_for(lambda: received == [b'ping'], 5)
        assert _wait_for(lambda: replies_sent == [b'ping'], 5)
        # ... but the reply never comes back
        client.settimeout(0.5)
        with pytest.raises(socket.timeout):
            client.recv(1 << 12)
        client.close()

        proxy.stall = False            # healthy again: full round trip
        client2 = socket.create_connection(('127.0.0.1', proxy.port),
                                           timeout=5)
        client2.sendall(b'pong')
        client2.settimeout(5)
        assert client2.recv(1 << 12) == b'reply:pong'
        client2.close()
    finally:
        proxy.close()
        lsock.close()


def test_parse_chaos_engine_knobs():
    spec = 'enginekill=4,enginestall=6,enginestall_secs=600,engine_max_faults=2'
    assert parse_chaos(spec) == {'enginekill': 4.0, 'enginestall': 6.0,
                                 'enginestall_secs': 600.0,
                                 'engine_max_faults': 2.0}


# ---------------------------------------------------------------------------
# engine hardening: bounded queue, crash fan-out, stall watchdog, stop leak


class _Endpoint:
    """Bare reply sink used when driving engines/supervisors directly."""

    def __init__(self):
        self.replies: queue.Queue = queue.Queue()


def _act_request(rid, obs, mid=1):
    return {'rid': rid, 'mid': mid, 'obs': obs, 'hidden': None,
            'legal': [0, 1, 2], 'seed': sample_seed(11, (0, rid), 0)}


def test_engine_bounded_queue_sheds_with_error_reply():
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    args = {'inference': {'enabled': True, 'queue_max': 2},
            'env': {'env': 'TicTacToe'}}
    engine = InferenceEngine(args, fetch_snapshot=lambda mid: w.snapshot(),
                             reply_fn=lambda ep, msg: ep.replies.put(msg),
                             clients=1, example_obs=obs)
    # NOT started: the queue cannot drain, so the bound is deterministic
    shed_before = _counter_value('engine_shed_total')
    ep = _Endpoint()
    for rid in range(3):
        engine.submit(ep, _act_request(rid, obs))
    assert len(engine._queue) == 2            # bound held
    reply = ep.replies.get(timeout=5)          # the third was shed, loudly
    assert reply['rid'] == 2 and reply.get('engine_fault')
    assert 'shed' in reply['error']
    assert _counter_value('engine_shed_total') == shed_before + 1


def _supervisor_for(w, obs, chaos, stall_timeout=0.5, queue_max=64):
    args = {'inference': {'enabled': True, 'batch_wait_ms': 1.0,
                          'stall_timeout': stall_timeout,
                          'restart_max_delay': 1.0, 'queue_max': queue_max},
            'env': {'env': 'TicTacToe'}}
    return EngineSupervisor(
        args, fetch_snapshot=lambda mid: w.snapshot(),
        reply_fn=lambda ep, msg: ep.replies.put(msg),
        clients=1, example_obs=obs, chaos=chaos)


@pytest.mark.timeout(120)
def test_supervisor_restarts_crashed_engine_with_error_fanout():
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    crashes_before = _counter_value('engine_restarts_total', reason='crash')
    sup = _supervisor_for(w, obs,
                          chaos={'enginekill': 1e-4, 'engine_max_faults': 1})
    try:
        ep = _Endpoint()
        sup.submit(ep, _act_request(1, obs))
        # the injected kill fires on the first tick: the in-flight request
        # is error-answered by the crash fan-out, not silently dropped
        reply = ep.replies.get(timeout=10)
        assert reply['rid'] == 1 and 'crashed' in reply['error']
        # wait for the DECLARED restart, not just a live engine thread —
        # the crashed engine's thread lingers in its crash handler for a
        # beat, so thread_alive() alone passes before the watchdog's first
        # tick and reads restarts too early
        assert _wait_for(
            lambda: (sup.restarts >= 1 and sup.engine is not None
                     and sup.engine.thread_alive()), 15)
        assert sup.restarts == 1
        assert (_counter_value('engine_restarts_total', reason='crash')
                == crashes_before + 1)
        sup.submit(ep, _act_request(2, obs))   # restarted engine serves
        reply = ep.replies.get(timeout=10)
        assert reply['rid'] == 2 and reply['action'] in (0, 1, 2)
    finally:
        sup.stop()


@pytest.mark.timeout(120)
def test_supervisor_detects_stall_and_restarts():
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    stalls_before = _counter_value('engine_restarts_total', reason='stall')
    sup = _supervisor_for(w, obs,
                          chaos={'enginestall': 1e-4, 'engine_max_faults': 1,
                                 'enginestall_secs': 120})
    try:
        ep = _Endpoint()
        sup.submit(ep, _act_request(1, obs))
        # the engine wedges holding the request; the watchdog declares the
        # stall, error-answers what the zombie holds, and restarts
        reply = ep.replies.get(timeout=15)
        assert reply['rid'] == 1 and 'stall' in reply['error']
        assert _wait_for(
            lambda: sup.engine is not None and sup.engine.thread_alive(), 15)
        assert (_counter_value('engine_restarts_total', reason='stall')
                == stalls_before + 1)
        sup.submit(ep, _act_request(2, obs))
        reply = ep.replies.get(timeout=10)
        assert reply['rid'] == 2 and reply['action'] in (0, 1, 2)
    finally:
        sup.stop()


@pytest.mark.timeout(120)
def test_stalled_snapshot_fetch_detected_via_chaos_proxy():
    """Deterministic stall via the ChaosProxy stall mode: the engine's
    snapshot fetch crosses a stalled TCP link (request accepted, reply
    never comes) — the engine wedges inside _serve, the watchdog restarts
    it, and once the link heals the restarted engine serves."""
    from tests.proxy import ChaosProxy
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    snap = w.snapshot()

    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(('127.0.0.1', 0))
    lsock.listen(8)

    def snapshot_server():
        while True:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return

            def serve_one(fc):
                try:
                    while True:            # hold the connection open: a
                        fc.recv()          # stalled service looks alive
                        fc.send(snap)
                except Exception:
                    pass
            threading.Thread(target=serve_one,
                             args=(FramedConnection(conn),),
                             daemon=True).start()

    threading.Thread(target=snapshot_server, daemon=True).start()
    proxy = ChaosProxy(target_port=lsock.getsockname()[1])
    proxy.stall = True

    def fetch(mid):
        conn = connect_socket_connection('127.0.0.1', proxy.port)
        try:
            conn.send(int(mid))
            return conn.recv()
        finally:
            conn.close()

    args = {'inference': {'enabled': True, 'batch_wait_ms': 1.0,
                          'stall_timeout': 0.5, 'restart_max_delay': 1.0},
            'env': {'env': 'TicTacToe'}}
    sup = EngineSupervisor(args, fetch_snapshot=fetch,
                           reply_fn=lambda ep, msg: ep.replies.put(msg),
                           clients=1, example_obs=obs, chaos={})
    try:
        ep = _Endpoint()
        sup.submit(ep, _act_request(1, obs))
        reply = ep.replies.get(timeout=20)     # stall detected + fanned out
        assert reply['rid'] == 1 and 'stall' in reply['error']
        assert _wait_for(lambda: sup.restarts >= 1, 15)
        proxy.stall = False                    # link heals
        assert _wait_for(
            lambda: sup.engine is not None and sup.engine.thread_alive(), 15)
        sup.submit(ep, _act_request(2, obs))
        reply = ep.replies.get(timeout=20)
        assert reply['rid'] == 2 and reply['action'] in (0, 1, 2)
    finally:
        sup.stop()
        proxy.close()
        lsock.close()


@pytest.mark.timeout(60)
def test_engine_stop_leak_is_visible():
    """stop() on a wedged engine cannot join the thread — that must be a
    logged warning plus an engine_stop_leaked_total increment, not a silent
    return (satellite)."""
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    args = {'inference': {'enabled': True, 'batch_wait_ms': 1.0},
            'env': {'env': 'TicTacToe'}}
    engine = InferenceEngine(args, fetch_snapshot=lambda mid: w.snapshot(),
                             reply_fn=lambda ep, msg: ep.replies.put(msg),
                             clients=1, example_obs=obs)
    engine.arm_fault('stall', 0.0, stall_secs=60)
    engine.start()
    ep = _Endpoint()
    engine.submit(ep, _act_request(1, obs))
    assert _wait_for(lambda: engine.busy() and engine.progress_age() > 0.3,
                     10)
    leaked_before = _counter_value('engine_stop_leaked_total')
    engine.stop(timeout=0.3)
    assert engine.thread_alive()               # really is wedged
    assert _counter_value('engine_stop_leaked_total') == leaked_before + 1


# ---------------------------------------------------------------------------
# worker-side client: deadline -> degrade -> probe -> re-promote, byte-exact


class _FakeGatherPipe:
    """Worker-side view of a gather pipe: INFER frames route into a real
    engine when healthy (or vanish when ``drop_infer`` — a dead/stalled
    engine whose replies never come), and the 'model' RPC serves snapshots
    like the real relay does — which is exactly what the degraded local
    path fetches through."""

    def __init__(self, engine, snapshots):
        self.engine = engine
        self.snapshots = snapshots
        self.drop_infer = False
        self.drop_after = None          # drop infer frames after N submits
        self.drop_until = None          # ... up to frame N (None = forever)
        self.infer_sent = 0
        self.model_fetches = 0
        self.replies: queue.Queue = queue.Queue()
        self._peeked: deque = deque()
        self._rpc_replies: deque = deque()

    def send(self, msg):
        if is_infer(msg):
            self.infer_sent += 1
            dropped = self.drop_infer or (
                self.drop_after is not None
                and self.infer_sent > self.drop_after
                and (self.drop_until is None
                     or self.infer_sent <= self.drop_until))
            if not dropped and self.engine is not None:
                self.engine.submit(self, pickle.loads(pickle.dumps(msg[1])))
            return
        kind, body = msg
        assert kind == 'model', 'unexpected worker RPC %r' % (kind,)
        self.model_fetches += 1
        self._rpc_replies.append(pickle.loads(pickle.dumps(
            self.snapshots[body])))

    def poll(self, timeout=0.0):
        if self._peeked:
            return True
        try:
            self._peeked.append(self.replies.get(timeout=max(timeout, 1e-4)))
        except queue.Empty:
            return False
        return True

    def recv(self):
        if self._peeked:
            return (INFER_KIND,
                    pickle.loads(pickle.dumps(self._peeked.popleft())))
        if not self.replies.empty():
            return (INFER_KIND,
                    pickle.loads(pickle.dumps(self.replies.get())))
        if self._rpc_replies:
            return self._rpc_replies.popleft()
        return (INFER_KIND, pickle.loads(pickle.dumps(
            self.replies.get(timeout=30))))


def _engine_and_pipe(snap, obs, **inf):
    args = {'inference': {'enabled': True, 'batch_wait_ms': 1.0, **inf},
            'env': {'env': 'TicTacToe'}}
    engine = InferenceEngine(
        args, fetch_snapshot=lambda mid: snap,
        reply_fn=lambda ep, msg: ep.replies.put(msg),
        clients=1, example_obs=obs).start()
    pipe = _FakeGatherPipe(engine, {1: snap})
    client = EngineClient(pipe, args)
    return engine, pipe, client


@pytest.mark.timeout(120)
def test_client_deadline_failover_is_bitwise_identical():
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    snap = w.snapshot()
    failovers_before = _counter_value('worker_engine_failovers_total')
    engine, pipe, client = _engine_and_pipe(
        snap, obs, request_timeout=0.2, request_retries=1,
        reprobe_initial_delay=30.0)
    try:
        remote = RemoteModel(client, 1)
        legal = env.legal_actions(0)
        seed_seq = sample_seed(11, (0, 3), 0)
        res_engine = remote.act(obs, None, legal, seed_seq)   # healthy
        assert client.engine_ok

        pipe.drop_infer = True        # engine "dies": replies never arrive
        t0 = time.monotonic()
        res_degraded = remote.act(obs, None, legal, seed_seq)
        waited = time.monotonic() - t0
        assert waited >= 0.4          # deadline + one bounded retry
        assert not client.engine_ok   # circuit opened
        assert pipe.model_fetches >= 1   # snapshot came over the model RPC
        assert (_counter_value('worker_engine_failovers_total')
                == failovers_before + 1)
        # lossless: the degraded reply is bit-identical to the engine's AND
        # to the plain per-worker path on the same inputs
        local = model_act(ModelWrapper.from_snapshot(snap, obs), obs,
                          None, legal, seed_seq)
        for res in (res_engine, res_degraded):
            assert res['action'] == local['action']
            assert res['prob'] == local['prob']
            np.testing.assert_array_equal(res['action_mask'],
                                          local['action_mask'])
            np.testing.assert_array_equal(res['value'], local['value'])
        # while degraded, requests are served locally, instantly
        t0 = time.monotonic()
        remote.act(obs, None, legal, sample_seed(11, (0, 3), 1))
        assert time.monotonic() - t0 < 0.2
    finally:
        engine.stop()


@pytest.mark.timeout(120)
def test_client_reprobes_and_repromotes():
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    snap = w.snapshot()
    repromotes_before = _counter_value('worker_engine_repromotions_total')
    engine, pipe, client = _engine_and_pipe(
        snap, obs, request_timeout=0.2, request_retries=0,
        reprobe_initial_delay=0.2, reprobe_max_delay=0.5)
    try:
        remote = RemoteModel(client, 1)
        legal = env.legal_actions(0)
        pipe.drop_infer = True
        remote.act(obs, None, legal, sample_seed(11, (0, 1), 0))
        assert not client.engine_ok
        # still down at probe time: the probe fails and backs off again
        time.sleep(0.3)
        remote.act(obs, None, legal, sample_seed(11, (0, 1), 1))
        assert not client.engine_ok
        pipe.drop_infer = False       # engine healed
        assert _wait_for(
            lambda: (remote.act(obs, None, legal,
                                sample_seed(11, (0, 1), 2)) or True)
            and client.engine_ok, 10, poll=0.2)
        assert (_counter_value('worker_engine_repromotions_total')
                == repromotes_before + 1)
    finally:
        engine.stop()


@pytest.mark.timeout(300)
def test_engine_killed_mid_episode_record_byte_identical():
    """Satellite: kill the engine mid-episode on a fixed seed — the worker
    degrades to the per-worker path, FINISHES the episode, and the record
    is byte-identical to an uninterrupted engine run (and to the plain
    local path)."""
    from handyrl_tpu.connection import pack
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    snap = w.snapshot()
    task = {'role': 'g', 'player': [0, 1], 'model_id': {0: 1, 1: 1},
            'sample_key': 5}

    def reference_episode(sample_key):
        e = make_env({'env': 'TicTacToe'})
        g = Generator(e, GEN_ARGS, namespace=0)
        m = ModelWrapper.from_snapshot(snap, obs)
        return g.generate({0: m, 1: m}, dict(task, sample_key=sample_key))

    def engine_episode(sample_key, drop_after=None, drop_until=None,
                       reprobe=30.0):
        engine, pipe, client = _engine_and_pipe(
            snap, obs, request_timeout=0.2, request_retries=0,
            reprobe_initial_delay=reprobe, reprobe_max_delay=reprobe)
        try:
            pipe.drop_after = drop_after
            pipe.drop_until = drop_until
            e = make_env({'env': 'TicTacToe'})
            g = Generator(e, GEN_ARGS, namespace=9)
            models = RemoteModelCache(client).obtain({0: 1, 1: 1})
            episode = g.generate(models, dict(task, sample_key=sample_key))
            return episode, client
        finally:
            engine.stop()

    ref = reference_episode(5)
    uninterrupted, _ = engine_episode(5)
    assert pack(ref) == pack(uninterrupted)

    # kill after the 3rd inference request: mid-episode degradation
    degraded, client = engine_episode(5, drop_after=3)
    assert not client.engine_ok, 'the mid-episode failover never happened'
    assert pack(ref) == pack(degraded)

    # and a degrade -> re-promote cycle WITHIN one episode is lossless too:
    # exactly frame 4 is lost, the probe (due immediately) heals on the
    # next ply, and the rest of the episode runs back on the engine
    cycled, client = engine_episode(5, drop_after=3, drop_until=4,
                                    reprobe=1e-6)
    assert client.engine_ok, 'the mid-episode re-promotion never happened'
    assert pack(ref) == pack(cycled)


# ---------------------------------------------------------------------------
# ledger stranding attribution + fleet controller


class _Clock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def test_ledger_stranding_events_attribute_endpoints():
    clock = _Clock()
    ledger = TaskLedger(deadline=10.0, clock=clock)
    ledger.assign('ep-a', {'role': 'g', 'model_id': {}})
    ledger.assign('ep-a', {'role': 'g', 'model_id': {}})
    ledger.assign('ep-b', {'role': 'e', 'model_id': {}})
    assert ledger.outstanding_by_endpoint() == {'ep-a': 2, 'ep-b': 1}
    ledger.fail_endpoint('ep-a')
    clock.now += 11.0
    ledger.reap()
    events = ledger.drain_stranding_events()
    assert [(ep, reason) for ep, reason, _t in events] == [
        ('ep-a', 'detach'), ('ep-a', 'detach'), ('ep-b', 'deadline')]
    assert ledger.drain_stranding_events() == []   # journal is consumed
    assert ledger.outstanding_by_endpoint() == {}


def test_fleet_controller_degrade_and_recover():
    clock = _Clock()
    fleet = FleetController(degrade_after=2, quarantine_after=5,
                            health_window=60.0, quarantine_period=30.0,
                            clock=clock)
    fleet.observe('host-a')
    assert fleet.state('host-a') == HOST_HEALTHY and fleet.admits('host-a')
    fleet.record_soft_fault('host-a')
    assert fleet.state('host-a') == HOST_HEALTHY    # below degrade_after
    fleet.record_soft_fault('host-a')
    assert fleet.state('host-a') == HOST_DEGRADED
    assert fleet.admits('host-a')                   # degraded still works
    clock.now += 61.0                               # quiet window passes
    fleet.tick({})
    assert fleet.state('host-a') == HOST_HEALTHY
    trans = [(h, a, b) for h, a, b, _t in fleet.drain_transitions()]
    assert trans == [('host-a', HOST_HEALTHY, HOST_DEGRADED),
                     ('host-a', HOST_DEGRADED, HOST_HEALTHY)]


def test_fleet_controller_drain_quarantine_readmit_cycle():
    clock = _Clock()
    fleet = FleetController(degrade_after=1, quarantine_after=3,
                            health_window=60.0, quarantine_period=30.0,
                            clock=clock)
    for _ in range(3):                 # flapping: repeated strandings
        fleet.record_stranding('host-a')
    assert fleet.state('host-a') == HOST_DRAINING
    assert not fleet.admits('host-a')  # no fresh tasks while draining
    fleet.tick({'host-a': 2})          # booked work still outstanding
    assert fleet.state('host-a') == HOST_DRAINING
    fleet.tick({'host-a': 0})          # drained -> quarantine clock starts
    assert fleet.state('host-a') == HOST_QUARANTINED
    assert not fleet.admits('host-a')
    clock.now += 29.0
    fleet.tick({})
    assert fleet.state('host-a') == HOST_QUARANTINED   # not yet
    clock.now += 2.0
    fleet.tick({})
    assert fleet.state('host-a') == HOST_HEALTHY       # re-admitted
    assert fleet.admits('host-a')
    assert fleet.stats['quarantined'] == 1
    assert fleet.stats['readmitted'] == 1
    # history cleared on re-admission: one more stranding only degrades
    fleet.record_stranding('host-a')
    assert fleet.state('host-a') == HOST_DEGRADED
    counts = fleet.counts()
    assert counts['degraded'] == 1 and counts['healthy'] == 0


def test_fleet_controller_state_codes_cover_all_states():
    assert set(telemetry.HOST_STATE_CODES) == {
        HOST_HEALTHY, HOST_DEGRADED, HOST_DRAINING, HOST_QUARANTINED}
    # severity-monotone: alerting on >= 2 means "not receiving work"
    assert (telemetry.HOST_STATE_CODES[HOST_HEALTHY]
            < telemetry.HOST_STATE_CODES[HOST_DEGRADED]
            < telemetry.HOST_STATE_CODES[HOST_DRAINING]
            < telemetry.HOST_STATE_CODES[HOST_QUARANTINED])


def test_worker_idle_task_naps_and_reasks():
    from handyrl_tpu.worker import Worker
    from handyrl_tpu.config import apply_defaults
    args = apply_defaults({'env_args': {'env': 'TicTacToe'}})['train_args']
    args['env'] = {'env': 'TicTacToe'}

    class _ScriptedConn:
        """Replies: one idle placeholder, then the shutdown None."""

        def __init__(self):
            self.sent = []
            self._replies = deque([{'role': 'idle', 'wait': 0.01}, None])

        def send(self, msg):
            self.sent.append(msg)

        def recv(self):
            return self._replies.popleft()

    conn = _ScriptedConn()
    idle_before = _counter_value('worker_idle_tasks_total')
    Worker(args, conn, wid=0).run()
    args_requests = [m for m in conn.sent if m[0] == 'args']
    assert len(args_requests) == 2     # re-asked after the idle nap
    assert _counter_value('worker_idle_tasks_total') == idle_before + 1


# ---------------------------------------------------------------------------
# chaos end-to-end: engine kills + stalls in a real TCP fleet


LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax, json
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 2,
                          'forward_steps': 8, 'num_batchers': 1,
                          'model_dir': %(model_dir)r,
                          'metrics_jsonl': %(metrics)r,
                          'telemetry_port': %(tport)d,
                          'inference': {
                              'enabled': True,
                              'request_timeout': 3.0,
                              'request_retries': 0,
                              'stall_timeout': 4.0,
                              'restart_max_delay': 2.0,
                              'reprobe_initial_delay': 2.0,
                              'reprobe_max_delay': 4.0},
                          'fault_tolerance': {
                              'heartbeat_interval': 1.0,
                              'liveness_timeout': 8.0,
                              'rpc_timeout': 30.0,
                              'task_deadline': 30.0,
                              'reconnect_initial_delay': 0.25,
                              'reconnect_max_delay': 2.0,
                              'reconnect_max_tries': 60,
                              'host_health_window': 30.0,
                              'host_quarantine_period': 5.0}}}
    args = apply_defaults(raw)
    learner = Learner(args=args, remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, learner.num_episodes,
          learner.num_returned_episodes, flush=True)
    print('LEDGER', json.dumps(learner.ledger.stats), flush=True)
    print('FLEETSTATES', json.dumps(learner.fleet.snapshot()), flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_engine_chaos_cluster_self_heals(tmp_path):
    """The acceptance e2e: a real learner + worker host over TCP with
    ``enginekill`` AND ``enginestall`` injected into the host inference
    engines must complete its full 2-epoch budget with zero permanently
    hung workers, at least one observed degrade -> re-promote cycle,
    converged episode accounting, and fleet_host_state visible in both
    metrics_jsonl and the Prometheus exposition during the run."""
    entry_port, data_port, tport = 21920, 21921, 21922
    model_dir = str(tmp_path / 'models')
    metrics = str(tmp_path / 'metrics.jsonl')
    learner_py = tmp_path / 'learner.py'
    worker_py = tmp_path / 'worker.py'
    learner_py.write_text(LEARNER_SCRIPT % {
        'model_dir': model_dir, 'metrics': metrics, 'tport': tport})
    worker_py.write_text(WORKER_SCRIPT)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
                'HANDYRL_TPU_ENTRY_PORT': str(entry_port),
                'HANDYRL_TPU_DATA_PORT': str(data_port),
                'PYTHONPATH': repo + os.pathsep
                + os.environ.get('PYTHONPATH', '')}
    worker_env = {**base_env,
                  'HANDYRL_TPU_CHAOS': ('enginekill=5,enginestall=7,'
                                        'enginestall_secs=600,'
                                        'engine_max_faults=4,seed=5')}

    learner_log = open(tmp_path / 'learner.log', 'w')
    worker_log = open(tmp_path / 'worker.log', 'w')
    learner = subprocess.Popen([sys.executable, str(learner_py)],
                               env=base_env, stdout=learner_log,
                               stderr=subprocess.STDOUT)
    worker = None
    scraped_states = False
    try:
        time.sleep(3)    # let the entry/data servers bind
        worker = subprocess.Popen([sys.executable, str(worker_py)],
                                  env=worker_env, stdout=worker_log,
                                  stderr=subprocess.STDOUT)

        def done():
            return (os.path.exists(os.path.join(model_dir, '2.ckpt'))
                    or learner.poll() is not None)

        deadline = time.time() + 420
        while not done() and time.time() < deadline:
            # scrape the live exporter mid-run: host states must be
            # visible in the Prometheus exposition DURING the chaos
            try:
                with urllib.request.urlopen(
                        'http://127.0.0.1:%d/metrics' % tport,
                        timeout=2) as resp:
                    text = resp.read().decode()
                if 'fleet_host_state{' in text:
                    scraped_states = True
            except OSError:
                pass
            time.sleep(2)

        assert os.path.exists(os.path.join(model_dir, '2.ckpt')), \
            'run did not reach its epoch budget under engine chaos'
        # zero permanently hung workers: the whole tree winds down on its
        # own once training ends (a wedged worker would hang these waits)
        learner.wait(timeout=120)
        worker.wait(timeout=120)
    finally:
        for proc in (worker, learner):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
        learner_log.close()
        worker_log.close()

    learner_out = (tmp_path / 'learner.log').read_text()
    worker_out = (tmp_path / 'worker.log').read_text()

    # chaos actually fired, and the self-healing machinery engaged
    assert 'chaos: armed engine' in worker_out
    # at least one degrade -> re-promote cycle was observed worker-side
    assert 'degrading to per-worker inference' in worker_out
    assert 're-promoted to engine inference' in worker_out

    # accounting converged (no double-counted re-issues, budget met)
    done_line = [l for l in learner_out.splitlines()
                 if l.startswith('LEARNER DONE')][0]
    _, _, epoch, _num_episodes, num_returned = done_line.split()
    assert int(epoch) == 2
    assert int(num_returned) >= 36
    ledger = json.loads(learner_out.split('LEDGER', 1)[1].splitlines()[0])
    assert ledger['completed'] <= ledger['assigned']

    # fleet host states reached metrics_jsonl ...
    host_state_records = []
    with open(metrics) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get('fleet_host_states'):
                host_state_records.append(rec['fleet_host_states'])
    assert host_state_records, 'fleet_host_states never hit metrics_jsonl'
    # ... and the engine faults were visible learner-side as a host-state
    # signal (healthy -> degraded at minimum) plus the live exposition
    assert 'fleet: host' in learner_out, 'no host state transition observed'
    assert scraped_states, \
        'fleet_host_state never appeared in the live Prometheus exposition'
