"""TorusConv impl='halo' must be bit-for-bit the same FUNCTION as
impl='pad' (the wrap-pad reference semantics of the torus conv,
reference hungry_geese.py:23-35) — same param tree, same outputs, same
gradients. The halo path exists purely to remove the wrap-pad's
full-activation HBM copies (BENCHMARKS.md round-5 per-op table)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from handyrl_tpu.models.blocks import TorusConv
from handyrl_tpu.models.geese import GeeseNet


def _pair(filters=8, norm=True, dtype=jnp.float32):
    pad = TorusConv(filters, norm=norm, impl='pad', dtype=dtype)
    halo = TorusConv(filters, norm=norm, impl='halo', dtype=dtype)
    return pad, halo


@pytest.mark.parametrize('norm', [True, False])
@pytest.mark.parametrize('shape', [(4, 7, 11, 17), (2, 3, 5, 5, 8),
                                   (1, 2, 2, 6)])
def test_outputs_match(norm, shape):
    pad, halo = _pair(norm=norm)
    x = jax.random.normal(jax.random.PRNGKey(0), shape)
    params = pad.init(jax.random.PRNGKey(1), x)
    # identical param trees: checkpoints transfer between impls
    assert (jax.tree_util.tree_structure(params)
            == jax.tree_util.tree_structure(halo.init(jax.random.PRNGKey(1), x)))
    yp = pad.apply(params, x)
    yh = halo.apply(params, x)
    np.testing.assert_allclose(np.asarray(yp), np.asarray(yh),
                               rtol=2e-5, atol=2e-5)


def test_outputs_match_bf16():
    """The production headline runs bf16 activations — pin parity there
    too (looser tolerance: different accumulation order in the .at[].add
    correction chain vs the fused pad conv)."""
    pad, halo = _pair(filters=16, norm=True, dtype=jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(6), (8, 7, 11, 17))
    params = pad.init(jax.random.PRNGKey(7), x)
    yp = np.asarray(pad.apply(params, x), np.float32)
    yh = np.asarray(halo.apply(params, x), np.float32)
    np.testing.assert_allclose(yp, yh, rtol=0.05, atol=0.05)


def test_gradients_match():
    pad, halo = _pair(norm=False)
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 7, 11, 6))
    params = pad.init(jax.random.PRNGKey(3), x)

    def loss(mod, p, xx):
        return (mod.apply(p, xx) ** 2).sum()

    gp_p, gp_x = jax.grad(lambda p, xx: loss(pad, p, xx), argnums=(0, 1))(
        params, x)
    gh_p, gh_x = jax.grad(lambda p, xx: loss(halo, p, xx), argnums=(0, 1))(
        params, x)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-4),
        gp_p, gh_p)
    np.testing.assert_allclose(np.asarray(gp_x), np.asarray(gh_x),
                               rtol=1e-4, atol=1e-4)


def test_non3x3_kernel_rejected():
    mod = TorusConv(4, kernel=5, impl='halo')
    x = jnp.zeros((1, 7, 11, 3))
    with pytest.raises(ValueError):
        mod.init(jax.random.PRNGKey(0), x)


def test_geesenet_halo_twin():
    """Full GeeseNet forward agrees across torus impls with shared params."""
    obs = jax.random.normal(jax.random.PRNGKey(4), (2, 17, 7, 11))
    net_pad = GeeseNet(torus_impl='pad')
    net_halo = GeeseNet(torus_impl='halo')
    params = net_pad.init(jax.random.PRNGKey(5), obs)
    out_p = net_pad.apply(params, obs)
    out_h = net_halo.apply(params, obs)
    for k in ('policy', 'value'):
        np.testing.assert_allclose(np.asarray(out_p[k]), np.asarray(out_h[k]),
                                   rtol=2e-5, atol=2e-5)
