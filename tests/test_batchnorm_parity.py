"""Full BatchNorm parity for norm_kind='batch' (VERDICT r4 #1).

The reference trains GeisterNet with nn.BatchNorm2d in the stem and both
heads (reference geister.py:107,122) and serves actors/evaluators in eval
mode with running averages (reference model.py:54 — ``self.eval()`` before
inference). These tests pin the three ingredients on this side:

  1. the norm block itself matches torch BatchNorm2d train-mode outputs
     exactly and eval-mode outputs through the running-average EMA;
  2. the compiled update step advances the ``batch_stats`` collection by
     EMA only — Adam never touches it (zero-grad moments + weight decay
     would shrink the averages toward 0);
  3. every inference path reads the running averages, so B=1 sequential
     host inference computes the SAME network function as the batched
     paths — the documented BatchStatsNorm trap (ADVICE r4) is gone for
     'batch' — and snapshots/checkpoints carry the averages.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.blocks import make_norm
from handyrl_tpu.models.geister import GeisterNet
from handyrl_tpu.ops.losses import split_batch_stats

torch = pytest.importorskip('torch')


def _np(x):
    return np.asarray(x, dtype=np.float32)


def test_make_norm_batch_matches_torch_bn2d():
    """Same data stream through flax make_norm('batch') and torch
    BatchNorm2d: train-mode outputs agree exactly (both normalize by the
    biased current-batch variance); after several EMA updates the
    running mean agrees exactly and the running variance to the
    unbiased-vs-biased estimator factor n/(n-1)."""
    rng = np.random.RandomState(0)
    B, H, W, C = 4, 6, 6, 5
    n = B * H * W

    tnorm = torch.nn.BatchNorm2d(C, eps=1e-5, momentum=0.1)
    tnorm.train()

    norm = make_norm('batch', C, jnp.float32, train=True)
    x0 = rng.randn(B, H, W, C).astype(np.float32)
    variables = norm.init(jax.random.PRNGKey(0), jnp.asarray(x0))

    for step in range(3):
        x = (rng.randn(B, H, W, C) * (1 + step) + 0.3 * step).astype(np.float32)
        y, mut = norm.apply(variables, jnp.asarray(x),
                            mutable=['batch_stats'])
        variables = {**variables, 'batch_stats': mut['batch_stats']}
        with torch.no_grad():
            ty = tnorm(torch.from_numpy(x.transpose(0, 3, 1, 2)))
        np.testing.assert_allclose(
            _np(y), ty.numpy().transpose(0, 2, 3, 1), atol=2e-5,
            err_msg='train-mode output step %d' % step)

    bs = variables['batch_stats']
    np.testing.assert_allclose(_np(bs['mean']),
                               tnorm.running_mean.numpy(), atol=1e-5)
    # torch's running update uses the unbiased batch variance; flax the
    # biased one — each EMA term differs by n/(n-1), so the averages agree
    # to that factor (1.7% at n=144); the init-value term is shared
    np.testing.assert_allclose(_np(bs['var']), tnorm.running_var.numpy(),
                               rtol=(1.0 / (n - 1)) * 1.5)

    # eval mode: both serve their running averages per-sample
    tnorm.eval()
    xe = rng.randn(1, H, W, C).astype(np.float32)
    enorm = make_norm('batch', C, jnp.float32, train=False)
    ye = enorm.apply(variables, jnp.asarray(xe))
    with torch.no_grad():
        tye = tnorm(torch.from_numpy(xe.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(_np(ye), tye.numpy().transpose(0, 2, 3, 1),
                               rtol=2e-2, atol=2e-3)


@pytest.fixture(scope='module')
def geister_batch_and_wrapper():
    """A small real Geister training batch + a norm_kind='batch' model."""
    import random
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.generation import BatchedGenerator
    from handyrl_tpu.ops.batch import make_batch, select_episode

    random.seed(7)
    args = {
        'turn_based_training': True, 'observation': False,
        'gamma': 0.9, 'forward_steps': 8, 'burn_in_steps': 2,
        'compress_steps': 4, 'maximum_episodes': 100,
        'lambda': 0.7, 'policy_target': 'TD', 'value_target': 'TD',
        'entropy_regularization': 0.1, 'entropy_regularization_decay': 0.1,
    }
    env = make_env({'env': 'Geister'})
    env.reset()
    wrapper = ModelWrapper(GeisterNet(filters=8, drc_layers=2,
                                      drc_repeats=1, norm_kind='batch'))
    wrapper.ensure_params(env.observation(0))
    gen = BatchedGenerator(lambda i: make_env({'env': 'Geister'}), wrapper,
                           args, n_envs=4)
    episodes = []
    for _ in range(400):
        episodes += gen.step()
        if len(episodes) >= 4:
            break
    assert len(episodes) >= 4
    windows = [select_episode(episodes, args) for _ in range(4)]
    return wrapper, make_batch(windows, args), args


def test_update_step_advances_batch_stats_ema_only(geister_batch_and_wrapper):
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.train_step import build_update_step, init_train_state

    wrapper, batch, args = geister_batch_and_wrapper
    assert 'batch_stats' in wrapper.params, 'init must create running stats'

    state = init_train_state(jax.tree_util.tree_map(jnp.array, wrapper.params))
    # Adam state covers ONLY the trainable collections
    trainable, _ = split_batch_stats(state.params)
    opt_leaves = len(jax.tree_util.tree_leaves(state.opt_state))
    train_leaves = len(jax.tree_util.tree_leaves(trainable))
    all_leaves = len(jax.tree_util.tree_leaves(state.params))
    assert all_leaves > train_leaves, 'batch_stats leaves exist'
    # clip + weight-decay carry no state; adam = (mu, nu) per TRAINABLE
    # leaf + 1 count scalar. Equality pins Adam to exactly the trainable
    # set — covering batch_stats too would give 2*all_leaves + 1
    assert opt_leaves == 2 * train_leaves + 1, \
        'optimizer must cover exactly the trainable collections'

    update = build_update_step(wrapper.module, LossConfig.from_args(args),
                               mesh=None, donate=False)
    before = jax.tree_util.tree_map(np.array, state.params['batch_stats'])
    state2, metrics = update(state, batch, jnp.float32(1e-3))
    after = state2.params['batch_stats']

    moved = [float(np.max(np.abs(_np(a) - b)))
             for a, b in zip(jax.tree_util.tree_leaves(after),
                             jax.tree_util.tree_leaves(before))]
    assert max(moved) > 1e-6, 'running averages must advance during training'
    assert np.isfinite(float(metrics['total']))

    # second application must keep advancing (scan carry, not a one-shot)
    state3, _ = update(state2, batch, jnp.float32(1e-3))
    moved2 = [float(np.max(np.abs(_np(a) - _np(b))))
              for a, b in zip(jax.tree_util.tree_leaves(state3.params['batch_stats']),
                              jax.tree_util.tree_leaves(after))]
    assert max(moved2) > 1e-7


def test_b1_inference_matches_batched_rows(geister_batch_and_wrapper):
    """Running-average inference is per-sample: the sequential B=1 host
    paths (worker Evaluator, NetworkAgent) now compute the same network
    function as the batched actors (the BatchStatsNorm trap, ADVICE r4)."""
    from handyrl_tpu.environment import make_env

    wrapper, _, _ = geister_batch_and_wrapper
    env = make_env({'env': 'Geister'})
    env.reset()
    obs0 = env.observation(0)
    obs1 = env.observation(1)

    h1 = wrapper.init_hidden()
    single = wrapper.inference(obs0, h1)

    obs_b = jax.tree_util.tree_map(
        lambda a, b: np.stack([a, b]), obs0, obs1)
    hb = wrapper.init_hidden((2,))
    batched = wrapper.batch_inference(obs_b, hb)
    np.testing.assert_allclose(single['policy'],
                               _np(batched['policy'][0]), atol=1e-5)
    np.testing.assert_allclose(single['value'],
                               _np(batched['value'][0]), atol=1e-5)


def test_snapshot_roundtrip_carries_batch_stats(geister_batch_and_wrapper):
    from handyrl_tpu.environment import make_env

    wrapper, _, _ = geister_batch_and_wrapper
    env = make_env({'env': 'Geister'})
    env.reset()

    # perturb the running stats so the roundtrip can't pass by init values
    params = dict(wrapper.params)
    params['batch_stats'] = jax.tree_util.tree_map(
        lambda v: v + 0.25, params['batch_stats'])
    src = ModelWrapper(wrapper.module, params)
    snap = src.snapshot()
    dst = ModelWrapper.from_snapshot(snap, env.observation(0))
    for a, b in zip(jax.tree_util.tree_leaves(src.params['batch_stats']),
                    jax.tree_util.tree_leaves(dst.params['batch_stats'])):
        np.testing.assert_allclose(_np(a), _np(b))
    # and the served function reflects them
    out_src = src.inference(env.observation(0), src.init_hidden())
    out_dst = dst.inference(env.observation(0), dst.init_hidden())
    np.testing.assert_allclose(out_src['policy'], out_dst['policy'], atol=1e-6)


def test_norm_kind_env_args_plumbing_geese():
    """env_args {'norm_kind': 'batch'} reaches GeeseNet (caught live:
    the geese env didn't store self.args)."""
    from handyrl_tpu.environment import make_env
    env = make_env({'env': 'HungryGeese', 'norm_kind': 'batch'})
    assert env.net().norm_kind == 'batch'
    assert make_env({'env': 'HungryGeese'}).net().norm_kind == 'group'


def test_spatial_policy_head_layout_and_plumbing():
    """SpatialPolicyHead flattens channel-major: logit index =
    direction*36 + x*6 + y, the env's move encoding
    (envs/geister.py:114-118). Pinned by forcing the final 1x1 conv to
    emit direction-constant maps and checking where they land."""
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.models.blocks import SpatialPolicyHead

    head = SpatialPolicyHead(4, 4)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 6, 6, 8),
                    jnp.float32)
    variables = head.init(jax.random.PRNGKey(0), x)
    out = head.apply(variables, x)
    assert out.shape == (2, 144)

    # zero the final conv kernel, set bias[f] = f: every cell of
    # direction-plane f must read f after flattening
    params = jax.tree_util.tree_map(np.array, variables['params'])
    last = sorted(k for k in params if k.startswith('Conv'))[-1]
    params[last]['kernel'] = np.zeros_like(params[last]['kernel'])
    params[last]['bias'] = np.arange(4, dtype=np.float32)
    out = np.asarray(head.apply({'params': params}, x))
    for d in range(4):
        for cell in (0, 7, 35):
            assert out[0, d * 36 + cell] == d

    # env_args plumbing + the A/B config (spatial head + full BatchNorm)
    # constructs, serves B=1 inference, and takes a training step
    env = make_env({'env': 'Geister', 'policy_head': 'spatial',
                    'norm_kind': 'batch'})
    assert env.net().policy_head == 'spatial'
    assert make_env({'env': 'Geister'}).net().policy_head == 'dense'
    from handyrl_tpu.model import ModelWrapper
    env.reset()
    w = ModelWrapper(env.net())
    out = w.inference(env.observation(0), w.init_hidden())
    assert out['policy'].shape == (214,)
    assert np.all(np.isfinite(out['policy']))
    assert 'batch_stats' in w.params


def test_spatial_batch_head_trains(geister_batch_and_wrapper):
    """One compiled update step on the exact round-5 A/B model config
    (policy_head='spatial', norm_kind='batch'): finite loss, advancing
    running stats — so the combination cannot first fail mid-benchmark."""
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.train_step import build_update_step, init_train_state

    _, batch, args = geister_batch_and_wrapper
    wrapper = ModelWrapper(GeisterNet(filters=8, drc_layers=2,
                                      drc_repeats=1, norm_kind='batch',
                                      policy_head='spatial'))
    from handyrl_tpu.environment import make_env
    env = make_env({'env': 'Geister'})
    env.reset()
    wrapper.ensure_params(env.observation(0))
    state = init_train_state(jax.tree_util.tree_map(jnp.array, wrapper.params))
    update = build_update_step(wrapper.module, LossConfig.from_args(args),
                               mesh=None, donate=False)
    before = jax.tree_util.tree_map(np.array, state.params['batch_stats'])
    state2, metrics = update(state, batch, jnp.float32(1e-3))
    assert np.isfinite(float(metrics['total']))
    moved = [float(np.max(np.abs(_np(a) - b)))
             for a, b in zip(jax.tree_util.tree_leaves(
                 state2.params['batch_stats']),
                 jax.tree_util.tree_leaves(before))]
    assert max(moved) > 1e-7
