"""Device-resident generation: pure-JAX env correctness and episode-record
compatibility with the standard batch builder."""

import numpy as np
import jax
import jax.numpy as jnp

from handyrl_tpu.envs import jax_tictactoe as jttt
from handyrl_tpu.envs.tictactoe import Environment as HostTicTacToe
from handyrl_tpu.device_generation import DeviceGenerator
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.tictactoe import SimpleConv2dModel
from handyrl_tpu.ops.batch import decompress_moments, make_batch, select_episode
from helpers import train_args


def test_jax_env_matches_host_env():
    """Drive both envs with the same action sequence; states must agree."""
    state = jttt.init_state(1)
    host = HostTicTacToe()
    rng = np.random.RandomState(0)
    while not host.terminal():
        legal = host.legal_actions()
        a = int(rng.choice(legal))
        # device legal mask agrees
        dev_legal = np.flatnonzero(np.asarray(jttt.legal_mask(state))[0]).tolist()
        assert dev_legal == legal
        # observations agree (side-to-move view)
        np.testing.assert_array_equal(
            np.asarray(jttt.observe(state))[0], host.observation(host.turn()))
        state = jttt.step(state, jnp.asarray([a]))
        host.play(a)
    assert bool(jttt.terminal(state)[0])
    oc = np.asarray(jttt.outcome(state))[0]
    host_oc = host.outcome()
    assert oc[0] == host_oc[0] and oc[1] == host_oc[1]


def test_device_generator_episodes_valid():
    wrapper = ModelWrapper(SimpleConv2dModel())
    host = HostTicTacToe()
    wrapper.ensure_params(host.observation(0))
    args = train_args(forward_steps=8)
    args['gamma'] = 0.8
    gen = DeviceGenerator(jttt, wrapper, args, n_envs=16, chunk_steps=16)

    episodes = []
    for _ in range(4):
        episodes += gen.step_chunk()
    assert len(episodes) >= 16

    for ep in episodes[:10]:
        assert 5 <= ep['steps'] <= 9
        assert abs(ep['outcome'][0] + ep['outcome'][1]) < 1e-9
        moments = decompress_moments(ep['moment'])
        assert len(moments) == ep['steps']
        # replay the recorded actions through the host env: all legal,
        # and the final outcome matches
        host = HostTicTacToe()
        host.reset()
        for t, m in enumerate(moments):
            player = m['turn'][0]
            assert player == t % 2
            action = m['action'][player]
            assert action in host.legal_actions()
            assert m['action_mask'][player][action] == 0
            host.play(action)
        assert host.terminal()
        assert host.outcome() == ep['outcome']

    # records feed the standard batch builder unchanged
    batch = make_batch([select_episode(episodes, args) for _ in range(4)], args)
    assert batch['observation'].shape[:3] == (4, 8, 1)
    assert np.isfinite(np.asarray(batch['selected_prob'])).all()


def test_device_generator_throughput_smoke():
    """One compiled dispatch advances all envs one ply — just assert the
    chunk API returns steadily without recompiles (same shapes)."""
    wrapper = ModelWrapper(SimpleConv2dModel())
    host = HostTicTacToe()
    wrapper.ensure_params(host.observation(0))
    args = train_args(forward_steps=8)
    gen = DeviceGenerator(jttt, wrapper, args, n_envs=8, chunk_steps=8)
    total = 0
    for _ in range(6):
        total += len(gen.step_chunk())
    assert total >= 5
