"""GeeseFormer: single-device vs sequence-parallel ring attention parity,
and trainability through the compiled update step."""

import numpy as np
import jax
import jax.numpy as jnp

from handyrl_tpu.models import build
from handyrl_tpu.parallel.mesh import make_mesh


def _obs(B=4, seed=0):
    rng = np.random.RandomState(seed)
    obs = (rng.rand(B, 17, 7, 11) < 0.1).astype(np.float32)
    obs[:, 0] = 0
    for b in range(B):
        obs[b, 0, rng.randint(7), rng.randint(11)] = 1.0   # own head
    return obs


def test_geese_former_shapes():
    module = build('GeeseFormer', dim=32, layers=2, heads=2)
    obs = _obs()
    params = module.init(jax.random.PRNGKey(0), obs, None)
    out = module.apply(params, obs, None)
    assert out['policy'].shape == (4, 4)
    assert out['value'].shape == (4, 1)


def test_ring_mesh_matches_single_device():
    """Same params, attention over the ring vs on one device."""
    mesh = make_mesh(model_parallel=4)     # ('data', 'model') = (2, 4)
    single = build('GeeseFormer', dim=32, layers=2, heads=2)
    ringed = build('GeeseFormer', dim=32, layers=2, heads=2,
                   mesh=mesh, ring_axis='model')
    obs = _obs(B=2, seed=1)
    params = single.init(jax.random.PRNGKey(0), obs, None)
    want = single.apply(params, obs, None)
    got = ringed.apply(params, obs, None)
    np.testing.assert_allclose(np.asarray(got['policy']),
                               np.asarray(want['policy']), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got['value']),
                               np.asarray(want['value']), rtol=2e-4, atol=2e-5)


def test_geese_former_trains():
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.train_step import build_update_step, init_train_state
    from __graft_entry__ import _synthetic_batch

    rng = np.random.RandomState(2)
    batch = _synthetic_batch(4, 6, 1, (17, 7, 11), 4, rng)
    module = build('GeeseFormer', dim=32, layers=2, heads=2)
    params = module.init(jax.random.PRNGKey(0), batch['observation'][:, 0, 0], None)
    state = init_train_state(params)
    cfg = LossConfig(turn_based_training=False, observation=True,
                     policy_target='VTRACE', value_target='VTRACE', gamma=0.99)
    step = build_update_step(module, cfg, donate=False)
    state2, metrics = step(state, batch, jnp.asarray(1e-4, jnp.float32))
    assert np.isfinite(float(metrics['total']))
