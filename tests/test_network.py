"""Socket-layer tests: framed transport, worker-server entry handshake, and
a full network battle (server-side env vs remote agents over TCP)."""

import random
import threading
import time

import pytest

from handyrl_tpu.agent import RandomAgent
from handyrl_tpu.connection import (FramedConnection, accept_socket_connections,
                                    connect_socket_connection)
from handyrl_tpu.environment import make_env
from handyrl_tpu.evaluation import (NetworkAgent, NetworkAgentClient,
                                    exec_network_match, network_match_acception)
from handyrl_tpu.worker import WorkerServer, entry


def _free_port():
    import socket
    s = socket.socket()
    s.bind(('', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_framed_connection_roundtrip():
    port = _free_port()
    results = {}

    def server():
        gen = accept_socket_connections(port=port, maxsize=1)
        conn = next(gen)
        results['got'] = conn.recv()
        conn.send({'pong': [1, 2, 3]})

    t = threading.Thread(target=server, daemon=True)
    t.start()
    time.sleep(0.2)
    conn = connect_socket_connection('localhost', port)
    import numpy as np
    payload = {'ping': np.arange(5), 'big': b'x' * 100000}
    conn.send(payload)
    reply = conn.recv()
    t.join(timeout=5)
    assert reply == {'pong': [1, 2, 3]}
    assert list(results['got']['ping']) == [0, 1, 2, 3, 4]
    assert len(results['got']['big']) == 100000


def test_worker_server_entry_handshake(monkeypatch):
    port = _free_port()
    monkeypatch.setattr(WorkerServer, 'ENTRY_PORT', port)
    monkeypatch.setattr(WorkerServer, 'WORKER_PORT', _free_port())
    server = WorkerServer({'env': {'env': 'TicTacToe'}, 'seed': 0})
    server.run()
    time.sleep(0.3)

    got = entry({'server_address': 'localhost', 'num_parallel': 5,
                 'address': 'testhost'})
    assert got['worker']['base_worker_id'] == 0
    assert got['worker']['num_parallel'] == 5
    assert got['env'] == {'env': 'TicTacToe'}

    got2 = entry({'server_address': 'localhost', 'num_parallel': 3,
                  'address': 'testhost2'})
    assert got2['worker']['base_worker_id'] == 5


def test_network_battle_over_sockets(monkeypatch):
    """Server env drives two remote RandomAgents purely through the
    diff_info/string-action protocol."""
    port = _free_port()
    env_args = {'env': 'TicTacToe'}
    random.seed(0)

    def client():
        conn = None
        for _ in range(50):   # wait for the server socket to bind
            try:
                conn = connect_socket_connection('localhost', port)
                conn.fileno()
                conn.sock.getpeername()
                break
            except OSError:
                time.sleep(0.1)
        received_env_args = conn.recv()
        env = make_env(received_env_args)
        NetworkAgentClient(RandomAgent(), env, conn).run()

    clients = [threading.Thread(target=client, daemon=True) for _ in range(2)]
    for c in clients:
        c.start()

    agents_list = network_match_acception(1, env_args, 2, port)
    agent_map = {p: agents_list[0][p] for p in (0, 1)}

    env = make_env(env_args)
    for _ in range(3):
        result = exec_network_match(env, agent_map)
        assert result is not None
        outcome = result['result']
        assert set(outcome.keys()) == {0, 1}
        assert abs(outcome[0] + outcome[1]) < 1e-9   # zero-sum
