"""Device actor backend: the fused on-device rollout engine that serves a
gather host's whole ledger task block (device_generation.DeviceActorEngine,
worker.DeviceActorGather).

Contracts pinned here:

  * strict envs (TicTacToe, ConnectX): device episodes are BYTE-compatible
    with the host Generator under identical (seed, sample_key, params) —
    the records land in the replay buffer indistinguishable;
  * device-contract envs (HungryGeese, Geister): episodes carry an
    explicit ``record_version`` stamp — divergence is declared, never
    silent (slow legs);
  * league populations: one compiled program serves every pairing via
    per-slot stacked params; slot overflow defers to the host fallback
    instead of retracing;
  * the jax ConnectX twin tracks the host env move for move, including
    the vectorized rule-based heuristic.
"""

import numpy as np
import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.connection import pack
from handyrl_tpu.device_generation import (DeviceActorEngine,
                                           resolve_record_mode)
from handyrl_tpu.environment import make_env, make_jax_env
from handyrl_tpu.generation import Generator
from handyrl_tpu.inference import ModelVault
from handyrl_tpu.league import plan_slots
from handyrl_tpu.model import ModelWrapper


def _train_args(env_name):
    cfg = apply_defaults({'env_args': {'env': env_name},
                          'train_args': {'seed': 11}})
    ta = dict(cfg['train_args'])
    ta['env'] = cfg['env_args']
    return ta


def _engine(env_name, slots=2, n_envs=6, record=''):
    ta = _train_args(env_name)
    env = make_env(ta['env'])
    env.reset()
    obs0 = env.observation(env.players()[0])
    snaps = {}

    def fetch(mid):
        if mid not in snaps:
            w = ModelWrapper(env.net(), seed=100 + int(mid))
            w.ensure_params(obs0)
            snaps[mid] = w.snapshot()
        return snaps[mid]

    vault = ModelVault(fetch, obs0, capacity=slots + 2)
    eng = DeviceActorEngine(make_jax_env(ta['env']), vault,
                            make_env(ta['env']), ta, n_envs=n_envs,
                            chunk_steps=8, slots=slots,
                            record_mode=record, seed=5)
    return ta, vault, eng


def _g_task(key, mids):
    players = sorted(mids)
    return {'role': 'g', 'player': players, 'model_id': dict(mids),
            'sample_key': key, 'task_id': key}


# -- units ---------------------------------------------------------------

def test_plan_slots_admission_and_overflow():
    assign, admitted = plan_slots([[1], [2], [1, 2]], 2)
    assert assign == {1: 0, 2: 1}
    assert admitted == [True, True, True]
    # a third distinct mid overflows: that task is refused, no eviction
    assign, admitted = plan_slots([[1], [2], [3]], 2)
    assert assign == {1: 0, 2: 1}
    assert admitted == [True, True, False]
    # mids <= 0 (random/none seats) never claim a slot
    assign, admitted = plan_slots([[0, -1]], 1)
    assert assign == {}
    assert admitted == [True]


def test_resolve_record_mode():
    from handyrl_tpu.envs import (jax_connectx, jax_geister,
                                  jax_hungry_geese, jax_tictactoe)
    assert resolve_record_mode(jax_tictactoe, recurrent=False) == 'strict'
    assert resolve_record_mode(jax_connectx, recurrent=False) == 'strict'
    # recurrence breaks the host byte contract (hidden-state replay)
    assert resolve_record_mode(jax_tictactoe, recurrent=True) == 'device'
    assert resolve_record_mode(jax_hungry_geese, recurrent=False) == 'device'
    assert resolve_record_mode(jax_geister, recurrent=True) == 'device'
    with pytest.raises(ValueError):
        resolve_record_mode(jax_hungry_geese, recurrent=False,
                            requested='strict')


def test_config_validates_backend_knobs():
    ta = apply_defaults({'env_args': {'env': 'TicTacToe'}})['train_args']
    gen = ta['generation']
    assert gen['backend'] == ''
    assert gen['device_actor_envs'] >= 1
    assert gen['device_actor_record'] in ('', 'strict', 'device')
    with pytest.raises(AssertionError):
        apply_defaults({'env_args': {'env': 'TicTacToe'},
                        'train_args': {'generation': {'backend': 'gpu'}}})
    with pytest.raises(AssertionError):
        apply_defaults({'env_args': {'env': 'TicTacToe'},
                        'train_args': {'generation':
                                       {'device_actor_record': 'exact'}}})


# -- strict byte parity (TicTacToe) --------------------------------------

@pytest.fixture(scope='module')
def ttt():
    return _engine('TicTacToe')


def test_strict_episodes_byte_match_host_generator(ttt):
    ta, vault, eng = ttt
    assert eng.record_mode == 'strict'
    tasks = [_g_task(k, {0: 1, 1: 1}) for k in range(4)]
    uploads, deferred = eng.run_block(tasks)
    assert not deferred
    by_key = {p['args']['sample_key']: p for k, p in uploads
              if k == 'episode' and p is not None}
    assert sorted(by_key) == [0, 1, 2, 3]

    env = make_env(ta['env'])
    gen = Generator(env, ta)
    models = {0: vault.model(1), 1: vault.model(1)}
    for task in tasks:
        host = gen.execute(models, task)
        assert pack(by_key[task['sample_key']]) == pack(host), \
            'device episode for key %s is not byte-identical' \
            % task['sample_key']
    # strict records carry no version stamp: they ARE the host format
    assert all('record_version' not in p for p in by_key.values())


def test_league_pairing_and_slot_overflow(ttt):
    _ta, _vault, eng = ttt
    # a cross-model pairing plays in ONE lane of the same compiled program
    uploads, deferred = eng.run_block([_g_task(7, {0: 1, 1: 2})])
    assert not deferred and uploads[0][1] is not None
    # three distinct mids into two slots: the overflow task defers to the
    # host fallback (never a retrace)
    tasks = [_g_task(0, {0: 1, 1: 1}), _g_task(1, {0: 2, 1: 2}),
             _g_task(2, {0: 3, 1: 1})]
    uploads, deferred = eng.run_block(tasks)
    assert len(uploads) == 2
    assert [t['task_id'] for t in deferred] == [2]


def test_eval_task_returns_deterministic_result(ttt):
    _ta, _vault, eng = ttt
    task = {'role': 'e', 'player': [0], 'model_id': {0: 1, 1: -1},
            'sample_key': 9, 'opponent': 'random', 'task_id': 99}
    uploads, _ = eng.run_block([dict(task)])
    kind, first = uploads[0]
    uploads, _ = eng.run_block([dict(task)])
    kind2, second = uploads[0]
    assert kind == kind2 == 'result'
    assert first['opponent'] == 'random'
    assert set(first['result']) == {0, 1}
    # keyed eval draws are deterministic: a ledger re-issue reproduces
    assert first['result'] == second['result']


def test_unservable_tasks_defer(ttt):
    _ta, _vault, eng = ttt
    # negative mid in a 'g' seat: only the host fallback can serve it
    _, deferred = eng.run_block([_g_task(0, {0: 1, 1: -5})])
    assert len(deferred) == 1


# -- device-contract records (slow: bigger nets, longer episodes) --------

@pytest.mark.slow
@pytest.mark.parametrize('env_name', ['HungryGeese', 'Geister'])
def test_device_records_are_version_stamped(env_name):
    from handyrl_tpu.ops.batch import decompress_moments
    ta, vault, eng = _engine(env_name, n_envs=4)
    assert eng.record_mode == 'device'
    P = eng.num_players
    tasks = [_g_task(k, {p: 1 for p in range(P)}) for k in range(2)]
    uploads, deferred = eng.run_block(tasks)
    assert not deferred
    eps = [p for k, p in uploads if k == 'episode' and p is not None]
    assert len(eps) == 2
    for ep in eps:
        assert ep['record_version'] == 1   # divergence declared, not silent
        moments = decompress_moments(ep['moment'])
        assert len(moments) == ep['steps'] > 0
        assert set(ep['outcome']) == set(range(P))


@pytest.mark.slow
def test_device_records_pass_network_oracle():
    """Non-recurrent device records must be network-consistent: re-running
    the SAME params on each recorded observation reproduces the recorded
    action probability and value (the stamp marks an rng contract change,
    not a different policy)."""
    import jax
    import jax.numpy as jnp
    from handyrl_tpu.ops.batch import decompress_moments
    ta, vault, eng = _engine('HungryGeese', n_envs=4)
    P = eng.num_players
    uploads, _ = eng.run_block([_g_task(0, {p: 1 for p in range(P)})])
    ep = next(p for k, p in uploads if k == 'episode' and p is not None)
    wrapper = vault.model(1)
    for moment in decompress_moments(ep['moment']):
        for p in moment['turn']:
            obs = moment['observation'][p]
            if obs is None:
                continue
            out = wrapper.inference(obs, None)
            probs = np.asarray(jax.nn.softmax(jnp.asarray(out['policy'])))
            a = moment['action'][p]
            assert abs(float(probs[a]) - moment['selected_prob'][p]) < 1e-4
            assert np.allclose(np.asarray(out['value']).reshape(-1),
                               np.asarray(moment['value'][p]).reshape(-1),
                               atol=1e-4)


# -- jax ConnectX twin parity --------------------------------------------

def test_jax_connectx_tracks_host_env():
    import jax
    from handyrl_tpu.envs import jax_connectx as jcx

    env = make_env({'env': 'ConnectX'})
    step = jax.jit(jcx.step)
    rng = np.random.default_rng(3)
    for game in range(3):
        env.reset()
        state = jcx.init_state(1)
        while not env.terminal():
            legal = env.legal_actions()
            mask = np.asarray(jcx.legal_mask(state))[0]
            assert sorted(legal) == [c for c in range(7) if mask[c] > 0]
            assert int(np.asarray(jcx.turn(state))[0]) == env.turn()
            obs = env.observation(env.turn())
            np.testing.assert_array_equal(
                np.asarray(jcx.observe(state))[0], obs)
            a = int(rng.choice(legal))
            env.play(a)
            state = step(state, jnp_action(a))
        assert bool(np.asarray(jcx.terminal(state))[0])
        out = np.asarray(jcx.outcome(state))[0]
        host_out = env.outcome()
        assert float(out[0]) == host_out[0] and float(out[1]) == host_out[1]


def jnp_action(a):
    import jax.numpy as jnp
    return jnp.asarray([a], jnp.int32)


def test_jax_connectx_greedy_matches_rule_based():
    from handyrl_tpu.envs import jax_connectx as jcx

    env = make_env({'env': 'ConnectX'})
    rng = np.random.default_rng(9)
    checked = 0
    for game in range(4):
        env.reset()
        state = jcx.init_state(1)
        while not env.terminal():
            want = env.rule_based_action(env.turn())
            got = int(np.asarray(jcx.greedy_action(state))[0])
            assert got == want, 'heuristic diverged at ply %d' % checked
            checked += 1
            a = int(rng.choice(env.legal_actions()))
            env.play(a)
            state = jcx.step(state, jnp_action(a))
    assert checked > 20
