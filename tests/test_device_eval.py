"""Device-resident online evaluation (DeviceEvaluator)."""

import numpy as np
import jax

from handyrl_tpu.device_generation import DeviceEvaluator
from handyrl_tpu.envs import jax_tictactoe, jax_hungry_geese
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.tictactoe import SimpleConv2dModel
from handyrl_tpu.models import build


def _wrapper(module, obs):
    w = ModelWrapper(module)
    w.params = module.init(jax.random.PRNGKey(0), obs, None)
    return w


def test_turn_based_results_shape_and_seat_rotation():
    obs = np.zeros((1, 3, 3, 3), np.float32)
    w = _wrapper(SimpleConv2dModel(), obs)
    ev = DeviceEvaluator(jax_tictactoe, w, {}, n_envs=8, chunk_steps=8)
    results = []
    for _ in range(6):
        results.extend(ev.step())
    assert len(results) >= 8
    seats = set()
    for r in results:
        assert r['args']['role'] == 'e'
        (seat,) = r['args']['player']
        seats.add(seat)
        assert r['opponent'] == 'random'
        # model_id 0 marks the evaluated seat, -1 the builtin opponent
        assert r['args']['model_id'][seat] == 0
        outcome = r['result']
        assert set(outcome) == {0, 1}
        assert all(v in (-1.0, 0.0, 1.0) for v in outcome.values())
        # zero-sum
        assert outcome[0] + outcome[1] == 0
    assert seats == {0, 1}, 'both seats must be evaluated'


def test_simultaneous_env_results():
    module = build('GeeseNet', layers=2, filters=16)
    obs = np.zeros((1, 17, 7, 11), np.float32)
    w = _wrapper(module, obs)
    ev = DeviceEvaluator(jax_hungry_geese, w, {}, n_envs=4, chunk_steps=16)
    results = []
    for _ in range(20):
        results.extend(ev.step())
        if len(results) >= 4:
            break
    assert len(results) >= 4
    for r in results:
        (seat,) = r['args']['player']
        assert 0 <= seat < 4
        assert set(r['result']) == {0, 1, 2, 3}
        # pairwise-rank outcomes lie in [-1, 1]
        assert all(-1.0 <= v <= 1.0 for v in r['result'].values())


def test_one_dispatch_returns_many_plies():
    """The point of the device evaluator: a single step() call advances
    every match chunk_steps plies, so short games finish within one call."""
    obs = np.zeros((1, 3, 3, 3), np.float32)
    w = _wrapper(SimpleConv2dModel(), obs)
    ev = DeviceEvaluator(jax_tictactoe, w, {}, n_envs=16, chunk_steps=16)
    # 16 envs x 16 plies: tictactoe games last 5-9 plies, so the very first
    # chunk must already complete a batch of matches
    results = ev.step()
    assert len(results) >= 8
