"""Device-resident online evaluation (DeviceEvaluator)."""

import numpy as np
import jax

from handyrl_tpu.device_generation import DeviceEvaluator
from handyrl_tpu.envs import jax_tictactoe, jax_hungry_geese
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.tictactoe import SimpleConv2dModel
from handyrl_tpu.models import build


def _wrapper(module, obs):
    w = ModelWrapper(module)
    w.params = module.init(jax.random.PRNGKey(0), obs, None)
    return w


def test_turn_based_results_shape_and_seat_rotation():
    obs = np.zeros((1, 3, 3, 3), np.float32)
    w = _wrapper(SimpleConv2dModel(), obs)
    ev = DeviceEvaluator(jax_tictactoe, w, {}, n_envs=8, chunk_steps=8)
    results = []
    for _ in range(6):
        results.extend(ev.step())
    assert len(results) >= 8
    seats = set()
    for r in results:
        assert r['args']['role'] == 'e'
        (seat,) = r['args']['player']
        seats.add(seat)
        assert r['opponent'] == 'random'
        # model_id 0 marks the evaluated seat, -1 the builtin opponent
        assert r['args']['model_id'][seat] == 0
        outcome = r['result']
        assert set(outcome) == {0, 1}
        assert all(v in (-1.0, 0.0, 1.0) for v in outcome.values())
        # zero-sum
        assert outcome[0] + outcome[1] == 0
    assert seats == {0, 1}, 'both seats must be evaluated'


def test_simultaneous_env_results():
    module = build('GeeseNet', layers=2, filters=16)
    obs = np.zeros((1, 17, 7, 11), np.float32)
    w = _wrapper(module, obs)
    ev = DeviceEvaluator(jax_hungry_geese, w, {}, n_envs=4, chunk_steps=16)
    results = []
    for _ in range(20):
        results.extend(ev.step())
        if len(results) >= 4:
            break
    assert len(results) >= 4
    for r in results:
        (seat,) = r['args']['player']
        assert 0 <= seat < 4
        assert set(r['result']) == {0, 1, 2, 3}
        # pairwise-rank outcomes lie in [-1, 1]
        assert all(-1.0 <= v <= 1.0 for v in r['result'].values())


def test_one_dispatch_returns_many_plies():
    """The point of the device evaluator: a single step() call advances
    every match chunk_steps plies, so short games finish within one call."""
    obs = np.zeros((1, 3, 3, 3), np.float32)
    w = _wrapper(SimpleConv2dModel(), obs)
    ev = DeviceEvaluator(jax_tictactoe, w, {}, n_envs=16, chunk_steps=16)
    # 16 envs x 16 plies: tictactoe games last 5-9 plies, so the very first
    # chunk must already complete a batch of matches
    results = ev.step()
    assert len(results) >= 8


def test_model_checkpoint_opponent_blocks(tmp_path):
    """League-style eval: checkpoint opponents play their own greedy policy
    inside the same compiled chunk (one dispatch, no host fallback), and
    results attribute to the opponent that actually played the env."""
    obs = np.zeros((1, 3, 3, 3), np.float32)
    w = _wrapper(SimpleConv2dModel(), obs)
    # a DIFFERENT set of params as the checkpoint opponent
    w2 = ModelWrapper(SimpleConv2dModel())
    w2.params = SimpleConv2dModel().init(jax.random.PRNGKey(9), obs, None)
    path = str(tmp_path / 'opp.ckpt')
    with open(path, 'wb') as f:
        f.write(w2.params_bytes())

    ev = DeviceEvaluator(jax_tictactoe, w, {}, n_envs=8, chunk_steps=8,
                         opponents=['random', path])
    results = []
    for _ in range(8):
        results.extend(ev.step())
    assert len(results) >= 8
    by_opp = {}
    for r in results:
        by_opp.setdefault(r['opponent'], []).append(r)
        outcome = r['result']
        assert outcome[0] + outcome[1] == 0
    # both halves of the env split produced finished games
    assert set(by_opp) == {'random', path}


def test_model_opponent_differs_from_random():
    """A strong fixed opponent must actually influence play: against a
    self-copy opponent (identical params, both greedy), the deterministic
    seat-balanced matches repeat the same game, so results differ from the
    uniform-random opponent distribution."""
    obs = np.zeros((1, 3, 3, 3), np.float32)
    w = _wrapper(SimpleConv2dModel(), obs)
    import tempfile, os
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, 'self.ckpt')
        with open(path, 'wb') as f:
            f.write(w.params_bytes())
        ev = DeviceEvaluator(jax_tictactoe, w, {}, n_envs=4, chunk_steps=16,
                             opponents=[path])
        results = []
        for _ in range(4):
            results.extend(ev.step())
        # greedy-vs-greedy with identical nets: every game from the same
        # seat assignment has the identical outcome
        per_seat = {}
        for r in results:
            (seat,) = r['args']['player']
            per_seat.setdefault(seat, set()).add(r['result'][seat])
        for seat, outs in per_seat.items():
            assert len(outs) == 1, (seat, outs)


def test_recurrent_checkpoint_opponent_on_device(tmp_path):
    """Geister league eval on device: a RECURRENT (DRC) checkpoint opponent
    plays inside the compiled chunk — its hidden state carried through the
    rollout scan — instead of falling back to the per-ply host evaluator."""
    from handyrl_tpu.envs import jax_geister
    from handyrl_tpu.models.geister import GeisterNet

    obs = jax_geister.observe(jax_geister.init_state(1))
    module = GeisterNet(filters=8, drc_layers=1)
    w = _wrapper(module, obs)
    w2 = ModelWrapper(GeisterNet(filters=8, drc_layers=1))
    w2.params = w2.module.init(jax.random.PRNGKey(9), obs, None)
    path = str(tmp_path / 'opp.ckpt')
    with open(path, 'wb') as f:
        f.write(w2.params_bytes())

    ev = DeviceEvaluator(jax_geister, w, {}, n_envs=4, chunk_steps=32,
                         opponents=[path])
    assert ev.recurrent and ev.opp_hidden is not None
    results = []
    for _ in range(16):
        results.extend(ev.step())
        if len(results) >= 4:
            break
    assert len(results) >= 4
    for r in results:
        assert r['opponent'] == path
        outcome = r['result']
        assert outcome[0] + outcome[1] == 0        # zero-sum
        (seat,) = r['args']['player']
        assert r['args']['model_id'][seat] == 0
    # the opponent's hidden tree is live device state, not zeros: at least
    # one leaf must have been written by the checkpoint policy's DRC
    leaves = jax.tree_util.tree_leaves(ev.opp_hidden)
    assert any(float(np.abs(np.asarray(l)).max()) > 0 for l in leaves)


def test_recurrent_checkpoint_opponent_simultaneous_env(tmp_path):
    """Same league-eval plumbing on a SIMULTANEOUS env: a recurrent
    (LSTM) geese checkpoint opponent folds its (N, P) hidden through the
    batch dim inside the compiled chunk."""
    from handyrl_tpu.models import build

    obs = np.zeros((1, 17, 7, 11), np.float32)
    module = build('GeeseNetLSTM', filters=8, stem_layers=1)
    w = _wrapper(module, obs)
    w2 = ModelWrapper(build('GeeseNetLSTM', filters=8, stem_layers=1))
    w2.params = w2.module.init(jax.random.PRNGKey(5), obs, None)
    path = str(tmp_path / 'opp_lstm.ckpt')
    with open(path, 'wb') as f:
        f.write(w2.params_bytes())

    ev = DeviceEvaluator(jax_hungry_geese, w, {}, n_envs=4, chunk_steps=24,
                         opponents=[path])
    assert ev.recurrent and ev.opp_hidden is not None
    results = []
    for _ in range(20):
        results.extend(ev.step())
        if len(results) >= 4:
            break
    assert len(results) >= 4
    for r in results:
        assert r['opponent'] == path
        assert set(r['result']) == {0, 1, 2, 3}
        assert all(-1.0 <= v <= 1.0 for v in r['result'].values())
    leaves = jax.tree_util.tree_leaves(ev.opp_hidden)
    assert any(float(np.abs(np.asarray(l)).max()) > 0 for l in leaves)


def test_learner_selects_device_eval_for_recurrent_league(tmp_path,
                                                          monkeypatch):
    """The Learner's device_eval_ok gate must keep a RECURRENT net with a
    checkpoint league opponent on the device evaluator (the host fallback
    is the dispatch-per-ply path the device evaluator exists to kill)."""
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.models.geister import GeisterNet
    from handyrl_tpu import train as train_mod
    from handyrl_tpu.train import Learner

    net = GeisterNet(filters=8, drc_layers=1)
    w = ModelWrapper(GeisterNet(filters=8, drc_layers=1))
    from handyrl_tpu.envs import jax_geister
    w.params = w.module.init(jax.random.PRNGKey(3),
                             jax_geister.observe(jax_geister.init_state(1)),
                             None)
    ckpt = tmp_path / 'league_opp.ckpt'
    ckpt.write_bytes(w.params_bytes())

    def _boom(*a, **k):
        raise AssertionError('host evaluator constructed: device_eval_ok '
                             'rejected the recurrent league opponent')
    monkeypatch.setattr(train_mod, 'BatchedEvaluator', _boom)

    raw = {
        'env_args': {'env': 'Geister'},
        'train_args': {
            'turn_based_training': True, 'observation': True,
            'gamma': 0.9, 'forward_steps': 4, 'compress_steps': 2,
            'batch_size': 4, 'update_episodes': 6, 'minimum_episodes': 6,
            'epochs': 1, 'generation_envs': 4, 'num_batchers': 1,
            'device_generation': True, 'device_replay': True,
            'eval': {'opponent': [str(ckpt)]},
            'model_dir': str(tmp_path / 'models'),
        },
    }
    learner = Learner(args=apply_defaults(raw), net=net)
    learner.run()
    assert learner.model_epoch == 1


def test_geese_rulebase_opponent_on_device():
    """The vectorized GreedyAgent plays the opponent seats on device; the
    untrained net should score clearly WORSE vs rulebase than vs random."""
    obs = np.zeros((1, 17, 7, 11), np.float32)
    w = _wrapper(build('GeeseNet', layers=2, filters=8), obs)

    def run(opp, n=48):
        ev = DeviceEvaluator(jax_hungry_geese, w, {}, n_envs=16,
                             chunk_steps=32, opponents=[opp])
        results = []
        while len(results) < n:
            results.extend(ev.step())
        vals = [r['result'][r['args']['player'][0]] for r in results]
        for r in results:
            assert r['opponent'] == opp
        return float(np.mean(vals))

    vs_random = run('random')
    vs_rule = run('rulebase')
    # untrained vs 3 greedy geese must be clearly below its vs-random score
    assert vs_rule < vs_random - 0.2, (vs_rule, vs_random)
