"""Offline evaluation harness test: evaluate_mp in-process with a trained
checkpoint vs random, with first/second balancing."""

import random

from handyrl_tpu.agent import Agent, RandomAgent
from handyrl_tpu.environment import make_env
from handyrl_tpu.evaluation import evaluate_mp, wp_func
from handyrl_tpu.model import ModelWrapper


def test_evaluate_mp_single_process(capsys):
    random.seed(0)
    env_args = {'env': 'TicTacToe'}
    env = make_env(env_args)
    env.reset()
    wrapper = ModelWrapper(env.net())
    wrapper.ensure_params(env.observation(0))

    agents = [Agent(wrapper), RandomAgent()]
    evaluate_mp(env, agents, None, env_args, {'default': {}},
                num_process=1, num_games=6, seed=1)
    out = capsys.readouterr().out
    assert 'total games = 6' in out
    # both seat-balanced patterns appear
    assert 'default-F' in out and 'default-S' in out
    assert '---agent 0---' in out and '---agent 1---' in out


def test_wp_func():
    assert wp_func({1.0: 3, -1.0: 1}) == 0.75
    assert wp_func({}) == 0.0
    assert wp_func({0.0: 2}) == 0.5
