"""The compact 'turn' window layout is TRAINING-EQUIVALENT to the wide
observation=True layout for turn-based envs that record only the acting
seat (every env: ``observers()`` defaults empty, as in the reference —
reference environment.py:84).

This is the proof obligation behind train.py's ingest gate admitting
observation=True configs to the device 'turn' windower: the same window,
expressed in both layouts, must produce the SAME loss and the SAME
gradients when the loss runs with the matching LossConfig.observation
flag. The wide layout runs the net on zero observations for non-acting
seats and masks the outputs; the compact layout skips them; per-player
recurrent hidden advances identically in both (omask-gated carry).

Scope: the identity holds for PER-SAMPLE models (GroupNorm/LayerNorm —
each row's output depends only on that row). With batch-statistics
normalization (models/blocks.py BatchStatsNorm, GeisterNet's
norm_kind='batch' investigation setting) the layouts intentionally
differ: the wide layout's statistics include the zeroed non-acting-seat
rows (as the torch reference's train-mode BatchNorm did) while the
compact layout's do not (window-tail pad rows still enter both). The
last test pins that difference so it stays a documented choice, not an
accident."""

import random

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from handyrl_tpu.environment import make_env
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.geister import GeisterNet
from handyrl_tpu.generation import BatchedGenerator
from handyrl_tpu.ops.batch import make_batch, select_episode
from handyrl_tpu.ops.losses import LossConfig, compute_loss

ENV_ARGS = {'env': 'Geister'}


def _args(observation, burn_in=2):
    return {
        'turn_based_training': True, 'observation': observation,
        'gamma': 0.9, 'forward_steps': 8, 'burn_in_steps': burn_in,
        'compress_steps': 4, 'maximum_episodes': 100,
        'lambda': 0.7, 'policy_target': 'TD', 'value_target': 'TD',
        'entropy_regularization': 0.1, 'entropy_regularization_decay': 0.1,
    }


def _wide_to_compact(batch):
    """Project an observation=True (B, T, P, ...) batch onto the compact
    turn layout (data leaves P axis 1, masks/values still span P) by
    selecting the acting seat's lane — the inverse of what the wide
    layout's zero-padding adds."""
    seat = jnp.argmax(batch['turn_mask'][..., 0], axis=-1)       # (B, T)

    def take(x, pad):
        # (B, T, P, ...) -> (B, T, 1, ...): acting seat's entry where one
        # exists, the layout's pad value on tail plies (no seat acted)
        sel = seat.reshape(seat.shape + (1,) * (x.ndim - 2))
        idx = jnp.broadcast_to(sel, x.shape[:2] + (1,) + x.shape[3:])
        got = jnp.take_along_axis(x, idx, axis=2)
        any_turn = jnp.any(batch['turn_mask'][..., 0] > 0, axis=-1)
        m = any_turn.reshape(any_turn.shape + (1,) * (x.ndim - 2))
        return jnp.where(m, got, pad)

    out = dict(batch)
    out['observation'] = jax.tree_util.tree_map(
        lambda x: take(x, 0.0), batch['observation'])
    out['selected_prob'] = take(batch['selected_prob'], 1.0)
    out['action'] = take(batch['action'], 0)
    out['action_mask'] = take(batch['action_mask'], 1e32)
    return out


@pytest.fixture(scope='module')
def wide_batch_and_params():
    random.seed(11)
    env = make_env(ENV_ARGS)
    env.reset()
    # norm_kind='group': the layout identity is a per-sample-model theorem
    # (see module docstring); batch-stats norm is covered separately below
    wrapper = ModelWrapper(GeisterNet(filters=8, drc_layers=2,
                                      drc_repeats=1, norm_kind='group'))
    wrapper.ensure_params(env.observation(0))
    gen = BatchedGenerator(lambda i: make_env(ENV_ARGS), wrapper,
                           _args(True), n_envs=4)
    episodes = []
    for _ in range(400):
        episodes += gen.step()
        if len(episodes) >= 4:
            break
    assert len(episodes) >= 4
    args = _args(True)
    windows = [select_episode(episodes, args) for _ in range(4)]
    return wrapper, make_batch(windows, args)


def _loss_and_grads(wrapper, batch, cfg):
    def init_hidden():
        B = batch['value'].shape[0]
        P = batch['value'].shape[2]
        return wrapper.module.init_hidden((B, P))

    def f(params):
        loss, aux = compute_loss(wrapper.module.apply, params,
                                 init_hidden(), batch, cfg)
        return loss, aux
    (loss, aux), grads = jax.value_and_grad(f, has_aux=True)(wrapper.params)
    return loss, aux, grads


def test_wide_and_compact_layouts_train_identically(wide_batch_and_params):
    wrapper, wide = wide_batch_and_params
    compact = _wide_to_compact(wide)
    # the compact layout really is compact: data leaves have P axis 1
    assert compact['action'].shape[2] == 1
    assert wide['action'].shape[2] == 2

    loss_w, aux_w, grads_w = _loss_and_grads(
        wrapper, wide, LossConfig.from_args(_args(True)))
    loss_c, aux_c, grads_c = _loss_and_grads(
        wrapper, compact, LossConfig.from_args(_args(False)))

    np.testing.assert_allclose(float(loss_w), float(loss_c),
                               rtol=1e-5, atol=1e-6)
    for k in aux_w['losses']:
        np.testing.assert_allclose(
            float(aux_w['losses'][k]), float(aux_c['losses'][k]),
            rtol=1e-5, atol=1e-6, err_msg=k)
    # gradient criterion is RELATIVE to each leaf's own scale (the
    # hbm_experiments parity-gate approach): a fixed absolute band is wrong
    # in both directions — float32 grads of scale ~5 legitimately differ by
    # a few e-6 between the two scan splits, while a tiny-scale leaf could
    # hide a real bug under the same band
    flat_w = jax.tree_util.tree_leaves(grads_w)
    flat_c = jax.tree_util.tree_leaves(grads_c)
    for gw, gc in zip(flat_w, flat_c):
        gw, gc = np.asarray(gw), np.asarray(gc)
        err = float(np.abs(gw - gc).max())
        scale = float(np.abs(gw).max())
        rel = err / max(scale, 1e-6)
        assert rel < 1e-4, \
            'gradient leaf mismatch: max|dw|=%.3g at scale %.3g (rel %.3g)' \
            % (err, scale, rel)


def test_wide_and_compact_no_burn_in(wide_batch_and_params):
    """Same equivalence with burn_in 0 (different scan split)."""
    wrapper, wide = wide_batch_and_params
    compact = _wide_to_compact(wide)
    cfg_w = LossConfig.from_args(_args(True, burn_in=0))
    cfg_c = LossConfig.from_args(_args(False, burn_in=0))
    loss_w, _, _ = _loss_and_grads(wrapper, wide, cfg_w)
    loss_c, _, _ = _loss_and_grads(wrapper, compact, cfg_c)
    np.testing.assert_allclose(float(loss_w), float(loss_c),
                               rtol=1e-5, atol=1e-6)


def test_batch_stats_norm_layouts_differ_by_design(wide_batch_and_params):
    """With BatchStatsNorm (norm_kind='batch') the compact layout's
    statistics exclude the wide layout's zero rows — the losses MUST
    differ; if this ever starts passing with equality, the norm silently
    stopped using batch statistics."""
    _, wide = wide_batch_and_params
    env = make_env(ENV_ARGS)
    env.reset()
    wrapper = ModelWrapper(GeisterNet(filters=8, drc_layers=2,
                                      drc_repeats=1, norm_kind='batch'))
    wrapper.ensure_params(env.observation(0))
    compact = _wide_to_compact(wide)
    loss_w, _, _ = _loss_and_grads(
        wrapper, wide, LossConfig.from_args(_args(True)))
    loss_c, _, _ = _loss_and_grads(
        wrapper, compact, LossConfig.from_args(_args(False)))
    assert np.isfinite(float(loss_w)) and np.isfinite(float(loss_c))
    assert abs(float(loss_w) - float(loss_c)) > 1e-6


def test_norm_kind_env_args_plumbing():
    """env_args {'norm_kind': 'batch'} reaches GeisterNet without a source
    edit (the BENCHMARKS round-5 A/B path)."""
    env = make_env({'env': 'Geister', 'norm_kind': 'batch'})
    assert env.net().norm_kind == 'batch'
    assert make_env(ENV_ARGS).net().norm_kind == 'group'
