"""Partition-rule engine + mesh staging: regex rules -> PartitionSpec ->
NamedSharding, per-shard host->device batch placement (each device receives
1/N of the batch bytes, observable on the ``mesh_shard_bytes_total``
counter), and the sharded train step's numerics against the single-device
step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from handyrl_tpu import telemetry
from handyrl_tpu.parallel import partition
from handyrl_tpu.parallel.mesh import (batch_sharding, make_mesh,
                                       replicated_sharding, shard_batch)


def _tiny_tree():
    return {'params': {'dense': {'kernel': np.zeros((8, 16), np.float32),
                                 'bias': np.zeros((16,), np.float32)},
                       'head': {'kernel': np.zeros((16, 4), np.float32)}},
            'count': np.zeros((), np.int32)}


# ---------------------------------------------------------------------------
# rule matching


def test_default_rules_replicate_everything():
    specs = partition.match_partition_rules(partition.DEFAULT_RULES,
                                            _tiny_tree())
    leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    assert leaves and all(s == P() for s in leaves)


def test_first_matching_rule_wins_and_scalars_replicate():
    rules = ((r'dense/kernel', P(None, 'model')),
             (r'kernel', P('model')),
             (r'.*', P()))
    specs = partition.match_partition_rules(rules, _tiny_tree())
    assert specs['params']['dense']['kernel'] == P(None, 'model')
    assert specs['params']['head']['kernel'] == P('model')   # 2nd rule
    assert specs['params']['dense']['bias'] == P()
    assert specs['count'] == P()      # scalar: replicated regardless


def test_unmatched_leaf_raises_with_its_path():
    with pytest.raises(ValueError, match='dense/bias'):
        partition.match_partition_rules(((r'kernel', P()),), _tiny_tree())


def test_spec_from_entry_config_forms():
    assert partition.spec_from_entry(None) == P()
    assert partition.spec_from_entry([]) == P()
    assert partition.spec_from_entry('data') == P('data')
    assert partition.spec_from_entry(['null', 'model']) == P(None, 'model')
    assert partition.spec_from_entry([None, 'model']) == P(None, 'model')


def test_rules_from_config_appends_catchall():
    args = {'parallel': {'partition_rules': [['kernel', ['model']]]}}
    rules = partition.rules_from_config(args)
    # the user rule survives, the implied catch-all replicates the rest
    specs = partition.match_partition_rules(rules, _tiny_tree())
    assert specs['params']['head']['kernel'] == P('model')
    assert specs['params']['dense']['bias'] == P()
    assert partition.rules_from_config({}) == partition.DEFAULT_RULES
    assert partition.pure_data_parallel(partition.DEFAULT_RULES)
    assert not partition.pure_data_parallel(rules)


def test_tree_shardings_validates_divisibility():
    mesh = make_mesh(jax.devices()[:4], model_parallel=2)  # data 2 x model 2
    shardings = partition.tree_shardings(
        mesh, _tiny_tree(), ((r'kernel', P(None, 'model')), (r'.*', P())))
    ks = shardings['params']['dense']['kernel']
    assert isinstance(ks, NamedSharding) and ks.spec == P(None, 'model')
    assert shardings['count'].spec == P()
    # 3 rows don't divide a 2-wide axis: fail at build time, by name
    bad = {'params': {'odd': {'kernel': np.zeros((3, 4), np.float32)}}}
    with pytest.raises(ValueError, match='odd/kernel'):
        partition.tree_shardings(mesh, bad, ((r'kernel', P('model')),
                                             (r'.*', P())))
    with pytest.raises(ValueError, match='unknown mesh axis'):
        partition.tree_shardings(mesh, _tiny_tree(),
                                 ((r'.*', P('nope')),))


def test_checkpoint_layout_and_describe():
    mesh = make_mesh(jax.devices()[:4])
    layout = partition.checkpoint_layout(mesh, partition.DEFAULT_RULES,
                                         steps=7)
    assert layout['format'] == partition.LAYOUT_FORMAT
    assert layout['mesh'] == {'data': 4, 'model': 1}
    assert layout['devices'] == 4 and layout['steps'] == 7
    assert layout['partition_rules'] == [['.*', []]]
    assert partition.describe_mesh(layout) == 'data=4xmodel=1'
    assert partition.describe_mesh(
        partition.checkpoint_layout(None)) == 'single device'


# ---------------------------------------------------------------------------
# per-shard batch staging (the prefetch-ring fix) + its telemetry contract


def test_shard_batch_transfers_one_nth_per_device():
    mesh = make_mesh(jax.devices()[:4])
    batch = {'observation': np.random.RandomState(0)
             .rand(8, 4, 3).astype(np.float32),
             'action': np.zeros((8, 1), np.int32)}
    total = sum(v.nbytes for v in batch.values())

    counter = telemetry.REGISTRY.counter('mesh_shard_bytes_total')
    mark = counter.value
    dev = shard_batch(mesh, batch)
    staged = counter.value - mark
    # staged bytes == the batch, once — NOT devices x batch
    assert staged == total
    for leaf, host in ((dev['observation'], batch['observation']),
                       (dev['action'], batch['action'])):
        shards = leaf.addressable_shards
        assert len(shards) == 4
        assert all(s.data.nbytes == host.nbytes // 4 for s in shards)
        assert np.array_equal(np.asarray(leaf), host)   # values intact
    # the replicated placement of the same batch really is N x bigger
    repl = jax.device_put(batch['observation'], replicated_sharding(mesh))
    repl_bytes = sum(s.data.nbytes for s in repl.addressable_shards)
    assert repl_bytes == 4 * batch['observation'].nbytes
    assert staged * 4 == repl_bytes + 4 * batch['action'].nbytes


def test_shard_batch_reshards_device_arrays_and_replicates_scalars():
    mesh = make_mesh(jax.devices()[:2])
    dev_leaf = jnp.arange(8.0)
    out = shard_batch(mesh, {'x': dev_leaf, 's': np.float32(3.0)})
    assert out['x'].sharding == batch_sharding(mesh)
    assert out['s'].sharding.spec == P()
    assert float(out['s']) == 3.0


# ---------------------------------------------------------------------------
# sharded train step: rule-built shardings, numerics, mesh portability


def _ttt_step_pieces(B=8, T=4):
    from __graft_entry__ import _synthetic_batch
    from handyrl_tpu.models import build
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.train_step import init_train_state

    module = build('SimpleConv2dModel')
    rng = np.random.RandomState(0)
    batch = _synthetic_batch(B, T, 1, (3, 3, 3), 9, rng)
    params = module.init(jax.random.PRNGKey(0),
                         batch['observation'][:, 0, 0], None)
    cfg = LossConfig(turn_based_training=False, observation=True,
                     policy_target='TD', value_target='TD', gamma=0.9)
    return module, cfg, batch, init_train_state(params)


def test_rule_built_update_step_matches_single_device():
    from handyrl_tpu.ops.train_step import build_update_step, init_train_state

    module, cfg, batch, state = _ttt_step_pieces()
    lr = jnp.asarray(1e-4, jnp.float32)
    mesh = make_mesh(jax.devices()[:4])
    shardings = partition.tree_shardings(mesh, state,
                                         partition.DEFAULT_RULES)
    step = build_update_step(module, cfg, mesh=mesh, donate=False,
                             state_shardings=shardings)
    s_mesh, m_mesh = step(state, shard_batch(mesh, batch), lr)

    step1 = build_update_step(module, cfg, donate=False)
    s_one, m_one = step1(init_train_state(state.params),
                         jax.tree_util.tree_map(jnp.asarray, batch), lr)
    rel = abs(float(m_mesh['total']) - float(m_one['total'])) \
        / abs(float(m_one['total']))
    assert rel < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(s_mesh.params)),
                    jax.tree_util.tree_leaves(jax.device_get(s_one.params))):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)
    # the output state keeps the rule layout: donation round-trips
    assert s_mesh.steps.sharding.spec == P()


def test_state_restores_bit_identical_across_mesh_shapes():
    """Save under a 4-device mesh, restore under 2- and 1-device meshes
    (and a 1-device save restored under 4): params bit-identical, and the
    restored state steps under the new mesh."""
    from flax import serialization
    from handyrl_tpu.ops.train_step import build_update_step
    from handyrl_tpu.utils.fetch import fetch_tree

    module, cfg, batch, state = _ttt_step_pieces()
    lr = jnp.asarray(1e-4, jnp.float32)

    def advance(mesh, state, batch):
        shardings = None
        if mesh is not None:
            shardings = partition.tree_shardings(mesh, state,
                                                 partition.DEFAULT_RULES)
            state = jax.device_put(state, shardings)
            batch = shard_batch(mesh, batch)
        else:
            batch = jax.tree_util.tree_map(jnp.asarray, batch)
        step = build_update_step(module, cfg, mesh=mesh, donate=False,
                                 state_shardings=shardings)
        return step(state, batch, lr)[0]

    mesh4 = make_mesh(jax.devices()[:4])
    stepped = advance(mesh4, state, batch)
    blob = serialization.to_bytes(fetch_tree(stepped))
    host = fetch_tree(stepped)

    for devices in (jax.devices()[:2], jax.devices()[:1], None):
        mesh = make_mesh(devices) if devices and len(devices) > 1 else None
        restored = serialization.from_bytes(host, blob)
        if mesh is not None:
            restored = jax.device_put(
                restored, partition.tree_shardings(
                    mesh, restored, partition.DEFAULT_RULES))
        for a, b in zip(jax.tree_util.tree_leaves(host),
                        jax.tree_util.tree_leaves(fetch_tree(restored))):
            assert np.array_equal(np.asarray(a), np.asarray(b))   # bitwise
        again = advance(mesh, jax.tree_util.tree_map(jnp.asarray, restored)
                        if mesh is None else restored, batch)
        assert int(again.steps) == int(stepped.steps) + 1

    # vice versa: a (1-device) host blob restores under the 4-device mesh
    restored4 = jax.device_put(
        serialization.from_bytes(host, blob),
        partition.tree_shardings(mesh4, host, partition.DEFAULT_RULES))
    assert int(advance(mesh4, restored4, batch).steps) \
        == int(stepped.steps) + 1
