"""Shared test fixtures: synthetic episodes and batch windows."""

import numpy as np

from handyrl_tpu.ops.batch import compress_moments


def turn_based_episode(steps=5, obs_shape=(3, 3, 3), n_actions=9, seed=None):
    """Synthetic 2-player turn-alternating episode: player t%2 acts at step t."""
    rng = np.random.RandomState(seed if seed is not None else 0)
    moments = []
    for t in range(steps):
        turn = t % 2
        m = {key: {0: None, 1: None} for key in
             ('observation', 'selected_prob', 'action_mask', 'action',
              'value', 'reward', 'return')}
        m['observation'][turn] = rng.rand(*obs_shape).astype(np.float32)
        m['selected_prob'][turn] = 0.5
        amask = np.full(n_actions, 1e32, np.float32)
        amask[:3] = 0
        m['action_mask'][turn] = amask
        m['action'][turn] = t % 3
        m['value'][turn] = np.array([0.1 * t], np.float32)
        m['reward'] = {0: 0.0, 1: 0.0}
        m['return'] = {0: 0.25, 1: -0.25}
        m['turn'] = [turn]
        moments.append(m)
    return {
        'args': {'player': [0, 1]}, 'steps': steps,
        'outcome': {0: 1.0, 1: -1.0},
        'moment': compress_moments(moments, compress_steps=2),
    }


def ragged_act_rows(n, n_actions=9, obs_shape=(3, 3, 3), hidden_dim=None,
                    seed=0):
    """Shared ragged-row fixture: ``n`` act requests with mixed legal-action
    counts (1..n_actions legal moves per row), random observations, and —
    when ``hidden_dim`` is set — a per-row recurrent state vector. Used by
    the padding/bucketing tests and the inference-engine tests, so both
    exercise the same raggedness."""
    rng = np.random.RandomState(seed)
    rows = []
    for _ in range(n):
        count = int(rng.randint(1, n_actions + 1))
        legal = sorted(rng.choice(n_actions, size=count,
                                  replace=False).tolist())
        obs = rng.rand(*obs_shape).astype(np.float32)
        hidden = (rng.rand(hidden_dim).astype(np.float32)
                  if hidden_dim else None)
        rows.append({'obs': obs, 'legal': legal, 'hidden': hidden})
    return rows


def train_args(forward_steps=4, burn_in=0, observation=False, turn_based=True):
    return {
        'turn_based_training': turn_based, 'observation': observation,
        'forward_steps': forward_steps, 'burn_in_steps': burn_in,
        'compress_steps': 2, 'maximum_episodes': 100,
        'lambda': 0.7, 'gamma': 0.8,
        'policy_target': 'TD', 'value_target': 'TD',
        'entropy_regularization': 0.1, 'entropy_regularization_decay': 0.1,
    }


def window(ep, start, end, train_start=None, cs=2):
    st_block, ed_block = start // cs, (end - 1) // cs + 1
    return {
        'args': ep['args'], 'outcome': ep['outcome'],
        'moment': ep['moment'][st_block:ed_block], 'base': st_block * cs,
        'start': start, 'end': end,
        'train_start': start if train_start is None else train_start,
        'total': ep['steps'],
    }
