"""Compiled-performance plane: device-memory gauges (stubbed accelerator
stats + the CPU RSS fallback), the steady-state retrace sentinel (counting,
flight-recorder events, warn/abort policies), the dispatch/host_block span
split on a real compiled CPU train step, and the perf_gate.py exit
contract against synthetic benchmarks.jsonl fixtures."""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from handyrl_tpu import telemetry
from handyrl_tpu.model import ModelWrapper  # noqa: F401 (env setup parity)

SCRIPTS = os.path.join(os.path.dirname(__file__), '..', 'scripts')
sys.path.insert(0, os.path.abspath(SCRIPTS))

import perf_gate  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_perf_plane(monkeypatch):
    """Every test starts outside steady state with the plane enabled and
    no env policy override, and leaves the process the same way."""
    monkeypatch.delenv('HANDYRL_TPU_RETRACE', raising=False)
    telemetry.configure_perf_plane(True, 'warn')
    telemetry.clear_steady_state()
    yield
    telemetry.clear_steady_state()
    telemetry.configure_perf_plane(True, 'warn')


# ---------------------------------------------------------------------------
# device-memory plane


class _StubDevice:
    platform = 'tpu'
    id = 3
    device_kind = 'fake-tpu'

    def memory_stats(self):
        return {'bytes_in_use': 6 * 2**30, 'peak_bytes_in_use': 7 * 2**30,
                'bytes_limit': 16 * 2**30}


def test_sample_device_memory_uses_backend_stats():
    rows = telemetry.sample_device_memory(devices=[_StubDevice()])
    assert rows == [{'device': 'tpu:3', 'bytes_in_use': 6 * 2**30,
                     'peak_bytes_in_use': 7 * 2**30,
                     'bytes_limit': 16 * 2**30}]
    snap = telemetry.REGISTRY.snapshot()
    assert snap['gauges']['device_mem_bytes_in_use{device="tpu:3"}'] \
        == 6 * 2**30
    assert snap['gauges']['device_mem_bytes_limit{device="tpu:3"}'] \
        == 16 * 2**30
    assert telemetry.device_memory_utilization(rows) == pytest.approx(6 / 16)


def test_sample_device_memory_cpu_rss_fallback():
    """CPU devices have no memory_stats: ONE process_rss row (all CPU
    "devices" share this process), real RSS and a physical-RAM limit."""
    rows = telemetry.sample_device_memory()   # real jax CPU devices
    assert len(rows) == 1 and rows[0]['device'] == 'process_rss'
    assert rows[0]['bytes_in_use'] > 0
    assert rows[0]['bytes_limit'] > rows[0]['bytes_in_use']
    assert rows[0]['peak_bytes_in_use'] >= rows[0]['bytes_in_use']
    util = telemetry.device_memory_utilization(rows)
    assert 0.0 < util < 1.0
    assert telemetry.perf_status()['device_memory'] == rows


def test_sample_device_memory_disabled_plane_is_inert():
    telemetry.configure_perf_plane(False)
    try:
        assert telemetry.sample_device_memory(devices=[_StubDevice()]) == []
    finally:
        telemetry.configure_perf_plane(True)


def test_hbm_pressure_builtin_alert_fires_on_sustained_ratio():
    rules = [dict(r) for r in telemetry.BUILTIN_ALERTS
             if r['name'] == 'hbm_pressure']
    assert rules, 'hbm_pressure must be in the builtin catalog'
    rule = rules[0]
    rule['for'] = 0.0   # no sustain window in a unit test
    eng = telemetry.AlertEngine([rule])
    telemetry.gauge('device_mem_utilization').set(0.95)
    now = time.time()
    eng.evaluate([telemetry.snapshot()], now=now)
    state = eng.evaluate([telemetry.snapshot()], now=now + 1.0)
    assert 'hbm_pressure' in state['active']
    telemetry.gauge('device_mem_utilization').set(0.0)


# ---------------------------------------------------------------------------
# retrace sentinel


def _fresh_jit():
    return jax.jit(lambda x: x * 3.0)


def _arr(n):
    # device_put, NOT jnp.ones: array construction must not itself compile
    # a program mid-test
    return jax.device_put(np.ones((n,), np.float32))


def test_warmup_compile_does_not_count_then_steady_retrace_does():
    assert telemetry.install_jax_monitoring()
    fn = _fresh_jit()
    fn(_arr(2))                        # warm-up compile, before the mark
    assert telemetry.steady_retrace_count() == 0
    assert telemetry.mark_steady_state('unit test')
    assert telemetry.steady_state_active()
    before = telemetry.REGISTRY.snapshot()['counters'].get(
        'xla_retraces_total', 0)
    fn(_arr(2))                        # cache hit: not a retrace
    assert telemetry.steady_retrace_count() == 0
    fn(_arr(4))                        # new shape: retrace
    assert telemetry.steady_retrace_count() == 1
    snap = telemetry.REGISTRY.snapshot()
    assert snap['counters']['xla_retraces_total'] == before + 1
    assert snap['gauges'].get('xla_steady_state') == 1
    # the flight recorder carries the event with the callable/shape key
    kinds = [e for e in telemetry.recorder().events()
             if e.get('kind') == 'retrace']
    assert kinds and 'retrace' in kinds[-1]['msg']


def test_clear_steady_state_disarms_the_sentinel():
    assert telemetry.install_jax_monitoring()
    telemetry.mark_steady_state()
    telemetry.clear_steady_state()
    assert not telemetry.steady_state_active()
    _fresh_jit()(_arr(6))              # fresh compile after clear
    assert telemetry.steady_retrace_count() == 0
    assert telemetry.REGISTRY.snapshot()['gauges'].get(
        'xla_steady_state') == 0


def test_abort_policy_raises_at_the_jit_call_site(monkeypatch):
    assert telemetry.install_jax_monitoring()
    fn = _fresh_jit()
    fn(_arr(2))
    telemetry.mark_steady_state()
    monkeypatch.setenv('HANDYRL_TPU_RETRACE', 'abort')
    with pytest.raises(telemetry.RetraceError):
        fn(_arr(8))


def test_retrace_policy_env_overrides_config(monkeypatch):
    telemetry.configure_perf_plane(retrace='abort')
    assert telemetry.retrace_policy() == 'abort'
    monkeypatch.setenv('HANDYRL_TPU_RETRACE', 'off')
    assert telemetry.retrace_policy() == 'off'
    monkeypatch.setenv('HANDYRL_TPU_RETRACE', 'bogus')
    assert telemetry.retrace_policy() == 'abort'   # bad env falls through


def test_off_policy_ignores_retraces(monkeypatch):
    assert telemetry.install_jax_monitoring()
    fn = _fresh_jit()
    fn(_arr(2))
    telemetry.mark_steady_state()
    monkeypatch.setenv('HANDYRL_TPU_RETRACE', 'off')
    fn(_arr(10))
    assert telemetry.steady_retrace_count() == 0


def test_retrace_storm_builtin_alert_in_catalog():
    names = [r['name'] for r in telemetry.BUILTIN_ALERTS]
    assert 'retrace_storm' in names


def test_expected_compile_scope_exempts_signature_polymorphic_jits(
        monkeypatch):
    """utils/fetch.py's per-signature packers compile fresh programs by
    design; inside expected_compile() the sentinel books them under
    xla_expected_compiles_total and neither counts nor aborts."""
    assert telemetry.install_jax_monitoring()
    fn = _fresh_jit()
    fn(_arr(2))
    telemetry.mark_steady_state()
    monkeypatch.setenv('HANDYRL_TPU_RETRACE', 'abort')
    before = telemetry.REGISTRY.snapshot()['counters'].get(
        'xla_expected_compiles_total', 0)
    with telemetry.expected_compile('unit test'):
        fn(_arr(12))                   # fresh shape, declared expected
    assert telemetry.steady_retrace_count() == 0
    snap = telemetry.REGISTRY.snapshot()
    assert snap['counters']['xla_expected_compiles_total'] == before + 1
    with pytest.raises(telemetry.RetraceError):
        fn(_arr(14))                   # outside the scope it aborts again


def test_fetch_tree_growth_is_expected_not_a_retrace(monkeypatch):
    """The real fetch path: a metric-set growth (more scalar leaves than
    warm-up saw) must NOT trip the abort policy — the exact failure the
    telemetry smoke exposed."""
    from handyrl_tpu.utils.fetch import fetch_tree
    assert telemetry.install_jax_monitoring()
    fetch_tree({'a': _arr(2), 'b': _arr(3)})       # warm one signature
    telemetry.mark_steady_state()
    monkeypatch.setenv('HANDYRL_TPU_RETRACE', 'abort')
    out = fetch_tree({'a': _arr(2), 'b': _arr(3), 'c': _arr(4)})
    assert telemetry.steady_retrace_count() == 0
    assert isinstance(out['c'], np.ndarray) and out['c'].shape == (4,)


# ---------------------------------------------------------------------------
# dispatch / host_block decomposition


def test_dispatch_host_block_split_on_real_train_step():
    """The trainer's timing seam, exercised with a REAL compiled CPU train
    step: dispatch (async issue) and host_block (block_until_ready) land in
    separate stage_seconds histograms, and the utilization proxy follows."""
    from handyrl_tpu.models.tictactoe import SimpleConv2dModel
    from handyrl_tpu.ops.batch import make_batch
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.train_step import build_update_step, \
        init_train_state
    from handyrl_tpu.utils.timing import StageTimer
    from helpers import turn_based_episode, train_args, window

    eps = [window(turn_based_episode(5, seed=i), 0, 4) for i in range(4)]
    batch = make_batch(eps, train_args(forward_steps=4))
    module = SimpleConv2dModel()
    obs = jax.tree_util.tree_map(lambda o: o[:, 0, 0], batch['observation'])
    params = module.init(jax.random.PRNGKey(0), obs, None)
    state = init_train_state(params)
    step = build_update_step(module, LossConfig(), donate=False)
    lr = jnp.asarray(1e-3, jnp.float32)

    timer = StageTimer(registry=telemetry.REGISTRY)
    with timer.section('dispatch'):
        state, metrics = step(state, batch, lr)
    with timer.section('host_block'):
        jax.block_until_ready(metrics['total'])
    snap = timer.snapshot()
    assert snap['dispatch']['s'] >= 0 and snap['dispatch']['n'] == 1
    assert snap['host_block']['n'] == 1
    hists = telemetry.REGISTRY.snapshot()['hists']
    assert 'stage_seconds{stage="dispatch"}' in hists
    assert 'stage_seconds{stage="host_block"}' in hists

    util = telemetry.utilization_from_stages(snap)
    assert util is not None and 0.0 <= util <= 1.0
    telemetry.set_utilization_proxy(util)
    assert telemetry.REGISTRY.snapshot()['gauges'][
        'device_utilization_proxy'] == pytest.approx(util)
    assert telemetry.perf_status()['device_utilization_proxy'] \
        == pytest.approx(util)


def test_utilization_from_stages_shapes_and_edges():
    assert telemetry.utilization_from_stages(
        {'dispatch': 1.0, 'host_block': 3.0}) == pytest.approx(0.75)
    # StageTimer.snapshot shape ({'s':..., 'n':...}) is accepted too
    assert telemetry.utilization_from_stages(
        {'dispatch': {'s': 1.0, 'n': 2},
         'host_block': {'s': 1.0, 'n': 1}}) == pytest.approx(0.5)
    assert telemetry.utilization_from_stages({}) is None
    assert telemetry.utilization_from_stages({'select': 0.0}) is None


def test_ingest_stage_vocabulary_has_the_decomposed_stages():
    assert 'dispatch' in telemetry.INGEST_STAGES
    assert 'host_block' in telemetry.INGEST_STAGES
    assert 'compute' not in telemetry.INGEST_STAGES
    assert 'drain' not in telemetry.INGEST_STAGES


def test_statusz_render_includes_perf_block():
    out = telemetry.render_status({
        'role': 'learner', 'pid': 1, 'run_id': 'r',
        'perf': {'steady_state': True, 'retraces': 2,
                 'retrace_policy': 'warn',
                 'device_utilization_proxy': 0.8,
                 'device_mem_utilization': 0.4,
                 'device_memory': [
                     {'device': 'process_rss', 'bytes_in_use': 2**30,
                      'peak_bytes_in_use': 2**30, 'bytes_limit': 2**32}]}})
    assert 'steady' in out and 'retraces=2' in out
    assert 'device_util=80%' in out and 'mem_util=40%' in out
    assert 'process_rss' in out


# ---------------------------------------------------------------------------
# perf-regression gate


def _hist(tmp_path, rows, name='hist.jsonl'):
    path = tmp_path / name
    path.write_text('\n'.join(json.dumps(r) for r in rows) + '\n')
    return str(path)


def _row(value, **kw):
    row = {'row': 'bench-ingest', 'value': value, 'backend': 'cpu',
           'geometry': 'headline'}
    row.update(kw)
    return row


def test_perf_gate_passes_fresh_row_within_tolerance(tmp_path):
    hist = _hist(tmp_path, [_row(40.0), _row(42.0), _row(41.0)])
    fresh = _hist(tmp_path, [_row(39.0)], 'fresh.json')
    assert perf_gate.main(['--history', hist, '--fresh', fresh]) == 0


def test_perf_gate_fails_regressed_row(tmp_path):
    hist = _hist(tmp_path, [_row(40.0), _row(42.0), _row(41.0)])
    fresh = _hist(tmp_path, [_row(20.0)], 'fresh.json')
    assert perf_gate.main(['--history', hist, '--fresh', fresh]) == 1


def test_perf_gate_insufficient_history_exit_2_or_allowed(tmp_path):
    hist = _hist(tmp_path, [_row(40.0)])
    fresh = _hist(tmp_path, [_row(5.0)], 'fresh.json')
    argv = ['--history', hist, '--fresh', fresh]
    assert perf_gate.main(argv) == 2
    assert perf_gate.main(argv + ['--allow-insufficient']) == 0


def test_perf_gate_tolerates_pre_v2_rows(tmp_path):
    """Rows without a numeric value (pre-schema-v2 history) are skipped,
    not crashed on, and do not count as history."""
    hist = _hist(tmp_path, [
        {'row': 'bench-ingest', 'note': 'ancient row, no value'},
        {'row': 'bench-ingest', 'value': 'n/a'},
        _row(40.0), _row(42.0)])
    fresh = _hist(tmp_path, [_row(41.0)], 'fresh.json')
    assert perf_gate.main(['--history', hist, '--fresh', fresh]) == 0


def test_perf_gate_degraded_rows_never_gate_or_enter_history(tmp_path):
    # degraded history rows are excluded from the baseline...
    hist = _hist(tmp_path, [_row(40.0), _row(42.0),
                            _row(2.0, degraded=True)])
    fresh = _hist(tmp_path, [_row(39.0)], 'fresh.json')
    assert perf_gate.main(['--history', hist, '--fresh', fresh]) == 0
    # ...and a degraded fresh row is skipped, not diffed against silicon
    deg = _hist(tmp_path, [_row(2.0, degraded=True)], 'deg.json')
    assert perf_gate.main(['--history', hist, '--fresh', deg,
                           '--allow-insufficient']) == 0


def test_perf_gate_newest_history_row_gates_without_fresh(tmp_path):
    hist = _hist(tmp_path, [_row(40.0), _row(42.0), _row(10.0)])
    assert perf_gate.main(['--history', hist]) == 1


def test_perf_gate_tolerance_override_and_baseline_update(tmp_path):
    hist = _hist(tmp_path, [_row(40.0), _row(42.0)])
    fresh = _hist(tmp_path, [_row(30.0)], 'fresh.json')
    # 41 -> 30 is ~-27%: fails at 10% tolerance, passes at 40%
    assert perf_gate.main(['--history', hist, '--fresh', fresh,
                           '--tolerance', 'bench-ingest=10']) == 1
    assert perf_gate.main(['--history', hist, '--fresh', fresh,
                           '--tolerance', 'bench-ingest=40']) == 0
    base = str(tmp_path / 'base.json')
    assert perf_gate.main(['--history', hist, '--fresh', fresh,
                           '--update-baseline', '--baseline', base]) == 0
    pinned = json.loads(open(base).read())
    assert pinned == {'bench-ingest|cpu|headline': 40.0}


def test_perf_gate_cli_entry(tmp_path):
    """The script is runnable as a CI step (python scripts/perf_gate.py)."""
    hist = _hist(tmp_path, [_row(40.0), _row(42.0), _row(41.0)])
    out = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, 'perf_gate.py'),
         '--history', hist], capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'PASS' in out.stdout


# ---------------------------------------------------------------------------
# config plumbing


def test_config_validates_retrace_knobs():
    from handyrl_tpu.config import apply_defaults, validate
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'telemetry': {'retrace': 'sometimes'}}}
    with pytest.raises(AssertionError):
        validate(apply_defaults(raw))
    raw['train_args']['telemetry'] = {'retrace': 'abort',
                                      'retrace_warmup_epochs': 2}
    validate(apply_defaults(raw))


def test_adopt_config_configures_perf_plane():
    telemetry.adopt_config({'telemetry': {'perf_plane': False,
                                          'retrace': 'off'}})
    try:
        assert not telemetry.perf_plane_enabled()
        assert telemetry.retrace_policy() == 'off'
    finally:
        telemetry.configure_perf_plane(True, 'warn')
