"""bfloat16 compute path: learner trains with compute_dtype='bfloat16'
(params stay float32, activations bf16 — the MXU-friendly mode)."""

import jax
import numpy as np

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


def test_learner_bf16_compute(tmp_path):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 8, 'update_episodes': 20, 'minimum_episodes': 20,
            'epochs': 1, 'generation_envs': 8, 'forward_steps': 8,
            'num_batchers': 1, 'compute_dtype': 'bfloat16',
            'model_dir': str(tmp_path / 'models'),
        },
    }
    learner = Learner(args=apply_defaults(raw))
    assert learner.wrapper.module.dtype == jax.numpy.bfloat16
    # params remain float32
    leaf = jax.tree_util.tree_leaves(learner.wrapper.params)[0]
    assert leaf.dtype == np.float32
    learner.run()
    assert learner.model_epoch == 1
    # after training + checkpointing, every param leaf is still float32
    for leaf in jax.tree_util.tree_leaves(learner.wrapper.params):
        assert leaf.dtype == np.float32, leaf.dtype
