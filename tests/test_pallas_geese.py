"""Fused Pallas GeeseNet trunk vs the Flax TorusConv stack: same params,
same outputs, same gradients. These tests run the kernel in interpret
mode; the REAL Mosaic lowering's numerics are probed on-chip by
scripts/hbm_experiments.py variant() (parity row: forward vs the
wrap-pad twin before any timing). N = 2 tiles so a wrong BlockSpec
index-map convention cannot pass."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from handyrl_tpu.models.blocks import TorusConv, to_nhwc
from handyrl_tpu.ops.pallas_geese import (tile_forward, trunk_apply,
                                          trunk_params_from_geesenet)

LAYERS = 3
FILTERS = 16


class Trunk(nn.Module):
    """The GeeseNet stem+blocks in isolation (geese.py __call__ trunk)."""

    @nn.compact
    def __call__(self, obs):
        x = to_nhwc(obs)
        h = nn.relu(TorusConv(FILTERS)(x))
        for _ in range(LAYERS):
            h = nn.relu(h + TorusConv(FILTERS)(h))
        return h


def _setup(N=8, seed=0):
    obs = jax.random.normal(jax.random.PRNGKey(seed), (N, 17, 7, 11))
    trunk = Trunk()
    params = trunk.init(jax.random.PRNGKey(1), obs)
    kp = trunk_params_from_geesenet(params, layers=LAYERS)
    return obs, trunk, params, kp


def test_tile_forward_matches_flax():
    obs, trunk, params, kp = _setup()
    ref = trunk.apply(params, obs)
    got = tile_forward(to_nhwc(obs), *kp, groups=8, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


def test_trunk_apply_interpret_two_tiles():
    obs, trunk, params, kp = _setup()
    ref = trunk.apply(params, obs)
    got = trunk_apply(to_nhwc(obs), *kp, 8, 4, True)   # tile=4, N=8
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


def test_trunk_grads_match_flax():
    obs, trunk, params, kp = _setup()

    def loss_flax(p):
        return (trunk.apply(p, obs) ** 2).mean()

    def loss_kernel(kp_):
        return (trunk_apply(to_nhwc(obs), *kp_, 8, 4, True) ** 2).mean()

    g_ref = trunk_params_from_geesenet(jax.grad(loss_flax)(params),
                                       layers=LAYERS)
    g_got = jax.grad(loss_kernel)(kp)
    for a, b in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_trunk_grad_x_matches():
    obs, trunk, params, kp = _setup()
    x = to_nhwc(obs)
    g_ref = jax.grad(lambda xx: (trunk.apply(
        params, jnp.moveaxis(xx, -1, -3)) ** 2).mean())(x)
    g_got = jax.grad(lambda xx: (trunk_apply(
        xx, *kp, 8, 4, True) ** 2).mean())(x)
    np.testing.assert_allclose(np.asarray(g_ref), np.asarray(g_got),
                               rtol=1e-4, atol=1e-4)


def test_geesenet_pallas_twin():
    """Full GeeseNet (heads included) agrees across torus impls with
    shared params, including a non-tile-divisible batch (pad path)."""
    from handyrl_tpu.models.geese import GeeseNet
    obs = jax.random.normal(jax.random.PRNGKey(8), (5, 17, 7, 11))
    net_pad = GeeseNet(layers=2, filters=16, torus_impl='pad')
    net_pal = GeeseNet(layers=2, filters=16, torus_impl='pallas',
                       pallas_tile=4)
    params = net_pad.init(jax.random.PRNGKey(9), obs)
    assert (jax.tree_util.tree_structure(params) ==
            jax.tree_util.tree_structure(net_pal.init(jax.random.PRNGKey(9),
                                                      obs)))
    out_p = net_pad.apply(params, obs)
    out_k = net_pal.apply(params, obs)
    for k in ('policy', 'value'):
        np.testing.assert_allclose(np.asarray(out_p[k]),
                                   np.asarray(out_k[k]),
                                   rtol=2e-5, atol=2e-5)
    # grads THROUGH the full net and the variables-read routing: the
    # dummy-touch mechanism must not detach params from autodiff
    # (frozen training would pass every forward-only test)
    def ploss(net):
        return lambda p: (net.apply(p, obs)['policy'] ** 2).mean()

    g_p = jax.grad(ploss(net_pad))(params)
    g_k = jax.grad(ploss(net_pal))(params)
    flat_p = jax.tree_util.tree_leaves_with_path(g_p)
    flat_k = dict(jax.tree_util.tree_leaves_with_path(g_k))
    for path, leaf in flat_p:
        got = flat_k[path]
        assert np.abs(np.asarray(got)).max() > 0 or \
            np.abs(np.asarray(leaf)).max() == 0, path
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(got),
                                   rtol=1e-4, atol=1e-4, err_msg=str(path))


def test_bad_tile_rejected():
    obs, _, _, kp = _setup(N=6)
    with pytest.raises(AssertionError):
        trunk_apply(to_nhwc(obs), *kp, 8, 4, True)


def test_trunk_bwd_non_divisible_batch():
    """N with no divisor 8/tile in common (N=12, fwd tile=12): the bwd pass
    must drop to the largest divisor of N <= 8 (here 6) instead of silently
    keeping the full forward tile — gradients stay exact either way."""
    obs, trunk, params, kp = _setup(N=12)

    def loss_flax(p):
        return (trunk.apply(p, obs) ** 2).mean()

    def loss_kernel(kp_):
        return (trunk_apply(to_nhwc(obs), *kp_, 8, 12, True) ** 2).mean()

    g_ref = trunk_params_from_geesenet(jax.grad(loss_flax)(params),
                                       layers=LAYERS)
    g_got = jax.grad(loss_kernel)(kp)
    for a, b in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
