"""Pure-JAX Geister: move-for-move agreement with the host env and
recurrent device-resident generation."""

import random

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from handyrl_tpu.envs import jax_geister as jg
from handyrl_tpu.envs.geister import Environment as HostGeister
from handyrl_tpu.device_generation import DeviceGenerator
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.geister import GeisterNet
from handyrl_tpu.ops.batch import decompress_moments, make_batch, select_episode
from helpers import train_args


def test_full_games_match_host():
    """Drive both implementations with identical random action sequences for
    several full games: legal sets, boards, winners and outcomes agree."""
    rng = random.Random(0)
    for game in range(5):
        host = HostGeister()
        dev = jg.init_state(1)
        guard = 0
        while not host.terminal():
            legal_host = sorted(host.legal_actions())
            legal_dev = sorted(np.flatnonzero(
                np.asarray(jg.legal_mask(dev))[0]).tolist())
            assert legal_dev == legal_host, (game, guard)
            action = rng.choice(legal_host)
            host.play(action)
            dev = jg.step(dev, jnp.asarray([action]))
            guard += 1
            assert guard <= 220
        assert bool(jg.terminal(dev)[0])
        oc = np.asarray(jg.outcome(dev))[0]
        host_oc = host.outcome()
        assert oc[0] == host_oc[0] and oc[1] == host_oc[1], game
        # piece counts agree
        np.testing.assert_array_equal(np.asarray(dev.counts)[0], host.counts)


def test_observation_matches_host():
    rng = random.Random(1)
    host = HostGeister()
    dev = jg.init_state(1)
    for _ in range(12):
        if host.terminal():
            break
        obs_host = host.observation(host.turn())
        obs_dev = jax.tree_util.tree_map(lambda v: np.asarray(v)[0],
                                         jg.observe(dev))
        np.testing.assert_array_equal(obs_dev['scalar'], obs_host['scalar'])
        np.testing.assert_array_equal(obs_dev['board'], obs_host['board'])
        action = rng.choice(host.legal_actions())
        host.play(action)
        dev = jg.step(dev, jnp.asarray([action]))


def test_observer_view_matches_host():
    """observe_as must reproduce host observation(player) for BOTH seats —
    including the observing (non-turn) player's rotated, turn-flag-0 view."""
    rng = random.Random(5)
    host = HostGeister()
    dev = jg.init_state(1)
    for _ in range(12):
        if host.terminal():
            break
        for player in (0, 1):
            obs_host = host.observation(player)
            obs_dev = jax.tree_util.tree_map(
                lambda v: np.asarray(v)[0],
                jg.observe_as(dev, jnp.asarray([player])))
            np.testing.assert_array_equal(obs_dev['scalar'],
                                          obs_host['scalar'])
            np.testing.assert_array_equal(obs_dev['board'],
                                          obs_host['board'])
        action = rng.choice(host.legal_actions())
        host.play(action)
        dev = jg.step(dev, jnp.asarray([action]))


def test_recurrent_device_generation():
    """DRC hidden state carried through the on-device rollout; episodes feed
    the standard (burn-in) batch builder."""
    net = GeisterNet(filters=8, drc_layers=2, drc_repeats=1)
    wrapper = ModelWrapper(net)
    host = HostGeister()
    wrapper.ensure_params(host.observation(0))
    args = train_args(forward_steps=8, burn_in=2)
    args['gamma'] = 0.9
    gen = DeviceGenerator(jg, wrapper, args, n_envs=4, chunk_steps=16, seed=2)

    episodes = []
    for _ in range(12):
        episodes += gen.step_chunk()
        if len(episodes) >= 2:
            break
    assert len(episodes) >= 2

    ep = episodes[0]
    moments = decompress_moments(ep['moment'])
    assert len(moments) == ep['steps']
    # the host env pays -0.01/ply to both players; the device path must too,
    # and the stored discounted returns must reflect it
    for m in moments:
        assert m['reward'][0] == pytest.approx(-0.01)
        assert m['reward'][1] == pytest.approx(-0.01)
    assert moments[-1]['return'][0] == pytest.approx(-0.01)
    assert moments[0]['return'][0] < -0.01
    # replay recorded actions through the host env (setup plies included)
    host = HostGeister()
    host.reset()
    for m in moments:
        player = m['turn'][0]
        action = m['action'][player]
        assert action in host.legal_actions(), action
        host.play(action)
    assert host.terminal()
    assert host.outcome() == ep['outcome']

    batch = make_batch([select_episode(episodes, args) for _ in range(2)], args)
    assert batch['observation']['board'].shape[:3] == (2, 10, 1)
    assert np.isfinite(np.asarray(batch['selected_prob'])).all()
