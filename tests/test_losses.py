"""Loss pipeline tests: forward masking/turn-gather, RNN hidden gating,
burn-in stop-gradient, and the compiled update step (single device + 8-device
mesh)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from flax import linen as nn

from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.tictactoe import SimpleConv2dModel
from handyrl_tpu.ops.batch import make_batch
from handyrl_tpu.ops.losses import LossConfig, compute_loss, forward_prediction
from handyrl_tpu.ops.train_step import build_update_step, init_train_state
from handyrl_tpu.parallel.mesh import make_mesh, shard_batch

from helpers import turn_based_episode, train_args, window


def _ttt_batch(B=4, steps=5, fs=4):
    eps = [window(turn_based_episode(steps, seed=i), 0, min(fs, steps))
           for i in range(B)]
    return make_batch(eps, train_args(forward_steps=fs))


def _params(module, batch):
    obs = jax.tree_util.tree_map(lambda o: o[:, 0, 0], batch['observation'])
    return module.init(jax.random.PRNGKey(0), obs, None)


def test_forward_prediction_turn_gather_and_masks():
    """Stub net with known outputs: verify turn-gather and mask algebra."""
    batch = _ttt_batch(B=2)

    def stub_apply(params, obs, hidden):
        s = obs.reshape(obs.shape[0], -1).sum(-1, keepdims=True)
        return {'policy': jnp.tile(s, (1, 9)), 'value': jnp.tanh(s)}

    cfg = LossConfig()
    out = forward_prediction(stub_apply, None, None, batch, cfg)
    B, T = batch['action'].shape[:2]
    # policy: (B,T,1,9) after turn-gather, minus action mask
    assert out['policy'].shape == (B, T, 1, 9)
    obs_sum = np.asarray(batch['observation']).reshape(B, T, -1).sum(-1)
    want = obs_sum[..., None, None] * np.asarray(batch['turn_mask']).sum(2, keepdims=True) \
        - np.asarray(batch['action_mask'])
    np.testing.assert_allclose(np.asarray(out['policy']), want, rtol=1e-4)
    # value: broadcast over P then masked by omask -> zero where not observed
    assert out['value'].shape == (B, T, 2, 1)
    omask = np.asarray(batch['observation_mask'])
    assert np.all(np.asarray(out['value'])[omask == 0] == 0)


def test_compute_loss_finite_and_grads_flow():
    batch = _ttt_batch()
    module = SimpleConv2dModel()
    params = _params(module, batch)
    cfg = LossConfig()

    def loss_fn(p):
        total, aux = compute_loss(module.apply, p, None, batch, cfg)
        return total, aux

    (total, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    assert np.isfinite(float(total))
    for k in ('p', 'v', 'ent', 'total'):
        assert np.isfinite(float(aux['losses'][k])), k
    gnorm = jax.tree_util.tree_reduce(
        lambda a, l: a + float(jnp.abs(l).sum()), grads, 0.0)
    assert gnorm > 0
    assert float(aux['data_count']) == float(np.asarray(batch['turn_mask']).sum())


@pytest.mark.parametrize('pt,vt', [('TD', 'TD'), ('UPGO', 'VTRACE'), ('MC', 'MC')])
def test_loss_all_target_algorithms(pt, vt):
    batch = _ttt_batch(B=2)
    module = SimpleConv2dModel()
    params = _params(module, batch)
    cfg = LossConfig(policy_target=pt, value_target=vt)
    total, _ = compute_loss(module.apply, params, None, batch, cfg)
    assert np.isfinite(float(total))


class TinyRNN(nn.Module):
    """Minimal recurrent net over (3,3,3) obs for RNN-path tests."""
    features: int = 4

    def init_hidden(self, batch_shape=()):
        return (jnp.zeros(tuple(batch_shape) + (self.features,)),)

    @nn.compact
    def __call__(self, obs, hidden):
        x = obs.reshape(obs.shape[:-3] + (-1,))
        if hidden is None:
            hidden = self.init_hidden(x.shape[:-1])
        h_prev = hidden[0]
        h = jnp.tanh(nn.Dense(self.features)(x) + nn.Dense(self.features)(h_prev))
        policy = nn.Dense(9)(h)
        value = jnp.tanh(nn.Dense(1)(h))
        return {'policy': policy, 'value': value, 'hidden': (h,)}


def _rnn_setup(burn_in=0, fs=4, steps=6):
    eps = [window(turn_based_episode(steps, seed=i), 0, min(fs + burn_in, steps),
                  train_start=burn_in)
           for i in range(2)]
    args = train_args(forward_steps=fs, burn_in=burn_in)
    batch = make_batch(eps, args)
    module = TinyRNN()
    obs = jax.tree_util.tree_map(lambda o: o[:, 0, 0], batch['observation'])
    params = module.init(jax.random.PRNGKey(1), obs, None)
    B, P = batch['value'].shape[0], batch['value'].shape[2]
    hidden = module.init_hidden((B, P))
    return module, params, hidden, batch, args


def test_rnn_forward_and_loss():
    module, params, hidden, batch, args = _rnn_setup()
    cfg = LossConfig.from_args(args)
    total, aux = compute_loss(module.apply, params, hidden, batch, cfg)
    assert np.isfinite(float(total))
    grads = jax.grad(lambda p: compute_loss(module.apply, p, hidden, batch, cfg)[0])(params)
    flat = jax.tree_util.tree_leaves(grads)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)
    assert any(np.abs(np.asarray(g)).sum() > 0 for g in flat)


def test_rnn_burn_in_matches_T_slicing():
    """With burn-in, loss terms only cover the main window; output time length
    must equal forward_steps after slicing."""
    module, params, hidden, batch, args = _rnn_setup(burn_in=2, fs=3, steps=6)
    cfg = LossConfig.from_args(args)
    assert batch['observation'].shape[1] == 5   # burn_in + forward
    out = forward_prediction(module.apply, params, hidden, batch, cfg)
    assert out['policy'].shape[1] == 5          # full window, burn-in rows zeroed
    total, aux = compute_loss(module.apply, params, hidden, batch, cfg)
    assert np.isfinite(float(total))


def test_compute_loss_off_policy_diagnostics():
    """The learning-dynamics aux: rho/c clip counts and importance-ratio
    moments, summed over acting pairs so data_count normalizes them."""
    batch = _ttt_batch()
    module = SimpleConv2dModel()
    params = _params(module, batch)
    _total, aux = compute_loss(module.apply, params, None, batch,
                               LossConfig())
    diag = aux['diag']
    dcnt = float(aux['data_count'])
    for key in ('rho_clip', 'c_clip', 'rho_sum', 'rho_sq_sum'):
        assert np.isfinite(float(diag[key])), key
    # clip fractions are counts of acting pairs: within [0, data_count]
    assert 0.0 <= float(diag['rho_clip']) <= dcnt
    assert 0.0 <= float(diag['c_clip']) <= dcnt
    # the ratio's second moment dominates its first (Jensen)
    assert float(diag['rho_sq_sum']) >= 0.0
    assert float(diag['rho_sum']) > 0.0


def test_update_step_emits_diag_metrics():
    """diag_* metrics (incl. the global grad norm) ride the compiled step's
    metric dict — and stay off the loss-line keys (no 'diag_' prefix there
    would leak into the printed reference format)."""
    batch = _ttt_batch(B=4)
    module = SimpleConv2dModel()
    state = init_train_state(_params(module, batch))
    step = build_update_step(module, LossConfig(), donate=False)
    _state2, metrics = step(state, batch, jnp.asarray(1e-3, jnp.float32))
    for key in ('diag_grad_norm', 'diag_rho_clip', 'diag_rho_sum'):
        assert key in metrics, sorted(metrics)
        assert np.isfinite(float(metrics[key])), key
    assert float(metrics['diag_grad_norm']) > 0


def test_update_step_single_device():
    batch = _ttt_batch(B=4)
    module = SimpleConv2dModel()
    params = _params(module, batch)
    state = init_train_state(params)
    step = build_update_step(module, LossConfig(), donate=False)
    lr = jnp.asarray(1e-3, jnp.float32)
    state2, metrics = step(state, batch, lr)
    assert int(state2.steps) == 1
    assert np.isfinite(float(metrics['total']))
    # params changed
    diff = jax.tree_util.tree_reduce(
        lambda a, pq: a + float(jnp.abs(pq).sum()),
        jax.tree_util.tree_map(lambda a, b: a - b, state.params, state2.params), 0.0)
    assert diff > 0


def test_update_step_8_device_mesh():
    """The full data-parallel path on the virtual 8-device CPU mesh."""
    assert len(jax.devices()) == 8, 'conftest must force 8 virtual devices'
    mesh = make_mesh()
    batch = _ttt_batch(B=8)
    module = SimpleConv2dModel()
    params = _params(module, batch)
    state = init_train_state(params)
    step = build_update_step(module, LossConfig(), mesh=mesh, donate=False)
    sbatch = shard_batch(mesh, batch)
    state2, metrics = step(state, sbatch, jnp.asarray(1e-3, jnp.float32))
    assert np.isfinite(float(metrics['total']))
    # sharded-batch result must match the single-device program
    step1 = build_update_step(module, LossConfig(), donate=False)
    _, metrics1 = step1(state, batch, jnp.asarray(1e-3, jnp.float32))
    np.testing.assert_allclose(float(metrics['total']), float(metrics1['total']),
                               rtol=2e-3)

def test_update_step_with_target_network():
    """IMPACT clipped target network (streaming.target_clip): the 4-arg
    compiled step runs, emits diag_target_* metrics, and — with the target
    an exact copy of the live params and target_clip == clip_rho — computes
    the same loss as the 3-arg step (rhos_tgt == rhos)."""
    batch = _ttt_batch(B=4)
    module = SimpleConv2dModel()
    params = _params(module, batch)
    state = init_train_state(params)
    cfg = LossConfig(target_clip=1.0)
    step = build_update_step(module, cfg, donate=False, use_target=True)
    lr = jnp.asarray(1e-3, jnp.float32)
    target = jax.tree_util.tree_map(jnp.copy, params)
    state2, metrics = step(state, batch, lr, target)
    for key in ('diag_target_clip', 'diag_target_ratio_sum',
                'diag_target_gap_sum'):
        assert key in metrics, sorted(metrics)
        assert np.isfinite(float(metrics[key])), key
    # fresh sync: the live policy IS the target -> zero log-prob gap
    np.testing.assert_allclose(float(metrics['diag_target_gap_sum']), 0.0,
                               atol=1e-5)
    base = build_update_step(module, LossConfig(), donate=False)
    _, metrics0 = base(state, batch, lr)
    np.testing.assert_allclose(float(metrics['total']),
                               float(metrics0['total']), rtol=1e-5)

    # a LAGGED target (one update old) changes the targets but stays finite,
    # and the policy gradient still flows through the live params
    state3, metrics_lag = step(state2, batch, lr, target)
    assert np.isfinite(float(metrics_lag['total']))
    assert int(state3.steps) == 2
    assert abs(float(metrics_lag['diag_target_gap_sum'])) > 0


def test_update_step_target_network_on_mesh():
    """The 4-arg program's mesh shardings: target params replicate like
    the state and the sharded result matches the single-device program."""
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    batch = _ttt_batch(B=8)
    module = SimpleConv2dModel()
    state = init_train_state(_params(module, batch))
    cfg = LossConfig(target_clip=1.0)
    target = jax.tree_util.tree_map(jnp.copy, state.params)
    lr = jnp.asarray(1e-3, jnp.float32)
    step = build_update_step(module, cfg, mesh=mesh, donate=False,
                             use_target=True)
    _, metrics = step(state, shard_batch(mesh, batch), lr, target)
    step1 = build_update_step(module, cfg, donate=False, use_target=True)
    _, metrics1 = step1(state, batch, lr, target)
    np.testing.assert_allclose(float(metrics['total']),
                               float(metrics1['total']), rtol=2e-3)
