"""Model zoo tests: output shapes, hidden-state carry, snapshot round-trip."""

import numpy as np

from handyrl_tpu.model import ModelWrapper, RandomModel
from handyrl_tpu.models import build
from handyrl_tpu.envs.tictactoe import Environment as TicTacToe


def test_simple_conv2d_shapes():
    env = TicTacToe()
    wrapper = ModelWrapper(env.net())
    obs = env.observation(0)
    out = wrapper.inference(obs)
    assert out['policy'].shape == (9,)
    assert out['value'].shape == (1,)
    assert -1.0 <= float(out['value'][0]) <= 1.0
    assert 'hidden' not in out


def test_batch_inference_matches_single():
    env = TicTacToe()
    wrapper = ModelWrapper(env.net())
    obs = env.observation(0)
    single = wrapper.inference(obs)
    batched = wrapper.batch_inference(np.stack([obs, obs]))
    # B=1 and B=2 are different XLA programs; allow cross-compile numeric drift
    np.testing.assert_allclose(np.asarray(batched['policy'])[0], single['policy'], atol=1e-2)
    np.testing.assert_allclose(np.asarray(batched['policy'])[0],
                               np.asarray(batched['policy'])[1], atol=1e-6)


def test_geister_net_hidden_carry():
    net = build('GeisterNet')
    wrapper = ModelWrapper(net)
    rng = np.random.RandomState(0)
    obs = {'scalar': rng.rand(18).astype(np.float32),
           'board': rng.rand(7, 6, 6).astype(np.float32)}
    hidden = wrapper.init_hidden()
    out = wrapper.inference(obs, hidden)
    assert out['policy'].shape == (4 * 36 + 70,)
    assert out['value'].shape == (1,)
    assert out['return'].shape == (1,)
    hs, cs = out['hidden']
    assert len(hs) == 3 and hs[0].shape == (6, 6, 32)
    # state must evolve under repeated observation
    out2 = wrapper.inference(obs, out['hidden'])
    assert not np.allclose(hs[0], out2['hidden'][0][0])


def test_geese_net_shapes():
    net = build('GeeseNet')
    wrapper = ModelWrapper(net)
    obs = np.zeros((17, 7, 11), np.float32)
    obs[0, 3, 5] = 1.0  # own head
    out = wrapper.inference(obs)
    assert out['policy'].shape == (4,)
    assert out['value'].shape == (1,)


def test_snapshot_roundtrip():
    env = TicTacToe()
    obs = env.observation(0)
    w1 = ModelWrapper(env.net(), seed=7)
    p1 = w1.inference(obs)['policy']
    snap = w1.snapshot()
    assert snap['architecture'] == 'SimpleConv2dModel'
    w2 = ModelWrapper.from_snapshot(snap, obs)
    np.testing.assert_allclose(w2.inference(obs)['policy'], p1, atol=1e-6)


def test_random_model_zero_outputs():
    env = TicTacToe()
    wrapper = ModelWrapper(env.net())
    rm = RandomModel(wrapper, env.observation(0))
    out = rm.inference()
    assert np.all(out['policy'] == 0) and out['policy'].shape == (9,)
    assert np.all(out['value'] == 0)
