"""Mesh-portable checkpoints: the layout manifest that rides next to every
checkpoint (utils/fs.py layout_path, parallel/partition.py
checkpoint_layout), restore across DIFFERENT device counts with
bit-identical params and monotonic step counts, and the corrupt-manifest
fallback through the PR 4 newest-valid path.

The e2e legs spawn learners with different XLA virtual-device counts (the
flag must precede jax import, hence subprocesses): a run checkpointed under
a 4-device mesh resumes under a 2-device mesh and keeps training.
"""

import hashlib
import io
import json
import multiprocessing as mp
import os

import pytest

from handyrl_tpu.config import apply_defaults

pytestmark = []


def _args(model_dir, epochs, restart=0, metrics=''):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 8, 'update_episodes': 16, 'minimum_episodes': 16,
            'epochs': epochs, 'generation_envs': 8, 'forward_steps': 4,
            'num_batchers': 1, 'model_dir': model_dir,
            'restart_epoch': restart, 'metrics_jsonl': metrics,
        },
    }
    return apply_defaults(raw)


def _value_sha1(params):
    """Order-independent hash of the raw param VALUES (leaf bytes in
    sorted-path order) — serialization byte order differs between a fresh
    template and a trained tree, the values are the contract."""
    import jax
    import numpy as np
    h = hashlib.sha1()
    for path, leaf in sorted(jax.tree_util.tree_flatten_with_path(params)[0],
                             key=lambda kv: str(kv[0])):
        h.update(str(path).encode())
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def _learner_child(args, device_count, report_path):
    # the virtual-device count must be pinned BEFORE jax imports; spawned
    # children also stay off the persistent compile cache (jaxlib 0.4.x CPU
    # resume-deserialization corruption, see test_resume)
    os.environ['XLA_FLAGS'] = \
        '--xla_force_host_platform_device_count=%d' % device_count
    os.environ['JAX_PLATFORMS'] = 'cpu'
    os.environ['HANDYRL_TPU_NO_COMPILE_CACHE'] = '1'
    import contextlib
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.train import Learner

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        ln = Learner(args=args)
        rep = {
            'devices': jax.device_count(),
            'mesh': dict(ln.trainer.mesh.shape) if ln.trainer.mesh else None,
            'steps_at_start': ln.trainer.steps,
            'epoch_at_start': ln.model_epoch,
            'params_sha1_at_start': _value_sha1(ln.wrapper.params),
        }
        ln.run()
    rep['epoch'] = ln.model_epoch
    rep['steps'] = ln.trainer.steps
    rep['params_sha1_at_end'] = _value_sha1(ln.wrapper.params)
    rep['stdout'] = buf.getvalue()
    with open(report_path, 'w') as f:
        json.dump(rep, f)


def _run_learner(args, device_count, tmp, tag, timeout=420):
    report = os.path.join(tmp, 'mesh_ckpt_%s.json' % tag)
    ctx = mp.get_context('spawn')
    proc = ctx.Process(target=_learner_child,
                       args=(args, device_count, report))
    proc.start()
    proc.join(timeout=timeout)
    if proc.is_alive():
        proc.terminate()
        pytest.fail('learner child %r timed out' % tag)
    assert proc.exitcode == 0, 'child %r exited %s' % (tag, proc.exitcode)
    with open(report) as f:
        return json.load(f)


@pytest.mark.timeout(900)
def test_checkpoint_restores_across_mesh_shapes(tmp_path):
    """Save under a 4-device mesh; resume under a 2-device mesh: the resumed
    params are bit-identical to the written checkpoint, the step counter
    continues monotonically, and the mesh change is logged, not silent."""
    from handyrl_tpu.utils.fs import read_layout_manifest

    model_dir = str(tmp_path / 'models')
    metrics = str(tmp_path / 'metrics.jsonl')

    a = _run_learner(_args(model_dir, epochs=2, metrics=metrics), 4,
                     str(tmp_path), 'save4')
    assert a['mesh'] == {'data': 4, 'model': 1}
    assert a['epoch'] == 2 and a['steps'] > 0

    # the manifest describes the writing mesh, next to the CRC sidecar
    state_path = os.path.join(model_dir, 'trainer_state.ckpt')
    layout, reason = read_layout_manifest(state_path)
    assert reason == 'ok'
    assert layout['mesh'] == {'data': 4, 'model': 1}
    assert layout['devices'] == 4
    assert layout['partition_rules'] == [['.*', []]]

    b = _run_learner(_args(model_dir, epochs=4, restart=-1,
                           metrics=metrics), 2, str(tmp_path), 'resume2')
    assert b['mesh'] == {'data': 2, 'model': 1}
    assert b['epoch_at_start'] == 2
    # bit-identical resumed params: what the 4-device run ended with is
    # exactly what the 2-device run starts from
    assert b['params_sha1_at_start'] == a['params_sha1_at_end']
    # the trainer state resumed too (not a params-only fallback): the
    # resumed step counter equals what epoch 2's checkpoint recorded (the
    # trainer thread's post-handover steps are uncheckpointed by design)
    with open(metrics) as f:
        rows = [json.loads(line) for line in f]
    a_rows = rows[:2]
    assert b['steps_at_start'] == a_rows[-1]['steps'] > 0
    assert 'resumed trainer state' in b['stdout']
    assert 'mesh-portable restore' in b['stdout']
    assert b['epoch'] == 4 and b['steps'] > b['steps_at_start']

    # the rewritten manifest now describes the NEW mesh
    layout, reason = read_layout_manifest(state_path)
    assert reason == 'ok' and layout['mesh'] == {'data': 2, 'model': 1}

    # metrics_jsonl: epoch/step counts monotonic across the mesh change
    steps_seq = [int(r['steps']) for r in rows]
    epochs_seq = [int(r['epoch']) for r in rows]
    assert steps_seq == sorted(steps_seq) and len(rows) >= 4
    assert epochs_seq == [1, 2, 3, 4]


def _write_fake_checkpoints(model_dir, layouts):
    """Numbered TicTacToe checkpoints with CRC sidecars and the given
    per-epoch layout bytes (None = no manifest)."""
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.model import ModelWrapper
    from handyrl_tpu.utils.fs import checksummed_write_bytes, layout_path

    env = make_env({'env': 'TicTacToe'})
    env.reset()
    wrapper = ModelWrapper(env.net(), seed=3)
    wrapper.ensure_params(env.observation(env.players()[0]))
    raw = wrapper.params_bytes()
    os.makedirs(model_dir, exist_ok=True)
    for epoch, layout_bytes in layouts.items():
        path = os.path.join(model_dir, '%d.ckpt' % epoch)
        checksummed_write_bytes(path, raw)
        if layout_bytes is not None:
            with open(layout_path(path), 'wb') as f:   # deliberately raw
                f.write(layout_bytes)
    return raw


def test_corrupt_manifest_falls_back_to_newest_valid(tmp_path):
    """A PRESENT but unparsable layout manifest disqualifies its checkpoint
    exactly like a CRC failure: resume falls back to the previous valid
    epoch (the PR 4 path); a corrupt trainer_state manifest degrades to a
    params-only resume instead of trusting the pair."""
    from handyrl_tpu import telemetry
    from handyrl_tpu.train import Learner
    from handyrl_tpu.utils.fs import (checksummed_write_bytes,
                                      layout_path, read_layout_manifest)

    model_dir = str(tmp_path / 'models')
    good = json.dumps({'format': 1, 'mesh': None, 'devices': 1,
                       'processes': 1, 'partition_rules': [['.*', []]]}
                      ).encode()
    _write_fake_checkpoints(model_dir, {1: good, 2: b'{not json'})

    # a corrupt trainer_state manifest must force the params-only path
    state_path = os.path.join(model_dir, 'trainer_state.ckpt')
    checksummed_write_bytes(state_path, b'\x00' * 64)
    with open(layout_path(state_path), 'wb') as f:
        f.write(b'\xff\xfe garbage')
    assert read_layout_manifest(state_path) == (None, 'unparsable')

    fallbacks = telemetry.REGISTRY.counter('guard_ckpt_fallbacks_total')
    mark = fallbacks.value
    args = _args(model_dir, epochs=0, restart=-1)
    ln = Learner(args=args)
    # epoch 2's corrupt manifest was skipped; epoch 1 resumed
    assert ln.model_epoch == 1
    # trainer_state pair untrusted: optimizer restarted fresh
    assert ln.trainer.steps == 0
    assert fallbacks.value >= mark + 2
    ln.shutdown()


def test_missing_manifest_is_legacy_ok(tmp_path):
    """Checkpoints from before the manifest era (no .layout file) stay
    loadable — reason 'missing', resume proceeds."""
    from handyrl_tpu.train import Learner
    from handyrl_tpu.utils.fs import read_layout_manifest

    model_dir = str(tmp_path / 'models')
    _write_fake_checkpoints(model_dir, {3: None})
    assert read_layout_manifest(
        os.path.join(model_dir, '3.ckpt')) == (None, 'missing')
    ln = Learner(args=_args(model_dir, epochs=0, restart=-1))
    assert ln.model_epoch == 3
    ln.shutdown()
