"""Streaming partial-episode ingest (streaming.py): chunk reassembly is
byte-identical to whole-episode ingest under fuzzed window sizes and
arrival orders, re-issued attempts merge without double-counting, the
ledger journal + episode spool round-trip the chunk book across a SIGKILL,
and the staleness-aware sampler's off path is RNG-sequence-identical to
the pre-streaming sampler."""

import random

import numpy as np
import pytest

from handyrl_tpu.connection import pack as conn_pack
from handyrl_tpu.connection import unpack as conn_unpack
from handyrl_tpu.environment import make_env
from handyrl_tpu.fault import LedgerJournal, TaskLedger
from handyrl_tpu.generation import Generator, build_chunk
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.ops.batch import decompress_moments, select_episode
from handyrl_tpu.spool import EpisodeSpool
from handyrl_tpu.streaming import ChunkAssembler, chunk_key


def _args(chunk_steps=4, compress_steps=4, **stream):
    s = {'enabled': True, 'chunk_steps': chunk_steps}
    s.update(stream)
    return {'observation': False, 'gamma': 0.8,
            'compress_steps': compress_steps, 'seed': 11, 'streaming': s}


@pytest.fixture(scope='module')
def wrapper():
    env = make_env({'env': 'TicTacToe'})
    env.reset()
    w = ModelWrapper(env.net())
    w.ensure_params(env.observation(0))
    return w


def _gen_args(sample_key, task_id=7):
    return {'role': 'g', 'player': [0, 1], 'model_id': {0: 1, 1: 1},
            'sample_key': sample_key, 'task_id': task_id}


def _generate(wrapper, args, sample_key, task_id=7, stream=True):
    """One TicTacToe episode under the purity contract; returns the whole
    record (stream=False) or the emitted chunk list (stream=True)."""
    env = make_env({'env': 'TicTacToe'})
    gen = Generator(env, args)
    if not stream:
        rec = gen.generate({0: wrapper, 1: wrapper},
                           _gen_args(sample_key, task_id))
        assert rec is not None and not rec.get('streamed')
        return rec
    chunks = []
    summary = gen.generate({0: wrapper, 1: wrapper},
                           _gen_args(sample_key, task_id),
                           emit=chunks.append)
    assert summary is not None and summary.get('streamed')
    assert summary['steps'] == sum(c['steps'] for c in chunks)
    return chunks


def _assemble(args, chunks, check_finite=True):
    asm = ChunkAssembler(args, check_finite=check_finite)
    result = None
    for c in chunks:
        res = asm.add(c)
        if res['status'] == 'complete':
            result = res
    return asm, result


def _canonical_moment_bytes(rec):
    """The training-visible bytes of a record's trajectory: one pickle of
    the decoded moment stream. pickle re-encoding is a fixed point after
    one decode (memo layout settles), so streamed reassembly and
    whole-episode ingest agree on these bytes exactly — the raw bz2 block
    bytes may differ only in pickle memo layout (numpy dtype sharing in
    the worker's fresh objects), never in content."""
    import pickle
    return pickle.dumps(decompress_moments(rec['moment']))


def _assert_records_byte_identical(a, b):
    assert a['steps'] == b['steps']
    assert a['outcome'] == b['outcome']
    assert a['args'] == b['args']
    assert len(a['moment']) == len(b['moment'])   # same block grid
    assert _canonical_moment_bytes(a) == _canonical_moment_bytes(b)


# ---------------------------------------------------------------------------
# reassembly byte-identity


def test_streamed_chunks_reassemble_byte_identically(wrapper):
    args = _args(chunk_steps=4, compress_steps=4)
    whole = _generate(wrapper, args, sample_key=100, stream=False)
    chunks = _generate(wrapper, args, sample_key=100)
    assert chunks[-1]['final'] and chunks[-1]['outcome'] is not None
    # non-final chunks carry no outcome and unfilled returns
    for c in chunks[:-1]:
        assert not c['final'] and c['outcome'] is None
        for m in decompress_moments(c['moment']):
            assert all(v is None for v in m['return'].values())
    _, res = _assemble(args, chunks)
    assert res is not None and res['record'] is not None
    _assert_records_byte_identical(res['record'], whole)
    # the buffer entry was swapped into the finished record in place
    assert res['entry']['moment'] == res['record']['moment']
    assert 'partial' not in res['entry']


def test_fuzz_window_sizes_and_arrival_orders(wrapper):
    rng = random.Random(17)
    for trial in range(12):
        cs = rng.choice([1, 2, 3])
        T = cs * rng.randint(1, 4)
        args = _args(chunk_steps=T, compress_steps=cs)
        skey = 1000 + trial
        whole = _generate(wrapper, args, sample_key=skey, stream=False)
        chunks = _generate(wrapper, args, sample_key=skey)
        shuffled = list(chunks)
        rng.shuffle(shuffled)
        asm, res = _assemble(args, shuffled)
        assert res is not None, 'assembly never completed (trial %d)' % trial
        _assert_records_byte_identical(res['record'], whole)
        assert asm.open_count() == 0


def test_reissued_attempt_merges_without_double_count(wrapper):
    """Purity: a re-issued attempt regenerates byte-identical chunks under
    the same sample_key; the ledger screen admits only the missing ones and
    the assembly completes exactly once."""
    args = _args(chunk_steps=2, compress_steps=2)
    first = _generate(wrapper, args, sample_key=555, task_id=1)
    again = _generate(wrapper, args, sample_key=555, task_id=2)
    assert len(first) == len(again) >= 2
    for a, b in zip(first, again):
        assert a['moment'] == b['moment']   # the byte-identity the screen rests on

    ledger = TaskLedger(deadline=60)
    asm = ChunkAssembler(args)
    # the first attempt dies after delivering only its first chunk
    admitted = ledger.admit_chunks([first[0]])
    assert len(admitted) == 1
    for c in admitted:
        asm.add(c)
    # the re-issued attempt replays the WHOLE episode
    admitted = ledger.admit_chunks(again)
    assert len(admitted) == len(again) - 1   # chunk 0 screens as duplicate
    completions = [asm.add(c) for c in admitted]
    done = [r for r in completions if r['status'] == 'complete']
    assert len(done) == 1 and done[0]['record'] is not None
    key = chunk_key(first[0])
    ledger.complete_chunked(key, done[0]['final_args'].get('task_id'))
    # post-completion stragglers (resend-buffer replays) all screen out
    assert ledger.admit_chunks(first) == []
    assert ledger.stats['duplicates'] >= len(first) + 1


# ---------------------------------------------------------------------------
# partial exposure / staleness bookkeeping


def test_partial_entry_grows_in_place_then_finalizes(wrapper):
    args = _args(chunk_steps=2, compress_steps=2)
    chunks = _generate(wrapper, args, sample_key=42)
    assert len(chunks) >= 3
    asm = ChunkAssembler(args)
    res0 = asm.add(chunks[0], mark=10)
    assert res0['status'] == 'open' and res0['new']
    entry = res0['entry']
    assert entry['partial'] and entry['steps'] == chunks[0]['steps']
    assert set(entry['outcome'].values()) == {0.0}   # provisional
    assert len(entry['chunk_recv']) == 1
    assert asm.min_open_mark() == 10

    res1 = asm.add(chunks[1], mark=11)
    assert res1['status'] in ('open', 'complete')
    assert res1['entry'] is entry and not res1['new']
    assert entry['steps'] == chunks[0]['steps'] + chunks[1]['steps']
    assert len(entry['chunk_recv']) == 2
    assert asm.min_open_mark() == 10     # min over the assembly's marks

    for c in chunks[2:]:
        res = asm.add(c)
    assert res['status'] == 'complete' and res['entry'] is entry
    assert 'partial' not in entry
    assert entry['outcome'] == res['record']['outcome']
    assert asm.min_open_mark() is None


def test_out_of_order_arrival_defers_exposure(wrapper):
    args = _args(chunk_steps=2, compress_steps=2)
    chunks = _generate(wrapper, args, sample_key=43)
    assert len(chunks) >= 2
    asm = ChunkAssembler(args)
    res = asm.add(chunks[-1])          # final first: no contiguous prefix
    assert res['status'] == 'open' and res['entry'] is None
    for c in chunks[:-1]:
        res = asm.add(c)
    assert res['status'] == 'complete' and res['record'] is not None


def test_poisoned_chunk_freezes_assembly_but_completes_task(wrapper):
    args = _args(chunk_steps=2, compress_steps=2)
    chunks = _generate(wrapper, args, sample_key=44)
    assert len(chunks) >= 2
    # poison chunk 0: NaN observation re-compressed on the same block grid
    window = decompress_moments(chunks[0]['moment'])
    window[0]['observation'][window[0]['turn'][0]] = np.full(3, np.nan)
    for m in window:
        for p in m['return']:
            m['return'][p] = None
    poisoned = build_chunk(chunks[0]['args'], 0, 0, window, args)
    asm = ChunkAssembler(args, check_finite=True)
    results = [asm.add(c) for c in [poisoned] + chunks[1:]]
    done = [r for r in results if r['status'] == 'complete']
    # the assembly closes (so the task completes) but the record drops whole
    assert len(done) == 1 and done[0]['record'] is None
    assert asm.open_count() == 0


def test_reap_abandons_stale_assemblies(wrapper):
    clock = [0.0]
    args = _args(chunk_steps=2, compress_steps=2)
    chunks = _generate(wrapper, args, sample_key=45)
    asm = ChunkAssembler(args, clock=lambda: clock[0])
    asm.add(chunks[0], mark=3)
    assert asm.open_count() == 1 and asm.min_open_mark() == 3
    clock[0] = 10.0
    assert asm.reap(older_than=100) == []
    clock[0] = 1000.0
    reaped = asm.reap(older_than=100)
    assert reaped == [chunk_key(chunks[0])]
    assert asm.open_count() == 0 and asm.min_open_mark() is None


# ---------------------------------------------------------------------------
# ledger journal + spool: the SIGKILL story


def test_journal_round_trips_chunk_book(tmp_path, wrapper):
    args = _args(chunk_steps=2, compress_steps=2)
    chunks = _generate(wrapper, args, sample_key=777, task_id=0)
    key = chunk_key(chunks[0])

    ledger = TaskLedger(deadline=60)
    ledger.journal = LedgerJournal(str(tmp_path))
    role = _gen_args(777)
    del role['task_id']
    tid = ledger.assign('w1', role)
    admitted = ledger.admit_chunks(chunks[:1])
    assert len(admitted) == 1
    ledger.flush_journal()   # the server flushes after the spool append

    state = LedgerJournal(str(tmp_path)).load()
    assert state['chunks'] == [[list(key), [0]]]
    restored = TaskLedger(deadline=60)
    restored.restore_state(state)
    # the restored screen drops the already-delivered chunk, admits the rest
    admitted = restored.admit_chunks(chunks)
    assert [c['chunk'] for c in admitted] == \
        [c['chunk'] for c in chunks[1:]]

    # closing the assembly journals 'q': the delta-only closure surfaces as
    # chunks_closed so spool recovery knows to replay those chunks
    ledger.complete_chunked(key, tid)
    ledger.flush_journal()
    state = LedgerJournal(str(tmp_path)).load()
    assert 'chunks' not in state
    assert state['chunks_closed'] == [list(key)]
    # a post-snapshot load folds the closure away entirely
    ledger.journal.snapshot(ledger.snapshot_state())
    state = LedgerJournal(str(tmp_path)).load()
    assert 'chunks' not in state and 'chunks_closed' not in state


def test_sigkill_mid_episode_replays_chunks_without_double_count(
        tmp_path, wrapper):
    """The learner dies after WAL'ing a strict prefix of an episode's
    chunks. The restarted learner replays them from the spool (screened by
    the journaled book), the re-issued attempt delivers the rest, and the
    episode completes exactly once, byte-identical to whole-episode ingest."""
    args = _args(chunk_steps=2, compress_steps=2)
    whole = _generate(wrapper, args, sample_key=888, task_id=0, stream=False)
    chunks = _generate(wrapper, args, sample_key=888, task_id=0)
    assert len(chunks) >= 2
    key = chunk_key(chunks[0])

    # --- first life: spool append THEN journal flush, per chunk
    spool = EpisodeSpool(str(tmp_path), segment_mb=1)
    ledger = TaskLedger(deadline=60)
    ledger.journal = LedgerJournal(str(tmp_path))
    role = _gen_args(888)
    del role['task_id']
    ledger.assign('w1', role)
    delivered = ledger.admit_chunks(chunks[:-1])
    for i, c in enumerate(delivered):
        spool.append(i, conn_pack({'idx': i, 'chunk': c}))
        ledger.flush_journal()
    ledger.journal.close()
    spool.close()
    # SIGKILL here: nothing below reuses first-life in-memory state

    # --- second life: journal -> book; spool -> chunk replay
    state = LedgerJournal(str(tmp_path)).load()
    ledger2 = TaskLedger(deadline=60)
    ledger2.restore_state(state)
    live_keys = {tuple(k) for k, _ in
                 (pair for pair in state.get('chunks') or ())}
    assert key in live_keys
    recovered = EpisodeSpool(str(tmp_path), segment_mb=1).recover(
        0, conn_unpack)
    replay = [rec['chunk'] for rec in recovered
              if rec.get('chunk') is not None
              and chunk_key(rec['chunk']) in live_keys]
    assert len(replay) == len(chunks) - 1
    asm = ChunkAssembler(args)
    for rec, c in zip(recovered, replay):
        asm.add(c, mark=rec['idx'])
        # replayed chunks were already journaled: re-seed, no new delta op
        ledger2.seed_chunk(chunk_key(c), c['chunk'])
    assert asm.open_count() == 1

    # the re-issued attempt regenerates the episode; only the tail admits
    admitted = ledger2.admit_chunks(chunks)
    assert [c['chunk'] for c in admitted] == [chunks[-1]['chunk']]
    results = [asm.add(c) for c in admitted]
    done = [r for r in results if r['status'] == 'complete']
    assert len(done) == 1
    _assert_records_byte_identical(done[0]['record'], whole)
    # recovery-completed assemblies seed the closed ring: a reattached
    # gather's resend replay of the SAME episode screens as duplicates
    ledger2.complete_chunked(key, done[0]['final_args'].get('task_id'))
    assert ledger2.admit_chunks(chunks) == []


# ---------------------------------------------------------------------------
# staleness-aware selection


def _buffer_args(**stream):
    a = {'maximum_episodes': 64, 'forward_steps': 2, 'burn_in_steps': 0,
         'compress_steps': 2}
    if stream:
        a['streaming'] = stream
    return a


def _fake_episodes(wrapper, n=6):
    args = _args(chunk_steps=2, compress_steps=2)
    eps = []
    for i in range(n):
        rec = _generate(wrapper, args, sample_key=2000 + i, stream=False)
        rec['recv_time'] = 100.0 + i
        eps.append(rec)
    return eps


def test_staleness_off_path_is_rng_sequence_identical(wrapper):
    """streaming.staleness_half_life == 0 must add ZERO random draws: the
    selection sequence is byte-identical to a config with no streaming
    block at all (the GL001 off-is-identical contract)."""
    eps = _fake_episodes(wrapper)
    baseline_args = _buffer_args()
    stream_args = _buffer_args(enabled=True, staleness_half_life=0.0,
                               max_reselect=4)
    random.seed(99)
    baseline = [select_episode(eps, baseline_args) for _ in range(40)]
    base_state = random.getstate()
    random.seed(99)
    streamed = [select_episode(eps, stream_args) for _ in range(40)]
    assert random.getstate() == base_state
    for a, b in zip(baseline, streamed):
        assert (a['train_start'], a['start'], a['end'], a['total']) == \
            (b['train_start'], b['start'], b['end'], b['total'])
        assert a['moment'] == b['moment']


def test_staleness_weighting_prefers_fresh_chunks(wrapper):
    eps = _fake_episodes(wrapper, n=2)
    now = 1000.0
    # episode 0: a streamed entry whose only exposed chunk is ancient
    eps[0]['chunk_recv'] = [now - 1e7]
    eps[0]['chunk_steps'] = 2
    # episode 1: fresh whole-episode entry
    eps[1]['recv_time'] = now
    args = _buffer_args(enabled=True, staleness_half_life=1.0,
                        max_reselect=4)
    random.seed(5)
    picks = [select_episode(eps, args, now=now) for _ in range(200)]
    stale = sum(1 for s in picks if s['recv_time'] == eps[0]['chunk_recv'][0])
    fresh = sum(1 for s in picks if s['recv_time'] == now)
    assert stale + fresh == len(picks)
    # the accept probability for the stale chunk is ~2^-1e7: it is only
    # ever taken when all max_reselect re-draws land on it
    assert fresh > stale
    # per-chunk sample_age plumbing: streamed picks report the CHUNK's
    # ingest stamp, not the episode-level one
    assert all(s['recv_time'] == eps[0]['chunk_recv'][0]
               for s in picks if s['total'] == eps[0]['steps']
               and s['recv_time'] != now)


# ---------------------------------------------------------------------------
# config contract


def test_config_rejects_misaligned_chunk_steps():
    from handyrl_tpu.config import apply_defaults
    apply_defaults({})   # defaults (streaming off) are self-consistent
    apply_defaults({'train_args': {
        'compress_steps': 4,
        'streaming': {'enabled': True, 'chunk_steps': 8}}})
    with pytest.raises(AssertionError):
        apply_defaults({'train_args': {
            'compress_steps': 4,
            'streaming': {'enabled': True, 'chunk_steps': 6}}})
