"""Batch builder tests: shapes, masks, and pad semantics on hand-built
episodes."""

import numpy as np
import pytest

from handyrl_tpu.ops.batch import (compress_moments, decompress_moments,
                                   make_batch, select_episode)

GAMMA = 0.8


def _turn_based_episode(steps=5, obs_shape=(3, 3, 3), n_actions=9):
    """Synthetic 2-player turn-alternating episode: player t%2 acts at step t."""
    moments = []
    for t in range(steps):
        turn = t % 2
        m = {key: {0: None, 1: None} for key in
             ('observation', 'selected_prob', 'action_mask', 'action',
              'value', 'reward', 'return')}
        m['observation'][turn] = np.full(obs_shape, t + 1, np.float32)
        m['selected_prob'][turn] = 0.5
        amask = np.full(n_actions, 1e32, np.float32)
        amask[:3] = 0
        m['action_mask'][turn] = amask
        m['action'][turn] = t % 3
        m['value'][turn] = np.array([0.1 * t], np.float32)
        m['reward'] = {0: 0.0, 1: 0.0}
        m['return'] = {0: 0.25, 1: -0.25}
        m['turn'] = [turn]
        moments.append(m)
    return {
        'args': {'player': [0, 1]}, 'steps': steps,
        'outcome': {0: 1.0, 1: -1.0},
        'moment': compress_moments(moments, compress_steps=2),
    }


def _args(forward_steps=4, burn_in=0, observation=False, turn_based=True):
    return {
        'turn_based_training': turn_based, 'observation': observation,
        'forward_steps': forward_steps, 'burn_in_steps': burn_in,
        'compress_steps': 2, 'maximum_episodes': 100,
    }


def _window(ep, start, end, train_start=None, cs=2):
    st_block, ed_block = start // cs, (end - 1) // cs + 1
    return {
        'args': ep['args'], 'outcome': ep['outcome'],
        'moment': ep['moment'][st_block:ed_block], 'base': st_block * cs,
        'start': start, 'end': end,
        'train_start': start if train_start is None else train_start,
        'total': ep['steps'],
    }


def test_compress_roundtrip():
    ep = _turn_based_episode(5)
    moments = decompress_moments(ep['moment'])
    assert len(moments) == 5
    assert moments[3]['turn'] == [1]


def test_turn_alternating_shapes_and_masks():
    ep = _turn_based_episode(5)
    batch = make_batch([_window(ep, 0, 4)], _args(forward_steps=4))
    # turn-alternating: obs/prob/act/amask have P=1; masks/values have P=2
    assert batch['observation'].shape == (1, 4, 1, 3, 3, 3)
    assert batch['selected_prob'].shape == (1, 4, 1, 1)
    assert batch['action'].shape == (1, 4, 1, 1)
    assert batch['action_mask'].shape == (1, 4, 1, 9)
    assert batch['value'].shape == (1, 4, 2, 1)
    assert batch['turn_mask'].shape == (1, 4, 2, 1)
    assert batch['observation_mask'].shape == (1, 4, 2, 1)
    assert batch['outcome'].shape == (1, 1, 2, 1)
    # step t: player t%2 acted, other didn't
    want_t = np.array([[1, 0], [0, 1], [1, 0], [0, 1]], np.float32)
    np.testing.assert_array_equal(batch['turn_mask'][0, :, :, 0], want_t)
    np.testing.assert_array_equal(batch['observation_mask'][0, :, :, 0], want_t)
    assert batch['episode_mask'].min() == 1.0


def test_short_window_padding_semantics():
    ep = _turn_based_episode(3)
    batch = make_batch([_window(ep, 0, 3)], _args(forward_steps=6))
    # 3 real steps + 3 pad steps after episode end
    assert batch['observation'].shape == (1, 6, 1, 3, 3, 3)
    assert np.all(batch['observation'][0, 3:] == 0)
    np.testing.assert_array_equal(batch['selected_prob'][0, 3:], 1.0)
    np.testing.assert_array_equal(batch['action_mask'][0, 3:], np.float32(1e32))
    np.testing.assert_array_equal(batch['episode_mask'][0, 3:], 0.0)
    np.testing.assert_array_equal(batch['turn_mask'][0, 3:], 0.0)
    # value is padded with the final OUTCOME beyond episode end
    np.testing.assert_array_equal(batch['value'][0, 3:, 0, 0], 1.0)
    np.testing.assert_array_equal(batch['value'][0, 3:, 1, 0], -1.0)
    np.testing.assert_array_equal(batch['progress'][0, 3:, 0], 1.0)


def test_burn_in_front_padding():
    ep = _turn_based_episode(5)
    # train window starts at 2 with burn_in 2 -> context from step 0
    w = _window(ep, 0, 5, train_start=2)
    batch = make_batch([w], _args(forward_steps=3, burn_in=2))
    assert batch['observation'].shape[1] == 5
    assert batch['episode_mask'][0].sum() == 5  # no padding needed


def test_burn_in_truncated_at_episode_start():
    ep = _turn_based_episode(5)
    # train_start=1 but only 1 step of burn-in context exists -> pad front by 1
    w = _window(ep, 0, 4, train_start=1)
    batch = make_batch([w], _args(forward_steps=3, burn_in=2))
    assert batch['observation'].shape[1] == 5
    np.testing.assert_array_equal(batch['episode_mask'][0, 0], 0.0)
    np.testing.assert_array_equal(batch['selected_prob'][0, 0], 1.0)
    np.testing.assert_array_equal(batch['episode_mask'][0, 1:], 1.0)


def test_observation_mode_all_players():
    ep = _turn_based_episode(4)
    batch = make_batch([_window(ep, 0, 4)], _args(observation=True))
    # with observation=True every player's row is kept: P=2 everywhere
    assert batch['observation'].shape == (1, 4, 2, 3, 3, 3)
    assert batch['selected_prob'].shape == (1, 4, 2, 1)
    # non-acting player's missing action_mask is the all-illegal +1e32 row
    np.testing.assert_array_equal(batch['action_mask'][0, 0, 1], np.float32(1e32))
    # non-acting player's prob backfilled to 1 => log prob 0
    assert batch['selected_prob'][0, 0, 1, 0] == 1.0


def test_dict_observation_support():
    steps = 3
    moments = []
    for t in range(steps):
        m = {key: {0: None} for key in
             ('observation', 'selected_prob', 'action_mask', 'action',
              'value', 'reward', 'return')}
        m['observation'][0] = {'scalar': np.ones(4, np.float32),
                               'board': np.ones((2, 3, 3), np.float32)}
        m['selected_prob'][0] = 1.0
        m['action_mask'][0] = np.zeros(5, np.float32)
        m['action'][0] = 0
        m['value'][0] = [0.0]
        m['reward'][0] = 0.0
        m['return'][0] = 0.0
        m['turn'] = [0]
        moments.append(m)
    ep = {'args': {'player': [0]}, 'steps': steps, 'outcome': {0: 0.0},
          'moment': compress_moments(moments, 2)}
    batch = make_batch([_window(ep, 0, 3)], _args(forward_steps=3))
    assert batch['observation']['scalar'].shape == (1, 3, 1, 4)
    assert batch['observation']['board'].shape == (1, 3, 1, 2, 3, 3)


def test_select_episode_window_bounds():
    import random
    random.seed(0)
    ep = _turn_based_episode(20)
    args = _args(forward_steps=8, burn_in=2)
    for _ in range(50):
        w = select_episode([ep], args)
        assert 0 <= w['start'] <= w['train_start'] < w['end'] <= 20
        assert w['end'] - w['train_start'] <= 8
        assert w['train_start'] - w['start'] <= 2
        moments = decompress_moments(w['moment'])
        assert len(moments) >= w['end'] - w['base'] - (w['start'] - w['base'])
