"""init_kind='torch' must reproduce the reference framework's default
weight distributions (torch Conv2d/Linear reset_parameters: kernel
kaiming_uniform(a=sqrt(5)) == uniform(+-1/sqrt(fan_in)), bias
uniform(+-1/sqrt(fan_in))) — the init-dynamics arm of the Geister
early-curve investigation."""

import jax
import jax.numpy as jnp
import numpy as np

from handyrl_tpu.models.geister import GeisterNet


def _obs(n=2):
    return {'board': jnp.zeros((n, 7, 6, 6)), 'scalar': jnp.zeros((n, 18))}


def _leaves(params):
    return {jax.tree_util.keystr(path): np.asarray(leaf)
            for path, leaf in jax.tree_util.tree_leaves_with_path(params)}


def test_torch_init_statistics():
    net = GeisterNet(init_kind='torch', policy_head='spatial')
    params = net.init(jax.random.PRNGKey(0), _obs(), None)
    leaves = _leaves(params)
    stem = next(v for k, v in leaves.items()
                if 'ConvBlock_0' in k and 'kernel' in k)
    fan_in = stem.shape[0] * stem.shape[1] * stem.shape[2]   # kh*kw*cin
    bound = 1.0 / np.sqrt(fan_in)
    # uniform(+-bound): everything inside the bound, std ~= bound/sqrt(3)
    assert np.abs(stem).max() <= bound * 1.0001
    assert np.isclose(stem.std(), bound / np.sqrt(3), rtol=0.15)
    # biases are NONZERO uniform (flax default would be exactly zero)
    gate_bias = next(v for k, v in leaves.items()
                     if 'ConvLSTMCell' in k and 'bias' in k
                     and 'Norm' not in k)
    assert np.abs(gate_bias).max() > 0
    # norm scale/bias unchanged by the knob (ones/zeros in both regimes)
    norm_scale = next(v for k, v in leaves.items()
                      if 'Norm' in k and 'scale' in k)
    assert np.allclose(norm_scale, 1.0)


def test_flax_default_differs():
    """The knob actually changes the distribution: flax kernels have
    1.73x the std and exactly-zero biases."""
    obs = _obs()
    p_f = GeisterNet(init_kind='flax').init(jax.random.PRNGKey(0), obs, None)
    p_t = GeisterNet(init_kind='torch').init(jax.random.PRNGKey(0), obs, None)
    lf, lt = _leaves(p_f), _leaves(p_t)
    k = next(k for k in lf if 'ConvBlock_0' in k and 'kernel' in k)
    assert lf[k].std() > lt[k].std() * 1.4
    bias_keys = [k for k in lf
                 if 'ConvLSTMCell' in k and k.endswith("['bias']")]
    assert bias_keys
    for k in bias_keys:
        assert np.allclose(lf[k], 0.0)
        assert np.abs(lt[k]).max() > 0
    # same tree structure: the knob swaps distributions, not architecture
    assert set(lf) == set(lt)


def test_unknown_init_kind_rejected():
    import pytest
    with pytest.raises(ValueError):
        GeisterNet(init_kind='typo').init(jax.random.PRNGKey(0), _obs(), None)


def test_three_knob_arm_update_step(geister_batch_and_wrapper):
    """One compiled update step on the full round-5 chip-arm config
    (spatial head + full BatchNorm + torch init — geister-fused-sp-bn-ti)
    so the combination cannot first fail mid-benchmark on the chip."""
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.model import ModelWrapper
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.train_step import build_update_step, init_train_state

    _, batch, args = geister_batch_and_wrapper
    wrapper = ModelWrapper(GeisterNet(
        filters=8, drc_layers=2, drc_repeats=1, norm_kind='batch',
        policy_head='spatial', init_kind='torch'))
    env = make_env({'env': 'Geister'})
    env.reset()
    wrapper.ensure_params(env.observation(0))
    state = init_train_state(jax.tree_util.tree_map(jnp.array,
                                                    wrapper.params))
    update = build_update_step(wrapper.module, LossConfig.from_args(args),
                               mesh=None, donate=False)
    _, metrics = update(state, batch, jnp.float32(1e-3))
    assert np.isfinite(float(metrics['total']))


# reuse the sp-bn batch fixture from the batchnorm parity suite
from tests.test_batchnorm_parity import geister_batch_and_wrapper  # noqa: E402,F401
