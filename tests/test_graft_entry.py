"""Driver entry points: entry() compiles, dryrun_multichip executes."""

import jax

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, (params, obs) = graft.entry()
    out = jax.jit(fn)(params, obs)
    assert out['policy'].shape == (64, 4)
    assert out['value'].shape == (64, 1)


def test_dryrun_multichip_two_devices():
    graft.dryrun_multichip(2)


def test_dryrun_multichip_eight_devices():
    graft.dryrun_multichip(8)
