"""Inference-service tests: the shared masked sampler, ragged-row padding,
the snapshot vault, and the coalescing InferenceEngine — including
byte-identity of episode records between the per-worker B=1 path and the
engine path (the bench/CI contract), with a simulated pipe hop so dtype
canonicalization is exercised too."""

import pickle
import queue
from collections import deque

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import pytest

from handyrl_tpu import models as model_zoo
from handyrl_tpu.connection import INFER_KIND, pack
from handyrl_tpu.environment import make_env
from handyrl_tpu.generation import (Generator, masked_sample,
                                    masked_sample_batch, model_act,
                                    pad_to_bucket, sample_seed)
from handyrl_tpu.inference import (EngineClient, InferenceEngine, ModelVault,
                                   RemoteModel, RemoteModelCache)
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.utils.tree import softmax

from helpers import ragged_act_rows

GEN_ARGS = {'observation': False, 'gamma': 0.8, 'compress_steps': 4,
            'seed': 11}


@model_zoo.register('TinyRecurrent')
class TinyRecurrent(nn.Module):
    """Minimal recurrent module for hidden round-trip coverage."""
    feats: int = 8
    n_actions: int = 9

    @nn.compact
    def __call__(self, x, hidden=None):
        if hidden is None:
            hidden = self.init_hidden(x.shape[:1])
        flat = x.reshape(x.shape[0], -1)
        h = jnp.tanh(nn.Dense(self.feats)(flat) + hidden)
        return {'policy': nn.Dense(self.n_actions)(h),
                'value': jnp.tanh(nn.Dense(1)(h)),
                'hidden': h}

    def init_hidden(self, batch_shape=()):
        return jnp.zeros(tuple(batch_shape) + (self.feats,))


def _ttt_wrapper(seed=7):
    env = make_env({'env': 'TicTacToe'})
    env.reset()
    w = ModelWrapper(env.net(), seed=seed)
    w.ensure_params(env.observation(0))
    return env, w


# ---------------------------------------------------------------------------
# masked sampling — the one audited routine


def test_masked_sample_deterministic_and_legal():
    rng = np.random.RandomState(3)
    for row in ragged_act_rows(16, seed=5):
        policy = rng.randn(9).astype(np.float32)
        seed_seq = sample_seed(11, (0, 4), 2)
        a1, p1, m1 = masked_sample(policy, row['legal'], seed_seq)
        a2, p2, m2 = masked_sample(policy, row['legal'], seed_seq)
        assert a1 == a2 and p1 == p2          # pure function of the seed
        assert a1 in row['legal']
        # mask: +1e32 on illegal, 0 on legal (the reference contract)
        assert np.all(m1[row['legal']] == 0)
        illegal = [a for a in range(9) if a not in row['legal']]
        assert np.all(m1[illegal] == np.float32(1e32))
        # recorded prob is the masked softmax prob of the taken action
        ref = softmax(policy - m1)
        assert p1 == ref[a1]
        np.testing.assert_array_equal(m1, m2)


def test_masked_sample_batch_matches_single():
    rng = np.random.RandomState(0)
    rows = ragged_act_rows(12, seed=1)
    policies = rng.randn(12, 9).astype(np.float32)
    seeds = [sample_seed(11, (0, k), k) for k in range(12)]
    actions, probs, masks = masked_sample_batch(
        policies, [r['legal'] for r in rows], seeds)
    for k, row in enumerate(rows):
        a, p, m = masked_sample(policies[k], row['legal'], seeds[k])
        assert actions[k] == a
        assert probs[k] == p                  # bit-identical
        np.testing.assert_array_equal(masks[k], m)


def test_masked_sample_draw_index_varies():
    policy = np.zeros(9, np.float32)          # uniform over legal
    legal = list(range(9))
    draws = {masked_sample(policy, legal,
                           sample_seed(11, (0, 1), i))[0]
             for i in range(32)}
    assert len(draws) > 3                     # different indices, new draws


# ---------------------------------------------------------------------------
# ragged-row padding


def test_pad_to_bucket_shapes_and_content():
    rows = ragged_act_rows(5, seed=2)
    batch, n = pad_to_bucket([r['obs'] for r in rows])
    assert n == 5 and batch.shape == (8, 3, 3, 3)   # min bucket 8
    for k, row in enumerate(rows):
        np.testing.assert_array_equal(batch[k], row['obs'])
    np.testing.assert_array_equal(batch[5], rows[0]['obs'])  # pad = row 0

    batch, n = pad_to_bucket([r['obs'] for r in ragged_act_rows(9, seed=3)])
    assert n == 9 and batch.shape[0] == 16          # next power of two

    batch, n = pad_to_bucket([rows[0]['obs']])
    assert n == 1 and batch.shape[0] == 8           # B=1 pads to min bucket


def test_pad_to_bucket_pytree():
    rows = [{'a': np.ones((2,), np.float32) * i,
             'b': (np.zeros((3,), np.float32) + i,)} for i in range(3)]
    batch, n = pad_to_bucket(rows)
    assert n == 3
    assert batch['a'].shape == (8, 2) and batch['b'][0].shape == (8, 3)
    np.testing.assert_array_equal(batch['a'][2], np.ones(2) * 2)
    np.testing.assert_array_equal(batch['a'][5], np.zeros(2))  # row-0 pad


# ---------------------------------------------------------------------------
# model vault


def _snapshots_for(mids, seed0=1):
    """mid -> distinct-params snapshot of the same architecture."""
    out = {}
    for mid in mids:
        _env, w = _ttt_wrapper(seed=seed0 + mid)
        out[mid] = w.snapshot()
    return out


def test_vault_distinct_ids_never_alias_params():
    import jax
    env, _ = _ttt_wrapper()
    snaps = _snapshots_for([1, 2])
    vault = ModelVault(lambda mid: snaps[mid], env.observation(0),
                       capacity=3)
    models = vault.obtain({0: 1, 1: 2})
    leaves1 = jax.tree_util.tree_leaves(models[0].params)
    leaves2 = jax.tree_util.tree_leaves(models[1].params)
    assert len(leaves1) == len(leaves2) > 0
    diff = False
    for a, b in zip(leaves1, leaves2):
        assert not np.shares_memory(np.asarray(a), np.asarray(b))
        diff = diff or not np.array_equal(np.asarray(a), np.asarray(b))
    assert diff, 'seed-1 and seed-2 snapshots should have different params'


def test_vault_eviction_rematerializes():
    env, _ = _ttt_wrapper()
    snaps = _snapshots_for([1, 2, 3])
    vault = ModelVault(lambda mid: snaps[mid], env.observation(0),
                       capacity=2)
    vault.obtain({0: 1})
    vault.obtain({0: 2})
    assert vault.fetches == 2
    vault.obtain({0: 3})                      # evicts 1 (LRU)
    assert vault.fetches == 3
    assert 1 not in vault._slots and {2, 3} <= set(vault._slots)
    m1 = vault.obtain({0: 1})[0]              # re-materialized, not stale
    assert vault.fetches == 4
    from flax import serialization
    assert serialization.to_bytes(m1.params) == snaps[1]['params']


def test_vault_negative_and_none_ids():
    env, _ = _ttt_wrapper()
    vault = ModelVault(lambda mid: (_ for _ in ()).throw(AssertionError),
                       env.observation(0))
    out = vault.obtain({0: None, 1: -1})
    assert out == {0: None, 1: None}
    assert vault.fetches == 0


def test_remote_model_cache_semantics():
    class _Conn:
        pass
    cache = RemoteModelCache(_Conn(), capacity=2)
    out = cache.obtain({0: None, 1: -1, 2: 5})
    assert out[0] is None and out[1] is None
    assert isinstance(out[2], RemoteModel) and out[2].model_id == 5
    again = cache.obtain({0: 5})
    assert again[0] is out[2]                 # cached proxy identity
    cache.obtain({0: 6})
    cache.obtain({0: 7})                      # evicts 5 under capacity 2
    assert cache.obtain({0: 5})[0] is not out[2]


# ---------------------------------------------------------------------------
# the engine itself


class _Loopback:
    """In-process stand-in for the worker<->gather pipe: requests go
    straight into a live engine; replies come back as the tagged
    ``(INFER_KIND, reply)`` frames the real relay posts, round-tripped
    through pickle to simulate the mp transport (fresh dtype instances and
    all)."""

    def __init__(self, engine):
        self.engine = engine
        self.replies: queue.Queue = queue.Queue()
        self._peeked: deque = deque()

    def send(self, msg):
        kind, body = msg
        assert kind == INFER_KIND
        self.engine.submit(self, pickle.loads(pickle.dumps(body)))

    def poll(self, timeout=0.0):
        if self._peeked:
            return True
        try:
            self._peeked.append(self.replies.get(timeout=max(timeout, 1e-4)))
        except queue.Empty:
            return False
        return True

    def recv(self):
        body = (self._peeked.popleft() if self._peeked
                else self.replies.get(timeout=30))
        return (INFER_KIND, pickle.loads(pickle.dumps(body)))


def _engine_for(snapshot_by_mid, example_obs, clients=1, batch_wait_ms=2.0,
                max_batch=64):
    args = {'inference': {'enabled': True, 'batch_wait_ms': batch_wait_ms,
                          'max_batch': max_batch},
            'env': {'env': 'TicTacToe'}}
    engine = InferenceEngine(
        args, fetch_snapshot=lambda mid: snapshot_by_mid[mid],
        reply_fn=lambda ep, msg: ep.replies.put(msg),
        clients=clients, example_obs=example_obs)
    return engine.start()


def _remote(engine, mid, failover=False, **inf):
    """RemoteModel over a fresh EngineClient + loopback pipe. ``failover``
    defaults OFF so engine errors raise (the pre-self-healing semantics
    most of these tests pin); the failover tests flip it on."""
    args = {'inference': {'enabled': True, 'request_timeout': 30.0,
                          'request_retries': 0, 'failover': failover, **inf},
            'env': {'env': 'TicTacToe'}}
    return RemoteModel(EngineClient(_Loopback(engine), args), mid)


@pytest.mark.timeout(120)
def test_engine_coalesces_across_clients():
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    engine = _engine_for({1: w.snapshot()}, obs, clients=4,
                         batch_wait_ms=500.0)
    try:
        models = [_remote(engine, 1) for _ in range(4)]
        rids = [m.act_send(obs, None, [0, 1, 2],
                           sample_seed(11, (0, k), 0))
                for k, m in enumerate(models)]
        replies = [m.act_recv(r) for m, r in zip(models, rids)]
        # 4 clients, quiescent queue: ONE forward served all four
        assert engine.batches_run == 1
        assert engine.requests_served == 4
        assert engine.batch_fill_ratio() == 4.0
        for rep in replies:
            assert rep['action'] in (0, 1, 2)
            assert isinstance(rep['prob'], np.float32)
            assert rep['action_mask'].shape == (9,)
    finally:
        engine.stop()


@pytest.mark.timeout(120)
def test_engine_act_matches_local_path_bitwise():
    """The engine's act reply must equal the local bucketed path exactly —
    same action, same float bits for prob/value/mask."""
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    engine = _engine_for({1: w.snapshot()}, obs)
    try:
        remote = _remote(engine, 1)
        legal = env.legal_actions(0)
        for draw in range(5):
            seed_seq = sample_seed(11, (0, 9), draw)
            res_local = model_act(w, obs, None, legal, seed_seq)
            res_engine = model_act(remote, obs, None, legal, seed_seq)
            assert res_local['action'] == res_engine['action']
            assert res_local['prob'] == res_engine['prob']
            np.testing.assert_array_equal(res_local['action_mask'],
                                          res_engine['action_mask'])
            np.testing.assert_array_equal(res_local['value'],
                                          res_engine['value'])
    finally:
        engine.stop()


@pytest.mark.timeout(120)
def test_engine_recurrent_hidden_round_trip():
    """Recurrent state rides requests/replies: a None hidden gets a fresh
    init engine-side, and the advanced state a worker sends back produces
    the same trajectory the local path computes."""
    wrapper = ModelWrapper(model_zoo.build('TinyRecurrent'), seed=3)
    rows = ragged_act_rows(1, obs_shape=(3, 3, 3), seed=4)
    obs = rows[0]['obs']
    wrapper.ensure_params(obs)
    engine = _engine_for({1: wrapper.snapshot()}, obs)
    try:
        remote = _remote(engine, 1)
        h_local = wrapper.init_hidden()       # real initial state
        h_remote = remote.init_hidden()       # None by design
        assert h_remote is None
        for step, row in enumerate(ragged_act_rows(6, seed=9)):
            seed_seq = sample_seed(11, (0, 2), step)
            res_l = model_act(wrapper, row['obs'], h_local,
                              row['legal'], seed_seq)
            res_r = model_act(remote, row['obs'], h_remote,
                              row['legal'], seed_seq)
            assert res_l['action'] == res_r['action']
            np.testing.assert_array_equal(np.asarray(res_l['hidden']),
                                          np.asarray(res_r['hidden']))
            h_local, h_remote = res_l['hidden'], res_r['hidden']
        assert h_remote is not None and np.any(np.asarray(h_remote) != 0)
    finally:
        engine.stop()


@pytest.mark.timeout(120)
def test_engine_error_reply_does_not_kill_service():
    env, w = _ttt_wrapper()
    obs = env.observation(0)

    def fetch(mid):
        if mid == 99:
            raise ConnectionError('no such snapshot')
        return w.snapshot()

    args = {'inference': {'enabled': True, 'batch_wait_ms': 1.0},
            'env': {'env': 'TicTacToe'}}
    engine = InferenceEngine(args, fetch_snapshot=fetch,
                             reply_fn=lambda ep, msg: ep.replies.put(msg),
                             clients=1, example_obs=obs).start()
    try:
        bad = _remote(engine, 99)
        with pytest.raises(RuntimeError, match='no such snapshot'):
            bad.act(obs, None, [0], sample_seed(0, (0, 0), 0))
        good = _remote(engine, 1)   # service still alive
        rep = good.act(obs, None, [0, 1], sample_seed(0, (0, 1), 0))
        assert rep['action'] in (0, 1)
    finally:
        engine.stop()


@pytest.mark.timeout(120)
def test_engine_random_model_id_zero_uniform():
    env, w = _ttt_wrapper()
    obs = env.observation(0)
    engine = _engine_for({0: w.snapshot()}, obs)
    try:
        remote = _remote(engine, 0)
        legal = [2, 5, 7]
        rep = remote.act(obs, None, legal, sample_seed(1, (0, 0), 0))
        assert rep['action'] in legal
        # zero policy => uniform over legal, like worker-side RandomModel
        assert rep['prob'] == np.float32(1.0) / np.float32(3.0) \
            or abs(float(rep['prob']) - 1 / 3) < 1e-6
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# episode records: engine path vs per-worker path, byte for byte


@pytest.mark.timeout(300)
def test_episode_records_bit_identical_across_paths():
    env, w = _ttt_wrapper()
    snap = w.snapshot()
    task = {'role': 'g', 'player': [0, 1], 'model_id': {0: 1, 1: 1},
            'sample_key': 5}

    local_env = make_env({'env': 'TicTacToe'})
    local = Generator(local_env, GEN_ARGS, namespace=0)
    w2 = ModelWrapper.from_snapshot(snap, env.observation(0))
    episodes_local = [local.generate({0: w2, 1: w2},
                                     dict(task, sample_key=k))
                      for k in range(4)]

    engine = _engine_for({1: snap}, env.observation(0))
    try:
        remote = _remote(engine, 1)
        eng_env = make_env({'env': 'TicTacToe'})
        eng = Generator(eng_env, GEN_ARGS, namespace=3)  # namespace ignored
        episodes_engine = [eng.generate({0: remote, 1: remote},
                                        dict(task, sample_key=k))
                           for k in range(4)]
    finally:
        engine.stop()

    for a, b in zip(episodes_local, episodes_engine):
        assert a is not None and b is not None
        assert pack(a) == pack(b)             # byte-for-byte identical


@pytest.mark.timeout(300)
def test_episode_records_reproducible_across_workers():
    """Same sample_key => same episode, no matter which 'worker' (namespace,
    local draw history) runs the task — the ledger re-issue guarantee."""
    env, w = _ttt_wrapper()
    snap = w.snapshot()

    def run(namespace, warmup_episodes):
        e = make_env({'env': 'TicTacToe'})
        g = Generator(e, GEN_ARGS, namespace=namespace)
        model = ModelWrapper.from_snapshot(snap, env.observation(0))
        for _ in range(warmup_episodes):      # advance local fallback stream
            g.generate({0: model, 1: model}, {'role': 'g', 'player': [0, 1],
                                              'model_id': {0: 1, 1: 1}})
        return g.generate({0: model, 1: model},
                          {'role': 'g', 'player': [0, 1],
                           'model_id': {0: 1, 1: 1}, 'sample_key': 17})

    assert pack(run(0, 0)) == pack(run(4, 3))


# ---------------------------------------------------------------------------
# end-to-end: one training epoch over the real process tree, engine enabled


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_local_worker_cluster_with_engine_one_epoch(tmp_path):
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 8, 'update_episodes': 20, 'minimum_episodes': 20,
            'epochs': 1, 'forward_steps': 8, 'num_batchers': 1,
            'batched_generation': False,
            'inference': {'enabled': True},
            'worker': {'num_parallel': 2},
            'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args)
    learner.run()
    assert learner.model_epoch == 1
    assert learner.num_returned_episodes >= 20
    assert (tmp_path / 'models' / '1.ckpt').exists()
