"""Test harness configuration.

Force JAX onto a virtual 8-device CPU mesh so all sharding/pjit code paths
run the same program they would on a TPU slice.

Note: the env var alone is not enough in this image — the axon TPU plugin's
site registration overrides jax_platforms at import time, and its backend
init blocks if another process holds the single TPU tunnel. The explicit
``jax.config.update`` below wins over that and keeps the test suite fully
off-device (so it can run in parallel with a training/bench process).
"""

import os

os.environ['JAX_PLATFORMS'] = 'cpu'
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (_flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
