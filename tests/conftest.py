"""Test harness configuration.

Force JAX onto a virtual 8-device CPU mesh BEFORE jax is imported anywhere, so
all sharding/pjit code paths run the same program they would on a TPU slice.
"""

import os

os.environ['JAX_PLATFORMS'] = 'cpu'
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (_flags + ' --xla_force_host_platform_device_count=8').strip()
