"""Test harness configuration.

Force JAX onto a virtual 8-device CPU mesh so all sharding/pjit code paths
run the same program they would on a TPU slice.

Note: the env var alone is not enough in this image — the axon TPU plugin's
site registration overrides jax_platforms at import time, and its backend
init blocks if another process holds the single TPU tunnel. The explicit
``jax.config.update`` below wins over that and keeps the test suite fully
off-device (so it can run in parallel with a training/bench process).
"""

import os

# Opt-in real-device run: HANDYRL_TPU_TESTS=1 keeps whatever backend the
# environment provides, so device-gated tests (e.g. the compiled Pallas
# kernels in test_pallas_targets.py) exercise real silicon. Only the
# modules in _TPU_SAFE_FILES run in this mode (see
# pytest_collection_modifyitems): the rest of the suite assumes the
# 8-virtual-device CPU mesh (some tests hard-assert it) and must stay off
# the exclusive single-chip tunnel. Default stays the CPU mesh.
_TPU_MODE = os.environ.get('HANDYRL_TPU_TESTS') == '1'
_TPU_SAFE_FILES = ('test_pallas_targets.py',)
if _TPU_MODE:
    import jax
else:
    os.environ['JAX_PLATFORMS'] = 'cpu'
    _flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in _flags:
        os.environ['XLA_FLAGS'] = (_flags + ' --xla_force_host_platform_device_count=8').strip()

    import jax

    jax.config.update('jax_platforms', 'cpu')


# ---------------------------------------------------------------------------
# In-tree 'timeout' mark: pytest-timeout is not installable in this image, so
# the deadlock guards on the multiprocess/socket e2e tests are enforced here
# with a SIGALRM watchdog (tests run in the main thread). A hung test raises
# TimeoutError instead of stalling CI until the job limit.

import signal  # noqa: E402

import pytest  # noqa: E402

from handyrl_tpu import setup_compile_cache  # noqa: E402

# the suite re-traces the same programs constantly; package import is
# side-effect free, so the persistent compile cache is enabled here
setup_compile_cache()


def pytest_configure(config):
    config.addinivalue_line(
        'markers',
        'timeout(seconds): fail the test if it runs longer than the deadline')
    config.addinivalue_line(
        'markers',
        'slow: excluded from the tier-1 run (-m "not slow"); exercised by '
        'dedicated CI steps (e.g. the chaos smoke)')


# Socket/multiprocess integration tests rely on POSIX semantics (SIGALRM
# hang watchdog, spawn+pipe teardown timing); on the windows CI leg they are
# skipped — the unit/oracle/golden suite still runs there in full.
_POSIX_ONLY_FILES = (
    'test_remote_cluster.py', 'test_network.py', 'test_cluster.py',
    'test_cli.py', 'test_eval_cli.py', 'test_multihost.py',
    'test_batcher_processes.py', 'test_stress.py',
    'test_fault_tolerance.py', 'test_guard.py', 'test_engine_failover.py',
    'test_serving.py',
)


def pytest_collection_modifyitems(config, items):
    import sys
    if sys.platform == 'win32':
        skip_win = pytest.mark.skip(
            reason='POSIX-only integration test (SIGALRM watchdog / '
                   'spawn+socket teardown semantics)')
        for item in items:
            if os.path.basename(str(item.fspath)) in _POSIX_ONLY_FILES:
                item.add_marker(skip_win)
    if not _TPU_MODE:
        return
    skip = pytest.mark.skip(
        reason='HANDYRL_TPU_TESTS=1 runs only the real-device-safe modules; '
               'the rest of the suite needs the 8-virtual-device CPU mesh')
    for item in items:
        if os.path.basename(str(item.fspath)) not in _TPU_SAFE_FILES:
            item.add_marker(skip)


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    mark = item.get_closest_marker('timeout')
    if mark is None or not hasattr(signal, 'SIGALRM'):
        return (yield)
    seconds = int(mark.args[0]) if mark.args else 300

    def _expired(signum, frame):
        raise TimeoutError('test exceeded %ds timeout' % seconds)

    previous = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(seconds)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, previous)
