"""Learner end to end with the HBM-resident replay ring: device generation +
on-device batch sampling (the fully device-centric pipeline), including the
replay observability contract: drop counters, ring occupancy, and
sample-reuse ratio must appear in the metrics JSONL."""

import json

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


def test_learner_with_device_replay(tmp_path):
    metrics_path = tmp_path / 'metrics.jsonl'
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 40, 'minimum_episodes': 40,
            'epochs': 2, 'generation_envs': 16, 'forward_steps': 8,
            'num_batchers': 1, 'device_generation': True,
            'device_replay': True,
            'model_dir': str(tmp_path / 'models'),
            'metrics_jsonl': str(metrics_path),
        },
    }
    learner = Learner(args=apply_defaults(raw))
    learner.run()
    assert learner.model_epoch == 2
    assert learner.trainer.replay is not None
    # this config takes the fused device-ingest route (sharded over the
    # test mesh): the ring lives in the pipeline, mirrored to the trainer
    # for observability; the host-push DeviceReplay path is covered below
    assert learner.trainer._ring_size_host > 0
    assert learner.trainer.steps > 0
    assert (tmp_path / 'models' / '2.ckpt').exists()

    # replay observability: every epoch record carries the audit fields
    records = [json.loads(line) for line in
               metrics_path.read_text().splitlines()]
    assert records, 'metrics JSONL should have one record per epoch'
    for rec in records:
        assert rec['replay_dropped_episodes'] >= 0
        assert 0.0 <= rec['replay_ring_occupancy'] <= 1.0
        assert rec['replay_sample_reuse'] >= 0.0
    # the trailing-window eval aggregate appears once any eval games have
    # resolved, and is a well-formed rate over a positive game count
    recent = [r for r in records if 'win_rate_recent10' in r]
    assert recent, 'expected trailing-window eval aggregate in metrics'
    for rec in recent:
        assert 0.0 <= rec['win_rate_recent10'] <= 1.0
        assert rec['eval_games_recent10'] > 0
    last = records[-1]
    stats = learner.trainer.replay_stats
    assert stats['windows_ingested'] > 0
    assert stats['samples_drawn'] > 0
    assert last['replay_ring_occupancy'] > 0.0


def test_learner_with_host_push_device_replay(tmp_path):
    """The host-push DeviceReplay flavor (device_ingest off): windows are
    built on the host and pushed into the HBM ring, sampling on device."""
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 40, 'minimum_episodes': 40,
            'epochs': 2, 'generation_envs': 16, 'forward_steps': 8,
            'num_batchers': 1, 'device_generation': True,
            'device_replay': True, 'device_ingest': False,
            'model_dir': str(tmp_path / 'models'),
        },
    }
    learner = Learner(args=apply_defaults(raw))
    learner.run()
    assert learner.model_epoch == 2
    assert learner.trainer.replay.size > 0
    assert learner.trainer.steps > 0


def test_max_sample_reuse_caps_replay_ratio(tmp_path):
    """With max_sample_reuse the threaded replay trainer waits for fresh
    windows instead of free-spinning; the audited reuse stays at the cap."""
    metrics_path = tmp_path / 'metrics.jsonl'
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 40, 'minimum_episodes': 40,
            'epochs': 3, 'generation_envs': 16, 'forward_steps': 8,
            'num_batchers': 1, 'device_generation': True,
            'device_replay': True, 'device_ingest': False,
            'max_sample_reuse': 2.0,
            'model_dir': str(tmp_path / 'models'),
            'metrics_jsonl': str(metrics_path),
        },
    }
    learner = Learner(args=apply_defaults(raw))
    learner.run()
    assert learner.trainer.steps > 0
    records = [json.loads(line) for line in
               metrics_path.read_text().splitlines()]
    # the final audited ratio respects the cap (one in-flight fused
    # dispatch of slack at most)
    final = records[-1]['replay_sample_reuse']
    # slack: the cap never throttles an epoch waiting to close, so up to
    # one fused dispatch per epoch may land above it
    slack = 3 * 16 * learner.trainer.fused_steps / max(
        1, learner.trainer.replay_stats['windows_ingested'])
    assert final <= 2.0 + slack + 1e-6, (final, slack)
