"""Learner end to end with the HBM-resident replay ring: device generation +
on-device batch sampling (the fully device-centric pipeline)."""

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


def test_learner_with_device_replay(tmp_path):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 40, 'minimum_episodes': 40,
            'epochs': 2, 'generation_envs': 16, 'forward_steps': 8,
            'num_batchers': 1, 'device_generation': True,
            'device_replay': True,
            'model_dir': str(tmp_path / 'models'),
        },
    }
    learner = Learner(args=apply_defaults(raw))
    learner.run()
    assert learner.model_epoch == 2
    assert learner.trainer.replay is not None
    assert learner.trainer.replay.size > 0
    assert learner.trainer.steps > 0
    assert (tmp_path / 'models' / '2.ckpt').exists()
