"""The fused device pipeline sharded over the 8-virtual-device CPU mesh:
device generation + device window ingest + device replay + SGD, end to end.

This is the multi-chip layout of the flagship loop (VERDICT round 2 #3):
shard_map over 'data' with per-shard env slices and ring shards, replicated
train state, and gradient psum — the only cross-chip traffic in steady
state. The reference scales actors with worker processes
(reference worker.py:169-254); this scales them with chips.
"""

import glob
import os

import jax
import numpy as np
import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


@pytest.mark.timeout(560)
def test_ttt_fused_pipeline_sharded_e2e(tmp_path, capsys):
    assert len(jax.devices()) == 8       # conftest's virtual CPU mesh
    args = apply_defaults({
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'forward_steps': 8, 'update_episodes': 30,
            'minimum_episodes': 16, 'generation_envs': 16, 'eval_envs': 8,
            'epochs': 3, 'device_generation': True, 'device_replay': True,
            'sgd_steps_per_chunk': 2, 'device_chunk_steps': 8,
            'model_dir': os.path.join(str(tmp_path), 'models')}})
    ln = Learner(args=args)
    ln.run()
    out = capsys.readouterr().out
    assert 'sharded over 8 devices' in out
    assert ln.model_epoch == 3
    assert ln.trainer.steps > 0
    assert ln.num_returned_episodes >= 30 * 3
    ckpts = glob.glob(os.path.join(str(tmp_path), 'models', '*.ckpt'))
    assert any(os.path.basename(p) == 'latest.ckpt' for p in ckpts)


def test_fused_pipeline_state_is_sharded(tmp_path):
    """The loop state really lives on the mesh: env axis and ring rows are
    split over 'data', train params replicated."""
    from handyrl_tpu.device_generation import DeviceEvaluator  # noqa: F401
    from handyrl_tpu.environment import make_jax_env
    from handyrl_tpu.model import ModelWrapper
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.ops.device_windows import DeviceWindower
    from handyrl_tpu.ops.fused_pipeline import FusedPipeline
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    env_args = {'env': 'TicTacToe'}
    env = make_env(env_args)
    env.reset()
    wrapper = ModelWrapper(env.net())
    wrapper.ensure_params(env.observation(0))
    env_mod = make_jax_env(env_args)
    args = apply_defaults({'env_args': env_args, 'train_args': {
        'batch_size': 16, 'forward_steps': 8}})['train_args']
    wd = DeviceWindower(mode='turn', fs=8, bi=0, max_steps=9,
                        windows_cap=1, capacity=64,   # per-shard rows
                        num_players=2, gamma=0.8, has_reward=False)
    fp = FusedPipeline(env_mod, wrapper, LossConfig.from_args(args), wd,
                       args, n_envs=16, chunk_steps=8, sgd_steps=2,
                       batch_size=16, mesh=mesh)

    def names(arr):
        spec = arr.sharding.spec
        return tuple(spec) if spec else ()

    first_env_leaf = jax.tree_util.tree_leaves(fp.state)[0]
    assert names(first_env_leaf)[:1] == ('data',)
    ring_leaf = next(iter(fp.ring.values()))
    assert ring_leaf.shape[0] == 64 * 8          # global rows = shards x 8
    assert names(ring_leaf)[:1] == ('data',)
    assert np.asarray(fp.cursor).shape == (8,)   # one cursor per shard

    # one warmup dispatch executes across the mesh and returns a global
    # done/outcome pack of the full env count
    parsed = fp.warm_step(jax.device_put(
        wrapper.params,
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())))
    assert parsed is None                        # pipelined one deep
    parsed = fp.warm_step(jax.device_put(
        wrapper.params,
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())))
    assert parsed['done'].shape == (8, 16)
    assert parsed['outcome'].shape == (8, 16, 2)
