"""Pure-JAX Hungry Geese: rule scenarios vs the host simulator, rollout
invariants, and device-resident generation through the batch builder."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from handyrl_tpu.envs import jax_hungry_geese as jhg
from handyrl_tpu.envs.kaggle.hungry_geese import Environment as HostGeese
from handyrl_tpu.device_generation import DeviceGenerator
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models import build
from handyrl_tpu.ops.batch import decompress_moments, make_batch, select_episode
from helpers import train_args


def _manual_state(geese, food, last_actions=None, steps=0):
    """Build a 1-env device state from explicit goose cell lists."""
    n = 1
    cells = np.full((n, 4, jhg.MAX_LEN), -1, np.int32)
    length = np.zeros((n, 4), np.int32)
    alive = np.zeros((n, 4), bool)
    for p, goose in enumerate(geese):
        for j, cell in enumerate(goose):
            cells[0, p, j] = cell
        length[0, p] = len(goose)
        alive[0, p] = len(goose) > 0
    la = np.full((n, 4), -1, np.int32)
    for p, a in (last_actions or {}).items():
        la[0, p] = a
    state = jhg.State(
        cells=jnp.asarray(cells), length=jnp.asarray(length),
        alive=jnp.asarray(alive), food=jnp.asarray([food], jnp.int32),
        last_action=jnp.asarray(la),
        prev_heads=jnp.full((n, 4), -1, jnp.int32),
        steps=jnp.asarray([steps], jnp.int32),
        scores=jnp.zeros((n, 4), jnp.float32), key=jax.random.split(jax.random.PRNGKey(0), 1),
    )
    return state._replace(scores=((state.steps[:, None] + 1) * jhg.MAX_LEN_SCORE
                                  + state.length).astype(jnp.float32)
                          * state.alive)


def _host_with(geese, food, last_actions=None, steps=0):
    e = HostGeese({})
    e.geese = [list(g) for g in geese]
    e.prev_geese = [list(g) for g in geese]
    e.food = list(food)
    e.alive = [len(g) > 0 for g in geese]
    e.last_actions = dict(last_actions or {})
    e.step_count = steps
    e.scores = [0.0] * 4
    e._update_scores()
    return e


def greedy_candidates(geese, food, last_actions, p):
    """Re-derive the host GreedyAgent's legal-candidate set for seat ``p``
    (docs/geese_rules.md): not the banned reversal, not adjacent to an
    opponent head, not a body cell, not a tail an opponent could keep by
    eating. Shared by the conformance agreement tests so the rule encoding
    cannot drift between them."""
    from handyrl_tpu.envs.kaggle.hungry_geese import (
        GREEDY_ACTION_ORDER, OPPOSITE as HOST_OPP, _move)
    goose = geese[p]
    opp = [g for q, g in enumerate(geese) if q != p and g]
    head_adj = {_move(g[0], a) for g in opp for a in range(4)}
    bodies = {c for g in geese if g for c in g[:-1]}
    eat_tails = {g[-1] for g in opp
                 if any(_move(g[0], a) in food for a in range(4))}
    last = last_actions.get(p)
    banned = HOST_OPP[last] if last is not None else None
    return [a for a in GREEDY_ACTION_ORDER
            if a != banned
            and _move(goose[0], a) not in head_adj
            and _move(goose[0], a) not in bodies
            and _move(goose[0], a) not in eat_tails]


SCENARIOS = [
    # (geese, food, actions, name)
    ([[0], [20], [40], [60]], [5, 70], {0: 3, 1: 3, 2: 3, 3: 3}, 'all-east'),
    # goose 0 eats the food at cell 1 (east of 0)
    ([[0], [20], [40], [60]], [1, 70], {0: 3, 1: 3, 2: 3, 3: 3}, 'eat'),
    # head-on collision: goose 0 at 0 moves east, goose 1 at 2 moves west
    ([[0], [2], [40], [60]], [70, 75], {0: 3, 1: 2, 2: 3, 3: 3}, 'head-on'),
    # goose 0 runs into goose 1's body
    ([[0], [12, 1, 2], [40], [60]], [70, 75], {0: 3, 1: 1, 2: 3, 3: 3}, 'body-hit'),
]


@pytest.mark.parametrize('geese,food,actions,name',
                         SCENARIOS, ids=[s[3] for s in SCENARIOS])
def test_step_matches_host_simulator(geese, food, actions, name):
    """Deterministic single steps (no food respawn randomness in the checked
    fields) must agree with the host simulator."""
    dev = _manual_state(geese, food)
    host = _host_with(geese, food)

    dev2 = jhg.step(dev, jnp.asarray([[actions[p] for p in range(4)]]))
    host.step(dict(actions))

    np.testing.assert_array_equal(np.asarray(dev2.alive)[0], host.alive)
    for p in range(4):
        L = int(np.asarray(dev2.length)[0, p])
        host_goose = host.geese[p]
        assert L == len(host_goose), (name, p)
        if L:
            np.testing.assert_array_equal(
                np.asarray(dev2.cells)[0, p, :L], host_goose)


def test_reversal_death_matches_host():
    geese = [[1, 0], [20], [40], [60]]       # goose 0 heading east (came from 0)
    dev = _manual_state(geese, [70, 75], last_actions={0: 3})
    host = _host_with(geese, [70, 75], last_actions={0: 3})
    actions = {0: 2, 1: 3, 2: 3, 3: 3}       # goose 0 reverses west
    dev2 = jhg.step(dev, jnp.asarray([[actions[p] for p in range(4)]]))
    host.step(dict(actions))
    assert not host.alive[0]
    assert not bool(np.asarray(dev2.alive)[0, 0])


def test_starvation_matches_host():
    geese = [[0], [20], [40], [60]]
    dev = _manual_state(geese, [70, 75], steps=jhg.HUNGER_RATE - 1)
    host = _host_with(geese, [70, 75], steps=jhg.HUNGER_RATE - 1)
    actions = {p: 3 for p in range(4)}
    dev2 = jhg.step(dev, jnp.asarray([[3, 3, 3, 3]]))
    host.step(actions)
    # everyone starved at length 1
    assert host.alive == [False] * 4
    assert not np.asarray(dev2.alive)[0].any()


def test_random_rollout_invariants():
    state = jhg.init_state(8, seed=1)
    key = jax.random.PRNGKey(2)
    for _ in range(60):
        key, k = jax.random.split(key)
        actions = jax.random.randint(k, (8, 4), 0, 4)
        state = jhg.step(state, actions)
        state = jhg.auto_reset(state, jhg.terminal(state))
        lengths = np.asarray(state.length)
        alive = np.asarray(state.alive)
        assert (lengths[alive] >= 1).all()
        assert (lengths[~alive] == 0).all()
        # no two living geese overlap
        cells = np.asarray(state.cells)
        for i in range(8):
            occ = []
            for p in range(4):
                if alive[i, p]:
                    occ += list(cells[i, p, :lengths[i, p]])
            assert len(occ) == len(set(occ))
        # food cells are distinct and unoccupied
        food = np.asarray(state.food)
        for i in range(8):
            assert len(set(food[i])) == jhg.N_FOOD


def test_observation_matches_host_layout():
    geese = [[0, 11], [20], [], [60]]
    dev = _manual_state(geese, [5, 70])
    host = _host_with(geese, [5, 70])
    obs_dev = np.asarray(jhg.observe(dev))[0]          # (P, 17, 7, 11)
    for viewer in range(4):
        want = host.observation(viewer)
        # prev-head channels: host uses prev_geese (= current here after our
        # manual construction both have no prev step); device has none
        got = obs_dev[viewer].copy()
        got[12:16] = want[12:16]                        # neutralize prev-head
        np.testing.assert_array_equal(got, want)


def test_device_generator_recurrent_simultaneous():
    """GeeseNetLSTM through the device rollout: per-player ConvLSTM state
    folded into the batch dim, zeroed on episode reset."""
    wrapper = ModelWrapper(build('GeeseNetLSTM', filters=8, stem_layers=1))
    wrapper.ensure_params(np.zeros((17, 7, 11), np.float32))
    args = train_args(forward_steps=8, turn_based=False, observation=True)
    args['gamma'] = 0.99
    gen = DeviceGenerator(jhg, wrapper, args, n_envs=4, chunk_steps=16, seed=7)
    episodes = []
    for _ in range(8):
        episodes += gen.step_chunk()
        if len(episodes) >= 2:
            break
    assert len(episodes) >= 2
    moments = decompress_moments(episodes[0]['moment'])
    assert moments[0]['observation'][0].shape == (17, 7, 11)
    batch = make_batch([select_episode(episodes, args) for _ in range(4)], args)
    assert np.isfinite(np.asarray(batch['selected_prob'])).all()


def test_device_generator_simultaneous_episodes():
    wrapper = ModelWrapper(build('GeeseNet', layers=2, filters=16))
    wrapper.ensure_params(np.zeros((17, 7, 11), np.float32))
    args = train_args(forward_steps=8, turn_based=False, observation=True)
    args['gamma'] = 0.99
    gen = DeviceGenerator(jhg, wrapper, args, n_envs=8, chunk_steps=16, seed=3)

    episodes = []
    for _ in range(10):
        episodes += gen.step_chunk()
        if len(episodes) >= 4:
            break
    assert len(episodes) >= 4

    ep = episodes[0]
    moments = decompress_moments(ep['moment'])
    assert len(moments) == ep['steps']
    m0 = moments[0]
    assert len(m0['turn']) == 4                       # everyone acts at start
    assert m0['observation'][0].shape == (17, 7, 11)
    total = sum(ep['outcome'].values())
    assert abs(total) < 1e-6                          # rank outcomes sum to 0

    batch = make_batch([select_episode(episodes, args) for _ in range(4)], args)
    # solo training: one random seat per window
    assert batch['observation'].shape[:3] == (4, 8, 1)
    assert np.isfinite(np.asarray(batch['selected_prob'])).all()
