"""Host ingest path: shared-memory batcher arenas, the zero-copy Batcher
round trip (spawned children writing slots the trainer maps), and the
prefetch_depth device staging ring — all on the CPU backend."""

import random
from collections import deque

import numpy as np
import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.ops.shm_batch import (ArenaMap, ArenaRing, SharedBatch,
                                       batch_spec, copy_into, map_batch)


def _tiny_batch(seed=0):
    rng = np.random.RandomState(seed)
    return {
        'observation': {'board': rng.rand(2, 3, 1, 4, 4).astype(np.float32),
                        'scalars': rng.rand(2, 3, 1, 5).astype(np.float32)},
        'selected_prob': rng.rand(2, 3, 1, 1).astype(np.float32),
        'action': rng.randint(0, 4, (2, 3, 1, 1)).astype(np.int32),
        'progress': rng.rand(2, 3, 1).astype(np.float32),
    }


def _assert_tree_equal(a, b, path=''):
    if isinstance(a, dict):
        assert set(a) == set(b), (path, set(a), set(b))
        for k in a:
            _assert_tree_equal(a[k], b[k], path + '/' + str(k))
    else:
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype, (path, a.dtype, b.dtype)
        np.testing.assert_array_equal(a, b, err_msg=path)


def test_shm_spec_map_roundtrip():
    """spec -> SharedMemory -> mapped views -> copy -> re-map: bit-exact,
    mixed dtypes, nested dict structure preserved."""
    batch = _tiny_batch()
    spec = batch_spec(batch)
    ring = ArenaRing(spec, slots=2)
    try:
        copy_into(ring.views[0], batch)
        # a second, independent mapping of the same segment sees the bits
        amap = ArenaMap()
        remap = amap.attach(ring.names[0], spec)
        _assert_tree_equal(batch, remap)
        # slot 1 is a different segment: writing it leaves slot 0 alone
        other = _tiny_batch(seed=9)
        copy_into(ring.views[1], other)
        _assert_tree_equal(batch, remap)
        amap.close()
    finally:
        ring.close()


def test_shm_slot_acquire_release_cycle():
    ring = ArenaRing(batch_spec(_tiny_batch()), slots=2)
    try:
        a, b = ring.acquire(), ring.acquire()
        assert {a, b} == {0, 1}
        assert ring.acquire() is None       # exhausted -> backpressure
        ring.release(a)
        assert ring.acquire() == a
    finally:
        ring.close()


def test_shared_batch_release_is_idempotent():
    calls = []
    sb = SharedBatch({'x': np.zeros(1)}, lambda: calls.append(1))
    sb.release()
    sb.release()
    assert calls == [1]


def _episodes_for_batcher(n=6, steps=10, n_actions=5):
    """Turn-based 2-player episodes shaped like generation output."""
    from handyrl_tpu.ops.batch import compress_moments
    rng = np.random.RandomState(0)
    eps = []
    for _ in range(n):
        moments = []
        for t in range(steps):
            turn = t % 2
            m = {k: {0: None, 1: None} for k in
                 ('observation', 'selected_prob', 'action_mask', 'action',
                  'value', 'reward', 'return')}
            m['observation'][turn] = rng.rand(3, 3, 3).astype(np.float32)
            m['selected_prob'][turn] = 0.5
            am = np.zeros(n_actions, np.float32)
            am[3:] = 1e32
            m['action_mask'][turn] = am
            m['action'][turn] = int(rng.randint(3))
            m['value'][turn] = np.array([0.1], np.float32)
            m['reward'] = {0: 0.0, 1: 0.0}
            m['return'] = {0: 0.1, 1: -0.1}
            m['turn'] = [turn]
            moments.append(m)
        eps.append({'args': {'player': [0, 1]}, 'steps': steps,
                    'outcome': {0: 1.0, 1: -1.0},
                    'moment': compress_moments(moments, 2)})
    return eps


@pytest.mark.timeout(600)
def test_shared_memory_batcher_roundtrip():
    """Spawned shm batcher children -> slot descriptors -> mapped
    SharedBatch views in this process, through slot recycling (more
    batches than slots), with sane contents every time."""
    from handyrl_tpu.train import _SHM_SLOTS, Batcher

    args = {'turn_based_training': True, 'observation': False,
            'forward_steps': 4, 'burn_in_steps': 0, 'compress_steps': 2,
            'maximum_episodes': 100, 'batch_size': 3, 'num_batchers': 1,
            'batcher_processes': True, 'batcher_shared_memory': True}
    random.seed(0)
    batcher = Batcher(args, deque(_episodes_for_batcher()))
    batcher.run()
    try:
        n_batches = 2 * _SHM_SLOTS + 1      # forces slot recycling
        for _ in range(n_batches):
            sb = batcher.batch(timeout=120)
            batch = sb.batch
            assert batch['observation'].shape == (3, 4, 1, 3, 3, 3)
            assert batch['selected_prob'].shape == (3, 4, 1, 1)
            assert batch['action'].dtype == np.dtype(np.int32)
            # semantic invariants survive arena reuse (stale-residue bugs
            # would break the mask/prob/progress ranges)
            assert set(np.unique(batch['turn_mask'])) <= {0.0, 1.0}
            assert np.all(batch['selected_prob'] > 0)
            assert np.all((batch['progress'] >= 0)
                          & (batch['progress'] <= 1))
            assert np.all(batch['episode_mask'][:, 0] == 1.0)
            sb.release()                    # hand the slot back
    finally:
        batcher.stop()


@pytest.mark.timeout(600)
def test_learner_with_shared_memory_batchers(tmp_path):
    """Full learner epoch over the zero-copy ingest path: spawned shm
    batchers, trainer maps slots, stages to device, releases."""
    from handyrl_tpu.train import Learner

    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 25, 'minimum_episodes': 30,
            'epochs': 1, 'generation_envs': 8, 'forward_steps': 8,
            'num_batchers': 2, 'batcher_processes': True,
            'batcher_shared_memory': True, 'prefetch_depth': 2,
            'model_dir': str(tmp_path / 'models'),
        },
    }
    learner = Learner(args=apply_defaults(raw))
    learner.run()
    assert learner.model_epoch == 1
    assert (tmp_path / 'models' / '1.ckpt').exists()


@pytest.mark.timeout(600)
def test_learner_prefetch_depth_staging_ring(tmp_path):
    """prefetch_depth > 1: the trainer holds an N-deep ring of staged
    device batches and still closes epochs correctly."""
    from handyrl_tpu.train import Learner

    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 30, 'minimum_episodes': 40,
            'epochs': 2, 'generation_envs': 8, 'forward_steps': 8,
            'num_batchers': 1, 'prefetch_depth': 3,
            'model_dir': str(tmp_path / 'models'),
        },
    }
    learner = Learner(args=apply_defaults(raw))
    learner.run()
    assert learner.trainer.prefetch_depth == 3
    # the persistent staging ring exists and never exceeds its depth
    staged = getattr(learner.trainer, '_staged', None)
    assert staged is not None and len(staged) <= 3
    assert learner.model_epoch == 2
    assert (tmp_path / 'models' / '2.ckpt').exists()


def test_prefetch_depth_validation():
    from handyrl_tpu.config import apply_defaults as ad
    with pytest.raises(AssertionError):
        ad({'env_args': {'env': 'TicTacToe'},
            'train_args': {'prefetch_depth': 0}})
    with pytest.raises(AssertionError):
        ad({'env_args': {'env': 'TicTacToe'},
            'train_args': {'batcher_shared_memory': True}})
