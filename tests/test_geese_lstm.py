"""Recurrent Hungry Geese model: hidden carry and training through the
observation-mode RNN path (the LSTM-era baseline configuration)."""

import numpy as np
import jax
import jax.numpy as jnp

from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models import build


def _obs(rng):
    obs = (rng.rand(17, 7, 11) < 0.1).astype(np.float32)
    obs[0] = 0
    obs[0, 3, 5] = 1.0
    return obs


def test_hidden_carry_and_shapes():
    rng = np.random.RandomState(0)
    wrapper = ModelWrapper(build('GeeseNetLSTM', filters=8, stem_layers=1))
    obs = _obs(rng)
    h0 = wrapper.init_hidden()
    out = wrapper.inference(obs, h0)
    assert out['policy'].shape == (4,)
    assert out['hidden'][0].shape == (7, 11, 8)
    out2 = wrapper.inference(obs, out['hidden'])
    assert not np.allclose(out['hidden'][0], out2['hidden'][0])


def test_trains_through_rnn_path():
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner

    import tempfile
    with tempfile.TemporaryDirectory() as td:
        raw = {
            'env_args': {'env': 'HungryGeese'},
            'train_args': {
                'turn_based_training': False, 'observation': True,
                'gamma': 0.99, 'forward_steps': 6, 'burn_in_steps': 2,
                'batch_size': 8, 'update_episodes': 6, 'minimum_episodes': 6,
                'epochs': 1, 'generation_envs': 4, 'num_batchers': 1,
                'policy_target': 'VTRACE', 'value_target': 'VTRACE',
                'model_dir': td + '/models',
            },
        }
        learner = Learner(args=apply_defaults(raw),
                          net=build('GeeseNetLSTM', filters=8, stem_layers=1))
        learner.run()
        assert learner.model_epoch == 1
