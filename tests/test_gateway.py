"""Match-gateway tests (serving/gateway.py, docs/serving.md "Match
gateway"): the deterministic session primitives (hidden-state digest,
audited per-session seeding), the SessionLedger affinity book, the
ChaosProxy ``flap`` fault mode the handoff/reconstruct chaos legs drive
with, and the gateway itself end to end against an in-process fleet —
session lifecycle, admission shed, protocol errors, outcome booking into
the RatingBook, and byte-identical journal reconstruction (including the
tampered-journal mismatch path)."""

import os
import random
import socket
import threading
import time

import numpy as np
import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.environment import make_env
from handyrl_tpu.league import LEARNER, journal_path, make_rating_book
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.serving.registry import ModelRegistry


def _ttt_wrapper(seed=7):
    env = make_env({'env': 'TicTacToe'})
    env.reset()
    w = ModelWrapper(env.net(), seed=seed)
    w.ensure_params(env.observation(0))
    return env, w


def _fleet_args(root, resolver_port=None, **flt):
    fleet = dict(flt)
    if resolver_port is not None:
        fleet['resolver'] = '127.0.0.1:%d' % resolver_port
    args = apply_defaults({
        'env_args': {'env': 'TicTacToe'},
        'train_args': {'serving': {'port': 0, 'registry_dir': str(root),
                                   'fleet': fleet}},
    })['train_args']
    args['env'] = {'env': 'TicTacToe'}
    return args


def _gw_args(root, resolver_port, **gw):
    gateway = dict({'resolver': '127.0.0.1:%d' % resolver_port,
                    'workers': 1, 'monitor_interval': 0.2}, **gw)
    args = apply_defaults({
        'env_args': {'env': 'TicTacToe'},
        'train_args': {'serving': {'port': 0, 'registry_dir': str(root),
                                   'gateway': gateway}},
    })['train_args']
    args['env'] = {'env': 'TicTacToe'}
    args['seed'] = 11
    return args


def _in_process_fleet(tmp_path, replicas=1):
    """A resolver plus ``replicas`` self-registering in-process services
    over a published TicTacToe champion; returns (resolver, services, w)."""
    from handyrl_tpu.serving.fleet import ServiceResolver
    from handyrl_tpu.serving.service import InferenceService
    _, w = _ttt_wrapper()
    ModelRegistry(str(tmp_path)).publish('default', snapshot=w.snapshot(),
                                         version=1, promote=True)
    resolver = ServiceResolver(_fleet_args(
        tmp_path, heartbeat_timeout=60.0)).start()
    services = [InferenceService(_fleet_args(
        tmp_path, resolver_port=resolver.port,
        heartbeat_interval=0.1)).start() for _ in range(replicas)]
    assert resolver.wait_routable(replicas, timeout=60)
    return resolver, services, w


def _gateway_close(gw):
    router = getattr(gw._tl, 'router', None)
    if router is not None:
        router.close()


def _play_to_outcome(gw, sid, reply, rng):
    """Drive a session to terminal through direct ``_op_play`` calls,
    returning (outcome, plies_played_by_client)."""
    plies = 0
    while not reply.get('done'):
        action = int(rng.choice(reply['legal'])) \
            if reply.get('to_move') and reply.get('legal') else None
        reply = gw._op_play({'sid': sid, 'action': action})
        assert 'error' not in reply, reply
        plies += 1
    return reply['outcome'], plies


# ---------------------------------------------------------------------------
# deterministic session primitives


def test_state_digest_order_insensitive_and_value_sensitive():
    """The journal's hidden digest keys on CONTENT: dict insertion order
    must not matter (seats are cached in play order, replayed in sorted
    order), while any value or structure change must."""
    from handyrl_tpu.serving.gateway import state_digest
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    d1 = state_digest({0: a, 1: (a * 2, None)})
    d2 = state_digest({1: (a * 2, None), 0: a.copy()})
    assert d1 == d2
    assert state_digest({0: a, 1: (a * 2 + 1, None)}) != d1
    assert state_digest({0: a}) != d1
    assert state_digest({}) == state_digest({})
    assert state_digest(None) != state_digest({})


def test_session_env_seed_audited_and_distinct_per_counter():
    """Env construction seeds are a pure function of (base_seed, session
    counter) — the journal replay rebuilds the identical env — and
    distinct sessions draw distinct seeds."""
    from handyrl_tpu.serving.gateway import session_env_seed
    assert session_env_seed(11, 1) == session_env_seed(11, 1)
    seeds = {session_env_seed(11, c) for c in range(1, 33)}
    assert len(seeds) == 32
    assert session_env_seed(12, 1) != session_env_seed(11, 1)


# ---------------------------------------------------------------------------
# SessionLedger (fault.py): the session-affinity book


def test_session_ledger_affinity_round_trip():
    from handyrl_tpu.fault import SessionLedger
    led = SessionLedger(clock=lambda: 0.0)
    led.book('s1', 'r0')
    led.book('s2', 'r0')
    led.book('s3', 'r1')
    assert led.replica_of('s1') == 'r0'
    assert led.sessions_on('r0') == ['s1', 's2']
    assert led.outstanding() == 3
    assert led.outstanding_by_replica() == {'r0': 2, 'r1': 1}
    # handoff re-pin returns the previous owner
    assert led.move('s2', 'r1') == 'r0'
    assert led.sessions_on('r1') == ['s2', 's3']
    assert led.move('s2', 'r1') == 'r1'   # idempotent re-pin
    assert led.release('s1') and not led.release('s1')
    assert led.stats['booked'] == 3
    assert led.stats['moved'] == 1
    assert led.stats['released'] == 1


def test_session_ledger_fail_replica_strands_and_journals():
    from handyrl_tpu.fault import SessionLedger
    led = SessionLedger(clock=lambda: 42.0)
    led.book('s1', 'r0')
    led.book('s2', 'r0')
    led.book('s3', 'r1')
    sids = led.fail_replica('r0', reason='killed')
    assert sids == ['s1', 's2']
    assert led.outstanding() == 1 and led.replica_of('s1') is None
    assert led.fail_replica('r0') == []       # already empty: no double count
    assert led.stats['stranded'] == 2
    assert led.stats['replica_failures'] == 1
    events = led.drain_stranding_events()
    assert [(s, r, why) for s, r, why, _t in events] == \
        [('s1', 'r0', 'killed'), ('s2', 'r0', 'killed')]
    assert all(t == 42.0 for _s, _r, _w, t in events)
    assert led.drain_stranding_events() == []  # consumed


# ---------------------------------------------------------------------------
# ChaosProxy flap: the bouncing-link fault mode


@pytest.mark.timeout(60)
def test_chaos_proxy_flap_bounces_and_restores():
    """``flap(period)`` must repeatedly sever live connections and refuse
    new ones for half a period, then restore — and ``stop_flap`` must
    leave the link usable (the deterministic driver for mid-match
    failover tests)."""
    from tests.proxy import ChaosProxy
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(('127.0.0.1', 0))
    lsock.listen(16)

    def echo_loop():
        while True:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return

            def serve(c):
                try:
                    while True:
                        data = c.recv(1 << 12)
                        if not data:
                            break
                        c.sendall(data)
                except OSError:
                    pass
                finally:
                    c.close()

            threading.Thread(target=serve, args=(conn,),
                             name='flap-echo', daemon=True).start()

    threading.Thread(target=echo_loop, name='flap-echo-accept',
                     daemon=True).start()
    proxy = ChaosProxy(target_port=lsock.getsockname()[1])

    def round_trip(payload):
        with socket.create_connection(('127.0.0.1', proxy.port),
                                      timeout=5) as c:
            c.settimeout(5)
            c.sendall(payload)
            return c.recv(1 << 12)

    try:
        assert round_trip(b'before') == b'before'
        # hold a connection open across the first bounce: the flap must
        # hard-sever it (EOF/RST at the client)
        held = socket.create_connection(('127.0.0.1', proxy.port),
                                        timeout=5)
        held.settimeout(10)
        held.sendall(b'ping')
        assert held.recv(1 << 12) == b'ping'
        proxy.flap(0.1)
        try:
            assert held.recv(1 << 12) == b''
        except OSError:
            pass                      # RST instead of EOF: equally severed
        finally:
            held.close()
        deadline = time.monotonic() + 30
        while proxy.flaps < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert proxy.flaps >= 3, 'link never bounced'
        proxy.stop_flap()
        assert proxy.accepting
        assert round_trip(b'after') == b'after'   # restored, not wedged
    finally:
        proxy.close()
        lsock.close()


# ---------------------------------------------------------------------------
# the gateway end to end (in-process fleet, direct op calls)


@pytest.mark.timeout(300)
def test_gateway_lifecycle_outcome_and_rating_booked(tmp_path):
    """Open -> play-to-terminal -> outcome booked: the env lives host-side,
    opponent seats act through the fleet, every step lands in the journal,
    and the finished match books a provisional ``gateway:<client>`` entry
    against the rated pinned model in the on-disk RatingBook."""
    from handyrl_tpu.serving.gateway import MatchGateway
    resolver, services, _w = _in_process_fleet(tmp_path)
    gw = MatchGateway(_gw_args(tmp_path, resolver.port))
    try:
        reply = gw._op_open({'env': 'TicTacToe', 'seat': 0,
                             'client': 'alice', 'seed': 23})
        assert 'error' not in reply, reply
        sid = reply['sid']
        assert reply['model'] == 'default@1'   # floating selector pinned
        assert reply['to_move'] and reply['legal']
        rng = random.Random(0)
        reply = gw._op_play({'sid': sid,
                             'action': int(rng.choice(reply['legal']))})
        assert 'error' not in reply, reply
        # the opponent seat just acted through the fleet: session booked
        assert gw.ledger.replica_of(sid) is not None
        outcome, plies = _play_to_outcome(gw, sid, reply, rng)
        assert plies >= 2
        assert set(outcome) == {0, 1}
        assert sum(outcome.values()) == pytest.approx(0.0)   # zero-sum
        # the session retired itself and booked the match
        assert sid not in gw._sessions and gw.ledger.outstanding() == 0
        session = gw._op_play({'sid': sid})
        assert 'error' in session                  # unknown after finish
        assert gw.ratings.is_provisional('gateway:alice')
        assert not gw.ratings.is_provisional('default@1')
        assert gw.ratings.games('gateway:alice') == 1
        # outcomes round-trip through the journal on disk
        book = make_rating_book({})
        assert book.load(journal_path(str(tmp_path)))
        assert book.is_provisional('gateway:alice')
        assert 'default@1' in book.names()
    finally:
        _gateway_close(gw)
        for svc in services:
            svc.stop(drain=False)
        resolver.stop(drain=False)


@pytest.mark.timeout(300)
def test_gateway_sheds_opens_and_rejects_bad_plays(tmp_path):
    """Admission control sheds OPENS past max_sessions (never plies on
    admitted sessions), and the play protocol rejects illegal actions,
    off-turn actions, and missing actions with typed errors."""
    from handyrl_tpu.serving.gateway import MatchGateway
    resolver, services, _w = _in_process_fleet(tmp_path)
    gw = MatchGateway(_gw_args(tmp_path, resolver.port, max_sessions=1))
    try:
        r1 = gw._op_open({'env': 'TicTacToe', 'seat': 0, 'client': 'a',
                          'seed': 5})
        assert 'error' not in r1, r1
        r2 = gw._op_open({'env': 'TicTacToe', 'seat': 0, 'client': 'b'})
        assert r2.get('shed') and 'error' in r2
        # protocol errors never kill the admitted session
        assert 'error' in gw._op_play({'sid': r1['sid'], 'action': 99})
        assert 'error' in gw._op_play({'sid': r1['sid']})   # turn, no action
        assert 'error' in gw._op_play({'sid': 'zzz', 'action': 0})
        good = gw._op_play({'sid': r1['sid'], 'action': r1['legal'][0]})
        assert 'error' not in good, good          # the ply still lands
        closed = gw._op_close({'sid': r1['sid']})
        assert closed['closed'] and gw.ledger.outstanding() == 0
        # bad opens error without shedding once a slot is free
        bad_env = gw._op_open({'env': 'NoSuchGame'})
        assert 'error' in bad_env and not bad_env.get('shed')
        bad_seat = gw._op_open({'env': 'TicTacToe', 'seat': 5})
        assert 'error' in bad_seat and not bad_seat.get('shed')
        # the shed slot freed: a fresh open is admitted again
        r3 = gw._op_open({'env': 'TicTacToe', 'seat': 0, 'client': 'c'})
        assert 'error' not in r3, r3
    finally:
        _gateway_close(gw)
        for svc in services:
            svc.stop(drain=False)
        resolver.stop(drain=False)


@pytest.mark.timeout(300)
def test_gateway_reconstruct_replays_journal_byte_identical(tmp_path):
    """The journal alone carries the match: ``_reconstruct`` rebuilds a
    session from (env, seed, actions) through the fleet, verifying the
    replayed opponent actions and the rebuilt hidden digest before
    adopting — and the adopted state plays on to the identical outcome.
    A tampered journal digest must be refused (mismatch + drop), never
    silently adopted."""
    from handyrl_tpu.serving.gateway import MatchGateway, state_digest
    resolver, services, _w = _in_process_fleet(tmp_path)
    gw = MatchGateway(_gw_args(tmp_path, resolver.port))
    try:
        rng = random.Random(3)
        reply = gw._op_open({'env': 'TicTacToe', 'seat': 0,
                             'client': 'rec', 'seed': 31})
        sid = reply['sid']
        for _ in range(2):
            reply = gw._op_play({'sid': sid,
                                 'action': int(rng.choice(reply['legal']))})
            assert 'error' not in reply, reply
        session = gw._sessions[sid]
        obs_before = np.asarray(session.env.observation(0)).copy()
        digest_before = session.journal['hidden_digest']
        draws_before = session.draws
        env_before = session.env
        assert gw._reconstruct(session, gw._router())
        assert session.env is not env_before       # a REBUILT env adopted
        np.testing.assert_array_equal(
            np.asarray(session.env.observation(0)), obs_before)
        assert state_digest(session.hiddens) == digest_before
        assert session.draws == draws_before       # seed cursor realigned
        # the adopted state is live: play on (from the byte-identical
        # pre-reconstruct reply) to a terminal outcome
        outcome, _ = _play_to_outcome(gw, sid, reply, rng)
        assert sum(outcome.values()) == pytest.approx(0.0)

        # tampered journal: digest divergence drops, never adopts
        reply = gw._op_open({'env': 'TicTacToe', 'seat': 0,
                             'client': 'tamper', 'seed': 37})
        sid2 = reply['sid']
        reply = gw._op_play({'sid': sid2,
                             'action': int(rng.choice(reply['legal']))})
        assert 'error' not in reply, reply
        session2 = gw._sessions[sid2]
        session2.journal['hidden_digest'] = '0' * 40
        assert not gw._reconstruct(session2, gw._router())
        assert sid2 not in gw._sessions            # dropped, not adopted
    finally:
        _gateway_close(gw)
        for svc in services:
            svc.stop(drain=False)
        resolver.stop(drain=False)


@pytest.mark.timeout(300)
def test_gateway_reconstruct_spans_link_open_trace(tmp_path):
    """Serving-path tracing through a journal reconstruction: the
    ``gateway_reconstruct`` link span carries the session's ORIGINAL
    open-time trace_id (adopted at ``_op_open``), every ply span points
    back at it via ``session_trace``, and a refused (tampered-journal)
    reconstruction emits the link span with ``ok`` false — the whole
    session reads as one causal chain."""
    import glob
    import json

    from handyrl_tpu import telemetry
    from handyrl_tpu.serving.gateway import MatchGateway
    resolver, services, _w = _in_process_fleet(tmp_path)
    trace_d = str(tmp_path / 'traces')
    telemetry.configure_tracing(trace_d, 1.0, force=True)
    gw = MatchGateway(_gw_args(tmp_path, resolver.port))
    try:
        rng = random.Random(3)
        reply = gw._op_open({'env': 'TicTacToe', 'seat': 0,
                             'client': 'tr', 'seed': 31})
        sid = reply['sid']
        session = gw._sessions[sid]
        assert session.trace, 'open did not mint a session trace id'
        for _ in range(2):
            reply = gw._op_play({'sid': sid,
                                 'action': int(rng.choice(reply['legal']))})
            assert 'error' not in reply, reply
        assert gw._reconstruct(session, gw._router())

        # tampered journal: the refusal is traced too (ok: false)
        reply2 = gw._op_open({'env': 'TicTacToe', 'seat': 0,
                              'client': 'tamper', 'seed': 37})
        sid2 = reply2['sid']
        reply2 = gw._op_play({'sid': sid2,
                              'action': int(rng.choice(reply2['legal']))})
        session2 = gw._sessions[sid2]
        tid2 = session2.trace
        session2.journal['hidden_digest'] = '0' * 40
        assert not gw._reconstruct(session2, gw._router())

        telemetry.trace_flush()
        events = []
        for path in glob.glob(os.path.join(trace_d, 'trace-*.jsonl')):
            events.extend(json.loads(l) for l in open(path) if l.strip())
        by_name = {}
        for e in events:
            by_name.setdefault(e['name'], []).append(e)
        opens = [e for e in by_name.get('gateway_open', ())
                 if e['args']['sid'] == sid]
        assert len(opens) == 1
        assert opens[0]['args']['trace_id'] == session.trace
        recs = [e for e in by_name.get('gateway_reconstruct', ())
                if e['args']['sid'] == sid]
        assert len(recs) == 1, recs
        assert recs[0]['args']['trace_id'] == session.trace
        assert recs[0]['args']['link'] == 'reconstruct'
        assert recs[0]['args']['ok'] is True
        assert recs[0]['args']['replayed'] >= 2
        # every ply span points back at the session's open-time chain
        plies = [e for e in by_name.get('gateway_ply', ())
                 if e['args']['sid'] == sid]
        assert len(plies) == 2
        assert all(e['args']['session_trace'] == session.trace
                   for e in plies)
        # opponent-seat fan-out rode the same chain into the fleet
        seats = [e for e in by_name.get('gateway_seat', ())
                 if e['args']['sid'] == sid]
        assert seats, 'no gateway_seat spans for the traced session'
        # the refused reconstruction links the tampered session's own id
        recs2 = [e for e in by_name.get('gateway_reconstruct', ())
                 if e['args']['sid'] == sid2]
        assert len(recs2) == 1
        assert recs2[0]['args']['trace_id'] == tid2
        assert recs2[0]['args']['ok'] is False
    finally:
        telemetry.trace_flush()
        telemetry.configure_tracing('', 1.0, force=True)
        os.environ.pop('HANDYRL_TPU_TRACE', None)
        os.environ.pop('HANDYRL_TPU_TRACE_RATE', None)
        _gateway_close(gw)
        for svc in services:
            svc.stop(drain=False)
        resolver.stop(drain=False)
