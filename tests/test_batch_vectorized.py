"""Arena batch builder vs the pinned reference builder: bit-exact parity on
fuzzed ragged episodes (turn-based and simultaneous, with/without
`observation`, dict and plain observations, burn-in, short-window padding),
plus arena reuse (`out=`) and decode-cache invariance."""

import random

import numpy as np
import pytest

from handyrl_tpu.ops.batch import (BlockCache, build_window,
                                   build_window_reference, compress_moments,
                                   decompress_moments, make_batch,
                                   make_batch_reference, select_episode)


def _rand_episode(rng, steps, n_players, obs_kind, n_actions, turn_based):
    """A ragged synthetic episode: actors per ply (alternating seats when
    turn-based, all seats otherwise), occasional extra observers, per-seat
    None entries everywhere a seat did not act/observe."""
    moments = []
    for t in range(steps):
        m = {k: {p: None for p in range(n_players)} for k in
             ('observation', 'selected_prob', 'action_mask', 'action',
              'value', 'reward', 'return')}
        actors = [t % n_players] if turn_based else list(range(n_players))
        observers = set(actors)
        if rng.random() < 0.3:
            observers.add(rng.randrange(n_players))
        for p in observers:
            if obs_kind == 'dict':
                m['observation'][p] = {
                    'board': np.random.rand(2, 3, 3).astype(np.float32),
                    'scalars': np.random.rand(4).astype(np.float32)}
            else:
                m['observation'][p] = np.random.rand(3, 3, 3).astype(np.float32)
        for p in actors:
            m['selected_prob'][p] = rng.random()
            am = np.zeros(n_actions, np.float32)
            am[rng.randrange(n_actions):] = 1e32
            m['action_mask'][p] = am
            m['action'][p] = rng.randrange(n_actions)
            m['value'][p] = np.array([rng.random()], np.float32)
        for p in range(n_players):
            m['reward'][p] = rng.random() - 0.5
            m['return'][p] = rng.random() - 0.5
        m['turn'] = list(actors)
        moments.append(m)
    return {'args': {'player': list(range(n_players))}, 'steps': steps,
            'outcome': {p: rng.random() * 2 - 1 for p in range(n_players)},
            'moment': compress_moments(moments, 4)}


def _assert_tree_equal(a, b, path=''):
    if isinstance(a, dict):
        assert set(a) == set(b), (path, set(a), set(b))
        for k in a:
            _assert_tree_equal(a[k], b[k], path + '/' + str(k))
    else:
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype, (path, a.dtype, b.dtype)
        assert a.shape == b.shape, (path, a.shape, b.shape)
        np.testing.assert_array_equal(a, b, err_msg=path)


def _fuzz_case(rng, trial):
    turn_based = rng.random() < 0.5
    args = {'turn_based_training': turn_based,
            'observation': rng.random() < 0.5,
            'forward_steps': rng.choice([4, 8]),
            'burn_in_steps': rng.choice([0, 2, 3]),
            'compress_steps': 4, 'maximum_episodes': 100}
    obs_kind = 'dict' if rng.random() < 0.4 else 'plain'
    n_players = rng.choice([1, 2, 3])
    eps = [_rand_episode(rng, rng.randrange(2, 20), n_players, obs_kind, 5,
                         turn_based)
           for _ in range(rng.randrange(1, 4))]
    random.seed(1000 + trial)
    windows = [select_episode(eps, args) for _ in range(rng.randrange(1, 5))]
    return args, windows


def test_make_batch_bit_exact_fuzz():
    rng = random.Random(0)
    for trial in range(150):
        args, windows = _fuzz_case(rng, trial)
        # seat selection in solo mode consumes RNG: seed identically so
        # both builders draw the same seats, then require identical bits
        random.seed(42 + trial)
        ref = make_batch_reference(windows, args)
        random.seed(42 + trial)
        new = make_batch(windows, args)
        _assert_tree_equal(ref, new, 'trial%d' % trial)


def test_build_window_bit_exact_fuzz():
    rng = random.Random(7)
    for trial in range(60):
        args, windows = _fuzz_case(rng, trial)
        for w in windows:
            moments = decompress_moments(w['moment'])[
                w['start'] - w['base']:w['end'] - w['base']]
            random.seed(300 + trial)
            ref = build_window_reference(moments, w, args)
            random.seed(300 + trial)
            new = build_window(moments, w, args)
            _assert_tree_equal(ref, new, 'trial%d' % trial)


def test_arena_reuse_is_bit_exact():
    """Writing batch k+1 into batch k's arenas (the shared-memory slot
    path) must leave no residue from batch k — pad defaults restored."""
    rng = random.Random(3)
    args = {'turn_based_training': True, 'observation': False,
            'forward_steps': 8, 'burn_in_steps': 2, 'compress_steps': 4,
            'maximum_episodes': 100}
    eps = [_rand_episode(rng, rng.randrange(3, 20), 2, 'plain', 5, True)
           for _ in range(6)]
    random.seed(21)
    windows_a = [select_episode(eps, args) for _ in range(4)]
    windows_b = [select_episode(eps, args) for _ in range(4)]
    arena = make_batch(windows_a, args)
    fresh = make_batch(windows_b, args)
    reused = make_batch(windows_b, args, out=arena)
    _assert_tree_equal(fresh, reused)
    assert reused is arena


def test_block_cache_is_semantically_invisible():
    """A shared BlockCache must never change batch contents — only cost."""
    rng = random.Random(9)
    cache = BlockCache(max_blocks=64)
    for trial in range(20):
        args, windows = _fuzz_case(rng, trial)
        random.seed(500 + trial)
        plain = make_batch(windows, args)
        random.seed(500 + trial)
        cached = make_batch(windows, args, cache=cache)
        random.seed(500 + trial)
        cached2 = make_batch(windows, args, cache=cache)  # warm hits
        _assert_tree_equal(plain, cached, 'cold%d' % trial)
        _assert_tree_equal(plain, cached2, 'warm%d' % trial)
    assert cache.hits > 0


def test_block_cache_eviction_bound():
    cache = BlockCache(max_blocks=4)
    rng = random.Random(1)
    ep = _rand_episode(rng, 40, 2, 'plain', 5, True)   # 10 blocks of 4
    for block in ep['moment']:
        cache.get(block)
    assert len(cache._od) == 4
    # re-decoding an evicted block still yields correct moments
    first = cache.get(ep['moment'][0])
    assert len(first) == 4
