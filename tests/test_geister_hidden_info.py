"""Geister imperfect-information guarantees: per-player observations hide
opponent piece types; the omniscient view doesn't; second player sees a
rotated board."""

import random

import numpy as np

from handyrl_tpu.envs.geister import Environment


def _setup_env():
    random.seed(5)
    e = Environment()
    e.play(144 + 3)    # black picks layout 3
    e.play(144 + 10)   # white picks layout 10
    return e


def test_player_view_hides_opponent_types():
    e = _setup_env()
    for viewer in (0, 1):
        obs = e.observation(viewer)
        board = obs['board']
        # channels 5/6 (opponent blue/red split) must be all-zero
        assert np.all(board[5] == 0)
        assert np.all(board[6] == 0)
        # but the opponent's pieces ARE visible as a union (channel 2)
        assert board[2].sum() == 8


def test_omniscient_view_reveals_types():
    e = _setup_env()
    obs = e.observation(None)
    board = obs['board']
    assert board[5].sum() == 4      # opponent blues
    assert board[6].sum() == 4      # opponent reds


def test_second_player_sees_rotated_board():
    e = _setup_env()
    obs0 = e.observation(None)                  # black to move, black's view
    e.play(random.choice(e.legal_actions()))    # now white to move
    obs1 = e.observation(None)                  # white's view
    # white's own-piece plane equals black's opponent plane rotated 180
    np.testing.assert_array_equal(
        obs1['board'][1], np.rot90(obs0['board'][2], 2))


def test_scalar_features_track_piece_counts():
    e = _setup_env()
    obs = e.observation(0)
    s = obs['scalar']
    assert s.shape == (18,)
    assert s[0] == 1.0                 # viewing player is black
    # 4 blues and 4 reds each side -> the '==4' one-hot of each group is set
    assert s[2 + 3] == 1.0             # my blues == 4
    assert s[6 + 3] == 1.0             # my reds == 4
    assert s[10 + 3] == 1.0            # opp blues == 4
    assert s[14 + 3] == 1.0            # opp reds == 4
