"""Device replay buffer: ring semantics, recency bias, and train-step
compatibility."""

import numpy as np
import jax
import jax.numpy as jnp

from handyrl_tpu.ops.batch import make_batch
from handyrl_tpu.ops.replay import DeviceReplay
from helpers import turn_based_episode, train_args, window


def _windows(n, args, offset=0):
    """n single-window batches stacked into one (n, T, P, ...) dict."""
    eps = [window(turn_based_episode(5, seed=offset + i), 0, 4)
           for i in range(n)]
    return make_batch(eps, args)


def test_push_and_sample_shapes():
    args = train_args(forward_steps=4)
    buf = DeviceReplay(capacity=32)
    buf.push(_windows(8, args))
    assert buf.size == 8
    batch = buf.sample(jax.random.PRNGKey(0), 4)
    assert batch['observation'].shape == (4, 4, 1, 3, 3, 3)
    assert batch['turn_mask'].shape == (4, 4, 2, 1)


def test_ring_overwrite():
    args = train_args(forward_steps=4)
    buf = DeviceReplay(capacity=8)
    for k in range(3):
        buf.push(_windows(4, args, offset=10 * k))
    assert buf.size == 8
    assert buf.cursor == 4
    batch = buf.sample(jax.random.PRNGKey(1), 8)
    assert np.isfinite(np.asarray(batch['selected_prob'])).all()


def test_recency_bias():
    """Tag windows via the action field; newer windows must be sampled more
    often under the triangular weighting."""
    args = train_args(forward_steps=4)
    buf = DeviceReplay(capacity=100)
    w = _windows(100, args)
    # overwrite action with the window's own index as a tag
    w = dict(w)
    w['action'] = np.arange(100, dtype=np.int32).reshape(100, 1, 1, 1) \
        * np.ones_like(np.asarray(w['action']))
    buf.push(w)
    batch = buf.sample(jax.random.PRNGKey(2), 4096)
    tags = np.asarray(batch['action'])[:, 0, 0, 0]
    older = (tags < 50).mean()
    newer = (tags >= 50).mean()
    # triangular weighting: newest half carries 75% of the mass
    assert newer > 0.68, (older, newer)


def test_sampled_batch_trains():
    from handyrl_tpu.models.tictactoe import SimpleConv2dModel
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.train_step import build_update_step, init_train_state

    args = train_args(forward_steps=4)
    buf = DeviceReplay(capacity=16)
    buf.push(_windows(8, args))
    batch = buf.sample(jax.random.PRNGKey(3), 4)

    module = SimpleConv2dModel()
    params = module.init(jax.random.PRNGKey(0),
                         batch['observation'][:, 0, 0], None)
    state = init_train_state(params)
    step = build_update_step(module, LossConfig(), donate=False)
    state2, metrics = step(state, batch, jnp.asarray(1e-4, jnp.float32))
    assert np.isfinite(float(metrics['total']))
