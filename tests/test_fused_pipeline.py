"""The fully-fused device loop: ONE dispatch = rollout chunk + on-device
window ingest + K SGD steps (ops/fused_pipeline.py). End-to-end learner runs
for both ingest layouts, plus resume."""

import json

import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.models import build
from handyrl_tpu.train import Learner


def _ttt_raw(tmp_path, **over):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            # batch 12 is not divisible by the 8-device test mesh, so the
            # trainer stays single-device — the device-ingest requirement
            'batch_size': 12, 'forward_steps': 4, 'compress_steps': 2,
            'update_episodes': 40, 'minimum_episodes': 40, 'epochs': 2,
            'generation_envs': 16, 'num_batchers': 1,
            'device_generation': True, 'device_replay': True,
            'sgd_steps_per_chunk': 4,
            'model_dir': str(tmp_path / 'models'),
            'metrics_jsonl': str(tmp_path / 'metrics.jsonl'),
        },
    }
    raw['train_args'].update(over)
    return raw


@pytest.mark.timeout(600)
def test_tictactoe_fused_pipeline_learner(tmp_path, capsys):
    args = apply_defaults(_ttt_raw(tmp_path))
    learner = Learner(args=args)
    learner.run()
    out = capsys.readouterr().out
    assert 'fused device pipeline' in out and '(turn mode)' in out
    assert 'loss =' in out          # metric futures drained and printed
    assert learner.model_epoch == 2
    assert learner.num_returned_episodes >= 80
    assert learner.trainer.steps > 0
    assert (tmp_path / 'models' / '2.ckpt').exists()
    assert (tmp_path / 'models' / 'trainer_state.ckpt').exists()
    # metrics JSONL carries the dispatch budget for the tunnel analysis
    rows = [json.loads(line)
            for line in (tmp_path / 'metrics.jsonl').read_text().splitlines()]
    assert rows and rows[-1]['dispatches_gen'] > 0
    assert rows[-1]['steps'] == learner.trainer.steps


@pytest.mark.timeout(600)
def test_fused_pipeline_ingest_accounting(tmp_path):
    """windows_ingested must be the CUMULATIVE ingest count, not the ring
    size (which saturates at capacity once the ring wraps)."""
    args = apply_defaults(_ttt_raw(
        tmp_path, maximum_episodes=2, replay_windows_per_episode=2))
    learner = Learner(args=args)
    learner.run()
    capacity = learner.trainer.replay.capacity
    assert capacity == 4
    stats = learner.trainer.replay_stats
    # ~80 episodes x >=1 window each went through a 4-row ring
    assert stats['windows_ingested'] > capacity * 4
    assert stats['samples_drawn'] > 0


@pytest.mark.timeout(600)
def test_geese_fused_pipeline_learner(tmp_path, capsys):
    raw = {
        'env_args': {'env': 'HungryGeese'},
        'train_args': {
            'turn_based_training': False, 'observation': True,
            'gamma': 0.99, 'forward_steps': 8, 'compress_steps': 4,
            'batch_size': 12, 'update_episodes': 10, 'minimum_episodes': 10,
            'epochs': 1, 'generation_envs': 8, 'num_batchers': 1,
            'device_generation': True, 'device_replay': True,
            'sgd_steps_per_chunk': 4,
            'policy_target': 'VTRACE', 'value_target': 'VTRACE',
            'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args, net=build('GeeseNet', layers=2, filters=16))
    learner.run()
    out = capsys.readouterr().out
    assert 'fused device pipeline' in out and '(solo mode)' in out
    assert learner.model_epoch == 1
    assert learner.trainer.steps > 0
    assert (tmp_path / 'models' / '1.ckpt').exists()


@pytest.mark.timeout(600)
def test_geister_fused_pipeline_learner(tmp_path, capsys):
    """Geister (turn-based, observation=True, recurrent DRC, dict
    observations) now runs the FUSED pipeline: the ingest gate admits
    observation=True via the compact 'turn' layout (equivalence proven by
    tests/test_turn_layout_parity.py), and the windower handles the
    pytree observation. This pins geister's sample reuse to
    sgd_steps_per_chunk instead of the threaded trainer's free spin."""
    from handyrl_tpu.models.geister import GeisterNet

    raw = {
        'env_args': {'env': 'Geister'},
        'train_args': {
            'turn_based_training': True, 'observation': True,
            'gamma': 0.9, 'forward_steps': 4, 'burn_in_steps': 2,
            'compress_steps': 2, 'batch_size': 8, 'update_episodes': 8,
            'minimum_episodes': 8, 'epochs': 2, 'generation_envs': 8,
            'num_batchers': 1, 'device_generation': True,
            'device_replay': True, 'sgd_steps_per_chunk': 2,
            'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args,
                      net=GeisterNet(filters=8, drc_layers=1))
    learner.run()
    out = capsys.readouterr().out
    assert 'fused device pipeline' in out and '(turn mode' in out
    assert learner.model_epoch == 2
    assert learner.trainer.steps > 0
    assert learner.trainer.device_cfg.observation is False
    assert learner.trainer.cfg.observation is True
    assert (tmp_path / 'models' / '2.ckpt').exists()


@pytest.mark.timeout(600)
def test_geister_threaded_turn_ingest(tmp_path, capsys):
    """fused_pipeline: False with an observation=True turn-based env:
    the THREADED device-ingest path must train with the rebuilt
    (observation=False) replay program against the compact windower rows
    — the Trainer.build_replay_update relayering, not the fused path."""
    from handyrl_tpu.models.geister import GeisterNet

    raw = {
        'env_args': {'env': 'Geister'},
        'train_args': {
            'turn_based_training': True, 'observation': True,
            'gamma': 0.9, 'forward_steps': 2, 'burn_in_steps': 0,
            'compress_steps': 2, 'batch_size': 4, 'update_episodes': 4,
            'minimum_episodes': 4, 'epochs': 1, 'generation_envs': 4,
            'num_batchers': 1, 'device_generation': True,
            'device_replay': True, 'fused_pipeline': False,
            'replay_fused_steps': 2, 'device_chunk_steps': 8,
            'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args,
                      net=GeisterNet(filters=4, drc_layers=1,
                                     drc_repeats=1))
    learner.run()
    out = capsys.readouterr().out
    assert 'device ingest: windows assembled on device' in out
    assert learner.model_epoch == 1
    assert learner.trainer.steps > 0
    assert learner.trainer.device_cfg.observation is False
    assert (tmp_path / 'models' / '1.ckpt').exists()


@pytest.mark.timeout(600)
def test_fused_pipeline_resume(tmp_path, capsys):
    args = apply_defaults(_ttt_raw(tmp_path))
    learner = Learner(args=args)
    learner.run()
    steps_before = learner.trainer.steps
    assert learner.model_epoch == 2

    args2 = apply_defaults(_ttt_raw(tmp_path, restart_epoch=2, epochs=3))
    learner2 = Learner(args=args2)
    assert learner2.trainer.steps == steps_before   # optimizer state resumed
    learner2.run()
    assert learner2.model_epoch == 3
    assert learner2.trainer.steps > steps_before
    assert (tmp_path / 'models' / '3.ckpt').exists()
