"""norm_kind='batch' through the shard_map'd update on the 8-virtual-
device CPU mesh: each shard normalizes by its LOCAL slice (torch
DataParallel semantics, train_step._update_core) and the pmean'd running
averages must leave the replicated train state IDENTICAL on every shard
— the invariant that keeps params from silently diverging across chips."""

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.geister import GeisterNet
from handyrl_tpu.environment import make_env
from handyrl_tpu.ops.losses import LossConfig
from handyrl_tpu.ops.train_step import (_update_core, init_train_state,
                                        make_optimizer)
from tests.test_batchnorm_parity import geister_batch_and_wrapper  # noqa: F401


def test_shard_map_batchnorm_stats_replicated(geister_batch_and_wrapper):
    _, batch, args = geister_batch_and_wrapper
    devices = jax.devices()
    assert len(devices) >= 8, 'conftest forces an 8-virtual-device mesh'
    mesh = jax.sharding.Mesh(np.array(devices[:8]), ('data',))

    wrapper = ModelWrapper(GeisterNet(filters=8, drc_layers=2,
                                      drc_repeats=1, norm_kind='batch'))
    env = make_env({'env': 'Geister'})
    env.reset()
    wrapper.ensure_params(env.observation(0))
    state = init_train_state(jax.tree_util.tree_map(jnp.array,
                                                    wrapper.params))
    cfg = LossConfig.from_args(args)
    core = _update_core(wrapper.module, cfg, make_optimizer(),
                        axis_name='data')

    # 8 identical batch slices -> every shard sees the same local batch,
    # so the pmean'd stats must equal the single-shard stats and the
    # post-step state must be bit-identical across shards
    rep8 = jax.tree_util.tree_map(
        lambda a: jnp.concatenate([a] * 8, axis=0), batch)

    try:
        shard_map = partial(jax.shard_map, mesh=mesh, check_vma=False)
    except AttributeError:
        from jax.experimental.shard_map import shard_map as _sm
        shard_map = partial(_sm, mesh=mesh, check_rep=False)

    P = jax.sharding.PartitionSpec

    def spec_like(tree, s):
        return jax.tree_util.tree_map(lambda _: s, tree)

    lr = jnp.asarray(1e-3, jnp.float32)
    # shape inference with the axis-free core (same output structure;
    # the psum'd core can only be traced under shard_map)
    out_shapes = jax.eval_shape(
        _update_core(wrapper.module, cfg, make_optimizer()),
        state, batch, lr)
    sharded = shard_map(
        lambda st, b, l: core(st, b, l),
        in_specs=(spec_like(state, P()), spec_like(rep8, P('data')), P()),
        out_specs=spec_like(out_shapes, P()),
    )

    state8, metrics8 = jax.jit(sharded)(state, rep8, lr)
    single_state, _ = jax.jit(
        _update_core(wrapper.module, cfg, make_optimizer()))(
        state, batch, lr)

    assert np.isfinite(float(metrics8['total']))
    # running averages advanced
    before = jax.tree_util.tree_leaves(state.params['batch_stats'])
    after = jax.tree_util.tree_leaves(state8.params['batch_stats'])
    assert max(float(jnp.abs(a - b).max())
               for a, b in zip(after, before)) > 1e-7
    # identical-slices construction: stats equal the single-device run's
    for a, b in zip(after, jax.tree_util.tree_leaves(
            single_state.params['batch_stats'])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
