"""End-to-end learner test: batched generation -> buffer -> compiled update
-> epoch cadence -> checkpoints, on a tiny TicTacToe config."""

import os

import numpy as np
import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


def test_learner_two_epochs(tmp_path):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 30, 'minimum_episodes': 40,
            'epochs': 2, 'generation_envs': 8, 'forward_steps': 8,
            'num_batchers': 1, 'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args)
    learner.run()

    assert learner.model_epoch == 2
    # checkpoints written, loadable, and non-trivial
    for name in ('1.ckpt', '2.ckpt', 'latest.ckpt'):
        path = os.path.join(str(tmp_path / 'models'), name)
        assert os.path.exists(path), name
    from handyrl_tpu.evaluation import load_model
    from handyrl_tpu.environment import make_env
    env = make_env({'env': 'TicTacToe'})
    wrapper = load_model(str(tmp_path / 'models' / 'latest.ckpt'), env)
    env.reset()
    out = wrapper.inference(env.observation(0))
    assert out['policy'].shape == (9,)
    # episode + eval accounting ran
    assert learner.num_returned_episodes >= 60
    assert learner.num_results > 0
    assert len(learner.trainer.episodes) > 0


@pytest.mark.skipif(not os.environ.get('RUN_SLOW'), reason='slow learning test')
def test_learner_beats_random(tmp_path):
    """Longer run: greedy agent must clearly beat random play."""
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 64, 'update_episodes': 200, 'minimum_episodes': 400,
            'epochs': 15, 'generation_envs': 32, 'forward_steps': 8,
            'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args)
    learner.run()
    # aggregate the last 5 epochs (per-epoch samples are ~25 games, too few
    # for a stable point estimate)
    last = learner.model_epoch - 1
    n = r = 0
    for epoch in range(max(1, last - 4), last + 1):
        if epoch in learner.results:
            en, er, _ = learner.results[epoch]
            n, r = n + en, r + er
    win_rate = (r / (n + 1e-6) + 1) / 2
    assert n >= 80
    assert win_rate > 0.6, win_rate
