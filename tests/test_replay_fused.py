"""Fused multi-step replay trainer: exact parity with the per-step path.

When every ring slot holds the SAME window, any sampled batch is identical,
so K fused steps (ops/train_step.py build_replay_update) must reproduce K
sequential build_update_step calls bit-for-bit — same LR schedule (computed
on device from the step counter), same optimizer trajectory, same metric
sums.
"""

import numpy as np
import jax
import jax.numpy as jnp

from handyrl_tpu.models.tictactoe import SimpleConv2dModel
from handyrl_tpu.ops.batch import make_batch
from handyrl_tpu.ops.losses import LossConfig
from handyrl_tpu.ops.replay import DeviceReplay
from handyrl_tpu.ops.train_step import (build_replay_update,
                                        build_update_step, init_train_state)
from helpers import turn_based_episode, train_args, window


K = 3
BATCH = 4
DATA_CNT_EMA = 32.0
DEFAULT_LR = 3e-8


def _identical_windows(n, args):
    eps = [window(turn_based_episode(5, seed=7), 0, 4) for _ in range(n)]
    return make_batch(eps, args)


def _setup():
    args = train_args(forward_steps=4)
    buf = DeviceReplay(capacity=8)
    buf.push(_identical_windows(8, args))
    module = SimpleConv2dModel()
    batch = buf.sample(jax.random.PRNGKey(0), BATCH)
    params = module.init(jax.random.PRNGKey(0),
                         batch['observation'][:, 0, 0], None)
    return buf, module, batch, params


def test_fused_matches_sequential_steps():
    buf, module, batch, params = _setup()

    # sequential reference: K single steps with the host-side LR schedule
    step = build_update_step(module, LossConfig(), donate=False)
    seq_state = init_train_state(params)
    seq_metrics = []
    for i in range(K):
        lr = jnp.asarray(DEFAULT_LR * DATA_CNT_EMA / (1 + i * 1e-5),
                         jnp.float32)
        seq_state, m = step(seq_state, batch, lr)
        seq_metrics.append(m)

    fused = build_replay_update(module, LossConfig(), capacity=buf.capacity,
                                batch_size=BATCH, num_steps=K,
                                default_lr=DEFAULT_LR,
                                spec_fn=lambda: (buf.window_spec, buf.treedef))
    state = init_train_state(params)
    state, key_out, summed = fused(
        state, buf.buffers, jax.random.PRNGKey(5),
        jnp.asarray(buf.size, jnp.int32), jnp.asarray(buf.cursor, jnp.int32),
        jnp.asarray(DATA_CNT_EMA, jnp.float32))

    assert int(state.steps) == K
    assert key_out.shape == jax.random.PRNGKey(5).shape

    for pa, pb in zip(jax.tree_util.tree_leaves(seq_state.params),
                      jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                                   rtol=2e-5, atol=2e-7)

    for k in summed:
        want = sum(float(m[k]) for m in seq_metrics)
        np.testing.assert_allclose(float(summed[k]), want, rtol=2e-4)


def test_fused_key_advances():
    """Steady state needs no host PRNG work: the returned key differs and
    feeding it back produces a different (but valid) trajectory."""
    buf, module, batch, params = _setup()
    fused = build_replay_update(module, LossConfig(), capacity=buf.capacity,
                                batch_size=BATCH, num_steps=2,
                                default_lr=DEFAULT_LR,
                                spec_fn=lambda: (buf.window_spec, buf.treedef))
    state = init_train_state(params)
    key = jax.random.PRNGKey(5)
    state, key2, _ = fused(state, buf.buffers, key,
                           jnp.asarray(buf.size, jnp.int32),
                           jnp.asarray(buf.cursor, jnp.int32),
                           jnp.asarray(DATA_CNT_EMA, jnp.float32))
    assert not np.array_equal(np.asarray(jax.random.key_data(key2)),
                              np.asarray(jax.random.key_data(jax.random.PRNGKey(5))))
    state, key3, summed = fused(state, buf.buffers, key2,
                                jnp.asarray(buf.size, jnp.int32),
                                jnp.asarray(buf.cursor, jnp.int32),
                                jnp.asarray(DATA_CNT_EMA, jnp.float32))
    assert int(state.steps) == 4
    assert np.isfinite(float(summed['total']))
