"""Hub fairness: one stalled peer must not delay another peer's traffic.

The reference serves each worker with its own connection thread
(reference connection.py:198-244), so a stalled worker never slows the
rest. The Hub keeps that property with per-endpoint outboxes + writer
threads behind one selector read loop; these tests pin it down.
"""

import socket
import time

import pytest

from handyrl_tpu.connection import FramedConnection, Hub


def _pair(sndbuf=None):
    a, b = socket.socketpair()
    if sndbuf is not None:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, sndbuf)
        b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, sndbuf)
    return FramedConnection(a), FramedConnection(b)


def test_roundtrip_two_peers():
    hub = Hub()
    ep1, client1 = _pair()
    ep2, client2 = _pair()
    hub.attach(ep1)
    hub.attach(ep2)
    assert hub.count() == 2
    client1.send({'who': 1})
    client2.send({'who': 2})
    got = {hub.recv(timeout=5)[1]['who'], hub.recv(timeout=5)[1]['who']}
    assert got == {1, 2}
    hub.send(ep1, 'a')
    hub.send(ep2, 'b')
    assert client1.recv() == 'a'
    assert client2.recv() == 'b'


def test_stalled_peer_does_not_block_others():
    """A peer that never reads (socket buffers full, writer thread wedged in
    sendall) must not delay a healthy peer's round trip."""
    hub = Hub()
    stalled_ep, _stalled_client = _pair(sndbuf=4096)   # client never reads
    live_ep, live_client = _pair()
    hub.attach(stalled_ep)
    hub.attach(live_ep)

    blob = b'x' * 65536
    for _ in range(8):            # far beyond 4 KB of kernel buffer
        hub.send(stalled_ep, blob)
    time.sleep(0.2)               # let the stalled writer wedge in sendall

    t0 = time.time()
    for i in range(5):
        hub.send(live_ep, {'seq': i})
        assert live_client.recv() == {'seq': i}
    assert time.time() - t0 < 2.0


def test_outbox_overflow_detaches_stalled_peer(monkeypatch):
    monkeypatch.setattr(Hub, 'OUTBOX_MAX', 4)
    hub = Hub()
    stalled_ep, _client = _pair(sndbuf=4096)
    hub.attach(stalled_ep)
    blob = b'y' * 65536
    deadline = time.time() + 10
    while hub.count() == 1 and time.time() < deadline:
        hub.send(stalled_ep, blob)
        time.sleep(0.01)
    assert hub.count() == 0       # hopelessly-behind peer detached


def test_detach_drops_sends():
    hub = Hub()
    ep, client = _pair()
    hub.attach(ep)
    hub.send(ep, 'first')
    assert client.recv() == 'first'
    hub.detach(ep)
    hub.send(ep, 'second')        # dropped, no error
    assert hub.count() == 0


@pytest.mark.parametrize('n', [8])
def test_many_peers_fan_out(n):
    hub = Hub()
    pairs = [_pair() for _ in range(n)]
    for ep, _ in pairs:
        hub.attach(ep)
    for i, (ep, _) in enumerate(pairs):
        hub.send(ep, {'rank': i})
    for i, (_, client) in enumerate(pairs):
        assert client.recv() == {'rank': i}
