"""Hub fairness + liveness: one stalled peer must not delay another peer's
traffic, and a silently-dead peer must not hold a slot forever.

The reference serves each worker with its own connection thread
(reference connection.py:198-244), so a stalled worker never slows the
rest. The Hub keeps that property with per-endpoint outboxes + writer
threads behind one selector read loop; these tests pin it down, plus the
heartbeat/liveness machinery (a peer that stops reading AND writing
without closing its socket — half-open TCP — is detached within the
liveness deadline) and the per-reason disconnect counters.
"""

import socket
import time

import pytest

from handyrl_tpu.connection import HEARTBEAT_KIND, FramedConnection, Hub


def _pair(sndbuf=None):
    a, b = socket.socketpair()
    if sndbuf is not None:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, sndbuf)
        b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, sndbuf)
    return FramedConnection(a), FramedConnection(b)


def test_roundtrip_two_peers():
    hub = Hub()
    ep1, client1 = _pair()
    ep2, client2 = _pair()
    hub.attach(ep1)
    hub.attach(ep2)
    assert hub.count() == 2
    client1.send({'who': 1})
    client2.send({'who': 2})
    got = {hub.recv(timeout=5)[1]['who'], hub.recv(timeout=5)[1]['who']}
    assert got == {1, 2}
    hub.send(ep1, 'a')
    hub.send(ep2, 'b')
    assert client1.recv() == 'a'
    assert client2.recv() == 'b'


def test_stalled_peer_does_not_block_others():
    """A peer that never reads (socket buffers full, writer thread wedged in
    sendall) must not delay a healthy peer's round trip."""
    hub = Hub()
    stalled_ep, _stalled_client = _pair(sndbuf=4096)   # client never reads
    live_ep, live_client = _pair()
    hub.attach(stalled_ep)
    hub.attach(live_ep)

    blob = b'x' * 65536
    for _ in range(8):            # far beyond 4 KB of kernel buffer
        hub.send(stalled_ep, blob)
    time.sleep(0.2)               # let the stalled writer wedge in sendall

    t0 = time.time()
    for i in range(5):
        hub.send(live_ep, {'seq': i})
        assert live_client.recv() == {'seq': i}
    assert time.time() - t0 < 2.0


def test_outbox_overflow_detaches_stalled_peer(monkeypatch):
    monkeypatch.setattr(Hub, 'OUTBOX_MAX', 4)
    hub = Hub()
    stalled_ep, _client = _pair(sndbuf=4096)
    live_ep, live_client = _pair()
    hub.attach(stalled_ep)
    hub.attach(live_ep)
    blob = b'y' * 65536
    deadline = time.time() + 10
    while hub.count() == 2 and time.time() < deadline:
        hub.send(stalled_ep, blob)
        time.sleep(0.01)
    assert hub.count() == 1       # hopelessly-behind peer detached...
    assert hub.stats_snapshot()['disconnect_outbox_overflow'] == 1
    hub.send(live_ep, {'seq': 1})  # ...without stalling the healthy peer
    assert live_client.recv() == {'seq': 1}


def test_detach_drops_sends():
    hub = Hub()
    ep, client = _pair()
    hub.attach(ep)
    hub.send(ep, 'first')
    assert client.recv() == 'first'
    hub.detach(ep)
    hub.send(ep, 'second')        # dropped, no error
    hub.detach(ep)                # double detach: no error, counted once
    assert hub.count() == 0
    assert hub.stats_snapshot()['detached'] == 1


def test_silent_peer_detached_within_liveness_deadline():
    """A peer that stops reading/writing WITHOUT closing its socket (the
    half-open TCP case) is detached once its liveness deadline lapses, and
    the detach is journaled for the server's task ledger."""
    hub = Hub()
    ep, client = _pair()
    hub.attach(ep, liveness=1.0)
    client.send({'hello': 1})
    hub.recv(timeout=5)
    assert hub.count() == 1
    deadline = time.time() + 10    # ...then the client goes silent
    while hub.count() == 1 and time.time() < deadline:
        time.sleep(0.05)
    assert hub.count() == 0
    assert hub.stats_snapshot()['disconnect_heartbeat_miss'] == 1
    events = hub.drain_detach_events()
    assert len(events) == 1
    assert events[0][0] is ep and events[0][1] == 'heartbeat_miss'
    assert hub.drain_detach_events() == []   # drained exactly once


def test_heartbeats_keep_peer_alive_and_stay_out_of_inbox():
    hub = Hub()
    ep, client = _pair()
    hub.attach(ep, liveness=1.5)
    for _ in range(6):             # 2.4s of beacons > 1.5s deadline
        client.send((HEARTBEAT_KIND, {'gather': 7, 'reconnects': 2}))
        time.sleep(0.4)
    assert hub.count() == 1        # beacons refreshed the deadline
    client.send(('real', 1))
    _ep, msg = hub.recv(timeout=5)
    assert msg == ['real', 1]      # heartbeats were filtered out
    assert hub.peer_info_snapshot()[ep] == {'gather': 7, 'reconnects': 2}
    assert hub.stats_snapshot()['heartbeats'] >= 6


def test_liveness_defaults_to_sockets_only():
    hub = Hub()
    ep, _client = _pair()
    hub.attach(ep)
    assert hub._liveness[ep] == Hub.LIVENESS_TIMEOUT   # socket: default on

    class _FakePipe:
        def fileno(self):
            return -1

        def close(self):
            pass
    pipe = _FakePipe()
    hub.attach(pipe)
    assert hub._liveness[pipe] == 0.0   # pipes carry no deadline
    hub.detach(pipe)


@pytest.mark.parametrize('n', [8])
def test_many_peers_fan_out(n):
    hub = Hub()
    pairs = [_pair() for _ in range(n)]
    for ep, _ in pairs:
        hub.attach(ep)
    for i, (ep, _) in enumerate(pairs):
        hub.send(ep, {'rank': i})
    for i, (_, client) in enumerate(pairs):
        assert client.recv() == {'rank': i}
