"""TorchScript export: the .pt artifact must reproduce the flax forward and
load standalone (no handyrl_tpu/flax/jax imports at load time) — the
portability contract of the reference's ONNX files
(reference scripts/make_onnx_model.py:28-58)."""

import os
import subprocess
import sys

import numpy as np
import pytest

torch = pytest.importorskip('torch')

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'scripts')
sys.path.insert(0, _SCRIPTS)


def _trained_wrapper(env_name):
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.model import ModelWrapper
    env = make_env({'env': env_name})
    env.reset()
    obs = env.observation(env.players()[0])
    wrapper = ModelWrapper(env.net())
    wrapper.ensure_params(obs)
    return env, obs, wrapper


@pytest.mark.parametrize('env_name', ['TicTacToe', 'HungryGeese'])
def test_torch_mirror_matches_flax(env_name, tmp_path):
    from torch_export import export_torchscript, validate_against_flax
    env, obs, wrapper = _trained_wrapper(env_name)
    arch = type(wrapper.module).__name__
    out = str(tmp_path / 'model.pt')

    mirror = export_torchscript(arch, wrapper.params, obs, out)
    dev = validate_against_flax(mirror, wrapper, obs)
    assert dev < 1e-4
    assert os.path.getsize(out) > 0

    # batched agreement on random observations
    rng = np.random.RandomState(0)
    batch = rng.rand(5, *np.asarray(obs).shape).astype(np.float32)
    reloaded = torch.jit.load(out)
    with torch.no_grad():
        t_policy, t_value = reloaded(torch.from_numpy(batch))
    f_out = wrapper.module.apply(wrapper.params, batch, None)
    assert np.allclose(t_policy.numpy(), np.asarray(f_out['policy']),
                       atol=1e-4)
    assert np.allclose(t_value.numpy(), np.asarray(f_out['value']),
                       atol=1e-4)


def test_artifact_loads_without_our_code(tmp_path):
    """torch.jit.load in a clean subprocess that never imports handyrl_tpu,
    jax, or flax."""
    from torch_export import export_torchscript
    env, obs, wrapper = _trained_wrapper('TicTacToe')
    out = str(tmp_path / 'model.pt')
    export_torchscript(type(wrapper.module).__name__, wrapper.params, obs,
                       out)

    # NB: this image's site hook pre-imports jax into every interpreter, so
    # "jax was never imported" cannot be asserted here; the artifact itself
    # pulls in neither our package nor flax, which is the portable contract.
    probe = (
        "import sys, numpy as np, torch\n"
        "m = torch.jit.load(%r)\n"
        "p, v = m(torch.zeros(2, 3, 3, 3))\n"
        "print('SHAPES', tuple(p.shape), tuple(v.shape))\n"
        "assert 'handyrl_tpu' not in sys.modules\n"
        "assert 'flax' not in sys.modules\n" % out)
    result = subprocess.run([sys.executable, '-c', probe],
                            capture_output=True, text=True, timeout=120)
    assert result.returncode == 0, result.stderr
    assert "SHAPES (2, 9) (2, 1)" in result.stdout


def test_unsupported_architecture_is_rejected(tmp_path):
    from torch_export import export_torchscript
    with pytest.raises(SystemExit):
        export_torchscript('GeisterNet', {}, None, str(tmp_path / 'x.pt'))
