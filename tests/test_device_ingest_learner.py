"""Learner end to end with on-device window ingestion: rollouts, window
assembly, replay ring, and fused SGD all on the accelerator — the host sees
only (done, outcome) accounting arrays."""

import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.models import build
from handyrl_tpu.train import Learner


@pytest.mark.timeout(600)
def test_tictactoe_device_ingest_learner(tmp_path, capsys):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            # batch 12 is not divisible by the 8-device test mesh, so the
            # trainer stays single-device — the device-ingest requirement
            'batch_size': 12, 'forward_steps': 4, 'compress_steps': 2,
            'update_episodes': 40, 'minimum_episodes': 40, 'epochs': 2,
            'generation_envs': 16, 'num_batchers': 1,
            'device_generation': True, 'device_replay': True,
            'fused_pipeline': False,   # the split threaded path under test
            'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args)
    learner.run()
    out = capsys.readouterr().out
    assert 'device ingest: windows assembled on device (turn mode)' in out
    assert learner.model_epoch == 2
    assert learner.num_returned_episodes >= 80
    assert (tmp_path / 'models' / '2.ckpt').exists()
    # SGD actually consumed ring windows
    assert learner.trainer.steps > 0
    assert learner.trainer.replay_stats['windows_ingested'] > 0


@pytest.mark.timeout(600)
def test_geese_device_ingest_learner(tmp_path, capsys):
    raw = {
        'env_args': {'env': 'HungryGeese'},
        'train_args': {
            'turn_based_training': False, 'observation': True,
            'gamma': 0.99, 'forward_steps': 8, 'compress_steps': 4,
            'batch_size': 12, 'update_episodes': 10, 'minimum_episodes': 10,
            'epochs': 1, 'generation_envs': 8, 'num_batchers': 1,
            'device_generation': True, 'device_replay': True,
            'fused_pipeline': False,   # the split threaded path under test
            'policy_target': 'VTRACE', 'value_target': 'VTRACE',
            'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args, net=build('GeeseNet', layers=2, filters=16))
    learner.run()
    out = capsys.readouterr().out
    assert 'device ingest: windows assembled on device (solo mode)' in out
    assert learner.model_epoch == 1
    assert learner.trainer.steps > 0
    assert (tmp_path / 'models' / '1.ckpt').exists()
