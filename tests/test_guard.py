"""Learner-side resilience (handyrl_tpu/guard.py): preemption snapshot-and-
exit, non-finite guards with rollback escalation, and checkpoint integrity.

Units cover the signal flag, the CRC sidecar round trip, corrupt-checkpoint
fallback selection, skip→rollback escalation, and the episode ingest
screen. The slow e2e legs drive real learners: SIGTERM mid-epoch →
exit 75 → restart completes the budget with monotonic step counts; an
injected NaN step is skipped (counted) and an injected NaN burst rolls the
TrainState back to the last good checkpoint.
"""

import bz2
import json
import os
import pickle
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from handyrl_tpu import guard
from handyrl_tpu.config import apply_defaults
from handyrl_tpu.utils.fs import (atomic_write_bytes, checksummed_write_bytes,
                                  read_verified_bytes, sidecar_path,
                                  verify_checkpoint)


# ---------------------------------------------------------------------------
# preemption guard


def test_preempt_exit_code_is_the_supervisor_contract():
    assert guard.PREEMPT_EXIT_CODE == 75   # EX_TEMPFAIL: restart me


def test_preempt_guard_sets_flag_on_sigterm():
    pg = guard.PreemptionGuard().install()
    try:
        assert not pg.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5
        while not pg.requested() and time.time() < deadline:
            time.sleep(0.01)
        assert pg.requested() and pg.fired
        assert pg.signum == signal.SIGTERM
    finally:
        pg.uninstall()
    # handlers restored: a fresh guard can install again
    assert signal.getsignal(signal.SIGTERM) is not pg._handle


def test_preempt_guard_disabled_never_installs():
    pg = guard.PreemptionGuard(enabled=False).install()
    assert not pg._previous
    pg.uninstall()


# ---------------------------------------------------------------------------
# CRC sidecar round trip


def test_checksummed_write_roundtrip(tmp_path):
    target = str(tmp_path / '3.ckpt')
    checksummed_write_bytes(target, b'checkpoint-bytes')
    ok, reason = verify_checkpoint(target)
    assert ok and reason == 'ok'
    assert read_verified_bytes(target) == b'checkpoint-bytes'
    manifest = json.loads(open(sidecar_path(target)).read())
    assert manifest['algo'] == 'crc32'
    assert manifest['size'] == len(b'checkpoint-bytes')


def test_bitflip_and_truncation_fail_verification(tmp_path):
    target = str(tmp_path / '3.ckpt')
    checksummed_write_bytes(target, b'checkpoint-bytes')

    raw = bytearray(open(target, 'rb').read())
    raw[4] ^= 0x40                      # single bit flip
    atomic_write_bytes(target, bytes(raw))
    ok, reason = verify_checkpoint(target)
    assert not ok and 'crc32' in reason
    assert read_verified_bytes(target) is None

    checksummed_write_bytes(target, b'checkpoint-bytes')
    atomic_write_bytes(target, b'check')   # truncated write
    ok, reason = verify_checkpoint(target)
    assert not ok and 'mismatch' in reason


def test_legacy_checkpoint_without_sidecar_passes(tmp_path):
    target = str(tmp_path / '1.ckpt')
    atomic_write_bytes(target, b'pre-manifest era')
    ok, reason = verify_checkpoint(target)
    assert ok and reason == 'unverified'
    assert read_verified_bytes(target) == b'pre-manifest era'


def test_missing_file_fails_verification(tmp_path):
    ok, reason = verify_checkpoint(str(tmp_path / 'nope.ckpt'))
    assert not ok
    assert read_verified_bytes(str(tmp_path / 'nope.ckpt')) is None


# ---------------------------------------------------------------------------
# fallback selection: newest VALID epoch wins


def test_newest_valid_epoch_skips_corrupt_checkpoints(tmp_path):
    d = str(tmp_path)
    checksummed_write_bytes(os.path.join(d, '1.ckpt'), b'one')
    checksummed_write_bytes(os.path.join(d, '3.ckpt'), b'three')
    assert guard.numbered_checkpoints(d) == [1, 3]
    assert guard.newest_valid_epoch(d) == (3, [])

    # bit-flip the newest: resume must fall back to epoch 1, reporting 3
    atomic_write_bytes(os.path.join(d, '3.ckpt'), b'thrEe')
    epoch, discarded = guard.newest_valid_epoch(d)
    assert epoch == 1 and discarded == [3]

    # corrupt everything: fresh start (epoch 0)
    atomic_write_bytes(os.path.join(d, '1.ckpt'), b'0ne')
    epoch, discarded = guard.newest_valid_epoch(d)
    assert epoch == 0 and discarded == [3, 1]   # newest discarded first


def test_newest_valid_epoch_empty_dir(tmp_path):
    assert guard.newest_valid_epoch(str(tmp_path)) == (0, [])
    assert guard.newest_valid_epoch(str(tmp_path / 'missing')) == (0, [])


# ---------------------------------------------------------------------------
# non-finite escalation policy


def test_guard_skip_policy_counts_but_never_rolls_back():
    g = guard.NonFiniteGuard({'nonfinite_policy': 'skip',
                              'rollback_after': 2})
    assert g.observe(1, 0) == 'skip'
    assert g.observe(1, 0) == 'skip'
    assert g.observe(1, 0) == 'skip'
    assert g.total_bad == 3 and g.consecutive == 3


def test_guard_skip_then_rollback_escalation():
    g = guard.NonFiniteGuard({'nonfinite_policy': 'rollback',
                              'rollback_after': 4})
    assert g.observe(1, 0) == 'skip'
    assert g.observe(2, 0) == 'skip'
    assert g.observe(0, 8) is None      # clean drain resets the streak
    assert g.consecutive == 0
    assert g.observe(3, 0) == 'skip'
    assert g.observe(2, 0) == 'rollback'   # 5 consecutive >= 4
    g.reset_streak()
    assert g.consecutive == 0 and g.total_bad == 8


def test_guard_abort_policy():
    g = guard.NonFiniteGuard({'nonfinite_policy': 'abort'})
    assert g.observe(1, 0) == 'abort'


def test_guard_loss_spike_zscore_trips_rollback():
    g = guard.NonFiniteGuard({'nonfinite_policy': 'rollback',
                              'rollback_after': 99,
                              'loss_spike_zscore': 6.0})
    rng = np.random.RandomState(0)
    for _ in range(50):
        assert g.observe(0, 8, 1.0 + 0.01 * rng.randn()) is None
    assert g.observe(0, 8, 50.0) == 'rollback'


def test_chaos_nan_consumes_its_burst():
    c = guard.ChaosNaN({'nanstep': 10, 'nanburst': 3})
    assert not c.due(8)            # before the window
    assert c.due(10)
    assert c.due(11) and c.due(12)
    assert not c.due(13)           # budget spent
    assert not c.due(10)           # a rollback rewind must NOT re-trigger


def test_chaos_nan_covers_fused_dispatch_ranges():
    c = guard.ChaosNaN({'nanstep': 10, 'nanburst': 2})
    assert not c.due(0, count=8)   # steps 0..7
    assert c.due(8, count=8)       # steps 8..15 overlap the window
    assert not c.due(16, count=8)  # budget consumed by the dispatch


# ---------------------------------------------------------------------------
# episode ingest screen


def _episode(obs_value=0.5, reward=0.25, outcome=1.0):
    moments = [{'observation': {0: np.full((3, 3), obs_value, np.float32),
                                1: None},
                'selected_prob': {0: 0.5, 1: None},
                'action_mask': {0: np.zeros(9, np.float32), 1: None},
                'action': {0: 4, 1: None},
                'value': {0: 0.1, 1: None},
                'reward': {0: reward, 1: None},
                'return': {0: 0.3, 1: None}}]
    block = bz2.compress(pickle.dumps(moments))
    return {'args': {'player': [0, 1], 'model_id': {0: 0, 1: 0}},
            'outcome': {0: outcome, 1: -outcome},
            'moment': [block], 'steps': 1}


def test_episode_screen_accepts_finite_and_none_entries():
    assert guard.episode_is_finite(_episode())


def test_episode_screen_rejects_nonfinite_payloads():
    assert not guard.episode_is_finite(_episode(obs_value=np.nan))
    assert not guard.episode_is_finite(_episode(reward=np.inf))
    assert not guard.episode_is_finite(_episode(outcome=np.nan))
    corrupt = _episode()
    corrupt['moment'] = [b'not a bz2 block']
    assert not guard.episode_is_finite(corrupt)


def test_feed_episodes_drops_and_counts_poisoned_episodes(tmp_path):
    from handyrl_tpu.train import Learner
    args = apply_defaults({
        'env_args': {'env': 'TicTacToe'},
        'train_args': {'batch_size': 8, 'update_episodes': 16,
                       'minimum_episodes': 16, 'epochs': 1,
                       'forward_steps': 8, 'num_batchers': 1,
                       'model_dir': str(tmp_path / 'models')}})
    learner = Learner(args=args)
    good, bad = _episode(), _episode(obs_value=np.nan)
    learner.feed_episodes([good, bad, None])
    assert learner._bad_episodes == 1
    assert list(learner.trainer.episodes) == [good]
    assert learner.num_returned_episodes == 1


# ---------------------------------------------------------------------------
# slow e2e: preempt-and-resume, NaN skip, NaN burst rollback


LEARNER_SCRIPT = r'''
import os, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['HANDYRL_TPU_NO_COMPILE_CACHE'] = '1'

def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import train_main
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': dict(
               batch_size=8, update_episodes=12, minimum_episodes=12,
               epochs=%(epochs)d, forward_steps=8, num_batchers=1,
               generation_envs=8,
               model_dir=%(model_dir)r, metrics_jsonl=%(metrics)r,
               restart_epoch=%(restart)d,
               guard=%(guard)r)}
    train_main(apply_defaults(raw))
    print('LEARNER DONE', flush=True)

if __name__ == '__main__':
    main()
'''


def _spawn_learner(tmp_path, tag, epochs=3, restart=0, guard_cfg=None,
                   chaos=''):
    script = tmp_path / ('learner_%s.py' % tag)
    script.write_text(LEARNER_SCRIPT % {
        'epochs': epochs, 'model_dir': str(tmp_path / 'models'),
        'metrics': str(tmp_path / 'metrics.jsonl'), 'restart': restart,
        'guard': guard_cfg or {}})
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'HANDYRL_TPU_NO_COMPILE_CACHE': '1',
           'PYTHONPATH': repo + os.pathsep + os.environ.get('PYTHONPATH', '')}
    if chaos:
        env['HANDYRL_TPU_CHAOS'] = chaos
    else:
        env.pop('HANDYRL_TPU_CHAOS', None)
    log = open(tmp_path / ('learner_%s.log' % tag), 'w')
    proc = subprocess.Popen([sys.executable, str(script)], env=env,
                            stdout=log, stderr=subprocess.STDOUT)
    return proc, log


def _wait_for(predicate, deadline, poll=0.5):
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


def _read_metrics(tmp_path):
    from handyrl_tpu.telemetry import validate_metrics_line
    path = tmp_path / 'metrics.jsonl'
    lines = [l for l in path.read_text().splitlines() if l.strip()]
    return [validate_metrics_line(l) for l in lines]


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_preempt_snapshot_and_resume(tmp_path):
    """SIGTERM mid-run → full checkpoint flush + 'preempted' metrics record
    + exit 75; the supervisor restart (restart_epoch: -1) completes the
    epoch budget with monotonic step counts and no duplicate epoch rows."""
    model_dir = tmp_path / 'models'
    proc, log = _spawn_learner(tmp_path, 'first', epochs=3)
    try:
        # preempt once the first epoch checkpoint exists (mid epoch 2)
        assert _wait_for(
            lambda: (model_dir / '1.ckpt').exists()
            or proc.poll() is not None,
            time.time() + 420), 'first epoch never completed'
        assert proc.poll() is None, 'learner died before the preempt'
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
        log.close()
    assert rc == guard.PREEMPT_EXIT_CODE, \
        'expected the supervisor-contract exit code, got %s' % rc
    out = (tmp_path / 'learner_first.log').read_text()
    assert 'preempted: checkpoint flushed' in out
    # the flushed pair is on disk, checksummed and valid
    assert (model_dir / 'trainer_state.ckpt').exists()
    assert verify_checkpoint(str(model_dir / 'trainer_state.ckpt'))[0]
    flushed_epoch, _ = guard.newest_valid_epoch(str(model_dir))
    assert flushed_epoch >= 1
    recs = _read_metrics(tmp_path)
    assert any(r.get('preempted') for r in recs)

    # supervisor restart: auto-resume, finish the budget
    proc, log = _spawn_learner(tmp_path, 'resume', epochs=3, restart=-1)
    try:
        rc = proc.wait(timeout=600)
    finally:
        if proc.poll() is None:
            proc.kill()
        log.close()
    out2 = (tmp_path / 'learner_resume.log').read_text()
    assert rc == 0, 'resumed learner failed (rc %s):\n%s' % (rc, out2[-2000:])
    assert 'LEARNER DONE' in out2
    assert ('auto-resume: newest valid checkpoint is epoch %d'
            % flushed_epoch) in out2
    assert (model_dir / '3.ckpt').exists(), 'budget not reached after resume'

    recs = _read_metrics(tmp_path)
    # resumed step counts are monotonic across the whole file
    steps = [r['steps'] for r in recs]
    assert steps == sorted(steps), 'step counts regressed across restart'
    # epoch rows are unique once the tagged preemption record is set aside
    epochs = [r['epoch'] for r in recs if not r.get('preempted')]
    assert len(epochs) == len(set(epochs)), \
        'duplicate epoch rows in metrics_jsonl: %s' % epochs
    assert max(epochs) == 3


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_bitflipped_checkpoint_resumes_from_previous_epoch(tmp_path):
    """Corrupting the newest numbered checkpoint after a finished run must
    make auto-resume fall back to the previous valid epoch, not crash."""
    model_dir = tmp_path / 'models'
    proc, log = _spawn_learner(tmp_path, 'first', epochs=2)
    try:
        rc = proc.wait(timeout=600)
    finally:
        if proc.poll() is None:
            proc.kill()
        log.close()
    assert rc == 0
    assert (model_dir / '2.ckpt').exists()

    raw = bytearray((model_dir / '2.ckpt').read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    atomic_write_bytes(str(model_dir / '2.ckpt'), bytes(raw))

    proc, log = _spawn_learner(tmp_path, 'resume', epochs=3, restart=-1)
    try:
        rc = proc.wait(timeout=600)
    finally:
        if proc.poll() is None:
            proc.kill()
        log.close()
    out = (tmp_path / 'learner_resume.log').read_text()
    assert rc == 0, 'resume crashed on the corrupt checkpoint:\n%s' % out[-2000:]
    assert 'auto-resume: newest valid checkpoint is epoch 1' in out
    assert 'discarding checkpoint' in out
    assert (model_dir / '3.ckpt').exists()


def _nan_learner_child(args, chaos, report_path):
    # spawned subprocess: an XLA-CPU crash fails one test instead of
    # killing the whole pytest run (same containment as test_resume)
    os.environ['HANDYRL_TPU_NO_COMPILE_CACHE'] = '1'
    os.environ['HANDYRL_TPU_CHAOS'] = chaos
    import jax
    import numpy as _np
    from handyrl_tpu.train import Learner
    learner = Learner(args=args)
    learner.run()
    finite = all(_np.isfinite(_np.asarray(l)).all()
                 for l in jax.tree_util.tree_leaves(learner.wrapper.params))
    with open(report_path, 'w') as f:
        json.dump({'total_bad': learner.trainer.guard.total_bad,
                   'rollbacks': learner.trainer.guard.rollbacks,
                   'model_epoch': learner.model_epoch,
                   'params_finite': finite}, f)


def _run_nan_learner(tmp_path, tag, chaos, guard_cfg, epochs=2):
    import multiprocessing as mp
    args = apply_defaults({
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 8, 'update_episodes': 12,
            'minimum_episodes': 12, 'epochs': epochs,
            'forward_steps': 8, 'num_batchers': 1,
            'generation_envs': 8,
            'model_dir': str(tmp_path / ('models_%s' % tag)),
            'metrics_jsonl': str(tmp_path / ('m_%s.jsonl' % tag)),
            'guard': guard_cfg}})
    report = str(tmp_path / ('report_%s.json' % tag))
    ctx = mp.get_context('spawn')
    proc = ctx.Process(target=_nan_learner_child, args=(args, chaos, report))
    proc.start()
    proc.join(timeout=600)
    if proc.is_alive():
        proc.terminate()
        proc.join(10)
        pytest.fail('learner subprocess timed out (%s)' % tag)
    if not os.path.exists(report):
        pytest.fail('learner subprocess died with exit code %s (%s)'
                    % (proc.exitcode, tag))
    with open(report) as f:
        return json.load(f)


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_nan_injection_is_skipped_and_counted(tmp_path):
    """An injected non-finite update under policy 'skip' is dropped on
    device (params stay finite) and counted in telemetry."""
    rep = _run_nan_learner(tmp_path, 'skip', 'nanepoch=1,nanburst=2',
                           {'nonfinite_policy': 'skip'})
    assert rep['total_bad'] >= 2, 'injected NaNs were not counted'
    assert rep['rollbacks'] == 0
    assert rep['params_finite'], 'params were poisoned despite the skip guard'
    recs = [json.loads(l) for l in
            (tmp_path / 'm_skip.jsonl').read_text().splitlines()]
    assert recs[-1]['guard_nonfinite'] >= 2


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_nan_burst_triggers_rollback(tmp_path):
    """A NaN burst under policy 'rollback' restores the last good
    checkpoint in place and the run still completes its budget with
    finite training."""
    rep = _run_nan_learner(tmp_path, 'rb', 'nanepoch=1,nanburst=64',
                           {'nonfinite_policy': 'rollback',
                            'rollback_after': 4}, epochs=3)
    assert rep['rollbacks'] >= 1, 'NaN burst never rolled back'
    assert rep['params_finite']
    assert rep['model_epoch'] == 3, 'run did not complete its budget'
    recs = [json.loads(l) for l in
            (tmp_path / 'm_rb.jsonl').read_text().splitlines()]
    assert recs[-1]['guard_rollbacks'] >= 1
