"""checkpoint_interval: file cadence, final-epoch flush, and resume.

The fused device loop may skip the per-epoch host state fetch + ckpt write
(train.py _fused_epoch); these pin the knob's contract: numbered ckpts at
multiples of N plus the final epoch, trainer state resumable from them.
"""

import glob
import os

import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


def _args(tmp, **over):
    # batch 12 is not divisible by the 8-device test mesh, so the trainer
    # stays single-device and the run takes the fused device loop (the
    # path checkpoint_interval applies to)
    train = {'batch_size': 12, 'forward_steps': 8, 'update_episodes': 30,
             'minimum_episodes': 15, 'generation_envs': 8, 'epochs': 7,
             'device_generation': True, 'device_replay': True,
             'sgd_steps_per_chunk': 2, 'device_chunk_steps': 8,
             'model_dir': os.path.join(tmp, 'models'),
             'checkpoint_interval': 3}
    train.update(over)
    return apply_defaults({'env_args': {'env': 'TicTacToe'},
                           'train_args': train})


def _ckpt_numbers(model_dir):
    return sorted(int(os.path.basename(p).split('.')[0])
                  for p in glob.glob(os.path.join(model_dir, '*.ckpt'))
                  if os.path.basename(p).split('.')[0].isdigit())


@pytest.mark.timeout(560)
def test_interval_cadence_and_final_flush(tmp_path):
    args = _args(str(tmp_path))
    ln = Learner(args=args)
    ln.run()
    model_dir = args['train_args']['model_dir']
    # multiples of 3 from the interval, 7 from the final-epoch force-write
    assert _ckpt_numbers(model_dir) == [3, 6, 7]
    assert os.path.exists(os.path.join(model_dir, 'trainer_state.ckpt'))
    assert ln.model_epoch == 7

    # resume from the final flush: params + optimizer state round-trip
    args2 = _args(str(tmp_path), restart_epoch=7, epochs=8)
    ln2 = Learner(args=args2)
    assert ln2.trainer.steps > 0          # trainer state actually loaded
    ln2.run()
    assert ln2.model_epoch == 8
    assert 8 in _ckpt_numbers(model_dir)
