"""checkpoint_interval: file cadence, final-epoch flush, and resume.

The fused device loop may skip the per-epoch host state fetch + ckpt write
(train.py _fused_epoch); these pin the knob's contract: numbered ckpts at
multiples of N plus the final epoch, trainer state resumable from them.

The learner itself runs in a SPAWNED subprocess: this exact fused program
has segfaulted XLA CPU on some hosts, and an in-process crash kills the
whole pytest run (hiding every later test file) instead of failing one
test. The subprocess boundary turns a backend crash into a plain failure
with an exit code; on healthy hosts the contract is tested unchanged.
"""

import glob
import json
import multiprocessing as mp
import os

import pytest

from handyrl_tpu.config import apply_defaults


def _args(tmp, **over):
    # batch 12 is not divisible by the 8-device test mesh, so the trainer
    # stays single-device and the run takes the fused device loop (the
    # path checkpoint_interval applies to)
    train = {'batch_size': 12, 'forward_steps': 8, 'update_episodes': 30,
             'minimum_episodes': 15, 'generation_envs': 8, 'epochs': 7,
             'device_generation': True, 'device_replay': True,
             'sgd_steps_per_chunk': 2, 'device_chunk_steps': 8,
             'model_dir': os.path.join(tmp, 'models'),
             'checkpoint_interval': 3}
    train.update(over)
    return apply_defaults({'env_args': {'env': 'TicTacToe'},
                           'train_args': train})


def _ckpt_numbers(model_dir):
    return sorted(int(os.path.basename(p).split('.')[0])
                  for p in glob.glob(os.path.join(model_dir, '*.ckpt'))
                  if os.path.basename(p).split('.')[0].isdigit())


def _learner_child(args, report_path):
    # keep the child off the persistent XLA compile cache: jaxlib 0.4.x CPU
    # corrupts the heap (malloc abort / SIGSEGV) deserializing the cached
    # fused-pipeline executable on the resume run; these programs compile in
    # seconds, so the child just recompiles
    os.environ['HANDYRL_TPU_NO_COMPILE_CACHE'] = '1'
    from handyrl_tpu.train import Learner
    ln = Learner(args=args)
    steps_at_start = ln.trainer.steps
    ln.run()
    with open(report_path, 'w') as f:
        json.dump({'model_epoch': ln.model_epoch,
                   'steps_at_start': steps_at_start}, f)


def _run_learner(args, tmp, tag, timeout=480):
    """Run the learner in a spawned child; return its exit report."""
    report = os.path.join(tmp, 'report_%s.json' % tag)
    ctx = mp.get_context('spawn')
    proc = ctx.Process(target=_learner_child, args=(args, report))
    proc.start()
    proc.join(timeout=timeout)
    if proc.is_alive():
        proc.terminate()
        proc.join(10)
        pytest.fail('learner subprocess timed out (%s)' % tag)
    # The report is written AFTER ln.run() returns, so its existence means
    # the training contract completed; a nonzero exit with a report present
    # is the known XLA daemon-thread abort at interpreter teardown
    # (train.py Learner.shutdown docstring), not a training failure.
    if not os.path.exists(report):
        pytest.fail('learner subprocess died with exit code %s (%s) — '
                    'backend crash, see stderr above' % (proc.exitcode, tag))
    if proc.exitcode != 0:
        print('note: learner child (%s) exited %s AFTER completing its run '
              '(teardown abort)' % (tag, proc.exitcode))
    with open(report) as f:
        return json.load(f)


@pytest.mark.timeout(560)
def test_interval_cadence_and_final_flush(tmp_path):
    args = _args(str(tmp_path))
    rep = _run_learner(args, str(tmp_path), 'first')
    model_dir = args['train_args']['model_dir']
    # multiples of 3 from the interval, 7 from the final-epoch force-write
    assert _ckpt_numbers(model_dir) == [3, 6, 7]
    assert os.path.exists(os.path.join(model_dir, 'trainer_state.ckpt'))
    assert rep['model_epoch'] == 7

    # resume from the final flush: params + optimizer state round-trip
    args2 = _args(str(tmp_path), restart_epoch=7, epochs=8)
    rep2 = _run_learner(args2, str(tmp_path), 'resume')
    assert rep2['steps_at_start'] > 0     # trainer state actually loaded
    assert rep2['model_epoch'] == 8
    assert 8 in _ckpt_numbers(model_dir)
