"""Chaos TCP proxy: a fault-injection man-in-the-middle for localhost tests.

Forwards byte streams between clients and a target port, and can inject
the four transport faults the fleet must survive:

* ``sever()``   — hard-close every live connection (peers see RST/EOF);
* ``blackhole`` — keep connections open but swallow all bytes (a silently
  dead peer: exactly the half-open-TCP case heartbeat liveness exists for);
* ``stall``     — ONE-WAY blackhole: client->target bytes still flow (the
  server accepts the request frames) but target->client bytes are
  swallowed — the request was received, the reply never comes. This is the
  stalled-service shape (a wedged inference engine, a hung RPC server)
  that request deadlines and the engine watchdog exist for, distinct from
  ``blackhole`` where not even the request arrives;
* ``delay``     — per-chunk forwarding latency (slow WAN links);
* ``flap``      — periodic sever/restore on a configurable period (a link
  that bounces: every half-period the proxy severs all connections and
  stops accepting, then restores — the deterministic mid-match failover
  driver for session handoff/reconstruct tests).
"""

import socket
import threading
import time


def _hard_close(sock):
    """shutdown() then close(): a bare close() of an fd another pump thread
    is blocked in recv() on does NOT release the kernel socket (the
    in-flight syscall pins it), so no FIN/RST ever reaches the peer —
    exactly the EOF the tests need to propagate. shutdown() disconnects
    immediately and wakes the blocked recv."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ChaosProxy:
    def __init__(self, target_port: int, listen_port: int = 0,
                 target_host: str = '127.0.0.1'):
        self._target = (target_host, int(target_port))
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(('127.0.0.1', int(listen_port)))
        self.port = self._lsock.getsockname()[1]
        self._lsock.listen(64)
        self._conns = []
        self._lock = threading.Lock()
        self.accepting = True
        self.blackhole = False
        self.stall = False
        self.delay = 0.0
        self.accepted = 0
        self.flaps = 0
        self._closed = False
        self._flap_stop = None
        threading.Thread(target=self._accept_loop, name='proxy-accept',
                 daemon=True).start()

    def _accept_loop(self):
        while not self._closed:
            try:
                client, _ = self._lsock.accept()
            except OSError:
                return
            if not self.accepting:
                client.close()
                continue
            try:
                upstream = socket.create_connection(self._target, timeout=10)
            except OSError:
                client.close()
                continue
            self.accepted += 1
            with self._lock:
                self._conns.append((client, upstream))
            for src, dst in ((client, upstream), (upstream, client)):
                threading.Thread(target=self._pump,
                                 args=(src, dst, src is upstream),
                                 name='proxy-pump', daemon=True).start()

    def _pump(self, src, dst, from_target: bool):
        while True:
            try:
                data = src.recv(1 << 16)
            except OSError:
                break
            if not data:
                break
            if self.delay:
                time.sleep(self.delay)
            if self.blackhole:
                continue          # swallow silently: peer looks alive-but-mute
            if self.stall and from_target:
                continue          # request accepted, reply never comes
            try:
                dst.sendall(data)
            except OSError:
                break
        for s in (src, dst):
            _hard_close(s)

    def sever(self):
        """Hard-close every live proxied connection."""
        with self._lock:
            conns, self._conns = self._conns, []
        for pair in conns:
            for s in pair:
                _hard_close(s)

    def flap(self, period: float):
        """Bounce the link every ``period`` seconds: down for half a period
        (sever + refuse new connections), up for the other half. Call
        ``stop_flap()`` (or ``close()``) to end with the link restored."""
        self.stop_flap()
        stop = threading.Event()
        self._flap_stop = stop

        def _loop():
            half = max(float(period), 1e-3) / 2.0
            while not stop.wait(half):
                self.accepting = False
                self.sever()
                self.flaps += 1
                if stop.wait(half):
                    break
                self.accepting = True
            self.accepting = True

        threading.Thread(target=_loop, name='proxy-flap', daemon=True).start()

    def stop_flap(self):
        """Stop a running flap loop and restore the link."""
        stop, self._flap_stop = self._flap_stop, None
        if stop is not None:
            stop.set()
        self.accepting = True

    def close(self):
        self._closed = True
        if self._flap_stop is not None:
            self._flap_stop.set()
            self._flap_stop = None
        try:
            self._lsock.close()
        except OSError:
            pass
        self.sever()
