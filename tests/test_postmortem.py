"""Postmortem collation: unit tests over synthetic blackbox dumps plus a
slow chaos e2e — a SIGKILLed gather must leave blackbox dumps from >= 2
processes, a firing-then-clearing alert trail in metrics_jsonl, and a
postmortem that names the killed gather's loss as the first failure."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

SCRIPTS = os.path.join(os.path.dirname(__file__), '..', 'scripts')
sys.path.insert(0, os.path.abspath(SCRIPTS))

import postmortem  # noqa: E402


# ---------------------------------------------------------------------------
# unit: discovery, attribution, alert-transition reconstruction


def _dump(role, pid, run_id, reason, t_dump, events):
    return {'schema': 'handyrl_tpu.blackbox/1', 'role': role, 'pid': pid,
            'run_id': run_id, 'reason': reason, 'time': t_dump,
            'stats': {'events': len(events), 'total': len(events)},
            'events': events, 'metrics': {}}


def _write(tmp_path, name, payload):
    path = tmp_path / name
    path.write_text(json.dumps(payload))
    return str(path)


def test_discover_filters_schema_and_run(tmp_path):
    _write(tmp_path, 'gather-0-11-runA.json',
           _dump('gather-0', 11, 'runA', 'gather-lost', 10.0, []))
    _write(tmp_path, 'worker-1-12-runB.json',
           _dump('worker-1', 12, 'runB', 'crash', 11.0, []))
    _write(tmp_path, 'junk.json', {'schema': 'something-else'})
    (tmp_path / 'torn.json').write_text('{not json')
    assert len(postmortem.discover_dumps(str(tmp_path))) == 2
    only_a = postmortem.discover_dumps(str(tmp_path), run_id='runA')
    assert [d['role'] for d in only_a] == ['gather-0']


def test_first_failure_ordered_by_last_event_not_dump_time(tmp_path):
    # the worker-host dumped LATER (t=200) about a death whose last
    # recorded event (t=100) precedes the worker's own loss at t=150 —
    # attribution must follow the event, not the file write
    early = _dump('worker-host', 20, 'r', 'gather-death', 200.0,
                  [{'t': 99.0, 'kind': 'log', 'msg': 'spawning'},
                   {'t': 100.0, 'kind': 'supervisor', 'msg': 'gather 0 died'}])
    late = _dump('worker-3', 21, 'r', 'gather-lost', 160.0,
                 [{'t': 150.0, 'kind': 'guard', 'msg': 'pipe EOF'}])
    report = postmortem.build_report([late, early], last_n=5)
    assert report['first_failure']['role'] == 'worker-host'
    assert report['first_failure']['time'] == 100.0
    times = [e['t'] for e in report['timeline']]
    assert times == sorted(times)
    deaths = [e for e in report['timeline'] if e['kind'] == 'death']
    assert len(deaths) == 2


def test_metrics_alert_transitions_span_rotation(tmp_path):
    path = str(tmp_path / 'metrics.jsonl')

    def rec(t, active, fired):
        return json.dumps({'epoch': 1, 'run_id': 'r', 'time': t,
                           'alerts': {'time': t, 'active': active,
                                      'fired': fired}}) + '\n'
    # older generation (rotated) + live file: firing then clearing
    with open(path + '.1', 'w') as f:
        f.write(rec(10.0, [], {}))
    with open(path, 'w') as f:
        f.write(rec(20.0, ['heartbeat_misses'], {'heartbeat_misses': 1}))
        f.write(rec(30.0, [], {'heartbeat_misses': 1}))
        f.write('{torn half-line')
    alerts = postmortem.load_metrics_alerts(path)[0]
    assert alerts['records'] == 3
    assert alerts['transitions'] == [
        {'t': 20.0, 'alert': 'heartbeat_misses', 'state': 'firing'},
        {'t': 30.0, 'alert': 'heartbeat_misses', 'state': 'cleared'}]
    assert alerts['fired'] == {'heartbeat_misses': 1}
    assert alerts['still_active'] == []


def test_metrics_alerts_fired_between_records(tmp_path):
    # alerts evaluate every few seconds but records land per epoch: a rule
    # firing AND clearing between two records must still leave a
    # transition, reconstructed from the cumulative fired count
    path = str(tmp_path / 'metrics.jsonl')
    with open(path, 'w') as f:
        f.write(json.dumps({'epoch': 1, 'time': 10.0, 'alerts': {
            'time': 10.0, 'active': [], 'fired': {}}}) + '\n')
        f.write(json.dumps({'epoch': 2, 'time': 20.0, 'alerts': {
            'time': 20.0, 'active': [],
            'fired': {'heartbeat_misses': 1}}}) + '\n')
    alerts = postmortem.load_metrics_alerts(path)[0]
    assert alerts['transitions'] == [
        {'t': 20.0, 'alert': 'heartbeat_misses', 'state': 'fired+cleared'}]


def test_main_exit_contract_and_json_schema(tmp_path, capsys):
    empty = tmp_path / 'empty'
    empty.mkdir()
    assert postmortem.main([str(empty)]) == 2
    capsys.readouterr()
    _write(tmp_path, 'gather-0-11-r.json',
           _dump('gather-0', 11, 'r', 'gather-lost', 10.0,
                 [{'t': 9.0, 'kind': 'guard', 'msg': 'pipe EOF'}]))
    assert postmortem.main([str(tmp_path), '--json']) == 0
    report = json.loads(capsys.readouterr().out)
    assert report['schema'] == 'handyrl_tpu.postmortem/1'
    assert report['dumps'] == 1
    assert report['first_failure']['reason'] == 'gather-lost'
    assert report['timeline'][-1]['kind'] == 'death'


# ---------------------------------------------------------------------------
# chaos e2e: SIGKILLed gather -> blackbox dumps + alert + postmortem


LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 2,
                          'forward_steps': 8, 'num_batchers': 1,
                          'model_dir': %(model_dir)r,
                          'metrics_jsonl': %(metrics)r,
                          'telemetry_port': %(tport)d,
                          'fault_tolerance': {
                              'heartbeat_interval': 1.0,
                              'liveness_timeout': 8.0,
                              'rpc_timeout': 30.0,
                              'task_deadline': 30.0,
                              'reconnect_initial_delay': 0.25,
                              'reconnect_max_delay': 2.0,
                              'reconnect_max_tries': 60}}}
    learner = Learner(args=apply_defaults(raw), remote=True)
    learner.run()
    print('LEARNER DONE', flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


def _wait_for(predicate, deadline, poll=1.0):
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_chaos_gather_kill_leaves_postmortem_trail(tmp_path):
    entry_port, data_port, tport = 21930, 21931, 21933
    model_dir = str(tmp_path / 'models')
    metrics = str(tmp_path / 'metrics.jsonl')
    blackbox = str(tmp_path / 'blackbox')
    learner_py = tmp_path / 'learner.py'
    worker_py = tmp_path / 'worker.py'
    learner_py.write_text(LEARNER_SCRIPT % {
        'model_dir': model_dir, 'metrics': metrics, 'tport': tport})
    worker_py.write_text(WORKER_SCRIPT)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
                'HANDYRL_TPU_BLACKBOX': blackbox,
                'HANDYRL_TPU_ENTRY_PORT': str(entry_port),
                'HANDYRL_TPU_DATA_PORT': str(data_port),
                'PYTHONPATH': repo + os.pathsep
                + os.environ.get('PYTHONPATH', '')}
    worker_env = {**base_env,
                  'HANDYRL_TPU_CHAOS': 'kill_gather=6,max_kills=1,seed=3'}

    learner_log = open(tmp_path / 'learner.log', 'w')
    worker_log = open(tmp_path / 'worker.log', 'w')
    learner = subprocess.Popen([sys.executable, str(learner_py)],
                               env=base_env, stdout=learner_log,
                               stderr=subprocess.STDOUT)
    worker = None
    statusz = None
    try:
        time.sleep(3)   # let the entry/data servers bind
        worker = subprocess.Popen([sys.executable, str(worker_py)],
                                  env=worker_env, stdout=worker_log,
                                  stderr=subprocess.STDOUT)

        # the chaos kill fires ~6 s into the worker host's life; wait for
        # the evidence (>= 2 dumps: the dead gather's orphaned workers +
        # the worker-host supervisor's declaration)
        def dumped():
            return (os.path.isdir(blackbox)
                    and len(os.listdir(blackbox)) >= 2)
        assert _wait_for(lambda: dumped() or learner.poll() is not None,
                         time.time() + 240), \
            'chaos kill never produced blackbox dumps'

        # live status surface, scraped mid-run from the learner exporter
        payload = json.loads(urllib.request.urlopen(
            'http://127.0.0.1:%d/statusz' % tport, timeout=10
        ).read().decode())
        statusz = payload
        assert payload['role'] == 'learner'
        assert 'progress' in payload and 'recorder' in payload

        def done():
            return (os.path.exists(os.path.join(model_dir, '2.ckpt'))
                    or learner.poll() is not None)
        assert _wait_for(done, time.time() + 240), \
            'learner hung after the injected kill'
        assert os.path.exists(os.path.join(model_dir, '2.ckpt'))
        learner.wait(timeout=120)
        worker.wait(timeout=120)
    finally:
        for proc in (worker, learner):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
        learner_log.close()
        worker_log.close()

    # blackbox evidence from >= 2 distinct processes
    dumps = postmortem.discover_dumps(blackbox)
    assert len(dumps) >= 2, 'expected >= 2 dumps, got %r' % (
        sorted(os.listdir(blackbox)))
    reasons = {d['reason'] for d in dumps}
    assert 'gather-death' in reasons            # the supervisor declared it
    assert 'gather-lost' in reasons             # its workers saw pipe EOF
    assert len({d['pid'] for d in dumps}) >= 2

    # the alert engine saw the disconnect: heartbeat_misses fired (and
    # is cumulative in every later record's alerts.fired)
    fired = {}
    for line in open(metrics):
        rec = json.loads(line)
        assert 'alerts' in rec, 'metrics record without an alerts block'
        fired = rec['alerts'].get('fired') or fired
    assert 'heartbeat_misses' in fired, \
        'gather kill never fired heartbeat_misses: %r' % fired
    # an alert landed on the live status surface payload too
    assert 'alerts' in statusz

    # the postmortem names the kill as the first failure and exits 0
    out = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, 'postmortem.py'), blackbox,
         '--metrics', metrics, '--json'],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    report = json.loads(out.stdout)
    assert report['dumps'] >= 2
    assert report['first_failure']['reason'] in ('gather-lost',
                                                 'gather-death')
    assert any(e['kind'] == 'alert' and 'heartbeat_misses' in e['msg']
               for e in report['timeline'])
